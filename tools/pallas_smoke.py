"""60-second on-chip smoke test for the Pallas kernels.

tpu_watch.sh runs this right after a successful tunnel probe and BEFORE the
benches: the fused chunk-Top-K kernel (ops/pallas_topk.py) is on the
headline path (use_pallas='auto'), so a Mosaic compile failure on the real
chip would otherwise crash every bench attempt. On failure the watcher
exports GRACE_DISABLE_PALLAS=1 so the benches measure the staged XLA path
instead of measuring nothing.

Exit 0 = kernel compiled and matches the staged path on-device.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax
    import jax.numpy as jnp
    import numpy as np

    if jax.devices()[0].platform != "tpu":
        print("smoke: not on tpu", file=sys.stderr)
        return 2

    from grace_tpu.compressors import TopKCompressor
    from grace_tpu.ops.pallas_topk import chunk_compress_feedback

    n, ratio = 1_000_000, 0.01
    k = max(1, int(n * ratio))
    flat = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (n,), jnp.float32) * 0.1

    vals, win, new_resid = chunk_compress_feedback(flat, resid, k)
    vals, win, new_resid = map(np.asarray, (vals, win, new_resid))

    ref = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                         use_pallas=False)
    payload, ctx, _ = ref.compress(flat + resid, None, jax.random.key(2))
    rvals, ridx = map(np.asarray, payload)

    idx = win * k + np.arange(k)
    if not np.array_equal(idx, ridx):
        print("smoke: index mismatch", file=sys.stderr)
        return 1
    if not np.array_equal(vals, rvals):
        print("smoke: value mismatch", file=sys.stderr)
        return 1
    dense = np.zeros(n, np.float32)
    dense[idx] = vals
    if not np.array_equal(new_resid, np.asarray(flat + resid) - dense):
        print("smoke: residual mismatch", file=sys.stderr)
        return 1
    # Exchange-side kernel: W=8 gathered payloads vs the staged vmap path.
    from grace_tpu.ops.pallas_topk import chunk_aggregate_dense
    world = 8
    xs = jax.random.normal(jax.random.key(3), (world, n), jnp.float32)
    payloads = [ref.compress(xs[w], None, jax.random.key(4))[0]
                for w in range(world)]
    gvals = jnp.stack([p[0] for p in payloads])
    gidx = jnp.stack([p[1] for p in payloads])
    ctx = (n, (n,), jnp.float32)
    staged = jnp.mean(jax.vmap(
        lambda v, i: ref.decompress((v, i), ctx))(gvals, gidx), axis=0)
    fused = chunk_aggregate_dense(gvals, (gidx // k).astype(jnp.int32), k, n,
                                  average=True)
    if not np.allclose(np.asarray(fused), np.asarray(staged), atol=1e-6):
        print("smoke: aggregate kernel mismatch", file=sys.stderr)
        return 1

    # QSGD quant kernel (ops/pallas_quant.py): the watcher's
    # degrade-to-staged hatch must also cover a Mosaic failure here, since
    # the sweep's qsgd_pallas row enables it. Bit-exact comparison against
    # the staged path is impossible (different PRNG bit source), so check
    # the deterministic invariants of stochastic rounding instead: every
    # |level| within the floor/ceil envelope of |x|·q/||x||, sign folded.
    from grace_tpu.ops.pallas_quant import quantize_stochastic
    q = 64
    norm = jnp.linalg.norm(flat)
    levels = np.asarray(quantize_stochastic(flat, norm, jnp.int32(7), q)
                        ).astype(np.float64)
    lf = np.abs(np.asarray(flat, np.float64)) * (q / float(norm))
    mag = np.abs(levels)
    if not ((mag >= np.floor(lf) - 1e-6) & (mag <= np.ceil(lf) + 1e-6)).all():
        print("smoke: qsgd level outside floor/ceil envelope",
              file=sys.stderr)
        return 1
    if (np.sign(levels) * np.sign(np.asarray(flat)) < 0).any():
        print("smoke: qsgd sign mismatch", file=sys.stderr)
        return 1

    print("smoke: pallas chunk-topk + qsgd-quant kernels OK on",
          jax.devices()[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())
