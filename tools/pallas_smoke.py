"""60-second on-chip smoke test for the Pallas kernels.

tpu_watch.sh runs this right after a successful tunnel probe and BEFORE the
benches: the sweep's topk1pct_pallas / qsgd_pallas ablation rows force the
Pallas kernels on (the headline 'auto' default resolves to the staged XLA
path since the round-4 A/B), so a Mosaic compile failure on the real chip
would otherwise crash every bench attempt. Per-kernel verdicts (round-4
postmortem: a Mosaic cast failure in the *quant* kernel used to disable the
headline *topk* kernels too, costing the whole fused-path measurement):

Exit 0 = all kernels compiled and match their staged paths on-device.
Exit 3 = topk kernels OK, quant kernel failed — the watcher exports
         GRACE_DISABLE_PALLAS_QUANT=1 only.
Exit 1 = topk failed — the watcher exports GRACE_DISABLE_PALLAS=1.
Exit 2 = not on TPU (treated as full failure by the watcher).
"""

from __future__ import annotations

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _require(ok: bool, msg: str) -> None:
    # NOT assert: PYTHONOPTIMIZE in the watcher's inherited environment
    # would strip asserts and turn the gatekeeper into a no-op.
    if not ok:
        raise RuntimeError(msg)


def _check_topk() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from grace_tpu.compressors import TopKCompressor
    from grace_tpu.ops.pallas_topk import (chunk_aggregate_dense,
                                           chunk_compress_feedback)

    n, ratio = 1_000_000, 0.01
    k = max(1, int(n * ratio))
    flat = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (n,), jnp.float32) * 0.1

    vals, win, new_resid = chunk_compress_feedback(flat, resid, k)
    vals, win, new_resid = map(np.asarray, (vals, win, new_resid))

    ref = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                         use_pallas=False)
    payload, ctx, _ = ref.compress(flat + resid, None, jax.random.key(2))
    rvals, ridx = map(np.asarray, payload)

    idx = win * k + np.arange(k)
    _require(np.array_equal(idx, ridx), "index mismatch")
    _require(np.array_equal(vals, rvals), "value mismatch")
    dense = np.zeros(n, np.float32)
    dense[idx] = vals
    _require(np.array_equal(new_resid, np.asarray(flat + resid) - dense),
             "residual mismatch")
    # Exchange-side kernel: W=8 gathered payloads vs the staged vmap path.
    world = 8
    xs = jax.random.normal(jax.random.key(3), (world, n), jnp.float32)
    payloads = [ref.compress(xs[w], None, jax.random.key(4))[0]
                for w in range(world)]
    gvals = jnp.stack([p[0] for p in payloads])
    gidx = jnp.stack([p[1] for p in payloads])
    ctx = (n, (n,), jnp.float32)
    staged = jnp.mean(jax.vmap(
        lambda v, i: ref.decompress((v, i), ctx))(gvals, gidx), axis=0)
    fused = chunk_aggregate_dense(gvals, (gidx // k).astype(jnp.int32), k, n,
                                  average=True)
    _require(np.allclose(np.asarray(fused), np.asarray(staged), atol=1e-6),
             "aggregate kernel mismatch")


def _check_quant() -> None:
    # QSGD quant kernel (ops/pallas_quant.py): the sweep's qsgd_pallas row
    # enables it. Bit-exact comparison against the staged path is impossible
    # (different PRNG bit source), so check the deterministic invariants of
    # stochastic rounding instead: every |level| within the floor/ceil
    # envelope of |x|*q/||x||, sign folded.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from grace_tpu.ops.pallas_quant import quantize_stochastic

    flat = jax.random.normal(jax.random.key(0), (1_000_000,), jnp.float32)
    q = 64
    norm = jnp.linalg.norm(flat)
    levels = np.asarray(quantize_stochastic(flat, norm, jnp.int32(7), q)
                        ).astype(np.float64)
    lf = np.abs(np.asarray(flat, np.float64)) * (q / float(norm))
    mag = np.abs(levels)
    _require(((mag >= np.floor(lf) - 1e-6)
              & (mag <= np.ceil(lf) + 1e-6)).all(),
             "qsgd level outside floor/ceil envelope")
    _require(not (np.sign(levels) * np.sign(np.asarray(flat)) < 0).any(),
             "qsgd sign mismatch")
    # Stochastic rounding must actually round both ways (a broken PRNG that
    # returns all zeros would floor everything and still pass the envelope).
    frac = lf - np.floor(lf)
    informative = (frac > 0.25) & (frac < 0.75)
    went_up = mag[informative] > np.floor(lf[informative]) + 0.5
    _require(0.05 < went_up.mean() < 0.95, "qsgd rounding is not stochastic")


def _check_pack() -> None:
    # Fused compress-and-pack kernels (ISSUE 10). Unlike the quant check,
    # BOTH comparisons here are bit-exact ON-CHIP: sign extraction is
    # deterministic, and the fused qsgd pack shares the quantize kernel's
    # hw-PRNG stream at equal seed/block layout, so fused == clamp->nibble
    # ->pack of the plain kernel's levels, byte for byte.
    import jax
    import jax.numpy as jnp
    import numpy as np

    from grace_tpu.ops.packing import pack_4bit, pack_bits
    from grace_tpu.ops.pallas_quant import (quantize_pack_stochastic,
                                            quantize_stochastic, sign_pack)

    flat = jax.random.normal(jax.random.key(1), (1_000_003,), jnp.float32)
    got = np.asarray(sign_pack(flat))
    want = np.asarray(pack_bits(flat >= 0))
    _require(np.array_equal(got, want), "sign_pack != pack_bits(x >= 0)")

    norm = jnp.linalg.norm(flat)
    packed = np.asarray(quantize_pack_stochastic(flat, norm, jnp.int32(7),
                                                 7))
    levels = np.clip(np.asarray(
        quantize_stochastic(flat, norm, jnp.int32(7), 7), np.int32), -7, 7)
    codes = np.where(levels < 0, levels + 16, levels).astype(np.uint8)
    _require(np.array_equal(packed, np.asarray(pack_4bit(
        jnp.asarray(codes)))),
             "fused quantize_pack != quantize -> clamp -> pack_4bit")


def main() -> int:
    import jax

    if jax.devices()[0].platform != "tpu":
        print("smoke: not on tpu", file=sys.stderr)
        return 2

    try:
        _check_topk()
    except Exception:
        traceback.print_exc()
        print("smoke: TOPK kernels FAILED", file=sys.stderr)
        return 1
    print("smoke: pallas chunk-topk kernels OK on", jax.devices()[0])

    try:
        _check_quant()
        _check_pack()
    except Exception:
        traceback.print_exc()
        print("smoke: QUANT kernel FAILED (topk OK)", file=sys.stderr)
        return 3
    print("smoke: pallas qsgd-quant + compress-and-pack kernels OK on",
          jax.devices()[0])
    return 0


if __name__ == "__main__":
    sys.exit(main())
