"""Render the repo's benchmark evidence files as one markdown summary.

Reads (all repo-root, all optional — missing files are skipped):
  BENCH_TPU_LAST.json      headline dense-vs-compressed pair (TPU)
  BENCH_ALL_TPU_LAST.json  per-algorithm TPU sweep
  BENCH_ALL_CPU.json       per-algorithm CPU-mesh smoke sweep
  TPU_VARIANTS.jsonl       selection-variant session rows

Usage: python tools/evidence_summary.py [--update-readme]
Prints markdown to stdout; --update-readme splices it between the
<!-- evidence:begin --> / <!-- evidence:end --> markers in README.md.
"""

from __future__ import annotations

import argparse
import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BEGIN, END = "<!-- evidence:begin -->", "<!-- evidence:end -->"


def _load(name):
    """Load a .json dict or a JSON-Lines row list (BENCH_ALL_CPU.json is
    JSONL despite its extension; rows whose only key is _meta are
    metadata, not data)."""
    try:
        with open(os.path.join(ROOT, name)) as f:
            text = f.read()
    except OSError:
        return None
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                return None
        return rows or None


def _fmt(x, nd=2):
    return "—" if x is None else f"{x:.{nd}f}"


def _row_table(rows, title):
    out = [f"**{title}**", "",
           "| config | imgs/sec | vs dense | wire ratio | MFU |",
           "|---|---|---|---|---|"]
    rows = [r for r in rows if r.get("config")]   # skip _meta-style rows
    for r in rows:
        flags = " ⚠staged" if r.get("env_pallas_disabled") else ""
        out.append(
            f"| {r.get('config')}{flags} | {_fmt(r.get('imgs_per_sec'))} | "
            f"{_fmt(r.get('vs_baseline'), 4)} | "
            f"{_fmt(r.get('wire_ratio'), 4)} | {_fmt(r.get('mfu'), 4)} |")
    return out


def build() -> str:
    parts = []
    head = _load("BENCH_TPU_LAST.json")
    if head and head.get("rows"):
        cap = head.get("captured_at", "?")
        chip = head.get("chip", "?")
        partial = " (PARTIAL)" if head.get("partial") else ""
        parts += _row_table(
            head["rows"],
            f"TPU headline ({chip}, captured {cap}){partial}")
        parts.append("")
    sweep = _load("BENCH_ALL_TPU_LAST.json")
    if sweep and sweep.get("rows"):
        cap = sweep.get("captured_at", "?")
        partial = " (PARTIAL)" if sweep.get("partial") else ""
        parts += _row_table(
            sweep["rows"], f"TPU per-algorithm sweep (captured {cap})"
            + partial)
        parts.append("")
    variants = _load("TPU_VARIANTS.jsonl")
    if variants:
        parts += _row_table(variants, "Top-K selection variants (TPU)")
        parts.append("")
    cpu = _load("BENCH_ALL_CPU.json")
    if isinstance(cpu, list):
        data_rows = [r for r in cpu if r.get("config")]
        if data_rows:
            parts.append(
                f"CPU-mesh smoke sweep: {len(data_rows)} configs in "
                "`BENCH_ALL_CPU.json` (throughput ratios are host-bound "
                "artifacts; the wire columns are the content).")
    return "\n".join(parts).rstrip() + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update-readme", action="store_true")
    args = ap.parse_args()
    md = build()
    if not args.update_readme:
        print(md, end="")
        return
    path = os.path.join(ROOT, "README.md")
    with open(path) as f:
        text = f.read()
    if BEGIN not in text or END not in text:
        raise SystemExit(f"README.md lacks {BEGIN} / {END} markers")
    pre = text.split(BEGIN)[0]
    post = text.split(END)[1]
    with open(path, "w") as f:
        f.write(pre + BEGIN + "\n" + md + END + post)
    print("README.md updated")


if __name__ == "__main__":
    main()
