"""Render the repo's benchmark evidence as one markdown summary.

Ledger-driven since graft-evidence: the enumeration authority is
``EVIDENCE/ledger.jsonl`` (``grace_tpu.evidence``) — every capture a
writer has attested shows up here, keyed by its ledger records. Captures
with a dedicated reader below render as rich tables/prose (annotated
with the ledger ids README claim markers cite); captures *without* one
fall through to a generic ledger table, so a ``REGION_LAST.json``-style
new artifact stops requiring per-file reader code the day its writer
lands. Flight-recorder incidents get a one-line roll-up. Known artifacts
found on disk still render even before their first ledger record, so a
fresh checkout (or a test tmp dir) degrades to the pre-ledger behavior.

Dedicated readers exist for (all repo-root, all optional):
  BENCH_TPU_LAST.json      headline dense-vs-compressed pair (TPU)
  BENCH_ALL_TPU_LAST.json  per-algorithm TPU sweep
  BENCH_BERT_TPU_LAST.json BERT-base + PowerSGD rows
  BENCH_ALL_CPU.json       per-algorithm CPU-mesh smoke sweep
  TPU_VARIANTS.jsonl       selection-variant session rows
  LINT_LAST.json / PROF_LAST.json / ELASTIC_LAST.json /
  REGION_LAST.json / ADAPT_LAST.json / RETUNE_LAST.json /
  WATCH_LAST.json / TUNE_LAST.json

Usage: python tools/evidence_summary.py [--update-readme]
Prints markdown to stdout; --update-readme splices it between the
<!-- evidence:begin --> / <!-- evidence:end --> markers in README.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
BEGIN, END = "<!-- evidence:begin -->", "<!-- evidence:end -->"


def _staleness(doc):
    """bench.evidence_staleness — the ONE stale-evidence detector, shared
    with the bench's own last_tpu carry-along readers (itself a delegate
    to grace_tpu.evidence.staleness since graft-evidence)."""
    import bench
    return bench.evidence_staleness(doc)


def _stale_parts(doc):
    """(title_suffix, trailing_lines) for a possibly-stale evidence doc."""
    reasons = _staleness(doc)
    if not reasons:
        return "", []
    return " — ⚠ STALE — predates PRs 7–10", [
        "", "⚠ **STALE — predates PRs 7–10**: " + "; ".join(reasons)
        + ". The numbers above describe the pre-hier/pre-bucketed/"
          "pre-fused-pack system; refresh the capture with "
          "`python bench_all.py --tuned` at the next chip window."]


def _load(name):
    """Load a .json dict or a JSON-Lines row list (BENCH_ALL_CPU.json is
    JSONL despite its extension; rows whose only key is _meta are
    metadata, not data)."""
    try:
        with open(os.path.join(ROOT, name)) as f:
            text = f.read()
    except OSError:
        return None
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                return None
        return rows or None


def _ledger_view():
    """(by_capture_basename, latest_by_id) over the repo ledger — empty
    dicts when no ledger exists (fresh checkout, test tmp dirs)."""
    path = os.path.join(ROOT, "EVIDENCE", "ledger.jsonl")
    try:
        from grace_tpu.evidence.ledger import latest_by_id, load_ledger
        latest = latest_by_id(load_ledger(path))
    except Exception:                                      # noqa: BLE001
        return {}, {}
    by_capture = {}
    for rec in latest.values():
        base = os.path.basename(str(rec.get("capture") or ""))
        if base:
            by_capture.setdefault(base, []).append(rec)
    for recs in by_capture.values():
        recs.sort(key=lambda r: (r.get("claim_class") or "",
                                 r.get("id") or ""))
    return by_capture, latest


def _ledger_note(recs):
    """One sub-line tying a rendered section to its ledger records — the
    same ids the README/CHANGELOG claim markers cite and graft_gate
    verifies."""
    if not recs:
        return []
    cite = ", ".join(f"`{r.get('id')}` [{r.get('claim_class', '?')}]"
                     for r in recs)
    return [f"<sub>ledger: {cite}</sub>"]


def _fmt(x, nd=2):
    return "—" if x is None else f"{x:.{nd}f}"


def _row_table(rows, title, value_key="imgs_per_sec",
               value_head="imgs/sec"):
    spread = any(r.get("spread_pct") is not None for r in rows)
    shead = " spread% |" if spread else ""
    out = [f"**{title}**", "",
           f"| config | {value_head} | vs dense | wire ratio | MFU |{shead}",
           "|---|---|---|---|---|" + ("---|" if spread else "")]
    rows = [r for r in rows if r.get("config")]   # skip _meta-style rows
    for r in rows:
        cfg_name = r.get("config") or ""
        # Scoped disables flag only the configs whose kernel family was
        # forced onto the staged path — keyed off the row's stamped
        # grace_params (ADVICE r4: a renamed config would silently lose
        # the caveat under name-substring matching; old rows without the
        # stamp keep the name fallback).
        compressor = (r.get("grace_params") or {}).get("compressor", "")
        flags = ""
        if r.get("env_pallas_disabled"):
            flags = " ⚠staged"
        elif r.get("env_pallas_quant_disabled") and (
                compressor == "qsgd" or
                (not compressor and "qsgd" in cfg_name)):
            flags = " ⚠staged-quant"
        elif r.get("env_pallas_topk_disabled") and (
                compressor == "topk" or
                (not compressor and "topk" in cfg_name)):
            flags = " ⚠staged-topk"
        if r.get("resumed"):
            flags += " ↻resumed"
        if r.get("error"):
            out.append(f"| {r.get('config')} | ERROR: {r['error'][:60]} |"
                       + " — |" * (3 + spread))
            continue
        scell = f" {_fmt(r.get('spread_pct'), 1)} |" if spread else ""
        out.append(
            f"| {r.get('config')}{flags} | {_fmt(r.get(value_key))} | "
            f"{_fmt(r.get('vs_baseline'), 4)} | "
            f"{_fmt(r.get('wire_ratio'), 4)} | {_fmt(r.get('mfu'), 4)} |"
            + scell)
    return out


def _curve_table():
    """Final-accuracy table over every committed curve TSV in
    examples/logs, read from each file's own provenance header (data
    source, config) and last data row — the files self-describe, so this
    can never quote a number the file does not contain."""
    import glob

    logs = sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "logs", "*.tsv")))
    rows = []
    for path in logs:
        prov, header, last = {}, None, None
        try:
            with open(path) as f:
                for line in f:
                    line = line.rstrip("\n")
                    if line.startswith("# ") and ": " in line:
                        k, v = line[2:].split(": ", 1)
                        prov[k] = v
                    elif line and header is None:
                        header = line.split("\t")
                    elif line:
                        last = line.split("\t")
        except OSError:
            continue
        if not header or not last:
            continue
        rec = dict(zip(header, last))
        acc = rec.get("test_acc") or rec.get("top1Accuracy")
        data = prov.get("data", "?").split(" (")[0]   # drop inline caveats
        comm_s = prov.get("communicator", "?")
        if prov.get("fusion"):   # stamped since round 5; absent = pre-stamp
            comm_s += f" ({prov['fusion']})"
        rows.append((os.path.basename(path), data,
                     prov.get("compressor", "?"), prov.get("memory", "?"),
                     prov.get("memory_dtype", ""), comm_s,
                     rec.get("epoch", "?"), acc if acc is not None else "?"))
    if not rows:
        return []
    out = ["**Convergence curves (examples/logs — final row of each "
           "committed TSV; provenance from the file's own header)**", "",
           "| file | data | compressor | memory | communicator | epochs |"
           " final acc |", "|---|---|---|---|---|---|---|"]
    for (name, data, comp, mem, mdt, comm, ep, acc) in rows:
        mem_s = f"{mem}({mdt})" if mdt else mem
        out.append(f"| {name} | {data} | {comp} | {mem_s} | {comm} |"
                   f" {ep} | {acc} |")
    if any(n.startswith("cifar10_") and "synthetic" in n
           for (n, *_rest) in rows):
        out += ["",
                "The `cifar10_*_synthetic` curves run the full DAWNBench "
                "recipe on synthetic data: they are recipe-mechanics and "
                "compression-stability evidence only. The reference's "
                "94%/24-epoch CIFAR-10 accuracy target "
                "(`examples/dist/CIFAR10-dawndist/README.md:17`) is "
                "**unvalidated here** — this box has zero network egress "
                "and no cached CIFAR-10 binaries (pip, keras.datasets and "
                "tfds download channels all fail). The real-data "
                "convergence evidence is the MNIST-10k / sklearn-digits "
                "family above."]
    return out


# ---------------------------------------------------------------------------
# Per-capture readers. Each takes the memoizing loader and returns the
# section's lines ([] = skip). The _SECTIONS table below is the dispatch:
# a capture basename listed there renders rich; anything else the ledger
# names renders through _generic_section.

def _sec_headline(docs):
    head = docs("BENCH_TPU_LAST.json")
    if not (head and head.get("rows")):
        return []
    cap = head.get("captured_at", "?")
    chip = head.get("chip", "?")
    partial = " (PARTIAL)" if head.get("partial") else ""
    suffix, trailer = _stale_parts(head)
    return _row_table(
        head["rows"],
        f"TPU headline ({chip}, captured {cap}){partial}{suffix}") + trailer


def _sec_sweep(docs):
    sweep = docs("BENCH_ALL_TPU_LAST.json")
    if not (sweep and sweep.get("rows")):
        return []
    cap = sweep.get("captured_at", "?")
    partial = " (PARTIAL)" if sweep.get("partial") else ""
    suffix, trailer = _stale_parts(sweep)
    parts = _row_table(
        sweep["rows"], f"TPU per-algorithm sweep (captured {cap})"
        + partial + suffix)
    parts += trailer
    # Same-named rows measured under different stamped params (e.g. the
    # round-5 headline moving to per-leaf after the sweep captured the
    # fused pair) read as contradictions without a caveat.
    head = docs("BENCH_TPU_LAST.json")
    if head and head.get("rows"):
        hp = {r["config"]: r.get("grace_params") for r in head["rows"]
              if r.get("grace_params")}
        drift = [r["config"] for r in sweep["rows"]
                 if r.get("grace_params") and
                 hp.get(r.get("config")) not in (None,
                                                 r["grace_params"])]
        if drift:
            parts += ["", "Note: " + ", ".join(sorted(set(drift))) +
                      " above were captured under different params than "
                      "the same-named headline rows (each row stamps its "
                      "own `grace_params`; the headline is the "
                      "authoritative config)."]
    return parts


def _sec_variants(docs):
    variants = docs("TPU_VARIANTS.jsonl")
    if not variants:
        return []
    return _row_table(
        variants,
        "Top-K selection variants (TPU) — SUPERSEDED: cross-session "
        "ratios (the dense row here hit the tunnel-RTT trap); the "
        "same-session sweep above is the quotable record")


def _sec_bert(docs):
    bert = docs("BENCH_BERT_TPU_LAST.json")
    if not (bert and bert.get("rows")):
        return []
    cap = bert.get("captured_at", "?")
    partial = " (PARTIAL)" if bert.get("partial") else ""
    return _row_table(
        bert["rows"], f"BERT-base + PowerSGD r4 (captured {cap})"
        + partial, value_key="tokens_per_sec", value_head="tokens/sec")


def _sec_projection(docs):
    rec = docs("BENCH_TPU_LAST.json") or {}
    proj = next((r["projection"] for r in rec.get("rows", [])
                 if r.get("config") == "topk1pct" and r.get("projection")),
                None)
    if not proj:
        return []
    parts = ["**Projected multi-chip speedup vs dense (topk1pct, "
             "analytic wire model over measured single-chip step)**", "",
             "| world | recv bytes/rank | step ms (ICI) | speedup ICI "
             "| speedup DCN |", "|---|---|---|---|---|"]
    for p in proj:
        parts.append(f"| {p['world']} | {p['recv_bytes_per_rank']:,} | "
                     f"{p['step_ms_ici']} | "
                     f"{p['speedup_vs_dense_ici']} | "
                     f"{p['speedup_vs_dense_dcn']} |")
    return parts


def _sec_cpu(docs):
    cpu = docs("BENCH_ALL_CPU.json")
    if not isinstance(cpu, list):
        return []
    data_rows = [r for r in cpu
                 if r.get("config") and r.get("imgs_per_sec")]
    skipped = [r["config"] for r in cpu if r.get("skipped")]
    if not data_rows:
        return []
    skip_s = (f"; skipped on cpu: {', '.join(skipped)}"
              if skipped else "")
    return [f"CPU-mesh smoke sweep: {len(data_rows)} configs measured "
            "in `BENCH_ALL_CPU.json` (throughput ratios are host-bound "
            f"artifacts; the wire columns are the content{skip_s})."]


def _sec_lint(docs):
    lint = docs("LINT_LAST.json")
    if not (isinstance(lint, dict) and "errors" in lint):
        return []
    when = (lint.get("captured_at") or "").split("T")[0]
    counts = lint.get("pass_counts") or {}
    if counts:
        dirty = {p: n for p, n in counts.items() if n}
        per_pass = (f"; per-pass findings: "
                    + ", ".join(f"{p} {n}"
                                for p, n in sorted(dirty.items()))
                    if dirty else
                    f"; all {len(counts)} passes clean")
    else:
        per_pass = ""
    bounds = lint.get("overlap_bounds") or {}
    bound_s = ""
    if bounds:
        bound_s = ("; bucketed overlap bounds: " + ", ".join(
            f"{name} static≤{rep.get('static_overlap_bound')} "
            f"({rep.get('independent_chains')}/"
            f"{rep.get('expected_chains')} chains)"
            for name, rep in sorted(bounds.items())
            if isinstance(rep, dict) and "error" not in rep))
    return [
        f"Static analysis: `graft_lint --all-configs` → "
        f"{lint['errors']} error(s) / {lint.get('warnings', 0)} "
        f"warning(s) over {lint.get('configs_audited', '?')} configs + "
        f"{lint.get('rules_checked', '?')} repo rules"
        f"{per_pass}{bound_s} "
        f"(`LINT_LAST.json`{', ' + when if when else ''})."]


def _sec_prof(docs):
    prof = docs("PROF_LAST.json")
    if not (isinstance(prof, dict) and prof.get("stages_ms")):
        return []
    when = (prof.get("captured_at") or "").split("T")[0]
    top = max(prof["stages_ms"].items(), key=lambda kv: kv[1])
    ov = prof.get("overlap_fraction")
    steps = prof.get("step_times") or {}
    bits = [f"total device time {_fmt(prof.get('total_device_ms'), 3)} "
            f"ms, top stage {top[0]} ({_fmt(top[1], 3)} ms)"]
    if ov is not None:
        bits.append(f"overlap fraction {100.0 * ov:.1f}%")
    sand = prof.get("overlap_sandwich")
    if isinstance(sand, dict):
        verdict = ("VIOLATED" if sand.get("violations") else "holds")
        bits.append(
            f"measured≤static sandwich vs {sand.get('config')} "
            f"(bound {sand.get('static_overlap_bound')}): {verdict}")
    if steps.get("p50_ms") is not None:
        bits.append(f"step p50 {_fmt(steps['p50_ms'], 3)} ms")
    regr = prof.get("regressions")
    if regr is not None:
        bits.append(f"{len(regr)} baseline regression(s)")
    note = f" — {prof['note']}" if prof.get("note") else ""
    return [
        f"Performance attribution: `perf_report --trace "
        f"{prof.get('trace', '?')}` → " + ", ".join(bits) +
        f" (`PROF_LAST.json`{', ' + when if when else ''}){note}."]


def _sec_elastic(docs):
    elastic = docs("ELASTIC_LAST.json")
    if not (isinstance(elastic, dict)
            and elastic.get("tool") == "chaos_smoke"):
        return []
    when = (elastic.get("captured_at") or "").split("T")[0]
    cycle = " → ".join(str(w) for w in (elastic.get("world_cycle") or []))
    resizes = elastic.get("resize_events") or []
    rejoin = elastic.get("rejoin") or {}
    floor = elastic.get("floor") or {}
    fp = elastic.get("footprint") or {}
    bits = [f"world cycle {cycle}" if cycle else "no resize recorded",
            f"{len(resizes)} resize event(s)"]
    if rejoin:
        verdict = ("bit-identical" if rejoin.get("replica_variants") == 1
                   else f"{rejoin.get('replica_variants')} variants")
        bits.append(
            f"rejoin barrier: {rejoin.get('barrier_repairs', '?')} "
            f"repair(s) for {rejoin.get('rejoins', '?')} rejoin(s), "
            f"replicas {verdict} "
            f"(fingerprint {rejoin.get('fingerprint_bytes', '?')} B)")
    if floor:
        met = "met" if floor.get("met") else "MISSED"
        bits.append(f"convergence floor {met} "
                    f"(final loss {_fmt(floor.get('final_loss'), 4)} vs "
                    f"floor {_fmt(floor.get('floor'), 2)})")
    if fp:
        ok = all(bool(v) for v in fp.values())
        bits.append("re-shard footprint vs flow pass 7 model: "
                    + ("matches at "
                       + ", ".join(f"W={k}" for k in sorted(fp))
                       if ok else f"MISMATCH {fp}"))
    return [
        "Elastic training (graft-elastic): `chaos_smoke --elastic` → "
        + ", ".join(bits)
        + f" (`ELASTIC_LAST.json`{', ' + when if when else ''})."]


def _sec_region(docs):
    region = docs("REGION_LAST.json")
    if not (isinstance(region, dict)
            and region.get("tool") == "chaos_smoke"):
        return []
    when = (region.get("captured_at") or "").split("T")[0]
    cycle = " → ".join(str(w) for w in (region.get("world_cycle") or []))
    drain = region.get("drain") or {}
    rejoin = region.get("rejoin") or {}
    floor = region.get("floor") or {}
    fp = region.get("footprint") or {}
    layout = (f"{region.get('regions', '?')} regions × "
              f"region {region.get('region_size', '?')} / "
              f"slice {region.get('slice_size', '?')}")
    bits = [f"world cycle {cycle} ({layout})"]
    if drain:
        scoped = ("region-wide" if drain.get("region_wide")
                  else f"PARTIAL scope {drain.get('scope')}")
        bits.append(
            f"{drain.get('transitions', '?')} drain transition(s) for "
            f"drift on ranks {region.get('drift_ranks')} — {scoped}, "
            f"{drain.get('drain_timeouts', 0)} watchdog timeout(s)")
    if rejoin:
        verdict = ("bit-identical" if rejoin.get("replica_variants") == 1
                   else f"{rejoin.get('replica_variants')} variants")
        bits.append(
            f"region rejoin barrier: {rejoin.get('barrier_repairs', '?')}"
            f" repair(s) for {rejoin.get('rejoins', '?')} region "
            f"rejoin(s) ({rejoin.get('rejoined_ranks', '?')} ranks), "
            f"replicas {verdict}")
    if floor:
        met = "met" if floor.get("met") else "MISSED"
        bits.append(f"convergence floor {met} "
                    f"(final loss {_fmt(floor.get('final_loss'), 4)} vs "
                    f"floor {_fmt(floor.get('floor'), 2)})")
    if fp:
        ok = all(bool(v) for v in fp.values())
        bits.append("re-shard footprint vs flow pass 7 model: "
                    + ("matches at "
                       + ", ".join(f"W={k}" for k in sorted(fp))
                       if ok else f"MISMATCH {fp}"))
    if region.get("guard_silent") is not None:
        bits.append("guard "
                    + ("silent through the drift phase"
                       if region.get("guard_silent") else "TRIPPED"))
    return [
        "Cross-region elasticity (graft-region): `chaos_smoke "
        "--region` → " + ", ".join(bits)
        + f" (`REGION_LAST.json`{', ' + when if when else ''})."]


def _sec_adapt(docs):
    adapt = docs("ADAPT_LAST.json")
    if not (isinstance(adapt, dict)
            and adapt.get("tool") == "chaos_smoke"):
        return []
    when = (adapt.get("captured_at") or "").split("T")[0]
    ti = adapt.get("tighten") or {}
    lo = adapt.get("loosen") or {}
    within = "within one window" if ti.get("within_one_window") \
        else "LATE (outside one window)"
    order = ("adapt_tighten precedes the first guard event"
             if adapt.get("ordering_ok")
             else "ORDERING VIOLATED (guard fired first)")
    bits = [
        f"{len(adapt.get('ladder') or [])}-rung ladder, window "
        f"{adapt.get('window', '?')} steps",
        f"drift → {ti.get('count', '?')} tighten(s), first at step "
        f"{ti.get('first_step', '?')} ({within})",
        f"quiet → {lo.get('count', '?')} loosen(s)",
        f"NaN → {adapt.get('guard_skips', '?')} guard skip(s), "
        f"{adapt.get('escalations', '?')} escalate-and-hold(s)",
        order,
    ]
    return [
        "Adaptive compression (graft-adapt): `chaos_smoke --adapt` → "
        + ", ".join(bits)
        + f" (`ADAPT_LAST.json`{', ' + when if when else ''})."]


def _sec_retune(docs):
    retune = docs("RETUNE_LAST.json")
    if not (isinstance(retune, dict)
            and retune.get("tool") == "chaos_smoke"):
        return []
    when = (retune.get("captured_at") or "").split("T")[0]
    drift = retune.get("drift") or {}
    fwd = retune.get("forward_promotion") or {}
    sab = retune.get("sabotage") or {}
    funnel = retune.get("funnel") or {}
    mig = fwd.get("migration") or {}
    mem = mig.get("mem") or {}
    comp = mig.get("comp") or {}
    bits = [
        f"{retune.get('incumbent', '?')} → {retune.get('candidate', '?')} "
        f"over window {retune.get('window', '?')} steps",
        f"drift verdict at step {drift.get('verdict_step', '?')} "
        f"(onset {drift.get('from_step', '?')})",
    ]
    if funnel:
        bits.append(f"re-tune funnel winner `{funnel.get('winner', '?')}` "
                    f"({len(funnel.get('measured') or [])} measured, "
                    f"{len(funnel.get('skipped') or [])} skipped)")
    if fwd:
        variants = ("bit-identical"
                    if fwd.get("replica_variants") == 1
                    else f"{fwd.get('replica_variants')} variants")
        bits.append(
            f"two-phase promotion at step {fwd.get('step', '?')} "
            f"(state migration carried {mem.get('carried', 0)}+"
            f"{comp.get('carried', 0)} / overlap "
            f"{mem.get('overlap', 0)}+{comp.get('overlap', 0)} / "
            f"fresh {mem.get('fresh', 0)}+{comp.get('fresh', 0)}, "
            f"replicas {variants})")
    if sab:
        within = ("inside probation" if sab.get("within_probation")
                  else "OUTSIDE probation")
        bit = ("bit-exact" if sab.get("bit_exact")
               else "NOT bit-exact" if sab.get("restored")
               else "NOT restored")
        bits.append(
            f"sabotaged promote → `{sab.get('trigger', '?')}` at step "
            f"{sab.get('trigger_step', '?')} ({within}), demotion to "
            f"last-known-good {bit}")
    order = ("drift→prepare→promote→clear ordering holds"
             if retune.get("ordering_ok")
             else "ORDERING VIOLATED")
    bits.append(order)
    return [
        "Online re-tuning (graft-retune): `chaos_smoke --retune` → "
        + ", ".join(bits)
        + f" (`RETUNE_LAST.json`{', ' + when if when else ''})."]


def _sec_watch(docs):
    watch = docs("WATCH_LAST.json")
    if not (isinstance(watch, dict)
            and watch.get("tool") == "graft_watch"):
        return []
    when = (watch.get("captured_at") or "").split("T")[0]
    counts = watch.get("kind_counts") or {}
    bits = [f"{watch.get('events', '?')} events "
            f"({', '.join(f'{k} {v}' for k, v in sorted(counts.items()))})",
            f"{watch.get('anomalies', 0)} anomaly record(s)"]
    ranks = watch.get("anomalous_ranks")
    if ranks:
        bits.append(f"anomalous rank(s) {ranks} first flagged at step "
                    f"{watch.get('first_anomaly_step')}")
    regr = watch.get("regressions")
    if regr is not None:
        bits.append(f"{len(regr)} baseline regression(s)")
    note = (" — seeded single-rank drift scenario, not a healthy run"
            if ranks else "")
    return [
        f"Run health (graft-watch): `graft_watch "
        f"{watch.get('artifact', '?')}` → " + ", ".join(bits) +
        f" (`WATCH_LAST.json`{', ' + when if when else ''}){note}."]


def _sec_tune(docs):
    tune = docs("TUNE_LAST.json")
    if not (isinstance(tune, dict) and tune.get("tool") == "graft_tune"):
        return []
    when = (tune.get("captured_at") or "").split("T")[0]
    bits = []
    for label, st in sorted((tune.get("static") or {}).items()):
        c = st.get("counts") or {}
        top = (st.get("ranking") or [{}])[0].get("candidate", "?")
        bits.append(
            f"{label}: {c.get('enumerated', '?')} enumerated → "
            f"{c.get('capability_rejected', 0)} capability / "
            f"{c.get('numeric_rejected', 0)} numeric / "
            f"{c.get('degradation_rejected', 0)} degradation rejected "
            f"→ {c.get('shortlisted', 0)} shortlisted, "
            f"top static pick `{top}`")
    w = tune.get("winner")
    if w:
        s = w.get("overlap_sandwich") or {}
        m = w.get("measured") or {}
        verdict = "holds" if s.get("holds") else "VIOLATED"
        bits.append(
            f"winner `{w.get('candidate')}` at {tune.get('target')} "
            f"(measured step {m.get('measured_step_ms', '?')} ms, "
            f"×{m.get('measured_speedup_vs_dense', '?')} vs dense "
            f"same-session; measured≤static overlap sandwich "
            f"{s.get('measured_overlap')}≤"
            f"{s.get('static_overlap_bound')}: {verdict}) — load with "
            f"`grace_from_params(TUNE_LAST.winner.grace_params)`")
    elif tune.get("static_only"):
        bits.append("static-only survey (no measured winner stamped)")
    platform = (tune.get("provenance") or {}).get("platform")
    note = (" — CPU-mesh pipeline evidence, not a chip capture"
            if platform and platform != "tpu" else "")
    return [
        "Autotuning (graft-tune): `graft_tune` → " + "; ".join(bits)
        + f" (`TUNE_LAST.json`{', ' + when if when else ''}){note}."]


# Dispatch: capture basename → dedicated reader, in render order. The
# None-keyed entries are views, not captures of their own (the projection
# table reads the headline doc; curve TSVs self-describe).
_SECTIONS = (
    ("BENCH_TPU_LAST.json", _sec_headline),
    ("BENCH_ALL_TPU_LAST.json", _sec_sweep),
    ("TPU_VARIANTS.jsonl", _sec_variants),
    ("BENCH_BERT_TPU_LAST.json", _sec_bert),
    (None, _sec_projection),
    (None, lambda docs: _curve_table()),
    ("BENCH_ALL_CPU.json", _sec_cpu),
    ("LINT_LAST.json", _sec_lint),
    ("PROF_LAST.json", _sec_prof),
    ("ELASTIC_LAST.json", _sec_elastic),
    ("REGION_LAST.json", _sec_region),
    ("ADAPT_LAST.json", _sec_adapt),
    ("RETUNE_LAST.json", _sec_retune),
    ("WATCH_LAST.json", _sec_watch),
    ("TUNE_LAST.json", _sec_tune),
)


def _generic_section(base, recs):
    """Ledger-driven fallback: a capture attested in the ledger but with
    no dedicated reader above still renders — ids, metric, claim class
    and provenance straight from its records."""
    out = [f"**`{base}`** (from the evidence ledger — no dedicated "
           "reader)", "",
           "| ledger id | metric | value | class | platform | devices |"
           " captured |", "|---|---|---|---|---|---|---|"]
    for r in recs:
        when = (r.get("timestamp") or "").split("T")[0]
        out.append(
            f"| `{r.get('id')}` | {r.get('metric', '?')} | "
            f"{r.get('value')} | {r.get('claim_class', '?')} | "
            f"{r.get('platform') or '—'} | {r.get('n_devices') or '—'} | "
            f"{when or '—'} |")
    return out


def _incident_rollup(latest):
    """Flight-recorder roll-up: ledger records minted by the incident
    recorder plus whatever sits under EVIDENCE/incidents/."""
    import glob
    incs = [r for r in latest.values()
            if r.get("tool") == "flight_recorder"]
    files = glob.glob(os.path.join(ROOT, "EVIDENCE", "incidents",
                                   "*.json"))
    if not incs and not files:
        return []
    return [f"Flight recorder: {len(files)} incident record(s) under "
            f"`EVIDENCE/incidents/` ({len(incs)} ledger-attached) — each "
            "snapshots the telemetry ring, watch timeline, adapt rung "
            "history and profiler attribution at its trigger step."]


def build() -> str:
    cache = {}

    def docs(name):
        if name not in cache:
            cache[name] = _load(name)
        return cache[name]

    by_capture, latest = _ledger_view()
    parts = []
    covered = set()
    for base, render in _SECTIONS:
        if base is not None:
            covered.add(base)
        lines = render(docs)
        if not lines:
            continue
        parts += lines
        if base is not None:
            parts += _ledger_note(by_capture.get(base) or [])
        parts.append("")
    # Ledger captures nobody above reads: generic render. Incident
    # records roll up as one line rather than one section per file.
    extras = sorted(base for base, recs in by_capture.items()
                    if base not in covered
                    and not all(r.get("tool") == "flight_recorder"
                                for r in recs))
    for base in extras:
        parts += _generic_section(base, by_capture[base])
        parts.append("")
    parts += _incident_rollup(latest)
    return "\n".join(parts).rstrip() + "\n"


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--update-readme", action="store_true")
    args = ap.parse_args()
    md = build()
    if not args.update_readme:
        print(md, end="")
        return
    path = os.path.join(ROOT, "README.md")
    with open(path) as f:
        text = f.read()
    if BEGIN not in text or END not in text:
        raise SystemExit(f"README.md lacks {BEGIN} / {END} markers")
    pre = text.split(BEGIN)[0]
    post = text.split(END)[1]
    with open(path, "w") as f:
        f.write(pre + BEGIN + "\n" + md + END + post)
    print("README.md updated")


if __name__ == "__main__":
    main()
