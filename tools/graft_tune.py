#!/usr/bin/env python
"""graft-tune CLI: topology-aware automatic config selection.

Enumerates (codec, communicator, fusion, pallas, precision) candidates
from the audited registry plus generated variants, prunes them statically
(capability gates, numeric safety at the target world, per-link wire
pricing under the target topology, graft-flow overlap/numeric/footprint
passes), measures the shortlist with real timed steps, and stamps a
provenance-carrying winner config into ``TUNE_LAST.json`` — gated by the
measured≤static overlap sandwich. See grace_tpu/tuning/ and IMPLEMENTING.md
"Static prune → measured shortlist → sandwich gate".

Exit status: 0 clean, 1 gate violation (no measurable winner, or the
winner's overlap sandwich is violated), 2 crash/usage — CI-gateable.

Usage::

    python tools/graft_tune.py --static-only              # rank, don't run
    python tools/graft_tune.py --topology 8               # single slice, W=8
    python tools/graft_tune.py --topology 256,8 --static-only
    python tools/graft_tune.py --topology 8 --shortlist 3 --json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The default --static-only survey: the single-slice regime every
# committed measurement ran in, and the xslice projection topology the
# hier communicator was built for.
DEFAULT_TOPOLOGIES = ("8", "256,8")


def _render(doc: dict) -> str:
    out = []
    for label, st in doc["static"].items():
        c = st["counts"]
        out.append(f"== static ranking @ {label} "
                   f"(model={doc['model']}) ==")
        out.append(
            f"funnel: {c['enumerated']} enumerated -> "
            f"{c['capability_rejected']} capability-rejected, "
            f"{c['numeric_rejected']} numeric-rejected, "
            f"{c['degradation_rejected']} degradation-rejected -> "
            f"{c['priced']} priced -> {c['flow_rejected']} flow-rejected "
            f"-> {c['shortlisted']} shortlisted")
        for i, r in enumerate(st["ranking"][:10]):
            mark = "*" if r["verdict"] == "shortlisted" else " "
            out.append(
                f" {mark}{i + 1:2d}. {r['candidate']:36s} "
                f"proj {r['projected_step_ms']:.4f} ms  "
                f"x{r['predicted_speedup_vs_dense']} vs dense  "
                f"(ici {r['ici_bytes']:,} B / dcn {r['dcn_bytes']:,} B)")
        out.append("")
    m = doc.get("measured")
    if m:
        out.append(f"== measured shortlist @ {doc['target']} "
                   f"(world={m['measured_world']}, {m['repeats']}x"
                   f"{m['timed_steps']} steps) ==")
        for r in m["rows"]:
            out.append(
                f"  {r['candidate']:36s} measured "
                f"{r['measured_step_ms']:.3f} ms "
                f"(dense {r['baseline_step_ms']:.3f}) -> projected "
                f"{r['projected_step_ms']:.3f} ms at target")
        for s in m["skipped"]:
            out.append(f"  {s['candidate']:36s} SKIPPED: {s['reason']}")
        out.append("")
    w = doc.get("winner")
    if w:
        s = w["overlap_sandwich"]
        out.append(f"WINNER: {w['candidate']} @ {doc['target']}")
        out.append(f"  grace_from_params({json.dumps(w['grace_params'])})")
        out.append(
            f"  sandwich: measured={s['measured_overlap']} <= "
            f"static bound={s['static_overlap_bound']} (+{s['slack']}): "
            + ("holds" if s["holds"] else "VIOLATED"))
    if doc.get("error"):
        out.append(f"ERROR: {doc['error']}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--topology", action="append", default=[],
                    help="target mesh as 'W' or 'W,slice_size' (repeatable;"
                         " first one is the decision target; default: "
                         + " + ".join(DEFAULT_TOPOLOGIES) + ")")
    ap.add_argument("--model", default="toy",
                    help="param tree to price and measure against "
                         "('toy' — the audit registry's model; resnet rows "
                         "run through bench_all --tuned)")
    ap.add_argument("--shortlist", type=int, default=3,
                    help="how many ranked survivors to measure (default 3)")
    ap.add_argument("--static-only", action="store_true",
                    help="enumerate + prune + rank only; no timed steps")
    ap.add_argument("--timed-steps", type=int, default=8,
                    help="steps per timing window (default 8)")
    ap.add_argument("--repeats", type=int, default=2,
                    help="interleaved dense/candidate sample pairs "
                         "(default 2)")
    ap.add_argument("--audit-world", type=int, default=8,
                    help="abstract mesh size for the flow passes "
                         "(default 8)")
    ap.add_argument("--json", action="store_true",
                    help="emit the evidence document instead of text")
    ap.add_argument("--out", default=None,
                    help="evidence path ('' disables; default TUNE_LAST."
                         "json at the repo root, consumed by "
                         "tools/evidence_summary.py)")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    on_cpu = os.environ["JAX_PLATFORMS"].lower() == "cpu"
    if on_cpu:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    if not args.static_only and on_cpu:
        # The measured shortlist needs a real mesh; mirror the test
        # harness's 8 simulated devices. Must run BEFORE the first
        # jax.devices() call — backend init freezes the device count.
        from grace_tpu.parallel import (relax_cpu_collective_timeouts,
                                        set_cpu_device_count)
        set_cpu_device_count(8)
        relax_cpu_collective_timeouts()

    from grace_tpu.tuning import (TUNE_EVIDENCE_PATH, run_tune,
                                  write_tune_evidence)

    topologies = tuple(args.topology) or DEFAULT_TOPOLOGIES
    doc = run_tune(topologies, model=args.model,
                   shortlist_n=args.shortlist,
                   static_only=args.static_only,
                   audit_world=args.audit_world,
                   timed_steps=args.timed_steps, repeats=args.repeats,
                   argv=" ".join(sys.argv[1:]))

    out = TUNE_EVIDENCE_PATH if args.out is None else args.out
    if out:
        try:
            write_tune_evidence(doc, out)
        except OSError as e:
            print(f"[graft_tune] could not save {out}: {e}",
                  file=sys.stderr)

    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(_render(doc))
    return 0 if doc.get("ok") else 1


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:                                 # noqa: BLE001
        print(f"[graft_tune] crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
