#!/usr/bin/env python
"""graft-watch CLI: unified run timeline + anomaly report over one artifact.

Input: a JSONL run artifact as written by ``grace_tpu.telemetry.JSONLSink``
— telemetry metric rows, graft-watch summaries, ``watch_anomaly`` records,
guard/consensus transitions, ``perf_*`` profiling records, and
``lint_finding`` events, all in one stream. This tool is the read side:

* default / ``--timeline``: the merged, step-keyed timeline
  (:class:`grace_tpu.telemetry.Timeline`) — the answer to "what happened
  around step N" without hand-joining five record shapes;
* ``--anomalies``: re-run the streaming detectors
  (:class:`grace_tpu.telemetry.WatchMonitor`) over the artifact offline —
  so a run recorded *without* live detection can still be triaged — and
  list both the recorded and the re-derived findings;
* ``--baseline FILE``: regression gate. Compares the run's summary
  (anomaly counts by detector kind, max scores, first-anomaly step,
  guard/consensus activity) against a stored baseline
  (``--write-baseline``): new anomaly kinds, growing counts, rising max
  scores, or resilience events appearing where the baseline had none are
  regressions. The graft-lint/perf_report idiom: watch facts become
  CI-checkable.

Writes the ``WATCH_LAST.json`` evidence document consumed by
``tools/evidence_summary.py`` (``--out ''`` disables). Pure host-side —
stdlib only, no jax import, usable on any box that holds the artifact.

Exit status: 0 clean, 1 baseline regression, 2 crash — CI-gateable.

Usage::

    python tools/graft_watch.py chaos_telemetry.jsonl
    python tools/graft_watch.py run.jsonl --anomalies
    python tools/graft_watch.py run.jsonl --json
    python tools/graft_watch.py run.jsonl --write-baseline WATCH_BASELINE.json
    python tools/graft_watch.py run.jsonl --baseline WATCH_BASELINE.json
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(ROOT, "WATCH_LAST.json")

# Headroom of the baseline gate on anomaly max scores: a detector score is
# already a ratio over its own threshold band, so growth beyond 25% over
# the baseline's worst episode is a real escalation, not jitter.
SCORE_RTOL = 0.25


def _now() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def _atomic_write(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def compare_to_baseline(current: dict, baseline: dict,
                        score_rtol: float = SCORE_RTOL) -> list:
    """Regression findings of a timeline summary against a stored one.

    Anomaly regressions: a detector kind fires that the baseline never
    saw, fires more often, or fires harder (max score beyond rtol).
    Resilience regressions: guard/consensus/lint events appear (or grow)
    where the baseline had fewer — watch is the early-warning layer, so
    the downstream layers lighting up IS the regression signal.
    """
    findings = []
    cur_by = current.get("anomalies_by_kind") or {}
    base_by = baseline.get("anomalies_by_kind") or {}
    for kind in sorted(cur_by):
        cur_n, base_n = cur_by[kind], base_by.get(kind, 0)
        if cur_n > base_n:
            findings.append(
                f"anomaly kind '{kind}': {cur_n} event(s) vs baseline "
                f"{base_n}" + (" (new kind)" if base_n == 0 else ""))
    cur_scores = current.get("anomaly_max_score") or {}
    base_scores = baseline.get("anomaly_max_score") or {}
    for kind, cur_s in sorted(cur_scores.items()):
        base_s = base_scores.get(kind)
        if base_s and cur_s > base_s * (1.0 + score_rtol):
            findings.append(
                f"anomaly kind '{kind}': max score {cur_s:.3g} vs "
                f"baseline {base_s:.3g} (+{100 * (cur_s / base_s - 1):.0f}%"
                f", tolerance {100 * score_rtol:.0f}%)")
    cur_counts = current.get("kind_counts") or {}
    base_counts = baseline.get("kind_counts") or {}
    for kind in ("guard", "consensus", "lint"):
        cur_n, base_n = cur_counts.get(kind, 0), base_counts.get(kind, 0)
        if cur_n > base_n:
            findings.append(
                f"{kind} events: {cur_n} vs baseline {base_n} — the "
                "downstream resilience layer fired more than the baseline "
                "run")
    cur_first = current.get("first_anomaly_step")
    base_first = baseline.get("first_anomaly_step")
    if cur_first is not None and base_first is not None \
            and cur_first < base_first:
        findings.append(
            f"first anomaly at step {cur_first} vs baseline {base_first} "
            "— the run degrades earlier than it used to")
    return findings


def baseline_view(summary: dict) -> dict:
    """The comparable subset of a timeline summary, for --write-baseline."""
    return {
        "anomalies": summary.get("anomalies", 0),
        "anomalies_by_kind": summary.get("anomalies_by_kind") or {},
        "anomaly_max_score": summary.get("anomaly_max_score") or {},
        "kind_counts": summary.get("kind_counts") or {},
        "first_anomaly_step": summary.get("first_anomaly_step"),
        "captured_at": _now(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="telemetry JSONL artifact (JSONLSink "
                                 "output)")
    ap.add_argument("--timeline", action="store_true",
                    help="render the merged step-keyed timeline (default "
                         "when no other view is selected)")
    ap.add_argument("--anomalies", action="store_true",
                    help="re-run the streaming detectors offline and list "
                         "recorded + re-derived anomalies")
    ap.add_argument("--kinds", default=None,
                    help="comma-separated timeline kind filter "
                         "(telemetry,watch,anomaly,guard,consensus,perf,"
                         "lint,other)")
    ap.add_argument("--limit", type=int, default=60,
                    help="max timeline lines (0 = unlimited)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON document (summary + anomalies) "
                         "instead of text")
    ap.add_argument("--baseline", default=None,
                    help="stored baseline JSON to gate against "
                         "(--write-baseline output)")
    ap.add_argument("--write-baseline", default=None,
                    help="write the comparable summary subset to this "
                         "path")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="evidence document path ('' disables; default "
                         "WATCH_LAST.json at the repo root, consumed by "
                         "tools/evidence_summary.py)")
    args = ap.parse_args(argv)

    from grace_tpu.telemetry.anomaly import WatchMonitor
    from grace_tpu.telemetry.timeline import Timeline

    timeline = Timeline.from_jsonl(args.path)
    summary = timeline.summary()

    recorded = [e.record for e in timeline.anomalies()]
    derived = []
    if args.anomalies:
        # Offline re-derivation: replay every non-anomaly record through a
        # fresh monitor. On a run that armed live detection this re-finds
        # the same episodes; on one that didn't, it's the triage pass.
        monitor = WatchMonitor()
        derived = monitor.observe(
            e.record for e in timeline if e.kind != "anomaly")

    regressions = []
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)
        regressions = compare_to_baseline(summary, baseline)

    doc = {
        "tool": "graft_watch",
        "artifact": args.path,
        **summary,
        "recorded_anomalies": recorded,
    }
    if args.anomalies:
        doc["derived_anomalies"] = derived
    if args.baseline:
        doc["baseline"] = args.baseline
        doc["regressions"] = regressions

    if args.write_baseline:
        _atomic_write(args.write_baseline, baseline_view(summary))
        print(f"[graft_watch] baseline -> {args.write_baseline}",
              file=sys.stderr)

    if args.out:
        # Uniform provenance stamp (ISSUE 17): the watch doc carries the
        # same n_devices/topology/git_rev triple as every other evidence
        # writer, lifted from the artifact's own provenance header.
        prov = timeline.provenance or {}
        n_dev = prov.get("n_devices")
        try:
            from grace_tpu.evidence.ledger import git_head_rev
            rev = git_head_rev()
        except Exception:                                  # noqa: BLE001
            rev = None
        stamped = {**doc, "git_rev": rev, "n_devices": n_dev,
                   "topology": ({"world": n_dev, "tiers": ["ici"],
                                 "slice": None, "region": None}
                                if n_dev else None),
                   "captured_at": _now()}
        try:
            _atomic_write(args.out, stamped)
        except OSError as e:
            print(f"[graft_watch] could not save {args.out}: {e}",
                  file=sys.stderr)
        else:
            if os.path.dirname(os.path.abspath(args.out)) == ROOT:
                try:
                    from grace_tpu.evidence.ledger import record_artifact
                    record_artifact(
                        args.out, id="watch-drill",
                        metric="watch_anomalies",
                        value=doc.get("anomalies"),
                        claim_class="measured", tool="graft_watch",
                        platform=prov.get("platform"),
                        chip=prov.get("device"), n_devices=n_dev,
                        topology=stamped["topology"],
                        config=args.path, lint_clean=None, git_rev=rev)
                except Exception as e:                     # noqa: BLE001
                    print(f"[graft_watch] ledger emission failed: {e}",
                          file=sys.stderr)

    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        kinds = tuple(args.kinds.split(",")) if args.kinds else None
        if args.timeline or not args.anomalies:
            print(timeline.render(kinds=kinds,
                                  limit=args.limit or None))
            print()
        if args.anomalies:
            print(f"== anomalies (recorded {len(recorded)}, re-derived "
                  f"{len(derived)}) ==")
            # Dedup by identity, not dict equality — a re-derived finding
            # is "the same anomaly" when it names the same episode, even
            # if float formatting differs across the JSON round-trip.
            ident = lambda a: (a.get("step"), a.get("kind"),       # noqa: E731
                               a.get("metric"), a.get("rank"))
            known = {ident(a) for a in recorded}
            seen = recorded + [d for d in derived
                               if ident(d) not in known]
            for a in seen:
                print(f"  step {a.get('step', '?'):>6}: "
                      f"{a.get('kind', '?')}/{a.get('metric', '?')} "
                      f"rank={a.get('rank', -1)} "
                      f"score={a.get('score', 0):.3g} "
                      f"value={a.get('value', 0):.4g}")
            if not seen:
                print("  (none)")
            print()
        counts = summary.get("kind_counts") or {}
        print("== summary ==")
        print("  " + ", ".join(f"{k}: {v}" for k, v in
                               sorted(counts.items())))
        if summary.get("anomalous_ranks"):
            print(f"  anomalous ranks: {summary['anomalous_ranks']} "
                  f"(first anomaly at step "
                  f"{summary.get('first_anomaly_step')})")
        if args.baseline:
            if regressions:
                print(f"\nBASELINE REGRESSIONS ({len(regressions)}) vs "
                      f"{args.baseline}:")
                for r in regressions:
                    print(f"  REGRESSION {r}")
            else:
                print(f"\nbaseline {args.baseline}: within tolerance")
    return 1 if regressions else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:                                 # noqa: BLE001
        print(f"[graft_watch] crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
