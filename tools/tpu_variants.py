"""One-off TPU sweep over Top-K pipeline variants to pick the headline fix.

VERDICT round-2 item 2: the measured compressed/dense ratio is 0.34 on the
chip; this sweeps the in-tree knobs (selection algorithm, wire dtype,
fusion) side by side in one session so the winner can be promoted into
bench.py's HEADLINE config. Results append to TPU_VARIANTS.jsonl row by row
(tunnel-death-safe, same rationale as bench.progressive_emit).

Usage (on the chip): python tools/tpu_variants.py [--configs a,b,...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

BASE = {"memory": "residual", "communicator": "allgather", "fusion": "flat"}

VARIANTS = {
    "none": {"compressor": "none", "memory": "none",
             "communicator": "allreduce", "fusion": "flat"},
    "approx": dict(BASE, compressor="topk", compress_ratio=0.01,
                   topk_algorithm="approx"),
    "chunk": dict(BASE, compressor="topk", compress_ratio=0.01,
                  topk_algorithm="chunk"),
    "chunk_bf16": dict(BASE, compressor="topk", compress_ratio=0.01,
                       topk_algorithm="chunk", wire_dtype="bfloat16"),
    "approx_bf16": dict(BASE, compressor="topk", compress_ratio=0.01,
                        topk_algorithm="approx", wire_dtype="bfloat16"),
    "exact": dict(BASE, compressor="topk", compress_ratio=0.01,
                  topk_algorithm="exact"),
}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--configs", default=None)
    ap.add_argument("--out", default="TPU_VARIANTS.jsonl")
    args = ap.parse_args()
    names = (args.configs.split(",") if args.configs
             else list(VARIANTS))
    configs = [{"name": n, "params": VARIANTS[n]} for n in names]

    rows = []

    def emit(row):
        rows.append(row)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(f"[variants] {row['config']}: {row['imgs_per_sec']} imgs/sec "
              f"(x{row['vs_baseline']})", file=sys.stderr, flush=True)

    bench.bench_configs("tpu", configs, emit)

    # Ledger emission (repo-root artifact only): one record for the whole
    # appended sweep, superseding the previous variants record.
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if rows and os.path.dirname(os.path.abspath(args.out)) == root:
        from grace_tpu.evidence.ledger import record_artifact
        n_dev = rows[0].get("n_devices")
        record_artifact(
            args.out, id="variants-tpu", metric="resnet50_variant_rows",
            value=len(rows), claim_class="measured", tool="tpu_variants",
            platform=rows[0].get("platform"), chip=rows[0].get("chip"),
            n_devices=n_dev,
            topology={"world": n_dev, "tiers": ["ici"], "slice": None,
                      "region": None},
            config=",".join(names), lint_clean=None)


if __name__ == "__main__":
    main()
