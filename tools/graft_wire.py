#!/usr/bin/env python
"""graft-wire CLI: static HBM-traffic projection of the fused wire path.

PR 19 moves the ring hop's decode→accumulate(→requantize) into one
VMEM-resident Pallas pass. Until the stage-attribution capture campaign
(ROADMAP item 1) measures the hop on silicon, the honest headline is a
*projection* through the documented byte model
(:func:`grace_tpu.ops.pallas_wire.hop_hbm_bytes`): hop device time on
TPU is HBM-bandwidth-bound — every op in the hop is elementwise or a
tiny constant dot — so bytes moved is the static proxy for device time.

This tool evaluates staged-vs-fused bytes over a grid of bucket sizes ×
pack widths, checks the ≥2× wire-cut target, optionally graft-lints the
shipping fused-pipelined registry config, writes ``WIRE_LAST.json``, and
appends a ``claim_class="projected"`` ledger record so
``tools/graft_gate.py`` can audit any README claim that cites the
number. The record carries a ``deferred_capture`` note naming the
measurement that will supersede it — the ledger idiom for "projected
today, measured later" (same as the multichip wire model rows).

Exit status: 0 when every grid point meets the target, 1 otherwise.

Usage::

    python tools/graft_wire.py                 # writes WIRE_LAST.json
    python tools/graft_wire.py --json          # print the doc, still write
    python tools/graft_wire.py --no-lint       # skip the config audit
    python tools/graft_wire.py --out ''        # stdout only, no artifact
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(ROOT, "WIRE_LAST.json")

# The ROADMAP item-2 bar: the fused hop must cut wire-stage HBM traffic
# at least 2x vs the staged spelling at every shipped pack width.
TARGET_RATIO = 2.0

# Representative flat-bucket sizes (elements): a LeNet-scale bucket, a
# bench bucket_mb=4-scale bucket, and a ResNet-50-scale flat buffer.
DEFAULT_NUMELS = (1 << 14, 1 << 20, 25_557_032)

# Shipped pack widths (ops.packing): sign 1-bit, qsgd quantum_num<=1 ->
# 2-bit, <=3 -> 3-bit, <=7 -> 4-bit.
DEFAULT_WIDTHS = (1, 2, 3, 4)

# The shipping fused-pipelined config this projection is claimed for —
# the same registry entry chaos_smoke --lint --pipeline audits.
WIRE_CONFIG = "qsgd2-ring-packed-pipelined"

DEFERRED_CAPTURE = (
    "hop_hbm_bytes is a static byte model, not a device measurement; "
    "supersede this record with a measured stage-attribution capture "
    "(tools/tpu_profile.py stage view of grace/bucket/*/wire on >=2 "
    "chips) under the same id once the ROADMAP item-1 campaign runs.")


def _now() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def _atomic_write(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def projection_grid(numels, widths):
    """Staged/fused byte rows for every (numel, width) grid point."""
    from grace_tpu.ops.pallas_wire import hop_hbm_bytes
    rows = []
    for n in numels:
        for w in widths:
            staged = hop_hbm_bytes(n, w, fused=False)
            fused = hop_hbm_bytes(n, w, fused=True)
            rows.append({"numel": int(n), "pack_width": int(w),
                         "staged_bytes": int(staged),
                         "fused_bytes": int(fused),
                         "ratio": round(staged / fused, 4)})
    return rows


def lint_wire_config(name: str = WIRE_CONFIG):
    """Audit the shipping fused-pipelined registry entry; returns
    (lint_clean, n_findings) or (None, None) when the audit itself is
    unavailable (e.g. no jax on this box)."""
    try:
        from grace_tpu.analysis import audit_config
        from grace_tpu.analysis.configs import AUDIT_CONFIGS
        entry = next(e for e in AUDIT_CONFIGS if e["name"] == name)
        findings = audit_config(entry)
        errors = [f for f in findings if f.severity == "error"]
        return (not errors), len(findings)
    except Exception as e:                                 # noqa: BLE001
        print(f"[graft_wire] lint of {name!r} unavailable: {e}",
              file=sys.stderr)
        return None, None


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="evidence doc path ('' disables)")
    ap.add_argument("--json", action="store_true",
                    help="print the doc to stdout")
    ap.add_argument("--no-lint", action="store_true",
                    help="skip the graft-lint audit of the shipping "
                         "fused-pipelined config")
    args = ap.parse_args(argv)

    rows = projection_grid(DEFAULT_NUMELS, DEFAULT_WIDTHS)
    ratios = [r["ratio"] for r in rows]
    min_ratio, max_ratio = min(ratios), max(ratios)
    meets = min_ratio >= TARGET_RATIO

    from grace_tpu.comm import WIRE_PIPELINE_EFFICIENCY
    lint_clean, n_findings = ((None, None) if args.no_lint
                              else lint_wire_config())

    try:
        from grace_tpu.evidence.ledger import git_head_rev
        rev = git_head_rev()
    except Exception:                                      # noqa: BLE001
        rev = None

    doc = {
        "tool": "graft_wire",
        "captured_at": _now(),
        "git_rev": rev,
        "claim_class": "projected",
        "model": "grace_tpu.ops.pallas_wire.hop_hbm_bytes",
        "target_ratio": TARGET_RATIO,
        "min_ratio": min_ratio,
        "max_ratio": max_ratio,
        "meets_target": meets,
        "grid": rows,
        # The overlap half of the wire story: the double-buffered ring
        # hides WIRE_PIPELINE_EFFICIENCY*(P-1)/P of wire time behind the
        # neighbouring segment's compute, statically refereed by flow
        # pass 5 (>= P independent chains per bucket).
        "pipeline_overlap": {
            "efficiency": WIRE_PIPELINE_EFFICIENCY,
            "hidden_fraction": {
                str(p): round(WIRE_PIPELINE_EFFICIENCY * (p - 1) / p, 4)
                for p in (2, 4)},
        },
        "config": WIRE_CONFIG,
        "lint_clean": lint_clean,
        "lint_findings": n_findings,
        "deferred_capture": DEFERRED_CAPTURE,
    }

    if args.out:
        try:
            _atomic_write(args.out, doc)
        except OSError as e:
            print(f"[graft_wire] could not save {args.out}: {e}",
                  file=sys.stderr)
        else:
            print(f"[graft_wire] wire projection -> {args.out}",
                  file=sys.stderr)
            if os.path.dirname(os.path.abspath(args.out)) == ROOT:
                try:
                    from grace_tpu.evidence.ledger import record_artifact
                    record_artifact(
                        args.out, id="wire-hop-projection",
                        metric="wire_hop_hbm_bytes_ratio",
                        value=min_ratio, claim_class="projected",
                        tool="graft_wire", platform="static-model",
                        chip=None, n_devices=None, topology=None,
                        config=WIRE_CONFIG, lint_clean=lint_clean,
                        git_rev=rev, unit="staged_over_fused",
                        deferred_capture=DEFERRED_CAPTURE)
                except Exception as e:                     # noqa: BLE001
                    print(f"[graft_wire] ledger emission failed: {e}",
                          file=sys.stderr)

    if args.json:
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        print(f"[graft_wire] hop HBM bytes staged/fused: "
              f"min {min_ratio:.2f}x, max {max_ratio:.2f}x "
              f"(target >= {TARGET_RATIO:.1f}x) -> "
              f"{'OK' if meets else 'MISS'}")
        if lint_clean is not None:
            print(f"[graft_wire] {WIRE_CONFIG}: lint_clean={lint_clean} "
                  f"({n_findings} finding(s))")
    return 0 if meets else 1


if __name__ == "__main__":
    sys.exit(main())
