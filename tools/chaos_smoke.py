#!/usr/bin/env python
"""Chaos smoke: LeNet under NaN injection must survive, and the guard must
actually fire.

A CI-able end-to-end probe of the resilience subsystem (ISSUE 1): train
LeNet on synthetic MNIST-shaped data for --steps steps on the 8-device mesh
with a --nan-prob per-(step, leaf) NaN implant on one rank
(``ChaosCommunicator``), under the full guard + dense-fallback stack.

Telemetry (ISSUE 2): the run records the in-graph telemetry ring
(grad/update norms, residual health, compression error, effective wire
bytes across the dense-fallback flip) and drains it through a provenance-
stamped JSONL artifact at --telemetry-out, with guard transitions emitted
into the same stream by ``GuardMonitor(sink=...)``. Render it with
``python tools/telemetry_report.py <artifact>``.

SDC scenario (ISSUE 3): ``--sdc`` runs the *full* chaos matrix — the NaN
injection above PLUS single-rank silent data corruption: ``ChaosParams``
flips one bit of one param element in exactly one device's replica at
``--sdc-steps``, a fault the guard is structurally blind to (finite values,
rank-identical updates). The consensus auditor
(``grace_tpu.resilience.consensus``, armed via ``consensus=``/
``make_train_step(consensus=...)``) must detect and repair it within one
audit window; repairs/escalations are emitted as ``consensus_repair`` /
``consensus_escalation`` events into the same JSONL artifact as the
telemetry rows and guard events (``ConsensusMonitor``), so the audit trail
is a CI artifact.

Exit status (for CI):
  0  final loss is finite AND the guard tripped at least once AND (with
     --sdc) every injected corruption was repaired and replicas end
     bit-identical
  1  final loss is non-finite (the guard failed to contain the faults), the
     guard never tripped (injection is not reaching the pipeline — the
     smoke itself is broken), or --sdc corruption went undetected /
     replicas end diverged

Hierarchical scenario (ISSUE 7): ``--hier`` swaps the communicator for the
two-level ICI×DCN ``HierarchicalAllreduce`` (``--slice-size`` ranks per
slice), so the guard's atomic rollback and the consensus repair are
exercised over the nested grouped-collective exchange — and the telemetry
artifact's ``wire_bytes_ici``/``wire_bytes_dcn`` rows carry the mixed
per-link split.

Homomorphic scenario (ISSUE 13): ``--homo`` swaps the codec for the
shared-scale homomorphic QSGD (``payload_algebra='shared_scale'``), so the
fault matrix rides the zero-requant payload-space integer summation: a
poisoned gradient NaNs the negotiated scale (pmax propagates NaN), every
rank's single decode goes NaN, and the guard's replicated predicate must
trip fleet-wide with rollback atomic around the hoisted negotiation.
Combine with ``--hier`` for the slice-boundary integer-add variant.

Watch scenario (ISSUE 8): ``--watch`` seeds a single-rank
*compression-error drift* — ``ChaosCompressor(drift_scale=...)``
attenuates one rank's payload values every step. The fault is perfectly
finite (the guard is structurally blind: NaN injection is disabled in
this mode and the smoke REQUIRES the guard to stay silent) and lives in
per-rank state (the consensus audit is blind by design) — yet graft-watch
(``grace_tpu.telemetry.aggregate`` + ``anomaly``) must flag the drifting
rank with a ``watch_anomaly`` record in the artifact within one watch
window, attributing the exact rank, before any guard/consensus event
exists. Combine with ``--sdc`` to cross-validate: the consensus repair
zeroes the SDC rank's residuals, which the watch skew detector also sees.

Adapt scenario (ISSUE 15): ``--adapt`` drills the in-graph adaptive
compression controller (``grace_tpu.resilience.adapt``) through its three
claims in one timeline-ordered run. Phase A seeds a single-rank
compression-error drift (``ChaosCompressor(drift_scale=...)`` on every
ladder rung's codec — finite, so the guard MUST stay silent) and requires
the controller to TIGHTEN a rung within one window of the spike, with the
``adapt_tighten`` event landing in the artifact BEFORE any guard event
exists. Phase B removes the drift and requires the controller to LOOSEN
back after ``quiet_windows`` quiet windows (the hysteresis claim). Phase C
injects NaNs so the guard genuinely trips, and requires the controller to
register the trip as escalate-and-hold evidence (``escalations > 0``) —
the ladder-floor-too-loose semantics. Evidence (tighten/loosen counts and
steps, the tighten-before-guard ordering verdict, the rung trace) lands in
``--adapt-out`` (ADAPT_LAST.json), rendered by evidence_summary.py;
``adapt_*`` events stream into the telemetry JSONL (timeline kind
``adapt``).

Elastic scenario (ISSUE 11): ``--elastic`` runs the full preemption
lifecycle on the 8-device mesh — drift on one rank (guard-blind, like
``--watch``) until graft-watch flags it, the :class:`ElasticController`
drains (last-known-good ``Checkpointer`` save), the flagged rank is killed
and the run RESUMES at W−1 (``reshard_grace_state``: replicated state
carried bit-exactly, per-rank residuals/rings re-initialized, validated
against flow pass 7's footprint model), then the rank REJOINS at W with
params restored from the stale pre-departure checkpoint and must pass the
consensus-gated rejoin barrier (one forced fingerprint audit; repairs ==
rejoins, replicas bit-identical after). With ``--hier`` the kill takes the
flagged rank's WHOLE slice — a K→K−1 DCN-level resize that keeps
``slice_size``. Evidence (resize events, rejoin fingerprint pricing,
convergence-floor verdict, per-world footprint checks) lands in
``--elastic-out`` (ELASTIC_LAST.json), rendered by evidence_summary.py;
``elastic_*`` events additionally stream into the telemetry JSONL.

Retune scenario (ISSUE 18): ``--retune`` drills fault-tolerant online
re-tuning (``grace_tpu.resilience.retune``) end to end. The run warms up
on the 4-bit homomorphic codec (homoqsgd ``quantum_num=7``) while the
:class:`RetuneController` learns its healthy compression-error baseline
from live telemetry rows, then a FLEET-WIDE finite drift
(``ChaosCompressor(drift_scale=..., rank=None)`` — every rank, so the
windowed mean moves; the guard must stay silent) forces a sustained-drift
verdict (``retune_drift``). The controller then promotes a PowerSGD
rank-4 config carrying a rank-1 adapt ladder as a two-phase transaction:
PREPARE (lint-audit the candidate, migrate ``GraceState`` leafwise with
the rung-invariant overlap rule, footprint-check the migrated tree,
checkpoint the incumbent as last-known-good) then COMMIT (consensus-gated
cutover behind the rejoin barrier; replicas must end bit-identical). The
probation window clears quietly, a second promotion migrates BACK to
homoqsgd4 (the cross-family migration in both directions), and finally a
SABOTAGED third promotion — the promoted codec wrapped in
``ChaosCompressor(nan_prob=1.0)`` — must trip the guard during probation
and trigger an automatic demotion that restores the pre-promotion
checkpoint BIT-EXACTLY (``state_digest`` witness) within the probation
window. Every transition leg is bounded by the drain watchdog discipline
(``--drain-timeout``). Evidence (drift/promote/demote steps, migration
stats, replica-variant counts, the event-ordering verdict, the bit-exact
restore witness) lands in ``--retune-out`` (RETUNE_LAST.json), rendered
by evidence_summary.py; ``retune_*`` events stream into the telemetry
JSONL (timeline kind ``retune``) and ``retune_promote``/``retune_demote``
open flight-recorder incidents when ``--incidents`` is set.

Region scenario (ISSUE 16): ``--region`` runs the cross-region failure
lifecycle on the 8-device mesh laid out as 2 regions × 2 slices × 2 ranks
(``Topology(slice_size=2, region_size=4)``, three-level hier exchange).
Drift is seeded on one rank PER SLICE of the doomed region (guard-blind);
graft-watch flags them, and once a quorum (``region_quorum=0.5``) of the
region's ranks carries skew episodes the :class:`ElasticController`
recognizes the region-wide episode (:meth:`region_scope`) and handles it
as ONE drain → resize → rejoin transition — never ``region_size``
independent rank losses. The kill takes the WHOLE region: an R→R−1
WAN-level resize that collapses to the two-tier ``Topology(slice_size=2)``
when a single region remains, resumes at W−4, then the region REJOINS at
W with stale pre-departure params implanted on every lost rank and must
pass the consensus-gated rejoin barrier (one region rejoin == one barrier
repair event; replicas bit-identical after). The guard must stay silent
throughout the healthy path, and the convergence floor is judged after
the rejoin. Evidence lands in ``--region-out`` (REGION_LAST.json),
rendered by evidence_summary.py.

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py            # defaults
    python tools/chaos_smoke.py --steps 200 --nan-prob 0.01
    python tools/chaos_smoke.py --sdc                        # + param SDC
    python tools/chaos_smoke.py --sdc --hier --slice-size 4  # hier matrix
    python tools/chaos_smoke.py --hier --homo                # zero-requant
    python tools/chaos_smoke.py --watch --watch-rank 3       # drift watch
    python tools/chaos_smoke.py --elastic                    # kill + rejoin
    python tools/chaos_smoke.py --elastic --hier --slice-size 4  # slice kill
    python tools/chaos_smoke.py --region                     # region kill
    python tools/chaos_smoke.py --retune                     # config retune
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _evidence_stamp(world, slice_size=None, region_size=None) -> dict:
    """Uniform provenance stamp for every chaos evidence doc — the same
    n_devices/topology/git_rev triple bench rows carry (ISSUE 17: the
    ADAPT/ELASTIC/REGION files used to ship with only a captured_at)."""
    from grace_tpu.evidence.ledger import git_head_rev
    tiers = ["ici"]
    if slice_size:
        tiers.append("dcn")
    if region_size:
        tiers.append("wan")
    return {"git_rev": git_head_rev(),
            "n_devices": world,
            "topology": {"world": world, "tiers": tiers,
                         "slice": slice_size or None,
                         "region": region_size or None}}


def _write_evidence_doc(doc: dict, out_path: str, *, ledger_id: str,
                        metric: str, value, world: int,
                        slice_size=None, region_size=None,
                        label: str = "evidence") -> None:
    """The one exit for chaos evidence docs: stamp provenance, write
    atomically, append the ledger record (repo-root artifacts only, so a
    test run against a tmp path never touches the ledger)."""
    import json
    doc = {**doc, **_evidence_stamp(world, slice_size, region_size)}
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, out_path)
    print(f"[chaos_smoke] {label}: {out_path}")
    if os.path.dirname(os.path.abspath(out_path)) != ROOT:
        return
    from grace_tpu.evidence.ledger import record_artifact
    record_artifact(
        out_path, id=ledger_id, metric=metric, value=value,
        claim_class="measured", tool="chaos_smoke", platform="cpu",
        chip="cpu", n_devices=world,
        topology=doc["topology"], config=doc.get("argv"),
        lint_clean=None, git_rev=doc["git_rev"])


def _incident_sink(jsonl_sink, args, provenance, tag: str):
    """Wrap the JSONL evidence sink with the flight recorder when
    --incidents is set: same record stream, plus ledger-attached
    incident snapshots on guard trips / adapt escalations / drains."""
    if not getattr(args, "incidents", None) or jsonl_sink is None:
        return jsonl_sink, None
    from grace_tpu.evidence.incident import IncidentRecorder
    from grace_tpu.telemetry import MultiSink
    recorder = IncidentRecorder(args.incidents, run_tag=tag,
                                provenance=provenance)
    return MultiSink(jsonl_sink, recorder), recorder


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--nan-prob", type=float, default=0.01,
                    help="per-(step, leaf) NaN implant probability")
    ap.add_argument("--rank", type=int, default=0,
                    help="mesh index the faults land on")
    ap.add_argument("--batch", type=int, default=32,
                    help="global batch (split over 8 devices)")
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--fallback-after", type=int, default=3)
    ap.add_argument("--fallback-steps", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry-out", default="chaos_telemetry.jsonl",
                    help="JSONL telemetry artifact path ('' disables)")
    ap.add_argument("--telemetry-every", type=int, default=25,
                    help="steps per telemetry flush (one device_get each)")
    ap.add_argument("--incidents", default="",
                    help="directory for flight-recorder incident "
                         "snapshots ('' disables): guard trips, adapt "
                         "escalations and drains each dump the telemetry "
                         "ring + watch timeline + adapt rung history as "
                         "a ledger-attached incident record")
    ap.add_argument("--sdc", action="store_true",
                    help="also inject single-rank param SDC (ChaosParams) "
                         "and require the consensus auditor to repair it")
    ap.add_argument("--sdc-rank", type=int, default=5,
                    help="mesh index whose param replica gets the bitflips")
    ap.add_argument("--sdc-steps", default="",
                    help="comma-separated injection steps (default: two "
                         "hits at 1/3 and 2/3 of --steps)")
    ap.add_argument("--audit-every", type=int, default=20,
                    help="consensus audit interval (with --sdc)")
    ap.add_argument("--profile", action="store_true",
                    help="record runtime profiling (step-time percentiles, "
                         "compile/retrace events, memory watermarks, "
                         "GraceState footprint check) into the telemetry "
                         "artifact as perf_* events "
                         "(grace_tpu.profiling.ProfileRecorder)")
    ap.add_argument("--hier", action="store_true",
                    help="run the chaos matrix over the hierarchical "
                         "ICI×DCN communicator (communicator='hier', "
                         "fusion='flat') instead of allgather — "
                         "guard rollback and consensus repair must stay "
                         "atomic across the two-level grouped exchange")
    ap.add_argument("--slice-size", type=int, default=4,
                    help="with --hier: ranks per ICI slice (the 8-device "
                         "mesh then spans 8/slice_size slices)")
    ap.add_argument("--homo", action="store_true",
                    help="run the chaos matrix over the aggregation-"
                         "homomorphic codec (compressor='homoqsgd', "
                         "payload_algebra='shared_scale') instead of "
                         "topk — the NaN implant must propagate through "
                         "the zero-requant payload-space integer "
                         "summation (and, with --hier, the boundary "
                         "integer add) to trip the guard on every rank, "
                         "and rollback must stay atomic around the "
                         "hoisted scale negotiation")
    ap.add_argument("--watch", action="store_true",
                    help="graft-watch scenario: seed a single-rank "
                         "compression-error drift (finite — guard-blind; "
                         "per-rank — consensus-blind) and require a "
                         "watch_anomaly record naming that rank within "
                         "one watch window. Disables NaN injection: the "
                         "guard MUST stay silent, proving watch warns "
                         "where guard/consensus cannot")
    ap.add_argument("--watch-rank", type=int, default=3,
                    help="mesh index whose encoder drifts (with --watch)")
    ap.add_argument("--drift-scale", type=float, default=0.5,
                    help="payload attenuation of the drifting rank "
                         "(with --watch)")
    ap.add_argument("--watch-window", type=int, default=10,
                    help="steps between in-graph cross-rank health "
                         "summaries (with --watch)")
    ap.add_argument("--adapt", action="store_true",
                    help="adaptive-controller scenario (ISSUE 15): "
                         "phase A single-rank drift -> controller "
                         "tightens within one window (guard silent, "
                         "adapt_tighten precedes any guard event); "
                         "phase B quiet -> controller loosens back; "
                         "phase C NaN injection -> guard trips and the "
                         "controller escalates-and-holds")
    ap.add_argument("--adapt-window", type=int, default=8,
                    help="controller decision window in steps "
                         "(with --adapt)")
    ap.add_argument("--adapt-rank", type=int, default=3,
                    help="mesh index whose encoder drifts in phase A "
                         "(with --adapt)")
    ap.add_argument("--adapt-out", default="ADAPT_LAST.json",
                    help="evidence JSON path for --adapt ('' disables)")
    ap.add_argument("--elastic", action="store_true",
                    help="run the full elastic lifecycle: drift → watch "
                         "drain signal → kill the flagged rank (its whole "
                         "slice with --hier) → resume at W-1 → rejoin at W "
                         "behind the consensus fingerprint barrier. "
                         "Disables NaN injection (the faults here are "
                         "drift and staleness; the guard must stay silent)")
    ap.add_argument("--elastic-rank", type=int, default=5,
                    help="mesh index that degrades and dies (with "
                         "--elastic; under --hier its whole slice is lost)")
    ap.add_argument("--elastic-out", default="ELASTIC_LAST.json",
                    help="evidence JSON path for --elastic ('' disables)")
    ap.add_argument("--region", action="store_true",
                    help="run the cross-region failure lifecycle (ISSUE "
                         "16): three-tier mesh (2 regions × 2 slices × 2 "
                         "ranks), drift on one rank per slice of the "
                         "doomed region → watch flags them → the "
                         "controller recognizes the region-wide episode "
                         "(region_scope quorum) and drains ONCE → the "
                         "whole region dies (R→R−1, topology collapses "
                         "to two-tier) → resume at W−4 → the region "
                         "rejoins at W behind the consensus barrier")
    ap.add_argument("--region-size", type=int, default=4,
                    help="ranks per region for --region (slices are half "
                         "a region wide so all three tiers are exercised)")
    ap.add_argument("--region-out", default="REGION_LAST.json",
                    help="evidence JSON path for --region ('' disables)")
    ap.add_argument("--retune", action="store_true",
                    help="online re-tuning drill (ISSUE 18): warm up on "
                         "homoqsgd4, inject fleet-wide drift until the "
                         "RetuneController flags it, promote to a powersgd "
                         "rank ladder as a two-phase transaction (guard "
                         "silent, replicas bit-identical), clear probation, "
                         "promote back, then sabotage a third promotion "
                         "(ChaosCompressor NaNs the promoted codec) and "
                         "require automatic bit-exact demotion within the "
                         "probation window")
    ap.add_argument("--retune-window", type=int, default=6,
                    help="controller drift window in telemetry rows "
                         "(with --retune)")
    ap.add_argument("--retune-probation", type=int, default=18,
                    help="probation steps after each promotion "
                         "(with --retune)")
    ap.add_argument("--retune-funnel", action="store_true",
                    help="with --retune: after drift fires, re-run the "
                         "tuner's bounded static+measured funnel against "
                         "the live mesh (RetuneController.propose) and "
                         "record its verdict in the evidence doc")
    ap.add_argument("--retune-out", default="RETUNE_LAST.json",
                    help="evidence JSON path for --retune ('' disables)")
    ap.add_argument("--drain-timeout", type=float, default=60.0,
                    help="ElasticController drain watchdog seconds "
                         "(--region; 0 disables the watchdog)")
    ap.add_argument("--floor", type=float, default=2.25,
                    help="convergence floor: the post-rejoin final loss "
                         "must be below this (10-class CE starts ~2.303)")
    ap.add_argument("--ckpt-dir", default="",
                    help="checkpoint directory for the elastic drain "
                         "(default: a fresh temp dir)")
    ap.add_argument("--fsdp", action="store_true",
                    help="sharded-model scenario (ISSUE 14): the chaos "
                         "matrix over a 2-D dp×fsdp mesh (4×2 of the 8 "
                         "devices) — a tensor-parallel MLP with w1 "
                         "sharded over fsdp, the ROUTED rscatter exchange "
                         "(big leaves topk per-shard reduce-scatter, "
                         "bias leaves dense fp16 psum), NaN injection "
                         "(guard rollback must stay atomic across the "
                         "per-shard exchanges) plus single-rank param "
                         "SDC (the consensus audit fingerprints "
                         "replicated fields PER FSDP SHARD over the dp "
                         "axis and must repair within one window, "
                         "residual zeroing scoped to the divergent "
                         "rank). Telemetry rows must carry the two-axis "
                         "wire split (wire_bytes_ici/wire_bytes_dcn)")
    ap.add_argument("--fsdp-size", type=int, default=2,
                    help="fsdp axis width (dp = 8 // fsdp_size)")
    ap.add_argument("--lint", action="store_true",
                    help="first run graft-lint (repo rules + a static "
                         "audit of this smoke's own grace config); "
                         "findings land in the telemetry artifact as "
                         "lint_finding events and fail the smoke")
    ap.add_argument("--pipeline", type=int, default=0,
                    help="ride the double-buffered wire path (ISSUE 19): "
                         "packed 4-bit qsgd over a ring with pipeline=N "
                         "segments (unless --homo/--hier already chose "
                         "the codec/communicator, which then just gain "
                         "pipeline=N). With --lint, the static audit "
                         "traces the FUSED spelling (use_pallas=True → "
                         "interpret-mode wire kernels inside the audited "
                         "graph) and flow pass 5 must count >= N "
                         "independent chains before chaos runs")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if os.environ["JAX_PLATFORMS"].lower() == "cpu":
        jax.config.update("jax_platforms", "cpu")
        from grace_tpu.parallel import (relax_cpu_collective_timeouts,
                                        set_cpu_device_count)
        try:
            set_cpu_device_count(8)
        except RuntimeError:
            # Backend already initialized — e.g. main() invoked from the
            # pytest harness, whose conftest set the 8-device mesh up
            # before any test ran. Reuse its devices.
            pass
        relax_cpu_collective_timeouts()

    if args.adapt:
        return _adapt_main(args)
    if args.retune:
        return _retune_main(args)
    if args.elastic:
        return _elastic_main(args)
    if args.region:
        return _region_main(args)
    if args.fsdp:
        return _fsdp_main(args)

    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.models import lenet
    from grace_tpu.parallel import data_parallel_mesh
    from grace_tpu.resilience import (ChaosCommunicator, ChaosParams,
                                      ConsensusConfig, audit_report,
                                      guarded_chain)
    from grace_tpu.telemetry import JSONLSink, TelemetryReader
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.utils.logging import (ConsensusMonitor, GuardMonitor,
                                         run_provenance)
    from grace_tpu.utils.metrics import guard_report

    mesh = data_parallel_mesh()
    world = mesh.devices.size
    batch = max(args.batch, world) // world * world

    rng = np.random.default_rng(args.seed)
    images = rng.normal(size=(4 * batch, 28, 28, 1)).astype(np.float32)
    labels = rng.integers(0, 10, size=(4 * batch,)).astype(np.int32)

    def loss_fn(params, b):
        x, y = b
        logits, _ = lenet.apply(params, {}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    consensus = None
    sdc = None
    if args.sdc:
        consensus = ConsensusConfig(
            audit_every=args.audit_every,
            escalate_window=4 * args.audit_every,
            escalate_steps=args.fallback_steps)
        sdc_steps = (tuple(int(s) for s in args.sdc_steps.split(","))
                     if args.sdc_steps
                     else (args.steps // 3, 2 * args.steps // 3))
        sdc = ChaosParams(rank=args.sdc_rank, at_steps=sdc_steps,
                          seed=args.seed + 2)
    if args.watch:
        # The drift must be the ONLY fault: the scenario's claim is that
        # watch flags a degradation the guard cannot see, so the guard
        # staying silent is part of the assertion.
        if args.nan_prob:
            print("[chaos_smoke] --watch: disabling NaN injection "
                  f"(nan_prob {args.nan_prob} -> 0.0) — the drift "
                  "scenario requires a guard-silent run")
        args.nan_prob = 0.0
    grace_params = {"compressor": "topk", "compress_ratio": 0.3,
                    "memory": "residual",
                    "communicator": "allgather",
                    "escape": "fp16",
                    "consensus": consensus,
                    # ring sized to the flush window so a healthy
                    # run never wraps between flushes
                    "telemetry": max(2 * args.telemetry_every, 16)}
    if args.watch:
        grace_params["watch"] = {
            "window": args.watch_window,
            # summary ring sized so a flush window never wraps it
            "capacity": max(2 * args.telemetry_every // args.watch_window,
                            8)}
    if args.homo and args.watch:
        print("[chaos_smoke] --homo is incompatible with --watch: the "
              "drift injector attenuates float payload lanes and the "
              "homomorphic codec ships integer levels (drift would be a "
              "silent no-op, voiding the scenario's claim)",
              file=sys.stderr)
        return 1
    if args.homo:
        # Homomorphic scenario (ISSUE 13): a NaN poisoned into one rank's
        # gradient rides the negotiate pmax (NaN-max → shared scale NaN)
        # and/or the integer level sums' decode into EVERY rank's update,
        # so the guard's replicated predicate must trip fleet-wide and the
        # rollback must restore GraceState around the zero-requant path.
        grace_params.update(compressor="homoqsgd", quantum_num=7)
        grace_params.pop("compress_ratio", None)
    if args.hier:
        # Guard + consensus over the two-level ICI×DCN exchange: the NaN
        # implant must propagate through the intra-slice ring AND the
        # cross-slice grouped gather to every rank (or the guard's psum-OR
        # desyncs), and the consensus repair must leave replicas
        # bit-identical when the update itself was hierarchically
        # aggregated. slice_size also flips the telemetry rows to the
        # mixed wire_bytes_ici/wire_bytes_dcn split.
        grace_params.update(communicator="hier",
                            slice_size=args.slice_size,
                            fusion="flat")
    if args.pipeline > 1:
        # graft-wire scenario (ISSUE 19): the double-buffered ring. The
        # RUN rides use_pallas='auto' (staged off-TPU, kernel on-chip —
        # bit-identical either way, the pack_widths contract); the --lint
        # audit below flips to use_pallas=True so the fused
        # decode→accumulate kernels trace INSIDE the audited pipelined
        # graph. --homo/--hier keep their own codec/communicator and just
        # gain the segmented schedule.
        if not (args.homo or args.hier):
            grace_params.update(compressor="qsgd", quantum_num=7,
                                use_pallas="auto", communicator="ring",
                                fusion="flat")
            grace_params.pop("compress_ratio", None)
        grace_params["pipeline"] = args.pipeline
    grc = grace_from_params(grace_params)
    grc = dataclasses.replace(grc, communicator=ChaosCommunicator(
        inner=grc.communicator, nan_prob=args.nan_prob, rank=args.rank,
        seed=args.seed + 1))
    if args.watch:
        from grace_tpu.resilience import ChaosCompressor
        grc = dataclasses.replace(grc, compressor=ChaosCompressor(
            inner=grc.compressor, drift_scale=args.drift_scale,
            rank=args.watch_rank, seed=args.seed + 3))
    tx = guarded_chain(grc, optax.sgd(args.lr),
                       fallback_after=args.fallback_after,
                       fallback_steps=args.fallback_steps)

    params, _ = lenet.init(jax.random.key(args.seed))
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False,
                           consensus=consensus)

    sink = None
    reader = None
    if args.watch and not args.telemetry_out:
        print("[chaos_smoke] --watch requires --telemetry-out: the "
              "acceptance artifact IS the watch_anomaly record",
              file=sys.stderr)
        return 1
    if args.telemetry_out:
        prov = run_provenance(
            data="synthetic",
            tool="chaos_smoke",
            argv=" ".join(sys.argv[1:]),
            nan_prob=args.nan_prob, steps=args.steps,
            fallback_after=args.fallback_after,
            fallback_steps=args.fallback_steps,
            homo=bool(args.homo))
        sink = JSONLSink(args.telemetry_out, provenance=prov)
        sink, _ = _incident_sink(sink, args, prov,
                                 "watch" if args.watch else "nan")
        reader = TelemetryReader(sink, every=args.telemetry_every,
                                 anomaly=args.watch)
    monitor = GuardMonitor(sink=sink)
    consensus_mon = ConsensusMonitor(sink=sink)
    profiler = None
    if args.profile:
        from grace_tpu.profiling import ProfileRecorder
        # Shares the telemetry sink so perf_* records land in the same
        # JSONL stream as the metric rows and guard/consensus events (one
        # artifact covers one run); close() is NOT delegated — the smoke
        # owns the sink's lifetime.
        profiler = ProfileRecorder(sink=sink, every=args.telemetry_every,
                                   step_fn=step)

    if args.lint:
        # Static gate before any step runs: repo rules + the jaxpr
        # passes over THIS smoke's production config (pre-chaos-wrapper —
        # the injectors are test fixtures, not an audited deployment).
        # Findings become lint_finding events in the same JSONL artifact
        # as the guard/consensus trail; errors fail the smoke fast.
        from grace_tpu.analysis import audit_config, run_repo_rules
        from grace_tpu.analysis.report import emit_to_sink
        lint_params = dict(grace_params)
        if args.pipeline > 1:
            # Audit the FUSED spelling of the pipelined wire: forcing the
            # kernels on (interpret off-TPU) puts the decode→accumulate
            # hops inside the audited graph, and flow pass 5's referee
            # must count >= pipeline independent chains per bucket.
            lint_params["use_pallas"] = True
        lint_findings = run_repo_rules() + audit_config(
            {"name": "chaos_smoke-config",
             "params": lint_params,
             # Everything except wire reconciliation (the escape cond makes
             # the wire cost bimodal, same exclusion as the registry's
             # escape entries) — the graft-flow passes (schedulability,
             # numeric safety, footprint) gate this run's config too.
             # ... plus the graft-sound stateful-semantics passes: the
             # chaos matrix's whole point is exercising guard rollback
             # and consensus repair, so the smoke config must itself
             # prove its rollback write-set and replication contract.
             "passes": ("collective_consistency", "bit_exactness",
                        "signature_stability", "overlap_schedulability",
                        "numeric_safety", "memory_footprint",
                        "rng_lineage", "rollback_coverage",
                        "replication_contract")})
        if sink is not None and lint_findings:
            emit_to_sink(lint_findings, sink)
        errors = [f for f in lint_findings if f.severity == "error"]
        print(f"[chaos_smoke] graft-lint: {len(errors)} error(s), "
              f"{len(lint_findings) - len(errors)} warning(s)")
        if errors:
            for f in errors:
                print(f"[chaos_smoke]   {f.pass_name} {f.config}: "
                      f"{f.message}", file=sys.stderr)
            print("[chaos_smoke] FAIL: graft-lint found static SPMD "
                  "hazards — not running the chaos matrix on a config "
                  "that can deadlock a pod", file=sys.stderr)
            if sink is not None:
                sink.close()
            return 1

    t0 = time.perf_counter()
    loss = float("nan")
    for i in range(args.steps):
        if sdc is not None:
            state = sdc(state, i)
        lo = (i * batch) % len(images)
        b = (jnp.asarray(images[lo:lo + batch]),
             jnp.asarray(labels[lo:lo + batch]))
        if profiler is not None:
            with profiler.step():
                state, loss = step(state, b)
                profiler.sync_on(loss)
            profiler.update(i)
        else:
            state, loss = step(state, b)
        monitor.update(i, guard_report(state))
        if sdc is not None:
            consensus_mon.update(i, audit_report(state))
        if reader is not None:
            reader.update(i, state)
    loss = float(loss)
    dt = time.perf_counter() - t0
    if profiler is not None:
        if args.steps % args.telemetry_every:
            profiler.flush(args.steps - 1)        # drain the tail window
        profiler.record_state_footprint(state, grc, params,
                                        world=world, step=args.steps - 1)
        arr = profiler.timer.steady * 1e3
        print(f"[chaos_smoke] profiling: step p50 "
              f"{np.percentile(arr, 50):.1f} ms, p99 "
              f"{np.percentile(arr, 99):.1f} ms over {arr.size} steps | "
              f"retraces {profiler.retraces}")
    if reader is not None:
        reader.flush(state)      # drain the tail window
        reader.close()
        print(f"[chaos_smoke] telemetry artifact: {args.telemetry_out} "
              f"({reader.flushes} flushes, {reader.dropped} dropped rows)")

    rep = guard_report(state)
    print(f"[chaos_smoke] {args.steps} steps in {dt:.1f}s | final loss "
          f"{loss:.4f} | skipped {rep['notfinite_count']} | "
          f"last_bad_step {rep['last_bad_step']} | "
          f"fallback_active {rep['fallback_active']}")

    if not np.isfinite(loss):
        print("[chaos_smoke] FAIL: final loss is non-finite — the guard did "
              "not contain the injected faults", file=sys.stderr)
        return 1
    if args.watch:
        anomalies = reader.monitor.anomalies if reader.monitor else []
        allowed = {args.watch_rank}
        if sdc is not None:
            # --sdc cross-validation: the consensus repair zeroes the SDC
            # rank's residuals, a legitimate residual-skew the watch sees.
            allowed.add(args.sdc_rank)
        # Attribution is judged on the CODEC-HEALTH metrics the drift
        # corrupts (compression error, residual norm). grad_norm skews are
        # excluded from the misattribution check: this smoke feeds each
        # rank a FIXED batch shard, so per-rank gradient-norm outliers are
        # real data heterogeneity the detector is right to report.
        fault_metrics = ("compression_error", "residual_norm")
        skews = [a for a in anomalies if a.get("kind") == "skew"
                 and a.get("metric") in fault_metrics]
        hits = [a for a in skews if a.get("rank") == args.watch_rank]
        wrong = [a for a in skews if a.get("rank") not in allowed]
        first = min((a["step"] for a in hits), default=None)
        print(f"[chaos_smoke] watch: {len(anomalies)} anomalies | "
              f"rank-{args.watch_rank} codec-skew hits {len(hits)} "
              f"(first at step {first}) | misattributed {len(wrong)}")
        if rep["notfinite_count"] != 0:
            print("[chaos_smoke] FAIL: guard tripped during the drift "
                  "scenario — the fault is supposed to be finite and "
                  "guard-invisible; the smoke itself is broken",
                  file=sys.stderr)
            return 1
        if not hits:
            print("[chaos_smoke] FAIL: seeded single-rank drift on rank "
                  f"{args.watch_rank} produced no skew watch_anomaly for "
                  "that rank", file=sys.stderr)
            return 1
        if wrong:
            print(f"[chaos_smoke] FAIL: skew anomalies misattributed to "
                  f"rank(s) {sorted({a['rank'] for a in wrong})}",
                  file=sys.stderr)
            return 1
        if first > args.watch_window:
            print(f"[chaos_smoke] FAIL: first rank-{args.watch_rank} "
                  f"anomaly at step {first} — later than one watch window "
                  f"({args.watch_window})", file=sys.stderr)
            return 1
    elif rep["notfinite_count"] == 0:
        print("[chaos_smoke] FAIL: guard never tripped — injection is not "
              "reaching the pipeline", file=sys.stderr)
        return 1
    if sdc is not None:
        arep = audit_report(state)
        diverged = max(
            len({np.asarray(s.data).tobytes()
                 for s in leaf.addressable_shards})
            for leaf in jax.tree_util.tree_leaves(state.params))
        print(f"[chaos_smoke] sdc: injected {len(sdc.injections)} | "
              f"audits {arep['audits']} | repairs {arep['repairs']} | "
              f"escalations {arep['escalations']} | "
              f"replica_variants {diverged}")
        if arep["repairs"] < len(sdc.injections):
            print("[chaos_smoke] FAIL: consensus auditor repaired "
                  f"{arep['repairs']} of {len(sdc.injections)} injected "
                  "corruptions", file=sys.stderr)
            return 1
        if diverged > 1:
            print("[chaos_smoke] FAIL: param replicas still diverged after "
                  "the final audit window", file=sys.stderr)
            return 1
    print("[chaos_smoke] OK")
    return 0


def _fsdp_main(args) -> int:
    """The sharded-model chaos scenario: guard rollback + consensus
    repair over a 2-D dp×fsdp mesh with the routed rscatter exchange.

    Exit 0 requires: final loss finite; the guard tripped (NaN injection
    reaches every per-shard exchange); every injected SDC repaired with
    residual zeroing scoped to the divergent rank (consensus fingerprints
    match replicas PER FSDP SHARD — param shards legitimately differ
    across fsdp); and the telemetry artifact's rows carry the two-axis
    wire split (``wire_bytes_ici``/``wire_bytes_dcn``).
    """
    import dataclasses as _dc

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from grace_tpu import grace_from_params
    from grace_tpu.parallel import make_mesh
    from grace_tpu.resilience import (ChaosCommunicator, ChaosParams,
                                      ConsensusConfig, audit_report,
                                      guarded_chain)
    from grace_tpu.telemetry import JSONLSink, TelemetryReader
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.transform import MeshSpec
    from grace_tpu.utils.logging import (ConsensusMonitor, GuardMonitor,
                                         run_provenance)
    from grace_tpu.utils.metrics import guard_report

    fsdp = max(1, args.fsdp_size)
    if 8 % fsdp:
        print(f"[chaos_smoke] --fsdp-size {fsdp} does not divide the "
              "8-device mesh", file=sys.stderr)
        return 1
    dp = 8 // fsdp
    mesh = make_mesh((dp, fsdp), ("data", "fsdp"))
    mesh_spec = MeshSpec("data", "fsdp")

    feat, hid, classes = 32, 16, 8
    rng = np.random.default_rng(args.seed)
    params = {
        "w1": jnp.asarray(rng.normal(scale=0.3, size=(feat, hid)),
                          jnp.float32),
        "b1": jnp.zeros((hid,), jnp.float32),
        "w2": jnp.asarray(rng.normal(scale=0.3, size=(hid, classes)),
                          jnp.float32),
        "b2": jnp.zeros((classes,), jnp.float32),
    }
    # EVERY param is fsdp-sharded — the honest FSDP layout. This is not
    # cosmetic: a param replicated across fsdp would have its gradient
    # aggregated independently per dp group (collectives span dp only),
    # so a single corrupt rank could contaminate ITS group's aggregate
    # and silently diverge the "replicated" copies ACROSS groups where
    # the per-fsdp-shard consensus audit structurally cannot see it.
    # Sharding everything over fsdp keeps each shard's trajectory inside
    # exactly one dp group — the audit's jurisdiction.
    param_specs = {"w1": P("fsdp", None), "b1": P("fsdp"),
                   "w2": P("fsdp", None), "b2": P("fsdp")}
    feat_sh, hid_sh = feat // fsdp, hid // fsdp

    def loss_fn(p, b):
        x, y = b
        f = lax.axis_index("fsdp")
        # FSDP forward: gather the sharded biases, contract each weight
        # shard against this shard's input slice, psum the partials over
        # fsdp. The all_gather's transpose hands each owner exactly its
        # shard's bias gradient — per-shard gradients by construction.
        b1 = lax.all_gather(p["b1"], "fsdp", axis=0, tiled=True)
        b2 = lax.all_gather(p["b2"], "fsdp", axis=0, tiled=True)
        xs = lax.dynamic_slice_in_dim(x, f * feat_sh, feat_sh, 1)
        h = jnp.tanh(lax.psum(xs @ p["w1"], "fsdp") + b1)
        hs = lax.dynamic_slice_in_dim(h, f * hid_sh, hid_sh, 1)
        logits = lax.psum(hs @ p["w2"], "fsdp") + b2
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    consensus = ConsensusConfig(
        audit_every=args.audit_every,
        escalate_window=4 * args.audit_every,
        escalate_steps=args.fallback_steps)
    sdc_steps = (tuple(int(s) for s in args.sdc_steps.split(","))
                 if args.sdc_steps
                 else (args.steps // 3, 2 * args.steps // 3))
    sdc = ChaosParams(rank=args.sdc_rank, at_steps=sdc_steps,
                      seed=args.seed + 2)

    grace_params = {
        "compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
        "communicator": "rscatter", "fsdp_axis": "fsdp",
        # slice boundary inside the dp axis: the flat rscatter's rows
        # honestly price a DCN leg — the artifact's two-axis wire split.
        "slice_size": max(1, dp // 2),
        "route": [("b*", {"compressor": "fp16", "memory": "none",
                          "communicator": "allreduce"})],
        "escape": "fp16", "consensus": consensus,
        "telemetry": max(2 * args.telemetry_every, 16),
    }
    grc = grace_from_params(grace_params)
    grc = _dc.replace(grc, communicator=ChaosCommunicator(
        inner=grc.communicator, nan_prob=args.nan_prob, rank=args.rank,
        seed=args.seed + 1))
    tx = guarded_chain(grc, optax.sgd(args.lr),
                       fallback_after=args.fallback_after,
                       fallback_steps=args.fallback_steps)

    state = init_train_state(params, tx, mesh, axis_name=mesh_spec,
                             param_specs=param_specs)
    step = make_train_step(loss_fn, tx, mesh, axis_name=mesh_spec,
                           param_specs=param_specs, donate=False,
                           consensus=consensus)

    sink = reader = None
    if args.telemetry_out:
        prov = run_provenance(
            data="synthetic", tool="chaos_smoke",
            argv=" ".join(sys.argv[1:]),
            nan_prob=args.nan_prob, steps=args.steps,
            fsdp=fsdp, dp=dp)
        sink = JSONLSink(args.telemetry_out, provenance=prov)
        sink, _ = _incident_sink(sink, args, prov, "fsdp")
        reader = TelemetryReader(sink, every=args.telemetry_every)
    monitor = GuardMonitor(sink=sink)
    consensus_mon = ConsensusMonitor(sink=sink)

    batch_n = max(args.batch, dp) // dp * dp
    images = rng.normal(size=(4 * batch_n, feat)).astype(np.float32)
    labels = rng.integers(0, classes, size=(4 * batch_n,)).astype(np.int32)

    def repairs_by_group(st) -> list:
        """Per-fsdp-group consensus repair counts. The AuditState is
        replicated WITHIN each dp group (its whole jurisdiction) but a
        repair in group 1 never bumps group 0's counter — reading one
        device (audit_report) under-reports, so sum the per-group view."""
        from grace_tpu.transform import GraceState
        audits = []

        def find(node):
            if isinstance(node, GraceState) and node.audit is not None:
                audits.append(node.audit)
            return node

        jax.tree_util.tree_map(find, st.opt_state,
                               is_leaf=lambda n: isinstance(n, GraceState))
        reps = audits[0].repairs
        per_dev = {s.device: int(np.asarray(s.data).reshape(-1)[0])
                   for s in reps.addressable_shards}
        return [max(per_dev[mesh.devices[d, f]] for d in range(dp))
                for f in range(fsdp)]

    loss = float("nan")
    t0 = time.perf_counter()
    for i in range(args.steps):
        state = sdc(state, i)
        lo = (i * batch_n) % len(images)
        b = (jnp.asarray(images[lo:lo + batch_n]),
             jnp.asarray(labels[lo:lo + batch_n]))
        state, loss = step(state, b)
        monitor.update(i, guard_report(state))
        consensus_mon.update(i, audit_report(state))
        if reader is not None:
            reader.update(i, state)
    loss = float(loss)
    dt = time.perf_counter() - t0
    if reader is not None:
        reader.flush(state)
        reader.close()

    rep = guard_report(state)
    arep = dict(audit_report(state))
    group_repairs = repairs_by_group(state)
    arep["repairs"] = sum(group_repairs)
    # Replicas must be bit-identical PER FSDP SHARD: group every param
    # leaf's device buffers by the global index window they cover (a
    # replicated leaf has one window — all 8 buffers must agree; w1 has
    # one window per fsdp shard — its dp replicas must agree within each).
    variants = 0
    for leaf in jax.tree_util.tree_leaves(state.params):
        groups: dict = {}
        for s in leaf.addressable_shards:
            key = str(s.index)
            groups.setdefault(key, set()).add(
                np.asarray(s.data).tobytes())
        variants = max(variants, max(len(v) for v in groups.values()))
    print(f"[chaos_smoke] fsdp: {args.steps} steps in {dt:.1f}s on "
          f"dp{dp}×fsdp{fsdp} | final loss {loss:.4f} | skipped "
          f"{rep['notfinite_count']} | injected {len(sdc.injections)} | "
          f"repairs {arep['repairs']} (per fsdp group: {group_repairs}) | "
          f"per-shard replica variants {variants}")

    ok = True
    if not np.isfinite(loss):
        print("[chaos_smoke] FAIL: final loss non-finite over the 2-D "
              "mesh", file=sys.stderr)
        ok = False
    if args.nan_prob and rep["notfinite_count"] == 0:
        print("[chaos_smoke] FAIL: guard never tripped — injection is "
              "not reaching the per-shard exchanges", file=sys.stderr)
        ok = False
    if arep["repairs"] < len(sdc.injections):
        print(f"[chaos_smoke] FAIL: consensus repaired {arep['repairs']} "
              f"of {len(sdc.injections)} injected corruptions over the "
              "2-D mesh", file=sys.stderr)
        ok = False
    if variants > 1:
        print("[chaos_smoke] FAIL: replicas still diverged within an "
              "fsdp shard after the final audit window", file=sys.stderr)
        ok = False
    if args.telemetry_out:
        import json as _json
        split_rows = both_axes = 0
        with open(args.telemetry_out) as f:
            for line in f:
                rec = _json.loads(line)
                if "step" not in rec or "wire_bytes" not in rec:
                    continue
                if "wire_bytes_ici" in rec and "wire_bytes_dcn" in rec:
                    split_rows += 1
                    if rec["wire_bytes_dcn"] > 0 and \
                            rec["wire_bytes_ici"] > 0:
                        both_axes += 1
        print(f"[chaos_smoke] fsdp: {split_rows} telemetry rows carry "
              f"the per-link split ({both_axes} with bytes on BOTH "
              "links)")
        if not split_rows:
            print("[chaos_smoke] FAIL: no telemetry row carries the "
                  "two-axis wire split", file=sys.stderr)
            ok = False
    print("[chaos_smoke] OK" if ok else "[chaos_smoke] FAIL",
          flush=True)
    return 0 if ok else 1


def _adapt_main(args) -> int:
    """The --adapt lifecycle: drift → tighten (before any guard event) →
    quiet → loosen → NaN → guard trip + escalate-and-hold. Returns 0 only
    when every acceptance fact holds (see module docstring)."""
    import dataclasses
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.parallel import data_parallel_mesh
    from grace_tpu.resilience import (AdaptMonitor, ChaosCommunicator,
                                      ChaosCompressor, adapt_report,
                                      guarded_chain)
    from grace_tpu.telemetry import JSONLSink, TelemetryReader
    from grace_tpu.telemetry.timeline import Timeline
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.utils.logging import GuardMonitor, run_provenance
    from grace_tpu.utils.metrics import guard_report

    mesh = data_parallel_mesh()
    world = mesh.devices.size
    window = args.adapt_window
    # Phase split: A (drift — must tighten), B (quiet — must loosen),
    # C (NaN — guard trips, controller escalates). Each phase spans
    # enough windows for its claim.
    steps_a = max(3 * window, args.steps // 3)
    steps_b = max(4 * window, args.steps // 3)
    steps_c = max(window + args.fallback_after + args.fallback_steps + 2,
                  args.steps - steps_a - steps_b)

    # The degradation ladder: dense escape (rung 0) → gentle 8-bit qsgd
    # (rung 1) → aggressive 4-bit-ish qsgd (rung 2, the steady state).
    # Thresholds sit between the healthy steady-state error (~0.2-0.3 for
    # q=15 on this model) and the drifted rank's error (~drift_scale):
    # quiet runs read below loosen_error, the drifting rank's pmax
    # crosses tighten_peak within its first window.
    drift = 0.9
    grace_params = {
        "compressor": "qsgd", "quantum_num": 15, "use_pallas": False,
        "memory": "none", "communicator": "allgather",
        "escape": "fp16",
        "telemetry": max(2 * args.telemetry_every, 16),
        "adapt": {"window": window,
                  "ladder": [{"quantum_num": 127}],
                  "tighten_error": 0.5, "tighten_peak": 0.6,
                  "loosen_error": 0.35, "quiet_windows": 2,
                  "hold_windows": 2},
    }

    def build(drift_rank=None, nan_prob=0.0):
        """(grace, guarded tx) for one phase. The drift injector must
        wrap EVERY ladder rung's codec (the controller swaps codecs
        mid-run; a drift that only afflicted the top rung would vanish
        the moment the controller tightened — voiding the scenario)."""
        grc = grace_from_params(grace_params)
        if drift_rank is not None:
            def wrap(c):
                return ChaosCompressor(inner=c, drift_scale=drift,
                                       rank=drift_rank,
                                       seed=args.seed + 3)
            grc = dataclasses.replace(
                grc, compressor=wrap(grc.compressor),
                adapt=dataclasses.replace(
                    grc.adapt,
                    ladder=tuple(wrap(c) for c in grc.adapt.ladder)))
        if nan_prob:
            grc = dataclasses.replace(grc, communicator=ChaosCommunicator(
                inner=grc.communicator, nan_prob=nan_prob, rank=args.rank,
                seed=args.seed + 1))
        tx = guarded_chain(grc, optax.sgd(args.lr),
                           fallback_after=args.fallback_after,
                           fallback_steps=args.fallback_steps)
        return grc, tx

    # Small dense MLP (the _fsdp_main scale): three phase recompiles with
    # a 3-branch ladder each — LeNet-sized compiles would triple that
    # cost for no extra coverage.
    feat, hid, classes = 32, 16, 8
    rng = np.random.default_rng(args.seed)
    params = {
        "w1": jnp.asarray(rng.normal(scale=0.3, size=(feat, hid)),
                          jnp.float32),
        "b1": jnp.zeros((hid,), jnp.float32),
        "w2": jnp.asarray(rng.normal(scale=0.3, size=(hid, classes)),
                          jnp.float32),
        "b2": jnp.zeros((classes,), jnp.float32),
    }

    def loss_fn(p, b):
        x, y = b
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    batch = max(args.batch, world) // world * world
    images = rng.normal(size=(4 * batch, feat)).astype(np.float32)
    labels = rng.integers(0, classes, size=(4 * batch,)).astype(np.int32)

    def at(i):
        lo = (i * batch) % (len(images) - batch + 1)
        return (jnp.asarray(images[lo:lo + batch]),
                jnp.asarray(labels[lo:lo + batch]))

    sink = reader = None
    if not args.telemetry_out:
        print("[chaos_smoke] --adapt requires --telemetry-out: the "
              "acceptance artifact IS the adapt_tighten/guard event "
              "ordering", file=sys.stderr)
        return 1
    prov = run_provenance(
        data="synthetic", tool="chaos_smoke",
        argv=" ".join(sys.argv[1:]), steps=args.steps,
        adapt=True, adapt_window=window, adapt_rank=args.adapt_rank)
    sink = JSONLSink(args.telemetry_out, provenance=prov)
    sink, _ = _incident_sink(sink, args, prov, "adapt")
    reader = TelemetryReader(sink, every=args.telemetry_every)
    adapt_mon = AdaptMonitor(sink=sink)
    monitor = GuardMonitor(sink=sink)

    total = float("nan")
    t0 = time.perf_counter()

    def run_phase(state, step_fn, lo, hi):
        loss = float("nan")
        for i in range(lo, hi):
            state, loss = step_fn(state, at(i))
            monitor.update(i, guard_report(state))
            adapt_mon.observe(reader.update(i, state))
        return state, float(loss)

    # ---- phase A: one rank's encoder drifts — tighten, guard silent ----
    grc_a, tx_a = build(drift_rank=args.adapt_rank)
    state = init_train_state(params, tx_a, mesh)
    step_a = make_train_step(loss_fn, tx_a, mesh, donate=False)
    state, _ = run_phase(state, step_a, 0, steps_a)
    adapt_mon.observe(reader.flush(state))        # drain the tail window
    guard_a = guard_report(state)
    tightens_a = [e for e in adapt_mon.events
                  if e["event"] == "adapt_tighten"]
    first_tighten = min((e["step"] for e in tightens_a), default=None)
    rep_a = adapt_report(state)
    print(f"[chaos_smoke] adapt phase A (drift rank {args.adapt_rank}): "
          f"{steps_a} steps | rung {rep_a['rung']} | tightens "
          f"{rep_a['tightens']} (first event step {first_tighten}) | "
          f"guard skips {guard_a['notfinite_count']}")
    if guard_a["notfinite_count"] != 0:
        print("[chaos_smoke] FAIL: guard tripped during the drift phase "
              "— the fault is finite and guard-invisible; the smoke "
              "itself is broken", file=sys.stderr)
        return 1
    if not tightens_a:
        print("[chaos_smoke] FAIL: seeded drift produced no adapt_tighten "
              "event — the controller is not reacting to the error "
              "spike", file=sys.stderr)
        return 1
    if first_tighten > 2 * window:
        print(f"[chaos_smoke] FAIL: first tighten at step {first_tighten} "
              f"— later than one window ({window}) plus the decision "
              "latency", file=sys.stderr)
        return 1

    # ---- phase B: drift off — the controller must loosen back ----------
    grc_b, tx_b = build()
    step_b = make_train_step(loss_fn, tx_b, mesh, donate=False)
    state, _ = run_phase(state, step_b, steps_a, steps_a + steps_b)
    adapt_mon.observe(reader.flush(state))
    loosens = [e for e in adapt_mon.events if e["event"] == "adapt_loosen"]
    rep_b = adapt_report(state)
    print(f"[chaos_smoke] adapt phase B (quiet): {steps_b} steps | rung "
          f"{rep_b['rung']} | loosens {rep_b['loosens']}")
    if not loosens:
        print("[chaos_smoke] FAIL: quiet phase produced no adapt_loosen "
              "event — the controller never recovers from degradation",
              file=sys.stderr)
        return 1

    # ---- phase C: NaN injection — guard trips, controller escalates ----
    grc_c, tx_c = build(nan_prob=1.0)
    step_c = make_train_step(loss_fn, tx_c, mesh, donate=False)
    state, total = run_phase(state, step_c, steps_a + steps_b,
                             steps_a + steps_b + steps_c)
    adapt_mon.observe(reader.flush(state))
    reader.close()
    dt = time.perf_counter() - t0

    guard_c = guard_report(state)
    rep_c = adapt_report(state)
    print(f"[chaos_smoke] adapt phase C (NaN): {steps_c} steps | final "
          f"loss {total:.4f} | guard skips {guard_c['notfinite_count']} | "
          f"escalations {rep_c['escalations']} | hold {rep_c['hold']} | "
          f"{dt:.1f}s total")

    # Ordering is judged from the ARTIFACT, not loop bookkeeping: the
    # first adapt event must precede the first guard event in the unified
    # timeline — tighten-before-guard is the scenario's whole claim.
    # (Step-less guard_only flush records are skipped: they carry
    # counters, not an event position.)
    tl = Timeline.from_jsonl(args.telemetry_out)
    first_adapt = next((e for e in tl.kinds("adapt")
                        if e.step is not None), None)
    first_guard = next((e for e in tl.kinds("guard")
                        if e.step is not None), None)
    ordering_ok = (first_adapt is not None and first_guard is not None
                   and first_adapt.step < first_guard.step)
    print(f"[chaos_smoke] adapt ordering: first adapt event step "
          f"{first_adapt.step if first_adapt else None} < first guard "
          f"event step {first_guard.step if first_guard else None} -> "
          f"{'OK' if ordering_ok else 'VIOLATED'}")

    if args.adapt_out:
        doc = {
            "tool": "chaos_smoke",
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "argv": " ".join(sys.argv[1:]),
            "world": world,
            "window": window,
            "ladder": ["fp16 dense escape (rung 0)",
                       "qsgd quantum_num=127 (rung 1)",
                       "qsgd quantum_num=15 (rung 2, steady state)"],
            "phases": {"drift": [0, steps_a],
                       "quiet": [steps_a, steps_a + steps_b],
                       "nan": [steps_a + steps_b,
                               steps_a + steps_b + steps_c]},
            "tighten": {"count": int(rep_c["tightens"]),
                        "first_step": first_tighten,
                        "within_one_window": bool(
                            first_tighten <= 2 * window)},
            "loosen": {"count": int(rep_c["loosens"]),
                       "first_step": min((e["step"] for e in loosens),
                                         default=None)},
            "escalations": int(rep_c["escalations"]),
            "final_rung": int(rep_c["rung"]),
            "first_adapt_step": (first_adapt.step if first_adapt
                                 else None),
            "first_guard_step": (first_guard.step if first_guard
                                 else None),
            "ordering_ok": bool(ordering_ok),
            "guard_skips": int(guard_c["notfinite_count"]),
            "final_loss": float(total),
        }
        _write_evidence_doc(doc, args.adapt_out,
                            ledger_id="adapt-drill",
                            metric="adapt_ordering_ok",
                            value=bool(ordering_ok), world=world,
                            label="adapt evidence")

    if not np.isfinite(total):
        print("[chaos_smoke] FAIL: final loss non-finite — the "
              "guard+ladder stack did not contain the NaN phase",
              file=sys.stderr)
        return 1
    if guard_c["notfinite_count"] == 0:
        print("[chaos_smoke] FAIL: guard never tripped in the NaN phase "
              "— injection is not reaching the pipeline", file=sys.stderr)
        return 1
    if rep_c["escalations"] == 0:
        print("[chaos_smoke] FAIL: the controller registered no "
              "escalate-and-hold evidence despite the guard's fallback "
              "windows", file=sys.stderr)
        return 1
    if not ordering_ok:
        print("[chaos_smoke] FAIL: the first adapt event does not "
              "precede the first guard event — tighten-before-guard is "
              "the scenario's claim", file=sys.stderr)
        return 1
    print("[chaos_smoke] OK")
    return 0


def _retune_main(args) -> int:
    """The --retune lifecycle: baseline → fleet drift → retune_drift →
    promote (two-phase) → probation clears → promote back → sabotaged
    promotion → automatic bit-exact demotion. Returns 0 only when every
    acceptance fact holds (see module docstring)."""
    import dataclasses
    import tempfile

    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.checkpoint import Checkpointer
    from grace_tpu.parallel import data_parallel_mesh
    from grace_tpu.resilience import (ChaosCompressor, ConsensusConfig,
                                      RetuneController, guarded_chain,
                                      replica_variants)
    from grace_tpu.telemetry import JSONLSink, MultiSink, TelemetryReader
    from grace_tpu.telemetry.timeline import Timeline
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.utils.logging import GuardMonitor, run_provenance
    from grace_tpu.utils.metrics import guard_report

    mesh = data_parallel_mesh()
    world = mesh.devices.size
    window = args.retune_window
    probation = args.retune_probation
    # Flush cadence must not exceed the drift window, or the controller
    # only ever sees rows (and can only fire) at flush boundaries.
    tev = max(1, min(args.telemetry_every, window))
    telem = max(2 * tev, 16)

    consensus = ConsensusConfig(audit_every=args.audit_every)
    # The incumbent: the 4-bit homomorphic family (shared-scale payload
    # algebra). The candidate: PowerSGD rank 4 carrying a rank-1 adapt
    # ladder — the stateful-codec migration the rung-invariant layout
    # exists for (Q/P padded to max rank, one lax.switch).
    old_params = {"compressor": "homoqsgd", "quantum_num": 7,
                  "memory": "residual", "communicator": "allreduce",
                  "fusion": "flat", "escape": "fp16",
                  "telemetry": telem, "consensus": consensus}
    new_params = {"compressor": "powersgd", "compress_rank": 4,
                  "memory": "powersgd", "communicator": "allreduce",
                  "escape": "fp16", "telemetry": telem,
                  "consensus": consensus,
                  "adapt": {"window": window,
                            "ladder": [{"compress_rank": 1}]}}

    # Chaos is toggled OUTSIDE the controller: the same build closure
    # serves every transition, and the sabotage variant flips "nan" only
    # between PREPARE and COMMIT of the doomed promotion — the demotion's
    # rebuild of the incumbent sees a clean flag, exactly like a config
    # push whose payload (not the push machinery) is poisoned.
    chaos = {"drift": False, "nan": False}

    def build(p):
        grc = grace_from_params(p)
        wraps = []
        if chaos["drift"]:
            # rank=None faults EVERY rank: the drift must move the
            # fleet-mean compression error (a single-rank drift is
            # graft-watch's scenario; sustained fleet drift is retune's).
            wraps.append(lambda c: ChaosCompressor(
                inner=c, drift_scale=args.drift_scale, rank=None,
                seed=args.seed + 3))
        if chaos["nan"]:
            wraps.append(lambda c: ChaosCompressor(
                inner=c, nan_prob=1.0, rank=args.rank,
                seed=args.seed + 5))
        for wrap in wraps:
            grc = dataclasses.replace(grc, compressor=wrap(grc.compressor))
            if grc.adapt is not None:
                grc = dataclasses.replace(grc, adapt=dataclasses.replace(
                    grc.adapt,
                    ladder=tuple(wrap(c) for c in grc.adapt.ladder)))
        tx = guarded_chain(grc, optax.sgd(args.lr),
                           fallback_after=args.fallback_after,
                           fallback_steps=args.fallback_steps)
        return grc, tx

    # Small dense MLP (the _adapt_main scale): this scenario recompiles
    # the step five times across two codec families.
    feat, hid, classes = 32, 16, 8
    rng = np.random.default_rng(args.seed)
    params = {
        "w1": jnp.asarray(rng.normal(scale=0.3, size=(feat, hid)),
                          jnp.float32),
        "b1": jnp.zeros((hid,), jnp.float32),
        "w2": jnp.asarray(rng.normal(scale=0.3, size=(hid, classes)),
                          jnp.float32),
        "b2": jnp.zeros((classes,), jnp.float32),
    }

    def loss_fn(p, b):
        x, y = b
        h = jnp.tanh(x @ p["w1"] + p["b1"])
        logits = h @ p["w2"] + p["b2"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    batch = max(args.batch, world) // world * world
    images = rng.normal(size=(4 * batch, feat)).astype(np.float32)
    labels = rng.integers(0, classes, size=(4 * batch,)).astype(np.int32)

    def at(i):
        lo = (i * batch) % (len(images) - batch + 1)
        return (jnp.asarray(images[lo:lo + batch]),
                jnp.asarray(labels[lo:lo + batch]))

    if not args.telemetry_out:
        print("[chaos_smoke] --retune requires --telemetry-out: the "
              "acceptance artifact IS the retune event ordering",
              file=sys.stderr)
        return 1
    prov = run_provenance(
        data="synthetic", tool="chaos_smoke",
        argv=" ".join(sys.argv[1:]), steps=args.steps,
        retune=True, retune_window=window, retune_probation=probation)

    class _Tape:
        """Sink that mirrors the record stream into a list — the
        probation watch is fed the same records the artifact gets."""

        def __init__(self):
            self.records = []

        def write(self, rec):
            self.records.append(dict(rec))

        def close(self):
            pass

    tape = _Tape()
    sink = MultiSink(JSONLSink(args.telemetry_out, provenance=prov), tape)
    sink, _ = _incident_sink(sink, args, prov, "retune")
    reader = TelemetryReader(sink, every=tev)
    monitor = GuardMonitor(sink=sink)

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="grace-retune-")
    ckpt = Checkpointer(ckpt_dir, max_to_keep=2)
    ctl = RetuneController(
        build=build, params=old_params, consensus=consensus,
        checkpointer=ckpt, sink=sink,
        window=window, drift_factor=1.4, drift_windows=2,
        probation_steps=probation,
        leg_timeout_s=args.drain_timeout or None, leg_retries=1,
        audit_world=world)

    t0 = time.perf_counter()
    grc, tx = build(old_params)
    state = init_train_state(params, tx, mesh)
    step_fn = make_train_step(loss_fn, tx, mesh, donate=False)

    def run_steps(state, step_fn, lo, hi, observe=False):
        """Advance [lo, hi); returns (state, loss, first drift step or
        None, probation trigger or None). Each step feeds the guard
        monitor, drains telemetry rows into the controller's drift watch
        (when asked), and — during probation — shows the controller the
        fresh trigger-event records from this step's tape window."""
        loss, drift_step, trigger = float("nan"), None, None
        for i in range(lo, hi):
            state, loss = step_fn(state, at(i))
            n0 = len(tape.records)
            monitor.update(i, guard_report(state))
            rows = reader.update(i, state)
            if observe:
                for row in rows:
                    if ctl.observe(row["step"],
                                   row.get("compression_error")):
                        drift_step = (drift_step if drift_step is not None
                                      else int(row["step"]))
                if drift_step is not None:
                    return state, float(loss), drift_step, None, i
            if ctl.phase == "probation":
                trigger = ctl.watch(i, tape.records[n0:])
                if trigger:
                    return state, float(loss), drift_step, trigger, i
        return state, float(loss), drift_step, None, hi - 1

    # ---- healthy drill: baseline → fleet drift → retune_drift ----------
    warmup = 3 * window + 2
    state, loss, _, _, _ = run_steps(state, step_fn, 0, warmup,
                                     observe=True)
    chaos["drift"] = True
    _, tx_d = build(old_params)
    step_d = make_train_step(loss_fn, tx_d, mesh, donate=False)
    drift_cap = warmup + 8 * window
    state, loss, drift_step, _, last = run_steps(state, step_d, warmup,
                                                 drift_cap, observe=True)
    chaos["drift"] = False
    guard_rep = guard_report(state)
    print(f"[chaos_smoke] retune drift: fleet drift_scale "
          f"{args.drift_scale} from step {warmup} | retune_drift at step "
          f"{drift_step} | guard skips {guard_rep['notfinite_count']}")
    if drift_step is None:
        print("[chaos_smoke] FAIL: sustained fleet-wide drift never "
              "produced a retune_drift verdict — the controller is not "
              "reading the telemetry it was built for", file=sys.stderr)
        return 1
    if guard_rep["notfinite_count"] != 0:
        print("[chaos_smoke] FAIL: guard tripped during the drift phase "
              "— the fault is finite and guard-invisible; the smoke "
              "itself is broken", file=sys.stderr)
        return 1

    # ---- optional bounded funnel against the live mesh -----------------
    funnel_doc = None
    if args.retune_funnel:
        funnel_doc = ctl.propose(
            last + 1, mesh, str(world), model="toy", shortlist_n=2,
            timed_steps=2, repeats=1, seed=args.seed, audit_world=world)
        print(f"[chaos_smoke] retune funnel: winner "
              f"{funnel_doc['winner'] if funnel_doc else None}")

    def promote(i, state, cand, label):
        """One PREPARE+COMMIT transaction; None on abort."""
        staged = ctl.prepare(i, state, mesh, cand)
        if staged is None:
            print(f"[chaos_smoke] FAIL: PREPARE aborted for {label}: "
                  f"{ctl.events[-1]}", file=sys.stderr)
            return None
        out = ctl.commit(i, mesh)
        if out is None:
            print(f"[chaos_smoke] FAIL: COMMIT timed out for {label} — "
                  f"incumbent retained: {ctl.events[-1]}", file=sys.stderr)
            return None
        state, (_, tx), ev = out
        mig = staged.migration
        print(f"[chaos_smoke] retune promote ({label}) at step {i}: "
              f"mem {mig['mem']} comp {mig['comp']} | footprint "
              f"{staged.footprint_matches} | checkpointed "
              f"{staged.checkpointed} | probation until "
              f"{ev['probation_until']}")
        return state, tx, ev, mig

    # ---- promotion 1: homoqsgd4 → powersgd rank ladder ------------------
    i0 = last + 1
    out = promote(i0, state, new_params, "homoqsgd4 -> powersgd ladder")
    if out is None:
        return 1
    state, tx2, ev_fwd, mig_fwd = out
    step2 = make_train_step(loss_fn, tx2, mesh, donate=False)
    variants_fwd = replica_variants(state.params)
    state, loss, _, trig, _ = run_steps(state, step2, i0,
                                        i0 + probation + 1)
    if trig is not None:
        print(f"[chaos_smoke] FAIL: healthy probation tripped "
              f"({trig}) after the forward promotion", file=sys.stderr)
        return 1
    if ctl.phase != "idle":
        print("[chaos_smoke] FAIL: probation never cleared after the "
              "forward promotion", file=sys.stderr)
        return 1

    # ---- promotion 2: back to homoqsgd4 (reverse migration) -------------
    i0 += probation + 1
    out = promote(i0, state, old_params, "powersgd ladder -> homoqsgd4")
    if out is None:
        return 1
    state, tx3, ev_back, mig_back = out
    step3 = make_train_step(loss_fn, tx3, mesh, donate=False)
    state, loss, _, trig, _ = run_steps(state, step3, i0,
                                        i0 + probation + 1)
    if trig is not None or ctl.phase != "idle":
        print(f"[chaos_smoke] FAIL: back-promotion probation did not "
              f"clear quietly (trigger={trig}, phase={ctl.phase})",
              file=sys.stderr)
        return 1
    guard_rep = guard_report(state)
    healthy_guard_events = [r for r in tape.records
                            if str(r.get("event", "")).startswith("guard")]
    print(f"[chaos_smoke] retune healthy drill done: loss {loss:.4f} | "
          f"replica variants after forward commit {variants_fwd} | guard "
          f"events {len(healthy_guard_events)}")
    if healthy_guard_events:
        print("[chaos_smoke] FAIL: the guard fired during the healthy "
              "drill — promotion must be guard-invisible", file=sys.stderr)
        return 1
    if variants_fwd != 1:
        print(f"[chaos_smoke] FAIL: {variants_fwd} replica variants "
              "after the consensus-gated cutover", file=sys.stderr)
        return 1

    # ---- sabotage: promoted config is poisoned → demote -----------------
    i0 += probation + 1
    chaos["nan"] = True
    out = promote(i0, state, new_params, "sabotaged powersgd ladder")
    chaos["nan"] = False
    if out is None:
        return 1
    state, tx_sab, ev_sab, _ = out
    step_sab = make_train_step(loss_fn, tx_sab, mesh, donate=False)
    sab_state, loss, _, trig, trig_step = run_steps(
        state, step_sab, i0, i0 + probation + 1)
    if trig is None:
        print("[chaos_smoke] FAIL: the poisoned promotion survived its "
              "probation window — the NaN injection never reached the "
              "guard", file=sys.stderr)
        return 1
    within = trig_step < ev_sab["probation_until"]
    state, (_, tx4), ev_dem = ctl.demote(trig_step, sab_state, mesh,
                                         trigger=trig)
    step4 = make_train_step(loss_fn, tx4, mesh, donate=False)
    state, loss, _, _, _ = run_steps(state, step4, trig_step,
                                     trig_step + 4)
    reader.flush(state)
    reader.close()
    ckpt.close()
    dt = time.perf_counter() - t0
    print(f"[chaos_smoke] retune sabotage: trigger {trig} at step "
          f"{trig_step} (probation until {ev_sab['probation_until']}) | "
          f"demote restored={ev_dem['restored']} "
          f"bit_exact={ev_dem['bit_exact']} | post-demote loss "
          f"{loss:.4f} | {dt:.1f}s total")

    # Ordering is judged from the ARTIFACT, not loop bookkeeping: the
    # transaction's event sequence in the unified timeline must read
    # drift < prepare < promote < probation_clear, and the sabotage
    # demotion must land before its probation horizon.
    tl = Timeline.from_jsonl(args.telemetry_out)
    firsts = {}
    for e in tl.kinds("retune"):
        name = str(e.record.get("event"))
        if name not in firsts and e.step is not None:
            firsts[name] = e.step
    order = ["retune_drift", "retune_prepare", "retune_promote",
             "retune_probation_clear"]
    ordering_ok = (all(n in firsts for n in order) and
                   all(firsts[a] <= firsts[b] for a, b in
                       zip(order, order[1:])) and
                   "retune_demote" in firsts)
    print(f"[chaos_smoke] retune ordering: "
          + " <= ".join(f"{n.split('retune_')[1]}@{firsts.get(n)}"
                        for n in order)
          + f", demote@{firsts.get('retune_demote')} -> "
          + ("OK" if ordering_ok else "VIOLATED"))

    if args.retune_out:
        doc = {
            "tool": "chaos_smoke",
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "argv": " ".join(sys.argv[1:]),
            "world": world,
            "window": window,
            "probation_steps": probation,
            "incumbent": "homoqsgd quantum_num=7 (4-bit shared-scale)",
            "candidate": "powersgd rank 4 + rank-1 adapt ladder",
            "drift": {"scale": args.drift_scale, "from_step": warmup,
                      "verdict_step": drift_step},
            "funnel": (None if funnel_doc is None else {
                "winner": funnel_doc.get("winner"),
                "measured": [
                    {"candidate": r["candidate"],
                     "measured_step_ms": r["measured_step_ms"],
                     "projected_step_ms": r["projected_step_ms"]}
                    for r in (funnel_doc.get("measured") or {})
                    .get("rows", [])],
                "skipped": (funnel_doc.get("measured") or {})
                .get("skipped", [])}),
            "forward_promotion": {
                "step": ev_fwd["step"],
                "migration": mig_fwd,
                "replica_variants": variants_fwd,
                "probation_until": ev_fwd["probation_until"]},
            "back_promotion": {
                "step": ev_back["step"],
                "migration": mig_back,
                "probation_until": ev_back["probation_until"]},
            "sabotage": {
                "promote_step": ev_sab["step"],
                "trigger": trig,
                "trigger_step": trig_step,
                "probation_until": ev_sab["probation_until"],
                "within_probation": bool(within),
                "restored": bool(ev_dem["restored"]),
                "bit_exact": bool(ev_dem["bit_exact"])},
            "guard_events_during_healthy_drill":
                len(healthy_guard_events),
            "ordering_ok": bool(ordering_ok),
            "first_steps": firsts,
            "final_loss": float(loss),
        }
        _write_evidence_doc(doc, args.retune_out,
                            ledger_id="retune-drill",
                            metric="retune_demote_bit_exact",
                            value=bool(ev_dem["bit_exact"]), world=world,
                            label="retune evidence")

    if not np.isfinite(loss):
        print("[chaos_smoke] FAIL: final loss non-finite after the "
              "demotion — the rollback did not restore a trainable "
              "state", file=sys.stderr)
        return 1
    if not within:
        print("[chaos_smoke] FAIL: the demotion landed outside the "
              "probation window", file=sys.stderr)
        return 1
    if not (ev_dem["restored"] and ev_dem["bit_exact"]):
        print("[chaos_smoke] FAIL: demotion did not restore the "
              "last-known-good checkpoint bit-exactly", file=sys.stderr)
        return 1
    if not ordering_ok:
        print("[chaos_smoke] FAIL: the artifact's retune event ordering "
              "violates the transaction sequence", file=sys.stderr)
        return 1
    print("[chaos_smoke] OK")
    return 0


def _elastic_main(args) -> int:
    """The --elastic lifecycle: drift → drain → kill → W−1 resume → rejoin
    → W, with the consensus barrier gating the rejoin. Returns 0 only when
    every acceptance fact holds (see module docstring)."""
    import dataclasses
    import json
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.checkpoint import Checkpointer
    from grace_tpu.core import Topology
    from grace_tpu.models import lenet
    from grace_tpu.parallel import data_parallel_mesh
    from grace_tpu.resilience import (ChaosCompressor, ConsensusConfig,
                                      ElasticController, guarded_chain,
                                      plan_resize, validate_resharded)
    from grace_tpu.telemetry import JSONLSink, TelemetryReader
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.utils.logging import GuardMonitor, run_provenance
    from grace_tpu.utils.metrics import guard_report

    devices = jax.devices()
    world = len(devices)
    doomed = args.elastic_rank
    if args.hier:
        if world % args.slice_size:
            print(f"[chaos_smoke] --elastic --hier: world {world} not a "
                  f"multiple of slice_size {args.slice_size}",
                  file=sys.stderr)
            return 1
        k = doomed // args.slice_size
        lost = tuple(range(k * args.slice_size, (k + 1) * args.slice_size))
        topo = Topology(slice_size=args.slice_size)
    else:
        lost = (doomed,)
        topo = Topology()
    plan = plan_resize(world, lost, topo)
    # Phase split: A (full world, drift until drained), B (survivors),
    # C (post-rejoin, where the convergence floor is judged).
    steps_a = max(args.steps // 3, 2 * args.watch_window)
    steps_b = max(args.steps // 4, 4)
    steps_c = max(args.steps - steps_a - steps_b, 4)

    consensus = ConsensusConfig(audit_every=args.audit_every)

    def build(slice_size, drift_rank=None):
        """(grace, guarded tx) for one phase. Rebuilding the transform is
        the resize's single topology-invalidation point."""
        p = {"compressor": "topk", "compress_ratio": 0.3,
             "memory": "residual", "communicator": "allgather",
             "escape": "fp16", "consensus": consensus,
             "telemetry": max(2 * args.telemetry_every, 16),
             "watch": {"window": args.watch_window,
                       "capacity": max(2 * args.telemetry_every
                                       // args.watch_window, 8)}}
        if args.hier:
            # A whole-slice loss keeps slice_size (the K→K−1 resize);
            # a partial loss would hand back None and the flat schedule —
            # exactly HierarchicalAllreduce.shrunk's contract.
            p.update(communicator="hier", fusion="flat")
            if slice_size:
                p["slice_size"] = slice_size
        grc = grace_from_params(p)
        if drift_rank is not None:
            grc = dataclasses.replace(grc, compressor=ChaosCompressor(
                inner=grc.compressor, drift_scale=args.drift_scale,
                rank=drift_rank, seed=args.seed + 3))
        tx = guarded_chain(grc, optax.sgd(args.lr),
                           fallback_after=args.fallback_after,
                           fallback_steps=args.fallback_steps)
        return grc, tx

    def batches(w):
        b = max(args.batch, w) // w * w
        rng = np.random.default_rng(args.seed)
        images = rng.normal(size=(4 * args.batch, 28, 28, 1)).astype(
            np.float32)
        labels = rng.integers(0, 10, size=(4 * args.batch,)).astype(np.int32)

        def at(i):
            lo = (i * b) % (len(images) - b + 1)
            return (jnp.asarray(images[lo:lo + b]),
                    jnp.asarray(labels[lo:lo + b]))
        return at

    def loss_fn(params, b):
        x, y = b
        logits, _ = lenet.apply(params, {}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    sink = None
    reader = None
    if args.telemetry_out:
        prov = run_provenance(
            data="synthetic", tool="chaos_smoke",
            argv=" ".join(sys.argv[1:]), steps=args.steps,
            elastic=True, elastic_rank=doomed, hier=args.hier)
        sink = JSONLSink(args.telemetry_out, provenance=prov)
        sink, _ = _incident_sink(sink, args, prov, "elastic")
        reader = TelemetryReader(sink, every=args.telemetry_every,
                                 anomaly=True)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="grace_elastic_")
    ckpt = Checkpointer(ckpt_dir, max_to_keep=2)
    controller = ElasticController(consensus=consensus, checkpointer=ckpt,
                                   sink=sink, anomaly_threshold=1)
    monitor = GuardMonitor(sink=sink)

    # ---- phase A: full world, one rank drifting -------------------------
    mesh_a = data_parallel_mesh(devices)
    grc_a, tx_a = build(args.slice_size if args.hier else None,
                        drift_rank=doomed)
    params, _ = lenet.init(jax.random.key(args.seed))
    state = init_train_state(params, tx_a, mesh_a)
    step = make_train_step(loss_fn, tx_a, mesh_a, donate=False,
                           consensus=consensus)
    at = batches(world)
    t0 = time.perf_counter()
    first_loss = None
    drain_rank = None
    drain_step = None
    seen_anomalies = 0
    for i in range(steps_a):
        state, loss = step(state, at(i))
        if first_loss is None:
            first_loss = float(loss)
        monitor.update(i, guard_report(state))
        if reader is not None:
            reader.update(i, state)
            anomalies = reader.monitor.anomalies
            rank = controller.observe(i, anomalies[seen_anomalies:])
            seen_anomalies = len(anomalies)
            if rank is not None and drain_rank is None:
                drain_rank = rank
                drain_step = i
                controller.drain(i, state, rank)
    if reader is not None and drain_rank is None:
        # Tail window: the last flush may hold the first episode.
        reader.flush(state)
        rank = controller.observe(
            steps_a - 1, reader.monitor.anomalies[seen_anomalies:])
        if rank is not None:
            drain_rank, drain_step = rank, steps_a - 1
            controller.drain(steps_a - 1, state, rank)
    if reader is not None and drain_rank is None:
        print("[chaos_smoke] FAIL: seeded drift on rank "
              f"{doomed} produced no watch drain signal in {steps_a} "
              "steps — the early-warning channel is broken", file=sys.stderr)
        return 1
    if reader is None:
        # No telemetry stream to carry the warning — drain unconditionally
        # so the lifecycle below still runs (documented degraded mode).
        controller.drain(steps_a - 1, state, doomed)
        drain_rank, drain_step = doomed, steps_a - 1
    if drain_rank != doomed and not args.hier:
        print(f"[chaos_smoke] FAIL: drain signal named rank {drain_rank}, "
              f"but rank {doomed} is the one drifting", file=sys.stderr)
        return 1
    guard_a = guard_report(state)
    if guard_a["notfinite_count"] != 0:
        print("[chaos_smoke] FAIL: guard tripped during the drift phase — "
              "the elastic faults are supposed to be guard-invisible",
              file=sys.stderr)
        return 1

    # ---- kill + resize to the survivor world ----------------------------
    survivors = [devices[r] for r in plan.survivors]
    mesh_b = data_parallel_mesh(survivors)
    grc_b, tx_b = build(plan.topology.slice_size)
    state_b, resize_down = controller.resize(
        steps_a, state, tx_b, mesh_a, mesh_b, plan,
        grace=grc_b, params=params)
    print(f"[chaos_smoke] resize: W{plan.old_world} -> W{plan.new_world} "
          f"(lost {list(plan.lost_ranks)}, slice_size "
          f"{plan.topology.slice_size}, footprint_matches "
          f"{resize_down['footprint_matches']})")

    # ---- phase B: survivors keep training -------------------------------
    step_b = make_train_step(loss_fn, tx_b, mesh_b, donate=False,
                             consensus=consensus)
    at_b = batches(plan.new_world)
    loss_b = float("nan")
    for i in range(steps_a, steps_a + steps_b):
        state_b, loss_b = step_b(state_b, at_b(i))
        if reader is not None:
            reader.update(i, state_b)
    if not np.isfinite(float(loss_b)):
        print("[chaos_smoke] FAIL: loss went non-finite at the survivor "
              f"world W{plan.new_world}", file=sys.stderr)
        return 1

    # ---- rejoin at full world behind the consensus barrier --------------
    mesh_c = data_parallel_mesh(devices)
    grc_c, tx_c = build(args.slice_size if args.hier else None)
    grow = plan_resize(world, (), topo)   # no losses: W stays, fresh plan
    state_c, _ = controller.resize(
        steps_a + steps_b, state_b, tx_c, mesh_b, mesh_c,
        dataclasses.replace(grow, old_world=plan.new_world),
        grace=grc_c, params=params)
    # The rejoining rank(s) come back with the state they drained with —
    # restore the last-known-good checkpoint and implant it on exactly
    # the replicas that left, which is what a preempted process restoring
    # from disk looks like to the survivors.
    from grace_tpu.resilience import implant_stale_replica
    stale = ckpt.restore_last_good(state_c)
    for r in plan.lost_ranks:
        state_c = implant_stale_replica(state_c, r, stale.params)
    state_c, barrier = controller.rejoin(steps_a + steps_b, state_c, mesh_c)
    print(f"[chaos_smoke] rejoin: barrier_repairs "
          f"{barrier['barrier_repairs']} | replica_variants "
          f"{barrier['replica_variants']} | divergent rank "
          f"{barrier['last_divergent_rank']} | fingerprint "
          f"{barrier['fingerprint_bytes']} B")
    if barrier["barrier_repairs"] != 1:
        print(f"[chaos_smoke] FAIL: rejoin barrier repaired "
              f"{barrier['barrier_repairs']} times for 1 rejoin event — "
              "repairs must equal rejoins", file=sys.stderr)
        return 1
    if barrier["replica_variants"] != 1:
        print("[chaos_smoke] FAIL: replicas not bit-identical after the "
              "rejoin barrier", file=sys.stderr)
        return 1

    # ---- phase C: full world again, judge the floor ---------------------
    step_c = make_train_step(loss_fn, tx_c, mesh_c, donate=False,
                             consensus=consensus)
    at_c = batches(world)
    loss_c = float("nan")
    for i in range(steps_a + steps_b, steps_a + steps_b + steps_c):
        state_c, loss_c = step_c(state_c, at_c(i))
        monitor.update(i, guard_report(state_c))
        if reader is not None:
            reader.update(i, state_c)
    loss_c = float(loss_c)
    dt = time.perf_counter() - t0
    if reader is not None:
        reader.flush(state_c)
        reader.close()
    ckpt.close()

    fp_down = bool(resize_down["footprint_matches"])
    fp_up = validate_resharded(state_c, grc_c, params, world)["matches"]
    floor_met = np.isfinite(loss_c) and loss_c < args.floor
    print(f"[chaos_smoke] elastic: {steps_a}+{steps_b}+{steps_c} steps in "
          f"{dt:.1f}s | W {plan.old_world}->{plan.new_world}->{world} | "
          f"loss {first_loss:.4f} -> {loss_c:.4f} (floor {args.floor}) | "
          f"drain rank {drain_rank} @ step {drain_step}")

    if args.elastic_out:
        doc = {
            "tool": "chaos_smoke",
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "argv": " ".join(sys.argv[1:]),
            "world_cycle": [plan.old_world, plan.new_world, world],
            "hier": bool(args.hier),
            "slice_size": args.slice_size if args.hier else None,
            "drain": {"rank": drain_rank, "step": drain_step,
                      "episodes": controller.episodes.get(drain_rank, 0)},
            "resize_events": controller.events,
            "rejoin": {"rejoins": 1, **{
                k: int(barrier[k]) for k in
                ("barrier_repairs", "repairs", "audits", "replica_variants",
                 "last_divergent_rank", "fingerprint_bytes",
                 "repair_bytes")}},
            "floor": {"first_loss": first_loss, "final_loss": loss_c,
                      "floor": args.floor, "met": bool(floor_met)},
            "footprint": {str(plan.new_world): fp_down, str(world): fp_up},
        }
        _write_evidence_doc(doc, args.elastic_out,
                            ledger_id="elastic-drill",
                            metric="elastic_floor_met",
                            value=bool(floor_met), world=world,
                            slice_size=(args.slice_size if args.hier
                                        else None),
                            label="elastic evidence")

    if not np.isfinite(loss_c):
        print("[chaos_smoke] FAIL: final loss non-finite after the rejoin",
              file=sys.stderr)
        return 1
    if not floor_met:
        print(f"[chaos_smoke] FAIL: final loss {loss_c:.4f} misses the "
              f"convergence floor {args.floor}", file=sys.stderr)
        return 1
    if not (fp_down and fp_up):
        print("[chaos_smoke] FAIL: re-sharded state does not match the "
              "static footprint model", file=sys.stderr)
        return 1
    print("[chaos_smoke] OK")
    return 0


def _region_main(args) -> int:
    """The --region lifecycle: drift inside one region → region-wide watch
    signal → ONE drain → whole-region kill (R→R−1, topology collapses to
    two-tier) → W−rz resume → region rejoin at W behind the consensus
    barrier. Returns 0 only when every acceptance fact holds (see module
    docstring)."""
    import dataclasses
    import json
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.checkpoint import Checkpointer
    from grace_tpu.core import Topology
    from grace_tpu.resilience import (ChaosCompressor, ConsensusConfig,
                                      ElasticController, guarded_chain,
                                      plan_resize, validate_resharded)
    from grace_tpu.telemetry import JSONLSink, TelemetryReader
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.utils.logging import GuardMonitor, run_provenance
    from grace_tpu.utils.metrics import guard_report
    from grace_tpu.models import lenet
    from grace_tpu.parallel import data_parallel_mesh

    devices = jax.devices()
    world = len(devices)
    rz = args.region_size
    if world % rz or rz < 2:
        print(f"[chaos_smoke] --region: world {world} is not a multiple of "
              f"region_size {rz} (>= 2 required)", file=sys.stderr)
        return 1
    if world // rz < 2:
        print(f"[chaos_smoke] --region: need >= 2 regions; world {world} "
              f"/ region_size {rz} leaves {world // rz}", file=sys.stderr)
        return 1
    # Slices half a region wide: every run exercises intra-slice ICI hops,
    # same-region cross-slice DCN gathers AND cross-region WAN gathers.
    s = max(1, rz // 2)
    topo3 = Topology(slice_size=s, region_size=rz)
    doomed_region = world // rz - 1              # the last region dies
    lost = tuple(range(doomed_region * rz, (doomed_region + 1) * rz))
    # One drifting rank per slice of the doomed region — enough for the
    # 0.5 region quorum, few enough (2 of 8) that the fleet median the
    # watch skew detector references stays healthy.
    drift_ranks = tuple(doomed_region * rz + k * s
                        for k in range(rz // s))
    plan = plan_resize(world, lost, topo3)

    steps_a = max(args.steps // 3, 2 * args.watch_window)
    steps_b = max(args.steps // 4, 4)
    steps_c = max(args.steps - steps_a - steps_b, 4)
    consensus = ConsensusConfig(audit_every=args.audit_every)

    def build(slice_size, region_size, drift=()):
        """(grace, guarded tx) for one phase; rebuilding the transform is
        the resize's single topology-invalidation point. slice/region
        sizes come from the surviving Topology — the whole-region kill
        hands back (slice_size, None) and the rejoin restores both."""
        p = {"compressor": "topk", "compress_ratio": 0.3,
             "memory": "residual", "communicator": "hier",
             "fusion": "flat", "escape": "fp16", "consensus": consensus,
             "telemetry": max(2 * args.telemetry_every, 16),
             "watch": {"window": args.watch_window,
                       "capacity": max(2 * args.telemetry_every
                                       // args.watch_window, 8)}}
        if slice_size:
            p["slice_size"] = slice_size
        if region_size:
            p["region_size"] = region_size
        grc = grace_from_params(p)
        for dr in drift:
            grc = dataclasses.replace(grc, compressor=ChaosCompressor(
                inner=grc.compressor, drift_scale=args.drift_scale,
                rank=dr, seed=args.seed + 3 + dr))
        tx = guarded_chain(grc, optax.sgd(args.lr),
                           fallback_after=args.fallback_after,
                           fallback_steps=args.fallback_steps)
        return grc, tx

    def batches(w):
        b = max(args.batch, w) // w * w
        rng = np.random.default_rng(args.seed)
        images = rng.normal(size=(4 * args.batch, 28, 28, 1)).astype(
            np.float32)
        labels = rng.integers(0, 10,
                              size=(4 * args.batch,)).astype(np.int32)

        def at(i):
            lo = (i * b) % (len(images) - b + 1)
            return (jnp.asarray(images[lo:lo + b]),
                    jnp.asarray(labels[lo:lo + b]))
        return at

    def loss_fn(params, b):
        x, y = b
        logits, _ = lenet.apply(params, {}, x)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    sink = None
    reader = None
    if args.telemetry_out:
        prov = run_provenance(
            data="synthetic", tool="chaos_smoke",
            argv=" ".join(sys.argv[1:]), steps=args.steps,
            region=True, region_size=rz, slice_size=s)
        sink = JSONLSink(args.telemetry_out, provenance=prov)
        sink, _ = _incident_sink(sink, args, prov, "region")
        reader = TelemetryReader(sink, every=args.telemetry_every,
                                 anomaly=True)
    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="grace_region_")
    ckpt = Checkpointer(ckpt_dir, max_to_keep=2)
    controller = ElasticController(
        consensus=consensus, checkpointer=ckpt, sink=sink,
        anomaly_threshold=1, topology=topo3, region_quorum=0.5,
        drain_timeout_s=args.drain_timeout or None, drain_retries=1)
    monitor = GuardMonitor(sink=sink)

    # ---- phase A: full world, one rank per slice of region R-1 drifting --
    mesh_a = data_parallel_mesh(devices)
    grc_a, tx_a = build(s, rz, drift=drift_ranks)
    params, _ = lenet.init(jax.random.key(args.seed))
    state = init_train_state(params, tx_a, mesh_a)
    step = make_train_step(loss_fn, tx_a, mesh_a, donate=False,
                           consensus=consensus)
    at = batches(world)
    t0 = time.perf_counter()
    first_loss = None
    drain_rank = None
    drain_step = None
    drain_scope = ()
    seen_anomalies = 0

    def try_drain(i, state):
        """Widen each flagged rank to its region scope; drain only when
        the episode is region-wide — the ONE-transition contract."""
        nonlocal drain_rank, drain_step, drain_scope
        for r in sorted(controller.episodes):
            scope = controller.region_scope(r)
            if len(scope) > 1:
                controller.drain(i, state, r, scope=scope)
                drain_rank, drain_step, drain_scope = r, i, scope
                return True
        return False

    for i in range(steps_a):
        state, loss = step(state, at(i))
        if first_loss is None:
            first_loss = float(loss)
        monitor.update(i, guard_report(state))
        if reader is not None and drain_rank is None:
            reader.update(i, state)
            anomalies = reader.monitor.anomalies
            # Feed one record at a time: observe() returns early at a
            # threshold crossing and would drop the rest of the batch.
            for a in anomalies[seen_anomalies:]:
                controller.observe(i, [a])
            seen_anomalies = len(anomalies)
            if try_drain(i, state):
                break
    if reader is not None and drain_rank is None:
        reader.flush(state)
        for a in reader.monitor.anomalies[seen_anomalies:]:
            controller.observe(steps_a - 1, [a])
        try_drain(steps_a - 1, state)
    if reader is not None and drain_rank is None:
        print(f"[chaos_smoke] FAIL: seeded drift on ranks "
              f"{list(drift_ranks)} produced no region-wide drain signal "
              f"in {steps_a} steps (episodes: {controller.episodes}) — "
              "the early-warning channel is broken", file=sys.stderr)
        return 1
    if reader is None:
        controller.episodes.update({r: 1 for r in drift_ranks})
        controller.drain(steps_a - 1, state, drift_ranks[0],
                         scope=controller.region_scope(drift_ranks[0]))
        drain_rank, drain_step = drift_ranks[0], steps_a - 1
        drain_scope = controller.region_scope(drift_ranks[0])
    if tuple(sorted(drain_scope)) != lost:
        print(f"[chaos_smoke] FAIL: drain scope {sorted(drain_scope)} is "
              f"not the doomed region {list(lost)}", file=sys.stderr)
        return 1
    drain_events = [e for e in controller.events
                    if e["event"] == "elastic_drain"]
    if len(drain_events) != 1:
        print(f"[chaos_smoke] FAIL: {len(drain_events)} drain transitions "
              "for ONE region-wide episode", file=sys.stderr)
        return 1
    guard_a = guard_report(state)
    if guard_a["notfinite_count"] != 0:
        print("[chaos_smoke] FAIL: guard tripped during the drift phase — "
              "the region faults are supposed to be guard-invisible",
              file=sys.stderr)
        return 1

    # ---- kill the whole region, resize to the survivor world ------------
    if not plan.whole_regions or plan.topology.region_size is not None:
        print(f"[chaos_smoke] FAIL: plan {plan} did not recognize the "
              "whole-region loss / single-region collapse", file=sys.stderr)
        return 1
    survivors = [devices[r] for r in plan.survivors]
    mesh_b = data_parallel_mesh(survivors)
    grc_b, tx_b = build(plan.topology.slice_size,
                        plan.topology.region_size)
    state_b, resize_down = controller.resize(
        drain_step, state, tx_b, mesh_a, mesh_b, plan,
        grace=grc_b, params=params)
    print(f"[chaos_smoke] resize: W{plan.old_world} -> W{plan.new_world} "
          f"(lost region {doomed_region}: ranks {list(plan.lost_ranks)}; "
          f"topology -> slice_size {plan.topology.slice_size}, "
          f"region_size {plan.topology.region_size}; whole_regions "
          f"{plan.whole_regions}; footprint_matches "
          f"{resize_down['footprint_matches']})")

    # ---- phase B: the surviving region keeps training --------------------
    step_b = make_train_step(loss_fn, tx_b, mesh_b, donate=False,
                             consensus=consensus)
    at_b = batches(plan.new_world)
    loss_b = float("nan")
    for i in range(steps_a, steps_a + steps_b):
        state_b, loss_b = step_b(state_b, at_b(i))
        if reader is not None:
            reader.update(i, state_b)
    if not np.isfinite(float(loss_b)):
        print("[chaos_smoke] FAIL: loss went non-finite at the survivor "
              f"world W{plan.new_world}", file=sys.stderr)
        return 1

    # ---- region rejoin at full world behind the consensus barrier --------
    mesh_c = data_parallel_mesh(devices)
    grc_c, tx_c = build(s, rz)
    grow = plan_resize(world, (), topo3)   # no losses: fresh 3-tier plan
    state_c, _ = controller.resize(
        steps_a + steps_b, state_b, tx_c, mesh_b, mesh_c,
        dataclasses.replace(grow, old_world=plan.new_world),
        grace=grc_c, params=params)
    from grace_tpu.resilience import implant_stale_replica
    stale = ckpt.restore_last_good(state_c)
    for r in plan.lost_ranks:
        state_c = implant_stale_replica(state_c, r, stale.params)
    state_c, barrier = controller.rejoin(steps_a + steps_b, state_c,
                                         mesh_c)
    print(f"[chaos_smoke] rejoin: barrier_repairs "
          f"{barrier['barrier_repairs']} | replica_variants "
          f"{barrier['replica_variants']} | fingerprint "
          f"{barrier['fingerprint_bytes']} B")
    # ONE region rejoin == ONE barrier repair event (the forced audit's
    # masked broadcast repairs every stale replica of the region at once
    # — region-granular, exactly like the drain).
    if barrier["barrier_repairs"] != 1:
        print(f"[chaos_smoke] FAIL: rejoin barrier repaired "
              f"{barrier['barrier_repairs']} times for 1 region rejoin — "
              "repairs must equal rejoins", file=sys.stderr)
        return 1
    if barrier["replica_variants"] != 1:
        print("[chaos_smoke] FAIL: replicas not bit-identical after the "
              "rejoin barrier", file=sys.stderr)
        return 1

    # ---- phase C: full three-tier world again, judge the floor -----------
    step_c = make_train_step(loss_fn, tx_c, mesh_c, donate=False,
                             consensus=consensus)
    at_c = batches(world)
    loss_c = float("nan")
    for i in range(steps_a + steps_b, steps_a + steps_b + steps_c):
        state_c, loss_c = step_c(state_c, at_c(i))
        monitor.update(i, guard_report(state_c))
        if reader is not None:
            reader.update(i, state_c)
    loss_c = float(loss_c)
    dt = time.perf_counter() - t0
    if reader is not None:
        reader.flush(state_c)
        reader.close()
    ckpt.close()

    fp_down = bool(resize_down["footprint_matches"])
    fp_up = validate_resharded(state_c, grc_c, params, world)["matches"]
    floor_met = np.isfinite(loss_c) and loss_c < args.floor
    timeouts = sum(e.get("drain_timeouts", 0) for e in drain_events)
    print(f"[chaos_smoke] region: {steps_a}+{steps_b}+{steps_c} steps in "
          f"{dt:.1f}s | W {plan.old_world}->{plan.new_world}->{world} | "
          f"loss {first_loss:.4f} -> {loss_c:.4f} (floor {args.floor}) | "
          f"drain scope {list(drain_scope)} @ step {drain_step}")

    if args.region_out:
        doc = {
            "tool": "chaos_smoke",
            "captured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "argv": " ".join(sys.argv[1:]),
            "world_cycle": [plan.old_world, plan.new_world, world],
            "slice_size": s,
            "region_size": rz,
            "regions": world // rz,
            "drift_ranks": list(drift_ranks),
            "drain": {"rank": drain_rank, "step": drain_step,
                      "scope": list(drain_scope),
                      "region_wide": len(drain_scope) == rz,
                      "transitions": len(drain_events),
                      "drain_timeouts": timeouts,
                      "episodes": dict(sorted(
                          (str(k), v)
                          for k, v in controller.episodes.items()))},
            "resize_events": controller.events,
            "rejoin": {"rejoins": 1, "rejoined_ranks": len(lost), **{
                k: int(barrier[k]) for k in
                ("barrier_repairs", "repairs", "audits",
                 "replica_variants", "last_divergent_rank",
                 "fingerprint_bytes", "repair_bytes")}},
            "floor": {"first_loss": first_loss, "final_loss": loss_c,
                      "floor": args.floor, "met": bool(floor_met)},
            "footprint": {str(plan.new_world): fp_down,
                          str(world): fp_up},
            "guard_silent": guard_a["notfinite_count"] == 0,
        }
        _write_evidence_doc(doc, args.region_out,
                            ledger_id="region-drill",
                            metric="region_floor_met",
                            value=bool(floor_met), world=world,
                            slice_size=s, region_size=rz,
                            label="region evidence")

    if not np.isfinite(loss_c):
        print("[chaos_smoke] FAIL: final loss non-finite after the rejoin",
              file=sys.stderr)
        return 1
    if not floor_met:
        print(f"[chaos_smoke] FAIL: final loss {loss_c:.4f} misses the "
              f"convergence floor {args.floor}", file=sys.stderr)
        return 1
    if not (fp_down and fp_up):
        print("[chaos_smoke] FAIL: re-sharded state does not match the "
              "static footprint model", file=sys.stderr)
        return 1
    print("[chaos_smoke] OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
