#!/bin/bash
# TPU tunnel watcher (round-2 postmortem: the tunnel to the single real chip
# goes down for hours at a stretch — backend init hangs rather than erroring).
#
# LAUNCH THIS FIRST THING IN A ROUND. Observed pattern (rounds 3 AND 4):
# the tunnel is alive at round start (the driver just ran benches on it)
# and dies within ~30 min, then stays dead for many hours (round 4:
# alive 18:44-19:13, dead for the following 10+ h). The first half hour
# of a round is most of the chip time you will get.
# Probe on a schedule; on first success run the headline dense-vs-compressed
# pair, then the full per-algorithm sweep, directly in TPU worker mode.
# Evidence lands incrementally in BENCH_TPU_LAST.json / BENCH_ALL_TPU_LAST.json
# (written row-by-row by the workers), so even a mid-run tunnel death keeps
# every measured config.
#
# Usage: setsid nohup tools/tpu_watch.sh &   (log: tpu_watch.log at repo root)
cd "$(dirname "$0")/.." || exit 1
LOG=tpu_watch.log
BENCH_ATTEMPTS=0
ORIG_GDP="${GRACE_DISABLE_PALLAS:-}"
ORIG_GDPQ="${GRACE_DISABLE_PALLAS_QUANT:-}"
# Sweep rows measured at/after this moment may be resumed by retry
# attempts instead of re-measured (see the bench_all invocation below).
export GRACE_BENCH_RESUME_SINCE="$(date -u +%s)"
# Single instance via flock (stop with: tools/tpu_watch.sh stop).
# pkill -f tpu_watch matches the *caller's own shell* when the launch
# command line contains the script path — that footgun killed two watcher
# restarts in a row. The lock (held for the process lifetime) is atomic —
# no check-then-write race between two near-simultaneous launches, and no
# stale-PID ambiguity after a SIGKILL: the kernel drops the lock with the
# process.
PIDFILE=/tmp/tpu_watch.pid
if [ "${1:-}" = "stop" ]; then
  # Identity-checked stop: never signal a recycled PID, and TERM (not
  # KILL) so the trap kills the in-flight bench child and resumes any
  # paused CPU jobs instead of leaving an orphan burning the chip.
  pid=$(cat "$PIDFILE" 2>/dev/null) || { echo "no pidfile"; exit 1; }
  if grep -qa "tools/tpu_watch.sh" "/proc/$pid/cmdline" 2>/dev/null; then
    kill "$pid" && echo "stopped watcher $pid"
  else
    echo "pid $pid is not a watcher (stale pidfile?)"; exit 1
  fi
  exit 0
fi
exec 9>"$PIDFILE.lock"
if ! flock -n 9; then
  echo "=== $(date -u +%FT%TZ) another watcher holds the lock — exiting" \
       >> "$LOG"
  exit 0
fi
echo $$ > "$PIDFILE"
# Kill the in-flight measurement child on TERM/INT so stopping the watcher
# cannot orphan a bench run that keeps the chip busy while the EXIT trap
# resumes CPU jobs into contention with it.
CHILD=
on_term() { [ -n "$CHILD" ] && kill "$CHILD" 2>/dev/null; exit 143; }
trap on_term TERM INT
# Artifact-freshness skips: a stage whose evidence already landed THIS
# watcher run (mtime >= GRACE_BENCH_RESUME_SINCE) is not re-measured by
# retry attempts — without this, a micro-only failure would re-burn the
# ~30 min headline and up to 60 min bert stages on every one of the 5
# attempts just to reach the failing extra again.
fresh_file() {
  [ -f "$1" ] && [ "$(stat -c %Y "$1")" -ge "$GRACE_BENCH_RESUME_SINCE" ]
}
fresh_complete() {  # JSON evidence file: fresh AND not a partial capture
  fresh_file "$1" && grep -q '"partial": false' "$1"
}
run_py() {  # run_py <timeout> <args...>: killable python step
  # 9>&- : children must NOT inherit the flock fd — an orphaned probe
  # once held the lock after its watcher died and blocked every restart.
  timeout "$@" >> "$LOG" 2>&1 9>&- &
  CHILD=$!
  wait "$CHILD"
  local rc=$?
  CHILD=
  return $rc
}

# The host has one core: pause any long-running CPU-mesh training
# (tools/cifar_runs.sh) for the duration of a TPU measurement so host
# contention cannot leak into the fetch-bounded timing windows.
# Identity check before signalling: the pgid file can go stale (SIGKILL/
# OOM skips cifar_runs.sh's EXIT trap) and the kernel recycles pgids — an
# unverified kill -STOP could freeze an unrelated process group for the
# length of a 2.5h sweep.
cifar_pgid() {
  local pgid
  [ -f /tmp/cifar_runs.pgid ] || return 1
  pgid=$(cat /tmp/cifar_runs.pgid) || return 1
  grep -qa cifar_runs "/proc/$pgid/cmdline" 2>/dev/null || return 1
  echo "$pgid"
}
pause_cpu_jobs() {
  local pgid
  pgid=$(cifar_pgid) && kill -STOP -"$pgid" 2>/dev/null \
    && echo "=== paused cifar_runs" >> "$LOG"
}
resume_cpu_jobs() {
  local pgid
  pgid=$(cifar_pgid) && kill -CONT -"$pgid" 2>/dev/null \
    && echo "=== resumed cifar_runs" >> "$LOG"
}
# Remove the pidfile only if it is still OURS: a dying watcher's trap must
# not delete the pidfile a just-started successor wrote (observed race).
trap 'resume_cpu_jobs;
      [ "$(cat "$PIDFILE" 2>/dev/null)" = "$$" ] && rm -f "$PIDFILE"' EXIT
MAX_BENCH_ATTEMPTS=5   # cap: a deterministic bench bug must not re-burn the
                       # shared chip for hours per loop iteration forever
while true; do
  echo "=== $(date -u +%FT%TZ) probing" >> "$LOG"
  if run_py 300 python -c \
      "import jax; d=jax.devices(); assert d[0].platform=='tpu', d"; then
    BENCH_ATTEMPTS=$((BENCH_ATTEMPTS + 1))
    echo "=== $(date -u +%FT%TZ) tunnel ALIVE — headline bench" \
         "(attempt $BENCH_ATTEMPTS/$MAX_BENCH_ATTEMPTS)" >> "$LOG"
    # Pre-flight the Pallas kernel that sits on the headline path; a Mosaic
    # compile failure on the real chip must degrade to the staged XLA path,
    # not crash every bench attempt. CPU jobs are paused FIRST so one-core
    # host contention cannot time out the smoke and falsely disable the
    # kernel. An operator-set GRACE_DISABLE_PALLAS from the launch
    # environment is preserved either way (ORIG_GDP).
    pause_cpu_jobs
    run_py 420 python tools/pallas_smoke.py
    smoke_rc=$?
    # Restore operator-set values first, then layer the smoke verdict on
    # top. rc=3 means the topk kernels (the headline path) are fine and
    # only the quant kernel must degrade — a quant Mosaic failure used to
    # disable ALL kernels, silently benching the staged topk path.
    if [ -n "$ORIG_GDP" ]; then
      export GRACE_DISABLE_PALLAS="$ORIG_GDP"
    else
      unset GRACE_DISABLE_PALLAS
    fi
    if [ -n "$ORIG_GDPQ" ]; then
      export GRACE_DISABLE_PALLAS_QUANT="$ORIG_GDPQ"
    else
      unset GRACE_DISABLE_PALLAS_QUANT
    fi
    if [ "$smoke_rc" -eq 3 ]; then
      export GRACE_DISABLE_PALLAS_QUANT=1
      echo "=== $(date -u +%FT%TZ) pallas QUANT smoke failed — benching" \
           "with GRACE_DISABLE_PALLAS_QUANT=1 (topk kernels stay on)" \
           >> "$LOG"
    elif [ "$smoke_rc" -ne 0 ]; then
      export GRACE_DISABLE_PALLAS=1
      echo "=== $(date -u +%FT%TZ) pallas smoke FAILED (rc=$smoke_rc) —" \
           "benching with GRACE_DISABLE_PALLAS=1" >> "$LOG"
    fi
    if fresh_complete BENCH_TPU_LAST.json; then
      rc1=0
      echo "=== headline: fresh complete artifact from an earlier attempt" \
           "this run — skipping" >> "$LOG"
    else
      run_py 1800 python bench.py --_worker tpu
      rc1=$?
      echo "=== headline rc=$rc1" >> "$LOG"
    fi
    rc2=1
    rc3=1
    rcm=1
    if [ "$rc1" -eq 0 ]; then
      # Round-5: micro breakdown moved UP, right after the headline —
      # round 4 gated it behind full-sweep success and the tunnel died
      # mid-sweep, so it never produced an artifact (VERDICT r4 item 2:
      # the ~9 ms overhead and 0.16 dense MFU are unexplained). Skip if
      # the artifact already landed this watcher run (retry attempts must
      # not re-burn ~20 min of chip re-measuring it).
      if fresh_file TPU_MICRO.txt; then
        rcm=0   # fresh artifact from an earlier attempt this run
      else
        echo "=== $(date -u +%FT%TZ) per-stage micro breakdown" >> "$LOG"
        run_py 2400 python tools/tpu_micro.py --out TPU_MICRO.txt
        rcm=$?
        echo "=== micro rc=$rcm" >> "$LOG"
      fi
      # Headline failure usually means the tunnel died again — skip the
      # 2.5h sweep in that case and go straight back to probing.
      echo "=== $(date -u +%FT%TZ) per-algorithm sweep" >> "$LOG"
      # 12000s: the sweep grew the bs-sweep + qsgd_pallas rows (round 4)
      # and each row now brackets itself with interleaved dense samples.
      # Retry attempts resume: rows persisted by an earlier attempt are
      # re-emitted, not re-measured (a hung remote compile once burned 9
      # already-measured rows). GRACE_BENCH_RESUME_SINCE (stamped at
      # script start, before the single-instance lock; a losing
      # invocation exits without using it) lets bench_all reject
      # evidence files older than this watcher run, so a stale sweep
      # can never replay as fresh; GRACE_BENCH_RESUME remains the
      # operator's explicit this-file-is-fresh override.
      # 18000s outer leash — in --_worker mode this IS the only bound on
      # a hung sweep (bench_all's WORKER_TIMEOUT_S applies to its
      # orchestrate() subprocess path, not --_worker; the per-config
      # try/except catches exceptions, not hangs). Sized above
      # 600s x 26 configs (round-5 list) so a merely slow sweep is never
      # cut short.
      run_py 18000 python bench_all.py --_worker tpu
      rc2=$?
      echo "=== sweep rc=$rc2" >> "$LOG"
      if fresh_complete BENCH_BERT_TPU_LAST.json; then
        rc3=0
        echo "=== bert: fresh complete artifact from an earlier attempt" \
             "this run — skipping" >> "$LOG"
      else
        echo "=== $(date -u +%FT%TZ) bert/powersgd bench" >> "$LOG"
        run_py 3600 python tools/tpu_bert_bench.py --platform tpu
        rc3=$?
        echo "=== bert rc=$rc3" >> "$LOG"
      fi
      # Best-effort extras: a failure here logs but does NOT block
      # retirement or trigger a whole-chain retry (a deterministic bug
      # in an extra must not re-burn the chip for 5 full attempts).
      # Only on the retiring attempt (sweep + bert both succeeded):
      # retry loops must re-probe the failing stage promptly, not burn
      # up to ~100 min of chip per attempt on extras that would be
      # overwritten next attempt anyway.
      if [ "$rc2" -eq 0 ] && [ "$rc3" -eq 0 ] && [ "$rcm" -eq 0 ]; then
      echo "=== $(date -u +%FT%TZ) torch interop bucket A/B" >> "$LOG"
      run_py 1800 sh -c 'python examples/torch_synthetic_benchmark.py \
        --compressor topk --compress-ratio 0.01 --memory residual \
        --num-iters 5 --bucket-cap-mb 32 \
        > TORCH_INTEROP_TPU_bucketed.txt 2>&1'
      rcb=$?
      run_py 1800 sh -c 'python examples/torch_synthetic_benchmark.py \
        --compressor topk --compress-ratio 0.01 --memory residual \
        --num-iters 5 --bucket-cap-mb 0 \
        > TORCH_INTEROP_TPU_single.txt 2>&1'
      echo "=== interop rc=$rcb/$?" >> "$LOG"
      fi
    fi
    resume_cpu_jobs
    # Only retire the watcher once ALL measurements actually landed —
    # a tunnel that dies mid-bench must put us back into the probe loop
    # (partial rows are already persisted by the workers either way).
    # rcm (micro breakdown) is part of the gate since round 5: round 4
    # retired without the TPU_MICRO.txt artifact and VERDICT item 2 had
    # nothing to cite; MAX_BENCH_ATTEMPTS still caps a deterministic
    # micro bug at 5 attempts.
    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ] && [ "$rc3" -eq 0 ] \
       && [ "$rcm" -eq 0 ]; then
      echo "=== $(date -u +%FT%TZ) both benches complete — watcher done" \
        >> "$LOG"
      break
    fi
    if [ "$BENCH_ATTEMPTS" -ge "$MAX_BENCH_ATTEMPTS" ]; then
      echo "=== $(date -u +%FT%TZ) bench attempt cap reached — watcher" \
           "stopping with partial evidence" >> "$LOG"
      break
    fi
    echo "=== $(date -u +%FT%TZ) bench(es) failed, sleeping 240s before" \
         "re-probe" >> "$LOG"
  else
    echo "=== $(date -u +%FT%TZ) tunnel dead, sleeping 240s" >> "$LOG"
  fi
  sleep 240 9>&- &  # background + wait: the TERM trap fires immediately
  wait $!         # instead of after up to 10 min of foreground sleep
done
