"""Microbenchmark the Top-K pipeline pieces on the chip.

The headline gap (chunk Top-K 0.55x dense, BENCH_TPU_LAST.json 2026-07-31)
is ~10 ms/step of overhead on a 25.5M-element fused gradient. This times
each stage of the GRACE pipeline in isolation so the fix targets the
measured hot spot instead of a guess.

Method: repetition runs ON DEVICE via lax.fori_loop with a data-dependent
carry, one dispatch per measurement — a Python-loop-of-dispatches floors
every op at the tunnel's ~5 ms per-dispatch overhead and reads pure noise
(first version of this tool did exactly that: an elementwise add "measured"
5.5 ms). The carry feeds each iteration's input so XLA cannot hoist the
body out of the loop; the reported per-iter time includes one carry add
(~0.1 ms), negligible against the ops under test.

Usage (on the chip): python tools/tpu_micro.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = 25_557_032          # ResNet-50 fused gradient element count
K = N // 100
ITERS = 20


def timed(name, make_body, *args):
    """make_body(carry, *args) -> new carry (same shape/dtype as carry)."""
    import jax
    from jax import lax

    @jax.jit
    def run(c0, *a):
        def body(i, c):
            # i-dependent perturbation pins the body inside the loop.
            return make_body(c + i * 1e-12, *a)
        return lax.fori_loop(0, ITERS, body, c0)

    c0 = args[0] * 0.0 + 1.0 if False else None  # placeholder, unused
    import jax.numpy as jnp
    c0 = jnp.zeros((N,), jnp.float32)
    out = run(c0, *args)
    out.block_until_ready()
    float(out[0])
    t0 = time.perf_counter()
    out = run(c0, *args)
    float(out[0])
    dt = (time.perf_counter() - t0) / ITERS
    print(f"{name:34s} {dt*1e3:8.3f} ms/iter", flush=True)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    assert jax.devices()[0].platform == "tpu"
    flat = jax.random.normal(jax.random.key(0), (N,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (N,), jnp.float32)
    k = K
    rows = -(-N // k)
    idx0 = jnp.arange(k, dtype=jnp.int32) * rows  # spread, in-range indices
    vals0 = jnp.ones((k,), jnp.float32)
    wr0 = jnp.zeros((k,), jnp.int32)

    print(f"n={N} k={k} rows={rows} iters={ITERS}", flush=True)

    timed("carry add only (baseline)", lambda c: c)
    timed("elementwise add", lambda c: c + resid)
    timed("abs+pad+argmax (chunk select)", lambda c: c.at[0].add(
        jnp.argmax(jnp.full((rows * k,), -1.0, c.dtype).at[:N]
                   .set(jnp.abs(c)).reshape(rows, k), axis=0)
        .astype(c.dtype).sum() * 1e-20))
    timed("approx_max_k", lambda c: c.at[0].add(
        lax.approx_max_k(jnp.abs(c), k, recall_target=0.95)[0].sum() * 1e-20))
    timed("gather flat[idx] (k)", lambda c: c.at[0].add(
        c[idx0].sum() * 1e-20))
    timed("scatter k into n", lambda c:
          jnp.zeros((N,), c.dtype).at[idx0].set(c[:k] * 0 + vals0) + c * 1e-20)
    timed("one-hot k into n", lambda c:
          jnp.where(jnp.arange(rows, dtype=jnp.int32)[:, None]
                    == (wr0 + c[0].astype(jnp.int32) * 0)[None, :],
                    vals0[None, :], 0.0).reshape(-1)[:N] + c * 1e-20)

    def full_pipeline(c):
        comp = c + resid
        body = jnp.full((rows * k,), -1.0, comp.dtype)
        body = body.at[:N].set(jnp.abs(comp)).reshape(rows, k)
        win_row = jnp.argmax(body, axis=0).astype(jnp.int32)
        idx = win_row * k + jnp.arange(k, dtype=jnp.int32)
        vals = comp[idx]
        mask = jnp.arange(rows, dtype=jnp.int32)[:, None] == win_row[None, :]
        dense = jnp.where(mask, vals[None, :], 0.0).reshape(-1)[:N]
        return comp - dense          # new residual: the carried state

    timed("full chunk pipeline", full_pipeline)

    def gatherfree_pipeline(c):
        comp = c + resid
        sbody = jnp.zeros((rows * k,), comp.dtype).at[:N].set(comp)
        sbody = sbody.reshape(rows, k)
        win_row = jnp.argmax(jnp.abs(sbody).at[-1].add(-1e-9), axis=0)
        mask = (jnp.arange(rows, dtype=jnp.int32)[:, None]
                == win_row.astype(jnp.int32)[None, :])
        dense = jnp.where(mask, sbody, 0.0)
        vals = jnp.sum(dense, axis=0)             # wire values, gather-free
        return comp - (dense.reshape(-1)[:N] + vals[0] * 1e-20)

    timed("gather-free chunk pipeline", gatherfree_pipeline)


if __name__ == "__main__":
    main()
