"""Microbenchmark the Top-K pipeline pieces on the chip.

The headline gap (chunk Top-K 0.55x dense, BENCH_TPU_LAST.json 2026-07-31)
is ~10 ms/step of overhead on a 25.5M-element fused gradient. This times
each stage of the GRACE pipeline in isolation so the fix targets the
measured hot spot instead of a guess.

Method: repetition runs ON DEVICE via lax.fori_loop with a data-dependent
carry, one dispatch per measurement — a Python-loop-of-dispatches floors
every op at the tunnel's ~5 ms per-dispatch overhead and reads pure noise
(first version of this tool did exactly that: an elementwise add "measured"
5.5 ms). The carry feeds each iteration's input so XLA cannot hoist the
body out of the loop; the reported per-iter time includes one carry add
(~0.1 ms), negligible against the ops under test.

Usage (on the chip): python tools/tpu_micro.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = 25_557_032          # ResNet-50 fused gradient element count
K = N // 100
ITERS = 20
ALLOW_CPU = False       # --allow-cpu: script self-test off-chip (tiny N)


OUT_PATH = None         # --out: mirror every result line to this file


def _report(line: str) -> None:
    print(line, flush=True)
    if OUT_PATH:
        # Lines accumulate in a .tmp sibling; __main__ os.replace()s it
        # over OUT_PATH only after a COMPLETE run, so a crashed/timed-out
        # run can neither clobber the previous complete breakdown nor
        # leave a fresh-stamped partial that reads as authoritative.
        with open(OUT_PATH + ".tmp", "a") as f:
            f.write(line + "\n")


def timed(name, make_body, *args, carry0=None):
    """make_body(carry, *args) -> new carry (same shape/dtype as carry)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def run(c0, *a):
        def body(i, c):
            # i-dependent perturbation pins the body inside the loop
            # (float leaves only: int leaves like a step counter must keep
            # their dtype or the fori_loop carry type check fails).
            def pin(x):
                if jnp.issubdtype(jnp.result_type(x), jnp.floating):
                    return x + i * 1e-12
                return x
            return jax.tree.map(pin, make_body(c, *a))
        return lax.fori_loop(0, ITERS, body, c0)

    c0 = jnp.zeros((N,), jnp.float32) if carry0 is None else carry0
    out = run(c0, *args)
    first = jax.tree.leaves(out)[0]
    float(first.reshape(-1)[0])
    t0 = time.perf_counter()
    out = run(c0, *args)
    float(jax.tree.leaves(out)[0].reshape(-1)[0])
    dt = (time.perf_counter() - t0) / ITERS
    _report(f"{name:34s} {dt*1e3:8.3f} ms/iter")


def main() -> None:
    import jax

    if ALLOW_CPU:
        # The dev image's sitecustomize imports jax at interpreter start
        # and pins the axon (real-TPU-tunnel) platform, so JAX_PLATFORMS
        # in the environment is too late — a CPU self-test would silently
        # grab the one real chip and contend with any in-flight bench.
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    if not ALLOW_CPU:
        assert jax.devices()[0].platform == "tpu"
    flat = jax.random.normal(jax.random.key(0), (N,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (N,), jnp.float32)
    k = K
    rows = -(-N // k)
    idx0 = jnp.arange(k, dtype=jnp.int32) * rows  # spread, in-range indices
    vals0 = jnp.ones((k,), jnp.float32)
    wr0 = jnp.zeros((k,), jnp.int32)

    _report(f"n={N} k={k} rows={rows} iters={ITERS}")

    timed("carry add only (baseline)", lambda c: c)
    timed("elementwise add", lambda c: c + resid)
    timed("abs+pad+argmax (chunk select)", lambda c: c.at[0].add(
        jnp.argmax(jnp.full((rows * k,), -1.0, c.dtype).at[:N]
                   .set(jnp.abs(c)).reshape(rows, k), axis=0)
        .astype(c.dtype).sum() * 1e-20))
    timed("approx_max_k", lambda c: c.at[0].add(
        lax.approx_max_k(jnp.abs(c), k, recall_target=0.95)[0].sum() * 1e-20))
    timed("gather flat[idx] (k)", lambda c: c.at[0].add(
        c[idx0].sum() * 1e-20))
    timed("scatter k into n", lambda c:
          jnp.zeros((N,), c.dtype).at[idx0].set(c[:k] * 0 + vals0) + c * 1e-20)
    timed("one-hot k into n", lambda c:
          jnp.where(jnp.arange(rows, dtype=jnp.int32)[:, None]
                    == (wr0 + c[0].astype(jnp.int32) * 0)[None, :],
                    vals0[None, :], 0.0).reshape(-1)[:N] + c * 1e-20)

    def full_pipeline(c):
        comp = c + resid
        body = jnp.full((rows * k,), -1.0, comp.dtype)
        body = body.at[:N].set(jnp.abs(comp)).reshape(rows, k)
        win_row = jnp.argmax(body, axis=0).astype(jnp.int32)
        idx = win_row * k + jnp.arange(k, dtype=jnp.int32)
        vals = comp[idx]
        mask = jnp.arange(rows, dtype=jnp.int32)[:, None] == win_row[None, :]
        dense = jnp.where(mask, vals[None, :], 0.0).reshape(-1)[:N]
        return comp - dense          # new residual: the carried state

    timed("full chunk pipeline", full_pipeline)

    def gatherfree_pipeline(c):
        comp = c + resid
        sbody = jnp.zeros((rows * k,), comp.dtype).at[:N].set(comp)
        sbody = sbody.reshape(rows, k)
        win_row = jnp.argmax(jnp.abs(sbody).at[-1].add(-1e-9), axis=0)
        mask = (jnp.arange(rows, dtype=jnp.int32)[:, None]
                == win_row.astype(jnp.int32)[None, :])
        dense = jnp.where(mask, sbody, 0.0)
        vals = jnp.sum(dense, axis=0)             # wire values, gather-free
        return comp - (dense.reshape(-1)[:N] + vals[0] * 1e-20)

    timed("gather-free chunk pipeline", gatherfree_pipeline)

    # ---- round-4 additions: the pieces the headline ACTUALLY runs -------
    # (fusion='flat' + chunk Top-K with the fused Pallas kernels; the rows
    # above are the staged building blocks, these are the deployed paths.)
    from grace_tpu.ops.pallas_topk import (chunk_aggregate_dense,
                                           chunk_compress_feedback)

    def pallas_fused(c):
        vals, win, new_resid = chunk_compress_feedback(
            flat, c, k, interpret=ALLOW_CPU)
        return new_resid + vals[0] * 1e-20

    timed("pallas fused compress+residual", pallas_fused)

    world = 8
    gvals = jax.random.normal(jax.random.key(5), (world, k), jnp.float32)
    gwin = jax.random.randint(jax.random.key(6), (world, k), 0, rows,
                              dtype=jnp.int32)

    def pallas_agg(c):
        # c[0]-dependence keeps the aggregate inside the loop.
        dense = chunk_aggregate_dense(gvals + c[0] * 1e-20, gwin, k, N,
                                      average=True, interpret=ALLOW_CPU)
        return c * 1e-20 + dense

    timed(f"pallas aggregate W={world}", pallas_agg)

    # Leaf plumbing around the fused buffer: ResNet-50's real leaf shapes —
    # unless N was overridden (script self-test off-chip), in which case
    # synthesize a same-cardinality split of N so every stage scales down.
    if N == 25_557_032:
        from grace_tpu.models import resnet
        pshapes = jax.eval_shape(
            lambda key: resnet.init(key, depth=50, num_classes=1000)[0],
            jax.random.key(0))
        shapes = [s.shape for s in jax.tree.leaves(pshapes)]
    else:
        n_leaves = 160
        per = max(1, N // n_leaves)
        shapes = [(per,)] * (n_leaves - 1) + [(N - per * (n_leaves - 1),)]
    total = sum(int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes)
    _report(f"resnet50 leaves={len(shapes)} total={total}")
    leaves = [jax.random.normal(jax.random.key(10 + j), s, jnp.float32)
              for j, s in enumerate(shapes)]

    def concat_leaves(c):
        scaled = [leaves[0] * (1.0 + c[0] * 1e-20)] + leaves[1:]
        flat_all = jnp.concatenate([jnp.ravel(l) for l in scaled])
        return c * 1e-20 + jnp.zeros((N,), jnp.float32
                                     ).at[:flat_all.size].set(flat_all[:N])

    timed(f"concat {len(shapes)} leaves", concat_leaves)

    def concat_split(lvs):
        flat_all = jnp.concatenate([jnp.ravel(l) for l in lvs])
        out, off = [], 0
        for s in shapes:
            size = int(np.prod(s, dtype=np.int64)) if s else 1
            out.append(flat_all[off:off + size].reshape(s))
            off += size
        return out

    timed("concat+split round trip", concat_split, carry0=leaves)

    # End-to-end transform.update — everything the compressed step does on
    # top of forward/backward/SGD: compensate, chunk-select (Pallas),
    # extract, residual, allgather (1 device), aggregate-decompress,
    # plus the concat/split plumbing. Init runs inside the timed fn but is
    # amortized over ITERS and is just zeros. Carry feeds each step's
    # output gradients back in, so the loop is honest.
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from grace_tpu import grace_from_params
    from grace_tpu.parallel import data_parallel_mesh

    mesh = data_parallel_mesh()

    for fusion, label in (("flat", "transform update (fusion=flat)"),
                          (None, "transform update (per-leaf)")):
        grc = grace_from_params({"compressor": "topk",
                                 "compress_ratio": 0.01,
                                 "topk_algorithm": "chunk",
                                 "memory": "residual",
                                 "communicator": "allgather",
                                 "fusion": fusion})
        tx = grc.transform(seed=0)

        def inner(lvs, _tx=tx):
            st = _tx.init(lvs)

            def body(i, carry):
                st, lv = carry
                out, st2 = _tx.update(lv, st)
                out = [o + i * 1e-12 for o in out]
                return (st2, out)

            _, out = lax.fori_loop(0, ITERS, body, (st, lvs))
            return out

        fn = jax.jit(shard_map(inner, mesh=mesh,
                               in_specs=(P(),), out_specs=P(),
                               check_rep=False))
        t_out = fn(leaves)
        float(jax.tree.leaves(t_out)[0].reshape(-1)[0])
        t0 = time.perf_counter()
        t_out = fn(leaves)
        float(jax.tree.leaves(t_out)[0].reshape(-1)[0])
        dt = (time.perf_counter() - t0) / ITERS
        _report(f"{label:34s} {dt*1e3:8.3f} ms/iter")


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=N)
    ap.add_argument("--iters", type=int, default=ITERS)
    ap.add_argument("--allow-cpu", action="store_true",
                    help="self-test the script off-chip (pair with small"
                         " --n; timings are meaningless)")
    ap.add_argument("--out", default=None,
                    help="also append every result line to this file "
                         "(the watcher points it at TPU_MICRO.txt)")
    a = ap.parse_args()
    N, K, ITERS, ALLOW_CPU = a.n, max(1, a.n // 100), a.iters, a.allow_cpu
    OUT_PATH = a.out
    if OUT_PATH:
        with open(OUT_PATH + ".tmp", "w") as f:
            f.write(f"=== tpu_micro run "
                    f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())}\n")
    main()
    if OUT_PATH:
        os.replace(OUT_PATH + ".tmp", OUT_PATH)
