#!/bin/bash
# Full 24-epoch CIFAR-10 DAWNBench runs on the 8-device CPU mesh (VERDICT
# round-2 item 4): uncompressed, Top-K 1% + residual, and Top-K 1% through
# the two-shot communicator. Sequential — the host has one core. Writes its
# process-group id to /tmp/cifar_runs.pgid so tools/tpu_watch.sh can
# SIGSTOP/SIGCONT the group around TPU measurements (host contention would
# otherwise leak into the fetch-bounded timing windows).
#
# Usage: setsid nohup tools/cifar_runs.sh & (log: cifar_runs.log at repo root)
cd "$(dirname "$0")/.." || exit 1
# Single instance via flock: two concurrent runs contend on the one-core
# host AND fight over the pgid file, leaving one of them unpausable by
# tpu_watch.sh (observed as interleaved epoch rows in cifar_runs.log).
exec 9>/tmp/cifar_runs.lock
if ! flock -n 9; then
  echo "=== $(date -u +%FT%TZ) another cifar_runs is alive — exiting" \
       >> cifar_runs.log
  exit 0
fi
echo $$ > /tmp/cifar_runs.pgid
# Abnormal exit must not leave a stale pgid for tpu_watch.sh to SIGSTOP
# after the kernel recycles it for an unrelated process group.
trap 'rm -f /tmp/cifar_runs.pgid' EXIT
LOG=cifar_runs.log
# Pin the 8-device simulated-CPU mesh BEFORE python starts — without this
# the example latches onto the TPU tunnel (sitecustomize), racing the
# benches for the one real chip when it is up and dying in backend init
# when it is not (observed: two runs burned 40 min each hanging in axon
# init, rc=1, zero epochs).
export JAX_PLATFORMS=cpu
export XLA_FLAGS=--xla_force_host_platform_device_count=8
# Real CIFAR-10 binaries are not available in this offline environment, so
# these 24-epoch runs exercise the full DAWNBench recipe on the synthetic
# default (VERDICT round-2 item 4's documented caveat): they are recipe/
# stability evidence, not 94%-accuracy evidence. Pass --data-dir through
# CIFAR_DATA_DIR if real data ever lands.
DATA_ARGS=()
SUFFIX="_synthetic"   # evidence filenames must say what the data was
[ -n "${CIFAR_DATA_DIR:-}" ] && { DATA_ARGS=(--data-dir "$CIFAR_DATA_DIR"); SUFFIX=""; }
run() {  # run <tsv> <extra args...>
  local tsv=$1; shift
  # Skip curves that already have all 24 epochs (the trainer cannot resume
  # mid-run, so a complete TSV is the only state worth keeping; anything
  # partial is re-run from scratch). Epoch rows start with a digit —
  # header/provenance lines do not.
  local done_epochs
  # NOT `|| echo 0`: grep -c already prints 0 (while exiting 1) on a
  # match-less file, and the fallback would append a second line.
  done_epochs=$(grep -c '^[0-9]' "$tsv" 2>/dev/null)
  done_epochs=${done_epochs:-0}
  if [ "$done_epochs" -ge 24 ]; then
    echo "=== $(date -u +%FT%TZ) skip (complete, $done_epochs epochs): $tsv" \
         >> "$LOG"
    return 0
  fi
  echo "=== $(date -u +%FT%TZ) --tsv $tsv $*" >> "$LOG"
  # 9>&- : children must not inherit the flock fd (an orphaned trainer
  # would hold the lock for hours and block restarts).
  python examples/cifar10_dawn.py --epochs 24 ${DATA_ARGS[@]+"${DATA_ARGS[@]}"} \
    --tsv "$tsv" "$@" >> "$LOG" 2>&1 9>&-
  echo "=== rc=$?" >> "$LOG"
}
run "examples/logs/cifar10_dawn_24ep${SUFFIX}.tsv"
run "examples/logs/cifar10_dawn_24ep_topk1pct${SUFFIX}.tsv" \
    --compressor topk --compress-ratio 0.01 --memory residual --peak-lr 0.1
run "examples/logs/cifar10_dawn_24ep_topk1pct_twoshot${SUFFIX}.tsv" \
    --compressor topk --compress-ratio 0.01 --memory residual --peak-lr 0.1 \
    --communicator twoshot
rm -f /tmp/cifar_runs.pgid
echo "=== $(date -u +%FT%TZ) all done" >> "$LOG"
