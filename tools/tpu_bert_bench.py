"""On-chip BERT-base bench rows: dense, PowerSGD r4, and the graft-shard
transformer track (rscatter + per-leaf codec routing).

BASELINE.json config 4 pairs "BERT-base SQuAD" with PowerSGD rank-4 over
allreduce (reference grace_dl/dist/compressor/powersgd.py); the committed
capture has that row LOSING at 0.80× dense single-chip (the before-picture
ROADMAP item 2 names). The graft-shard rows are the after-picture: Top-K 1%
through the compressed per-shard reduce-scatter (``communicator:
"rscatter"``), and the ROUTED config — embeddings and the big matrices
ride sparsification, LayerNorm/bias leaves ride dense fp16 psum — whose
per-link xslice projection is the test-pinned >1× vs dense at W≥64
(tests/test_shard.py). All rows measure dense interleaved in ONE session —
the same same-session discipline as bench.bench_configs — reporting
tokens/sec, spread, per-leaf wire bytes (helper.route_leaves for routed
rows), and per-link projections through the ONE shared wire model
(helper.routed_recv_link_bytes — collapses to the plain model for
unrouted rows). Rows persist row-by-row to BENCH_BERT_TPU_LAST.json
(bench.progressive_emit), so a mid-run tunnel death keeps the dense row.

Run by tools/tpu_watch.sh after the main sweep; manual:
    python tools/tpu_bert_bench.py --platform tpu    # on the chip
    python tools/tpu_bert_bench.py --platform cpu    # tiny-model smoke
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

EVIDENCE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_BERT_TPU_LAST.json")

# Transformer routing (ISSUE 14): LayerNorm scales/offsets and biases hate
# sparsification and are a rounding error of the wire bill — they ride
# dense fp16 psum; everything else (embeddings, qkv/proj/ff matrices — the
# >99% of BERT's 108.8M params where wire bytes concentrate) rides chunked
# Top-K 1% through the per-shard reduce-scatter.
BERT_ROUTE = [("*ln*", {"compressor": "fp16", "memory": "none",
                        "communicator": "allreduce"}),
              ("*bias*", {"compressor": "fp16", "memory": "none",
                          "communicator": "allreduce"}),
              ("*/b", {"compressor": "fp16", "memory": "none",
                       "communicator": "allreduce"})]

CONFIGS = [
    # fusion "none", twice over: (a) like-for-like with the powersgd config
    # below (also per-leaf); (b) fusion "flat" on the 108.8M-element BERT
    # gradient trips an XLA-TPU layout pathology — the materialized flat
    # f32[108793346] consumed by the 200-way split gets laid out as
    # f32[54396673,2]{1,0:T(8,128)}, whose minor-dim pad 2->128 inflates
    # 435 MB to 27.8 GB and OOMs 16 GB HBM at compile. Allreduce chunks
    # oversized dense psums to sidestep this (comm/__init__.py), but the
    # per-leaf program is the cleaner baseline here regardless.
    {"name": "bert_dense", "params": {"compressor": "none", "memory": "none",
                                      "communicator": "allreduce",
                                      "fusion": "none"}},
    {"name": "bert_powersgd_r4", "params": {"compressor": "powersgd",
                                            "compress_rank": 4,
                                            "memory": "powersgd",
                                            "communicator": "allreduce",
                                            "fusion": "none"}},
    # graft-shard (ISSUE 14): the per-shard reduce-scatter — one
    # all_to_all + one all_gather per leaf, requant chain 1 at any W.
    {"name": "bert_topk1pct_rscatter",
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "rscatter", "fusion": "none"}},
    # ...and the routed config: the transformer-track headline shape.
    {"name": "bert_routed_rscatter",
     "params": {"compressor": "topk", "compress_ratio": 0.01,
                "topk_algorithm": "chunk", "memory": "residual",
                "communicator": "rscatter", "fusion": "none",
                "route": BERT_ROUTE}},
]


def routed_wire_report(grace, params):
    """(wire_bytes, dense_bytes) summed per leaf through each leaf's own
    routed codec — collapses to wire_report's totals for unrouted rows."""
    import numpy as np

    from grace_tpu.helper import route_leaves
    from grace_tpu.utils.metrics import payload_nbytes

    wire = dense = 0
    for _p, s, comp, _m, _cm in route_leaves(grace, params):
        ne = int(np.prod(s.shape, dtype=np.int64))
        dense += ne * s.dtype.itemsize
        wire += payload_nbytes(comp, s)
    return wire, dense


def project_routed(step_s: float, dense_step_s: float, grace, params,
                   n_elems: int) -> list:
    """Per-link multi-chip projection for a (possibly routed) config
    through ``helper.routed_recv_link_bytes`` — the routed spelling of
    ``bench.project_multichip``, same worlds, same bandwidth constants,
    same NO-OVERLAP convention, dense priced through the identical shared
    model."""
    from grace_tpu.comm import Allreduce
    from grace_tpu.core import Topology
    from grace_tpu.helper import routed_recv_link_bytes

    dense_comm = Allreduce()
    dense_b = sum(x.size * x.dtype.itemsize
                  for x in __import__("jax").tree_util.tree_leaves(params))
    xtopo = Topology(slice_size=bench.XSLICE_CHIPS)
    out = []
    for w in bench.PROJECTION_WORLDS:
        cfg_recv = routed_recv_link_bytes(grace, params, w).total
        dense_recv = dense_comm.recv_wire_bytes(dense_b, n_elems, w)
        row = {"world": w, "recv_bytes_per_rank": cfg_recv}
        for net, bw in (("ici", bench.ICI_RING_BYTES_PER_S),
                        ("dcn", bench.DCN_BYTES_PER_S)):
            t_cfg = step_s + cfg_recv / bw
            t_dense = dense_step_s + dense_recv / bw
            row[f"step_ms_{net}"] = round(t_cfg * 1e3, 3)
            row[f"speedup_vs_dense_{net}"] = round(t_dense / t_cfg, 3)
        cfg_link = routed_recv_link_bytes(grace, params, w, topology=xtopo)
        dense_link = dense_comm.recv_link_bytes(dense_b, n_elems, w,
                                                topology=xtopo)

        def t_split(base_s, link):
            return (base_s + link.ici / bench.ICI_RING_BYTES_PER_S
                    + link.dcn / bench.DCN_BYTES_PER_S)

        t_cfg = t_split(step_s, cfg_link)
        row["xslice"] = {
            "slice_size": bench.XSLICE_CHIPS,
            "ici_bytes": cfg_link.ici,
            "dcn_bytes": cfg_link.dcn,
            "step_ms": round(t_cfg * 1e3, 3),
            "speedup_vs_dense": round(
                t_split(dense_step_s, dense_link) / t_cfg, 3),
        }
        out.append(row)
    return out


def run(platform: str, emit) -> None:
    devices = bench.setup_platform(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.models import layers as L
    from grace_tpu.models import transformer
    from grace_tpu.parallel import batch_sharded, data_parallel_mesh
    from grace_tpu.train import (init_stateful_train_state,
                                 make_stateful_train_step)

    on_tpu = devices[0].platform == "tpu"
    mesh = data_parallel_mesh(devices)
    # BERT-base at the standard SQuAD fine-tuning length on the chip; a tiny
    # encoder on the CPU mesh so the smoke finishes on a one-core host.
    seq = 384 if on_tpu else 64
    per_device_bs = 8 if on_tpu else 2
    cfg = (transformer.base(num_classes=2, max_len=seq) if on_tpu
           else transformer.tiny(num_classes=2, max_len=seq))
    repeats = 3 if on_tpu else 1
    # Window >= ~1.3 s against tunnel RTT jitter (memory: timed windows
    # must dwarf the ~65-400 ms fetch RTT): BERT-base steps are ~10x a
    # ResNet bs=32 step, so fewer batches suffice.
    n_batches = 40 if on_tpu else 2

    n = per_device_bs * len(devices)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (n, seq)), jnp.int32)
    spans = jnp.asarray(
        np.stack([rng.integers(0, seq // 2, n),
                  rng.integers(seq // 2, seq, n)], 1), jnp.int32)
    batch = jax.device_put((ids, spans), batch_sharded(mesh))

    def build(grace_params):
        grace = grace_from_params(grace_params)
        optimizer = optax.chain(grace.transform(seed=0), optax.adamw(5e-5))

        def loss_fn(params, mstate, b):
            idb, spanb = b
            x = transformer.encode(params, idb, cfg, dtype=jnp.bfloat16)
            logits = L.dense_apply(params["cls"], x.astype(jnp.float32))
            loss = (optax.softmax_cross_entropy_with_integer_labels(
                        logits[..., 0], spanb[:, 0])
                    + optax.softmax_cross_entropy_with_integer_labels(
                        logits[..., 1], spanb[:, 1]))
            return loss.mean(), mstate

        step = make_stateful_train_step(loss_fn, optimizer, mesh)
        params, mstate = transformer.init(jax.random.key(0), cfg)
        ts = init_stateful_train_state(params, mstate, optimizer, mesh)
        return step, ts, grace, params

    chip = getattr(devices[0], "device_kind", devices[0].platform)
    print(f"[bert-bench] mesh: {len(devices)}x {devices[0].platform} "
          f"({chip}), seq={seq}, bs={per_device_bs}/device",
          file=sys.stderr, flush=True)

    built = [build(c["params"]) for c in CONFIGS]
    samples = [[] for _ in CONFIGS]
    for r in range(repeats):
        warm = 4 if r == 0 else 2
        for j, (step, ts, _g, _p) in enumerate(built):
            s, ts = bench.throughput(step, ts, batch, n_batches,
                                     warmup=warm)
            built[j] = (step, ts, built[j][2], built[j][3])
            samples[j].append(s)

    med = statistics.median
    base_samples = samples[0]
    n_elems = sum(x.size for x in jax.tree_util.tree_leaves(built[0][3]))
    for c, (step, ts, grace, params), ss in zip(CONFIGS, built, samples):
        seqs = med(ss)
        wire_b, dense_b = routed_wire_report(grace, params)
        spread = (100.0 * (max(ss) - min(ss)) / seqs if seqs else 0.0)
        from grace_tpu.helper import routed_recv_link_bytes
        emit({
            "config": c["name"],
            "tokens_per_sec": round(seqs * seq, 1),
            "seqs_per_sec": round(seqs, 2),
            "samples_seqs_per_sec": [round(s, 2) for s in ss],
            "spread_pct": round(spread, 2),
            "vs_baseline": round(seqs / med(base_samples), 4),
            "same_session": True,
            "seq_len": seq,
            "per_device_bs": per_device_bs,
            "model": "bert-base" if on_tpu else "bert-tiny(smoke)",
            "n_params": n_elems,
            "routed": bool(c["params"].get("route")),
            "wire_bytes_per_step": wire_b,
            "wire_ratio": round(wire_b / max(1, dense_b), 6),
            "wire_recv_bytes_per_step": routed_recv_link_bytes(
                grace, params, len(devices)).total,
            "projection": project_routed(
                n / seqs, n / med(base_samples), grace, params, n_elems),
            "platform": devices[0].platform,
            "n_devices": len(devices),
            "chip": chip,
        })


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default="tpu", choices=["tpu", "cpu"])
    args = ap.parse_args()
    emit = bench.progressive_emit(
        lambda r: print(json.dumps(r), flush=True),
        n_expected=len(CONFIGS),
        evidence_path=EVIDENCE_PATH,
        metric="bert_powersgd_r4_tokens_per_sec",
        headline_config="bert_powersgd_r4",
        value_key="tokens_per_sec")
    run(args.platform, emit)


if __name__ == "__main__":
    main()
