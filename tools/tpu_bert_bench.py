"""On-chip BERT-base + PowerSGD rank-4 bench row (VERDICT round-3 item 7).

BASELINE.json config 4 pairs "BERT-base SQuAD" with PowerSGD rank-4 over
allreduce (reference grace_dl/dist/compressor/powersgd.py); the convergence
example is examples/bert_powersgd.py, but no perf row existed. This measures
the dense baseline and powersgd_r4 interleaved in ONE session — the same
same-session discipline as bench.bench_configs — reporting tokens/sec,
spread, and PowerSGD's analytic wire bytes (compressors/powersgd.py
wire_nbytes). Rows persist row-by-row to BENCH_BERT_TPU_LAST.json
(bench.progressive_emit), so a mid-run tunnel death keeps the dense row.

Run by tools/tpu_watch.sh after the main sweep; manual:
    python tools/tpu_bert_bench.py --platform tpu    # on the chip
    python tools/tpu_bert_bench.py --platform cpu    # tiny-model smoke
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402

EVIDENCE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "BENCH_BERT_TPU_LAST.json")

CONFIGS = [
    # fusion "none", twice over: (a) like-for-like with the powersgd config
    # below (also per-leaf); (b) fusion "flat" on the 108.8M-element BERT
    # gradient trips an XLA-TPU layout pathology — the materialized flat
    # f32[108793346] consumed by the 200-way split gets laid out as
    # f32[54396673,2]{1,0:T(8,128)}, whose minor-dim pad 2->128 inflates
    # 435 MB to 27.8 GB and OOMs 16 GB HBM at compile. Allreduce chunks
    # oversized dense psums to sidestep this (comm/__init__.py), but the
    # per-leaf program is the cleaner baseline here regardless.
    {"name": "bert_dense", "params": {"compressor": "none", "memory": "none",
                                      "communicator": "allreduce",
                                      "fusion": "none"}},
    {"name": "bert_powersgd_r4", "params": {"compressor": "powersgd",
                                            "compress_rank": 4,
                                            "memory": "powersgd",
                                            "communicator": "allreduce",
                                            "fusion": "none"}},
]


def run(platform: str, emit) -> None:
    devices = bench.setup_platform(platform)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu import grace_from_params
    from grace_tpu.models import layers as L
    from grace_tpu.models import transformer
    from grace_tpu.parallel import batch_sharded, data_parallel_mesh
    from grace_tpu.train import (init_stateful_train_state,
                                 make_stateful_train_step)
    from grace_tpu.utils import wire_report

    on_tpu = devices[0].platform == "tpu"
    mesh = data_parallel_mesh(devices)
    # BERT-base at the standard SQuAD fine-tuning length on the chip; a tiny
    # encoder on the CPU mesh so the smoke finishes on a one-core host.
    seq = 384 if on_tpu else 64
    per_device_bs = 8 if on_tpu else 2
    cfg = (transformer.base(num_classes=2, max_len=seq) if on_tpu
           else transformer.tiny(num_classes=2, max_len=seq))
    repeats = 3 if on_tpu else 1
    # Window >= ~1.3 s against tunnel RTT jitter (memory: timed windows
    # must dwarf the ~65-400 ms fetch RTT): BERT-base steps are ~10x a
    # ResNet bs=32 step, so fewer batches suffice.
    n_batches = 40 if on_tpu else 2

    n = per_device_bs * len(devices)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (n, seq)), jnp.int32)
    spans = jnp.asarray(
        np.stack([rng.integers(0, seq // 2, n),
                  rng.integers(seq // 2, seq, n)], 1), jnp.int32)
    batch = jax.device_put((ids, spans), batch_sharded(mesh))

    def build(grace_params):
        grace = grace_from_params(grace_params)
        optimizer = optax.chain(grace.transform(seed=0), optax.adamw(5e-5))

        def loss_fn(params, mstate, b):
            idb, spanb = b
            x = transformer.encode(params, idb, cfg, dtype=jnp.bfloat16)
            logits = L.dense_apply(params["cls"], x.astype(jnp.float32))
            loss = (optax.softmax_cross_entropy_with_integer_labels(
                        logits[..., 0], spanb[:, 0])
                    + optax.softmax_cross_entropy_with_integer_labels(
                        logits[..., 1], spanb[:, 1]))
            return loss.mean(), mstate

        step = make_stateful_train_step(loss_fn, optimizer, mesh)
        params, mstate = transformer.init(jax.random.key(0), cfg)
        ts = init_stateful_train_state(params, mstate, optimizer, mesh)
        return step, ts, grace, params

    chip = getattr(devices[0], "device_kind", devices[0].platform)
    print(f"[bert-bench] mesh: {len(devices)}x {devices[0].platform} "
          f"({chip}), seq={seq}, bs={per_device_bs}/device",
          file=sys.stderr, flush=True)

    base_step, base_ts, base_grace, base_params = build(CONFIGS[0]["params"])
    comp_step, comp_ts, comp_grace, comp_params = build(CONFIGS[1]["params"])

    bsamples, csamples = [], []
    for r in range(repeats):
        warm = 4 if r == 0 else 2
        s, base_ts = bench.throughput(base_step, base_ts, batch, n_batches,
                                      warmup=warm)
        bsamples.append(s)
        s, comp_ts = bench.throughput(comp_step, comp_ts, batch, n_batches,
                                      warmup=warm)
        csamples.append(s)

    med = statistics.median
    n_elems = sum(x.size for x in jax.tree_util.tree_leaves(base_params))
    for name, samples, other, grace, params in (
            ("bert_dense", bsamples, bsamples, base_grace, base_params),
            ("bert_powersgd_r4", csamples, bsamples, comp_grace,
             comp_params)):
        seqs = med(samples)
        rep = wire_report(grace.compressor, params)
        spread = (100.0 * (max(samples) - min(samples)) / seqs
                  if seqs else 0.0)
        vote = getattr(grace.compressor, "vote_aggregate", False)
        emit({
            "config": name,
            "tokens_per_sec": round(seqs * seq, 1),
            "seqs_per_sec": round(seqs, 2),
            "samples_seqs_per_sec": [round(s, 2) for s in samples],
            "spread_pct": round(spread, 2),
            "vs_baseline": round(seqs / med(other), 4),
            "same_session": True,
            "seq_len": seq,
            "per_device_bs": per_device_bs,
            "model": "bert-base" if on_tpu else "bert-tiny(smoke)",
            "n_params": n_elems,
            "wire_bytes_per_step": rep.wire_bytes,
            "wire_ratio": round(rep.ratio, 6),
            "wire_recv_bytes_per_step": bench.recv_bytes_model(
                grace.communicator, vote, rep.wire_bytes, n_elems,
                len(devices)),
            "projection": bench.project_multichip(
                n / seqs, n / med(bsamples), grace, rep.wire_bytes,
                rep.dense_bytes, n_elems),
            "platform": devices[0].platform,
            "n_devices": len(devices),
            "chip": chip,
        })


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default="tpu", choices=["tpu", "cpu"])
    args = ap.parse_args()
    emit = bench.progressive_emit(
        lambda r: print(json.dumps(r), flush=True),
        n_expected=len(CONFIGS),
        evidence_path=EVIDENCE_PATH,
        metric="bert_powersgd_r4_tokens_per_sec",
        headline_config="bert_powersgd_r4",
        value_key="tokens_per_sec")
    run(args.platform, emit)


if __name__ == "__main__":
    main()
