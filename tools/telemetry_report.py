#!/usr/bin/env python
"""Render a telemetry JSONL run log as a per-stage / per-metric summary.

Input: a file written by ``grace_tpu.telemetry.JSONLSink`` — a provenance
header line followed by per-step metric records
(``TelemetryReader``) and guard-transition events (``GuardMonitor``).

Output (text, stdout): the provenance block, a per-metric stats table
(count / mean / min / max / last over the per-step records), wire-traffic
accounting including dense-fallback windows reconstructed from the
``fallback`` flag flips, a graft-watch section (cross-rank health
summaries and ``watch_anomaly`` findings, from
``grace_tpu.telemetry.aggregate``/``anomaly``), a profiling section
(step-time percentiles, compile/retrace events, memory watermarks, and the
GraceState footprint check, from ``grace_tpu.profiling.ProfileRecorder``'s
``perf_*`` records), and the guard event log — one report covers one run.
``--json`` emits the same content as one machine-readable document, so CI
consumes structure instead of scraping text. Pure stdlib — usable on any
box that holds the artifact, no jax required.

Usage::

    python tools/telemetry_report.py chaos_telemetry.jsonl
    python tools/telemetry_report.py run.jsonl --metrics grad_norm,wire_bytes
    python tools/telemetry_report.py run.jsonl --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

# Metric columns in display order; anything else numeric found in records
# is appended after these.
PREFERRED = ["grad_norm", "update_norm", "residual_norm", "residual_max",
             "compression_error", "wire_bytes", "wire_bytes_ici",
             "wire_bytes_dcn", "dense_bytes", "fallback", "audit_bytes",
             "watch_bytes", "negotiation_bytes", "adapt_rung",
             "adapt_bytes"]


def load(path: str):
    provenance, records, events = None, [], []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                print(f"[telemetry_report] {path}:{lineno}: bad JSON "
                      f"({e}); skipping", file=sys.stderr)
                continue
            if "provenance" in obj and provenance is None:
                provenance = obj["provenance"]
            elif "event" in obj:
                events.append(obj)
            else:
                records.append(obj)
    return provenance, records, events


def _stats(values: List[float]) -> dict:
    return {"count": len(values),
            "mean": sum(values) / len(values),
            "min": min(values),
            "max": max(values),
            "last": values[-1]}


def _fmt(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return f"{int(v):>12,d}"
    return f"{v:>12.6g}"


def fallback_windows(records: List[dict]) -> List[tuple]:
    """[(first_step, last_step), ...] of contiguous fallback==1 records."""
    windows, start, prev = [], None, None
    for rec in records:
        if rec.get("fallback"):
            if start is None:
                start = rec["step"]
            prev = rec["step"]
        elif start is not None:
            windows.append((start, prev))
            start = None
    if start is not None:
        windows.append((start, prev))
    return windows


def render(provenance, records, events,
           metrics: Optional[List[str]] = None) -> str:
    out = []
    out.append("== provenance ==")
    if provenance:
        for k, v in provenance.items():
            out.append(f"  {k}: {v}")
    else:
        out.append("  (no provenance header — was this file written by "
                   "JSONLSink?)")

    out.append("")
    out.append(f"== per-step metrics ({len(records)} records) ==")
    if records:
        steps = [r["step"] for r in records if "step" in r]
        if steps:
            out.append(f"  steps {min(steps)}..{max(steps)}")
        dropped = sum(r.get("dropped_steps", 0) for r in records)
        if dropped:
            out.append(f"  ring-wraparound dropped rows: {dropped} "
                       "(flush interval exceeded telemetry capacity)")
        numeric = [k for k in records[-1]
                   if isinstance(records[-1][k], (int, float))
                   and not isinstance(records[-1][k], bool)
                   and k != "step"]
        cols = [m for m in (metrics or PREFERRED) if any(m in r
                                                         for r in records)]
        cols += [k for k in sorted(numeric)
                 if k not in cols and metrics is None]
        head = f"  {'metric':<24s}{'count':>8s}" + "".join(
            f"{h:>13s}" for h in ("mean", "min", "max", "last"))
        out.append(head)
        for m in cols:
            vals = [float(r[m]) for r in records if m in r]
            if not vals:
                continue
            s = _stats(vals)
            out.append(f"  {m:<24s}{s['count']:>8d}"
                       + "".join(" " + _fmt(s[k])
                                 for k in ("mean", "min", "max", "last")))

        wire = [float(r["wire_bytes"]) for r in records if "wire_bytes" in r]
        dense = [float(r["dense_bytes"]) for r in records
                 if "dense_bytes" in r]
        if wire and dense:
            out.append("")
            out.append("== wire traffic ==")
            out.append(f"  effective bytes received per rank, total: "
                       f"{int(sum(wire)):,d} (raw dense gradient bytes "
                       f"{int(sum(dense)):,d}; ratio "
                       f"{sum(wire) / max(sum(dense), 1):.4f} — "
                       "communicator-aware, so allgather at scale can "
                       "legitimately exceed 1.0)")
            ici = [float(r["wire_bytes_ici"]) for r in records
                   if "wire_bytes_ici" in r]
            dcn = [float(r["wire_bytes_dcn"]) for r in records
                   if "wire_bytes_dcn" in r]
            wan = [float(r.get("wire_bytes_wan", 0.0)) for r in records
                   if "wire_bytes_ici" in r]
            if ici and dcn:
                tot = sum(ici) + sum(dcn) + sum(wan)
                wan_part = (f", wan {int(sum(wan)):,d} B" if sum(wan)
                            else "")
                out.append(
                    f"  per-link split: ici {int(sum(ici)):,d} B, "
                    f"dcn {int(sum(dcn)):,d} B{wan_part} "
                    f"({100.0 * sum(dcn) / max(tot, 1):.1f}% over DCN, "
                    f"{100.0 * sum(wan) / max(tot, 1):.1f}% over WAN — "
                    "flat communicators bill everything at the worst "
                    "tier they cross; a mixed split means a "
                    "hierarchical schedule)")
            wins = fallback_windows(records)
            if wins:
                spans = ", ".join(f"{a}..{b}" for a, b in wins)
                out.append(f"  dense-fallback windows (recorded steps): "
                           f"{spans}")
            else:
                out.append("  dense-fallback windows: none")
            out.append("  (logical payload bytes — XLA may pad/repack on "
                       "the wire; treat as the algorithmic lower bound, "
                       "see grace_tpu/utils/metrics.py)")
        guard_keys = sorted(k for k in records[-1] if k.startswith("guard_"))
        if guard_keys:
            out.append("")
            out.append("== guard counters (at last flush) ==")
            for k in guard_keys:
                out.append(f"  {k}: {records[-1][k]}")
    else:
        out.append("  (none)")

    perf = [e for e in events if str(e.get("event", "")).startswith("perf_")]
    watch = [e for e in events
             if e.get("event") in ("watch", "watch_anomaly")]
    lint = [e for e in events if e.get("event") == "lint_finding"]
    adapt = [e for e in events
             if str(e.get("event", "")).startswith("adapt")]
    retune = [e for e in events
              if str(e.get("event", "")).startswith("retune")]
    other = [e for e in events
             if e not in perf and e not in watch and e not in lint
             and e not in adapt and e not in retune]
    if adapt or any("adapt_rung" in r and float(r["adapt_rung"]) >= 0
                    for r in records):
        out.append("")
        out.append("== adapt (graft-adapt rung transitions) ==")
        out.extend(_render_adapt(adapt, records))
    if retune:
        out.append("")
        out.append("== retune (graft-retune config transactions) ==")
        out.extend(_render_retune(retune))
    if watch:
        out.append("")
        out.append("== watch (graft-watch summaries + anomalies) ==")
        out.extend(_render_watch(watch))
    if perf:
        out.append("")
        out.append("== profiling (ProfileRecorder perf_* records) ==")
        out.extend(_render_perf(perf))
    if lint:
        out.append("")
        out.append(f"== static analysis ({len(lint)} lint_finding "
                   "event(s)) ==")
        out.extend(_render_lint(lint))

    out.append("")
    out.append(f"== guard events ({len(other)}) ==")
    for e in other:
        extras = {k: v for k, v in e.items() if k not in ("event", "step")}
        brief = ", ".join(f"{k}={v}" for k, v in sorted(extras.items())
                          if isinstance(v, (int, float, bool)))
        out.append(f"  step {e.get('step', '?'):>6}: {e['event']}"
                   + (f"  ({brief})" if brief else ""))
    if not other:
        out.append("  (none)")
    return "\n".join(out)


def _render_adapt(adapt: List[dict], records: List[dict]) -> List[str]:
    """graft-adapt controller trail: the rung trajectory from the metric
    rows' ``adapt_rung`` column plus one line per tighten/loosen
    transition event — rendered before the guard log because tightening
    ahead of the guard is the controller's whole claim."""
    out = []
    rungs = [(r["step"], int(r["adapt_rung"])) for r in records
             if "adapt_rung" in r and float(r["adapt_rung"]) >= 0
             and "step" in r]
    if rungs:
        lo = min(v for _, v in rungs)
        hi = max(v for _, v in rungs)
        out.append(f"  rung range over {len(rungs)} recorded steps: "
                   f"{lo}..{hi} (0 = dense escape; last "
                   f"{rungs[-1][1]} at step {rungs[-1][0]})")
        # Effective-rung dwell: how the state-dependent wire bill splits.
        counts: dict = {}
        for _, v in rungs:
            counts[v] = counts.get(v, 0) + 1
        dwell = ", ".join(f"rung {k}: {v}" for k, v in sorted(counts.items()))
        out.append(f"  dwell (steps per effective rung): {dwell}")
    tightens = [e for e in adapt if e.get("event") == "adapt_tighten"]
    loosens = [e for e in adapt if e.get("event") == "adapt_loosen"]
    out.append(f"  transitions: {len(tightens)} tighten(s), "
               f"{len(loosens)} loosen(s)")
    for e in adapt:
        out.append(f"    step {e.get('step', '?'):>6}: {e['event']} "
                   f"rung {e.get('from_rung', '?')} -> {e.get('rung', '?')}")
    if not adapt and not rungs:
        out.append("  (controller armed but no rows recorded)")
    return out


def _render_retune(retune: List[dict]) -> List[str]:
    """graft-retune transaction trail: one line per event, plus a tally
    of promotions/demotions/timeouts — a demotion inside a probation
    window is the rollback working, not a failure, and the report says
    which config survived."""
    out = []
    promotes = [e for e in retune if e.get("event") == "retune_promote"]
    demotes = [e for e in retune if e.get("event") == "retune_demote"]
    timeouts = [e for e in retune if e.get("event") == "retune_timeout"]
    aborts = [e for e in retune if e.get("event") == "retune_abort"]
    out.append(f"  transactions: {len(promotes)} promotion(s), "
               f"{len(demotes)} demotion(s), {len(aborts)} abort(s), "
               f"{len(timeouts)} bounded-leg timeout(s)")
    for e in retune:
        name = str(e.get("event", "?"))
        extras = {k: v for k, v in e.items() if k not in ("event", "step")}
        brief = ", ".join(f"{k}={v}" for k, v in sorted(extras.items())
                          if isinstance(v, (int, float, bool, str))
                          and k not in ("reason",))
        out.append(f"    step {e.get('step', '?'):>6}: {name}"
                   + (f"  ({brief})" if brief else ""))
        if e.get("reason"):
            msg = str(e["reason"])
            out.append(f"            {msg[:150]}"
                       + ("…" if len(msg) > 150 else ""))
    closers = [e for e in retune
               if e.get("event") in ("retune_promote", "retune_demote")]
    if closers:
        last = closers[-1]
        survivor = (last.get("new") if last["event"] == "retune_promote"
                    else last.get("config"))
        out.append(f"  surviving config: {survivor}")
    return out


def _render_watch(watch: List[dict]) -> List[str]:
    """Cross-rank health summaries (one line per window) and anomaly
    findings — the early-warning layer, rendered before the guard log it
    is meant to preempt."""
    out = []
    summaries = [e for e in watch if e["event"] == "watch"]
    anomalies = [e for e in watch if e["event"] == "watch_anomaly"]
    if summaries:
        out.append(f"  {len(summaries)} cross-rank summaries "
                   f"(steps {summaries[0].get('step', '?')}"
                   f"..{summaries[-1].get('step', '?')})")
        worst = max(summaries, key=lambda e: e.get("skew_max", 0.0))
        out.append(
            f"  worst compression-error skew: {worst.get('skew_max', 0):.4g}"
            f" (rank {worst.get('skew_rank', '?')} at step "
            f"{worst.get('step', '?')}; relative to the cross-rank mean)")
        last = summaries[-1]
        for metric in ("grad_norm", "compression_error", "residual_norm"):
            mean = last.get(f"{metric}_mean")
            lo, hi = last.get(f"{metric}_min"), last.get(f"{metric}_max")
            if mean is None:
                continue
            out.append(f"  last window {metric}: mean {mean:.6g} "
                       f"(cross-rank min {lo:.6g} / max {hi:.6g})")
    if anomalies:
        out.append(f"  ANOMALIES ({len(anomalies)}):")
        for a in anomalies:
            rank = a.get("rank", -1)
            who = f"rank {rank}" if isinstance(rank, int) and rank >= 0 \
                else "fleet-wide"
            out.append(
                f"    step {a.get('step', '?'):>6}: "
                f"{a.get('kind', '?')}/{a.get('metric', '?')} ({who}) "
                f"score {a.get('score', 0):.3g} "
                f"threshold {a.get('threshold', 0):.3g} "
                f"value {a.get('value', 0):.4g}")
    else:
        out.append("  anomalies: none")
    return out


def _render_lint(lint: List[dict]) -> List[str]:
    """graft-lint ``lint_finding`` events (the chaos_smoke --lint gate and
    ``graft_lint --jsonl``), one line per finding with the same stage
    attribution the passes computed — so a schedulability/numeric/footprint
    finding lands in the unified run timeline next to the guard/consensus
    events of the step range it would have bitten."""
    out = []
    for e in lint:
        loc = str(e.get("config", "?"))
        if e.get("stage"):
            loc += f" [{e['stage']}]"
        out.append(f"  {str(e.get('severity', '?')).upper():7s} "
                   f"{str(e.get('pass', '?')):24s} {loc}")
        msg = str(e.get("message", ""))
        out.append(f"          {msg[:160]}" + ("…" if len(msg) > 160 else ""))
    return out


def _render_perf(perf: List[dict]) -> List[str]:
    """Step-time percentiles (last window wins — they are cumulative),
    compile/retrace events, memory watermarks, footprint check."""
    out = []
    times = [e for e in perf if e["event"] == "perf_step_times"]
    if times:
        t = times[-1]
        order = ["mean_ms", "p50_ms", "p90_ms", "p99_ms", "max_ms"]
        keys = [k for k in order if k in t] + \
            [k for k in sorted(t) if k.endswith("_ms") and k not in order]
        pcts = ", ".join(f"{k[:-3]} {t[k]:.3f}" for k in keys)
        out.append(f"  step times (n={t.get('n_steps', '?')}): {pcts} ms")
        if t.get("sync_missing"):
            out.append("  WARNING: timed without sync_on() — these are "
                       "async-dispatch times, not step times")
        if t.get("failed_steps"):
            out.append(f"  failed steps recorded: {t['failed_steps']}")
    compiles = [e for e in perf if e["event"] == "perf_compile"]
    retraces = [e for e in perf if e["event"] == "perf_retrace"]
    if compiles or retraces:
        steps = ", ".join(str(e.get("step", "?")) for e in retraces)
        out.append(f"  compiles observed: {len(compiles)}; retraces: "
                   f"{len(retraces)}"
                   + (f" at step(s) {steps} — the step function recompiled "
                      "mid-run (weak-type/shape leak into carried state; "
                      "see graft-lint signature_stability)"
                      if retraces else ""))
    mems = [e for e in perf if e["event"] == "perf_memory"]
    if mems:
        m = mems[-1]
        peak = m.get("peak_bytes_in_use")
        cur = m.get("bytes_in_use")
        bits = []
        if peak is not None:
            bits.append(f"peak {peak:,d} B")
        if cur is not None:
            bits.append(f"in use {cur:,d} B")
        out.append(f"  device memory watermark (max over "
                   f"{m.get('n_devices', '?')} devices): "
                   + ", ".join(bits))
    feet = [e for e in perf if e["event"] == "perf_state_footprint"]
    if feet:
        f = feet[-1]
        out.append(
            f"  GraceState footprint: mem {f.get('mem_bytes', 0):,d} B, "
            f"comp {f.get('comp_bytes', 0):,d} B, "
            f"telem {f.get('telem_bytes', 0):,d} B")
        if "footprint_matches" in f:
            out.append("  footprint vs codec model: "
                       + ("matches" if f["footprint_matches"] else
                          "MISMATCH — live state was built under a "
                          "different config than reported"))
    if not out:
        out.append("  (perf records present but empty)")
    return out


def build_doc(provenance, records, events,
              metrics: Optional[List[str]] = None) -> dict:
    """Machine-readable twin of :func:`render` — the ``--json`` document
    CI consumes instead of scraping the text layout."""
    numeric = sorted({k for r in records for k, v in r.items()
                      if isinstance(v, (int, float))
                      and not isinstance(v, bool) and k != "step"})
    cols = [m for m in (metrics or PREFERRED)
            if any(m in r for r in records)]
    cols += [k for k in numeric if k not in cols and metrics is None]
    stats = {}
    for m in cols:
        vals = [float(r[m]) for r in records if m in r]
        if vals:
            stats[m] = _stats(vals)
    steps = [r["step"] for r in records if "step" in r]
    doc = {
        "provenance": provenance,
        "records": len(records),
        "step_span": [min(steps), max(steps)] if steps else None,
        "dropped_steps": sum(r.get("dropped_steps", 0) for r in records),
        "metrics": stats,
        "fallback_windows": [list(w) for w in fallback_windows(records)],
        "guard_counters": ({k: records[-1][k] for k in sorted(records[-1])
                            if k.startswith("guard_")} if records else {}),
        "watch_summaries": [e for e in events if e.get("event") == "watch"],
        "watch_anomalies": [e for e in events
                            if e.get("event") == "watch_anomaly"],
        "perf_events": [e for e in events
                        if str(e.get("event", "")).startswith("perf_")],
        "lint_findings": [e for e in events
                          if e.get("event") == "lint_finding"],
        "adapt_events": [e for e in events
                         if str(e.get("event", "")).startswith("adapt")],
        "retune_events": [e for e in events
                          if str(e.get("event", "")).startswith("retune")],
        "guard_events": [e for e in events
                         if e.get("event") not in ("watch", "watch_anomaly",
                                                   "lint_finding")
                         and not str(e.get("event", "")).startswith("perf_")
                         and not str(e.get("event", "")).startswith("adapt")
                         and not str(e.get("event", "")).startswith(
                             "retune")],
    }
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", help="telemetry JSONL file (JSONLSink output)")
    ap.add_argument("--metrics", default=None,
                    help="comma-separated metric subset to summarize")
    ap.add_argument("--json", action="store_true",
                    help="emit one machine-readable JSON document instead "
                         "of the text report")
    args = ap.parse_args(argv)
    provenance, records, events = load(args.path)
    metrics = args.metrics.split(",") if args.metrics else None
    if args.json:
        print(json.dumps(build_doc(provenance, records, events, metrics),
                         indent=1))
    else:
        print(render(provenance, records, events, metrics))
    return 0 if (records or events) else 1


if __name__ == "__main__":
    sys.exit(main())
