#!/usr/bin/env python
"""perf-report CLI: performance attribution from a saved profiler trace.

Renders what ``grace_tpu.profiling.trace_analysis`` extracts from a
``jax.profiler`` artifact (``trace.json.gz`` or raw ``xplane.pb``, or a
profile directory): the per-stage device-time table over the canonical
``grace/...`` vocabulary (summing exactly to total device time), the
compute-vs-collective split, the **overlap fraction** (collective time
hidden under compute — the number the bench projection model assumes is
zero), and step-time percentiles from the trace's step markers.

Optionally gates against a stored baseline with a tolerance band (the
graft-lint idiom: measured perf facts become CI-checkable), and writes the
``PROF_LAST.json`` evidence document ``tools/evidence_summary.py`` renders.

Pure host-side: runs on a CPU-only box with no devices against a saved
trace (pinned by tests/test_profiling.py on the canned fixture
``tests/data/perf_trace.json.gz``).

Exit status: 0 clean, 1 baseline regression, 2 crash — CI-gateable.

Usage::

    python tools/perf_report.py --trace profiles/topk1pct
    python tools/perf_report.py --trace tests/data/perf_trace.json.gz
    python tools/perf_report.py --trace t.json.gz --write-baseline PROF_BASELINE.json
    python tools/perf_report.py --trace t.json.gz --baseline PROF_BASELINE.json
    python tools/perf_report.py --trace t.json.gz --json
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_OUT = os.path.join(ROOT, "PROF_LAST.json")

# Tolerance band of the baseline gate. Relative for times (a step-time or
# stage-time growth beyond rtol is a regression), absolute for the overlap
# fraction (already a ratio; a 5-point drop means hidden collective time
# became exposed wall-clock). Improvements never fail.
DEFAULT_RTOL = 0.10
STAGE_ATOL_MS = 0.05          # ignore sub-50µs stage jitter
OVERLAP_ATOL = 0.05


def compare_to_baseline(current: dict, baseline: dict,
                        rtol: float) -> list:
    """Regression findings of ``current`` (an ``TraceAnalysis.as_dict``)
    against a stored baseline of the same shape. Time-like metrics regress
    upward; overlap fraction regresses downward."""
    findings = []

    def worse(name, cur, base, atol=0.0):
        if cur is None or base is None:
            return
        if cur > base * (1.0 + rtol) + atol:
            findings.append(
                f"{name}: {cur:.3f} vs baseline {base:.3f} "
                f"(+{100.0 * (cur / base - 1.0) if base else 0.0:.1f}%, "
                f"tolerance {100.0 * rtol:.0f}%)")

    cur_steps = current.get("step_times") or {}
    base_steps = baseline.get("step_times") or {}
    worse("step p50 ms", cur_steps.get("p50_ms"), base_steps.get("p50_ms"))
    worse("step p99 ms", cur_steps.get("p99_ms"), base_steps.get("p99_ms"))
    worse("total device ms", current.get("total_device_ms"),
          baseline.get("total_device_ms"))
    base_stages = baseline.get("stages_ms") or {}
    for stage, base_ms in sorted(base_stages.items()):
        worse(f"stage {stage} ms",
              (current.get("stages_ms") or {}).get(stage),
              base_ms, atol=STAGE_ATOL_MS)
    cur_ov = current.get("overlap_fraction")
    base_ov = baseline.get("overlap_fraction")
    if cur_ov is not None and base_ov is not None \
            and cur_ov < base_ov - OVERLAP_ATOL:
        findings.append(
            f"overlap fraction: {cur_ov:.3f} vs baseline {base_ov:.3f} "
            f"(collective time that used to hide under compute is now "
            f"exposed; tolerance {OVERLAP_ATOL:.2f} absolute)")
    return findings


def baseline_view(analysis_dict: dict) -> dict:
    """The comparable subset of an analysis, for --write-baseline."""
    return {
        "step_times": analysis_dict.get("step_times"),
        "total_device_ms": analysis_dict.get("total_device_ms"),
        "stages_ms": analysis_dict.get("stages_ms"),
        "overlap_fraction": analysis_dict.get("overlap_fraction"),
        "trace": analysis_dict.get("trace"),
        "captured_at": _now(),
    }


def _now() -> str:
    return datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")


def _atomic_write(path: str, doc: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--trace", required=True,
                    help="profiler artifact (trace.json.gz / xplane.pb) "
                         "or a profile directory (newest capture wins)")
    ap.add_argument("--baseline", default=None,
                    help="stored baseline JSON to gate against "
                         "(--write-baseline output)")
    ap.add_argument("--rtol", type=float, default=DEFAULT_RTOL,
                    help="relative tolerance of the baseline gate "
                         f"(default {DEFAULT_RTOL})")
    ap.add_argument("--write-baseline", default=None,
                    help="write the comparable metric subset to this path "
                         "and exit clean")
    ap.add_argument("--overlap-config", default=None,
                    help="registry config name (tools/graft_lint.py --list) "
                         "to sandwich the trace's measured overlap fraction "
                         "against: measured must stay <= graft-flow's "
                         "static schedulability bound (+slack) for that "
                         "config's traced dataflow; a violation means the "
                         "capture's attribution is lying and exits 1")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON document instead of text")
    ap.add_argument("--out", default=DEFAULT_OUT,
                    help="evidence document path ('' disables; default "
                         "PROF_LAST.json at the repo root, consumed by "
                         "tools/evidence_summary.py)")
    args = ap.parse_args(argv)

    # The analyzer is pure host-side (stdlib + numpy over a saved trace),
    # but grace_tpu imports jax at package load — pin CPU so a box with a
    # latched TPU tunnel never blocks on backend init for an offline report.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from grace_tpu.profiling import analyze_trace

    analysis = analyze_trace(args.trace)
    doc = analysis.as_dict()
    if os.sep + os.path.join("tests", "data") + os.sep in \
            os.path.abspath(str(doc.get("trace") or "")):
        doc["note"] = ("canned CPU fixture trace — pipeline evidence, "
                       "not a chip capture")

    regressions = []
    if args.baseline:
        with open(args.baseline) as f:
            baseline = json.load(f)
        regressions = compare_to_baseline(doc, baseline, args.rtol)
        doc["baseline"] = args.baseline
        doc["baseline_rtol"] = args.rtol
        doc["regressions"] = regressions

    if args.overlap_config:
        # The measured<=possible overlap sandwich: graft-flow's
        # schedulability pass computes the byte-weighted static upper bound
        # the config's dataflow permits, and judges THIS capture's measured
        # overlap against it (meta['measured_overlap'] — the same hook the
        # lint tests use). A measured fraction above the bound is not the
        # scheduler over-performing; it is the trace attribution lying.
        from grace_tpu.analysis import AUDIT_CONFIGS, build_grace, \
            overlap_summary, trace_update
        from grace_tpu.analysis.flow import (OVERLAP_SLACK,
                                             pass_overlap_schedulability)
        entry = next((e for e in AUDIT_CONFIGS
                      if e["name"] == args.overlap_config), None)
        if entry is None:
            print(f"unknown config {args.overlap_config!r}; "
                  "tools/graft_lint.py --list shows the registry",
                  file=sys.stderr)
            return 2
        measured = doc.get("overlap_fraction")
        grace = build_grace(entry)
        traced = trace_update(
            grace, name=entry["name"],
            meta={"grace": grace, "measured_overlap": measured})
        bound = overlap_summary(traced)["static_overlap_bound"]
        sandwich = {
            "config": entry["name"],
            "measured_overlap": measured,
            "static_overlap_bound": (round(bound, 6)
                                     if bound is not None else None),
            "slack": OVERLAP_SLACK,
        }
        violations = [f.message for f in pass_overlap_schedulability(traced)
                      if "measured overlap" in f.message]
        sandwich["violations"] = violations
        doc["overlap_sandwich"] = sandwich
        regressions = regressions + violations

    if args.write_baseline:
        _atomic_write(args.write_baseline, baseline_view(doc))
        print(f"[perf_report] baseline -> {args.write_baseline}",
              file=sys.stderr)

    if args.out:
        evidence = {"tool": "perf_report", **doc, "captured_at": _now()}
        try:
            _atomic_write(args.out, evidence)
        except OSError as e:
            print(f"[perf_report] could not save {args.out}: {e}",
                  file=sys.stderr)

    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(analysis.render())
        if args.overlap_config:
            s = doc["overlap_sandwich"]
            print()
            print(f"overlap sandwich vs {s['config']}: measured="
                  f"{s['measured_overlap']} <= static bound="
                  f"{s['static_overlap_bound']} (+{s['slack']} slack): "
                  + ("VIOLATED" if s["violations"] else "holds"))
        if args.baseline:
            print()
            if regressions:
                print(f"BASELINE REGRESSIONS ({len(regressions)}) vs "
                      f"{args.baseline}:")
                for r in regressions:
                    print(f"  REGRESSION {r}")
            else:
                print(f"baseline {args.baseline}: within tolerance "
                      f"(rtol {args.rtol})")
    return 1 if regressions else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:                                 # noqa: BLE001
        print(f"[perf_report] crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
