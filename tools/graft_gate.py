#!/usr/bin/env python
"""graft-gate: audit README/CHANGELOG claims against the evidence ledger.

ROADMAP item 1's ``evidence_gate`` CI mode: every quantitative headline
ratio in README.md/CHANGELOG.md must sit in a paragraph carrying a claim
marker (``<!-- evidence: <ledger-id> -->``), and every cited ledger
record must verify — capture file hash unchanged, provenance rev an
ancestor of HEAD (``git merge-base --is-ancestor``), claim class
consistent with the capture's device count. Verdicts render as
MEASURED / PROJECTED / STALE badges.

Usage:
  python tools/graft_gate.py                 # report (exit 0 always)
  python tools/graft_gate.py --ci            # exit 1 on unmarked claims
                                             # or STALE citations
  python tools/graft_gate.py --update-readme # splice the badge block
  python tools/graft_gate.py --backfill      # mint ledger records from
                                             # the committed artifacts
  python tools/graft_gate.py --json          # machine-readable report

Exit status: 0 gate passes (or report-only mode); 1 gate failures under
--ci; 2 crashed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[1])
    ap.add_argument("--ci", action="store_true",
                    help="exit 1 on unmarked quantitative claims or "
                         "STALE citations")
    ap.add_argument("--update-readme", action="store_true",
                    help="splice the MEASURED/PROJECTED/STALE badge "
                         "block into README.md")
    ap.add_argument("--backfill", action="store_true",
                    help="mint ledger records for committed artifacts "
                         "not yet in the ledger")
    ap.add_argument("--json", action="store_true",
                    help="print the full report as JSON")
    ap.add_argument("--root", default=ROOT,
                    help="repo root to audit (default: this repo)")
    ap.add_argument("--ledger", default=None,
                    help="ledger path (default: <root>/EVIDENCE/"
                         "ledger.jsonl)")
    args = ap.parse_args(argv)

    from grace_tpu.evidence.backfill import backfill_ledger
    from grace_tpu.evidence.gate import gate_report, splice_badges

    if args.backfill:
        appended = backfill_ledger(args.root, args.ledger, verbose=True)
        print(f"[graft_gate] backfill appended {len(appended)} record(s)")

    report = gate_report(args.root, args.ledger)

    if args.update_readme:
        changed = splice_badges(os.path.join(args.root, "README.md"),
                                report)
        print(f"[graft_gate] README badge block "
              f"{'updated' if changed else 'unchanged'}")

    if args.json:
        slim = {
            "ok": report["ok"],
            "failures": report["failures"],
            "records": {cid: {"status": r["status"],
                              "failures": r["failures"],
                              "notes": r["notes"]}
                        for cid, r in report["records"].items()},
            "claims": {doc: {"n_claims": len(scan["claims"]),
                             "n_unmarked": len(scan["unmarked"])}
                       for doc, scan in report["docs"].items()},
        }
        print(json.dumps(slim, indent=1))
    else:
        for doc, scan in sorted(report["docs"].items()):
            print(f"[graft_gate] {doc}: {len(scan['claims'])} "
                  f"quantitative claim line(s), "
                  f"{len(scan['unmarked'])} unmarked")
        for cid, res in sorted(report["records"].items()):
            rec = res.get("record") or {}
            print(f"  {res['status']:<9} {cid}  "
                  f"[{rec.get('claim_class', '?')}] "
                  f"{rec.get('metric', 'no-record')}")
            for f in res["failures"]:
                print(f"            ! {f}")
        if report["failures"]:
            print(f"[graft_gate] {len(report['failures'])} gate "
                  f"failure(s):")
            for f in report["failures"]:
                print(f"  FAIL {f}")
        else:
            print("[graft_gate] gate clean: every claim marked, every "
                  "citation verifies")

    if args.ci and not report["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:                                 # noqa: BLE001
        print(f"[graft_gate] crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
