"""Capture a jax.profiler trace of the headline bench configs on the chip.

VERDICT round-2 item 2's contingency: if the TPU compressed/dense ratio
lands below the 0.90 target, the next step is a device trace of the Top-K
1% step on the fused 25.5M-element buffer (prime suspects: approx_max_k on
the full buffer, the scatter in decompress — grace_tpu/ops/sparse.py).
This script reuses bench.py's measurement core but wraps the timed window
in a profiler trace so the per-op timeline is on disk for offline analysis
even after the tunnel dies again. `--report` runs the shared trace analyzer
(grace_tpu.profiling.trace_analysis — the same stage attribution, overlap
fraction, and step percentiles tools/perf_report.py gates CI with) against
the newest saved capture; it needs no devices, so the report works on any
CPU box holding the profiles directory.

Usage (on the chip):  python tools/tpu_profile.py [--config topk1pct]
Offline anywhere:     python tools/tpu_profile.py --report [--outdir profiles]
Output: profiles/<config>/plugins/profile/... (xplane + trace.json.gz)
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def profile_config(cfg_name: str, outdir: str) -> None:
    import jax

    cfg = next(c for c in bench.HEADLINE if c["name"] == cfg_name)
    captured = []

    def emit(row):
        captured.append(row)

    # Build + warm up via the shared core, but trace only a short window:
    # bench_configs compiles and measures; we re-run a few steps under the
    # profiler afterwards using the same jitted step via a tiny shim.
    devices = bench.setup_platform("tpu")
    import jax.numpy as jnp
    import numpy as np
    import optax

    from grace_tpu.parallel import batch_sharded, data_parallel_mesh
    from grace_tpu import grace_from_params
    from grace_tpu.models import resnet
    from grace_tpu.train import (init_stateful_train_state,
                                 make_stateful_train_step)

    mesh = data_parallel_mesh(devices)
    grace = grace_from_params(cfg["params"])
    optimizer = optax.chain(grace.transform(seed=0), optax.sgd(1e-3))

    def loss_fn(params, mstate, batch):
        x, y = batch
        logits, new_mstate = resnet.apply(
            params, mstate, x.astype(jnp.bfloat16), train=True)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, y)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    params, mstate = resnet.init(jax.random.key(0), depth=50,
                                 num_classes=1000)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    n = 32 * len(devices)
    rng = np.random.default_rng(0)
    batch = jax.device_put(
        (jnp.asarray(rng.standard_normal((n, 224, 224, 3)), jnp.float32),
         jnp.asarray(rng.integers(0, 1000, (n,)), jnp.int32)),
        batch_sharded(mesh))

    for _ in range(3):                       # compile + settle
        ts, loss = step(ts, batch)
    float(loss)

    os.makedirs(outdir, exist_ok=True)
    with jax.profiler.trace(outdir):
        for _ in range(5):
            ts, loss = step(ts, batch)
        float(loss)
    print(f"[profile] {cfg_name}: trace -> {outdir}", file=sys.stderr)


def report(outdir: str) -> None:
    """Stage-attributed report of the newest capture under ``outdir`` via
    the shared trace analyzer — per-stage device time (canonical
    ``grace/...`` vocabulary), compute/collective split, overlap fraction,
    step percentiles. Works offline on CPU against a saved trace; the
    ad-hoc top-ops-by-name summary this replaces could not attribute time
    to pipeline stages nor see overlap at all."""
    from grace_tpu.profiling import analyze_trace, find_latest_trace

    path = find_latest_trace(outdir)
    if path is None:
        print(f"no *.trace.json.gz / *.xplane.pb under {outdir}",
              file=sys.stderr)
        return
    analysis = analyze_trace(path)
    print(f"{path}:")
    print(analysis.render())


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="one headline config (default: both)")
    ap.add_argument("--outdir", default="profiles")
    ap.add_argument("--report", action="store_true",
                    help="summarize existing traces instead of capturing")
    args = ap.parse_args()
    names = [args.config] if args.config else [c["name"]
                                               for c in bench.HEADLINE]
    for name in names:
        d = os.path.join(args.outdir, name)
        if args.report:
            report(d)
        else:
            profile_config(name, d)


if __name__ == "__main__":
    main()
