#!/usr/bin/env python
"""graft-lint CLI: static SPMD collective auditor + repo rule engine.

Traces registered codec x communicator x resilience configs to jaxprs on an
AbstractMesh (no devices, CPU-only, CI-safe) and runs the ten audit
passes — the four jaxpr walkers (collective consistency across cond
branches, bit-exactness of cross-replica reductions, wire-byte
reconciliation against Communicator.recv_wire_bytes, retrace/host-sync
sniffing), the three graft-flow dependence-graph passes (overlap
schedulability: static overlap bounds and independent compress→exchange
chain counting; numeric-range safety: fp16 accumulation overflow, vote
integer-exactness, index/pack-width contracts; HBM footprint: GraceState
accounting vs the config's own eval_shape model, replicated-O(W) buffers),
and the three graft-sound stateful-semantics passes (rng lineage:
independent stochastic sites must consume independently derived,
replicated PRNG keys; rollback coverage: every state leaf a guarded step
writes is restored by a rollback select or declared written-through;
replication contract: replicated GraceState fields provably leave the
step replicated, and the field-role constants agree with partition_specs)
— plus the AST-level repo rules (compressor capability declarations,
telemetry FIELDS reducers, pytest marker registration, GraceState
field-role coverage). See grace_tpu/analysis/ and IMPLEMENTING.md "What
graft-lint checks and why".

A full-matrix run lands LINT_LAST.json and attaches it to the evidence
ledger (id ``lint-clean``, claim_class measured) so README lint-clean
claims can carry ``<!-- evidence: -->`` markers through the graft-gate.

Exit status: 0 clean, 1 findings, 2 crash — CI-gateable.

Usage::

    python tools/graft_lint.py                   # repo rules + core configs
    python tools/graft_lint.py --all-configs     # the full compat matrix
    python tools/graft_lint.py --config topk-ring --config qsgd-ring
    python tools/graft_lint.py --all-configs --passes numeric_safety
    python tools/graft_lint.py --all-configs \
        --passes rng_lineage,rollback_coverage,replication_contract
    python tools/graft_lint.py --all-configs --json
    python tools/graft_lint.py --all-configs --jsonl lint_findings.jsonl
    python tools/graft_lint.py --list            # show registry names
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The headline subset audited on a default (argument-free) run: one config
# per communicator family plus the resilience stack — fast enough for a
# pre-commit hook; --all-configs is the CI spelling.
CORE_CONFIGS = ("topk-allgather", "none-allreduce", "qsgd-ring",
                "topk-twoshot", "signsgd-sign_allreduce",
                "topk-allgather-bucketed", "qsgd4-allgather-packed",
                "topk-escape-telemetry", "topk-guard-consensus")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--all-configs", action="store_true",
                    help="audit the full registered compat matrix "
                         "(default: repo rules + a core subset)")
    ap.add_argument("--config", action="append", default=[],
                    help="audit only the named registry config(s)")
    ap.add_argument("--rules-only", action="store_true",
                    help="run only the AST repo rules (no tracing)")
    ap.add_argument("--no-rules", action="store_true",
                    help="skip the AST repo rules")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (intersected with "
                         "each config's own pass selection; configs with "
                         "an empty intersection are skipped)")
    ap.add_argument("--evidence", default=None,
                    help="where --all-configs writes its LINT_LAST.json "
                         "evidence (default: the repo root copy)")
    ap.add_argument("--world", type=int, default=8,
                    help="abstract mesh size to trace at (default 8)")
    ap.add_argument("--json", action="store_true",
                    help="emit a JSON document instead of text")
    ap.add_argument("--jsonl", default=None,
                    help="also append findings as lint_finding events to "
                         "this JSONL file (telemetry_report.py-compatible)")
    ap.add_argument("--list", action="store_true",
                    help="list registered config names and exit")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    if os.environ["JAX_PLATFORMS"].lower() == "cpu":
        # Tracing never executes anything, but the dev image's
        # sitecustomize may have latched a TPU tunnel — pin CPU.
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    from grace_tpu.analysis import (AUDIT_CONFIGS, PASS_NAMES, audit_all,
                                    render_text, findings_to_json,
                                    run_repo_rules, write_jsonl, RULE_NAMES)

    if args.list:
        for entry in AUDIT_CONFIGS:
            print(f"{entry['name']:30s} mode={entry['mode']:6s} "
                  f"passes={','.join(entry['passes'])}")
        return 0

    if args.config:
        by_name = {e["name"]: e for e in AUDIT_CONFIGS}
        unknown = [n for n in args.config if n not in by_name]
        if unknown:
            print(f"unknown config(s) {unknown}; --list shows the registry",
                  file=sys.stderr)
            return 2
        configs = [by_name[n] for n in args.config]
    elif args.all_configs:
        configs = list(AUDIT_CONFIGS)
    else:
        configs = [e for e in AUDIT_CONFIGS if e["name"] in CORE_CONFIGS]
    if args.passes:
        selected = tuple(p.strip() for p in args.passes.split(",")
                         if p.strip())
        unknown = [p for p in selected if p not in PASS_NAMES]
        if unknown:
            print(f"unknown pass(es) {unknown}; registered: "
                  f"{', '.join(PASS_NAMES)}", file=sys.stderr)
            return 2
        configs = [dict(e, passes=tuple(p for p in e["passes"]
                                        if p in selected))
                   for e in configs]
        configs = [e for e in configs if e["passes"]]
    if args.rules_only:
        configs = []

    findings = []
    rules_checked = 0
    if not args.no_rules:
        findings.extend(run_repo_rules())
        rules_checked = len(RULE_NAMES)
    progress = None
    if not args.json:
        progress = lambda name: print(f"[graft_lint] tracing {name}",  # noqa: E731
                                      file=sys.stderr, flush=True)
    findings.extend(audit_all(configs, world=args.world, progress=progress))

    if args.all_configs and not args.rules_only:
        # Evidence artifact (same incremental-evidence idiom as the bench
        # files): the last full-matrix lint verdict, consumed by
        # tools/evidence_summary.py. Atomic tmp+replace like the rest of
        # the evidence flow.
        import datetime
        import json as _json
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # Per-pass finding counts over every pass that could have run —
        # zeros are evidence too (a pass that ran clean is a different
        # statement than a pass that never ran); consumed by
        # tools/evidence_summary.py.
        passes_run = sorted({p for e in configs for p in e["passes"]})
        pass_counts = {p: sum(1 for f in findings if f.pass_name == p)
                       for p in passes_run}
        # Static overlap bounds for every bucketed (fusion=<int>) config:
        # the static half of the measured<=possible overlap sandwich, kept
        # in the evidence so a later chip capture (tools/perf_report.py
        # --overlap-config) is judged against the bound the lint run that
        # blessed the schedule actually computed.
        from grace_tpu.analysis import overlap_bound_report
        overlap_bounds = {}
        for e in configs:
            try:
                rep = overlap_bound_report(e, world=args.world)
            except Exception as err:            # noqa: BLE001
                rep = {"error": f"{type(err).__name__}: {err}"}
            if rep is not None:
                overlap_bounds[e["name"]] = rep
        doc = {
            "tool": "graft_lint",
            "errors": sum(1 for f in findings if f.severity == "error"),
            "warnings": sum(1 for f in findings if f.severity != "error"),
            "configs_audited": len(configs),
            "rules_checked": rules_checked,
            "world": args.world,
            "passes_run": passes_run,
            "pass_counts": pass_counts,
            "overlap_bounds": overlap_bounds,
            "findings": [f.as_dict() for f in findings],
            "captured_at": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
        }
        path = args.evidence or os.path.join(root, "LINT_LAST.json")
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                _json.dump(doc, f, indent=1)
                f.write("\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as e:
            print(f"[graft_lint] could not save {path}: {e}",
                  file=sys.stderr)
        else:
            if os.path.dirname(os.path.abspath(path)) == root:
                # Ledger-attach the repo-root artifact (same idiom as the
                # bench/chaos evidence writers): the README's lint-clean
                # claim cites this record through the graft-gate. Ad-hoc
                # --evidence paths stay off the ledger, like ad-hoc bench
                # output paths do.
                from grace_tpu.evidence.ledger import record_artifact
                record_artifact(
                    path, id="lint-clean", metric="configs_lint_clean",
                    value=doc["configs_audited"], claim_class="measured",
                    tool="graft_lint", platform="cpu", chip="cpu",
                    n_devices=args.world,
                    config=" ".join(sys.argv[1:] if argv is None
                                    else argv) or None,
                    lint_clean=(doc["errors"] == 0),
                    passes_run=passes_run)

    if args.jsonl:
        try:
            from grace_tpu.utils.logging import run_provenance
            provenance = run_provenance(data="static", tool="graft_lint",
                                        argv=" ".join(sys.argv[1:]))
        except Exception:
            provenance = None
        write_jsonl(findings, args.jsonl, provenance=provenance)
    if args.json:
        print(findings_to_json(findings, audited=len(configs),
                               rules_checked=rules_checked))
    else:
        print(render_text(findings, audited=len(configs),
                          rules_checked=rules_checked))
    return 1 if any(f.severity == "error" for f in findings) else 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:                                 # noqa: BLE001
        print(f"[graft_lint] crashed: {type(e).__name__}: {e}",
              file=sys.stderr)
        sys.exit(2)
