# Reproducible environment for grace-tpu — the analog of the reference's
# Dockerfile + environment.yml (reference Dockerfile:1-10 builds on a
# horovod image and patches it; here the "native stack" is jax + libtpu,
# so a plain Python base suffices).
#
# Two targets, mirroring the reference's gpu/cpu image pair:
#   docker build --target tpu -t grace-tpu .       # TPU VM (libtpu)
#   docker build --target cpu -t grace-tpu:cpu .   # CPU-only dev/test
#
# NOTE: authored and lint-checked in an offline environment (no docker
# daemon, zero egress); the pinned wheels in requirements.lock are the
# exact versions the test suite and benches ran against, so the build is
# expected to be deterministic, but the Dockerfile itself is untested.

FROM python:3.12-slim AS base
WORKDIR /grace
# g++/cmake/ninja: the native data loader (native/dataloader.cpp) builds
# at install time via setup hooks or on first use through ctypes.
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ cmake ninja-build make && rm -rf /var/lib/apt/lists/*
COPY requirements.lock pyproject.toml README.md ./
COPY grace_tpu ./grace_tpu
COPY native ./native
COPY examples ./examples
RUN pip install --no-cache-dir -r requirements.lock && \
    pip install --no-cache-dir -e .

# CPU-only image: simulated multi-device meshes for dev and CI
# (tests run with XLA_FLAGS=--xla_force_host_platform_device_count=8).
FROM base AS cpu
ENV JAX_PLATFORMS=cpu
CMD ["python", "-c", "import grace_tpu, jax; print(jax.devices())"]

# TPU image: run on a TPU VM (the libtpu pin in requirements.lock provides
# the runtime; the VM's /dev/accel* devices must be mapped in).
FROM base AS tpu
CMD ["python", "-c", "import grace_tpu, jax; print(jax.devices())"]
