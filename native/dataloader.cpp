// grace-tpu native data pipeline: threaded, prefetching batch loader.
//
// TPU-native replacement for the host-side input machinery the reference
// delegates to torch (DataLoader worker processes + DistributedSampler,
// examples/torch/pytorch_mnist.py:63-70) and tf.data. The training step is
// one jitted XLA program, so the host's only job is to keep batches ready
// ahead of device consumption — exactly what this library does: worker
// threads assemble normalized float32 batches into a bounded queue while
// the previous step runs on the TPU.
//
// Design:
//   * Dataset: raw samples held in memory as uint8 (images) + int32 labels.
//     Loaders for MNIST idx(.gz) and CIFAR-10 binary batches; arbitrary
//     in-memory datasets can be registered from the host language.
//   * Sampler: per-epoch Fisher-Yates shuffle from a counter-based seed
//     (seed, epoch) — deterministic and identical on every process — then
//     rank r takes the strided slice r::world (the DistributedSampler
//     contract, so ranks partition each epoch disjointly).
//   * Pipeline: N worker threads claim batch indices from an atomic
//     counter, normalize ((x - mean·255)/(std·255), same fp32 op order as MemoryDataset.normalize so results are bit-identical) into preallocated slots of a
//     bounded ring, and a consumer thread hands slots to the caller in
//     batch order. Backpressure via condition variables, capacity fixed at
//     queue_depth batches.
//
// C ABI (for ctypes): every function returns 0 on success, negative on
// error; gl_last_error() describes the most recent failure per handle-less
// thread.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <zlib.h>

namespace {

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

// ---------------------------------------------------------------------------
// File readers
// ---------------------------------------------------------------------------

bool read_file_maybe_gz(const std::string& path, std::vector<uint8_t>* out) {
  // gzread transparently handles both plain and gzip files.
  gzFile f = gzopen(path.c_str(), "rb");
  if (!f) {
    set_error("cannot open " + path);
    return false;
  }
  out->clear();
  constexpr size_t kChunk = 1 << 20;
  std::vector<uint8_t> buf(kChunk);
  int n;
  while ((n = gzread(f, buf.data(), kChunk)) > 0) {
    out->insert(out->end(), buf.data(), buf.data() + n);
  }
  gzclose(f);
  if (n < 0) {
    set_error("read error on " + path);
    return false;
  }
  return true;
}

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// ---------------------------------------------------------------------------
// Dataset
// ---------------------------------------------------------------------------

struct Dataset {
  std::vector<uint8_t> images;  // n * h * w * c, NHWC
  std::vector<int32_t> labels;  // n
  int64_t n = 0, h = 0, w = 0, c = 0;
  float mean[3] = {0, 0, 0};
  float stddiv[3] = {255, 255, 255};

  int64_t sample_size() const { return h * w * c; }
};

bool exists(const std::string& p) {
  if (FILE* f = fopen(p.c_str(), "rb")) {
    fclose(f);
    return true;
  }
  return false;
}

std::string pick(const std::string& base) {
  if (exists(base)) return base;
  if (exists(base + ".gz")) return base + ".gz";
  return "";
}

bool load_mnist(const std::string& dir, bool train, Dataset* ds) {
  const std::string prefix = train ? "train" : "t10k";
  std::string ip = pick(dir + "/" + prefix + "-images-idx3-ubyte");
  std::string lp = pick(dir + "/" + prefix + "-labels-idx1-ubyte");
  if (ip.empty() || lp.empty()) {
    set_error("MNIST idx files not found under " + dir);
    return false;
  }
  std::vector<uint8_t> ib, lb;
  if (!read_file_maybe_gz(ip, &ib) || !read_file_maybe_gz(lp, &lb))
    return false;
  if (ib.size() < 16 || be32(ib.data()) != 2051) {
    set_error("bad idx image magic in " + ip);
    return false;
  }
  if (lb.size() < 8 || be32(lb.data()) != 2049) {
    set_error("bad idx label magic in " + lp);
    return false;
  }
  ds->n = be32(ib.data() + 4);
  ds->h = be32(ib.data() + 8);
  ds->w = be32(ib.data() + 12);
  ds->c = 1;
  if ((int64_t)ib.size() - 16 < ds->n * ds->sample_size()) {
    set_error("truncated " + ip);
    return false;
  }
  ds->images.assign(ib.begin() + 16,
                    ib.begin() + 16 + ds->n * ds->sample_size());
  ds->labels.resize(ds->n);
  for (int64_t i = 0; i < ds->n; ++i) ds->labels[i] = lb[8 + i];
  ds->mean[0] = 0.1307f * 255.0f;
  ds->stddiv[0] = 0.3081f * 255.0f;
  return true;
}

bool load_cifar10(const std::string& dir, bool train, Dataset* ds) {
  std::vector<std::string> names;
  if (train) {
    for (int i = 1; i <= 5; ++i)
      names.push_back(dir + "/data_batch_" + std::to_string(i) + ".bin");
  } else {
    names.push_back(dir + "/test_batch.bin");
  }
  ds->h = ds->w = 32;
  ds->c = 3;
  ds->n = 0;
  constexpr int64_t kRec = 3073;  // label + 3*32*32 CHW
  for (const auto& name : names) {
    std::vector<uint8_t> raw;
    if (!read_file_maybe_gz(name, &raw)) return false;
    if (raw.size() % kRec) {
      set_error("bad CIFAR record size in " + name);
      return false;
    }
    int64_t records = raw.size() / kRec;
    for (int64_t r = 0; r < records; ++r) {
      const uint8_t* rec = raw.data() + r * kRec;
      ds->labels.push_back(rec[0]);
      // CHW -> HWC
      for (int64_t y = 0; y < 32; ++y)
        for (int64_t x = 0; x < 32; ++x)
          for (int64_t ch = 0; ch < 3; ++ch)
            ds->images.push_back(rec[1 + ch * 1024 + y * 32 + x]);
    }
    ds->n += records;
  }
  const float mean[3] = {0.4914f, 0.4822f, 0.4465f};
  const float stdv[3] = {0.2471f, 0.2435f, 0.2616f};
  for (int i = 0; i < 3; ++i) {
    ds->mean[i] = mean[i] * 255.0f;
    ds->stddiv[i] = stdv[i] * 255.0f;
  }
  return true;
}

// ---------------------------------------------------------------------------
// Loader: sampler + prefetch pipeline
// ---------------------------------------------------------------------------

struct Slot {
  std::vector<float> x;
  std::vector<int32_t> y;
  int64_t batch_idx = -1;
  bool ready = false;
};

struct Loader {
  Dataset ds;
  int64_t batch = 0;
  int64_t rank = 0, world = 1;
  uint64_t seed = 0;
  bool shuffle = true;
  bool drop_last = true;

  // epoch state
  int64_t epoch = -1;
  std::vector<int64_t> order;       // this rank's sample order for the epoch
  int64_t batches_per_epoch = 0;

  // pipeline
  std::vector<std::thread> workers;
  std::vector<Slot> slots;
  std::mutex mu;
  std::condition_variable cv_ready, cv_free;
  std::atomic<int64_t> next_claim{0};
  int64_t next_serve = 0;
  bool stopping = false;

  ~Loader() { stop(); }

  void stop() {
    {
      std::lock_guard<std::mutex> l(mu);
      stopping = true;
    }
    cv_free.notify_all();
    cv_ready.notify_all();
    for (auto& t : workers) {
      if (t.joinable()) t.join();
    }
    workers.clear();
  }

  void build_epoch(int64_t e) {
    epoch = e;
    std::vector<int64_t> perm(ds.n);
    for (int64_t i = 0; i < ds.n; ++i) perm[i] = i;
    if (shuffle) {
      std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + uint64_t(e));
      for (int64_t i = ds.n - 1; i > 0; --i) {
        std::uniform_int_distribution<int64_t> d(0, i);
        std::swap(perm[i], perm[d(rng)]);
      }
    }
    order.clear();
    for (int64_t i = rank; i < ds.n; i += world) order.push_back(perm[i]);
    int64_t local = (int64_t)order.size();
    batches_per_epoch =
        drop_last ? local / batch : (local + batch - 1) / batch;
  }

  void fill(Slot* s, int64_t b) {
    const int64_t ss = ds.sample_size();
    const int64_t start = b * batch;
    const int64_t count =
        std::min<int64_t>(batch, (int64_t)order.size() - start);
    s->x.resize(batch * ss);
    s->y.resize(batch);
    for (int64_t j = 0; j < batch; ++j) {
      // Short final batch wraps deterministically (only when !drop_last).
      const int64_t src = order[start + (j % std::max<int64_t>(count, 1))];
      const uint8_t* img = ds.images.data() + src * ss;
      float* out = s->x.data() + j * ss;
      const int64_t cc = ds.c;
      for (int64_t p = 0; p < ss; ++p) {
        const int64_t ch = p % cc;
        out[p] = (float(img[p]) - ds.mean[ch]) / ds.stddiv[ch];
      }
      s->y[j] = ds.labels[src];
    }
    s->batch_idx = b;
  }

  void worker() {
    for (;;) {
      // Acquire a slot FIRST, then claim the next batch index. Claiming
      // before holding a slot can deadlock: with more workers than slots,
      // the slot-holders may all hold batches ahead of next_serve while
      // the worker owning next_serve starves for a slot the consumer will
      // never free. Claim-after-acquire bounds outstanding batch claims to
      // the slot count, so the consumer's next batch always has a slot.
      Slot* slot = nullptr;
      {
        std::unique_lock<std::mutex> l(mu);
        for (;;) {
          if (stopping) return;
          if (next_claim.load() >= batches_per_epoch) return;
          for (auto& s : slots) {
            if (!s.ready && s.batch_idx == -1) {
              s.batch_idx = -2;  // claimed
              slot = &s;
              break;
            }
          }
          if (slot) break;
          cv_free.wait(l);
        }
      }
      int64_t b = next_claim.fetch_add(1);
      if (b >= batches_per_epoch) {
        std::lock_guard<std::mutex> l(mu);
        slot->batch_idx = -1;  // release unused slot
        cv_free.notify_all();
        return;
      }
      fill(slot, b);
      {
        std::lock_guard<std::mutex> l(mu);
        slot->ready = true;
      }
      cv_ready.notify_all();
    }
  }

  void start_epoch(int64_t e, int64_t n_threads, int64_t queue_depth) {
    stop();
    {
      std::lock_guard<std::mutex> l(mu);
      stopping = false;
    }
    build_epoch(e);
    next_claim = 0;
    next_serve = 0;
    slots.assign(std::max<int64_t>(queue_depth, 2), Slot{});
    workers.clear();
    for (int64_t i = 0; i < std::max<int64_t>(n_threads, 1); ++i)
      workers.emplace_back([this] { worker(); });
  }

  // Returns 1 and fills (x, y) if a batch was produced; 0 at epoch end.
  int next(float* x, int32_t* y) {
    if (next_serve >= batches_per_epoch) return 0;
    Slot* slot = nullptr;
    {
      std::unique_lock<std::mutex> l(mu);
      for (;;) {
        if (stopping) return -1;
        for (auto& s : slots) {
          if (s.ready && s.batch_idx == next_serve) {
            slot = &s;
            break;
          }
        }
        if (slot) break;
        cv_ready.wait(l);
      }
    }
    std::memcpy(x, slot->x.data(), slot->x.size() * sizeof(float));
    std::memcpy(y, slot->y.data(), slot->y.size() * sizeof(int32_t));
    {
      std::lock_guard<std::mutex> l(mu);
      slot->ready = false;
      slot->batch_idx = -1;
      ++next_serve;
    }
    cv_free.notify_all();
    return 1;
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

const char* gl_last_error() { return g_error.c_str(); }

// kind: 0 = MNIST idx, 1 = CIFAR-10 binary
void* gl_open(int kind, const char* dir, int train, int64_t batch,
              int shuffle, int drop_last, uint64_t seed, int64_t rank,
              int64_t world) {
  auto* ld = new Loader();
  bool ok = kind == 0 ? load_mnist(dir, train != 0, &ld->ds)
                      : load_cifar10(dir, train != 0, &ld->ds);
  if (!ok) {
    delete ld;
    return nullptr;
  }
  ld->batch = batch;
  ld->shuffle = shuffle != 0;
  ld->drop_last = drop_last != 0;
  ld->seed = seed;
  ld->rank = rank;
  ld->world = world;
  return ld;
}

// Register an in-memory uint8 NHWC dataset (for synthetic/custom data).
void* gl_open_memory(const uint8_t* images, const int32_t* labels, int64_t n,
                     int64_t h, int64_t w, int64_t c, const float* mean,
                     const float* stdv, int64_t batch, int shuffle,
                     int drop_last, uint64_t seed, int64_t rank,
                     int64_t world) {
  auto* ld = new Loader();
  Dataset& ds = ld->ds;
  ds.n = n;
  ds.h = h;
  ds.w = w;
  ds.c = c;
  ds.images.assign(images, images + n * h * w * c);
  ds.labels.assign(labels, labels + n);
  for (int i = 0; i < 3; ++i) {
    ds.mean[i] = mean ? mean[i] * 255.0f : 0.0f;
    ds.stddiv[i] = stdv ? stdv[i] * 255.0f : 255.0f;
  }
  ld->batch = batch;
  ld->shuffle = shuffle != 0;
  ld->drop_last = drop_last != 0;
  ld->seed = seed;
  ld->rank = rank;
  ld->world = world;
  return ld;
}

void gl_shape(void* h, int64_t* n, int64_t* hh, int64_t* ww, int64_t* cc) {
  auto* ld = static_cast<Loader*>(h);
  *n = ld->ds.n;
  *hh = ld->ds.h;
  *ww = ld->ds.w;
  *cc = ld->ds.c;
}

int64_t gl_start_epoch(void* h, int64_t epoch, int64_t n_threads,
                       int64_t queue_depth) {
  auto* ld = static_cast<Loader*>(h);
  ld->start_epoch(epoch, n_threads, queue_depth);
  return ld->batches_per_epoch;
}

int gl_next(void* h, float* x, int32_t* y) {
  return static_cast<Loader*>(h)->next(x, y);
}

void gl_close(void* h) { delete static_cast<Loader*>(h); }

}  // extern "C"
