"""Bucketed overlap executor (transform.py ``fusion=<int bytes>``, ISSUE 10).

The acceptance criteria pinned here: the executor's K per-bucket pipelines
are numerically the flat-fusion step for exact codecs (bit-identical on
integer grads — no tolerance can hide a bucket-boundary bug); the traced
graph exposes EXACTLY the bucketing plan's K independent compress→exchange
chains (graft-flow's schedulability contract); resilience stays step-atomic
across the split (guard NaN in one bucket rolls back every bucket's state,
consensus is a bit-exact no-op over a healthy bucketed run); telemetry wire
accounting equals the sum of per-bucket collective prices — incl. the
ici/dcn split — and still reconciles with the whole-payload
``recv_wire_bytes`` model within ``WIRE_MODEL_RTOL``; and a REAL profiler
capture of a bucketed run satisfies the measured ≤ static-bound overlap
sandwich with per-bucket stages attributed.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from grace_tpu import grace_from_params
from grace_tpu.analysis import AUDIT_CONFIGS, build_grace, trace_update
from grace_tpu.analysis.flow import (OVERLAP_SLACK, _expected_chains,
                                     overlap_summary,
                                     pass_overlap_schedulability)
from grace_tpu.core import WIRE_MODEL_RTOL, Topology
from grace_tpu.parallel import shard_map
from grace_tpu.resilience import ConsensusConfig, audit_report, guarded_chain
from grace_tpu.telemetry import TelemetryReader
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import (_bucketize, fusion_payload_structs)
from grace_tpu.utils.metrics import guard_report, payload_nbytes

pytestmark = pytest.mark.bucketed

W = 8
BATCH, DIM, CLASSES = 64, 20, 4

# w is DIM*CLASSES*4 = 320 B, b is 16 B: fusion=128 buckets them as
# [[w], [b]] — K=2 pipelines with visibly different payload sizes, so
# per-bucket wire pricing cannot accidentally pass via symmetry.
BUCKET_BYTES = 128


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * W, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _update_once(mesh, cfg, grads):
    """One bare transform update inside shard_map over per-rank integer
    gradients ``grads`` (dict of (W, ...) arrays); returns rank 0's
    aggregated updates."""
    grc = grace_from_params(dict(cfg))
    tx = grc.transform(seed=1)

    def body(g):
        g = jax.tree_util.tree_map(lambda l: l[0], g)
        state = tx.init(g)
        out, _ = tx.update(g, state, None)
        return jax.tree_util.tree_map(lambda l: l[None], out)

    fn = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                   out_specs=P("data"), check_vma=False)
    out = fn(grads)
    return jax.tree_util.tree_map(lambda l: np.asarray(l[0]), out)


# ---------------------------------------------------------------------------
# numerics: bucketed == flat for exact codecs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("compressor", ["none", "fp16"])
def test_bucketed_bit_identical_to_flat_for_exact_codecs(mesh, compressor):
    """Integer-valued grads: every intermediate sum is exactly
    representable, so the K-bucket step must match the flat-fusion step
    BIT-for-bit — psum is elementwise, and the executor only changed which
    collective each element rides, never its arithmetic."""
    rng = np.random.default_rng(0)
    grads = {"w": jnp.asarray(rng.integers(-8, 9, size=(W, DIM, CLASSES)),
                              jnp.float32),
             "b": jnp.asarray(rng.integers(-8, 9, size=(W, CLASSES)),
                              jnp.float32)}
    base = {"compressor": compressor, "memory": "none",
            "communicator": "allreduce"}
    flat = _update_once(mesh, {**base, "fusion": "flat"}, grads)
    bucketed = _update_once(mesh, {**base, "fusion": BUCKET_BYTES}, grads)
    for k in grads:
        np.testing.assert_array_equal(flat[k], bucketed[k])


def test_bucketize_plan_is_two_buckets():
    """The K this file's configs promise — pinned so a plan change cannot
    silently turn the tests below into K=1 trivia."""
    buckets, _ = _bucketize([((DIM, CLASSES), jnp.float32),
                             ((CLASSES,), jnp.float32)], BUCKET_BYTES)
    assert buckets == [[0], [1]]


# ---------------------------------------------------------------------------
# schedulability: exactly K independent chains, pinned per registry config
# ---------------------------------------------------------------------------

def test_depgraph_tags_both_bucket_chains():
    """The executor's grace/bucket/<b> scopes reach the traced equations:
    build_depgraph records a distinct chain tag per bucket (the tag chain
    counting separates train-mode pipelines by), and each bucket's
    exchange collective carries its own bucket's tag."""
    from grace_tpu.analysis.flow import build_depgraph
    from grace_tpu.telemetry.scopes import STAGE_EXCHANGE

    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "topk-allgather-bucketed")
    grace = build_grace(entry)
    traced = trace_update(grace, name=entry["name"], meta={"grace": grace})
    g = build_depgraph(traced)
    tags = {n.chain for n in g.nodes if n.chain is not None}
    assert tags == {"grace/bucket/0", "grace/bucket/1"}
    ex_tags = {n.chain for n in g.nodes
               if n.collective and n.stage == STAGE_EXCHANGE}
    assert ex_tags == {"grace/bucket/0", "grace/bucket/1"}


@pytest.mark.parametrize("name", ["topk-allgather-bucketed",
                                  "qsgd4-ring-packed-bucketed"])
def test_registered_bucketed_config_exposes_exactly_k_chains(name):
    """Acceptance: graft-flow reports K = len(_bucketize) independent
    compress→exchange chains on the executor's traced graph — no more (a
    payload's several wire tensors group into one chain per bucket), no
    fewer (a serialization point would fail the pass)."""
    entry = next(e for e in AUDIT_CONFIGS if e["name"] == name)
    grace = build_grace(entry)
    traced = trace_update(grace, name=name, meta={"grace": grace})
    from grace_tpu.analysis.trace import default_param_structs
    structs = list(default_param_structs().values())
    buckets, _ = _bucketize([(s.shape, s.dtype) for s in structs],
                            int(entry["params"]["fusion"]))
    s = overlap_summary(traced)
    assert _expected_chains(traced) == len(buckets) == 2
    assert s["independent_chains"] == len(buckets)
    assert pass_overlap_schedulability(traced) == []


# ---------------------------------------------------------------------------
# resilience across the split
# ---------------------------------------------------------------------------

def _guarded_build(mesh, cfg, consensus=None, lr=0.3, **guard_kw):
    grc = grace_from_params(dict(cfg))
    tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=consensus)
    return state, step


def _grace_of(state):
    return state.opt_state.inner[0]


BUCKETED_EF = {"compressor": "topk", "compress_ratio": 0.3,
               "memory": "residual", "communicator": "allgather",
               "fusion": BUCKET_BYTES, "escape": "fp16"}


def test_guard_nan_in_one_bucket_rolls_back_whole_step(mesh):
    """NaN reaching only bucket 0 (w's gradient; b's gradient is a clean
    zero) must skip the WHOLE step atomically: bucket 1's exchange landed
    fine, but committing it alone would desync the two buckets' error
    feedback — params and BOTH buckets' mem/comp stay bitwise-identical."""
    def loss_fn(params, batch):
        x, _ = batch
        # b's gradient is identically zero (finite); only w sees the data.
        return jnp.mean(x @ params["w"]) + jnp.sum(params["b"]) * 0.0

    x, y = _problem()
    grc = grace_from_params(dict(BUCKETED_EF))
    tx = guarded_chain(grc, optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    for _ in range(3):
        state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    before = state
    g_before = _grace_of(before)
    assert len(g_before.mem) == 2          # two buckets -> two residuals

    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan                    # rank 0's shard only
    state, _ = step(state, (jnp.asarray(xbad), y))

    rep = guard_report(state)
    assert rep["notfinite_count"] == 1
    assert _leaves_equal(before.params, state.params)
    g0, g1 = _grace_of(before), _grace_of(state)
    assert _leaves_equal(g0.mem, g1.mem)     # bucket 1 rolled back too
    assert _leaves_equal(g0.comp, g1.comp)
    assert _leaves_equal(g0.count, g1.count)

    state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    assert guard_report(state)["notfinite_count"] == 1


def test_consensus_noop_over_healthy_bucketed_run(mesh):
    """The audit (fingerprint gather + untaken repair cond) over the
    bucketed executor's post-apply state must not perturb a bit."""
    x, y = _problem()
    cfg = dict(BUCKETED_EF, consensus=True)
    consensus = ConsensusConfig(audit_every=2)
    s_on, step_on = _guarded_build(mesh, cfg, consensus=consensus)
    s_off, step_off = _guarded_build(mesh, BUCKETED_EF)
    for _ in range(6):
        s_on, l_on = step_on(s_on, (x, y))
        s_off, l_off = step_off(s_off, (x, y))
        assert float(l_on) == float(l_off)
    assert _leaves_equal(s_on.params, s_off.params)
    rep = audit_report(s_on)
    assert rep["audits"] >= 2
    assert rep["repairs"] == 0


# ---------------------------------------------------------------------------
# telemetry: per-bucket wire accounting
# ---------------------------------------------------------------------------

def _per_bucket_link_sum(grc, params, world, topo):
    """The model the executor's telemetry claims: each bucket's collective
    priced separately through recv_link_bytes, summed."""
    leaves = jax.tree_util.tree_leaves(params)
    vote = bool(getattr(grc.compressor, "vote_aggregate", False))
    ici = dcn = 0
    for s, count in fusion_payload_structs(leaves, grc.fusion):
        lb = grc.communicator.recv_link_bytes(
            payload_nbytes(grc.compressor, s),
            int(np.prod(s.shape, dtype=np.int64)), world,
            topology=topo, vote=vote)
        ici += count * lb.ici
        dcn += count * lb.dcn
    return ici, dcn


@pytest.mark.telemetry
@pytest.mark.parametrize("communicator", ["allgather", "ring"])
def test_telemetry_wire_bytes_sum_per_bucket(mesh, communicator):
    """Acceptance: per-step telemetry wire bytes equal the SUM of
    per-bucket collective prices (each bucket is its own exchange), the
    ici+dcn split identity survives, and the per-bucket sum still
    reconciles with the whole-payload recv_wire_bytes model within
    WIRE_MODEL_RTOL."""
    cfg = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": communicator,
           "fusion": BUCKET_BYTES, "telemetry": True}
    x, y = _problem()
    grc = grace_from_params(dict(cfg))
    tx = optax.chain(grc.transform(seed=1), optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    reader = TelemetryReader(sink=None, every=4)
    rows = []
    for i in range(4):
        state, _ = step(state, (x, y))
        rows += [r for r in reader.update(i, state)
                 if "wire_bytes" in r]
    params = _init_params()
    ici, dcn = _per_bucket_link_sum(grc, params, W, Topology())
    assert rows, "no telemetry rows flushed"
    for rec in rows:
        assert rec["wire_bytes"] == ici + dcn
        assert rec["wire_bytes_ici"] == ici
        assert rec["wire_bytes_dcn"] == dcn
    # ...and the sum-of-buckets stays inside the whole-payload model's
    # documented tolerance (the auditor reconciles THAT model against the
    # traced schedule, so the two views can never drift apart silently).
    from grace_tpu.transform import fusion_payload_nbytes
    leaves = jax.tree_util.tree_leaves(params)
    _, comp_b, n_elems = fusion_payload_nbytes(grc.compressor, leaves,
                                               grc.fusion)
    whole = grc.communicator.recv_wire_bytes(comp_b, n_elems, W)
    assert abs((ici + dcn) - whole) <= WIRE_MODEL_RTOL * whole + 256


@pytest.mark.telemetry
def test_watch_gather_folds_over_bucketed_run(mesh):
    """graft-watch over the bucketed executor: boundary rows carry the
    per-bucket wire sum PLUS the health gather's bytes, and the
    ici+dcn == wire_bytes identity survives the fold."""
    from grace_tpu.telemetry.aggregate import watch_gather_bytes

    cfg = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": "allgather",
           "fusion": BUCKET_BYTES, "telemetry": True, "watch": 2}
    x, y = _problem()
    grc = grace_from_params(dict(cfg))
    tx = optax.chain(grc.transform(seed=1), optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    reader = TelemetryReader(sink=None, every=4)
    rows = []
    for i in range(4):
        state, _ = step(state, (x, y))
        rows += [r for r in reader.update(i, state)
                 if "wire_bytes" in r]
    ici, dcn = _per_bucket_link_sum(grc, _init_params(), W, Topology())
    gb = watch_gather_bytes(W)
    assert rows
    for rec in rows:
        boundary = rec["step"] % 2 == 0
        assert rec["watch_bytes"] == (gb if boundary else 0.0)
        assert rec["wire_bytes"] == ici + dcn + (gb if boundary else 0.0)
        assert rec["wire_bytes_ici"] + rec["wire_bytes_dcn"] \
            == rec["wire_bytes"]


@pytest.mark.telemetry
def test_telemetry_split_per_bucket_under_sliced_topology(mesh):
    """slice_size=4 on the 8-way mesh: the hierarchical communicator's
    mixed ici/dcn split is priced per bucket and summed — the split
    refines the scalar bucket-by-bucket, leg-by-leg."""
    cfg = {"compressor": "topk", "compress_ratio": 0.3,
           "topk_algorithm": "chunk", "memory": "residual",
           "communicator": "hier", "slice_size": 4,
           "fusion": BUCKET_BYTES, "telemetry": True}
    x, y = _problem()
    grc = grace_from_params(dict(cfg))
    tx = optax.chain(grc.transform(seed=1), optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    reader = TelemetryReader(sink=None, every=2)
    rows = []
    for i in range(2):
        state, _ = step(state, (x, y))
        rows += [r for r in reader.update(i, state.opt_state)
                 if "wire_bytes" in r]
    ici, dcn = _per_bucket_link_sum(grc, _init_params(), W,
                                    Topology(slice_size=4))
    assert rows
    assert dcn > 0                       # the cross-slice leg is real
    for rec in rows:
        assert rec["wire_bytes_ici"] == ici
        assert rec["wire_bytes_dcn"] == dcn
        assert rec["wire_bytes"] == ici + dcn


# ---------------------------------------------------------------------------
# the measured <= static-bound sandwich on a REAL capture
# ---------------------------------------------------------------------------

def test_real_bucketed_capture_overlap_sandwich(mesh, tmp_path):
    """Capture a real profiler trace of the bucketed config's train step,
    attribute it with graft-prof, and close the loop: the measured overlap
    fraction must sit under graft-flow's static schedulability bound
    (+slack), and the capture must show the executor's per-bucket stages —
    the two halves of ROADMAP item 2's 'make overlap real' evidence."""
    from grace_tpu.profiling import analyze_trace

    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "topk-allgather-bucketed")
    grace = build_grace(entry)
    tx = optax.chain(grace.transform(seed=1), optax.sgd(0.3))
    # The capture's model must BE the audited config's model (the default
    # param structs), so the static bound talks about the captured graph.
    from grace_tpu.analysis.trace import default_param_structs
    rng = np.random.default_rng(0)
    params = {k: jnp.asarray(rng.normal(size=s.shape).astype(np.float32))
              for k, s in default_param_structs().items()}

    dim, classes = params["w"].shape          # the default (60, 8) model

    def loss_fn(p, batch):
        x, y = batch
        logits = x @ p["w"] + p["b"][:classes]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    x = jnp.asarray(rng.normal(size=(W * 8, dim)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, classes, size=(W * 8,)))
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    state, loss = step(state, (x, y))        # compile outside the capture
    with jax.profiler.trace(str(tmp_path)):
        for _ in range(3):
            state, loss = step(state, (x, y))
        jax.block_until_ready(loss)

    analysis = analyze_trace(str(tmp_path))
    doc = analysis.as_dict()
    # The canonical pipeline stages are attributed in the REAL capture
    # (the per-bucket scopes nest OUTSIDE these; their own ops fuse into
    # stage ops on XLA:CPU, so the bucket tags are asserted on the traced
    # graph in test_depgraph_tags_both_bucket_chains instead).
    assert any(s.startswith("grace/") for s in (doc.get("stages_ms") or {}))
    measured = doc.get("overlap_fraction")
    traced = trace_update(grace, name=entry["name"],
                          meta={"grace": grace,
                                "measured_overlap": measured})
    bound = overlap_summary(traced)["static_overlap_bound"]
    assert bound is not None
    if measured is not None:
        assert measured <= bound + OVERLAP_SLACK
    # The lint pass agrees the capture is honest (no 'lying profile').
    assert [f for f in pass_overlap_schedulability(traced)
            if "measured overlap" in f.message] == []


def test_perf_report_overlap_config_cli(tmp_path, capsys):
    """tools/perf_report.py --overlap-config: sandwich recorded in the
    evidence doc, exit 0 when it holds, exit 2 on an unknown config."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "perf_report", os.path.join(os.path.dirname(__file__), "..",
                                    "tools", "perf_report.py"))
    perf_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(perf_report)
    trace = os.path.join(os.path.dirname(__file__), "data",
                         "perf_trace.json.gz")
    out = tmp_path / "PROF.json"
    rc = perf_report.main(["--trace", trace, "--out", str(out),
                           "--overlap-config", "topk-allgather-bucketed"])
    assert rc == 0
    doc = json.loads(out.read_text())
    s = doc["overlap_sandwich"]
    assert s["config"] == "topk-allgather-bucketed"
    assert s["measured_overlap"] == pytest.approx(0.25)
    assert s["static_overlap_bound"] is not None
    assert s["violations"] == []
    capsys.readouterr()
    assert perf_report.main(["--trace", trace, "--out", "",
                             "--overlap-config", "no-such-config"]) == 2
