"""Fusion-buffer tests: bucketed/flat gradient exchange.

The Horovod fusion buffer (SURVEY.md §2.4) is opaque C++; here fusion is an
explicit, testable transform option. Key properties: exactness for linear
codecs, convergence for sparsifiers, bucketing plan correctness, and dtype
round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import _bucketize


class TestBucketize:
    def test_flat_is_one_bucket(self):
        buckets, _ = _bucketize([((10,), jnp.float32), ((5, 5), jnp.float32)],
                                None)
        assert buckets == [[0, 1]]

    def test_byte_limit_splits(self):
        specs = [((100,), jnp.float32)] * 3  # 400B each
        buckets, _ = _bucketize(specs, 500)
        assert buckets == [[0], [1], [2]]
        buckets, _ = _bucketize(specs, 800)
        assert buckets == [[0, 1], [2]]

    def test_oversized_leaf_own_bucket(self):
        specs = [((10,), jnp.float32), ((1000,), jnp.float32),
                 ((10,), jnp.float32)]
        buckets, _ = _bucketize(specs, 100)
        assert buckets == [[0], [1], [2]]

    def test_common_dtype_promotion(self):
        _, dt = _bucketize([((4,), jnp.bfloat16), ((4,), jnp.float32)], None)
        assert dt == jnp.float32

    # The plan below is a PINNED contract: the static auditor's
    # schedulability pass (grace_tpu.analysis.flow) derives the promised
    # number of independent compress→exchange chains from this exact
    # bucket count and ordering, so a plan change is an API change.

    def test_empty_leaf_list_yields_no_buckets(self):
        """No leaves → no buckets, in BOTH modes (one empty bucket would
        make the fused update concatenate nothing), dtype defaults f32."""
        for bucket_bytes in (None, 512):
            buckets, dt = _bucketize([], bucket_bytes)
            assert buckets == []
            assert dt == jnp.float32

    def test_single_leaf_larger_than_bucket_is_one_bucket(self):
        """One leaf over the limit: exactly one bucket holding it — never
        split (whole leaves only), never dropped."""
        buckets, _ = _bucketize([((1000,), jnp.float32)], 64)
        assert buckets == [[0]]

    def test_oversized_leaf_keeps_count_and_ordering(self):
        """Oversized leaf in front: it closes its own bucket and the rest
        re-pack after it — bucket count and leaf ordering are pinned."""
        specs = [((1000,), jnp.float32)] + [((10,), jnp.float32)] * 3
        buckets, _ = _bucketize(specs, 100)          # 40 B each after [0]
        assert buckets == [[0], [1, 2], [3]]         # greedy: 80+40 > 100
        # concatenating the buckets is always the identity permutation
        assert [i for b in buckets for i in b] == list(range(len(specs)))

    def test_mixed_dtype_bucket_promotion_prices_at_common_dtype(self):
        """A bf16+f32 mix promotes to f32 and the byte accounting uses the
        PROMOTED itemsize: 100 bf16 elements cost 400 B in the bucket, so
        two of them no longer fit an 800 B bucket alongside an f32 leaf."""
        specs = [((100,), jnp.bfloat16), ((100,), jnp.bfloat16),
                 ((100,), jnp.float32)]
        buckets, dt = _bucketize(specs, 800)
        assert dt == jnp.float32
        assert buckets == [[0, 1], [2]]              # 400+400, then 400
        # at bf16's native itemsize all three would have fit — pin that
        # the plan does NOT do that
        assert buckets != [[0, 1, 2]]


def _make_problem(rng, n=64):
    x = rng.standard_normal((n * 8, 12)).astype(np.float32)
    w = rng.standard_normal((12, 3)).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _params(rng):
    return {"w": jnp.asarray(rng.standard_normal((12, 3)).astype(np.float32)
                             * 0.1),
            "b": jnp.zeros((3,), jnp.float32)}


def _train(mesh, cfg, steps=30, lr=0.2, seed=0):
    rng = np.random.default_rng(seed)
    batch = _make_problem(rng)
    grc = grace_from_params(cfg)
    tx = optax.chain(grc.transform(seed=1), optax.sgd(lr))
    state = init_train_state(_params(rng), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state


class TestFusedTraining:
    def test_flat_none_matches_per_leaf_exactly(self, mesh):
        """Uncompressed exchange is linear: fused == per-leaf bit-for-bit."""
        base_cfg = {"compressor": "none", "memory": "none",
                    "communicator": "allreduce"}
        l0, s0 = _train(mesh, base_cfg, steps=5)
        l1, s1 = _train(mesh, {**base_cfg, "fusion": "flat"}, steps=5)
        np.testing.assert_allclose(np.asarray(s0.params["w"]),
                                   np.asarray(s1.params["w"]), rtol=1e-6)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)

    @pytest.mark.parametrize("fusion", ["flat", 256])
    def test_topk_fused_converges(self, mesh, fusion):
        losses, _ = _train(mesh, {"compressor": "topk", "compress_ratio": 0.3,
                                  "memory": "residual",
                                  "communicator": "allgather",
                                  "fusion": fusion}, steps=40)
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_topk_twoshot_fused_converges(self, mesh):
        """The bench's topk1pct_twoshot config: flat fusion hands the
        two-shot communicator ONE whole-model buffer to chunk."""
        losses, _ = _train(mesh, {"compressor": "topk",
                                  "compress_ratio": 0.3,
                                  "memory": "residual",
                                  "communicator": "twoshot",
                                  "fusion": "flat"}, steps=40)
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_qsgd_fused_converges(self, mesh):
        losses, _ = _train(mesh, {"compressor": "qsgd", "quantum_num": 64,
                                  "memory": "none",
                                  "communicator": "allgather",
                                  "fusion": "flat"}, steps=40)
        assert losses[-1] < losses[0] * 0.7

    def test_fused_state_is_per_bucket(self, mesh):
        _, state = _train(mesh, {"compressor": "topk", "compress_ratio": 0.3,
                                 "memory": "residual",
                                 "communicator": "allgather",
                                 "fusion": "flat"}, steps=3)
        grace_state = state.opt_state[0]
        assert len(grace_state.mem) == 1  # one bucket -> one residual buffer
        # world axis: 8 ranks x 39 fused elements (12*3 + 3)
        assert grace_state.mem[0].shape == (8, 39)

    def test_mixed_dtype_roundtrip(self, mesh):
        rng = np.random.default_rng(0)
        grc = grace_from_params({"compressor": "none", "memory": "none",
                                 "communicator": "allreduce",
                                 "fusion": "flat"})
        tx = optax.chain(grc.transform(), optax.sgd(0.1))
        params = {"w": jnp.zeros((4, 3), jnp.bfloat16),
                  "b": jnp.zeros((3,), jnp.float32)}

        def loss_fn(p, batch):
            x, y = batch
            return jnp.mean((x.astype(jnp.bfloat16) @ p["w"]).astype(
                jnp.float32) + p["b"] - y) ** 2

        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        x = jnp.asarray(rng.standard_normal((16, 4)), jnp.float32)
        y = jnp.asarray(rng.standard_normal((16, 3)), jnp.float32)
        state, loss = step(state, (x, y))
        assert state.params["w"].dtype == jnp.bfloat16
        assert state.params["b"].dtype == jnp.float32
        assert jnp.isfinite(loss)

    def test_invalid_fusion_rejected(self):
        grc = grace_from_params({"compressor": "none", "memory": "none",
                                 "communicator": "allreduce",
                                 "fusion": "banana"})
        with pytest.raises(ValueError, match="fusion"):
            grc.transform()


def _mlp_params(rng):
    """Three same-shaped hidden layers + a head: exercises grouped fusion's
    shape grouping (hidden weights form one group of 3, biases one of 3)."""
    def mat(shape):
        # ~1/sqrt(fan_in) scale: 0.1 starved 3 stacked ReLU layers of signal
        # (activations shrink ~10x per layer; even the dense control stalls)
        return jnp.asarray(rng.standard_normal(shape).astype(np.float32) * 0.3)
    return {"h1": mat((12, 12)), "h2": mat((12, 12)), "h3": mat((12, 12)),
            "b1": jnp.zeros((12,)), "b2": jnp.zeros((12,)),
            "b3": jnp.zeros((12,)), "w": mat((12, 3)),
            "b": jnp.zeros((3,), jnp.float32)}


def _mlp_loss(params, batch):
    x, y = batch
    for i in (1, 2, 3):
        x = jax.nn.relu(x @ params[f"h{i}"] + params[f"b{i}"])
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _train_mlp(mesh, cfg, steps=5, lr=0.2, seed=0):
    rng = np.random.default_rng(seed)
    batch = _make_problem(rng)
    grc = grace_from_params(cfg)
    tx = optax.chain(grc.transform(seed=1), optax.sgd(lr))
    state = init_train_state(_mlp_params(np.random.default_rng(1)), tx, mesh)
    step = make_train_step(_mlp_loss, tx, mesh, donate=False)
    losses = []
    for _ in range(steps):
        state, loss = step(state, batch)
        losses.append(float(loss))
    return losses, state


class TestGroupedFusion:
    """fusion='grouped': same-shaped leaves vmapped as one batched pipeline.

    Per-tensor semantics are exact (vmap is just batching), so for codecs
    that ignore the rng (none, topk, warm-start PowerSGD) grouped must match
    fusion=None bit-for-bit despite the different key derivation."""

    @pytest.mark.parametrize("cfg", [
        {"compressor": "none", "memory": "none", "communicator": "allreduce"},
        {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
         "communicator": "allgather"},
        {"compressor": "powersgd", "compress_rank": 2, "memory": "powersgd",
         "communicator": "allreduce"},
    ], ids=["none", "topk", "powersgd"])
    def test_grouped_matches_per_leaf_exactly(self, mesh, cfg):
        l0, s0 = _train_mlp(mesh, cfg, steps=5)
        l1, s1 = _train_mlp(mesh, {**cfg, "fusion": "grouped"}, steps=5)
        for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(l0, l1, rtol=1e-6)

    def test_grouped_stochastic_converges(self, mesh):
        losses, _ = _train_mlp(mesh, {"compressor": "qsgd",
                                      "quantum_num": 64,
                                      "memory": "residual",
                                      "communicator": "allgather",
                                      "fusion": "grouped"}, steps=60)
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

    def test_grouped_state_is_per_group(self, mesh):
        _, state = _train_mlp(mesh, {"compressor": "topk",
                                     "compress_ratio": 0.3,
                                     "memory": "residual",
                                     "communicator": "allgather",
                                     "fusion": "grouped"}, steps=3)
        grace_state = state.opt_state[0]
        # leaf order is sorted dict keys (b, b1-3, h1-3, w), so the groups
        # are (3,)x1, (12,)x3, (12,12)x3, (12,3)x1
        assert len(grace_state.mem) == 4
        # world axis 8, then the group axis
        assert grace_state.mem[1].shape == (8, 3, 12)
        assert grace_state.mem[2].shape == (8, 3, 12, 12)

    def test_grouped_state_mismatch_raises(self, mesh):
        cfg = {"compressor": "topk", "compress_ratio": 0.3,
               "memory": "residual", "communicator": "allgather"}
        rng = np.random.default_rng(0)
        batch = _make_problem(rng)
        grc_g = grace_from_params({**cfg, "fusion": "grouped"})
        tx_g = optax.chain(grc_g.transform(seed=1), optax.sgd(0.1))
        grc_p = grace_from_params(cfg)
        tx_p = optax.chain(grc_p.transform(seed=1), optax.sgd(0.1))
        state = init_train_state(_mlp_params(np.random.default_rng(1)),
                                 tx_p, mesh)   # per-leaf state...
        # 8 per-leaf entries vs 4 shape groups: the count check must raise
        # the intended re-init message, not an opaque vmap batch error.
        step = make_train_step(_mlp_loss, tx_g, mesh, donate=False)
        with pytest.raises(ValueError,
                           match="different fusion setting.*Re-init"):
            step(state, batch)   # ...fed to a grouped transform

    def test_grouped_state_count_coincidence_raises(self, mesh):
        """All-distinct-shaped leaves make the per-leaf state count EQUAL
        the grouped group count (one leaf per group), so the old
        len()-only check passed a stale state straight into vmap. The
        per-group stacked-leading-dim validation must catch it with the
        same re-init message."""
        cfg = {"compressor": "topk", "compress_ratio": 0.3,
               "memory": "residual", "communicator": "allgather"}
        rng = np.random.default_rng(0)
        batch = _make_problem(rng)
        grc_g = grace_from_params({**cfg, "fusion": "grouped"})
        tx_g = optax.chain(grc_g.transform(seed=1), optax.sgd(0.1))
        grc_p = grace_from_params(cfg)
        tx_p = optax.chain(grc_p.transform(seed=1), optax.sgd(0.1))
        # w (12,3) and b (3,): two distinct shapes -> 2 groups == 2 leaves
        state = init_train_state(_params(np.random.default_rng(1)),
                                 tx_p, mesh)   # per-leaf state, count 2
        step = make_train_step(_loss_fn, tx_g, mesh, donate=False)
        with pytest.raises(ValueError,
                           match="leading dim.*different fusion setting"):
            step(state, batch)

    @pytest.mark.parametrize("communicator", ["twoshot", "ring"])
    def test_grouped_shard_parallel_rejected(self, communicator):
        """fusion='grouped' x a shard-parallel communicator is an untraced
        path (vmapping the all_to_all/ppermute schedule): build-time
        ValueError naming the supported families, not a silent trace."""
        grc = grace_from_params({"compressor": "topk",
                                 "compress_ratio": 0.3,
                                 "memory": "residual",
                                 "communicator": communicator,
                                 "fusion": "grouped"})
        with pytest.raises(ValueError, match="shard-parallel|grouped"):
            grc.transform()
