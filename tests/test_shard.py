"""graft-shard (ISSUE 14): compressed reduce-scatter on 2-D dp×fsdp meshes
with per-leaf codec routing.

Covers the acceptance criteria end to end:

* bit-identity — ``rscatter`` with exact codecs (none/fp16) matches the
  1-D allgather path bitwise on integer grads; the homomorphic codec
  matches the ring's payload-space summation bitwise (same stage-1 shard
  encode, same integer sums); the requant path is bit-identical to
  TwoShot's single re-encode;
* degenerate collapse — a W×1 fsdp-degenerate mesh reproduces today's
  1-D behavior bitwise, and every registered config's state structure is
  unchanged under a 2-D MeshSpec;
* 2-D lint seeding — the per-axis replication analysis blesses the legal
  fsdp-varying-predicate/dp-collective shape and condemns a seeded
  WRONG-AXIS replication bug (predicate psummed over fsdp, still
  dp-varying, gating a dp-collective cond) live;
* routing — per-leaf codec routing resolves the right triads, prices the
  wire as the sum of per-leaf models, and refuses non-per-leaf fusion;
* the transformer track wins on the model — the routed rscatter BERT
  config's per-link xslice projection is >1.0× vs dense at W≥64 where
  the committed flat BERT row (bert_powersgd_r4) is the 0.80× before-
  picture.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu.parallel import data_parallel_mesh, make_mesh, shard_map
from grace_tpu.transform import MeshSpec, partition_specs

pytestmark = pytest.mark.shard

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _update_once(params_cfg, grads, mesh, in_spec=P("data")):
    """One grace_transform update on integer-valued grads inside
    shard_map; returns the aggregated updates."""
    g = grace_from_params(params_cfg)
    tx = g.transform(0)

    def body(gr):
        state = tx.init(gr)
        out, _ = tx.update(gr, state, None)
        return out

    f = shard_map(body, mesh=mesh, in_specs=(in_spec,),
                  out_specs=in_spec, check_vma=False)
    return np.asarray(jax.jit(f)(grads))


@pytest.fixture(scope="module")
def int_grads():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.integers(-8, 8, (8, 64)), jnp.float32)


# ---------------------------------------------------------------------------
# bit-identity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("compressor", ["none", "fp16"])
def test_rscatter_exact_bit_identical_to_allgather(mesh, int_grads,
                                                   compressor):
    """Exact codecs: payload-space sum then decode == per-rank decode
    then sum, bitwise on integer grads (small ints are exact in fp16)."""
    a = _update_once({"compressor": compressor, "memory": "none",
                      "communicator": "rscatter", "fusion": "flat"},
                     int_grads, mesh)
    b = _update_once({"compressor": compressor, "memory": "none",
                      "communicator": "allgather", "fusion": "flat"},
                     int_grads, mesh)
    assert np.array_equal(a, b)


def test_rscatter_homomorphic_bit_identical_to_ring(mesh, int_grads):
    """shared_scale: the rscatter all_to_all+sum and the ring's hop adds
    accumulate the SAME stage-1 integer level payloads (same shard
    encode, same negotiated scale, same rng folds) — one decode each,
    bit-identical results."""
    a = _update_once({"compressor": "homoqsgd", "quantum_num": 7,
                      "memory": "none", "communicator": "rscatter",
                      "fusion": "flat"}, int_grads, mesh)
    b = _update_once({"compressor": "homoqsgd", "quantum_num": 7,
                      "memory": "none", "communicator": "ring",
                      "fusion": "flat"}, int_grads, mesh)
    assert np.array_equal(a, b)


def test_rscatter_requant_bit_identical_to_twoshot(mesh, int_grads):
    """The single-requant path IS TwoShot's schedule (same stage-1 shard
    encode, same owned-chunk aggregate, same shared stage-2 key) realized
    with the reduce-scatter all_to_all — pinned bitwise."""
    for cfg in ({"compressor": "topk", "compress_ratio": 0.5,
                 "memory": "none"},
                {"compressor": "qsgd", "quantum_num": 64,
                 "use_pallas": False, "memory": "none"}):
        a = _update_once({**cfg, "communicator": "rscatter",
                          "fusion": "flat"}, int_grads, mesh)
        b = _update_once({**cfg, "communicator": "twoshot",
                          "fusion": "flat"}, int_grads, mesh)
        assert np.array_equal(a, b), cfg["compressor"]


def test_rscatter_rejects_non_summable_non_requant(mesh):
    grc = grace_from_params({"compressor": "onebit", "memory": "residual",
                             "communicator": "rscatter", "fusion": "flat"})
    tx = grc.transform(0)
    grads = jnp.ones((8, 64), jnp.float32)

    def body(gr):
        state = tx.init(gr)
        out, _ = tx.update(gr, state, None)
        return out

    f = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)
    with pytest.raises(TypeError, match="payload algebra"):
        jax.jit(f)(grads)


def test_rscatter_wire_model_w_edges():
    cm = comm.ReduceScatterAllreduce()
    assert cm.recv_wire_bytes(1000, 500, 0) == 0
    assert cm.recv_wire_bytes(1000, 500, 1) == 0
    assert cm.recv_wire_bytes(1000, 500, 8) == 2 * 1000 * 7 // 8
    # flat schedule: all-ICI within one slice, all-DCN beyond it
    from grace_tpu.core import Topology
    lb = cm.recv_link_bytes(1000, 500, 8, topology=Topology(slice_size=4))
    assert lb.ici == 0 and lb.dcn == cm.recv_wire_bytes(1000, 500, 8)


# ---------------------------------------------------------------------------
# cyclic local-selection topk (ScaleCom)
# ---------------------------------------------------------------------------

def test_cyclictopk_shared_indices_sum_exactly(mesh, int_grads):
    """The rng+step-derived shared index set makes the payload exactly
    summable: the psum allreduce and the gather-then-sum agree bitwise,
    and — the summability claim in its strongest spelling — EVERY
    schedule's selected coordinates carry the exact dense mean bitwise
    (integer grads are exact in f32), including the hierarchical
    two-level gather the data-free ctx just unlocked. Schedules that
    chunk the buffer differently (ring's W shards, hier's slice shards)
    legitimately select different windows, so cross-schedule bitwise
    identity is only pinned where the chunking agrees."""
    cfg = {"compressor": "cyclictopk", "compress_ratio": 0.5,
           "memory": "none"}
    a = _update_once({**cfg, "communicator": "allreduce"}, int_grads, mesh)
    b = _update_once({**cfg, "communicator": "allgather"}, int_grads, mesh)
    assert np.array_equal(a, b)
    h = _update_once({**cfg, "communicator": "hier", "slice_size": 4,
                      "fusion": "flat"}, int_grads, mesh)
    dense = np.asarray(int_grads).mean(axis=0)
    for name, out in (("allreduce", a), ("hier", h)):
        row = out[0]
        # replicas bit-identical (the shared-set algebra's rank identity)
        assert all(np.array_equal(out[i], row) for i in range(out.shape[0]))
        nz = row != 0
        assert nz.any()
        # exact payload-space summation: no requant loss anywhere
        assert np.array_equal(row[nz], dense[nz]), name


def test_cyclictopk_negotiation_free():
    """The cyclic schedule is rank-deterministic (rng + step, not data):
    there is no index broadcast, so the wire model prices ZERO
    negotiation bytes through both accessor spellings."""
    from grace_tpu.core import needs_negotiation, negotiation_bytes_for
    from grace_tpu.compressors import CyclicTopKCompressor

    c = CyclicTopKCompressor(compress_ratio=0.1)
    assert not needs_negotiation(c)
    assert negotiation_bytes_for(c, 1000, 8) == 0
    assert c.negotiation_nbytes(8) == 0


def test_cyclictopk_schedule_deterministic_and_distinct():
    """The cyclic window is a pure function of the replicated key: same
    key -> same indices (the rank-identity proof obligation), distinct
    indices (the scatter never collides), rotating with the step fold."""
    from grace_tpu.compressors import CyclicTopKCompressor

    c = CyclicTopKCompressor(compress_ratio=0.1)
    key = jax.random.key(7)
    a = np.asarray(c._schedule(key, 1000))
    b = np.asarray(c._schedule(key, 1000))
    assert np.array_equal(a, b)
    assert len(set(a.tolist())) == a.size
    stepped = np.asarray(c._schedule(jax.random.fold_in(key, 1), 1000))
    assert not np.array_equal(a, stepped)


def test_cyclictopk_accepted_by_shard_parallel_comms(mesh, int_grads):
    """The data-free ctx unlocks the hop-pipelined decode paths (ROADMAP
    item 4): ring and rscatter run cyclictopk end to end, agree with the
    allgather reference bitwise on integer grads (exact payload algebra,
    same shared index set) — and the tuner's capability mirror agrees."""
    cfg = {"compressor": "cyclictopk", "compress_ratio": 0.5,
           "memory": "none", "fusion": "flat"}
    ring = _update_once({**cfg, "communicator": "ring"}, int_grads, mesh)
    rsc = _update_once({**cfg, "communicator": "rscatter"},
                       int_grads, mesh)
    # Same stage-1 shard encode (same chunk-folded keys), exact payload
    # algebra on both schedules — the hop adds and the all_to_all sum are
    # the same arithmetic, so the two outputs are bit-identical.
    assert np.array_equal(ring, rsc)
    dense = np.asarray(int_grads).mean(axis=0)
    nz = ring[0] != 0
    assert nz.any() and np.array_equal(ring[0][nz], dense[nz])

    from grace_tpu.tuning.candidates import Candidate, candidate_legal
    from grace_tpu.tuning.cost import TuneTopology
    legal, reason, _ = candidate_legal(
        Candidate("cyclic-ring", {"compressor": "cyclictopk",
                                  "memory": "none", "communicator": "ring",
                                  "fusion": "flat"}),
        TuneTopology(world=8))
    assert legal, reason


# ---------------------------------------------------------------------------
# degenerate collapse + 2-D state layout
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cfg", [
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "allgather"},
    {"compressor": "fp16", "memory": "none", "communicator": "rscatter",
     "fusion": "flat"},
    {"compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
     "communicator": "ring", "fusion": "flat"},
], ids=["topk-allgather", "fp16-rscatter", "homoqsgd-ring"])
def test_fsdp_degenerate_mesh_collapses_bitwise(mesh, int_grads, cfg):
    """A W×1 fsdp-degenerate 2-D mesh reproduces the 1-D path bitwise:
    same collectives over dp, a size-1 fsdp axis contributing nothing."""
    one_d = _update_once(cfg, int_grads, mesh)
    mesh2 = make_mesh((8, 1), ("data", "fsdp"))
    two_d = _update_once({**cfg, "fsdp_axis": "fsdp"}, int_grads, mesh2,
                         in_spec=P("data"))
    assert np.array_equal(one_d, two_d)


def test_every_registered_config_state_unchanged_under_meshspec():
    """The 1×W collapse, registry-wide: for every registered update-mode
    config, arming the 2-D MeshSpec changes NO state structure or shapes
    — the fsdp axis re-shards the same state, it never resizes it."""
    from grace_tpu.analysis.configs import AUDIT_CONFIGS, build_grace
    from grace_tpu.analysis.trace import default_param_structs

    params = default_param_structs()
    checked = 0
    for entry in AUDIT_CONFIGS:
        if entry.get("mode", "update") != "update":
            continue
        if entry["params"].get("use_pallas") is True:
            continue                      # interpret-mode kernel: slow
        base = build_grace(entry)
        import dataclasses
        two_d = dataclasses.replace(base, mesh=MeshSpec("data", "fsdp"))
        s1 = jax.eval_shape(base.transform(0).init, params)
        s2 = jax.eval_shape(two_d.transform(0).init, params)
        assert jax.tree_util.tree_structure(s1) == \
            jax.tree_util.tree_structure(s2), entry["name"]
        for a, b in zip(jax.tree_util.tree_leaves(s1),
                        jax.tree_util.tree_leaves(s2)):
            assert a.shape == b.shape and a.dtype == b.dtype, entry["name"]
        checked += 1
    assert checked >= 40


def test_partition_specs_2d_layout():
    """mem/comp/telem/watch shard over the dp×fsdp product; replicated
    fields and non-grace leaves stay P(); the 1-D spelling is unchanged."""
    g = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                           "memory": "residual",
                           "communicator": "allgather",
                           "telemetry": True})
    tx = g.transform(0)
    params = {"w": jnp.ones((16, 4)), "b": jnp.ones((8,))}
    state = jax.eval_shape(tx.init, params)
    one_d = partition_specs(state, "data")
    assert one_d.mem[0] == P("data")
    assert one_d.count == P()
    two_d = partition_specs(state, MeshSpec("data", "fsdp"))
    assert two_d.mem[0] == P(("data", "fsdp"))
    assert two_d.count == P() and two_d.fallback == P()
    assert jax.tree_util.tree_leaves(
        partition_specs(state.telem, MeshSpec("data", "fsdp")),
        is_leaf=lambda x: isinstance(x, P)) != []


def test_meshspec_validation():
    with pytest.raises(ValueError, match="fsdp_axis must differ"):
        MeshSpec("data", "data")
    # a 2-D Grace builds its transform fine
    grace_from_params({"compressor": "none", "memory": "none",
                       "communicator": "allreduce",
                       "fsdp_axis": "fsdp"}).transform(0)
    # mismatched: communicator on another axis than the MeshSpec dp
    from grace_tpu.transform import grace_transform
    from grace_tpu.compressors import NoneCompressor
    from grace_tpu.memories import NoneMemory
    with pytest.raises(ValueError, match="dp_axis"):
        grace_transform(NoneCompressor(), NoneMemory(),
                        comm.Allreduce(axis_name="data"),
                        mesh=MeshSpec("dp2", "fsdp"))


# ---------------------------------------------------------------------------
# 2-D fsdp training end to end
# ---------------------------------------------------------------------------

def test_fsdp_train_step_per_shard_residuals():
    """A sharded-model train step on the 4×2 mesh: loss decreases, the
    GraceState mem leaves carry the dp×fsdp product world axis, and each
    device's residual covers exactly its own param shard (error feedback
    lives on the shard owner)."""
    from grace_tpu.train import init_train_state, make_train_step
    from grace_tpu.transform import GraceState

    mesh2 = make_mesh((4, 2), ("data", "fsdp"))
    ms = MeshSpec("data", "fsdp")
    feat, hid, classes = 16, 8, 10
    rng = np.random.default_rng(0)
    params = {"w1": jnp.asarray(rng.normal(size=(feat, hid)), jnp.float32),
              "b1": jnp.zeros((hid,)),
              "w2": jnp.asarray(rng.normal(size=(hid, classes)),
                                jnp.float32)}
    param_specs = {"w1": P("fsdp", None), "b1": P(), "w2": P()}
    shard = feat // 2

    def loss_fn(p, b):
        x, y = b
        f = lax.axis_index("fsdp")
        xs = lax.dynamic_slice_in_dim(x, f * shard, shard, 1)
        h = lax.psum(xs @ p["w1"], "fsdp") + p["b1"]
        logits = jnp.tanh(h) @ p["w2"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, y).mean()

    g = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
        "communicator": "rscatter", "fsdp_axis": "fsdp",
        "route": [("b1", {"compressor": "fp16", "memory": "none",
                          "communicator": "allreduce"})]})
    tx = optax.chain(g.transform(0), optax.sgd(0.1))
    st = init_train_state(params, tx, mesh2, axis_name=ms,
                          param_specs=param_specs)
    step = make_train_step(loss_fn, tx, mesh2, axis_name=ms,
                           param_specs=param_specs, donate=False)
    x = jnp.asarray(rng.normal(size=(16, feat)), jnp.float32)
    y = jnp.asarray(rng.integers(0, classes, (16,)), jnp.int32)
    losses = []
    for _ in range(8):
        st, loss = step(st, (x, y))
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    grace_states = []

    def find(node):
        if isinstance(node, GraceState):
            grace_states.append(node)
        return node

    jax.tree_util.tree_map(find, st.opt_state,
                           is_leaf=lambda n: isinstance(n, GraceState))
    mem_leaves = [m for m in jax.tree_util.tree_leaves(grace_states[0].mem)]
    # routed b1 has no residual (NoneMemory); w1/w2 do — leading world
    # axis spans the dp×fsdp product, body is the LOCAL shard
    shapes = sorted(tuple(m.shape) for m in mem_leaves)
    assert shapes == sorted([(8, shard, hid), (8, hid, classes)])
    # the w1 residual genuinely differs across fsdp shard owners
    w1_mem = next(m for m in mem_leaves if m.shape == (8, shard, hid))
    host = np.asarray(w1_mem)
    assert host.shape[0] == 8


# ---------------------------------------------------------------------------
# 2-D lint seeding: the wrong-axis replication bug, condemned live
# ---------------------------------------------------------------------------

def _two_axis_trace(fn, varying_axes=None):
    from grace_tpu.analysis.trace import trace_fn

    args = (jax.ShapeDtypeStruct((8,), jnp.float32),)
    return trace_fn(fn, args, mesh_axes=(("data", 4), ("fsdp", 2)),
                    varying_axes=varying_axes, name="seeded-2d")


def test_wrong_axis_replication_condemned_by_pass1():
    """The seeded wrong-axis bug: a predicate psummed over FSDP (so it
    looks 'reduced') but still dp-varying gates a cond whose branches
    issue different dp-axis collectives — ranks of one dp group can take
    different branches. Pass 1's per-axis analysis must condemn it."""
    from grace_tpu.analysis.passes import pass_collective_consistency

    def bad(x):
        # varies over dp (seeded); the fsdp psum does NOT grant dp
        # replication — the wrong axis
        p = lax.psum(jnp.sum(x), "fsdp") > 0

        def taken(v):
            return lax.psum(v, "data")

        return lax.cond(p, taken, lambda v: v, x)

    traced = _two_axis_trace(bad)
    findings = pass_collective_consistency(traced)
    assert len(findings) == 1
    assert "data" in str(dict(findings[0].details)["varying_axes"])


def test_right_axis_replication_blessed_by_pass1():
    """The legal twins: (a) a predicate psummed over dp gating dp-axis
    branch divergence; (b) an fsdp-varying predicate gating DP-axis
    collectives — dp peers share an fsdp index, so they agree — which
    the old single-axis analysis would have false-positived."""
    from grace_tpu.analysis.passes import pass_collective_consistency

    def legal_reduced(x):
        p = lax.psum(jnp.sum(x), "data") > 0

        def taken(v):
            return lax.psum(v, "data")

        return lax.cond(p, taken, lambda v: v, x)

    assert pass_collective_consistency(_two_axis_trace(legal_reduced)) == []

    def legal_fsdp_varying(x):
        p = lax.axis_index("fsdp") > 0     # fsdp-varying, dp-replicated

        def taken(v):
            return lax.psum(v, "data")

        return lax.cond(p, taken, lambda v: v, x)

    # seed x replicated on both axes so only axis_index drives variance
    traced = _two_axis_trace(legal_fsdp_varying,
                             varying_axes={"data": [False],
                                           "fsdp": [False]})
    assert pass_collective_consistency(traced) == []


def test_2d_rscatter_wire_reconciles_leg_by_leg():
    """wire_reconciliation on the 2-D fsdp config: the dp-axis schedule's
    counted bytes reconcile against the model at the dp world, leg by
    leg, under the audit slice boundary."""
    from grace_tpu.analysis.configs import AUDIT_CONFIGS, audit_config

    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "topk-rscatter-fsdp")
    assert "wire_reconciliation" in entry["passes"]
    assert audit_config(entry) == []


def test_2d_trace_worlds_and_axes():
    from grace_tpu.analysis.configs import AUDIT_CONFIGS, build_grace
    from grace_tpu.analysis.trace import trace_update

    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "fp16-rscatter-fsdp")
    traced = trace_update(build_grace(entry), world=8, fsdp=2,
                          name=entry["name"])
    assert traced.world == 4                    # the dp (exchange) world
    assert traced.mesh_axes == ("data", "fsdp")
    assert traced.axis_sizes == {"data": 4, "fsdp": 2}
    # per-axis seeds really differ from a single mask: mem leaves vary
    # over BOTH axes, replicated fields over neither
    assert set(traced.varying_axes) == {"data", "fsdp"}


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_route_resolution_and_wire_sum():
    from grace_tpu.helper import route_leaves, routed_recv_link_bytes
    from grace_tpu.utils.metrics import payload_nbytes

    g = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "communicator": "allgather",
        "route": [("b", {"compressor": "fp16", "memory": "none",
                         "communicator": "allreduce"})]})
    params = {"w": jnp.ones((100, 10)), "b": jnp.ones((10,))}
    leaves = route_leaves(g, params)
    by_path = {p: (type(c).__name__, type(cm).__name__)
               for p, _s, c, _m, cm in leaves}
    assert by_path["b"] == ("FP16Compressor", "Allreduce")
    assert by_path["w"] == ("TopKCompressor", "Allgather")
    total = routed_recv_link_bytes(g, params, 8).total
    # = per-leaf sum: allgather (W-1)*payload for w, ring-style psum for b
    w_payload = payload_nbytes(g.compressor, jnp.ones((100, 10)))
    b_payload = payload_nbytes(
        next(c for p, _s, c, _m, _cm in leaves if p == "b"),
        jnp.ones((10,)))
    expect = 7 * w_payload + 2 * b_payload * 7 // 8
    assert total == expect


def test_routes_require_per_leaf_fusion():
    with pytest.raises(ValueError, match="fusion=None"):
        grace_from_params({
            "compressor": "topk", "compress_ratio": 0.1,
            "memory": "residual", "communicator": "allgather",
            "fusion": "flat",
            "route": [("b", {"compressor": "fp16", "memory": "none",
                             "communicator": "allreduce"})]}).transform(0)


def test_route_axis_mismatch_rejected():
    with pytest.raises(ValueError, match="same mesh axis|dp axis"):
        grace_from_params({
            "compressor": "topk", "compress_ratio": 0.1,
            "memory": "residual", "communicator": "allgather",
            "route": [("b", {"compressor": "fp16", "memory": "none",
                             "communicator": "allreduce",
                             "axis_name": "other"})]})


def test_routed_update_applies_per_leaf_codecs(mesh, int_grads):
    """Routed leaves genuinely take their own pipeline: route the second
    half of the tree dense and compare each part against the unrouted
    runs of the matching codec."""
    grads = {"w": int_grads, "b": jnp.asarray(
        np.random.default_rng(1).integers(-4, 4, (8, 16)), jnp.float32)}

    g = grace_from_params({
        "compressor": "fp16", "memory": "none",
        "communicator": "allgather",
        "route": [("b", {"compressor": "none", "memory": "none",
                         "communicator": "allreduce"})]})
    tx = g.transform(0)

    def body(gr):
        state = tx.init(gr)
        out, _ = tx.update(gr, state, None)
        return out

    f = shard_map(body, mesh=mesh, in_specs=(P("data"),),
                  out_specs=P("data"), check_vma=False)
    out = jax.jit(f)(grads)
    a = _update_once({"compressor": "fp16", "memory": "none",
                      "communicator": "allgather"}, int_grads, mesh)
    b = _update_once({"compressor": "none", "memory": "none",
                      "communicator": "allreduce"}, grads["b"], mesh)
    assert np.array_equal(np.asarray(out["w"]), a)
    assert np.array_equal(np.asarray(out["b"]), b)


# ---------------------------------------------------------------------------
# the transformer track wins on the model
# ---------------------------------------------------------------------------

def test_routed_bert_projection_beats_dense_at_scale():
    """ISSUE 14 acceptance: the routed rscatter BERT config's per-link
    xslice projection is >1.0× vs dense at W≥64, priced with the
    committed on-chip dense step time (BENCH_BERT_TPU_LAST.json) on BOTH
    sides — the tuner's wire-dominated convention — through the shared
    per-link model; the committed flat bert_powersgd_r4 row stays the
    0.80× before-picture."""
    import sys
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import tpu_bert_bench as B

    from grace_tpu.models import transformer

    with open(os.path.join(ROOT, "BENCH_BERT_TPU_LAST.json")) as f:
        doc = json.load(f)
    rows = {r["config"]: r for r in doc["rows"]}
    # the before-picture: the committed flat BERT row LOSES
    assert rows["bert_powersgd_r4"]["vs_baseline"] < 1.0
    dense = rows["bert_dense"]
    n = dense["per_device_bs"] * doc.get("n_devices", 1)
    step_s = n / dense["seqs_per_sec"]

    cfg = transformer.base(num_classes=2, max_len=dense["seq_len"])
    params = jax.eval_shape(
        lambda k: transformer.init(k, cfg)[0], jax.random.key(0))
    n_elems = sum(int(np.prod(l.shape, dtype=np.int64))
                  for l in jax.tree_util.tree_leaves(params))
    assert n_elems == dense["n_params"]

    grace = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.01,
        "topk_algorithm": "chunk", "memory": "residual",
        "communicator": "rscatter", "fusion": "none",
        "route": B.BERT_ROUTE})
    proj = B.project_routed(step_s, step_s, grace, params, n_elems)
    by_world = {p["world"]: p for p in proj}
    for w in (64, 256):
        assert by_world[w]["xslice"]["speedup_vs_dense"] > 1.0, (
            w, by_world[w]["xslice"])
    # honest split: a flat schedule's xslice bytes ride DCN beyond one
    # slice, and the routed wire is a small fraction of dense
    assert by_world[64]["xslice"]["dcn_bytes"] > 0
    assert by_world[64]["recv_bytes_per_rank"] < 0.05 * 4 * n_elems


# ---------------------------------------------------------------------------
# tuner 2-D spec + chaos smoke
# ---------------------------------------------------------------------------

def test_tune_topology_2d_spec():
    from grace_tpu.tuning.cost import TuneTopology

    t = TuneTopology.parse("64x4,8")
    assert (t.world, t.fsdp, t.slice_size) == (64, 4, 8)
    assert t.devices == 256
    assert t.label == "W64x4/slice8"
    assert TuneTopology.parse("256,8").fsdp is None
    with pytest.raises(ValueError):
        TuneTopology.parse("8,4,2")


def test_tuner_generates_routed_fsdp_variant():
    from grace_tpu.tuning.candidates import (candidate_legal,
                                             enumerate_candidates)
    from grace_tpu.tuning.cost import TuneTopology

    spec = TuneTopology(world=64, slice_size=8, fsdp=4)
    cands = {c.name: c for c in enumerate_candidates(spec)}
    assert "tune-routed-rscatter-fsdp" in cands
    legal, reason, grace = candidate_legal(
        cands["tune-routed-rscatter-fsdp"], spec)
    assert legal, reason
    assert grace.mesh.is_2d and grace.routes


@pytest.mark.chaos
def test_chaos_smoke_fsdp_scenario(tmp_path):
    """Tier-1 drill of the --fsdp scenario: guard + consensus over the
    2-D mesh, SDC repaired per fsdp shard, artifact rows carry the
    two-axis wire split."""
    import sys
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    import chaos_smoke

    out = tmp_path / "fsdp_telemetry.jsonl"
    rc = chaos_smoke.main(["--fsdp", "--steps", "60",
                           "--telemetry-out", str(out)])
    assert rc == 0
    rows = [json.loads(line) for line in out.read_text().splitlines()]
    telem = [r for r in rows if "step" in r and "wire_bytes" in r]
    assert telem and all("wire_bytes_ici" in r and "wire_bytes_dcn" in r
                         for r in telem)
    assert any(r["wire_bytes_dcn"] > 0 for r in telem)
