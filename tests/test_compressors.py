"""Per-compressor property tests (round-trip, payload shape/dtype, semantics).

The reference backs its algorithms with no tests at all; the semantics
asserted here are transcribed from SURVEY.md §2.3 and the reference sources
cited in each compressor's docstring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu import compressors as C

KEY = jax.random.key(42)


def _compress(comp, x, state=None, key=KEY):
    if state is None:
        state = comp.init_state(x)
    return comp.compress(x, state, key)


def _roundtrip(comp, x, key=KEY):
    payload, ctx, _ = _compress(comp, x, key=key)
    return comp.decompress(payload, ctx)


def rand(shape, rng, scale=1.0):
    return jnp.asarray(rng.normal(size=shape).astype(np.float32) * scale)


def test_none_identity(rng):
    x = rand((13, 7), rng)
    out = _roundtrip(C.NoneCompressor(), x)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize("dtype", ["bfloat16", "float16"])
def test_fp16_roundtrip(rng, dtype):
    x = rand((64,), rng)
    comp = C.FP16Compressor(dtype=dtype)
    payload, ctx, _ = _compress(comp, x)
    assert payload[0].dtype == jnp.dtype(dtype)
    out = comp.decompress(payload, ctx)
    assert out.dtype == x.dtype
    tol = 0.04 if dtype == "bfloat16" else 0.01
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=tol, atol=tol)


def test_topk_keeps_largest(rng):
    x = rand((10, 10), rng)
    comp = C.TopKCompressor(compress_ratio=0.1)
    payload, ctx, _ = _compress(comp, x)
    values, indices = payload
    assert values.shape == (10,) and indices.shape == (10,)
    out = comp.decompress(payload, ctx)
    assert out.shape == x.shape
    flat = np.asarray(x).ravel()
    expect_idx = np.argsort(-np.abs(flat))[:10]
    got = np.asarray(out).ravel()
    # kept entries match original, everything else is zero
    np.testing.assert_allclose(got[expect_idx], flat[expect_idx], rtol=1e-6)
    mask = np.ones_like(flat, bool)
    mask[expect_idx] = False
    assert np.all(got[mask] == 0)


def test_randomk_shared_seed(rng):
    """Same rng key on every 'rank' -> identical ctx indices (the wire contract)."""
    comp = C.RandomKCompressor(compress_ratio=0.25)
    x1, x2 = rand((40,), rng), rand((40,), rng)
    key = jax.random.key(7)
    _, ctx1, _ = _compress(comp, x1, key=key)
    _, ctx2, _ = _compress(comp, x2, key=key)
    np.testing.assert_array_equal(np.asarray(ctx1[0]), np.asarray(ctx2[0]))
    assert ctx1[0].shape == (10,)
    # indices are distinct (sampling without replacement)
    assert len(np.unique(np.asarray(ctx1[0]))) == 10


def test_threshold_static_capacity(rng):
    x = jnp.asarray([0.5, -0.001, 0.2, 0.0009, -0.9, 0.003])
    comp = C.ThresholdCompressor(threshold=0.1, capacity_ratio=1.0)
    payload, ctx, _ = _compress(comp, x)
    out = np.asarray(comp.decompress(payload, ctx))
    expect = np.where(np.abs(np.asarray(x)) > 0.1, np.asarray(x), 0.0)
    np.testing.assert_allclose(out, expect, rtol=1e-6)


def test_qsgd_bound(rng):
    x = rand((257,), rng)
    comp = C.QSGDCompressor(quantum_num=64)
    payload, ctx, _ = _compress(comp, x)
    levels, norm = payload
    assert levels.dtype == jnp.int8
    out = np.asarray(comp.decompress(payload, ctx))
    # quantization error per element is at most norm/quantum_num
    bound = float(norm) / 64 + 1e-6
    assert np.max(np.abs(out - np.asarray(x))) <= bound


def test_qsgd_int16_for_many_levels(rng):
    comp = C.QSGDCompressor(quantum_num=256)
    payload, _, _ = _compress(comp, rand((32,), rng))
    assert payload[0].dtype == jnp.int16


def test_terngrad_values(rng):
    x = rand((500,), rng)
    comp = C.TernGradCompressor()
    payload, ctx, _ = _compress(comp, x)
    out = np.asarray(comp.decompress(payload, ctx))
    scalar = float(payload[1])
    uniq = np.unique(out)
    assert set(np.round(uniq / scalar).astype(int)) <= {-1, 0, 1}
    # signs agree where nonzero
    nz = out != 0
    assert np.all(np.sign(out[nz]) == np.sign(np.asarray(x)[nz]))


def test_terngrad_unbiased(rng):
    """Stochastic ternarization is unbiased in expectation (clip aside)."""
    x = jnp.asarray(rng.normal(size=2000).astype(np.float32) * 0.1)
    comp = C.TernGradCompressor()

    @jax.jit
    def rt(key):
        payload, ctx, _ = comp.compress(x, None, key)
        return comp.decompress(payload, ctx)

    outs = [np.asarray(rt(jax.random.key(i))) for i in range(200)]
    mean = np.mean(outs, axis=0)
    assert np.abs(mean - np.asarray(x)).mean() < 0.02


def test_signsgd_majority_vote(rng):
    comp = C.SignSGDCompressor()
    assert comp.average is False
    x = rand((33,), rng)
    out = np.asarray(_roundtrip(comp, x))
    np.testing.assert_array_equal(out, np.where(np.asarray(x) >= 0, 1.0, -1.0))
    stacked = jnp.asarray([[1.0, 1, -1], [1, -1, -1], [-1, -1, -1]])
    vote = np.asarray(comp.aggregate(stacked))
    np.testing.assert_array_equal(vote, [1.0, -1.0, -1.0])


def test_signum_momentum(rng):
    comp = C.SignumCompressor(momentum=0.5)
    x = jnp.asarray([1.0, -1.0, 4.0])
    state = comp.init_state(x)
    payload, ctx, state = comp.compress(x, state, KEY)
    # first step: sign of raw gradient
    np.testing.assert_array_equal(np.asarray(comp.decompress(payload, ctx)),
                                  [1.0, -1.0, 1.0])
    y = jnp.asarray([-4.0, -1.0, -1.0])
    payload, ctx, state = comp.compress(y, state, KEY)
    # m = 0.5*y + 0.5*m_prev = [-1.5, -1.0, 1.5]
    np.testing.assert_array_equal(np.asarray(comp.decompress(payload, ctx)),
                                  [-1.0, -1.0, 1.0])
    np.testing.assert_allclose(np.asarray(state["momentum"]), [-1.5, -1.0, 1.5])


def test_efsignsgd_roundtrip(rng):
    x = rand((100,), rng)
    comp = C.EFSignSGDCompressor(lr=0.5)
    payload, ctx, _ = _compress(comp, x)
    out = np.asarray(comp.decompress(payload, ctx))
    mean = float(np.mean(np.abs(np.asarray(x))))
    np.testing.assert_allclose(np.abs(out), mean, rtol=1e-5)
    assert np.all(np.sign(out) == np.where(np.asarray(x) >= 0, 1, -1))
    # aggregate divides by lr
    stacked = jnp.stack([x, x])
    np.testing.assert_allclose(np.asarray(comp.aggregate(stacked)),
                               np.asarray(x + x) / 0.5, rtol=1e-5)


def test_onebit_means(rng):
    x = jnp.asarray([-2.0, -4.0, 1.0, 3.0, 5.0])
    comp = C.OneBitCompressor()
    payload, ctx, _ = _compress(comp, x)
    out = np.asarray(comp.decompress(payload, ctx))
    np.testing.assert_allclose(out, [-3.0, -3.0, 3.0, 3.0, 3.0], rtol=1e-6)


def test_onebit_all_positive(rng):
    x = jnp.asarray([1.0, 2.0, 3.0])
    out = np.asarray(_roundtrip(C.OneBitCompressor(), x))
    np.testing.assert_allclose(out, [2.0, 2.0, 2.0], rtol=1e-6)


def test_natural_power_of_two(rng):
    x = rand((1000,), rng)
    comp = C.NaturalCompressor()
    payload, ctx, _ = _compress(comp, x)
    assert payload[0].dtype == jnp.uint8
    out = np.asarray(comp.decompress(payload, ctx))
    nz = out != 0
    # every decompressed magnitude is a power of two
    log2 = np.log2(np.abs(out[nz]))
    np.testing.assert_allclose(log2, np.round(log2), atol=1e-6)
    # signs preserved, magnitude within a factor of two
    xs = np.asarray(x)[nz]
    assert np.all(np.sign(out[nz]) == np.sign(xs))
    ratio = np.abs(out[nz]) / np.abs(xs)
    assert np.all(ratio <= 2.0 + 1e-6) and np.all(ratio >= 0.5 - 1e-6)


def test_natural_unbiased(rng):
    x = jnp.asarray([0.75] * 512, jnp.float32)
    comp = C.NaturalCompressor()

    @jax.jit
    def rt(key):
        payload, ctx, _ = comp.compress(x, None, key)
        return comp.decompress(payload, ctx)

    outs = [np.asarray(rt(jax.random.key(i))) for i in range(64)]
    mean = np.mean(outs)
    assert abs(mean - 0.75) < 0.02


def test_dgc_selects_about_ratio(rng):
    x = rand((10000,), rng)
    comp = C.DgcCompressor(compress_ratio=0.05)
    payload, ctx, _ = _compress(comp, x)
    values, indices = payload
    nnz = int(np.sum(np.asarray(values) != 0))
    # refinement targets [0.7k, 1.3k]; sampling noise can leave an extra margin
    assert 0.4 * 500 <= nnz <= 1.3 * 500 + 1
    out = np.asarray(comp.decompress(payload, ctx))
    flat = np.asarray(x)
    sent = out != 0
    np.testing.assert_allclose(out[sent], flat[sent], rtol=1e-6)


def test_compressor_hashable():
    """Frozen dataclasses: usable as static jit args / dict keys."""
    assert hash(C.TopKCompressor(0.5)) == hash(C.TopKCompressor(0.5))
    assert C.TopKCompressor(0.5) != C.TopKCompressor(0.25)


class TestTopKAlgorithms:
    """TPU-first selection variants share the exact variant's wire format."""

    def _roundtrip(self, algo, n=10000, ratio=0.01):
        from grace_tpu.compressors import TopKCompressor
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(n), jnp.float32)
        c = TopKCompressor(compress_ratio=ratio, algorithm=algo)
        # ctx carries static host data (shape/dtype) — jit only the payload.
        vals, idx = jax.jit(
            lambda x: c.compress(x, None, jax.random.key(0))[0])(x)
        _, ctx, _ = c.compress(x, None, jax.random.key(0))
        k = max(1, int(n * ratio))
        assert vals.shape == (k,) and idx.shape == (k,)
        assert jnp.all(idx >= 0) and jnp.all(idx < n)
        np.testing.assert_allclose(np.asarray(vals),
                                   np.asarray(x)[np.asarray(idx)])
        dec = c.decompress((vals, idx), ctx)
        assert dec.shape == x.shape
        return x, vals, idx

    def test_exact_is_true_topk(self):
        x, vals, idx = self._roundtrip("exact")
        thresh = np.sort(np.abs(np.asarray(x)))[-100]
        assert np.all(np.abs(np.asarray(vals)) >= thresh - 1e-6)

    def test_approx_high_recall(self):
        x, vals, idx = self._roundtrip("approx")
        exact = set(np.argsort(np.abs(np.asarray(x)))[-100:].tolist())
        got = set(np.asarray(idx).tolist())
        assert len(exact & got) / 100 >= 0.9

    def test_chunk_selects_chunk_maxima(self):
        # Strided chunks: chunk c = elements {c, c+k, c+2k, ...}.
        x, vals, idx = self._roundtrip("chunk")
        xn = np.abs(np.asarray(x))
        k = 100  # n=10000, ratio=0.01
        for c, i in enumerate(np.asarray(idx)):
            members = xn[c::k]
            assert i % k == c
            assert xn[i] == members.max()

    def test_chunk_indices_unique_and_cover(self):
        _, _, idx = self._roundtrip("chunk", n=10007, ratio=0.013)
        idx = np.asarray(idx)
        assert len(np.unique(idx)) == len(idx)

    @pytest.mark.parametrize("n,ratio", [
        (27, 0.3),          # pad spans whole contiguous chunks (regression)
        (25_557, 0.01),     # ResNet-50-like shape scaled down
        (101, 0.5),
    ])
    def test_chunk_indices_in_range_awkward_shapes(self, n, ratio):
        """Regression: contiguous chunking emitted out-of-range indices when
        the tail padding spanned whole chunks; strided chunking cannot."""
        self._roundtrip("chunk", n=n, ratio=ratio)

    @pytest.mark.parametrize("n,ratio", [
        (10_000, 0.01),
        (27, 0.3),
        (25_557, 0.01),
        (101, 0.5),
    ])
    def test_chunk_onehot_decompress_matches_scatter(self, n, ratio):
        """Chunk mode's scatter-free one-hot decompress must equal the
        general scatter build bit-exactly for every payload."""
        from grace_tpu.compressors import TopKCompressor
        from grace_tpu.ops.sparse import scatter_dense

        c = TopKCompressor(compress_ratio=ratio, algorithm="chunk")
        x = jax.random.normal(jax.random.key(3), (n,))
        (vals, idx), ctx, _ = c.compress(x, None, jax.random.key(0))
        numel, shape, dtype = ctx
        got = c.decompress((vals, idx), ctx)
        want = scatter_dense(vals.astype(dtype), idx, numel, shape)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_chunk_subk_payload_falls_back_to_scatter(self):
        """A sliced payload (TwoShot per-rank slice) loses the full-column
        structure; decompress must route it through the general scatter."""
        from grace_tpu.compressors import TopKCompressor
        from grace_tpu.ops.sparse import scatter_dense

        c = TopKCompressor(compress_ratio=0.01, algorithm="chunk")
        x = jax.random.normal(jax.random.key(5), (10_000,))
        (vals, idx), ctx, _ = c.compress(x, None, jax.random.key(0))
        sub = (vals[:40], idx[:40])                    # 40 < k=100
        got = c.decompress(sub, ctx)
        want = scatter_dense(sub[0], sub[1], *ctx[:2])
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_unknown_algorithm_rejected(self):
        from grace_tpu.compressors import TopKCompressor
        with pytest.raises(ValueError, match="algorithm"):
            TopKCompressor(algorithm="banana")

    def test_helper_plumbs_algorithm(self):
        from grace_tpu import grace_from_params
        g = grace_from_params({"compressor": "topk", "compress_ratio": 0.01,
                               "topk_algorithm": "chunk"})
        assert g.compressor.algorithm == "chunk"


ALL_CODECS = ["none", "fp16", "bf16", "topk", "randomk", "threshold", "qsgd",
              "terngrad", "signsgd", "signum", "efsignsgd", "onebit",
              "natural", "dgc", "u8bit", "sketch", "adaq", "inceptionn"]


@pytest.mark.parametrize("name", ALL_CODECS)
def test_payload_shapes_are_value_independent(name, rng):
    """The XLA contract: payload shapes depend only on the input SHAPE, never
    on values (data-dependent sizes cannot compile; SURVEY.md §7 hard part 1).
    Two very different value distributions must produce identical payload
    shapes/dtypes and identical static ctx."""
    from grace_tpu.helper import grace_from_params
    c = grace_from_params({"compressor": name}).compressor
    a = jnp.asarray(rng.normal(size=60).astype(np.float32))
    b = jnp.asarray((rng.normal(size=60) * 1e6).astype(np.float32))
    key = jax.random.key(0)
    pa, ctxa, _ = c.compress(a, c.init_state(a), key)
    pb, ctxb, _ = c.compress(b, c.init_state(b), key)
    assert [(p.shape, p.dtype) for p in pa] == \
           [(p.shape, p.dtype) for p in pb]
    # static (non-array) ctx leaves must not depend on values either —
    # a data-derived static aux value would break jit caching
    def static_leaves(ctx):
        return [l for l in jax.tree_util.tree_leaves(ctx)
                if not isinstance(l, jax.Array)]
    assert static_leaves(ctxa) == static_leaves(ctxb)


@pytest.mark.parametrize("name", ALL_CODECS)
@pytest.mark.parametrize("case", ["zeros", "tiny", "single", "constant"])
def test_degenerate_inputs_stay_finite(name, case, rng):
    """Zero gradients (frozen params, step 0 biases), denormals, single
    elements and constants hit every divide-by-norm/scale path; decompress
    must stay finite with the right shape/dtype."""
    from grace_tpu.helper import grace_from_params
    c = grace_from_params({"compressor": name}).compressor
    x = {"zeros": jnp.zeros(48), "tiny": jnp.full(48, 1e-30),
         "single": jnp.zeros(1), "constant": jnp.full(48, 3.25)}[case]
    p, ctx, _ = c.compress(x, c.init_state(x), jax.random.key(1))
    d = c.decompress(p, ctx)
    assert d.shape == x.shape and d.dtype == x.dtype
    assert bool(jnp.all(jnp.isfinite(d)))
