"""graft-tune: topology-aware autotuner (ISSUE 12).

The acceptance criteria pinned here: the static funnel is auditable (every
candidate leaves with a stage + reason), seeded-bad candidates die at the
right gate (capability-illegal combos never reach measurement, the W=4096
fp16 hop-sum dies in the numeric stage, the flat hop-requant ring dies at
pod scale in the degradation stage), the full-registry static ranking puts
the hier family on top at the W=256/slice8 projection topology, the tuner
is deterministic (same registry + topology → byte-identical TUNE_LAST.json
modulo timestamps), and a real end-to-end CPU run produces a
provenance-stamped winner that beats the worst shortlisted candidate on
measured step time and passes the measured≤static overlap sandwich —
consumed by evidence_summary. Plus the stale-evidence honesty satellites:
bench.evidence_staleness flags the committed pre-PR-7–10 captures, and
bench_all's --tuned family is the one-command refresh.
"""

import importlib.util
import json
import os

import pytest

import bench
import bench_all
from grace_tpu.helper import grace_from_params
from grace_tpu.tuning import (Candidate, TuneTopology, candidate_legal,
                              enumerate_candidates, run_tune, static_prune,
                              variant_audit_entries, write_tune_evidence)
from grace_tpu.tuning.measure import model_structs
from grace_tpu.tuning.prune import (MAX_REQUANT_CHAIN, degradation_verdict,
                                    numeric_verdict, requant_chain_length)

pytestmark = pytest.mark.tune

W8 = TuneTopology(world=8)
XSLICE = TuneTopology(world=256, slice_size=8)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# topology spec + gates
# ---------------------------------------------------------------------------

def test_topology_parse():
    assert TuneTopology.parse("8") == TuneTopology(8)
    assert TuneTopology.parse("256,8") == TuneTopology(256, 8)
    assert TuneTopology.parse(" 64 , 4 ").label == "W64/slice4"
    for bad in ("", "8,4,2", "0", "8,0"):
        with pytest.raises(ValueError):
            TuneTopology.parse(bad)


@pytest.mark.parametrize("params,why", [
    ({"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
      "communicator": "allreduce"}, "summable_payload"),
    ({"compressor": "fp16", "memory": "none",
      "communicator": "sign_allreduce"}, "vote_aggregate"),
    ({"compressor": "dgc", "compress_ratio": 0.3, "memory": "dgc",
      "communicator": "ring"}, "payload algebra"),
    ({"compressor": "signum", "momentum": 0.9, "memory": "none",
      "communicator": "twoshot"}, "stateless"),
    ({"compressor": "topk", "compress_ratio": 0.01,
      "topk_algorithm": "chunk", "memory": "residual",
      "communicator": "hier", "slice_size": 3}, "does not divide world"),
])
def test_capability_gate_mirrors_runtime(params, why):
    """Illegal combos the communicators reject at build/step time are
    rejected statically, with the communicator's rationale."""
    legal, reason, _ = candidate_legal(
        Candidate("bad", params, "generated"), W8)
    assert not legal and why in reason


def test_capability_gate_accepts_the_registry():
    """Every enumerated candidate is legal at the world-8 audit mesh —
    the registry IS the enforced compat matrix."""
    for c in enumerate_candidates(W8):
        legal, reason, _ = candidate_legal(c, W8)
        assert legal, (c.name, reason)


def test_numeric_gate_fp16_hop_sum_at_4096():
    """THE seeded numeric-unsafe candidate: W=4096 fp16 payload-space sums
    blow the 65504 cliff — rejected statically, same constant as flow
    pass 6 (safe_sum_terms)."""
    spec = TuneTopology(world=4096)
    reason = numeric_verdict(
        grace_from_params({"compressor": "fp16", "memory": "none",
                           "communicator": "allreduce"}), spec)
    assert reason is not None and "safe_sum_terms" in reason
    # bf16 has no cliff at any real W (same registry shape, safe dtype).
    assert numeric_verdict(
        grace_from_params({"compressor": "bf16", "memory": "none",
                           "communicator": "allreduce"}), spec) is None


def test_numeric_gate_vote_bound():
    g = grace_from_params({"compressor": "signsgd", "memory": "none",
                           "communicator": "sign_allreduce"})
    assert numeric_verdict(g, TuneTopology(256)) is None      # bf16 edge
    reason = numeric_verdict(g, TuneTopology(512))
    assert reason is not None and "vote_exact_max_world" in reason


def test_requant_chain_lengths():
    ring_topk = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.01,
        "topk_algorithm": "chunk", "memory": "residual",
        "communicator": "ring", "fusion": "flat"})
    hier_topk = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.01,
        "topk_algorithm": "chunk", "memory": "residual",
        "communicator": "hier", "slice_size": 8, "fusion": "flat"})
    fp16_ring = grace_from_params({"compressor": "fp16", "memory": "none",
                                   "communicator": "ring",
                                   "fusion": "flat"})
    gather = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                                "memory": "residual",
                                "communicator": "allgather"})
    assert requant_chain_length(ring_topk, W8) == 7
    assert requant_chain_length(ring_topk, XSLICE) == 255
    # hier: S-1 intra hops + ONE boundary re-encode regardless of K.
    assert requant_chain_length(hier_topk, XSLICE) == 8
    assert requant_chain_length(hier_topk, W8) == 7    # collapses to ring
    assert requant_chain_length(fp16_ring, XSLICE) == 0   # exact path
    assert requant_chain_length(gather, XSLICE) == 0
    # The gate: flat hop-requant ring dies at pod scale, hier survives.
    assert degradation_verdict(ring_topk, XSLICE) is not None
    assert "ScaleCom" in degradation_verdict(ring_topk, XSLICE)
    assert degradation_verdict(hier_topk, XSLICE) is None
    assert requant_chain_length(hier_topk, XSLICE) <= MAX_REQUANT_CHAIN


# ---------------------------------------------------------------------------
# the prune funnel
# ---------------------------------------------------------------------------

def test_prune_funnel_seeded_bad_candidates():
    """Every seeded-bad candidate dies at its own stage with a recorded
    reason, and none of them reaches the shortlist (i.e. measurement)."""
    structs = model_structs("toy")
    spec = TuneTopology(world=4096)
    cands = [
        Candidate("bad-capability",
                  {"compressor": "topk", "compress_ratio": 0.3,
                   "memory": "residual", "communicator": "allreduce"},
                  "generated"),
        Candidate("bad-numeric",
                  {"compressor": "fp16", "memory": "none",
                   "communicator": "allreduce"}, "generated"),
        Candidate("bad-degradation",
                  {"compressor": "qsgd", "quantum_num": 64,
                   "use_pallas": False, "memory": "none",
                   "communicator": "ring", "fusion": "flat"}, "generated"),
        Candidate("good",
                  {"compressor": "topk", "compress_ratio": 0.01,
                   "topk_algorithm": "chunk", "memory": "residual",
                   "communicator": "hier", "slice_size": 8,
                   "fusion": "flat"}, "generated"),
    ]
    out = static_prune(cands, spec, structs, shortlist_n=2)
    by = {r["candidate"]: r for r in out["funnel"]}
    assert by["bad-capability"]["stage"] == "capability"
    assert by["bad-numeric"]["stage"] == "numeric"
    assert by["bad-degradation"]["stage"] == "degradation"
    for name in ("bad-capability", "bad-numeric", "bad-degradation"):
        assert by[name]["verdict"] == "rejected"
        assert by[name]["reason"]            # auditable, never silent
    assert out["shortlist"] == ["good"]
    assert by["good"]["verdict"] == "shortlisted"
    assert by["good"]["flow"]["overlap_bound"] is not None
    c = out["counts"]
    assert (c["capability_rejected"], c["numeric_rejected"],
            c["degradation_rejected"], c["shortlisted"]) == (1, 1, 1, 1)


@pytest.fixture(scope="module")
def static_doc():
    """One full-registry static survey under both acceptance topologies,
    shared across the ranking assertions (the expensive part is the flow
    audit of each topology's ranked head)."""
    return run_tune(("8", "256,8"), static_only=True, shortlist_n=2,
                    argv="test-static")


def test_static_ranks_full_registry_under_both_topologies(static_doc):
    assert set(static_doc["static"]) == {"W8", "W256/slice8"}
    for label, st in static_doc["static"].items():
        # every enumerated candidate leaves the funnel with a verdict
        assert all(r.get("verdict") for r in st["funnel"]), label
        rejected = [r for r in st["funnel"] if r["verdict"] == "rejected"]
        assert all(r.get("reason") for r in rejected), label
        assert st["counts"]["enumerated"] == len(st["funnel"])
        assert len(st["ranking"]) == st["counts"]["priced"]
    assert static_doc["ok"] is True


def test_static_top_pick_at_xslice_is_sharded_or_hier_family(static_doc):
    """ISSUE 12/14 acceptance: the top static pick at W=256/slice8 is the
    rscatter family — the ISSUE-14 one-shot reduce-scatter moves ~2·k
    over DCN where hier still ships (K−1)·k/S partials, and its requant
    chain is ≤1 at any W so the degradation gate never rejects it — with
    the hier family (the pinned 1.06× xslice projection) right behind,
    still carrying the genuinely mixed split."""
    st = static_doc["static"]["W256/slice8"]
    top = st["ranking"][0]
    rec = next(r for r in st["funnel"] if r["candidate"] == top["candidate"])
    assert rec["params"]["communicator"] == "rscatter"
    assert rec["requant_chain"] <= 1
    assert top["predicted_speedup_vs_dense"] > 1.0
    # hier is the runner-up family, and its mixed split is real: both
    # links carry bytes
    hier = next(r for r in st["ranking"]
                if "hier" in r["candidate"])
    assert hier["ici_bytes"] > 0 and hier["dcn_bytes"] > 0
    # while the flat-communicator candidates degenerate to all-DCN there
    flat = next(r for r in st["funnel"]
                if r["candidate"] == "topk-allgather"
                and r.get("predicted"))
    assert flat["predicted"]["ici_bytes"] == 0
    assert flat["predicted"]["dcn_bytes"] > 0


def test_cost_model_stamped_and_shared_with_bench(static_doc):
    cm = static_doc["cost_model"]
    assert cm["ici_bytes_per_s"] == bench.ICI_RING_BYTES_PER_S
    assert cm["dcn_bytes_per_s"] == bench.DCN_BYTES_PER_S
    assert "recv_link_bytes" in cm["rule"]


def test_tune_determinism(tmp_path):
    """Same registry + topology → byte-identical TUNE_LAST.json modulo
    the two timestamps (captured_at, provenance.generated_utc)."""
    paths = []
    for i in range(2):
        doc = run_tune(("8",), static_only=True, shortlist_n=1,
                       argv="determinism")
        p = tmp_path / f"tune{i}.json"
        write_tune_evidence(doc, str(p))
        paths.append(p)

    def canon(p):
        d = json.loads(p.read_text())
        d.pop("captured_at")
        d["provenance"].pop("generated_utc")
        return json.dumps(d, sort_keys=True)

    assert canon(paths[0]) == canon(paths[1])


# ---------------------------------------------------------------------------
# end-to-end: measured shortlist + sandwich + evidence
# ---------------------------------------------------------------------------

def test_tune_e2e_cpu_winner_and_sandwich(mesh, tmp_path, monkeypatch):
    """The whole loop on the 8-device CPU mesh: enumerate → prune →
    measure (real timed steps, dense brackets interleaved same-session) →
    winner stamped with provenance + topology + the measured≤static
    sandwich — and the winner beats the worst shortlisted candidate on
    measured step time (what makes the measured stage worth its steps)."""
    doc = run_tune(("8",), shortlist_n=2, timed_steps=2, repeats=1,
                   mesh=mesh, trace_dir=str(tmp_path / "prof"),
                   argv="e2e")
    assert doc["ok"] is True
    rows = doc["measured"]["rows"]
    assert len(rows) >= 2
    assert all(r["same_session"] for r in rows)
    w = doc["winner"]
    winner_row = next(r for r in rows if r["candidate"] == w["candidate"])
    worst = max(rows, key=lambda r: r["measured_step_ms"])
    assert winner_row["measured_step_ms"] <= worst["measured_step_ms"]
    # provenance-stamped, topology-stamped, loadable
    assert doc["provenance"]["git_commit"]
    assert w["topology"] == {"world": 8, "slice_size": None}
    rebuilt = grace_from_params(dict(w["grace_params"]))
    assert type(rebuilt.communicator).__name__   # builds verbatim
    # the honesty gate
    s = w["overlap_sandwich"]
    assert s["holds"] and s["violations"] == []
    if s["measured_overlap"] is not None:
        assert s["measured_overlap"] \
            <= s["static_overlap_bound"] + s["slack"]

    # evidence round-trip: TUNE_LAST.json consumed by evidence_summary
    write_tune_evidence(doc, str(tmp_path / "TUNE_LAST.json"))
    evidence_summary = _load_tool("evidence_summary")
    monkeypatch.setattr(evidence_summary, "ROOT", str(tmp_path))
    md = evidence_summary.build()
    assert "Autotuning (graft-tune)" in md
    assert w["candidate"] in md
    assert "sandwich" in md and "holds" in md


def test_graft_tune_cli_static(tmp_path):
    """tools/graft_tune.py --static-only: exit 0, evidence written."""
    tool = _load_tool("graft_tune")
    out = tmp_path / "TUNE_LAST.json"
    rc = tool.main(["--static-only", "--topology", "8",
                    "--shortlist", "1", "--out", str(out)])
    assert rc == 0
    doc = json.loads(out.read_text())
    assert doc["tool"] == "graft_tune" and doc["static_only"]
    assert doc["static"]["W8"]["counts"]["enumerated"] > 40


# ---------------------------------------------------------------------------
# satellites: lint registry coverage, bench_all --tuned, stale evidence
# ---------------------------------------------------------------------------

def test_variant_configs_registered_for_lint():
    """The tuner-generated variants are first-class lint registry entries
    — what the tuner can emit is never a static-analysis blind spot."""
    from grace_tpu.analysis import AUDIT_CONFIGS
    names = {e["name"] for e in AUDIT_CONFIGS}
    for name, params, _why in variant_audit_entries():
        assert name in names
        entry = next(e for e in AUDIT_CONFIGS if e["name"] == name)
        assert entry["params"] == params
    # and they are part of the enumerated candidate space
    cand_names = {c.name for c in enumerate_candidates(W8)}
    assert {"tune-topk1pct-hier-bucketed",
            "tune-qsgd4-hier-packed"} <= cand_names


def test_variant_config_audits_clean():
    from grace_tpu.analysis import AUDIT_CONFIGS, audit_config
    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "tune-qsgd4-hier-packed")
    findings = audit_config(entry)
    assert [f for f in findings if f.severity == "error"] == []


def test_bench_all_tuned_family(monkeypatch):
    names = {c["name"] for c in bench_all.CONFIGS}
    assert set(bench_all.TUNED_ROW_NAMES) <= names
    row = next(c for c in bench_all.CONFIGS
               if c["name"] == "qsgd4_packed_bucketed_pallas_bs256")
    assert row["tpu_only"] and row["per_device_bs"] == 256
    assert row["params"] == {"compressor": "qsgd", "quantum_num": 7,
                             "use_pallas": True, "memory": "none",
                             "communicator": "ring", "fusion": 1024}
    hier = next(c for c in bench_all.CONFIGS
                if c["name"] == "topk1pct_hier_bs256")
    assert hier["params"]["slice_size"] == 8    # the projection topology
    # --tuned selection: one command, dense anchor first, nothing else
    monkeypatch.setenv("GRACE_BENCH_TUNED", "1")
    active = bench_all.active_configs()
    assert [c["name"] for c in active][0] == "none"
    assert {c["name"] for c in active} == set(bench_all.TUNED_ROW_NAMES)
    monkeypatch.delenv("GRACE_BENCH_TUNED")
    assert len(bench_all.active_configs()) == len(bench_all.CONFIGS)


def test_evidence_staleness_detector():
    # The committed captures predate PRs 7-10: no provenance block, no
    # fusion row stamps, no hier rows — all three detectors fire.
    head = bench.load_tpu_evidence(
        os.path.join(os.path.dirname(bench.__file__),
                     "BENCH_TPU_LAST.json"))
    assert head is not None
    reasons = bench.evidence_staleness(head)
    assert reasons and any("provenance" in r for r in reasons)
    sweep = bench.load_tpu_evidence(bench.SWEEP_SUMMARY_PATH)
    assert any("PR 7" in r for r in bench.evidence_staleness(sweep))
    # A fresh-shaped capture clears every detector.
    fresh = {
        "provenance": {"git_commit": "abc1234", "pallas_enabled": True,
                       "fusion": 1024},
        "rows": [
            {"config": "none", "imgs_per_sec": 1.0, "fusion": None,
             "grace_params": {"communicator": "allreduce"}},
            {"config": "topk1pct_hier_bs256", "imgs_per_sec": 1.0,
             "fusion": "flat", "grace_params": {"communicator": "hier"}},
            {"config": "qsgd4_packed_bucketed_pallas_bs256",
             "imgs_per_sec": 1.0, "fusion": 1024,
             "grace_params": {"communicator": "ring"}},
        ],
    }
    assert bench.evidence_staleness(fresh) == []
    # _mark_stale stamps the carried-along copy, never the clean one.
    assert "stale" not in bench._mark_stale(fresh)
    marked = bench._mark_stale(head)
    assert marked["stale"] == bench.STALE_BANNER
    assert marked["stale_reasons"]


# ---------------------------------------------------------------------------
# graft-wire tuner integration (ISSUE 19)
# ---------------------------------------------------------------------------

def test_price_candidate_wire_pipeline_discount():
    """The double-buffered ring's declared overlap fraction discounts the
    compressed wire leg — and ONLY that leg: link bytes are
    pipeline-invariant and the dense bracket always rides the flat
    undiscounted psum."""
    from grace_tpu.tuning.cost import price_candidate
    structs = model_structs("toy")
    base = {"compressor": "qsgd", "quantum_num": 7, "use_pallas": False,
            "memory": "none", "communicator": "ring", "fusion": "flat"}
    serial = price_candidate(grace_from_params(base), structs, W8)
    piped = price_candidate(
        grace_from_params({**base, "pipeline": 2}), structs, W8)
    assert serial["wire_pipeline_overlap"] == 0.0
    assert piped["wire_pipeline_overlap"] == 0.25   # 0.5 * (2-1)/2
    # same bytes on the wire — the discount models overlap, not volume
    for k in ("payload_bytes", "ici_bytes", "dcn_bytes", "wire_ms"):
        assert piped[k] == serial[k], k
    assert piped["projected_step_ms"] == pytest.approx(
        0.75 * serial["projected_step_ms"], abs=1e-9)   # record rounds @9dp
    assert piped["dense_projected_step_ms"] == \
        serial["dense_projected_step_ms"]
    # deeper buffering asymptotes at the declared efficiency cap
    p4 = price_candidate(
        grace_from_params({**base, "pipeline": 4}), structs, W8)
    assert p4["wire_pipeline_overlap"] == 0.375     # 0.5 * (4-1)/4


def test_pipelined_variant_candidate_registered_and_audits_clean():
    """The tuner-generated pipelined ring variant is a legal candidate, a
    first-class lint registry entry, and traces clean — flow pass 5's
    pipelined-chain referee is the static backing for the pricing
    discount, so the discounted candidate can never be an audit blind
    spot."""
    from grace_tpu.analysis import AUDIT_CONFIGS, audit_config
    name = "tune-qsgd4-ring-packed-pipelined"
    assert name in {n for n, _, _ in variant_audit_entries()}
    cand = next(c for c in enumerate_candidates(W8) if c.name == name)
    assert cand.params["pipeline"] == 2
    legal, reason, _ = candidate_legal(cand, W8)
    assert legal, reason
    entry = next(e for e in AUDIT_CONFIGS if e["name"] == name)
    findings = audit_config(entry)
    assert [f for f in findings if f.severity == "error"] == []


def test_numeric_gate_shared_scale_2bit():
    """The 2-bit shared-scale accumulator bound: accum_bits=2 at q=1
    holds ONE level sum (payload_sum_max_world=1), so any multi-rank
    topology dies in the numeric stage — the same single constant the
    communicators raise on a live mesh and flow pass 6 flags statically."""
    homo2 = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 1, "accum_bits": 2,
        "use_pallas": False, "memory": "residual", "communicator": "ring",
        "fusion": "flat"})
    assert homo2.compressor.payload_sum_max_world() == 1
    reason = numeric_verdict(homo2, TuneTopology(world=2))
    assert reason is not None and "payload_sum_max_world=1" in reason
    # the 4-bit sibling survives exactly to its own bound (7) and no
    # further — the registry's world=4 audit override is inside it
    homo4 = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 1, "accum_bits": 4,
        "use_pallas": False, "memory": "residual", "communicator": "ring",
        "fusion": "flat"})
    assert numeric_verdict(homo4, TuneTopology(world=4)) is None
    r8 = numeric_verdict(homo4, W8)
    assert r8 is not None and "payload_sum_max_world=7" in r8


def test_evidence_summary_stale_banner(tmp_path, monkeypatch):
    evidence_summary = _load_tool("evidence_summary")
    monkeypatch.setattr(evidence_summary, "ROOT", str(tmp_path))
    stale_doc = {"chip": "TPU v5 lite", "captured_at": "2026-08-01",
                 "rows": [{"config": "topk1pct", "imgs_per_sec": 2264.6,
                           "vs_baseline": 0.9897}]}
    (tmp_path / "BENCH_TPU_LAST.json").write_text(json.dumps(stale_doc))
    md = evidence_summary.build()
    assert "STALE — predates PRs 7–10" in md
    assert "bench_all.py --tuned" in md
    # a fresh doc renders with no banner
    fresh = {**stale_doc,
             "provenance": {"pallas_enabled": True, "fusion": None},
             "rows": [{**stale_doc["rows"][0], "fusion": None}]}
    (tmp_path / "BENCH_TPU_LAST.json").write_text(json.dumps(fresh))
    assert "STALE" not in evidence_summary.build()
