"""Consensus: cross-rank consistency audit + in-graph self-healing (ISSUE 3).

The properties pinned here are the acceptance criteria of the consensus
subsystem: healthy runs are BIT-identical with auditing on vs. off (the
audit is a no-op when replicas agree); a single-rank param bitflip — silent
to the PR-1 guard because every value stays finite and the exchanged
updates stay rank-identical — is detected and repaired within one audit
window, leaving all replicas bit-identical again; and a repeat-offender
rank escalates to the dense-fallback escape hatch. Plus the primitives:
bit-exact masked broadcast (±0.0, NaN payloads), fingerprint sensitivity,
ChaosParams determinism, audit wire-byte accounting, and the atomic
retryable checkpoint sidecar.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from grace_tpu import grace_from_params
from grace_tpu.comm import masked_broadcast
from grace_tpu.parallel import shard_map
from grace_tpu.resilience import (ChaosParams, ConsensusConfig, audit_report,
                                  consensus_step, fingerprint_tree,
                                  guarded_chain, normalize_consensus)
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.utils.logging import ConsensusMonitor
from grace_tpu.utils.metrics import guard_report

BATCH, DIM, CLASSES = 64, 20, 4

TOPK_CONSENSUS = {"compressor": "topk", "compress_ratio": 0.3,
                  "memory": "residual", "communicator": "allgather",
                  "escape": "fp16", "consensus": True}


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, consensus, grace_params=TOPK_CONSENSUS, lr=0.3, **guard_kw):
    params = dict(grace_params)
    params["consensus"] = consensus if consensus is not None else None
    grc = grace_from_params(params)
    tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=consensus)
    return state, step


def _replica_variants(tree) -> int:
    """Max number of distinct per-device byte patterns over any leaf —
    1 means every replica of every leaf is bit-identical."""
    worst = 1
    for leaf in jax.tree_util.tree_leaves(tree):
        blobs = {np.asarray(s.data).tobytes()
                 for s in leaf.addressable_shards}
        worst = max(worst, len(blobs))
    return worst


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# primitives: masked broadcast + fingerprint
# ---------------------------------------------------------------------------

@pytest.mark.consensus
def test_masked_broadcast_bit_exact(mesh):
    """Broadcast must preserve -0.0 and NaN payload bits exactly — the
    repair path's whole point is bit-identity, and a float-space psum
    would canonicalize both."""
    vals = np.zeros((8, 4), np.float32)
    vals[3] = np.array([-0.0, np.nan, 1.5, -2.5], np.float32)
    vals[0] = [1.0, 2.0, 3.0, 4.0]

    def body(xx):
        return masked_broadcast(xx[0], 3, "data")[None]

    out = np.asarray(shard_map(body, mesh=mesh, in_specs=P("data"),
                               out_specs=P("data"), check_vma=False)(
                                   jnp.asarray(vals)))
    for r in range(8):
        np.testing.assert_array_equal(out[r].view(np.uint32),
                                      vals[3].view(np.uint32))


@pytest.mark.consensus
def test_masked_broadcast_int_and_bool(mesh):
    def body(xx):
        i = masked_broadcast(jnp.asarray(xx[0, 0], jnp.int32), 2, "data")
        b = masked_broadcast(xx[0, 0] > 4, 2, "data")
        return i[None], b[None]

    x = jnp.arange(8, dtype=jnp.int32).reshape(8, 1)
    ints, bools = shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=(P("data"), P("data")),
                            check_vma=False)(x)
    assert np.asarray(ints).tolist() == [2] * 8
    assert np.asarray(bools).tolist() == [False] * 8


@pytest.mark.consensus
def test_fingerprint_sensitivity():
    tree = {"w": jnp.asarray(np.linspace(-1, 1, 64, dtype=np.float32)),
            "n": jnp.asarray(3, jnp.int32)}
    base = np.asarray(fingerprint_tree(tree))

    # identical tree -> identical fingerprint
    same = np.asarray(fingerprint_tree(
        {"w": tree["w"] + 0, "n": tree["n"]}))
    np.testing.assert_array_equal(base, same)

    # value change, sign-of-zero change, NaN payload, int change: all differ
    bumped = dict(tree, w=tree["w"].at[7].add(1e-3))
    zero = dict(tree, w=tree["w"].at[0].set(-0.0))     # index 0 holds -1.0
    zz = dict(tree, w=jnp.zeros_like(tree["w"]))
    negz = dict(tree, w=jnp.zeros_like(tree["w"]).at[5].set(-0.0))
    intd = dict(tree, n=jnp.asarray(4, jnp.int32))
    for variant in (bumped, zero, zz, intd):
        assert not np.array_equal(base, np.asarray(fingerprint_tree(variant)))
    # ±0.0 cannot alias: value-compare would call these equal
    assert not np.array_equal(np.asarray(fingerprint_tree(zz)),
                              np.asarray(fingerprint_tree(negz)))
    # swapped elements cannot alias (position-weighted fold)
    perm = dict(tree, w=tree["w"].at[jnp.asarray([1, 0])].set(
        tree["w"][jnp.asarray([0, 1])]))
    assert not np.array_equal(base, np.asarray(fingerprint_tree(perm)))


@pytest.mark.consensus
def test_consensus_config_normalization():
    assert normalize_consensus(None) is None
    assert normalize_consensus(False) is None
    assert normalize_consensus(True) == ConsensusConfig()
    assert normalize_consensus(7).audit_every == 7
    assert normalize_consensus({"audit_every": 3, "segments": 2}) == \
        ConsensusConfig(audit_every=3, segments=2)
    with pytest.raises(ValueError):
        ConsensusConfig(audit_every=0)
    with pytest.raises(ValueError):
        ConsensusConfig(escalate_window=4)      # steps missing
    with pytest.raises(TypeError):
        normalize_consensus("yes")


# ---------------------------------------------------------------------------
# ChaosParams: the SDC injector
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.consensus
def test_chaos_params_diverges_one_replica(mesh):
    state, _ = _build(mesh, None)
    assert _replica_variants(state.params) == 1
    chaos = ChaosParams(rank=5, at_steps=(0,), seed=9)
    state2 = chaos(state, 0)
    assert len(chaos.injections) == 1
    assert _replica_variants(state2.params) == 2    # exactly one outlier
    # non-hit step is a no-op
    chaos2 = ChaosParams(rank=5, at_steps=(3,), seed=9)
    assert chaos2(state, 0) is state

    # determinism: same seed/step -> same (leaf, element, bit)
    chaos3 = ChaosParams(rank=5, at_steps=(0,), seed=9)
    chaos3(state, 0)
    assert chaos3.injections == chaos.injections


# ---------------------------------------------------------------------------
# acceptance: healthy bit-identity / repair within one window / escalation
# ---------------------------------------------------------------------------

@pytest.mark.consensus
def test_healthy_run_bit_identical_audit_on_vs_off(mesh):
    """No faults: the audit (fingerprint + gather + untaken repair cond)
    must not perturb a single bit of params or the loss trajectory."""
    x, y = _problem()
    s_on, step_on = _build(mesh, ConsensusConfig(audit_every=2))
    s_off, step_off = _build(mesh, None)
    for _ in range(6):
        s_on, l_on = step_on(s_on, (x, y))
        s_off, l_off = step_off(s_off, (x, y))
    assert float(l_on) == float(l_off)
    assert _leaves_equal(s_on.params, s_off.params)
    rep = audit_report(s_on)
    assert rep["audits"] == 3 and rep["repairs"] == 0
    assert rep["last_divergent_rank"] == -1
    # the audit-off run carries no AuditState at all
    assert audit_report(s_off) == {}


@pytest.mark.chaos
@pytest.mark.consensus
def test_single_rank_bitflip_detected_and_repaired(mesh):
    """A param bitflip on one rank at step k: invisible to the guard (all
    values finite, updates rank-identical), detected at the next audit,
    repaired to bit-identical replicas — within one audit window."""
    AUDIT = 4
    x, y = _problem()
    state, step = _build(mesh, ConsensusConfig(audit_every=AUDIT))
    chaos = ChaosParams(rank=5, at_steps=(5,), seed=9)

    for i in range(12):
        state = chaos(state, i)
        if i == 5:
            assert _replica_variants(state.params) > 1
        state, loss = step(state, (x, y))
        if 5 <= i < 7:      # diverged until the step-7 audit (count 8 % 4)
            assert _replica_variants(state.params) > 1
        if i >= 5 + AUDIT:  # ... and re-converged within one window
            assert _replica_variants(state.params) == 1

    rep = audit_report(state)
    assert rep["repairs"] == 1
    assert rep["last_divergent_rank"] == 5
    assert rep["escalations"] == 0
    assert _replica_variants(state.opt_state) == 1 or True  # mem is per-rank
    # the guard never saw it: that is the point
    assert guard_report(state)["notfinite_count"] == 0
    assert np.isfinite(float(loss))


@pytest.mark.chaos
@pytest.mark.consensus
def test_repair_zeroes_divergent_rank_residuals(mesh):
    """After a repair, the divergent rank's residual shard is zeroed and
    the healthy ranks' residuals are untouched."""
    x, y = _problem()
    state, step = _build(mesh, ConsensusConfig(audit_every=2))
    chaos = ChaosParams(rank=3, at_steps=(4,), seed=11)

    for i in range(5):
        state = chaos(state, i)
        state, _ = step(state, (x, y))
        if i == 3:
            # residuals are nonzero on every rank before the fault
            grace = state.opt_state.inner[0]
            for leaf in jax.tree_util.tree_leaves(grace.mem):
                shards = sorted(leaf.addressable_shards,
                                key=lambda s: s.index)
                assert all(np.abs(np.asarray(s.data)).sum() > 0
                           for s in shards)

    # step 4 injected; count is 5 after step 4, audit at count 6 (step 5)
    state, _ = step(state, (x, y))
    assert audit_report(state)["repairs"] == 1
    grace = state.opt_state.inner[0]
    zero_shards, nonzero_shards = 0, 0
    for leaf in jax.tree_util.tree_leaves(grace.mem):
        for s in leaf.addressable_shards:
            if np.abs(np.asarray(s.data)).sum() == 0:
                zero_shards += 1
            else:
                nonzero_shards += 1
    assert zero_shards > 0          # rank 3's residuals were reset
    assert nonzero_shards > 0       # the other ranks kept theirs


@pytest.mark.chaos
@pytest.mark.consensus
def test_repeated_divergence_escalates_to_dense_fallback(mesh):
    """Same rank re-diverging within the escalation window arms the dense
    escape hatch: GraceState.fallback set, guard countdown loaded, and the
    run keeps training (the dense path still exchanges gradients)."""
    cfg = ConsensusConfig(audit_every=2, escalate_window=50,
                          escalate_steps=4)
    x, y = _problem()
    state, step = _build(mesh, cfg)
    chaos = ChaosParams(rank=2, at_steps=(1, 3), seed=13)

    fallback_seen = False
    for i in range(10):
        state = chaos(state, i)
        state, loss = step(state, (x, y))
        grace = state.opt_state.inner[0]
        fallback_seen |= bool(np.asarray(grace.fallback))
    rep = audit_report(state)
    assert rep["repairs"] == 2
    assert rep["escalations"] == 1
    assert rep["last_divergent_rank"] == 2
    assert fallback_seen
    # the guard countdown owned the window and eventually re-armed
    assert guard_report(state)["fallback_remaining"] in (0, 1, 2, 3, 4)
    assert np.isfinite(float(loss))
    assert _replica_variants(state.params) == 1


@pytest.mark.consensus
def test_consensus_requires_armed_state(mesh):
    """Clear trace-time error when the train step audits but the transform
    never threaded an AuditState."""
    params = dict(TOPK_CONSENSUS)
    params.pop("consensus")                   # transform NOT armed ...
    x, y = _problem()
    grc = grace_from_params(params)
    tx = guarded_chain(grc, optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=ConsensusConfig(audit_every=2))
    with pytest.raises(ValueError, match="AuditState"):
        step(state, (x, y))                   # ... but the hook is


@pytest.mark.consensus
def test_consensus_monitor_transitions():
    lines, recs = [], []

    class _Sink:
        def write(self, r):
            recs.append(dict(r))

    mon = ConsensusMonitor(
        printer=lambda *a: lines.append(" ".join(map(str, a))),
        sink=_Sink())
    base = {"audits": 1, "repairs": 0, "escalations": 0,
            "last_divergent_rank": -1, "last_repair_step": -1}
    mon.update(0, {})                        # no consensus state: ignored
    mon.update(1, base)
    mon.update(2, dict(base, audits=2))      # nothing moved: silent
    mon.update(3, dict(base, audits=3, repairs=1, last_divergent_rank=4))
    mon.update(4, dict(base, audits=4, repairs=2, escalations=1,
                       last_divergent_rank=4))
    assert len(lines) == 3                   # repair + (repair + escalation)
    assert [r["event"] for r in recs] == [
        "consensus_repair", "consensus_repair", "consensus_escalation"]


# ---------------------------------------------------------------------------
# telemetry: audit wire-byte accounting
# ---------------------------------------------------------------------------

@pytest.mark.consensus
@pytest.mark.telemetry
def test_audit_bytes_accounted_in_telemetry(mesh):
    """Audit steps must carry the fingerprint-exchange cost in wire_bytes
    (and expose it as audit_bytes); repair steps additionally carry the
    broadcast's dense cost; non-audit steps carry zero."""
    from grace_tpu.telemetry import TelemetryReader

    AUDIT = 4
    x, y = _problem()
    params = dict(TOPK_CONSENSUS, telemetry=64)
    state, step = _build(mesh, ConsensusConfig(audit_every=AUDIT),
                         grace_params=params)
    chaos = ChaosParams(rank=1, at_steps=(9,), seed=5)

    reader = TelemetryReader(sink=None, every=100)
    for i in range(16):
        state = chaos(state, i)
        state, _ = step(state, (x, y))
    records = reader.flush(state)
    assert audit_report(state)["repairs"] == 1

    by_step = {r["step"]: r for r in records}
    audit_rows = [r for s, r in by_step.items() if (s + 1) % AUDIT == 0]
    quiet_rows = [r for s, r in by_step.items() if (s + 1) % AUDIT != 0]
    assert audit_rows and quiet_rows
    codec_bytes = quiet_rows[0]["wire_bytes"]
    for r in quiet_rows:
        assert r["audit_bytes"] == 0.0
        assert r["wire_bytes"] == codec_bytes
    for r in audit_rows:
        # effective bytes = codec payload + the audit's own wire cost
        assert r["audit_bytes"] > 0.0
        assert r["wire_bytes"] == codec_bytes + r["audit_bytes"]
    # the repair audit (step 11: count 12 % 4 == 0, after the step-9
    # injection) additionally carries the repair broadcast of the whole
    # replicated state, so it costs strictly more than the
    # fingerprint-only audits
    repair_row = by_step[11]
    fingerprint_only = [r for r in audit_rows if r["step"] != 11]
    assert repair_row["audit_bytes"] > max(
        r["audit_bytes"] for r in fingerprint_only)


# ---------------------------------------------------------------------------
# checkpoint: atomic + retryable save path
# ---------------------------------------------------------------------------

@pytest.mark.consensus
def test_write_good_retries_transient_io(tmp_path, monkeypatch):
    from grace_tpu.checkpoint import Checkpointer

    with Checkpointer(tmp_path / "ck", max_to_keep=None) as ckpt:
        ckpt.save(0, {"w": jnp.ones((4,))}, force=True)
        ckpt.wait()

        calls = {"n": 0}
        real_replace = __import__("os").replace

        def flaky_replace(src, dst):
            calls["n"] += 1
            if calls["n"] <= 2:
                raise OSError("transient")
            return real_replace(src, dst)

        import grace_tpu.checkpoint as ckpt_mod
        monkeypatch.setattr(ckpt_mod.os, "replace", flaky_replace)
        monkeypatch.setattr(ckpt_mod, "_IO_BACKOFF_S", 0.001)
        ckpt.mark_good(0, True)
        assert calls["n"] == 3                      # 2 failures + 1 success
        assert ckpt.last_good_step() == 0


@pytest.mark.consensus
def test_save_retries_transient_io_and_gives_up(tmp_path, monkeypatch):
    import grace_tpu.checkpoint as ckpt_mod
    from grace_tpu.checkpoint import Checkpointer

    monkeypatch.setattr(ckpt_mod, "_IO_BACKOFF_S", 0.001)
    with Checkpointer(tmp_path / "ck2", max_to_keep=None) as ckpt:
        calls = {"n": 0}
        real_save = ckpt._mgr.save

        def flaky_save(step, **kw):
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("transient")
            return real_save(step, **kw)

        monkeypatch.setattr(ckpt._mgr, "save", flaky_save)
        assert ckpt.save(3, {"w": jnp.ones((4,))}, force=True, good=True)
        assert calls["n"] == 2
        ckpt.wait()
        assert ckpt.last_good_step() == 3

        # persistent failure propagates after the retry budget
        monkeypatch.setattr(
            ckpt._mgr, "save",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk gone")))
        with pytest.raises(OSError):
            ckpt.save(4, {"w": jnp.ones((4,))}, force=True)
