import jax.numpy as jnp
import numpy as np

from grace_tpu.ops import pack_2bit, pack_bits, unpack_2bit, unpack_bits


def test_pack_bits_roundtrip(rng):
    for n in [1, 7, 8, 9, 64, 1000]:
        bits = rng.integers(0, 2, size=n).astype(bool)
        packed = pack_bits(jnp.asarray(bits))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 8),)
        out = unpack_bits(packed, n)
        np.testing.assert_array_equal(np.asarray(out), bits)


def test_pack_2bit_roundtrip(rng):
    for n in [1, 3, 4, 5, 17, 1000]:
        codes = rng.integers(0, 4, size=n).astype(np.uint8)
        packed = pack_2bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 4),)
        out = unpack_2bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)
