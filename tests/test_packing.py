import jax.numpy as jnp
import numpy as np

from grace_tpu.ops import (pack_2bit, pack_4bit, pack_bits, unpack_2bit,
                           unpack_4bit, unpack_bits)


def test_pack_bits_roundtrip(rng):
    for n in [1, 7, 8, 9, 64, 1000]:
        bits = rng.integers(0, 2, size=n).astype(bool)
        packed = pack_bits(jnp.asarray(bits))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 8),)
        out = unpack_bits(packed, n)
        np.testing.assert_array_equal(np.asarray(out), bits)


def test_pack_2bit_roundtrip(rng):
    for n in [1, 3, 4, 5, 17, 1000]:
        codes = rng.integers(0, 4, size=n).astype(np.uint8)
        packed = pack_2bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 4),)
        out = unpack_2bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)


def test_pack_4bit_roundtrip(rng):
    for n in [1, 2, 3, 17, 1000]:
        codes = rng.integers(0, 16, size=n).astype(np.uint8)
        packed = pack_4bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 2),)
        out = unpack_4bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)


def test_pack_4bit_low_nibble_first():
    """The byte layout the fused Pallas kernel emits: element 0 is the LOW
    nibble — pinned so kernel and reference packer can never disagree."""
    packed = np.asarray(pack_4bit(jnp.asarray([0x3, 0xA], dtype=jnp.uint8)))
    assert packed.tolist() == [0xA3]


def test_pack_widths_declares_all_packers():
    """The numeric-safety audit contract covers 1/2/4-bit packers — the
    4-bit entry is what puts QSGD's packed wire format under audit."""
    from grace_tpu.ops.packing import pack_widths
    widths = {w for w, _, _ in pack_widths()}
    assert widths == {1, 2, 4}
    for width, pack, unpack in pack_widths():
        n = 9
        codes = np.full((n,), (1 << width) - 1, np.uint8)
        packed = np.asarray(pack(jnp.asarray(codes)))
        assert packed.size == -(-n * width // 8)
        np.testing.assert_array_equal(
            np.asarray(unpack(jnp.asarray(packed), n)), codes)
