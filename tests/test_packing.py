import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu.ops import (pack_2bit, pack_3bit, pack_4bit, pack_bits,
                           unpack_2bit, unpack_3bit, unpack_4bit,
                           unpack_bits)


def test_pack_bits_roundtrip(rng):
    for n in [1, 7, 8, 9, 64, 1000]:
        bits = rng.integers(0, 2, size=n).astype(bool)
        packed = pack_bits(jnp.asarray(bits))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 8),)
        out = unpack_bits(packed, n)
        np.testing.assert_array_equal(np.asarray(out), bits)


def test_pack_2bit_roundtrip(rng):
    for n in [1, 3, 4, 5, 17, 1000]:
        codes = rng.integers(0, 4, size=n).astype(np.uint8)
        packed = pack_2bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 4),)
        out = unpack_2bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)


def test_pack_3bit_roundtrip(rng):
    for n in [1, 2, 3, 7, 8, 9, 17, 1000]:
        codes = rng.integers(0, 8, size=n).astype(np.uint8)
        packed = pack_3bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-3 * n // 8),)
        out = unpack_3bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)


def test_pack_3bit_lsb_first_bitstream():
    """The declared 3-bit layout: bit b of code l is global bit 3l+b,
    global bit 8j+k is bit k of byte j — pinned so the fused Pallas
    bit-plane decode can never disagree with the reference packer."""
    # codes [0b101, 0b011, 0b110] -> bitstream (LSB-first per code)
    # 1,0,1, 1,1,0, 0,1,1 -> byte0 = 0b10011101 = 157, byte1 = 0b1
    packed = np.asarray(pack_3bit(jnp.asarray([5, 3, 6], dtype=jnp.uint8)))
    assert packed.tolist() == [0b10011101, 0b1]


def test_pack_4bit_roundtrip(rng):
    for n in [1, 2, 3, 17, 1000]:
        codes = rng.integers(0, 16, size=n).astype(np.uint8)
        packed = pack_4bit(jnp.asarray(codes))
        assert packed.dtype == jnp.uint8
        assert packed.shape == (-(-n // 2),)
        out = unpack_4bit(packed, n)
        np.testing.assert_array_equal(np.asarray(out), codes)


def test_pack_4bit_low_nibble_first():
    """The byte layout the fused Pallas kernel emits: element 0 is the LOW
    nibble — pinned so kernel and reference packer can never disagree."""
    packed = np.asarray(pack_4bit(jnp.asarray([0x3, 0xA], dtype=jnp.uint8)))
    assert packed.tolist() == [0xA3]


def test_pack_widths_declares_all_packers():
    """The numeric-safety audit contract covers every shipped width —
    1-bit (sign masks), 2-bit (qsgd/homoqsgd at quantum_num<=1), 3-bit
    (<=3) and 4-bit (<=7): each new width joins the flow pass-6 audit the
    moment it joins this tuple."""
    from grace_tpu.ops.packing import pack_widths
    widths = {w for w, _, _ in pack_widths()}
    assert widths == {1, 2, 3, 4}
    for width, pack, unpack in pack_widths():
        n = 9
        codes = np.full((n,), (1 << width) - 1, np.uint8)
        packed = np.asarray(pack(jnp.asarray(codes)))
        assert packed.size == -(-n * width // 8)
        np.testing.assert_array_equal(
            np.asarray(unpack(jnp.asarray(packed), n)), codes)


@pytest.mark.parametrize("n", [1, 3, 5, 7, 9, 11, 13, 17, 23, 63, 97, 255])
def test_roundtrip_property_every_width_odd_lengths(rng, n):
    """Round-trip property across the full width × odd-length grid: any
    in-range code vector reconstructs exactly and the byte count matches
    the declared ceil(n*width/8) — odd lengths exercise every partial
    tail byte (1-bit: n%8, 2-bit: n%4, 3-bit: straddled boundaries,
    4-bit: n%2)."""
    from grace_tpu.ops.packing import pack_widths
    for width, pack, unpack in pack_widths():
        codes = rng.integers(0, 1 << width, size=n).astype(np.uint8)
        packed = np.asarray(pack(jnp.asarray(codes)))
        assert packed.dtype == np.uint8
        assert packed.size == -(-n * width // 8), (width, n)
        got = np.asarray(unpack(jnp.asarray(packed), n)).astype(np.uint8)
        np.testing.assert_array_equal(got, codes, err_msg=f"w={width} n={n}")
