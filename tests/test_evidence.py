"""graft-evidence: the provenance ledger, claim gate and flight recorder.

Pins the ISSUE-17 contracts:

* ledger schema — required fields enforced at mint time, append-only
  with last-writer-wins per id, torn tails skipped;
* gate verdicts — sha mismatch → STALE, non-ancestor provenance rev →
  STALE (strict policy), measured claim whose topology world exceeds its
  capture's n_devices → gate failure;
* the two ancestry policies — an *unresolvable* rev passes the document
  detector (``bench.evidence_staleness``) but fails the gate;
* claim scanning — ratio-vs-dense lines must sit in a marker-carrying
  paragraph; fences and the generated evidence/gate blocks are exempt;
* the real repo's gate passes (the --ci acceptance criterion);
* Chrome-trace export → ``parse_chrome_trace`` round-trips exactly,
  including the multi-host merge;
* the incident recorder triggers, debounces, and attaches to the ledger;
* backfill is idempotent.

All host-side and device-free.
"""

from __future__ import annotations

import json
import os
import subprocess

import pytest

from grace_tpu.evidence import (backfill_ledger, gate, incident, ledger,
                                staleness)

pytestmark = pytest.mark.evidence

REPO = ledger.repo_root()


def _rec(**over):
    """A valid record template; tests override the field under test."""
    base = dict(id="t-rec", metric="m", value=1.0, claim_class="measured",
                capture="cap.json", capture_sha256="0" * 64,
                git_rev="deadbeef", platform="cpu", chip=None, n_devices=1,
                topology={"world": 1, "tiers": ["ici"], "slice": None,
                          "region": None},
                config="cfg", lint_clean=None, tool="test")
    base.update(over)
    return base


# ---------------------------------------------------------------------------
# ledger schema


def test_new_record_validates_schema():
    rec = ledger.new_record(**_rec())
    assert rec["timestamp"]                       # defaulted
    with pytest.raises(ValueError, match="missing fields"):
        ledger.new_record(**{k: v for k, v in _rec().items()
                             if k != "capture_sha256"})
    with pytest.raises(ValueError, match="claim_class"):
        ledger.new_record(**_rec(claim_class="vibes"))
    with pytest.raises(ValueError, match="topology"):
        ledger.new_record(**_rec(topology="8x1"))


def test_append_load_latest_and_torn_tail(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    ledger.append_record(_rec(id="a", value=1.0), path)
    ledger.append_record(_rec(id="b", value=2.0), path)
    ledger.append_record(_rec(id="a", value=3.0), path)
    with open(path, "a") as f:
        f.write('{"id": "torn", "met')          # killed writer
    recs = ledger.load_ledger(path)
    assert [r["id"] for r in recs] == ["a", "b", "a"]
    latest = ledger.latest_by_id(recs)
    assert latest["a"]["value"] == 3.0          # last writer wins
    assert latest["b"]["value"] == 2.0


def test_record_artifact_hashes_capture(tmp_path):
    cap = tmp_path / "cap.json"
    cap.write_text('{"rows": []}\n')
    path = str(tmp_path / "ledger.jsonl")
    rec = ledger.record_artifact(
        str(cap), id="x", metric="m", value=0.5, claim_class="projected",
        tool="test", platform="cpu", n_devices=1,
        topology={"world": 8, "tiers": ["ici"]}, config=None,
        lint_clean=None, ledger_path=path)
    assert rec is not None
    assert rec["capture_sha256"] == ledger.sha256_file(str(cap))
    assert ledger.load_ledger(path)[0]["id"] == "x"
    # raise-free contract: a bad claim_class reports None, never raises
    assert ledger.record_artifact(
        str(cap), id="y", metric="m", value=0.5, claim_class="vibes",
        tool="test", ledger_path=path) is None


# ---------------------------------------------------------------------------
# gate verdicts


def test_verify_record_sha_mismatch_is_stale(tmp_path):
    cap = tmp_path / "cap.json"
    cap.write_text("v1\n")
    rec = _rec(capture=str(cap), capture_sha256=ledger.sha256_file(str(cap)),
               git_rev=ledger.git_head_rev())
    assert gate.verify_record(rec)["status"] == "MEASURED"
    cap.write_text("v2 — capture edited after the record was minted\n")
    res = gate.verify_record(rec)
    assert res["status"] == "STALE"
    assert any("hash mismatch" in f for f in res["failures"])


def test_verify_record_class_mismatch(tmp_path):
    cap = tmp_path / "cap.json"
    cap.write_text("v1\n")
    sha = ledger.sha256_file(str(cap))
    head = ledger.git_head_rev()
    # A single-chip capture presented as a MEASURED world-256 claim is
    # the exact dishonesty the gate exists for.
    bad = _rec(capture=str(cap), capture_sha256=sha, git_rev=head,
               n_devices=1, topology={"world": 256, "tiers": ["ici", "dcn"]})
    res = gate.verify_record(bad)
    assert res["status"] == "STALE"
    assert any("class mismatch" in f for f in res["failures"])
    # ... while the same capture, honestly classed, is PROJECTED.
    ok = dict(bad, claim_class="projected")
    assert gate.verify_record(ok)["status"] == "PROJECTED"
    assert gate.verify_record(None)["status"] == "STALE"


def _seeded_history(tmp_path):
    """A throwaway repo whose history forks: main A--C, side branch B.
    Returns (repo_dir, side_rev_B) — B is NOT an ancestor of HEAD (C)."""
    repo = str(tmp_path / "hist")
    os.makedirs(repo)

    def git(*args):
        out = subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "-c", "commit.gpgsign=false"] + list(args),
            cwd=repo, capture_output=True, text=True)
        assert out.returncode == 0, out.stderr
        return out.stdout.strip()

    git("init", "-q")
    git("commit", "-q", "--allow-empty", "-m", "A")
    git("checkout", "-q", "-b", "side")
    git("commit", "-q", "--allow-empty", "-m", "B")
    side_rev = git("rev-parse", "HEAD")
    git("checkout", "-q", "-")
    git("commit", "-q", "--allow-empty", "-m", "C")
    return repo, side_rev


def test_non_ancestor_rev_renders_stale(tmp_path):
    repo, side_rev = _seeded_history(tmp_path)
    assert staleness.ancestor_verdict(side_rev, repo) == "not_ancestor"
    assert staleness.ancestor_verdict(
        staleness.head_rev(repo), repo) == "ancestor"
    cap = tmp_path / "hist" / "cap.json"
    cap.write_text("x\n")
    rec = _rec(capture="cap.json",
               capture_sha256=ledger.sha256_file(str(cap)),
               git_rev=side_rev)
    res = gate.verify_record(rec, root=repo)
    assert res["status"] == "STALE"
    assert any("not an ancestor" in f for f in res["failures"])


def test_ancestry_policies_differ_on_unresolvable_rev(tmp_path):
    # "abc1234" is the fake rev the pinned tuning tests stamp into fresh
    # docs: unresolvable, so the document policy must NOT flag it...
    assert staleness.ancestor_verdict("abc1234") == "unknown"
    assert staleness.ancestry_staleness("abc1234") == []
    # ...while the gate, which backs published claims, must.
    cap = tmp_path / "cap.json"
    cap.write_text("x\n")
    rec = _rec(capture=str(cap),
               capture_sha256=ledger.sha256_file(str(cap)),
               git_rev="abc1234")
    res = gate.verify_record(rec)
    assert res["status"] == "STALE"
    assert any("unprovable" in f or "does not resolve" in f
               for f in res["failures"])


def test_bench_delegates_to_unified_staleness():
    import bench
    assert bench.STALE_BANNER == staleness.STALE_BANNER
    doc = {"provenance": {"git_commit": "abc1234", "pallas_enabled": True,
                          "fusion": "per_leaf"},
           "rows": [{"config": "c", "imgs_per_sec": 1.0,
                     "fusion": "per_leaf"}]}
    assert bench.evidence_staleness(doc) == staleness.evidence_staleness(doc)
    assert bench.evidence_staleness(doc) == []
    assert bench.evidence_staleness({"rows": []})  # pre-provenance doc


# ---------------------------------------------------------------------------
# claim scanning


def test_scan_claims_paragraph_marking():
    text = "\n".join([
        "The headline runs 0.9895× dense on one chip.",
        "<!-- evidence: bench-headline-tpu proj-topk1pct-xslice -->",
        "",
        "PowerSGD projects 1.47–1.54× vs dense at W=64.",
        "",
        "```",
        "code claims 3× dense but fences are exempt",
        "```",
        "<!-- evidence:begin -->",
        "| generated table says 8.7× dense |",
        "<!-- evidence:end -->",
    ])
    scan = gate.scan_claims(text)
    assert scan["cited_ids"] == ["bench-headline-tpu",
                                 "proj-topk1pct-xslice"]
    assert [n for n, _ in scan["claims"]] == [1, 4]
    assert [n for n, _ in scan["unmarked"]] == [4]   # only the bare one


def test_scan_claims_marker_covers_adjacent_paragraph():
    text = "\n".join([
        "<!-- evidence: some-id -->",
        "A table headline at 2.2× vs dense.",
    ])
    assert gate.scan_claims(text)["unmarked"] == []


def test_gate_report_passes_on_this_repo():
    """The --ci acceptance criterion: every README/CHANGELOG ratio is
    marked and every cited ledger record verifies on HEAD."""
    report = gate.gate_report()
    assert report["failures"] == []
    assert report["ok"]
    statuses = {cid: r["status"] for cid, r in report["records"].items()}
    # Single-device captures are MEASURED; cross-slice / three-tier /
    # W=256 ratios ride the analytic wire model and must say PROJECTED.
    assert statuses["bench-headline-tpu"] == "MEASURED"
    for cid, status in statuses.items():
        if cid.startswith("proj-"):
            assert status == "PROJECTED", (cid, status)
    badges = gate.render_badges(report)
    assert gate.GATE_BEGIN in badges and "**MEASURED**" in badges


# ---------------------------------------------------------------------------
# Chrome-trace export round-trip


def _spans():
    from grace_tpu.profiling.trace_analysis import Span
    return [
        Span(name="allreduce-hop0", ts=0.0, dur=10.0,
             device="/device:TPU:0", lane="XLA Ops", scope="ici"),
        Span(name="allreduce-hop1", ts=10.0, dur=12.0,
             device="/device:TPU:0", lane="XLA Ops", scope="dcn"),
        Span(name="step", ts=0.0, dur=25.0,
             device="/device:TPU:0", lane="Steps", scope=""),
        Span(name="allreduce-hop0", ts=1.0, dur=9.0,
             device="/device:TPU:1", lane="XLA Ops", scope="ici"),
    ]


@pytest.mark.parametrize("suffix", [".json", ".json.gz"])
def test_chrome_trace_round_trip(tmp_path, suffix):
    from grace_tpu.profiling.trace_analysis import load_trace_events
    from grace_tpu.profiling.trace_export import write_chrome_trace
    spans = _spans()
    path = str(tmp_path / f"trace{suffix}")
    write_chrome_trace(spans, path)
    assert set(load_trace_events(path)) == set(spans)


def test_chrome_trace_doc_is_deterministic():
    from grace_tpu.profiling.trace_export import chrome_trace_doc
    spans = _spans()
    assert (json.dumps(chrome_trace_doc(spans))
            == json.dumps(chrome_trace_doc(list(reversed(spans)))))


def test_merge_host_traces_prefixes_and_aligns():
    from grace_tpu.profiling.trace_analysis import parse_chrome_trace
    from grace_tpu.profiling.trace_export import (chrome_trace_doc,
                                                  merge_host_traces)
    spans = _spans()
    # host1's clock starts 1e6 µs later; align rebases both to t=0.
    shifted = [type(s)(name=s.name, ts=s.ts + 1e6, dur=s.dur,
                       device=s.device, lane=s.lane, scope=s.scope)
               for s in spans]
    merged = merge_host_traces({"host0": spans, "host1": shifted})
    assert len(merged) == 2 * len(spans)
    devices = {s.device for s in merged}
    assert "host0//device:TPU:0" in devices
    assert "host1//device:TPU:1" in devices
    by_host = {h: [s for s in merged if s.device.startswith(h + "/")]
               for h in ("host0", "host1")}
    assert min(s.ts for s in by_host["host0"]) == 0.0
    assert min(s.ts for s in by_host["host1"]) == 0.0
    # the merged timeline still round-trips through the parser
    assert set(parse_chrome_trace(chrome_trace_doc(merged))) == set(merged)


# ---------------------------------------------------------------------------
# incident flight recorder


def test_incident_recorder_triggers_debounces_and_ledgers(tmp_path):
    out = str(tmp_path / "incidents")
    led = str(tmp_path / "ledger.jsonl")
    rec = incident.IncidentRecorder(
        out, run_tag="t", min_gap_steps=10, ledger_path=led,
        provenance={"platform": "cpu", "n_devices": 8})
    with rec:
        for step in range(5):
            rec.write({"step": step, "metric": "wire_bytes", "value": 1.0})
        rec.write({"step": 5, "event": "adapt_tighten", "rung": 2})
        rec.write({"step": 7, "event": "guard_skip"})       # debounced
        rec.attach_profile({"stages_ms": {"compress": 1.2}})
        rec.write({"step": 30, "event": "guard_skip"})      # new incident
    assert len(rec.incidents) == 2
    first = json.load(open(rec.incidents[0]))
    assert first["trigger"]["event"] == "adapt_tighten"
    assert first["adapt_rungs"] and first["prof"] is None
    assert len(first["telemetry_ring"]) == 6
    assert first["watch_timeline"]["kind_counts"]
    second = json.load(open(rec.incidents[1]))
    assert second["prof"] == {"stages_ms": {"compress": 1.2}}
    assert [r["event"] for r in second["guard_events"]] == ["guard_skip",
                                                            "guard_skip"]
    led_recs = ledger.load_ledger(led)
    assert len(led_recs) == 2
    assert all(r["tool"] == "flight_recorder" and
               r["claim_class"] == "measured" for r in led_recs)
    assert led_recs[1]["value"] == 30                # trigger step


def test_incident_recorder_never_raises(tmp_path):
    bad = incident.IncidentRecorder(
        str(tmp_path / "nope"), ledger_path=str(tmp_path / "l.jsonl"))
    bad.write("not-a-mapping")                       # swallowed, not raised
    assert bad.incidents == []


# ---------------------------------------------------------------------------
# backfill + ledger-driven summary


def test_backfill_is_idempotent(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    first = backfill_ledger(REPO, led)
    assert first, "committed artifacts should mint records"
    assert backfill_ledger(REPO, led) == []
    ids = {r["id"] for r in first}
    assert "bench-headline-tpu" in ids and "proj-topk1pct-xslice" in ids


def test_evidence_summary_renders_ledger_extras(tmp_path, monkeypatch):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "evidence_summary_under_test",
        os.path.join(REPO, "tools", "evidence_summary.py"))
    ev = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ev)
    monkeypatch.setattr(ev, "ROOT", str(tmp_path))

    cap = tmp_path / "NEWTOOL_LAST.json"
    cap.write_text('{"ok": true}\n')
    led = tmp_path / "EVIDENCE" / "ledger.jsonl"
    ledger.record_artifact(
        str(cap), id="newtool-drill", metric="newtool_ok", value=True,
        claim_class="measured", tool="newtool", platform="cpu",
        n_devices=8, topology={"world": 8, "tiers": ["ici"]}, config=None,
        lint_clean=None, ledger_path=str(led))
    md = ev.build()
    # no dedicated reader, yet it renders — straight from the ledger
    assert "NEWTOOL_LAST.json" in md and "`newtool-drill`" in md
    assert "no dedicated reader" in md
