"""TensorFlow/Keras interop tests.

Parity targets: the reference's TF2 tape path (patch_files/horovod/
tensorflow/__init__.py:314-365), the Keras optimizer path
(_keras/__init__.py:20-80), grace-aware load_model
(tensorflow/keras/__init__.py:121-150), and the Keras example's callbacks
(examples/tensorflow/tensorflow2_keras_mnist.py:69-89). The point
throughout: gradients leaving the TF side are the globally aggregated,
compressed-exchanged result of the jitted JAX pipeline.
"""

import jax
import numpy as np
import pytest

from grace_tpu import grace_from_params

tf = pytest.importorskip("tensorflow")
keras = pytest.importorskip("keras")

from grace_tpu.interop.keras import (  # noqa: E402
    BroadcastGlobalVariablesCallback, DistributedOptimizer,
    LearningRateWarmupCallback, MetricAverageCallback, load_model)
from grace_tpu.interop.tensorflow import (  # noqa: E402
    DistributedGradientTape, TFExchanger, broadcast_variables)

NONE_CFG = {"compressor": "none", "memory": "none",
            "communicator": "allreduce"}


class TestTFExchanger:
    def test_none_exchange_is_identity_single_process(self, mesh):
        """Single process: every rank carries this process's grads, so the
        uncompressed global mean is the input itself."""
        ex = TFExchanger(grace_from_params(NONE_CFG), mesh=mesh)
        grads = [tf.constant(np.arange(6, dtype=np.float32).reshape(2, 3)),
                 None,
                 tf.constant([1.5, -2.5], tf.float32)]
        out = ex.exchange(grads)
        np.testing.assert_allclose(out[0].numpy(), grads[0].numpy(),
                                   rtol=1e-6)
        assert out[1] is None
        np.testing.assert_allclose(out[2].numpy(), grads[2].numpy(),
                                   rtol=1e-6)

    def test_shapes_and_dtypes_preserved(self, mesh):
        ex = TFExchanger(grace_from_params(NONE_CFG), mesh=mesh)
        g = [tf.constant(np.ones((3, 4)), tf.float64)]
        out = ex.exchange(g)
        assert out[0].shape == (3, 4) and out[0].dtype == tf.float64

    def test_works_inside_tf_function(self, mesh):
        ex = TFExchanger(grace_from_params(NONE_CFG), mesh=mesh)

        @tf.function
        def f(x):
            return ex.exchange([x])[0]

        x = tf.constant(np.arange(8, dtype=np.float32))
        np.testing.assert_allclose(f(x).numpy(), x.numpy(), rtol=1e-6)

    def test_aggregation_matches_numpy_topk(self, mesh):
        """Top-K 50% + no memory: aggregate must equal the numpy emulation
        (mean over ranks of top-k-sparsified inputs). Single process: all
        rank rows are identical, so the mean is the sparsified input."""
        ex = TFExchanger(grace_from_params(
            {"compressor": "topk", "compress_ratio": 0.5, "memory": "none",
             "communicator": "allgather"}), mesh=mesh)
        x = np.array([3.0, -0.1, 0.2, -4.0], np.float32)
        out = ex.exchange([tf.constant(x)])[0].numpy()
        expect = np.where(np.abs(x) >= np.sort(np.abs(x))[-2], x, 0.0)
        np.testing.assert_allclose(out, expect, rtol=1e-6)


class TestExchangerState:
    def test_state_restore_resumes_error_feedback(self, mesh):
        """exchanger_for + grace_state assignment (queued pre-build) must
        reproduce an uninterrupted run — the TRAINING.md resume recipe."""
        from grace_tpu.interop.tensorflow import exchanger_for

        cfg = {"compressor": "topk", "compress_ratio": 0.25,
               "memory": "residual", "communicator": "allgather"}
        g = np.linspace(-1.0, 1.0, 16).astype(np.float32)

        a = grace_from_params(cfg)
        ex_a = exchanger_for(a, mesh, 0)
        assert ex_a.grace_state is None          # no exchange yet
        ex_a.exchange([tf.constant(g)])
        assert ex_a.grace_state is not None
        # Host-copy before continuing (what save_checkpoint does): the next
        # exchange donates the previous state buffers.
        saved = jax.device_get(ex_a.grace_state)
        cont = ex_a.exchange([tf.constant(g)])[0].numpy()

        b = grace_from_params(cfg)               # fresh process-equivalent
        ex_b = exchanger_for(b, mesh, 0)
        assert ex_b is not ex_a
        ex_b.grace_state = saved                 # queued: bridge not built
        resumed = ex_b.exchange([tf.constant(g)])[0].numpy()
        np.testing.assert_array_equal(cont, resumed)


class TestDistributedGradientTape:
    def test_gradient_correctness_vs_analytic(self, mesh):
        v = tf.Variable([1.0, 2.0, 3.0])
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(v * v)
        tape = DistributedGradientTape(tape, grace_from_params(NONE_CFG),
                                       mesh=mesh)
        grad = tape.gradient(loss, v)
        np.testing.assert_allclose(grad.numpy(), 2 * v.numpy(), rtol=1e-6)

    def test_list_sources_structure(self, mesh):
        a, b = tf.Variable(2.0), tf.Variable([1.0, -1.0])
        with tf.GradientTape() as tape:
            loss = a * tf.reduce_sum(b * b)
        tape = DistributedGradientTape(tape, grace_from_params(NONE_CFG),
                                       mesh=mesh)
        ga, gb = tape.gradient(loss, [a, b])
        np.testing.assert_allclose(ga.numpy(), 2.0, rtol=1e-6)
        np.testing.assert_allclose(gb.numpy(), 2 * 2.0 * b.numpy(),
                                   rtol=1e-6)

    def test_per_step_wrapping_shares_exchanger_and_state(self, mesh):
        """The reference idiom wraps the tape anew every step; the shared
        exchanger must persist (no per-step recompile) and carry residual
        error-feedback state across wraps — while a *different* Grace object
        with an equal config must get its own exchanger (residuals are
        per-model state)."""
        from grace_tpu.interop.tensorflow import _shared_exchanger

        cfg = {"compressor": "topk", "compress_ratio": 0.34,
               "memory": "residual", "communicator": "allgather"}
        grc = grace_from_params(cfg)
        v = tf.Variable([1.0, 2.0, 3.0])

        def one_step():
            with tf.GradientTape() as tape:
                loss = tf.reduce_sum(v * v)
            tape = DistributedGradientTape(tape, grc, mesh=mesh)
            return tape.gradient(loss, v)

        one_step()
        ex1 = _shared_exchanger(grc, mesh, 0)
        state1 = ex1._bridge.state
        res1 = np.asarray(state1.mem[0])        # GraceState.mem residuals
        assert np.abs(res1).sum() > 0           # topk 34% left a residual
        one_step()
        ex2 = _shared_exchanger(grc, mesh, 0)
        assert ex1 is ex2                       # same bridge across wraps
        res2 = np.asarray(ex2._bridge.state.mem[0])
        assert not np.array_equal(res1, res2)   # state advanced, not reset

        twin = grace_from_params(cfg)
        assert twin == grc                      # equal config...
        ex3 = _shared_exchanger(twin, mesh, 0)
        assert ex3 is not ex1                   # ...but its own state

    def test_training_step_under_tf_function(self, mesh):
        model = keras.Sequential([keras.layers.Dense(4, activation="relu"),
                                  keras.layers.Dense(2)])
        model.build((None, 3))
        grc = grace_from_params({"compressor": "fp16", "memory": "none",
                                 "communicator": "allreduce"})
        opt = keras.optimizers.SGD(0.1)

        @tf.function
        def step(x, y):
            with tf.GradientTape() as tape:
                logits = model(x, training=True)
                loss = tf.reduce_mean(
                    keras.losses.sparse_categorical_crossentropy(
                        y, logits, from_logits=True))
            dtape = DistributedGradientTape(tape, grc, mesh=mesh)
            grads = dtape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            return loss

        x = tf.constant(np.random.default_rng(0)
                        .standard_normal((16, 3)).astype(np.float32))
        y = tf.constant(np.random.default_rng(1).integers(0, 2, 16))
        first = float(step(x, y))
        for _ in range(20):
            last = float(step(x, y))
        assert last < first


class TestKerasDistributedOptimizer:
    def _data(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((128, 8)).astype(np.float32)
        y = (x.sum(axis=1) > 0).astype(np.int32)
        return x, y

    def _model(self):
        keras.utils.set_random_seed(0)
        return keras.Sequential([keras.layers.Dense(16, activation="relu"),
                                 keras.layers.Dense(2)])

    def test_wraps_and_preserves_config(self, mesh):
        opt = DistributedOptimizer(
            keras.optimizers.SGD(learning_rate=0.25, momentum=0.5),
            grace_from_params(NONE_CFG), mesh=mesh)
        assert isinstance(opt, keras.optimizers.SGD)
        assert float(np.asarray(opt.learning_rate)) == 0.25
        assert opt.get_config()["momentum"] == 0.5

    def test_rejects_non_optimizer(self, mesh):
        with pytest.raises(TypeError, match="keras optimizer"):
            DistributedOptimizer(object(), grace_from_params(NONE_CFG),
                                 mesh=mesh)

    @pytest.mark.filterwarnings("ignore::UserWarning")
    def test_fit_trains_with_signsgd(self, mesh):
        """BASELINE.json config 5: the 1-bit/signSGD optimizer path, end to
        end through model.fit graph mode on the CPU mesh."""
        x, y = self._data()
        model = self._model()
        # sign updates have unit magnitude regardless of gradient scale —
        # signSGD needs a far smaller lr than vanilla SGD.
        opt = DistributedOptimizer(
            keras.optimizers.SGD(0.002),
            grace_from_params({"compressor": "signsgd", "memory": "none",
                               "communicator": "allreduce"}), mesh=mesh)
        model.compile(optimizer=opt, metrics=["accuracy"],
                      loss=keras.losses.SparseCategoricalCrossentropy(
                          from_logits=True))
        hist = model.fit(x, y, batch_size=32, epochs=8, verbose=0)
        losses = hist.history["loss"]
        assert losses[-1] < losses[0], losses

    def test_fit_trains_with_onebit_residual(self, mesh):
        x, y = self._data()
        model = self._model()
        opt = DistributedOptimizer(
            keras.optimizers.Adam(1e-2),
            grace_from_params({"compressor": "onebit", "memory": "residual",
                               "communicator": "allgather"}), mesh=mesh)
        model.compile(optimizer=opt, loss=keras.losses.
                      SparseCategoricalCrossentropy(from_logits=True))
        hist = model.fit(x, y, batch_size=32, epochs=8, verbose=0)
        losses = hist.history["loss"]
        assert losses[-1] < losses[0], losses


class TestLoadModel:
    def test_load_model_revives_distributed_optimizer(self, mesh, tmp_path):
        x = np.random.default_rng(0).standard_normal((32, 4)).astype("f4")
        y = (x.sum(axis=1) > 0).astype(np.int32)
        model = keras.Sequential([keras.layers.Dense(2)])
        model.compile(optimizer=keras.optimizers.SGD(0.1),
                      loss=keras.losses.SparseCategoricalCrossentropy(
                          from_logits=True))
        model.fit(x, y, epochs=1, verbose=0)
        path = str(tmp_path / "model.keras")
        model.save(path)

        loaded = load_model(path, grace_from_params(NONE_CFG), mesh=mesh)
        opt = loaded.optimizer
        assert isinstance(opt, keras.optimizers.SGD)
        assert type(opt).__qualname__ == "DistributedSGD"
        loaded.fit(x, y, epochs=1, verbose=0)  # exchange path is live


class TestCallbacks:
    def test_lr_warmup_ramps_to_world_size(self, mesh):
        model = keras.Sequential([keras.layers.Dense(1)])
        model.compile(optimizer=keras.optimizers.SGD(0.1), loss="mse")
        cb = LearningRateWarmupCallback(world_size=8, warmup_epochs=4)
        cb.set_model(model)
        cb.on_train_begin()
        lrs = []
        for e in range(6):
            cb.on_epoch_begin(e)
            lrs.append(float(np.asarray(model.optimizer.learning_rate)))
        expect0 = 0.1 * (1 + 7 * 1 / 4)
        np.testing.assert_allclose(lrs[0], expect0, rtol=1e-6)
        np.testing.assert_allclose(lrs[3], 0.8, rtol=1e-6)   # full 8x
        np.testing.assert_allclose(lrs[5], 0.8, rtol=1e-6)   # holds

    def test_metric_average_single_process_passthrough(self):
        logs = {"loss": 1.25, "accuracy": 0.5, "note": "str"}
        MetricAverageCallback()._average(logs)
        assert logs == {"loss": 1.25, "accuracy": 0.5, "note": "str"}

    def test_broadcast_variables_single_process_noop(self):
        v = tf.Variable([[1.0, 2.0]])
        broadcast_variables([v], root_rank=0)
        np.testing.assert_array_equal(np.asarray(v), [[1.0, 2.0]])

    def test_broadcast_callback_runs_once(self, mesh):
        x = np.zeros((8, 2), np.float32)
        y = np.zeros((8,), np.int32)
        model = keras.Sequential([keras.layers.Dense(2)])
        model.compile(optimizer=keras.optimizers.SGD(0.1),
                      loss=keras.losses.SparseCategoricalCrossentropy(
                          from_logits=True))
        cb = BroadcastGlobalVariablesCallback(root_rank=0)
        model.fit(x, y, batch_size=4, epochs=1, verbose=0, callbacks=[cb])
        assert cb._done
