"""Hop-pipelined compressed ring all-reduce (comm.RingAllreduce, ISSUE 4).

The properties pinned here are the ring communicator's acceptance criteria:
exact-codec numerics match the allgather path (bit-identical when every
intermediate sum is exactly representable — integer-valued grads — so no
tolerance can hide a wire-format bug); the per-hop requantization error is
bounded and grows ~linearly in hop count (one requant hop vs world−1),
never explodes; communicator-aware wire bytes are < 0.5× allgather's at
W=8 and agree with the shared ``recv_wire_bytes`` model the bench
projections use; the enforced compatibility gates (stateless +
summable-or-hop-requant) reject everything else with an actionable
TypeError; and the ring composes with the resilience stack — guard
rollback stays atomic and the consensus audit stays a bit-exact no-op on
healthy steps.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu import compressors as C
from grace_tpu.memories import NoneMemory, ResidualMemory
from grace_tpu.parallel import shard_map
from grace_tpu.resilience import ConsensusConfig, audit_report, guarded_chain
from grace_tpu.telemetry import TelemetryReader
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.utils.metrics import guard_report

W = 8

pytestmark = pytest.mark.ring

BATCH, DIM, CLASSES = 64, 20, 4


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full pipeline step per rank on ``mesh``; returns (out, mem) of rank 0."""
    w = len(mesh.devices)

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        ms_leaf = ms if ms is not None else jnp.zeros_like(x)
        return out[None], ms_leaf[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=(P("data"), P("data")), check_vma=False)
    assert per_rank.shape[0] == w
    out, ms = fn(per_rank)
    return np.asarray(out[0]), np.asarray(ms[0])


def submesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


# ---------------------------------------------------------------------------
# exact path: linear codecs accumulate in payload space, no requant loss
# ---------------------------------------------------------------------------

def test_none_equals_dense_mean_with_padding(mesh, rng):
    x = rng.normal(size=(W, 41)).astype(np.float32)  # 41: exercises padding
    out, _ = run_step(mesh, comm.RingAllreduce(), C.NoneCompressor(),
                      NoneMemory(), jnp.asarray(x))
    # ring accumulation order differs from jnp.sum's, so float
    # associativity allows last-ulp differences — but nothing more.
    np.testing.assert_allclose(out, x.mean(0), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("comp", [C.NoneCompressor(), C.FP16Compressor()],
                         ids=["none", "fp16"])
def test_exact_codec_matches_allgather_bit_identical(mesh, rng, comp):
    """Integer-valued gradients make every partial sum exactly
    representable in f32 AND fp16, so summation order cannot matter:
    ring == allgather + aggregate to the BIT. Any wire-format bug (wrong
    shard routing, a dropped hop, double-counted own contribution,
    mis-aligned ctx) shows up as an integer-sized error."""
    x = rng.integers(-8, 9, size=(W, 37)).astype(np.float32)

    def via_allgather(xa):
        def body(t):
            t = t[0]
            payload, ctx, _ = comp.compress(t, None, jax.random.key(0))
            return comm.Allgather().exchange(payload, ctx, comp)[None]
        fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"), check_vma=False)
        return np.asarray(fn(xa)[0])

    ref = via_allgather(jnp.asarray(x))
    out, _ = run_step(mesh, comm.RingAllreduce(), comp, NoneMemory(),
                      jnp.asarray(x))
    np.testing.assert_array_equal(out, ref)


def test_randomk_shared_indices_exact_on_selected(mesh, rng):
    """randomk rides the exact (summable) path; its ring selection is
    per-shard (shard-folded keys) rather than global — same relaxation as
    two-shot — but every selected lane must carry the exact mean."""
    x = rng.normal(size=(W, 64)).astype(np.float32)
    out, _ = run_step(mesh, comm.RingAllreduce(),
                      C.RandomKCompressor(compress_ratio=0.5), NoneMemory(),
                      jnp.asarray(x), seed=3)
    nz = out != 0
    assert nz.sum() == 32           # 8 shards x k=4 of 8 lanes
    np.testing.assert_allclose(out[nz], x.mean(0)[nz], rtol=1e-5)


# ---------------------------------------------------------------------------
# requant path: decompress -> accumulate -> requantize each hop
# ---------------------------------------------------------------------------

def test_topk_residual_memory_sees_stage1_error(mesh, rng):
    """Error feedback covers the stage-1 shard encode exactly (the hop
    requant losses are downstream, like two-shot's stage-2):
    residual + stage-1 reconstruction == the compensated gradient."""
    x = rng.normal(size=(W, 64)).astype(np.float32)
    comp = C.TopKCompressor(compress_ratio=0.25)
    out, residual = run_step(mesh, comm.RingAllreduce(), comp,
                             ResidualMemory(), jnp.asarray(x))
    recon = x[0] - residual
    kept = recon != 0
    np.testing.assert_allclose(recon[kept], x[0][kept], rtol=1e-6)
    assert 0 < kept.sum() <= 64 * 0.25 + 8     # per-shard k of 8 lanes


def test_qsgd_hop_error_bounded_one_vs_seven_hops(mesh, rng):
    """Per-hop requantization error accumulates ~linearly in hop count,
    never explodes. W=2 runs ONE hop with no intermediate requant (hop 0
    accumulates, then the final shard encode); W=8 runs 7 hops with 6
    intermediate requants. Both relative errors must sit well under the
    analytic ladder (each QSGD encode errs <= ||t||/q per element) and the
    7-hop error must stay within a small linear factor of the 1-hop one."""
    q = 64
    comp = C.QSGDCompressor(quantum_num=q)

    def rel_err(w):
        xw = rng.normal(size=(w, 64)).astype(np.float32)
        out, _ = run_step(submesh(w), comm.RingAllreduce(), comp,
                          NoneMemory(), jnp.asarray(xw))
        return np.linalg.norm(out - xw.mean(0)) / np.linalg.norm(xw.mean(0))

    err1, err7 = rel_err(2), rel_err(8)
    assert err7 < 0.25, err7                  # sane in absolute terms
    # linear (not exponential) accumulation: 7 hops of extra encodes stay
    # within ~W x the single-hop error (generous: shard layouts differ too)
    assert err7 < 8 * max(err1, 1.0 / q), (err1, err7)


def test_signsgd_cascaded_vote_preserves_unanimity(mesh):
    """The hop requant re-signs the running partial — a cascaded vote.
    Unanimous coordinates MUST survive exactly; split coordinates may
    differ from the one-shot majority, but the output stays ±1."""
    col0 = np.ones((W,), np.float32)
    x = np.stack([col0, -col0, col0, -col0], axis=1)
    out, _ = run_step(mesh, comm.RingAllreduce(), C.SignSGDCompressor(),
                      NoneMemory(), jnp.asarray(x))
    np.testing.assert_array_equal(out, [1.0, -1.0, 1.0, -1.0])
    rng = np.random.default_rng(7)
    xr = rng.normal(size=(W, 53)).astype(np.float32)
    outr, _ = run_step(mesh, comm.RingAllreduce(), C.SignSGDCompressor(),
                       NoneMemory(), jnp.asarray(xr))
    assert set(np.unique(outr)) <= {-1.0, 1.0}


# ---------------------------------------------------------------------------
# enforced compatibility gates
# ---------------------------------------------------------------------------

def test_rejects_stateful_compressors(mesh, rng):
    x = rng.normal(size=(W, 16)).astype(np.float32)
    with pytest.raises(TypeError, match="stateless"):
        run_step(mesh, comm.RingAllreduce(), C.SignumCompressor(),
                 NoneMemory(), jnp.asarray(x))


def test_rejects_codecs_without_requant_or_summable(mesh, rng):
    """The Allreduce-style compat matrix is enforced, not documented: a
    codec that is neither linear nor hop-requant-capable (its payload
    carries structure a partial sum destroys) is a TypeError."""
    x = rng.normal(size=(W, 16)).astype(np.float32)
    for comp in [C.OneBitCompressor(), C.SketchCompressor(bins=16),
                 C.DgcCompressor(compress_ratio=0.5)]:
        with pytest.raises(TypeError, match="supports_hop_requant"):
            run_step(mesh, comm.RingAllreduce(), comp, NoneMemory(),
                     jnp.asarray(x))


def test_rejects_bare_exchange(mesh):
    with pytest.raises(TypeError, match="step"):
        comm.RingAllreduce().exchange((jnp.zeros(4),), None,
                                      C.NoneCompressor())


def test_catalog_requant_flags():
    """The shipped hop-requant matrix: topk/qsgd/signsgd opt in; codecs
    with non-summable structural payloads stay out."""
    assert C.TopKCompressor(0.1).supports_hop_requant
    assert C.QSGDCompressor().supports_hop_requant
    assert C.SignSGDCompressor().supports_hop_requant
    for comp in [C.OneBitCompressor(), C.SketchCompressor(),
                 C.DgcCompressor(0.1), C.ThresholdCompressor(0.01),
                 C.AdaqCompressor(0.1)]:
        assert not comp.supports_hop_requant, comp


def test_from_params_builds_ring():
    g = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                           "memory": "residual", "communicator": "ring"})
    assert isinstance(g.communicator, comm.RingAllreduce)
    assert g.communicator.shard_parallel


# ---------------------------------------------------------------------------
# wire-byte accounting: the shared recv_wire_bytes model + live telemetry
# ---------------------------------------------------------------------------

def test_recv_wire_bytes_model():
    """One model shared by bench projections and the telemetry ring:
    ring receives ~2·payload·(W−1)/W — flat in W — vs allgather's
    (W−1)·payload; under half allgather's bytes from W=8 up."""
    payload, n = 1000, 4096
    ring = comm.RingAllreduce()
    gather = comm.Allgather()
    for w in (2, 4, 8, 64, 256):
        rb = ring.recv_wire_bytes(payload, n, w)
        gb = gather.recv_wire_bytes(payload, n, w)
        assert rb == 2 * payload * (w - 1) // w
        assert gb == payload * (w - 1)
        if w >= 4:
            assert rb < gb
        if w >= 8:
            assert rb < 0.5 * gb
    # bench's model is a delegation to the same method — keep them fused
    import bench
    assert bench.recv_bytes_model(ring, False, payload, n, 8) == \
        ring.recv_wire_bytes(payload, n, 8)


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, grace_params, lr=0.3, guard=False, consensus=None,
           **guard_kw):
    grc = grace_from_params(dict(grace_params))
    if guard or consensus is not None:
        tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    else:
        tx = optax.chain(grc.transform(seed=0), optax.sgd(lr))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=consensus)
    return state, step


@pytest.mark.telemetry
def test_telemetry_wire_bytes_ring_under_half_of_allgather(mesh):
    """ISSUE 4 acceptance: telemetry-reported wire bytes per step on the
    8-device mesh are < 0.5× the Allgather communicator's for the same
    compressor config — measured from real sharded steps, not a formula."""
    x, y = _problem()
    base = {"compressor": "topk", "compress_ratio": 0.3,
            "memory": "residual", "fusion": "flat", "telemetry": 16}

    def wire_of(communicator):
        state, step = _build(mesh, dict(base, communicator=communicator))
        for _ in range(2):
            state, _ = step(state, (x, y))
        rows = TelemetryReader(sink=None, every=100).flush(state)
        assert rows
        return rows[-1]["wire_bytes"], rows[-1]["dense_bytes"]

    ring_b, dense_r = wire_of("ring")
    gather_b, dense_g = wire_of("allgather")
    assert dense_r == dense_g                 # same gradients, same model
    assert ring_b < 0.5 * gather_b, (ring_b, gather_b)
    # and both agree with the shared static model at W=8
    assert gather_b / ring_b == pytest.approx(7 / (2 * 7 / 8), rel=1e-6)


# ---------------------------------------------------------------------------
# resilience composition: guard rollback + consensus audit
# ---------------------------------------------------------------------------

RING_EF = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": "ring", "escape": "fp16"}


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(p), np.asarray(q))
               for p, q in zip(la, lb))


@pytest.mark.chaos
def test_guard_rolls_back_ring_step_atomically(mesh):
    """A NaN in one rank's batch shard propagates around the ring to all
    ranks; the guard must skip the step atomically — params and every
    mem leaf bitwise-unchanged — exactly as on the allgather path."""
    x, y = _problem()
    state, step = _build(mesh, RING_EF, guard=True)
    for _ in range(3):
        state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    before = state

    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan                       # rank 0's shard only
    state, _ = step(state, (jnp.asarray(xbad), y))

    rep = guard_report(state)
    assert rep["notfinite_count"] == 1
    assert _leaves_equal(before.params, state.params)
    g0 = before.opt_state.inner[0]
    g1 = state.opt_state.inner[0]
    assert _leaves_equal(g0.mem, g1.mem)
    assert _leaves_equal(g0.count, g1.count)

    state, loss = step(state, (x, y))         # clean data -> resumes
    assert np.isfinite(float(loss))
    assert not _leaves_equal(before.params, state.params)


@pytest.mark.consensus
def test_consensus_audit_is_noop_on_healthy_ring_run(mesh):
    """The consensus audit must stay a bit-exact no-op over the ring: same
    loss trajectory and params as the audit-off run, zero repairs."""
    x, y = _problem()
    cfg = dict(RING_EF, consensus=True)
    on = ConsensusConfig(audit_every=2)
    s_on, step_on = _build(mesh, cfg, consensus=on)
    s_off, step_off = _build(mesh, dict(RING_EF), guard=True)
    for _ in range(6):
        s_on, l_on = step_on(s_on, (x, y))
        s_off, l_off = step_off(s_off, (x, y))
    assert float(l_on) == float(l_off)
    assert _leaves_equal(s_on.params, s_off.params)
    rep = audit_report(s_on)
    assert rep["audits"] == 3 and rep["repairs"] == 0
