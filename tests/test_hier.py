"""Hierarchical ICI×DCN communicator (comm.HierarchicalAllreduce, ISSUE 7).

The properties pinned here are the two-level schedule's acceptance
criteria: exact codecs are BIT-identical to the flat ring at any slice
split (integer-valued grads make every partial sum exactly representable,
so no tolerance can hide a wrong shard route or a dropped cross-slice
partial); the requant path's extra loss stays bounded (one slice-boundary
re-encode, not K−1 cross-slice hops); the per-link wire model satisfies the
PR-6 split-sum identity, is monotone-in-slices on the DCN leg, and
collapses to the flat ring formula when there is nothing to split; the
telemetry ring's new ``wire_bytes_ici``/``wire_bytes_dcn`` fields carry the
honest mixed split from a REAL sharded step; ``Topology.detect`` rejects
the device lists it used to mis-size silently; and the bench xslice
projection — priced through the shared ``recv_link_bytes`` model at the
committed on-chip step times — shows topk1pct_hier beating dense at W=256
over DCN where the flat allgather loses (the ISSUE 7 headline).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu import compressors as C
from grace_tpu.core import LinkBytes, Topology
from grace_tpu.memories import NoneMemory, ResidualMemory
from grace_tpu.parallel import shard_map
from grace_tpu.resilience import ConsensusConfig, audit_report, guarded_chain
from grace_tpu.telemetry import TelemetryReader
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import set_fallback_flag
from grace_tpu.utils.metrics import guard_report

W = 8

pytestmark = pytest.mark.hier

BATCH, DIM, CLASSES = 64, 20, 4

SPLITS = (None, 1, 2, 4, 8)      # slice_size values that divide the 8-mesh


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full pipeline step per rank on ``mesh``; returns (out, mem) of rank 0."""
    w = len(mesh.devices)

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        ms_leaf = ms if ms is not None else jnp.zeros_like(x)
        return out[None], ms_leaf[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=(P("data"), P("data")), check_vma=False)
    assert per_rank.shape[0] == w
    out, ms = fn(per_rank)
    return np.asarray(out[0]), np.asarray(ms[0])


def submesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


# ---------------------------------------------------------------------------
# exact path: payload-space accumulation intra-slice AND cross-slice
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("s", SPLITS, ids=[f"s{s}" for s in SPLITS])
def test_none_equals_dense_mean_with_padding(mesh, rng, s):
    x = rng.normal(size=(W, 41)).astype(np.float32)  # 41: exercises padding
    out, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=s),
                      C.NoneCompressor(), NoneMemory(), jnp.asarray(x))
    np.testing.assert_allclose(out, x.mean(0), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("comp", [C.NoneCompressor(), C.FP16Compressor()],
                         ids=["none", "fp16"])
@pytest.mark.parametrize("s", SPLITS, ids=[f"s{s}" for s in SPLITS])
def test_exact_codec_bit_identical_to_flat_ring_at_any_split(mesh, rng,
                                                             comp, s):
    """ISSUE 7 acceptance: bit-identity vs the flat ring for exact codecs
    at ANY slice split. Integer-valued gradients make every partial sum
    exactly representable in f32 AND fp16, so summation order (intra-slice
    ring order + cross-slice gather-sum vs the flat ring's W−1 sequential
    hops) cannot matter — a wrong shard route, a double-counted slice
    partial, or a mis-aligned ctx shows up as an integer-sized error."""
    x = rng.integers(-8, 9, size=(W, 37)).astype(np.float32)
    ref, _ = run_step(mesh, comm.RingAllreduce(), comp, NoneMemory(),
                      jnp.asarray(x))
    out, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=s), comp,
                      NoneMemory(), jnp.asarray(x))
    np.testing.assert_array_equal(out, ref)


def test_randomk_shared_indices_exact_on_selected(mesh, rng):
    """randomk rides the exact path end to end: per-shard selection
    (shard-folded keys, like the flat ring) and every selected lane
    carries the exact mean through both levels."""
    x = rng.normal(size=(W, 64)).astype(np.float32)
    out, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=4),
                      C.RandomKCompressor(compress_ratio=0.5), NoneMemory(),
                      jnp.asarray(x), seed=3)
    nz = out != 0
    assert nz.sum() == 32           # 4 shards x k=8 of 16 lanes
    # cross-slice gather-sum order differs from the flat ring's hop order,
    # so float associativity allows last-ulp differences — nothing more.
    np.testing.assert_allclose(out[nz], x.mean(0)[nz], rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# requant path: intra-slice hop requant + ONE slice-boundary re-encode
# ---------------------------------------------------------------------------

def test_topk_residual_memory_sees_stage1_error(mesh, rng):
    """Error feedback covers the stage-1 shard encode exactly (intra-hop
    requants and the boundary re-encode are downstream, like the flat
    ring's hop losses): residual + stage-1 reconstruction == compensated."""
    x = rng.normal(size=(W, 64)).astype(np.float32)
    comp = C.TopKCompressor(compress_ratio=0.25)
    out, residual = run_step(mesh, comm.HierarchicalAllreduce(slice_size=4),
                             comp, ResidualMemory(), jnp.asarray(x))
    recon = x[0] - residual
    kept = recon != 0
    np.testing.assert_allclose(recon[kept], x[0][kept], rtol=1e-6)
    assert 0 < kept.sum() <= 64 * 0.25 + 8     # per-shard k of 16 lanes


def test_qsgd_error_comparable_to_flat_ring(mesh, rng):
    """The two-level schedule trades W−2 flat-ring intermediate requants
    for S−2 intra-slice ones plus ONE boundary re-encode — its total
    requant error must stay within a small factor of the flat ring's at
    the same world, never explode."""
    q = 64
    comp = C.QSGDCompressor(quantum_num=q)
    x = rng.normal(size=(W, 64)).astype(np.float32)

    def rel_err(communicator):
        out, _ = run_step(mesh, communicator, comp, NoneMemory(),
                          jnp.asarray(x))
        return np.linalg.norm(out - x.mean(0)) / np.linalg.norm(x.mean(0))

    err_ring = rel_err(comm.RingAllreduce())
    err_hier = rel_err(comm.HierarchicalAllreduce(slice_size=4))
    assert err_hier < 0.25, err_hier
    assert err_hier < 4 * max(err_ring, 1.0 / q), (err_ring, err_hier)


def test_signsgd_cascaded_vote_preserves_unanimity(mesh):
    """Intra-slice hops re-sign the running partial (cascaded vote), the
    boundary encode re-signs the slice tally, and the cross-slice
    aggregate majority-votes over slices. Unanimous coordinates MUST
    survive exactly; the output stays ±1 everywhere."""
    col0 = np.ones((W,), np.float32)
    x = np.stack([col0, -col0, col0, -col0], axis=1)
    for s in (2, 4):
        out, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=s),
                          C.SignSGDCompressor(), NoneMemory(),
                          jnp.asarray(x))
        np.testing.assert_array_equal(out, [1.0, -1.0, 1.0, -1.0])
    rng = np.random.default_rng(7)
    xr = rng.normal(size=(W, 53)).astype(np.float32)
    outr, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=2),
                       C.SignSGDCompressor(), NoneMemory(), jnp.asarray(xr))
    assert set(np.unique(outr)) <= {-1.0, 1.0}


# ---------------------------------------------------------------------------
# enforced compatibility gates
# ---------------------------------------------------------------------------

def test_rejects_stateful_compressors(mesh, rng):
    x = rng.normal(size=(W, 16)).astype(np.float32)
    with pytest.raises(TypeError, match="stateless"):
        run_step(mesh, comm.HierarchicalAllreduce(slice_size=4),
                 C.SignumCompressor(), NoneMemory(), jnp.asarray(x))


def test_rejects_codecs_without_requant_or_summable(mesh, rng):
    """Same capability gates as Ring — enforced, not documented."""
    x = rng.normal(size=(W, 16)).astype(np.float32)
    for comp in [C.OneBitCompressor(), C.SketchCompressor(bins=16),
                 C.DgcCompressor(compress_ratio=0.5)]:
        with pytest.raises(TypeError, match="supports_hop_requant"):
            run_step(mesh, comm.HierarchicalAllreduce(slice_size=4), comp,
                     NoneMemory(), jnp.asarray(x))


def test_rejects_bare_exchange():
    with pytest.raises(TypeError, match="step"):
        comm.HierarchicalAllreduce().exchange((jnp.zeros(4),), None,
                                              C.NoneCompressor())


def test_non_divisible_world_raises(mesh, rng):
    """world % slice_size != 0 is a trace-time ValueError, not a silent
    mis-shard (8 ranks cannot form whole 3-wide slices)."""
    x = rng.normal(size=(W, 16)).astype(np.float32)
    with pytest.raises(ValueError, match="does not divide"):
        run_step(mesh, comm.HierarchicalAllreduce(slice_size=3),
                 C.NoneCompressor(), NoneMemory(), jnp.asarray(x))
    with pytest.raises(ValueError, match="does not divide"):
        comm.HierarchicalAllreduce(slice_size=3).recv_wire_bytes(1000, 256, 8)


def test_from_params_builds_hier_with_topology():
    g = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                           "memory": "residual", "communicator": "hier",
                           "slice_size": 4})
    assert isinstance(g.communicator, comm.HierarchicalAllreduce)
    assert g.communicator.slice_size == 4
    assert g.communicator.shard_parallel
    # slice_size also declares the Topology telemetry prices against
    assert g.topology == Topology(slice_size=4)
    # without it the layout is detected (None = detect at wire-plan time)
    g2 = grace_from_params({"compressor": "none", "communicator": "hier"})
    assert g2.communicator.slice_size is None and g2.topology is None


def test_grouped_fusion_rejected():
    g = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                           "memory": "residual", "communicator": "hier",
                           "slice_size": 4, "fusion": "grouped"})
    with pytest.raises(ValueError, match="shard-parallel"):
        g.transform(seed=0)


# ---------------------------------------------------------------------------
# per-link wire model: split-sum identity, monotonicity, collapse
# ---------------------------------------------------------------------------

PAYLOAD, NELEMS = 8192, 2048


def test_recv_link_bytes_split_sum_identity():
    """The PR-6 identity, now over a genuinely MIXED split: ici + dcn ==
    recv_wire_bytes for every world, slice split, topology, and vote flag
    — bench projections and telemetry must price the same bytes."""
    for s in (None, 1, 2, 4, 8, 64):
        c = comm.HierarchicalAllreduce(slice_size=s)
        for w in (1, 2, 8, 64, 256):
            if s is not None and w > s and w % s:
                continue
            for topo in (None, Topology(), Topology(slice_size=s),
                         Topology(slice_size=8), Topology(slice_size=1024)):
                if topo is not None and topo.slice_size == 0:
                    continue
                for vote in (False, True):
                    total = c.recv_wire_bytes(PAYLOAD, NELEMS, w, vote=vote)
                    lb = c.recv_link_bytes(PAYLOAD, NELEMS, w,
                                           topology=topo, vote=vote)
                    assert lb.ici + lb.dcn == total == lb.total, \
                        (s, w, topo, vote, lb, total)


def test_dcn_bytes_monotone_in_num_slices():
    """More slices (smaller S at fixed W) => strictly more DCN bytes: the
    cross-slice leg ships (K−1)·payload/S, which grows as the hierarchy
    fragments — slice_size is a real knob, not a relabeling."""
    w = 256
    dcns = []
    for s in (128, 64, 32, 16, 8, 4, 2, 1):
        c = comm.HierarchicalAllreduce(slice_size=s)
        lb = c.recv_link_bytes(PAYLOAD, NELEMS, w,
                               topology=Topology(slice_size=s))
        assert lb.dcn > 0
        dcns.append(lb.dcn)
    assert all(a < b for a, b in zip(dcns, dcns[1:])), dcns


def test_collapses_to_flat_ring_formula():
    """slice_size=None or world <= slice_size: one slice, no DCN leg, and
    the scalar model IS the flat ring's 2·p·(W−1)/W."""
    ring = comm.RingAllreduce()
    for s, w in ((None, 8), (None, 256), (8, 8), (8, 4), (64, 8), (1024, 256)):
        c = comm.HierarchicalAllreduce(slice_size=s)
        assert c.recv_wire_bytes(PAYLOAD, NELEMS, w) == \
            ring.recv_wire_bytes(PAYLOAD, NELEMS, w), (s, w)
        assert c.recv_link_bytes(PAYLOAD, NELEMS, w).dcn == 0


def test_mixed_split_values_and_misaligned_topology():
    """slice_size=8 at W=256 under the matching physical topology: ICI leg
    is the flat-ring-within-a-slice 2·p·7/8, DCN leg the 31 cross-slice
    partials of p/8. A topology the schedule's slices straddle (physical
    slices of 4 under 8-wide comm slices, or an unsliced comm on a sliced
    mesh) degrades to the flat all-DCN critical path — honestly."""
    c = comm.HierarchicalAllreduce(slice_size=8)
    lb = c.recv_link_bytes(PAYLOAD, NELEMS, 256,
                           topology=Topology(slice_size=8))
    assert lb == LinkBytes(ici=2 * PAYLOAD * 7 // 8, dcn=31 * PAYLOAD // 8)
    # comm slices of 8 nest in physical slices of 16: still mixed
    nested = c.recv_link_bytes(PAYLOAD, NELEMS, 256,
                               topology=Topology(slice_size=16))
    assert nested.ici == lb.ici and nested.dcn == lb.dcn
    # comm slices of 8 straddle physical slices of 4: all DCN
    straddle = c.recv_link_bytes(PAYLOAD, NELEMS, 256,
                                 topology=Topology(slice_size=4))
    assert straddle.ici == 0 and straddle.dcn == lb.total
    # and far below the flat ALLGATHER's all-DCN cost at the same world —
    # the schedule topk actually rides today (255·p over DCN vs 31·p/8).
    gather_dcn = comm.Allgather().recv_link_bytes(
        PAYLOAD, NELEMS, 256, topology=Topology(slice_size=8)).dcn
    assert lb.dcn < 0.02 * gather_dcn


# ---------------------------------------------------------------------------
# Topology.detect hardening (fake device objects)
# ---------------------------------------------------------------------------

class _Dev:
    def __init__(self, slice_index=None):
        if slice_index is not None:
            self.slice_index = slice_index


def test_detect_even_multislice():
    devs = [_Dev(i // 4) for i in range(16)]      # 4 slices of 4
    assert Topology.detect(devs) == Topology(slice_size=4)


def test_detect_single_slice_and_missing_attr():
    assert Topology.detect([_Dev(0) for _ in range(8)]) == Topology()
    assert Topology.detect([_Dev() for _ in range(8)]) == Topology()
    assert Topology.detect([]) == Topology()
    # CPU / simulated devices: always one slice
    assert Topology.detect().slice_size is None


def test_detect_heterogeneous_slice_index_raises():
    devs = [_Dev(0), _Dev(0), _Dev(), _Dev(1)]
    with pytest.raises(ValueError, match="heterogeneous|no slice_index"):
        Topology.detect(devs)


def test_detect_uneven_slices_raise():
    """5+3 devices across two slices: the old len//n_slices floor would
    have silently reported slice_size=4 — a layout no rank actually has."""
    devs = [_Dev(0)] * 5 + [_Dev(1)] * 3
    with pytest.raises(ValueError, match="uneven"):
        Topology.detect(devs)
    # slice_index=None mixed with real indices is heterogeneous, not 0
    with pytest.raises(ValueError):
        Topology.detect([_Dev(None), _Dev(1), _Dev(1)])


# ---------------------------------------------------------------------------
# telemetry: the per-link wire_bytes_ici / wire_bytes_dcn fields
# ---------------------------------------------------------------------------

def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, grace_params, lr=0.3, guard=False, consensus=None,
           **guard_kw):
    grc = grace_from_params(dict(grace_params))
    if guard or consensus is not None:
        tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    else:
        tx = optax.chain(grc.transform(seed=0), optax.sgd(lr))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=consensus)
    return state, step


@pytest.mark.telemetry
def test_telemetry_link_split_mixed_for_hier_all_ici_for_flat(mesh):
    """ISSUE 7 telemetry honesty: hier rows carry a genuinely mixed split
    that sums to wire_bytes; flat comms fall back to the all-ICI split on
    the (single-slice-detected) CPU mesh."""
    x, y = _problem()
    base = {"compressor": "topk", "compress_ratio": 0.3,
            "memory": "residual", "fusion": "flat", "telemetry": 16}

    def last_row(extra):
        state, step = _build(mesh, dict(base, **extra))
        for _ in range(2):
            state, _ = step(state, (x, y))
        rows = TelemetryReader(sink=None, every=100).flush(state)
        assert rows
        return rows[-1]

    hier = last_row({"communicator": "hier", "slice_size": 4})
    assert hier["wire_bytes_ici"] > 0 and hier["wire_bytes_dcn"] > 0
    assert hier["wire_bytes_ici"] + hier["wire_bytes_dcn"] == \
        hier["wire_bytes"]
    # the model the row must match: this config's own recv_link_bytes
    g = grace_from_params(dict(base, communicator="hier", slice_size=4))
    from grace_tpu.transform import fusion_payload_nbytes
    _, comp_b, n_elems = fusion_payload_nbytes(
        g.compressor, jax.tree_util.tree_leaves(_init_params()), "flat")
    lb = g.communicator.recv_link_bytes(comp_b, n_elems, 8,
                                        topology=Topology(slice_size=4))
    assert (hier["wire_bytes_ici"], hier["wire_bytes_dcn"]) == \
        (lb.ici, lb.dcn)

    flat = last_row({"communicator": "allgather"})
    assert flat["wire_bytes_dcn"] == 0.0
    assert flat["wire_bytes_ici"] == flat["wire_bytes"]


@pytest.mark.telemetry
def test_telemetry_link_split_flips_with_fallback_window(mesh):
    """During a dense-fallback window the split flips with the scalar: the
    escape psum is a FLAT schedule, so under the hier config's 2-slice
    topology its bytes ride DCN entirely — the row must say so."""
    x, y = _problem()
    params = {"compressor": "topk", "compress_ratio": 0.3,
              "memory": "residual", "communicator": "hier", "slice_size": 4,
              "fusion": "flat", "escape": "fp16", "telemetry": 32}
    state, step = _build(mesh, params)
    for _ in range(2):
        state, _ = step(state, (x, y))
    state = set_fallback_flag(state, True)
    for _ in range(2):
        state, _ = step(state, (x, y))
    state = set_fallback_flag(state, False)
    state, _ = step(state, (x, y))
    rows = TelemetryReader(sink=None, every=100).flush(state)
    assert [r["fallback"] for r in rows] == [0, 0, 1, 1, 0]
    for r in rows:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]
    compressed = [r for r in rows if not r["fallback"]]
    dense = [r for r in rows if r["fallback"]]
    assert all(r["wire_bytes_ici"] > 0 and r["wire_bytes_dcn"] > 0
               for r in compressed)
    assert all(r["wire_bytes_ici"] == 0 and r["wire_bytes_dcn"] > 0
               for r in dense)


# ---------------------------------------------------------------------------
# bench xslice projection: the ISSUE 7 headline
# ---------------------------------------------------------------------------

def test_xslice_projection_hier_beats_dense_where_flat_loses():
    """ISSUE 7 acceptance: at the committed on-chip step times (bs=256
    headline capture, BENCH_ALL_TPU_LAST 2026-08-01: dense 2285.27
    imgs/sec, per-leaf Top-K at 0.9895× dense) and the measured topk 1%
    wire bytes, the W=256 / slice_size=8 xslice projection puts the flat
    allgather UNDER dense (the ROADMAP's 0.896× indictment) and the
    hierarchical schedule ABOVE it — same step times, same codec, only
    the schedule differs."""
    import bench

    dense_step = 256 / 2285.27           # s, bs=256 on the one v5e chip
    topk_step = dense_step / 0.9895      # headline per-leaf ratio
    wire_b, dense_b = 2_044_104, 102_228_128
    n_elems = dense_b // 4

    class _FakeComp:
        vote_aggregate = False

    def project(communicator):
        grace = dataclasses.make_dataclass(
            "G", ["compressor", "communicator"])(_FakeComp(), communicator)
        rows = bench.project_multichip(topk_step, dense_step, grace,
                                       wire_b, dense_b, n_elems)
        return {r["world"]: r["xslice"] for r in rows}

    flat = project(comm.Allgather())
    hier = project(comm.HierarchicalAllreduce(slice_size=bench.XSLICE_CHIPS))
    # the flat indictment, reproduced from the committed numbers
    assert flat[256]["speedup_vs_dense"] == pytest.approx(0.896, abs=0.01)
    assert flat[256]["ici_bytes"] == 0            # all-DCN beyond one slice
    # the hier fix: same step time, >1× dense at cross-slice scale
    assert hier[256]["speedup_vs_dense"] > 1.0
    assert hier[256]["ici_bytes"] > 0 and hier[256]["dcn_bytes"] > 0
    assert hier[256]["dcn_bytes"] < 0.05 * flat[256]["dcn_bytes"]
    # and the win grows with scale: every cross-slice world beats flat
    for w in (16, 64, 256):
        assert hier[w]["speedup_vs_dense"] > flat[w]["speedup_vs_dense"]


# ---------------------------------------------------------------------------
# static analysis: the auditor learned the nested-axis schedule
# ---------------------------------------------------------------------------

@pytest.mark.analysis
def test_wire_pass_counts_grouped_collectives_by_group_size():
    """count_recv_link_bytes attributes the traced hier schedule's bytes
    by link class under the comm's own slice split — intra legs ICI, the
    cross-slice gather DCN — and both legs reconcile with the model."""
    from grace_tpu.analysis import build_grace
    from grace_tpu.analysis.passes import count_recv_link_bytes
    from grace_tpu.analysis.trace import default_param_structs, trace_update
    from grace_tpu.core import WIRE_MODEL_ATOL, WIRE_MODEL_RTOL
    from grace_tpu.transform import fusion_payload_nbytes

    grace = build_grace({"name": "hier", "params": {
        "compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
        "communicator": "hier", "slice_size": 4, "fusion": "flat"}})
    t = trace_update(grace, name="hier", meta={"grace": grace})
    topo = Topology(slice_size=4)
    ici, dcn, wan = count_recv_link_bytes(t.body, t.axis_name, t.world,
                                          topo)
    assert wan == 0  # 2-tier topology: nothing crosses a region
    _, comp_b, n_elems = fusion_payload_nbytes(
        grace.compressor, list(default_param_structs().values()), "flat")
    lb = grace.communicator.recv_link_bytes(comp_b, n_elems, t.world,
                                            topology=topo)
    assert dcn > 0 and ici > 0
    for got, want in ((ici, lb.ici), (dcn, lb.dcn)):
        assert abs(got - want) <= max(WIRE_MODEL_RTOL * max(got, want),
                                      WIRE_MODEL_ATOL), (ici, dcn, lb)


@pytest.mark.analysis
def test_wire_pass_fires_on_lying_link_split():
    """The forcing function, proven live: a hier comm whose recv_link_bytes
    claims the cross-slice leg rides ICI keeps the scalar total intact —
    only the new leg-by-leg reconciliation against the traced collectives
    catches it."""
    from grace_tpu.analysis import build_grace
    from grace_tpu.analysis.passes import pass_wire_reconciliation
    from grace_tpu.analysis.trace import trace_update

    @dataclasses.dataclass(frozen=True)
    class AllIciHier(comm.HierarchicalAllreduce):
        def recv_link_bytes(self, payload_nbytes, n_elems, world,
                            topology=None, vote=False):
            total = self._recv_total_bytes(payload_nbytes, n_elems, world,
                                           vote=vote)
            return LinkBytes(ici=int(total), dcn=0)      # the lie

    base = build_grace({"name": "x", "params": {
        "compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
        "communicator": "hier", "slice_size": 4, "fusion": "flat"}})
    grace = dataclasses.replace(base,
                                communicator=AllIciHier(slice_size=4))
    t = trace_update(grace, name="lying-split", meta={"grace": grace})
    findings = pass_wire_reconciliation(t)
    assert len(findings) == 1
    assert "link" in findings[0].message
    # the honest comm on the same trace reconciles leg-by-leg
    t2 = trace_update(base, name="honest-split", meta={"grace": base})
    assert pass_wire_reconciliation(t2) == []


@pytest.mark.analysis
def test_hoisted_constants_seed_replicated():
    """The tracer regression the hier configs exposed: jnp constants
    created inside the step are hoisted to extra shard_map invars, and a
    naive positional mask seeded them (and everything after them)
    rank-varying — turning the legal escape-cond shape into a false
    positive. Constants must seed replicated."""
    from grace_tpu.analysis import trace_fn
    from grace_tpu.analysis.passes import pass_collective_consistency
    from jax import lax

    table = jnp.arange(7, dtype=jnp.int32)       # hoisted constant

    def ok(x, flag):
        y = x[:7] * table                        # closes over the constant
        return lax.cond(flag,
                        lambda o: lax.psum(o, "data"),
                        lambda o: o * 2.0, y)

    t = trace_fn(ok, [jax.ShapeDtypeStruct((64,), jnp.float32),
                      jax.ShapeDtypeStruct((), jnp.bool_)],
                 varying=[True, False], name="const-hoist")
    # the constant's body invar must be seeded replicated
    assert sum(1 for v in t.varying.values() if v) == 1
    assert pass_collective_consistency(t) == []


# ---------------------------------------------------------------------------
# resilience composition: guard rollback + consensus audit over two levels
# ---------------------------------------------------------------------------

HIER_EF = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": "hier", "slice_size": 4,
           "fusion": "flat", "escape": "fp16"}


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(p), np.asarray(q))
               for p, q in zip(la, lb))


@pytest.mark.chaos
def test_guard_rolls_back_hier_step_atomically(mesh):
    """A NaN in one rank's shard propagates through the intra-slice ring
    AND the cross-slice exchange to every rank; the guard must skip the
    step atomically — params and every mem leaf bitwise-unchanged."""
    x, y = _problem()
    state, step = _build(mesh, HIER_EF, guard=True)
    for _ in range(3):
        state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    before = state

    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan                       # rank 0's shard only
    state, _ = step(state, (jnp.asarray(xbad), y))

    rep = guard_report(state)
    assert rep["notfinite_count"] == 1
    assert _leaves_equal(before.params, state.params)
    g0 = before.opt_state.inner[0]
    g1 = state.opt_state.inner[0]
    assert _leaves_equal(g0.mem, g1.mem)
    assert _leaves_equal(g0.count, g1.count)

    state, loss = step(state, (x, y))         # clean data -> resumes
    assert np.isfinite(float(loss))
    assert not _leaves_equal(before.params, state.params)


@pytest.mark.chaos
@pytest.mark.telemetry
def test_chaos_smoke_hier_scenario(tmp_path):
    """tools/chaos_smoke.py --hier: the guard+fallback matrix over the
    two-level exchange must survive end to end, and the artifact's metric
    rows must carry the mixed per-link split (this CPU run declares
    slice_size=4, so 2 slices of 4 and a real DCN leg in every row)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "chaos_smoke_hier_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "chaos_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    out = tmp_path / "hier_chaos.jsonl"
    rc = smoke.main(["--steps", "12", "--nan-prob", "1.0", "--batch", "16",
                     "--fallback-after", "2", "--fallback-steps", "4",
                     "--hier", "--slice-size", "4",
                     "--telemetry-out", str(out), "--telemetry-every", "6"])
    assert rc == 0
    import json
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    metric = [r for r in rows if "wire_bytes_dcn" in r]
    assert metric, "no per-step metric rows in the artifact"
    for r in metric:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]
        # nan_prob=1.0 puts every accepted step in a dense-fallback
        # window: the escape psum is flat, so its bytes all ride DCN
        # under the 2-slice layout.
        assert r["wire_bytes_dcn"] > 0


@pytest.mark.consensus
def test_consensus_audit_is_noop_on_healthy_hier_run(mesh):
    """The consensus audit must stay a bit-exact no-op over the two-level
    exchange: same loss trajectory and params as the audit-off run, zero
    repairs — i.e. the hierarchically aggregated updates really are
    rank-identical."""
    x, y = _problem()
    cfg = dict(HIER_EF, consensus=True)
    on = ConsensusConfig(audit_every=2)
    s_on, step_on = _build(mesh, cfg, consensus=on)
    s_off, step_off = _build(mesh, dict(HIER_EF), guard=True)
    for _ in range(6):
        s_on, l_on = step_on(s_on, (x, y))
        s_off, l_off = step_off(s_off, (x, y))
    assert float(l_on) == float(l_off)
    assert _leaves_equal(s_on.params, s_off.params)
    rep = audit_report(s_on)
    assert rep["audits"] == 3 and rep["repairs"] == 0
