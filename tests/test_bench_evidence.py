"""Unit tests for bench.py's TPU evidence persistence.

The evidence files are the round's crown jewels (the tunnel dies for hours
at a stretch, so whatever landed on disk is often all there is). These
tests pin the protection logic: row-by-row persistence, atomicity of the
write, and the no-regression rule that keeps a fresh 1-row partial from
clobbering an earlier complete record; plus the sweep-resume gates
(bench_all) and the cached-row passthrough — the passthrough test calls
bench_configs, which does initialize the (CPU) jax backend.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _row(config, imgs, platform="tpu"):
    return {"config": config, "imgs_per_sec": imgs, "vs_baseline": 1.0,
            "platform": platform, "n_devices": 1, "chip": "TPU test",
            "peak_flops": 1.0, "mfu": 0.5}


def test_progressive_emit_persists_each_tpu_row(tmp_path):
    path = str(tmp_path / "ev.json")
    seen = []
    emit = bench.progressive_emit(seen.append, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))
    rec = json.load(open(path))
    assert rec["partial"] is True and rec["rows_measured"] == 1
    emit(_row("topk1pct", 50.0))
    rec = json.load(open(path))
    assert rec["partial"] is False and rec["rows_measured"] == 2
    assert rec["value"] == 50.0          # headline = the topk1pct row
    assert len(seen) == 2


def test_progressive_emit_ignores_non_tpu_rows(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 1.0, platform="cpu"))
    assert not os.path.exists(path)


def test_partial_never_clobbers_complete(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))
    emit(_row("topk1pct", 50.0))        # complete record on disk
    complete = json.load(open(path))

    # A fresh attempt dies after one row: its 1-row partial must land in
    # the .partial sibling, leaving the complete record untouched.
    emit2 = bench.progressive_emit(lambda r: None, n_expected=2,
                                   evidence_path=path, metric="m")
    emit2(_row("none", 90.0))
    assert json.load(open(path)) == complete
    demoted = json.load(open(path + ".partial"))
    assert demoted["partial"] is True and demoted["rows_measured"] == 1


def test_longer_partial_replaces_shorter(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=3,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))            # 1-row partial on disk
    emit2 = bench.progressive_emit(lambda r: None, n_expected=3,
                                   evidence_path=path, metric="m")
    emit2(_row("none", 90.0))            # same length: not a regression
    emit2(_row("topk1pct", 40.0))        # longer prefix: must replace
    rec = json.load(open(path))
    assert rec["rows_measured"] == 2
    assert rec["rows"][0]["imgs_per_sec"] == 90.0


def test_regresses_handles_round2_format():
    # Round-2 records lack rows/partial fields; a non-null value means a
    # real measured headline that a fresh 1-row partial must not erase.
    old = {"metric": "m", "value": 985.68, "vs_baseline": None}
    new = {"partial": True, "rows_measured": 1}
    assert bench._regresses(new, old) is True
    complete = {"partial": False, "rows_measured": 2}
    assert bench._regresses(complete, old) is False


def test_headline_metric_prefers_topk_row(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("topk1pct", 42.0))         # compressed row can land first
    rec = json.load(open(path))
    assert rec["value"] == 42.0 and rec["mfu"] == 0.5


# ---------------------------------------------------------------------------
# Sweep resume (bench_all._resume_configs + bench_configs cached_row)
# ---------------------------------------------------------------------------

import datetime  # noqa: E402

import bench_all  # noqa: E402


def _evidence_file(tmp_path, captured_at=None, rows=()):
    doc = {"metric": "resnet50_all_configs_imgs_per_sec",
           "captured_at": captured_at
           or datetime.datetime.now(datetime.timezone.utc).isoformat(),
           "rows": list(rows)}
    path = tmp_path / "sweep.json"
    path.write_text(json.dumps(doc))
    return str(path)


def _sweep_row(config, bs=32, hw=224, pdtype="float32", **extra):
    row = {"config": config, "imgs_per_sec": 100.0, "vs_baseline": 0.9,
           "per_device_bs": bs, "image_hw": hw, "param_dtype": pdtype,
           "platform": "tpu", **extra}
    for c in bench_all.CONFIGS:       # stamp the real params, like bench.py
        if c["name"] == config:
            row.setdefault("grace_params", c["params"])
    return row


def _patch_evidence(monkeypatch, path):
    monkeypatch.setattr(bench_all, "SWEEP_EVIDENCE_PATH", path)


def test_resume_no_gate_no_cache(tmp_path, monkeypatch):
    _patch_evidence(monkeypatch, _evidence_file(
        tmp_path, rows=[_sweep_row("topk1pct_bs64", bs=64)]))
    monkeypatch.delenv("GRACE_BENCH_RESUME", raising=False)
    monkeypatch.delenv("GRACE_BENCH_RESUME_SINCE", raising=False)
    assert not any("cached_row" in c for c in bench_all._resume_configs())


def test_resume_explicit_matches_shapes_and_skips_errors(tmp_path,
                                                         monkeypatch):
    _patch_evidence(monkeypatch, _evidence_file(tmp_path, rows=[
        _sweep_row("topk1pct_bs64", bs=64),
        _sweep_row("topk1pct", bs=32),       # headline is bs=256 now
        {"config": "signsgd_vote", "error": "boom", "per_device_bs": 32,
         "image_hw": 224, "param_dtype": "float32"},
    ]))
    monkeypatch.setenv("GRACE_BENCH_RESUME", "1")
    monkeypatch.delenv("GRACE_BENCH_RESUME_SINCE", raising=False)
    cfgs = bench_all._resume_configs()
    cached = {c["name"]: c["cached_row"] for c in cfgs if "cached_row" in c}
    assert set(cached) == {"topk1pct_bs64"}
    assert cached["topk1pct_bs64"]["resumed"] is True


def test_resume_rejects_edited_params(tmp_path, monkeypatch):
    # Same name + shapes but different grace_params (config edited since
    # the row was measured) -> re-measure; a row with no stamp at all is
    # trusted only under the explicit operator override.
    edited = _sweep_row("topk1pct_bs64", bs=64)
    edited["grace_params"] = {**edited["grace_params"],
                              "compress_ratio": 0.05}
    unstamped = _sweep_row("topk1pct_bs128", bs=128)
    del unstamped["grace_params"]
    _patch_evidence(monkeypatch, _evidence_file(
        tmp_path, rows=[edited, unstamped]))
    monkeypatch.delenv("GRACE_BENCH_RESUME", raising=False)
    monkeypatch.setenv("GRACE_BENCH_RESUME_SINCE", "0")
    assert not any("cached_row" in c for c in bench_all._resume_configs())
    monkeypatch.setenv("GRACE_BENCH_RESUME", "1")
    cached = {c["name"] for c in bench_all._resume_configs()
              if "cached_row" in c}
    assert cached == {"topk1pct_bs128"}   # unstamped ok ONLY when explicit


def test_resume_since_rejects_stale_accepts_fresh(tmp_path, monkeypatch):
    path = _evidence_file(tmp_path, rows=[_sweep_row("topk1pct_bs64",
                                                     bs=64)])
    _patch_evidence(monkeypatch, path)
    monkeypatch.delenv("GRACE_BENCH_RESUME", raising=False)
    # Watcher started an hour from now -> the file predates it: stale.
    import time
    monkeypatch.setenv("GRACE_BENCH_RESUME_SINCE", str(time.time() + 3600))
    assert not any("cached_row" in c for c in bench_all._resume_configs())
    monkeypatch.setenv("GRACE_BENCH_RESUME_SINCE", "0")
    assert any("cached_row" in c for c in bench_all._resume_configs())


def test_cached_row_passthrough_no_measurement():
    # bench_configs must emit cached rows verbatim without building a model
    # (a real build would compile ResNet-50 — the sub-second runtime of
    # this test is itself the proof the passthrough short-circuits).
    rows = []
    cfg = {"name": "x", "params": {"compressor": "none"},
           "cached_row": {"config": "x", "imgs_per_sec": 1.0,
                          "resumed": True}}
    # platform="cpu" under the test env (conftest pins the 8-dev CPU mesh).
    bench.bench_configs("cpu", [cfg], rows.append)
    assert rows == [{"config": "x", "imgs_per_sec": 1.0, "resumed": True}]


def test_cached_row_invalid_on_pallas_resolution_change():
    # A row stamped pallas_enabled=True replays only if the config still
    # resolves the kernel on today ('auto' resolves staged everywhere
    # since round 4, so a kernel-measured row must re-measure).
    params = {"compressor": "topk", "compress_ratio": 0.01,
              "topk_algorithm": "chunk", "memory": "residual",
              "communicator": "allgather", "fusion": "flat"}
    cfg = {"name": "topk1pct", "params": params,
           "cached_row": {"config": "topk1pct", "imgs_per_sec": 1.0,
                          "pallas_enabled": True, "resumed": True}}
    assert bench._cached_row_valid(cfg) is False
    cfg["cached_row"]["pallas_enabled"] = False
    assert bench._cached_row_valid(cfg) is True
    # Pre-stamp row on a kernel-capable config: fails CLOSED (the round-4
    # bs-sweep rows were measured under the old kernel-on default and
    # nothing in them says so) unless the operator override vouches.
    del cfg["cached_row"]["pallas_enabled"]
    assert bench._cached_row_valid(cfg) is False
    cfg["cached_row"]["resume_trusted"] = True
    assert bench._cached_row_valid(cfg) is True
    # Non-kernel-capable config (e.g. compressor none): nothing to compare.
    cfg2 = {"name": "none", "params": {"compressor": "none",
                                       "memory": "none",
                                       "communicator": "allreduce"},
            "cached_row": {"config": "none", "imgs_per_sec": 1.0}}
    assert bench._cached_row_valid(cfg2) is True


def test_stamped_row_fails_closed_when_capability_gone(monkeypatch):
    # A row stamped pallas_enabled=True for a config that no longer
    # resolves any kernel capability (now=None) must re-measure.
    class NoKernel:
        compressor = object()      # no _pallas_mode attribute

    cfg = {"name": "topk1pct", "params": {"compressor": "topk",
                                          "compress_ratio": 0.01},
           "cached_row": {"config": "topk1pct", "imgs_per_sec": 1.0,
                          "pallas_enabled": True, "resume_trusted": True}}
    monkeypatch.setattr("grace_tpu.grace_from_params",
                        lambda params: NoKernel())
    assert bench._cached_row_valid(cfg) is False


def test_sweep_summary_trims_rows(tmp_path):
    # Fallback runs carry a trimmed sweep view; bulky fields (projection,
    # samples, grace_params) must not ride along, error rows must.
    big = {"metric": "m", "captured_at": "2026-07-31T19:04:30+00:00",
           "partial": True,
           "rows": [{"config": "topk1pct_bs256", "imgs_per_sec": 2114.1,
                     "vs_baseline": 0.9246, "same_session": True,
                     "per_device_bs": 256, "projection": [{"world": 8}],
                     "samples": [1, 2, 3], "grace_params": {"x": 1}},
                    {"config": "boom", "error": "died"}]}
    p = tmp_path / "BENCH_ALL_TPU_LAST.json"
    p.write_text(json.dumps(big))
    s = bench.load_tpu_sweep_summary(str(p))
    assert s["partial"] is True
    assert s["rows"][0]["vs_baseline"] == 0.9246
    assert "projection" not in s["rows"][0]
    assert "samples" not in s["rows"][0]
    assert "grace_params" not in s["rows"][0]
    assert s["rows"][1] == {"config": "boom", "error": "died"}


# ---------------------------------------------------------------------------
# Multi-chip projection model (VERDICT r4 item 5: "unit-test the arithmetic")
# ---------------------------------------------------------------------------

def _mk_grace(comm, vote=False):
    class _Comp:
        vote_aggregate = vote

    class _G:
        communicator = comm
        compressor = _Comp()

    return _G()


def test_recv_bytes_model_arithmetic():
    from grace_tpu.comm import (Allgather, Allreduce, Identity,
                                SignAllreduce, TwoShotAllreduce)
    payload, n, w = 1_000_000, 500_000, 8
    # Ring allreduce: 2·(W-1)/W·payload received per rank.
    assert bench.recv_bytes_model(Allreduce(), False, payload, n, w) == \
        2 * payload * (w - 1) // w
    # Allgather: every other rank's payload, O(W·k).
    assert bench.recv_bytes_model(Allgather(), False, payload, n, w) == \
        payload * (w - 1)
    # Two-shot: all_to_all + all_gather of the O(k) reduced payload.
    assert bench.recv_bytes_model(TwoShotAllreduce(), False, payload, n,
                                  w) == 2 * payload * (w - 1) // w
    # Sign vote: dense bf16 votes (2 bytes/elem) on a ring — payload-blind.
    assert bench.recv_bytes_model(SignAllreduce(), False, payload, n, w) == \
        2 * 2 * n * (w - 1) // w
    assert bench.recv_bytes_model(Identity(), False, payload, n, w) == 0


def test_recv_bytes_twoshot_flat_allgather_linear_in_world():
    # The round-5 beat-dense argument hangs on this property: twoshot's
    # per-rank recv saturates (~2·payload) while allgather's grows
    # linearly with world size.
    from grace_tpu.comm import Allgather, TwoShotAllreduce
    payload, n = 1_000_000, 500_000
    two = [bench.recv_bytes_model(TwoShotAllreduce(), False, payload, n, w)
           for w in (8, 64, 256)]
    gat = [bench.recv_bytes_model(Allgather(), False, payload, n, w)
           for w in (8, 64, 256)]
    assert max(two) < 2 * payload                     # saturates below 2k
    assert gat[2] == (256 - 1) * payload              # linear growth
    assert gat[2] / gat[0] > 30


def test_project_multichip_arithmetic_and_assumptions():
    from grace_tpu.comm import Allgather
    step_s, dense_step_s = 0.1, 0.09
    wire_b, dense_b, n = 1_000_000, 100_000_000, 25_000_000
    rows = bench.project_multichip(step_s, dense_step_s,
                                   _mk_grace(Allgather()), wire_b, dense_b,
                                   n)
    assert [r["world"] for r in rows] == list(bench.PROJECTION_WORLDS)
    for r in rows:
        w = r["world"]
        cfg_recv = wire_b * (w - 1)
        dense_recv = 2 * dense_b * (w - 1) // w
        assert r["recv_bytes_per_rank"] == cfg_recv
        for net, bw in (("ici", bench.ICI_RING_BYTES_PER_S),
                        ("dcn", bench.DCN_BYTES_PER_S)):
            t_cfg = step_s + cfg_recv / bw
            t_dense = dense_step_s + dense_recv / bw
            assert abs(r[f"step_ms_{net}"] - t_cfg * 1e3) < 1e-2
            assert abs(r[f"speedup_vs_dense_{net}"] - t_dense / t_cfg) < 1e-3
    # The stamped model metadata matches the constants actually used.
    assert bench.PROJECTION_MODEL["ici_bytes_per_s"] == \
        bench.ICI_RING_BYTES_PER_S
    assert bench.PROJECTION_MODEL["dcn_bytes_per_s"] == bench.DCN_BYTES_PER_S
    assert "no-overlap" in bench.PROJECTION_MODEL["assumption"].lower() or \
        "NO-OVERLAP" in bench.PROJECTION_MODEL["assumption"]
