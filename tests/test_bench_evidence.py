"""Unit tests for bench.py's TPU evidence persistence.

The evidence files are the round's crown jewels (the tunnel dies for hours
at a stretch, so whatever landed on disk is often all there is). These
tests pin the protection logic: row-by-row persistence, atomicity of the
write, and the no-regression rule that keeps a fresh 1-row partial from
clobbering an earlier complete record.

No jax/device needed — everything here is host-side file logic.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _row(config, imgs, platform="tpu"):
    return {"config": config, "imgs_per_sec": imgs, "vs_baseline": 1.0,
            "platform": platform, "n_devices": 1, "chip": "TPU test",
            "peak_flops": 1.0, "mfu": 0.5}


def test_progressive_emit_persists_each_tpu_row(tmp_path):
    path = str(tmp_path / "ev.json")
    seen = []
    emit = bench.progressive_emit(seen.append, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))
    rec = json.load(open(path))
    assert rec["partial"] is True and rec["rows_measured"] == 1
    emit(_row("topk1pct", 50.0))
    rec = json.load(open(path))
    assert rec["partial"] is False and rec["rows_measured"] == 2
    assert rec["value"] == 50.0          # headline = the topk1pct row
    assert len(seen) == 2


def test_progressive_emit_ignores_non_tpu_rows(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 1.0, platform="cpu"))
    assert not os.path.exists(path)


def test_partial_never_clobbers_complete(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))
    emit(_row("topk1pct", 50.0))        # complete record on disk
    complete = json.load(open(path))

    # A fresh attempt dies after one row: its 1-row partial must land in
    # the .partial sibling, leaving the complete record untouched.
    emit2 = bench.progressive_emit(lambda r: None, n_expected=2,
                                   evidence_path=path, metric="m")
    emit2(_row("none", 90.0))
    assert json.load(open(path)) == complete
    demoted = json.load(open(path + ".partial"))
    assert demoted["partial"] is True and demoted["rows_measured"] == 1


def test_longer_partial_replaces_shorter(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=3,
                                  evidence_path=path, metric="m")
    emit(_row("none", 100.0))            # 1-row partial on disk
    emit2 = bench.progressive_emit(lambda r: None, n_expected=3,
                                   evidence_path=path, metric="m")
    emit2(_row("none", 90.0))            # same length: not a regression
    emit2(_row("topk1pct", 40.0))        # longer prefix: must replace
    rec = json.load(open(path))
    assert rec["rows_measured"] == 2
    assert rec["rows"][0]["imgs_per_sec"] == 90.0


def test_regresses_handles_round2_format():
    # Round-2 records lack rows/partial fields; a non-null value means a
    # real measured headline that a fresh 1-row partial must not erase.
    old = {"metric": "m", "value": 985.68, "vs_baseline": None}
    new = {"partial": True, "rows_measured": 1}
    assert bench._regresses(new, old) is True
    complete = {"partial": False, "rows_measured": 2}
    assert bench._regresses(complete, old) is False


def test_headline_metric_prefers_topk_row(tmp_path):
    path = str(tmp_path / "ev.json")
    emit = bench.progressive_emit(lambda r: None, n_expected=2,
                                  evidence_path=path, metric="m")
    emit(_row("topk1pct", 42.0))         # compressed row can land first
    rec = json.load(open(path))
    assert rec["value"] == 42.0 and rec["mfu"] == 0.5
