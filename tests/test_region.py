"""graft-region: three-tier WAN topology (core N-tier link model, the
three-level hierarchical schedule, region-loss elasticity — ISSUE 16).

The properties pinned here are the region track's acceptance criteria:

* the N-tier ``LinkBytes`` stays an exact alias of the committed 2-tier
  constructor and pins the W=0/W=1 edges to zero on EVERY tier for every
  communicator (vote routes included);
* the three-level schedule's wire split follows the documented formula
  (ICI ``2p(S−1)/S``, DCN ``(Kr−1)p/S``, WAN ``(R−1)p/S``) and degrades
  tier by tier when the schedule's groupings stop nesting in the physical
  ones;
* a single-region fleet IS the two-tier fleet: model split and mesh
  output both collapse bitwise — no tolerance, no vestigial WAN leg;
* exact/homomorphic/sketch payloads (none/fp16/randomk/homoqsgd/
  countsketch) cross the WAN boundary exactly-summable — bit-identical to
  the flat ring on integer-valued gradients at every (slice, region)
  split — while requant codecs re-encode the region partial ONCE through
  the aggressive per-level ``wan_compressor`` (whose gates reject the
  combinations that would silently lose the zero-requant property);
* ``Topology.shrink``/``plan_resize`` resolve losses at the finest
  violated granularity (region → slice → rank), and ``Topology.detect``
  gives ``region_index`` the same hardening ``slice_index`` has;
* ``ElasticController`` treats a region-wide skew episode as ONE
  drain→resize transition (``region_scope`` quorum) and bounds the drain
  checkpoint behind a backoff watchdog (``elastic_drain_timeout``);
* telemetry's ``wire_bytes_ici + wire_bytes_dcn + wire_bytes_wan ==
  wire_bytes`` identity survives the fallback flip and the flat-collective
  folds (watch gather, shared-scale negotiation, adapt signal), all of
  which land on the WAN leg when the axis spans regions.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu import compressors as C
from grace_tpu.core import LinkBytes, Topology
from grace_tpu.memories import NoneMemory
from grace_tpu.parallel import shard_map
from grace_tpu.resilience import ElasticController, plan_resize
from grace_tpu.telemetry import TelemetryReader
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import set_fallback_flag

W = 8

pytestmark = pytest.mark.region

# 2 regions x 2 slices x 2 ranks on the 8-device mesh: the smallest layout
# where all three tiers carry traffic (same layout as chaos_smoke --region
# and the registered *-hier3 configs).
TOPO3 = Topology(slice_size=2, region_size=4)

BATCH, DIM, CLASSES = 64, 20, 4


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full pipeline step per rank on ``mesh``; returns rank 0's output."""
    w = len(mesh.devices)

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        return out[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"), check_vma=False)
    assert per_rank.shape[0] == w
    return np.asarray(fn(per_rank)[0])


# ---------------------------------------------------------------------------
# the N-tier LinkBytes value itself
# ---------------------------------------------------------------------------

def test_linkbytes_two_tier_constructor_is_exact_alias():
    """Every pre-region call site builds LinkBytes(ici, dcn): that value
    must be indistinguishable from the 3-tier one with wan=0 — committed
    evidence (BENCH/TUNE/LINT_LAST) stays bit-identical."""
    two = LinkBytes(ici=3, dcn=4)
    three = LinkBytes(ici=3, dcn=4, wan=0)
    assert two == three
    assert two.wan == 0
    assert two.total == 7
    assert two.tiers == (3, 4, 0)
    assert LinkBytes(1, 2, 5).total == 8
    assert LinkBytes(1, 2, 5).tiers == (1, 2, 5)


ALL_COMMS = [comm.Allreduce(), comm.Allgather(), comm.RingAllreduce(),
             comm.TwoShotAllreduce(), comm.ReduceScatterAllreduce(),
             comm.SignAllreduce(), comm.Broadcast(),
             comm.HierarchicalAllreduce(slice_size=2),
             comm.HierarchicalAllreduce(slice_size=2, region_size=4)]


@pytest.mark.parametrize("world", [0, 1], ids=["w0", "w1"])
@pytest.mark.parametrize("vote", [False, True], ids=["payload", "vote"])
@pytest.mark.parametrize("c", ALL_COMMS, ids=lambda c: type(c).__name__)
def test_recv_link_bytes_degenerate_worlds_are_zero_on_every_tier(
        c, vote, world):
    """W=0/W=1 edge pin: no peer, no wire — zero on EVERY tier, under a
    topology that would otherwise claim the axis spans regions. A formula
    that goes negative (S−1 terms) or prices a self-exchange is a wire
    model bug the auditor would inherit."""
    lb = c.recv_link_bytes(1000, 250, world, topology=TOPO3, vote=vote)
    assert lb == LinkBytes(ici=0, dcn=0, wan=0)
    assert c.recv_wire_bytes(1000, 250, world, vote=vote) == 0


# ---------------------------------------------------------------------------
# the three-level schedule's wire split and its degradation ladder
# ---------------------------------------------------------------------------

def test_hier3_split_formula_and_sum_identity():
    """The documented three-leg formula at W=8 / slice 2 / region 4:
    S=2, Kr=2 slices per region, R=2 regions."""
    p = 1600
    h = comm.HierarchicalAllreduce(slice_size=2, region_size=4)
    lb = h.recv_link_bytes(p, 400, W, topology=TOPO3)
    s, kr, r = 2, 2, 2
    assert lb.ici == 2 * p * (s - 1) // s        # intra-slice ring legs
    assert lb.dcn == (kr - 1) * p // s           # cross-slice partials
    assert lb.wan == (r - 1) * p // s            # cross-region partials
    assert lb.total == h.recv_wire_bytes(p, 400, W)
    assert lb.ici > 0 and lb.dcn > 0 and lb.wan > 0


def test_flat_schedule_prices_at_worst_tier():
    """A flat collective's whole bill lands on the slowest boundary the
    axis spans (Topology.flat_tier): WAN across regions, DCN across
    slices, ICI inside one."""
    p = 1600
    ring = comm.RingAllreduce()
    assert TOPO3.flat_tier(W) == "wan"
    lb = ring.recv_link_bytes(p, 400, W, topology=TOPO3)
    assert (lb.ici, lb.dcn) == (0, 0) and lb.wan == lb.total > 0
    assert TOPO3.flat_tier(4) == "dcn"           # one region, two slices
    lb4 = ring.recv_link_bytes(p, 400, 4, topology=TOPO3)
    assert (lb4.ici, lb4.wan) == (0, 0) and lb4.dcn > 0
    assert TOPO3.flat_tier(2) == "ici"           # inside one slice
    lb2 = ring.recv_link_bytes(p, 400, 2, topology=TOPO3)
    assert (lb2.dcn, lb2.wan) == (0, 0) and lb2.ici > 0


def test_two_level_schedule_on_three_tier_fleet_pays_wan_for_cross():
    """Degradation ladder: a two-level schedule whose cross-slice groups
    span regions puts the WHOLE cross bill on WAN — some group member's
    incoming link is a region boundary."""
    p = 1600
    h2 = comm.HierarchicalAllreduce(slice_size=2)
    lb = h2.recv_link_bytes(p, 400, W, topology=TOPO3)
    h2_flat = h2.recv_link_bytes(p, 400, W,
                                 topology=Topology(slice_size=2))
    assert lb.ici == h2_flat.ici                 # intra legs still ICI
    assert lb.dcn == 0
    assert lb.wan == h2_flat.dcn                 # cross bill, one tier down
    assert lb.total == h2_flat.total             # the scalar never moves


def test_single_region_collapses_to_two_tier_bitwise():
    """One region == no WAN tier. Model: the 3-tier split equals the
    committed 2-tier split exactly. Mesh: the schedules are identical, so
    the outputs are bit-identical even on float data."""
    p = 1600
    h3 = comm.HierarchicalAllreduce(slice_size=2, region_size=8)
    h2 = comm.HierarchicalAllreduce(slice_size=2)
    t3 = Topology(slice_size=2, region_size=8)
    t2 = Topology(slice_size=2)
    lb3 = h3.recv_link_bytes(p, 400, W, topology=t3)
    lb2 = h2.recv_link_bytes(p, 400, W, topology=t2)
    assert lb3 == lb2 and lb3.wan == 0
    assert not t3.crosses_wan(W)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(W, 41)).astype(np.float32))
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    out3 = run_step(mesh, h3, C.TopKCompressor(compress_ratio=0.3),
                    NoneMemory(), x)
    out2 = run_step(mesh, h2, C.TopKCompressor(compress_ratio=0.3),
                    NoneMemory(), x)
    np.testing.assert_array_equal(out3, out2)


# ---------------------------------------------------------------------------
# exact summation across the WAN boundary
# ---------------------------------------------------------------------------

REGION_SPLITS = [(1, 2), (1, 4), (2, 4), (2, 8), (4, 8), (2, 2)]

# Every split is covered, but only the canonical s2r4 split (both
# boundaries inside the mesh) runs in tier-1 — each traced step costs
# seconds of shard_map compile, so the full matrix is `slow`.
_FAST_SPLIT = (2, 4)


def _split_params(splits):
    return [pytest.param(sp, id=f"s{sp[0]}r{sp[1]}",
                         marks=() if sp == _FAST_SPLIT
                         else pytest.mark.slow)
            for sp in splits]


@pytest.mark.parametrize("comp", [C.NoneCompressor(), C.FP16Compressor(),
                                  C.HomoQSGDCompressor(quantum_num=7)],
                         ids=["none", "fp16", "homoqsgd"])
@pytest.mark.parametrize("split", _split_params(REGION_SPLITS))
def test_hier3_bit_identical_to_flat_ring_on_integer_grads(rng, comp,
                                                           split):
    """ISSUE 16 acceptance: the three-level schedule — intra-slice ring,
    cross-slice gather-sum, cross-region gather-sum — is BIT-identical to
    the flat ring for selection-free exact payloads at every (slice,
    region) split. Integer-valued gradients make every partial sum exactly
    representable (f32, fp16 AND homoqsgd's shared-scale integer levels),
    so a wrong region grouping, a dropped cross-region partial, or a
    requant sneaking into the WAN leg shows up as an integer-sized
    error."""
    s, r = split
    x = rng.integers(-7, 8, size=(W, 37)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    ref = run_step(mesh, comm.RingAllreduce(), comp, NoneMemory(),
                   jnp.asarray(x))
    out = run_step(mesh,
                   comm.HierarchicalAllreduce(slice_size=s, region_size=r),
                   comp, NoneMemory(), jnp.asarray(x))
    np.testing.assert_array_equal(out, ref)


THREE_TIER_SPLITS = [(2, 4), (2, 8), (4, 8), (2, 2)]


# The selection codecs (randomk, countsketch) are the ones the flat-ring
# comparison above cannot cover, so they are the tier-1 representatives
# here; the exact/homomorphic codecs already have a fast bit-identity pin
# vs the ring and run this matrix only in the full (slow) suite.
@pytest.mark.parametrize(
    "comp",
    [pytest.param(C.NoneCompressor(), id="none", marks=pytest.mark.slow),
     pytest.param(C.FP16Compressor(), id="fp16", marks=pytest.mark.slow),
     pytest.param(C.RandomKCompressor(compress_ratio=0.5), id="randomk"),
     pytest.param(C.HomoQSGDCompressor(quantum_num=7), id="homoqsgd",
                  marks=pytest.mark.slow),
     pytest.param(C.CountSketchCompressor(compress_ratio=0.5),
                  id="countsketch")])
@pytest.mark.parametrize("split", _split_params(THREE_TIER_SPLITS))
def test_region_tier_adds_zero_loss_vs_two_tier(rng, comp, split):
    """The WAN level costs NOTHING in accuracy for every payload algebra:
    at the same slice width, the three-level schedule is bit-identical to
    the two-level one on integer-valued gradients — splitting the
    cross-slice sum into a DCN stage and a WAN stage only reassociates an
    exact payload-space sum. (Selection codecs — randomk's shard-folded
    keys, countsketch's hash stream — shard identically at equal S, so
    this holds where the flat-ring comparison cannot: the flat ring
    shards W ways, not S ways.)"""
    s, r = split
    x = rng.integers(-7, 8, size=(W, 37)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    two = run_step(mesh, comm.HierarchicalAllreduce(slice_size=s), comp,
                   NoneMemory(), jnp.asarray(x), seed=5)
    three = run_step(mesh,
                     comm.HierarchicalAllreduce(slice_size=s,
                                                region_size=r),
                     comp, NoneMemory(), jnp.asarray(x), seed=5)
    np.testing.assert_array_equal(three, two)


def test_wan_compressor_gates_and_wan_leg_width(rng):
    """The aggressive per-level WAN codec: only legal over a requant base
    (exact payloads must keep their zero-requant WAN sum), must itself be
    a hop-requant codec, needs a region tier to encode for — and when
    armed, the WAN leg is priced at the WAN codec's own payload width."""
    wan = C.TopKCompressor(compress_ratio=0.05)
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    x = jnp.asarray(rng.normal(size=(W, 16)).astype(np.float32))
    with pytest.raises(TypeError, match="exactly-summable"):
        run_step(mesh,
                 comm.HierarchicalAllreduce(slice_size=2, region_size=4,
                                            wan_compressor=wan),
                 C.FP16Compressor(), NoneMemory(), x)
    with pytest.raises(TypeError, match="supports_hop_requant"):
        run_step(mesh,
                 comm.HierarchicalAllreduce(
                     slice_size=2, region_size=4,
                     wan_compressor=C.FP16Compressor()),
                 C.TopKCompressor(compress_ratio=0.3), NoneMemory(), x)
    with pytest.raises(ValueError, match="region_size"):
        comm.HierarchicalAllreduce(slice_size=2, wan_compressor=wan)

    base = comm.HierarchicalAllreduce(slice_size=2, region_size=4)
    armed = comm.HierarchicalAllreduce(slice_size=2, region_size=4,
                                       wan_compressor=wan)
    p, n = 1600, 400
    lb0 = base.recv_link_bytes(p, n, W, topology=TOPO3)
    lb1 = armed.recv_link_bytes(p, n, W, topology=TOPO3)
    # intra and cross-slice legs are untouched; the WAN leg shrinks to
    # the aggressive codec's width (5% topk of a 200-element f32 shard).
    assert (lb1.ici, lb1.dcn) == (lb0.ici, lb0.dcn)
    assert 0 < lb1.wan < lb0.wan
    assert lb1.total == armed.recv_wire_bytes(p, n, W)
    # shrunk to a region-less topology drops the WAN codec with the tier
    assert armed.shrunk(Topology(slice_size=2)).wan_compressor is None
    assert armed.shrunk(TOPO3).wan_compressor is wan


@pytest.mark.slow
def test_wan_compressor_step_converges_on_mesh(rng):
    """The armed WAN requant path runs end to end on the 3-tier mesh and
    stays a faithful (if aggressive) estimate of the dense mean: the
    region boundary pays ONE re-encode, not R−1."""
    x = rng.normal(size=(W, 64)).astype(np.float32)
    mesh = Mesh(np.array(jax.devices()[:W]), ("data",))
    out = run_step(
        mesh,
        comm.HierarchicalAllreduce(
            slice_size=2, region_size=4,
            wan_compressor=C.TopKCompressor(compress_ratio=0.5)),
        C.TopKCompressor(compress_ratio=0.5), NoneMemory(),
        jnp.asarray(x))
    ref = x.mean(0)
    assert np.isfinite(out).all()
    nz = out != 0
    assert nz.any()
    # surviving lanes carry twice-top-k'd PARTIAL sums (the intra-slice
    # selection runs before the boundary), so the pin is bounded error +
    # strong alignment with the dense mean, not bit-equality.
    rel = np.linalg.norm(out - ref) / np.linalg.norm(ref)
    assert rel < 0.75
    cos = float(out @ ref) / (np.linalg.norm(out) * np.linalg.norm(ref))
    assert cos > 0.7


# ---------------------------------------------------------------------------
# shrink / plan_resize granularity: region -> slice -> rank
# ---------------------------------------------------------------------------

def test_shrink_granularity_matrix():
    """The finest violated level decides what survives (ROADMAP item 4):
    whole regions keep the full 3-tier layout (until one region remains),
    whole slices keep the slice tier only, partial slices keep nothing."""
    t = Topology(slice_size=2, region_size=4)
    # whole region lost, >= 2 regions remain: full 3-tier survives
    assert t.shrink(16, range(12, 16)) == (t, 12)
    # two whole regions lost of four: still 3-tier
    assert t.shrink(16, range(4, 12)) == (t, 8)
    # three whole regions lost: one region remains -> WAN tier is vacuous
    assert t.shrink(16, range(4, 16)) == (Topology(slice_size=2), 4)
    assert t.shrink(8, range(4, 8)) == (Topology(slice_size=2), 4)
    # whole slice lost (not a whole region): slice tier survives alone
    assert t.shrink(16, (2, 3)) == (Topology(slice_size=2), 14)
    # partial slice lost: flat layout
    assert t.shrink(16, (5,)) == (Topology(), 15)
    # nothing lost: identity
    assert t.shrink(16, ()) == (t, 16)


def test_plan_resize_whole_regions_flag():
    """ResizePlan surfaces region granularity the way it surfaces slice
    granularity — the elastic_resize event and chaos_smoke assert on it."""
    p = plan_resize(W, (4, 5, 6, 7), TOPO3)
    assert p.whole_regions and p.whole_slices
    assert p.topology == Topology(slice_size=2)   # one region remains
    assert p.new_world == 4 and p.survivors == (0, 1, 2, 3)
    p = plan_resize(W, (2, 3), TOPO3)             # a slice, not a region
    assert p.whole_slices and not p.whole_regions
    assert p.topology == Topology(slice_size=2)
    p = plan_resize(W, (1,), TOPO3)               # a rank, not a slice
    assert not p.whole_slices and not p.whole_regions
    assert p.topology == Topology()
    p = plan_resize(W, (), TOPO3)                 # no loss: 3-tier intact
    assert not p.whole_regions and p.topology == TOPO3


# ---------------------------------------------------------------------------
# Topology.detect: region_index gets slice_index's hardening, never less
# ---------------------------------------------------------------------------

class _Dev:
    def __init__(self, slice_index=None, region_index=None):
        if slice_index is not None:
            self.slice_index = slice_index
        if region_index is not None:
            self.region_index = region_index


def test_detect_reads_region_index_like_slice_index():
    devs = [_Dev(slice_index=i // 2, region_index=i // 4) for i in range(8)]
    assert Topology.detect(devs) == Topology(slice_size=2, region_size=4)


def test_detect_single_region_is_no_region_tier():
    devs = [_Dev(slice_index=i // 2, region_index=0) for i in range(8)]
    assert Topology.detect(devs) == Topology(slice_size=2)


def test_detect_rejects_partial_region_exposure():
    devs = [_Dev(slice_index=i // 2,
                 region_index=(i // 4 if i < 4 else None))
            for i in range(8)]
    with pytest.raises(ValueError, match="region_index"):
        Topology.detect(devs)


def test_detect_rejects_uneven_regions():
    sizes = [5, 3]
    devs = []
    for rho, n in enumerate(sizes):
        devs += [_Dev(slice_index=len(devs) + i, region_index=rho)
                 for i in range(n)]
    with pytest.raises(ValueError, match="uneven"):
        Topology.detect(devs)


def test_detect_rejects_region_without_slice_tier():
    devs = [_Dev(region_index=i // 4) for i in range(8)]
    with pytest.raises(ValueError, match="region tier without a slice"):
        Topology.detect(devs)


def test_detect_rejects_slice_straddling_region_boundary():
    # slices of 3 inside regions of 4: region width is not a multiple of
    # the slice width — the contiguous-block descriptor cannot express it.
    devs = [_Dev(slice_index=i // 3, region_index=i // 4)
            for i in range(12)]
    with pytest.raises(ValueError, match="multiple of the slice"):
        Topology.detect(devs)


# ---------------------------------------------------------------------------
# ElasticController: region-wide episodes are ONE transition; bounded drain
# ---------------------------------------------------------------------------

@pytest.mark.elastic
def test_region_scope_quorum():
    """region_scope widens a flagged rank to its whole region exactly when
    region_quorum of the region's ranks carry skew episodes."""
    ctl = ElasticController(anomaly_threshold=1, topology=TOPO3,
                            region_quorum=0.5)
    ctl.episodes = {4: 1}
    assert ctl.region_scope(4) == (4,)            # 1 of 4 hot: below quorum
    ctl.episodes = {4: 1, 6: 2}
    assert ctl.region_scope(4) == (4, 5, 6, 7)    # 2 of 4 hot: region-wide
    assert ctl.region_scope(6) == (4, 5, 6, 7)
    assert ctl.region_scope(0) == (0,)            # the healthy region
    strict = ElasticController(anomaly_threshold=1, topology=TOPO3,
                               region_quorum=1.0)
    strict.episodes = {4: 1, 5: 1, 6: 1}
    assert strict.region_scope(4) == (4,)         # 3 of 4 < full quorum
    strict.episodes = {4: 1, 5: 1, 6: 1, 7: 1}
    assert strict.region_scope(4) == (4, 5, 6, 7)
    # no region layout: scope is always the rank itself
    flat = ElasticController(anomaly_threshold=1)
    flat.episodes = {4: 9}
    assert flat.region_scope(4) == (4,)


@pytest.mark.elastic
def test_region_drain_is_one_transition():
    """Draining with a region scope marks every member drained, so later
    threshold crossings inside the same region are absorbed — one failing
    domain, one drain event."""
    ctl = ElasticController(anomaly_threshold=1, topology=TOPO3,
                            region_quorum=0.5)
    skew = [{"kind": "skew", "metric": "compression_error", "rank": r}
            for r in (4, 6)]
    assert ctl.observe(0, skew[:1]) == 4
    rec = ctl.drain(0, state=None, rank=4, scope=(4, 5, 6, 7))
    assert rec["event"] == "elastic_drain"
    assert rec["scope"] == [4, 5, 6, 7]
    assert rec["drain_timeouts"] == 0
    assert not rec["checkpointed"]                # no checkpointer armed
    assert ctl.drained_ranks == {4, 5, 6, 7}
    # rank 6 crosses the threshold next — absorbed, no second transition
    assert ctl.observe(1, skew[1:]) is None
    assert [e["event"] for e in ctl.events] == ["elastic_drain"]


class _StallingCheckpointer:
    """A wedged checkpoint backend: save returns, wait never does."""

    def __init__(self, stall_s=30.0):
        self.stall_s = stall_s
        self.saves = 0

    def save(self, step, state, force=True, good=True):
        self.saves += 1

    def wait(self):
        time.sleep(self.stall_s)

    def last_good_step(self):
        return 7


@pytest.mark.elastic
def test_drain_timeout_backoff_and_proceed_with_last_known_good():
    """A stalled checkpoint backend must not wedge the drain: each attempt
    gets a bounded window, stalls emit elastic_drain_timeout with the
    doubled-backoff window and the last known good step, and the drain
    proceeds with checkpointed=False after the retry budget."""
    ckpt = _StallingCheckpointer()
    ctl = ElasticController(anomaly_threshold=1, checkpointer=ckpt,
                            topology=TOPO3, drain_timeout_s=0.05,
                            drain_retries=1)
    t0 = time.perf_counter()
    rec = ctl.drain(3, state=None, rank=4, scope=(4, 5, 6, 7))
    assert time.perf_counter() - t0 < 5.0         # bounded, not 30 s
    assert not rec["checkpointed"]
    assert rec["drain_timeouts"] == 2             # first try + 1 retry
    assert ckpt.saves == 2
    touts = [e for e in ctl.events
             if e["event"] == "elastic_drain_timeout"]
    assert [e["attempt"] for e in touts] == [1, 2]
    assert touts[0]["timeout_s"] == pytest.approx(0.05)
    assert touts[1]["timeout_s"] == pytest.approx(0.10)  # doubled backoff
    assert [e["retries_left"] for e in touts] == [1, 0]
    assert all(e["last_good_step"] == 7 for e in touts)
    # events stay ordered: the timeouts precede the drain record
    assert [e["event"] for e in ctl.events] == [
        "elastic_drain_timeout", "elastic_drain_timeout", "elastic_drain"]


@pytest.mark.elastic
def test_drain_timeout_validation_and_fast_path():
    with pytest.raises(ValueError, match="drain_timeout_s"):
        ElasticController(drain_timeout_s=0.0)
    with pytest.raises(ValueError, match="drain_retries"):
        ElasticController(drain_retries=-1)
    with pytest.raises(ValueError, match="region_quorum"):
        ElasticController(region_quorum=0.0)

    class _Fast(_StallingCheckpointer):
        def wait(self):
            pass

    ctl = ElasticController(checkpointer=_Fast(), drain_timeout_s=5.0)
    rec = ctl.drain(0, state=None, rank=1)
    assert rec["checkpointed"] and rec["drain_timeouts"] == 0
    assert rec["scope"] == [1]                    # default scope: the rank


# ---------------------------------------------------------------------------
# telemetry: the three-way split identity through every fold
# ---------------------------------------------------------------------------

def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * W, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _run_rows(mesh, grace_params, schedule=("run", "run")):
    """Rows from real steps; ``schedule`` entries: run | fallback."""
    grc = grace_from_params(dict(grace_params))
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.3))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    batch = _problem()
    for mode in schedule:
        state = set_fallback_flag(state, mode == "fallback")
        state, _ = step(state, batch)
    rows = TelemetryReader(sink=None, every=100).flush(state)
    assert rows
    return grc, rows


HIER3 = {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
         "communicator": "hier", "slice_size": 2, "region_size": 4,
         "fusion": "flat", "telemetry": 16}


@pytest.mark.telemetry
def test_telemetry_three_way_split_identity_and_fallback_flip(mesh):
    """hier3 rows carry a genuinely three-way split that sums to
    wire_bytes and matches the config's own recv_link_bytes; during a
    dense-fallback window the flat escape psum's bytes land ENTIRELY on
    WAN (flat_tier of a region-spanning axis), and the identity holds
    through the flip."""
    grc, rows = _run_rows(mesh, dict(HIER3, escape="fp16"),
                          schedule=("run", "fallback", "run"))
    assert [r["fallback"] for r in rows] == [0, 1, 0]
    for r in rows:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] \
            + r["wire_bytes_wan"] == r["wire_bytes"]
    compressed = [r for r in rows if not r["fallback"]]
    dense = [r for r in rows if r["fallback"]]
    assert all(r["wire_bytes_ici"] > 0 and r["wire_bytes_dcn"] > 0
               and r["wire_bytes_wan"] > 0 for r in compressed)
    assert all(r["wire_bytes_ici"] == 0 and r["wire_bytes_dcn"] == 0
               and r["wire_bytes_wan"] == r["wire_bytes"] > 0
               for r in dense)
    # the model the compressed rows must match bit-exactly
    from grace_tpu.transform import fusion_payload_nbytes
    _, comp_b, n_elems = fusion_payload_nbytes(
        grc.compressor, jax.tree_util.tree_leaves(_init_params()), "flat")
    lb = grc.communicator.recv_link_bytes(comp_b, n_elems, W,
                                          topology=TOPO3)
    for r in compressed:
        assert (r["wire_bytes_ici"], r["wire_bytes_dcn"],
                r["wire_bytes_wan"]) == (lb.ici, lb.dcn, lb.wan)


@pytest.mark.telemetry
@pytest.mark.watch
def test_telemetry_watch_gather_folds_into_wan_leg(mesh):
    """The watch health gather is a flat full-axis collective: on a
    region-spanning axis its bytes fold into the WAN leg (and the scalar),
    keeping the split identity exact on gather steps."""
    grc, rows = _run_rows(
        mesh, dict(HIER3, watch={"window": 1, "capacity": 8}))
    # the reader interleaves watch summary rows with metric rows — only
    # the metric rows carry the split (same filter as tests/test_hier.py)
    gathered = [r for r in rows
                if "wire_bytes_ici" in r and r.get("watch_bytes", 0) > 0]
    assert gathered
    from grace_tpu.transform import fusion_payload_nbytes
    _, comp_b, n_elems = fusion_payload_nbytes(
        grc.compressor, jax.tree_util.tree_leaves(_init_params()), "flat")
    lb = grc.communicator.recv_link_bytes(comp_b, n_elems, W,
                                          topology=TOPO3)
    for r in gathered:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] \
            + r["wire_bytes_wan"] == r["wire_bytes"]
        assert (r["wire_bytes_ici"], r["wire_bytes_dcn"]) == (lb.ici,
                                                              lb.dcn)
        assert r["wire_bytes_wan"] == lb.wan + r["watch_bytes"]


@pytest.mark.telemetry
@pytest.mark.homo
def test_telemetry_negotiation_folds_into_wan_leg(mesh):
    """The shared-scale negotiation pmax is a flat full-axis collective:
    on a region-spanning axis its bytes land on the WAN leg — the split
    identity survives the homomorphic codec's hoisted negotiation."""
    grc, rows = _run_rows(
        mesh, {"compressor": "homoqsgd", "quantum_num": 7,
               "memory": "none", "communicator": "hier", "slice_size": 2,
               "region_size": 4, "fusion": "flat", "telemetry": 16})
    assert all(r["negotiation_bytes"] > 0 for r in rows)
    from grace_tpu.transform import fusion_payload_nbytes
    _, comp_b, n_elems = fusion_payload_nbytes(
        grc.compressor, jax.tree_util.tree_leaves(_init_params()), "flat")
    lb = grc.communicator.recv_link_bytes(comp_b, n_elems, W,
                                          topology=TOPO3)
    for r in rows:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] \
            + r["wire_bytes_wan"] == r["wire_bytes"]
        assert (r["wire_bytes_ici"], r["wire_bytes_dcn"]) == (lb.ici,
                                                              lb.dcn)
        assert r["wire_bytes_wan"] == lb.wan + r["negotiation_bytes"]


@pytest.mark.telemetry
@pytest.mark.adapt
def test_telemetry_adapt_signal_folds_into_wan_leg(mesh):
    """graft-adapt's per-step signal reductions are flat full-axis
    collectives too: priced on the WAN leg of a region-spanning axis, with
    the identity exact at every rung (including the forced dense rung)."""
    from grace_tpu.resilience.adapt import adapt_signal_bytes
    grc, rows = _run_rows(
        mesh, dict(HIER3, escape="fp16",
                   adapt={"window": 4, "ladder": [{"compress_ratio": 0.1}],
                          "tighten_error": 0.99, "tighten_peak": 0.999,
                          "loosen_error": 0.25, "quiet_windows": 2,
                          "hold_windows": 2}),
        schedule=("run", "fallback", "run"))
    sig = float(adapt_signal_bytes(W))
    assert all(r["adapt_bytes"] == sig for r in rows)
    for r in rows:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] \
            + r["wire_bytes_wan"] == r["wire_bytes"]
    dense = [r for r in rows if r["fallback"]]
    assert dense
    # rung 0 is the flat escape psum: everything (payload + signal) on WAN
    assert all(int(r["adapt_rung"]) == 0
               and r["wire_bytes_ici"] == 0 and r["wire_bytes_dcn"] == 0
               and r["wire_bytes_wan"] == r["wire_bytes"] for r in dense)
