"""Test harness: 8 simulated CPU devices.

The reference has no test suite at all (SURVEY.md §4) — multi-rank behavior
was only exercised on real NCCL clusters. JAX lets us run real collective
semantics single-process: 8 host devices via XLA_FLAGS, a Mesh over them,
and `shard_map` executes genuine all_gather/psum. Env vars must be set
before jax initializes, hence this conftest-level setup.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

# The dev image's sitecustomize imports jax and latches JAX_PLATFORMS to the
# TPU tunnel before this file runs, so setting env vars is not enough —
# override via config (legal until the first backend initializes).
jax.config.update("jax_platforms", "cpu")
import numpy as np  # noqa: E402
import pytest  # noqa: E402

from grace_tpu.parallel import (data_parallel_mesh,  # noqa: E402
                                relax_cpu_collective_timeouts,
                                set_cpu_device_count)

# JAX >= 0.4.38 spells this as the jax_num_cpu_devices config option; on
# older JAX (e.g. 0.4.37) the helper falls back to XLA_FLAGS, which is
# still effective here because the CPU backend has not initialized yet
# (nothing above touches jax.devices()).
set_cpu_device_count(8)

# 8 device threads on a possibly 1-core host: don't let XLA's 40s collective
# rendezvous terminate-timeout kill a slow-but-healthy test step.
relax_cpu_collective_timeouts()


@pytest.fixture(scope="session")
def mesh():
    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 simulated devices, got {len(devices)}"
    return data_parallel_mesh(devices)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
