"""Kernel-resident wire path (ops.pallas_wire + comm pipeline, ISSUE 19).

The acceptance bar pinned here:

* **bit-identity** — the fused decode→accumulate(→requant) kernels equal
  the staged spelling bit-for-bit, at the kernel level (same payloads in,
  identical f32/uint8 out for every wire width) AND end-to-end (the same
  seed through RingAllreduce / HierarchicalAllreduce with the wire
  kernels forced on vs forced off via ``GRACE_DISABLE_PALLAS_WIRE``
  produces identical results across hop counts and the hier slice
  boundary) — fusing changes WHERE the hop runs, never WHAT it computes;
* **≥2× wire cut** — the documented HBM-traffic model
  (``pallas_wire.hop_hbm_bytes``) projects at least a 2× per-hop byte cut
  at every shipped pack width (claim_class="projected" in the evidence
  ledger via tools/graft_wire.py — a stage-attribution projection, not a
  device measurement);
* **one overflow constant** — the packed homoqsgd 2-bit config is
  rejected statically (flow pass 6) AND at runtime (the communicators'
  gate) from the same ``payload_sum_max_world`` constant;
* **double-buffered schedule** — ``pipeline=P`` validates, segments the
  buffer exactly, keeps the scalar wire model pipeline-invariant, and
  reports the tuner's ``wire_overlap_fraction`` discount.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu import compressors as C
from grace_tpu.memories import NoneMemory
from grace_tpu.ops.pallas_wire import (WIRE_WIDTHS, decode_accumulate,
                                       hop_hbm_bytes, packed_int_accumulate)
from grace_tpu.parallel import shard_map

pytestmark = pytest.mark.wire

# quantum_num per packed qsgd field width (QSGDCompressor.pack_width).
_Q_FOR_WIDTH = {2: 1, 3: 3, 4: 7}


def submesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full communicator step per rank on ``mesh``; returns rank 0's out."""

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        return out[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=P("data"), check_vma=False)
    return np.asarray(fn(per_rank)[0])


# ---------------------------------------------------------------------------
# kernel-level bit identity: fused decode_accumulate == staged spelling
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("width,k", [(2, 2), (3, 2), (4, 2), (4, 4)])
def test_qsgd_decode_accumulate_bit_identical(rng, width, k):
    """The ring hop's contract at every packed width: K payloads through
    the fused kernel (interpret mode off-TPU) == the committed sequential
    ``decompress + decompress`` staged spelling, bitwise."""
    q = _Q_FOR_WIDTH[width]
    staged = C.QSGDCompressor(quantum_num=q, use_pallas=False)
    fused = dataclasses.replace(staged, use_pallas=True)
    payloads, ctxs = [], []
    for j in range(k):
        x = jnp.asarray(rng.normal(size=(617,)).astype(np.float32))
        p, c, _ = staged.compress(x, None, jax.random.key(j))
        payloads.append(p)
        ctxs.append(c)
    want = staged.decode_accumulate(tuple(payloads), tuple(ctxs))
    got = fused.decode_accumulate(tuple(payloads), tuple(ctxs))
    assert np.asarray(want).tobytes() == np.asarray(got).tobytes()


@pytest.mark.parametrize("k", [2, 3])
def test_signsgd_decode_accumulate_bit_identical(rng, k):
    staged = C.SignSGDCompressor(use_pallas=False)
    fused = dataclasses.replace(staged, use_pallas=True)
    payloads, ctxs = [], []
    for j in range(k):
        x = jnp.asarray(rng.normal(size=(413,)).astype(np.float32))
        p, c, _ = staged.compress(x, None, jax.random.key(j))
        payloads.append(p)
        ctxs.append(c)
    want = staged.decode_accumulate(tuple(payloads), tuple(ctxs))
    got = fused.decode_accumulate(tuple(payloads), tuple(ctxs))
    assert np.asarray(want).tobytes() == np.asarray(got).tobytes()


def test_sign_vote_kernel_matches_staged_majority(rng):
    """vote=True re-signs the K-way tally inside the kernel — exactly the
    staged sum-then-sign (ties +1, like SignSGDCompressor.aggregate)."""
    from grace_tpu.ops.packing import pack_bits, unpack_bits
    n, k = 300, 3
    bits = rng.integers(0, 2, size=(k, n)).astype(bool)
    stacked = jnp.stack([pack_bits(jnp.asarray(b)) for b in bits])
    got = decode_accumulate(stacked, jnp.ones((k,), jnp.float32), n, 1,
                            sign=True, vote=True, interpret=True)
    signs = np.stack([np.asarray(unpack_bits(jnp.asarray(
        pack_bits(jnp.asarray(b))), n)) for b in bits]).astype(np.float32)
    summed = (signs * 2 - 1).sum(0)
    want = (summed >= 0).astype(np.float32) * 2 - 1
    np.testing.assert_array_equal(np.asarray(got), want)


def test_decode_accumulate_rejects_bad_widths(rng):
    stacked = jnp.zeros((2, 8), jnp.uint8)
    scales = jnp.ones((2,), jnp.float32)
    with pytest.raises(ValueError, match="width"):
        decode_accumulate(stacked, scales, 8, 5, interpret=True)
    with pytest.raises(ValueError, match="sign"):
        decode_accumulate(stacked, scales, 8, 4, sign=True, interpret=True)
    with pytest.raises(ValueError, match="vote"):
        decode_accumulate(stacked, scales, 8, 4, vote=True, interpret=True)


@pytest.mark.parametrize("width,k", [(2, 2), (3, 3), (4, 5)])
def test_packed_int_accumulate_byte_identical(rng, width, k):
    """The homoqsgd packed accumulate: fused kernel output is BYTE-equal
    to the staged unpack→add→repack whenever the true sums fit the field
    (levels masked so the K-way sum stays in the two's-complement range —
    the payload_sum_max_world invariant)."""
    comp = C.HomoQSGDCompressor(quantum_num=1, accum_bits=width,
                                use_pallas=False)
    fused = dataclasses.replace(comp, use_pallas=True)
    n = 531
    levels = rng.integers(-1, 2, size=(k, n)).astype(np.int32)
    if width == 2:
        # 2-bit field range is [-2, 1]: zero the second rank wherever the
        # first is +1 so the pair sum never reaches +2.
        levels[1] = np.where(levels[0] == 1, 0, levels[1])
    stacked = jnp.stack([comp._pack_levels(jnp.asarray(lv))
                         for lv in levels])
    want = np.asarray(comp._packed_accumulate(stacked))
    got = np.asarray(fused._packed_accumulate(stacked))
    np.testing.assert_array_equal(got, want)
    # and the packed sum decodes to the true integer sum
    np.testing.assert_array_equal(
        np.asarray(comp._unpack_levels(jnp.asarray(got), n)),
        levels.sum(0))


# ---------------------------------------------------------------------------
# end-to-end bit identity: wire kernels on vs off, same seed
# ---------------------------------------------------------------------------

def _ring_both_ways(monkeypatch, world, compressor, n=600, seed=3):
    """One RingAllreduce step with the wire kernels live (interpret) and
    one with ONLY the wire family disabled (encode kernels unchanged, so
    stage-1/requant payloads are identical draws); returns both outs."""
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(world, n)).astype(np.float32))
    mesh = submesh(world)
    monkeypatch.delenv("GRACE_DISABLE_PALLAS_WIRE", raising=False)
    fused = run_step(mesh, comm.RingAllreduce(), compressor, NoneMemory(),
                     x, seed=seed)
    monkeypatch.setenv("GRACE_DISABLE_PALLAS_WIRE", "1")
    with pytest.warns(RuntimeWarning, match="GRACE_DISABLE_PALLAS_WIRE"):
        staged = run_step(mesh, comm.RingAllreduce(), compressor,
                          NoneMemory(), x, seed=seed)
    monkeypatch.delenv("GRACE_DISABLE_PALLAS_WIRE", raising=False)
    return fused, staged


@pytest.mark.parametrize("world,q", [
    (2, 7),
    (2, 1),
    pytest.param(8, 7, marks=pytest.mark.slow),   # 7-hop chain: ~30 s
    pytest.param(4, 1, marks=pytest.mark.slow),   # 3-hop 2-bit chain
])
def test_ring_qsgd_fused_wire_bit_identical(monkeypatch, world, q):
    """ACCEPTANCE: qsgd4 and qsgd2 through the ring with fused
    decode→accumulate→requant hops == the staged wire path bitwise, same
    seed — GRACE_DISABLE_PALLAS_WIRE flips only WHERE the hop runs. The
    single-hop W=2 cases ride tier-1; the multi-hop chains (7-hop qsgd4,
    3-hop qsgd2) are the slow-marked long spellings of the same
    contract."""
    comp = C.QSGDCompressor(quantum_num=q, use_pallas=True)
    fused, staged = _ring_both_ways(monkeypatch, world, comp)
    assert fused.tobytes() == staged.tobytes()


@pytest.mark.slow
def test_ring_signsgd_fused_wire_bit_identical(monkeypatch):
    comp = C.SignSGDCompressor(use_pallas=True)
    fused, staged = _ring_both_ways(monkeypatch, 4, comp)
    assert fused.tobytes() == staged.tobytes()


def test_ring_homoqsgd_packed_fused_wire_bit_identical(monkeypatch):
    """The exact-path twin: packed homoqsgd hop adds are integer-exact in
    both spellings (W=4 <= payload_sum_max_world=7), so kernel-on equals
    kernel-off bitwise with no caveats."""
    comp = C.HomoQSGDCompressor(quantum_num=1, accum_bits=4,
                                use_pallas=True)
    assert comp.payload_sum_max_world() == 7
    fused, staged = _ring_both_ways(monkeypatch, 4, comp)
    assert fused.tobytes() == staged.tobytes()


@pytest.mark.hier
@pytest.mark.parametrize("comp", [
    C.QSGDCompressor(quantum_num=7, use_pallas=True),
    pytest.param(C.SignSGDCompressor(use_pallas=True),
                 marks=pytest.mark.slow),
])
def test_hier_slice_boundary_fused_bit_identical(monkeypatch, comp):
    """ACCEPTANCE: the hier slice boundary (world=4, slice_size=2 → Kr=2
    gathered slice partials) through _gathered_aggregate's fused K-way
    pass == the staged vmap-decompress + aggregate, bitwise — a 2-term
    sum is order-invariant, so the fused sequential accumulate and the
    staged jnp.sum spell the identical f32 adds."""
    world = 4
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(world, 600)).astype(np.float32))
    mesh = submesh(world)
    hier = comm.HierarchicalAllreduce(slice_size=2)
    monkeypatch.delenv("GRACE_DISABLE_PALLAS_WIRE", raising=False)
    fused = run_step(mesh, hier, comp, NoneMemory(), x, seed=5)
    monkeypatch.setenv("GRACE_DISABLE_PALLAS_WIRE", "1")
    with pytest.warns(RuntimeWarning, match="GRACE_DISABLE_PALLAS_WIRE"):
        staged = run_step(mesh, hier, comp, NoneMemory(), x, seed=5)
    monkeypatch.delenv("GRACE_DISABLE_PALLAS_WIRE", raising=False)
    assert fused.tobytes() == staged.tobytes()


def test_wire_fused_gate_reflects_selection_rule(monkeypatch):
    """wire_fused() is the live gate the gather boundaries consult: off on
    CPU under 'auto', on when forced, off again under the wire-family env
    override (encode family untouched) — all through the ONE shared
    pallas_mode rule."""
    from grace_tpu.ops import pallas_mode
    monkeypatch.delenv("GRACE_DISABLE_PALLAS", raising=False)
    monkeypatch.delenv("GRACE_DISABLE_PALLAS_WIRE", raising=False)
    assert not C.QSGDCompressor(quantum_num=7).wire_fused()  # auto, no TPU
    assert C.QSGDCompressor(quantum_num=7, use_pallas=True).wire_fused()
    assert not C.QSGDCompressor(quantum_num=64,
                                use_pallas=True).wire_fused()  # unpacked
    assert C.SignSGDCompressor(use_pallas=True).wire_fused()
    assert not C.HomoQSGDCompressor(use_pallas=True).wire_fused()  # no bits
    assert C.HomoQSGDCompressor(quantum_num=1, accum_bits=4,
                                use_pallas=True).wire_fused()
    monkeypatch.setenv("GRACE_DISABLE_PALLAS_WIRE", "1")
    with pytest.warns(RuntimeWarning):
        assert not C.QSGDCompressor(quantum_num=7,
                                    use_pallas=True).wire_fused()
    with pytest.warns(RuntimeWarning):
        assert pallas_mode(True, kernel="wire") == (False, False)
    assert pallas_mode(True, kernel="quant")[0]  # encode family untouched


# ---------------------------------------------------------------------------
# the >=2x wire cut, as a pinned stage-attribution projection
# ---------------------------------------------------------------------------

def test_hop_hbm_projection_meets_two_x_at_every_width():
    """ACCEPTANCE: the static byte model projects >= 2x per-hop HBM
    traffic cut at every shipped pack width and bucket size — the number
    tools/graft_wire.py stamps into WIRE_LAST.json and ledger-marks
    claim_class='projected' (deferred to the on-silicon capture)."""
    for width in WIRE_WIDTHS:
        for numel in (4096, 1 << 16, 1 << 20, 25_557_032):
            staged = hop_hbm_bytes(numel, width, fused=False)
            fused = hop_hbm_bytes(numel, width, fused=True)
            assert staged / fused >= 2.0, (width, numel)
    # pin the asymptotic ratios so a silent model edit shows up here
    big = 1 << 22
    r4 = hop_hbm_bytes(big, 4, False) / hop_hbm_bytes(big, 4, True)
    r2 = hop_hbm_bytes(big, 2, False) / hop_hbm_bytes(big, 2, True)
    assert 4.5 < r4 < 4.7          # 43.5n / 9.5n
    assert 4.8 < r2 < 5.0          # 42.75n / 8.75n


def test_graft_wire_tool_writes_projection(tmp_path):
    import importlib.util
    import json
    import os
    spec = importlib.util.spec_from_file_location(
        "graft_wire", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "graft_wire.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "WIRE_LAST.json"
    # outside the repo root: no ledger append, doc only
    assert mod.main(["--out", str(out), "--no-lint"]) == 0
    doc = json.loads(out.read_text())
    assert doc["claim_class"] == "projected"
    assert doc["meets_target"] and doc["min_ratio"] >= 2.0
    assert doc["deferred_capture"]
    assert {r["pack_width"] for r in doc["grid"]} == set(WIRE_WIDTHS)


# ---------------------------------------------------------------------------
# one overflow constant: 2-bit homoqsgd rejected statically AND at runtime
# ---------------------------------------------------------------------------

def test_homoqsgd_2bit_rejected_from_the_one_constant(rng):
    """accum_bits=2 @ quantum_num=1 → payload_sum_max_world == 1: flow
    pass 6 rejects any traced world beyond 1 and the communicators' gate
    raises at trace time on a 2-rank mesh — both reading the codec's ONE
    constant (the test_homo int8 idiom, tightened to the packed field)."""
    from grace_tpu.analysis.flow import pass_numeric_safety
    from grace_tpu.analysis.trace import trace_fn

    params = {"compressor": "homoqsgd", "quantum_num": 1, "accum_bits": 2,
              "memory": "none", "communicator": "ring", "fusion": "flat"}
    grace = grace_from_params(params)
    bound = grace.compressor.payload_sum_max_world()
    assert bound == 1                      # (2^(2-1) - 1) // 1

    # static: the numeric-safety pass fires at world 2 with the constant
    X = jax.ShapeDtypeStruct((16,), jnp.float32)
    hot = trace_fn(lambda x: x * 1.0, [X], world=bound + 1,
                   name="homo-2bit", meta={"grace": grace})
    mine = [f for f in pass_numeric_safety(hot)
            if "payload_sum_max_world" in f.message]
    assert len(mine) == 1 and mine[0].severity == "error"
    assert dict(mine[0].details)["payload_sum_max_world"] == bound

    # runtime: the ring's gate raises from the same constant at trace
    x = jnp.asarray(rng.normal(size=(2, 64)).astype(np.float32))
    with pytest.raises(ValueError, match="payload_sum_max_world"):
        run_step(submesh(2), comm.RingAllreduce(), grace.compressor,
                 NoneMemory(), x)


# ---------------------------------------------------------------------------
# double-buffered schedule: validation, segmentation, invariants
# ---------------------------------------------------------------------------

def test_pipeline_validates():
    with pytest.raises(ValueError, match="pipeline"):
        comm.RingAllreduce(pipeline=0)
    with pytest.raises(ValueError, match="pipeline"):
        comm.HierarchicalAllreduce(slice_size=4, pipeline=-1)


def test_pipeline_segments_partition_exactly():
    from grace_tpu.comm import _pipeline_segments
    for n, p in [(10, 1), (10, 2), (10, 3), (3, 8), (1, 4), (16384, 2)]:
        segs = _pipeline_segments(n, p)
        assert segs[0][0] == 0 and segs[-1][1] == n
        assert all(lo < hi for lo, hi in segs)
        assert all(a[1] == b[0] for a, b in zip(segs, segs[1:]))
        assert len(segs) <= max(1, p)      # tiny buffers pipeline less


def test_pipelined_ring_exact_codec_matches_serial(rng):
    """pipeline only re-schedules: for a deterministic exact codec the
    P=2 double-buffered ring equals the serial schedule exactly on
    integer-valued grads (every partial sum exactly representable)."""
    x = jnp.asarray(rng.integers(-8, 8, size=(4, 101)).astype(np.float32))
    mesh = submesh(4)
    serial = run_step(mesh, comm.RingAllreduce(), C.NoneCompressor(),
                      NoneMemory(), x)
    piped = run_step(mesh, comm.RingAllreduce(pipeline=2),
                     C.NoneCompressor(), NoneMemory(), x)
    np.testing.assert_array_equal(piped, serial)


@pytest.mark.slow
def test_pipelined_ring_packed_qsgd_valid_draw(rng):
    """The shipping qsgd2-ring-packed-pipelined shape: a pipelined packed
    ring is a different (per-segment rng fold) but equally valid draw —
    unbiasedness bounds the deviation from the dense mean like the serial
    twin's."""
    x = jnp.asarray(rng.normal(size=(4, 240)).astype(np.float32))
    mesh = submesh(4)
    comp = C.QSGDCompressor(quantum_num=7, use_pallas=False)
    piped = run_step(mesh, comm.RingAllreduce(pipeline=2), comp,
                     NoneMemory(), x)
    dense = np.asarray(x).mean(0)
    # per-hop requant error bound, not bit equality: same budget the
    # serial ring's error tests allow
    assert np.abs(piped - dense).max() < 1.0


def test_wire_overlap_fraction_and_recv_bytes_invariance():
    assert comm.RingAllreduce().wire_overlap_fraction() == 0.0
    assert comm.RingAllreduce(pipeline=2).wire_overlap_fraction() == 0.25
    assert comm.RingAllreduce(pipeline=4).wire_overlap_fraction() == 0.375
    h = comm.HierarchicalAllreduce(slice_size=4, pipeline=2)
    assert h.wire_overlap_fraction() == 0.25
    assert comm.Allgather().wire_overlap_fraction() == 0.0
    # the scalar wire model is pipeline-invariant (P segments each move
    # the same formula over 1/P of the buffer)
    a = comm.RingAllreduce()._recv_total_bytes(1000, 2000, 8)
    b = comm.RingAllreduce(pipeline=4)._recv_total_bytes(1000, 2000, 8)
    assert a == b
