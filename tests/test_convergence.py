"""Real-data convergence floor (VERDICT round-1 item 5).

Trains LeNet on the UCI digits dataset (real scanned digits bundled with
scikit-learn — the only real image data available offline) through the FULL
compressed pipeline on the 8-device mesh and asserts an accuracy floor. The
committed 60-epoch curves live in examples/logs/digits_*.tsv (98.9% with
Top-K 1%, matching the uncompressed baseline); this test runs a shortened
30-epoch version with a conservative floor so it stays deterministic across
environments yet still fails on any real convergence regression.
"""

import os
import sys

import pytest

pytest.importorskip("sklearn", reason="digits dataset ships with scikit-learn")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))


@pytest.mark.slow
def test_digits_topk_reaches_97pct():
    import digits_lenet

    acc = digits_lenet.run([
        "--compressor", "topk", "--compress-ratio", "0.01",
        "--memory", "residual", "--communicator", "allgather",
        "--epochs", "30",
    ])
    assert acc >= 0.97, f"digits Top-K 1% convergence regressed: acc={acc}"


@pytest.mark.slow
def test_digits_topk_bf16_residual_floor():
    # ResidualMemory(state_dtype='bfloat16'): the narrow-state rounding
    # must stay inside what error feedback absorbs (committed 60-epoch
    # curve: 99.17% vs 98.89% f32 — examples/logs/digits_topk1pct_rbf16.tsv).
    import digits_lenet

    acc = digits_lenet.run([
        "--compressor", "topk", "--compress-ratio", "0.01",
        "--topk-algorithm", "chunk",
        "--memory", "residual", "--memory-dtype", "bfloat16",
        "--communicator", "allgather", "--epochs", "30",
    ])
    assert acc >= 0.97, f"bf16-residual convergence regressed: acc={acc}"


@pytest.mark.slow
@pytest.mark.ring
def test_digits_topk_ring_floor():
    """ISSUE 4 acceptance: the hop-pipelined compressed ring converges on
    real data through the full transform — per-hop re-selection (W-1 hops,
    W-2 intermediate requants) must stay inside what error feedback plus
    SGD noise absorb. The intermediate requants are NOT covered by error
    feedback (IMPLEMENTING.md "Per-hop requantization"), so the curve lags
    allgather's slightly: measured 97.2% at epoch 45 (vs allgather's 98.9%
    at 60) — the floor is set conservatively below the deterministic
    plateau, and a broken ring lands at 10-60%."""
    import digits_lenet

    acc = digits_lenet.run([
        "--compressor", "topk", "--compress-ratio", "0.01",
        "--memory", "residual", "--communicator", "ring",
        "--epochs", "45",
    ])
    assert acc >= 0.96, f"digits Top-K 1% + ring convergence regressed: acc={acc}"


@pytest.mark.slow
def test_real_mnist_topk_floor():
    """Flagship real-data evidence (VERDICT round-2 item 3): LeNet on the
    bundled 10k real MNIST images through Top-K 1% + residual on the mesh.
    The committed 50-epoch curve (examples/logs/mnist10k_topk1pct.tsv)
    reaches 97.75%; 10 epochs with a conservative floor keeps the test
    affordable while still failing on any real convergence regression
    (the curve passes 96% by epoch 7)."""
    import mnist10k_lenet

    acc = mnist10k_lenet.run([
        "--compressor", "topk", "--compress-ratio", "0.01",
        "--memory", "residual", "--communicator", "allgather",
        "--epochs", "10",
    ])
    assert acc >= 0.94, f"real-MNIST Top-K 1% convergence regressed: acc={acc}"
