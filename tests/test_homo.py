"""Aggregation-homomorphic codec family (ISSUE 13): payload algebra,
shared-scale homomorphic QSGD, mergeable count-sketch, zero-requant
ring/hier summation.

The properties pinned here are the acceptance criteria:

* the payload-algebra capability is declared by every cataloged codec and
  ``summable_payload`` derives from it (no call site broke);
* payload-space summation is BIT-exact against decode-then-sum on integer
  gradients across ring hop counts and hier slice splits (integer-valued
  grads at ``max|x| == quantum_num`` make the shared-scale encode
  lossless, so a wrong hop route, a double-counted partial or a stray
  requant shows up as an integer-sized error);
* homoqsgd's compression error is hop-count-INDEPENDENT (one encode, zero
  requant) where qsgd's grows ~linearly in hops (the pinned PR-4 bound);
* the shared-scale accumulator overflow bound fires statically at exactly
  the world ``payload_sum_max_world`` predicts, and the runtime gate
  raises the same bound from the same constant;
* the tuner prices homomorphic configs at requant-chain 0 with the
  negotiation bytes in the wire model, and ``graft_tune --static-only``'s
  funnel ranks hier/ring+homoqsgd at W=256 without a degradation
  rejection (where qsgd-ring still dies at the ScaleCom cliff);
* hier+homoqsgd4 converges to the exact-summation (fp16) floor.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from grace_tpu import comm, grace_from_params
from grace_tpu import compressors as C
from grace_tpu.core import PAYLOAD_ALGEBRAS
from grace_tpu.memories import NoneMemory, ResidualMemory
from grace_tpu.parallel import shard_map
from grace_tpu.train import init_train_state, make_train_step

W = 8

pytestmark = pytest.mark.homo


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full pipeline step per rank on ``mesh``; returns (out, mem) of rank 0."""
    w = len(mesh.devices)

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        ms_leaf = ms if ms is not None else jnp.zeros_like(x)
        return out[None], ms_leaf[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                   out_specs=(P("data"), P("data")), check_vma=False)
    assert per_rank.shape[0] == w
    out, ms = fn(per_rank)
    return np.asarray(out[0]), np.asarray(ms[0])


def submesh(n):
    from jax.sharding import Mesh
    return Mesh(np.array(jax.devices()[:n]), ("data",))


# ---------------------------------------------------------------------------
# the capability: declared algebra, derived summable_payload
# ---------------------------------------------------------------------------

def test_catalog_payload_algebras():
    """Every cataloged codec declares its algebra; summable_payload is the
    derived view and never disagrees with it."""
    exact = [C.NoneCompressor(), C.FP16Compressor(),
             C.RandomKCompressor(0.5), C.PowerSGDCompressor()]
    homo = [C.HomoQSGDCompressor(), C.CountSketchCompressor()]
    none = [C.TopKCompressor(0.1), C.QSGDCompressor(),
            C.SignSGDCompressor(), C.SignumCompressor(),
            C.EFSignSGDCompressor(), C.OneBitCompressor(),
            C.NaturalCompressor(), C.DgcCompressor(0.1),
            C.ThresholdCompressor(0.01), C.SketchCompressor(),
            C.U8bitCompressor(), C.AdaqCompressor(0.1),
            C.TernGradCompressor(), C.InceptionNCompressor()]
    for comp in exact:
        assert comp.payload_algebra == "exact", comp
        assert comp.summable_payload
    assert homo[0].payload_algebra == "shared_scale"
    assert homo[1].payload_algebra == "sketch"
    for comp in homo:
        assert comp.payload_algebra in PAYLOAD_ALGEBRAS
        assert comp.summable_payload
    for comp in none:
        assert comp.payload_algebra is None, comp
        assert not comp.summable_payload, comp


def test_chaos_wrapper_delegates_algebra():
    """ChaosCompressor rides the inner codec's algebra (and the derived
    summable view), exactly like supports_hop_requant — so chaos injection
    qualifies for the homomorphic summation path."""
    from grace_tpu.resilience import ChaosCompressor

    inner = C.HomoQSGDCompressor()
    chaos = ChaosCompressor(inner=inner, bitflip_prob=0.5, rank=0)
    assert chaos.payload_algebra == "shared_scale"
    assert chaos.summable_payload
    assert chaos.payload_sum_max_world() == inner.payload_sum_max_world()
    assert chaos.negotiation_nbytes(8) == inner.negotiation_nbytes(8)
    assert ChaosCompressor(inner=C.TopKCompressor(0.1)).payload_algebra \
        is None


# ---------------------------------------------------------------------------
# bit-exact payload-space sum vs decode-then-sum
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("w", [2, 4, 8], ids=lambda w: f"w{w}")
def test_ring_payload_sum_bit_exact_vs_decode_then_sum(rng, w):
    """Integer grads with ``max|x| == quantum_num`` make the shared-scale
    encode lossless (levels == values, scale == q), so the ring's hop-added
    integer payloads must decode to EXACTLY what decoding every rank's
    payload and summing gives — which is what Allgather computes. Any
    requant sneaking into a hop, a wrong shard route or a scale drift is
    an integer-sized error. Runs 1 hop (w=2) through 7 hops (w=8)."""
    comp = C.HomoQSGDCompressor(quantum_num=7)
    x = rng.integers(-7, 8, size=(w, 37)).astype(np.float32)
    ref, _ = run_step(submesh(w), comm.Allgather(), comp, NoneMemory(),
                      jnp.asarray(x))                 # decode-then-sum
    out, _ = run_step(submesh(w), comm.RingAllreduce(), comp, NoneMemory(),
                      jnp.asarray(x))                 # payload-space sum
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(out, x.mean(0))     # and both are exact


@pytest.mark.hier
@pytest.mark.parametrize("s", [None, 1, 2, 4, 8], ids=lambda s: f"s{s}")
def test_hier_payload_sum_bit_exact_at_any_split(rng, s):
    """The two-level schedule — intra-slice integer hop adds AND the
    slice-boundary integer add — is bit-identical to the flat ring and to
    decode-then-sum at ANY slice split (zero requant at the boundary,
    where the requant path pays its ONE re-encode)."""
    comp = C.HomoQSGDCompressor(quantum_num=7)
    x = rng.integers(-7, 8, size=(W, 41)).astype(np.float32)  # 41: padding
    mesh = submesh(W)
    ref, _ = run_step(mesh, comm.RingAllreduce(), comp, NoneMemory(),
                      jnp.asarray(x))
    out, _ = run_step(mesh, comm.HierarchicalAllreduce(slice_size=s), comp,
                      NoneMemory(), jnp.asarray(x))
    np.testing.assert_array_equal(out, ref)
    np.testing.assert_array_equal(out, x.mean(0))


def test_countsketch_tables_merge_exactly(rng):
    """The sketch algebra's load-bearing identity:
    sketch(x) + sketch(y) == sketch(x + y), bit-exact on integer values
    (same shared hash stream on both sides)."""
    comp = C.CountSketchCompressor(compress_ratio=0.5)
    key = jax.random.key(3)
    x = jnp.asarray(rng.integers(-8, 9, size=(128,)).astype(np.float32))
    y = jnp.asarray(rng.integers(-8, 9, size=(128,)).astype(np.float32))
    (tx,), ctx, _ = comp.compress(x, None, key)
    (ty,), _, _ = comp.compress(y, None, key)
    (txy,), _, _ = comp.compress(x + y, None, key)
    np.testing.assert_array_equal(np.asarray(tx + ty), np.asarray(txy))
    # and the single decode of the merged table IS the decode of the sum
    np.testing.assert_array_equal(
        np.asarray(comp.decompress((tx + ty,), ctx)),
        np.asarray(comp.decompress((txy,), ctx)))


def test_countsketch_rides_ring_and_hier(rng):
    """countsketch qualifies for the payload-space path end to end (its
    hash ctx is rng-derived → data-free), and on its natural workload — a
    heavy-hitter gradient (few large coordinates over a small floor) — the
    merged sketch's single decode recovers the mean's heavy coordinates
    through 7 hops + a slice boundary."""
    comp = C.CountSketchCompressor(compress_ratio=1.0, rows=5)
    # ~2 heavy hitters per 32-element shard: collisions are rare at
    # width=ceil(32/5) and the 5-row median suppresses the rest.
    x = 0.01 * rng.normal(size=(W, 256)).astype(np.float32)
    heavy = rng.choice(256, size=16, replace=False)
    x[:, heavy] += rng.normal(scale=4.0, size=(W, 16)).astype(np.float32)
    mean = x.mean(0)
    for cm in (comm.RingAllreduce(),
               comm.HierarchicalAllreduce(slice_size=4)):
        out, _ = run_step(submesh(W), cm, comp, NoneMemory(),
                          jnp.asarray(x))
        err = (np.linalg.norm(out[heavy] - mean[heavy])
               / np.linalg.norm(mean[heavy]))
        assert err < 0.5, (type(cm).__name__, err)


# ---------------------------------------------------------------------------
# hop-count-independent error (vs qsgd's ~linear-in-W hop-error bound)
# ---------------------------------------------------------------------------

def test_homoqsgd_error_hop_count_independent(rng):
    """THE requant-tax kill shot, pinned: homoqsgd pays ONE stochastic
    encode regardless of hop count, so its relative error at 7 hops (w=8)
    must stay within a small constant of the 1-hop (w=2) error — where the
    committed qsgd bound (test_ring.py::
    test_qsgd_hop_error_bounded_one_vs_seven_hops) only promises a ~W×
    LINEAR envelope for the requant path's compounding re-encodes."""
    comp = C.HomoQSGDCompressor(quantum_num=7)

    def rel_err(w):
        xw = rng.normal(size=(w, 64)).astype(np.float32)
        out, _ = run_step(submesh(w), comm.RingAllreduce(), comp,
                          NoneMemory(), jnp.asarray(xw))
        return np.linalg.norm(out - xw.mean(0)) / np.linalg.norm(xw.mean(0))

    err1, err7 = rel_err(2), rel_err(8)
    assert err7 < 1.0, err7
    # hop-count independence: NOT the requant path's ~W× linear envelope —
    # 7 hops of extra encodes would blow this constant bound.
    assert err7 < 2.5 * max(err1, 1.0 / 7), (err1, err7)


# ---------------------------------------------------------------------------
# overflow bound: static finding and runtime gate from ONE constant
# ---------------------------------------------------------------------------

def test_overflow_bound_fires_at_the_statically_predicted_world(rng):
    """int8 @ quantum_num=32 → payload_sum_max_world == 127 // 32 == 3:
    the numeric-safety pass rejects any traced world beyond 3, the tuner's
    numeric gate rejects the same worlds, and the runtime gate raises at
    step time — all three reading the codec's one constant."""
    from grace_tpu.analysis.flow import pass_numeric_safety
    from grace_tpu.analysis.trace import trace_fn, trace_update
    from grace_tpu.tuning.cost import TuneTopology
    from grace_tpu.tuning.prune import numeric_verdict

    params = {"compressor": "homoqsgd", "quantum_num": 32,
              "accum_dtype": "int8", "memory": "none",
              "communicator": "ring", "fusion": "flat"}
    grace = grace_from_params(params)
    bound = grace.compressor.payload_sum_max_world()
    assert bound == 127 // 32 == 3

    # Static: world == bound is clean, world == bound + 1 fires — the
    # seeded proof the pass is live at exactly the predicted W. The full
    # pipeline cannot even TRACE past the bound (the communicators' gate
    # raises from the same constant at trace time, below), so the
    # seeded-bad graph rides trace_fn like the other flow seeded tests.
    clean = trace_update(grace, world=bound, name="homo-ok",
                         meta={"grace": grace})
    assert [f for f in pass_numeric_safety(clean)
            if "payload_sum_max_world" in f.message] == []
    X = jax.ShapeDtypeStruct((16,), jnp.float32)
    hot = trace_fn(lambda x: x * 1.0, [X], world=bound + 1,
                   name="homo-overflow", meta={"grace": grace})
    mine = [f for f in pass_numeric_safety(hot)
            if "payload_sum_max_world" in f.message]
    assert len(mine) == 1 and mine[0].severity == "error"
    assert dict(mine[0].details)["payload_sum_max_world"] == bound
    # a gather communicator never payload-sums: same codec, no finding
    ag = grace_from_params({**params, "communicator": "allgather"})
    cold = trace_fn(lambda x: x * 1.0, [X], world=bound + 1,
                    name="homo-gather", meta={"grace": ag})
    assert [f for f in pass_numeric_safety(cold)
            if "payload_sum_max_world" in f.message] == []

    # Tuner numeric gate: same constant, same verdict at the target world.
    assert numeric_verdict(grace, TuneTopology(world=bound)) is None
    reason = numeric_verdict(grace, TuneTopology(world=bound + 1))
    assert reason is not None and "payload_sum_max_world" in reason

    # Runtime: the communicator raises the same bound on a live mesh.
    x = rng.normal(size=(4, 16)).astype(np.float32)
    with pytest.raises(ValueError, match="payload_sum_max_world"):
        run_step(submesh(4), comm.RingAllreduce(), grace.compressor,
                 NoneMemory(), jnp.asarray(x))
    # ... and stays silent within it.
    x2 = rng.normal(size=(2, 16)).astype(np.float32)
    run_step(submesh(2), comm.RingAllreduce(), grace.compressor,
             NoneMemory(), jnp.asarray(x2))


# ---------------------------------------------------------------------------
# error feedback covers the single shared-scale encode
# ---------------------------------------------------------------------------

def test_residual_memory_sees_the_single_encode(rng):
    """The negotiation is hoisted BEFORE stage 1, so the residual is
    exactly compensated − decode(own shard payloads) — the one encode the
    schedule performs. With a lossless integer encode the residual is
    exactly zero; with real data it equals the per-shard encode error."""
    comp = C.HomoQSGDCompressor(quantum_num=7)
    xi = rng.integers(-7, 8, size=(W, 48)).astype(np.float32)
    _, ms = run_step(submesh(W), comm.HierarchicalAllreduce(slice_size=4),
                     comp, ResidualMemory(), jnp.asarray(xi))
    np.testing.assert_array_equal(ms, np.zeros_like(ms))
    xr = rng.normal(size=(W, 48)).astype(np.float32)
    _, ms = run_step(submesh(W), comm.HierarchicalAllreduce(slice_size=4),
                     comp, ResidualMemory(), jnp.asarray(xr))
    # bounded by one quantization step of the NEGOTIATED (global pmax)
    # scale — the single encode's worst case under stochastic rounding
    assert np.max(np.abs(ms)) <= np.max(np.abs(xr)) / 7 + 1e-6


# ---------------------------------------------------------------------------
# tuner: requant-chain 0, negotiation priced, no degradation rejection
# ---------------------------------------------------------------------------

@pytest.mark.tune
def test_funnel_ranks_homomorphic_configs_without_degradation_at_w256():
    """ISSUE 13 acceptance: at W=256/slice8 the funnel prices hier+homoqsgd
    AND ring+homoqsgd at requant-chain 0 with the negotiation bytes in the
    wire model — while qsgd-ring (same schedule, per-rank scales) still
    dies at the PR-12 degradation gate. The flat-ring codec the ScaleCom
    cliff kept out of the ranking is finally rankable."""
    from grace_tpu.analysis.trace import default_param_structs
    from grace_tpu.tuning.candidates import enumerate_candidates
    from grace_tpu.tuning.cost import TuneTopology
    from grace_tpu.tuning.prune import requant_chain_length, static_prune

    spec = TuneTopology.parse("256,8")
    doc = static_prune(enumerate_candidates(spec), spec,
                       default_param_structs())
    rec = {r["candidate"]: r for r in doc["funnel"]}

    for name in ("homoqsgd-ring", "homoqsgd-hier", "tune-homoqsgd4-hier8"):
        r = rec[name]
        assert r["verdict"] in ("priced", "shortlisted"), (name, r)
        assert r.get("stage") != "degradation", (name, r)
        assert r["requant_chain"] == 0, (name, r)
        assert r["predicted"]["negotiation_bytes"] > 0, (name, r)
    # the before-picture the homomorphic family retires:
    assert rec["qsgd-ring"]["verdict"] == "rejected"
    assert rec["qsgd-ring"]["stage"] == "degradation"
    assert rec["qsgd-ring"]["requant_chain"] == 255

    # requant_chain_length itself reports 0 at ANY world for the algebra.
    g = grace_from_params({"compressor": "homoqsgd", "memory": "residual",
                           "communicator": "ring", "fusion": "flat"})
    assert requant_chain_length(g, TuneTopology(4096)) == 0
    # and homoqsgd outranks every surviving qsgd-family candidate that
    # still pays a requant (the hier boundary re-encode path).
    order = [x["candidate"] for x in doc["ranking"]]
    assert order.index("homoqsgd-ring") < order.index("qsgd_hier")


@pytest.mark.analysis
def test_new_homo_configs_audit_clean_including_wire_reconciliation():
    """The registered homomorphic configs trace and pass ALL passes —
    wire_reconciliation included, which audits the negotiation pmax's
    bytes against the model (a scalar collective inside the documented
    atol) and the integer payload schedule against recv_link_bytes."""
    from grace_tpu.analysis.configs import AUDIT_CONFIGS, audit_config

    names = {"homoqsgd-ring", "homoqsgd-hier", "countsketch-allgather",
             "homoqsgd-hier-guard-consensus"}
    seen = set()
    for entry in AUDIT_CONFIGS:
        if entry["name"] in names:
            seen.add(entry["name"])
            findings = audit_config(entry)
            assert findings == [], (entry["name"], [
                f"{f.pass_name}: {f.message}" for f in findings])
    assert seen == names
    # the two bare-update homo entries keep wire_reconciliation armed
    by_name = {e["name"]: e for e in AUDIT_CONFIGS}
    for name in ("homoqsgd-ring", "homoqsgd-hier", "countsketch-allgather"):
        assert "wire_reconciliation" in tuple(by_name[name]["passes"])


# ---------------------------------------------------------------------------
# telemetry: negotiation bytes folded like watch_bytes
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_negotiation_bytes_fold_into_wire_accounting(mesh):
    """Every homoqsgd step's row carries negotiation_bytes == the codec's
    negotiation_nbytes model (one pmax per compress call; fusion='flat' →
    one call), folded into wire_bytes AND the per-link split so the
    ici + dcn == wire_bytes identity survives."""
    from grace_tpu.telemetry import TelemetryReader

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64 * 8, 20)).astype(np.float32))
    y = jnp.asarray((rng.integers(0, 4, size=(64 * 8,))).astype(np.int32))

    def loss_fn(params, batch):
        xb, yb = batch
        logits = xb @ params["w"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    grc = grace_from_params({"compressor": "homoqsgd", "quantum_num": 7,
                             "memory": "residual", "communicator": "ring",
                             "fusion": "flat", "telemetry": 8})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.1))
    params = {"w": jnp.zeros((20, 4), jnp.float32)}
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)

    class _Sink:
        def __init__(self):
            self.records = []

        def write(self, r):
            self.records.append(dict(r))

        def close(self):
            pass

    sink = _Sink()
    reader = TelemetryReader(sink, every=4)
    for i in range(4):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    reader.flush(state)

    comp = grc.compressor
    metric = [r for r in sink.records if "negotiation_bytes" in r]
    assert metric, "no metric rows flushed"
    for r in metric:
        assert r["negotiation_bytes"] == comp.negotiation_nbytes(8) == 7
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]
    # a codec without a negotiation prices zero (the field is honest)
    assert C.TopKCompressor(0.1).negotiation_nbytes(8) == 0


# ---------------------------------------------------------------------------
# chaos: the homomorphic scenario end to end
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.hier
def test_chaos_smoke_hier_homo_scenario(tmp_path):
    """tools/chaos_smoke.py --hier --homo: a NaN poisoned into one rank's
    gradient must propagate through the negotiate pmax and the
    zero-requant integer summation to every rank, trip the guard
    fleet-wide, and the fallback/recovery matrix must survive over the
    two-level schedule with the homomorphic codec in place."""
    import importlib.util
    import json
    import os

    spec = importlib.util.spec_from_file_location(
        "chaos_smoke_homo_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "chaos_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    out = tmp_path / "homo_chaos.jsonl"
    rc = smoke.main(["--steps", "12", "--nan-prob", "1.0", "--batch", "16",
                     "--fallback-after", "2", "--fallback-steps", "4",
                     "--hier", "--slice-size", "4", "--homo",
                     "--telemetry-out", str(out), "--telemetry-every", "6"])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows and rows[0]["provenance"]["homo"] is True
    metric = [r for r in rows if "negotiation_bytes" in r]
    assert metric, "no per-step metric rows in the artifact"
    for r in metric:
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]
        # fallback windows bypass the negotiation (the dense branch never
        # negotiates) — the field must read zero exactly then.
        if r["fallback"]:
            assert r["negotiation_bytes"] == 0.0


# ---------------------------------------------------------------------------
# convergence floor: hier+homoqsgd4 matches exact summation (fp16)
# ---------------------------------------------------------------------------

@pytest.mark.hier
def test_hier_homoqsgd4_matches_fp16_convergence_floor(mesh):
    """ISSUE 13 target (ROADMAP item 5): hier with homomorphic qsgd4
    matches EXACT summation's convergence floor — fp16 over the identical
    two-level schedule is the exact-summation reference (payload-space
    float adds, zero requant), and the homomorphic integer path must land
    within noise of it on a real optimization trajectory."""
    rng = np.random.default_rng(7)
    w_true = rng.normal(size=(20, 4)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(64 * 8, 20)).astype(np.float32))
    y = jnp.asarray(np.argmax(np.asarray(x) @ w_true, axis=1)
                    .astype(np.int32))

    def loss_fn(params, batch):
        xb, yb = batch
        logits = xb @ params["w"]
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, yb).mean()

    def final_loss(compressor_params):
        grc = grace_from_params({**compressor_params,
                                 "communicator": "hier", "slice_size": 4,
                                 "fusion": "flat"})
        tx = optax.chain(grc.transform(seed=0), optax.sgd(0.3))
        params = {"w": jnp.zeros((20, 4), jnp.float32)}
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        loss = None
        for _ in range(60):
            state, loss = step(state, (x, y))
        return float(loss)

    exact = final_loss({"compressor": "fp16", "memory": "none"})
    homo = final_loss({"compressor": "homoqsgd", "quantum_num": 7,
                       "memory": "residual"})
    # the exact-summation reference must itself have converged (this
    # problem's 60-step deterministic plateau is ~0.39)...
    assert exact < 0.45, exact
    # ...and the zero-requant homomorphic path matches its floor (error
    # feedback absorbs the single stochastic encode).
    assert homo < exact + 0.05, (homo, exact)


def test_allreduce_homomorphic_psum_path(rng):
    """The third accumulation path exists on the flat Allreduce too: the
    psum of integer levels decodes once and divides after decode — exact
    on integer grads, no 'requires float payloads' TypeError."""
    comp = C.HomoQSGDCompressor(quantum_num=7)
    x = rng.integers(-7, 8, size=(W, 33)).astype(np.float32)
    out, _ = run_step(submesh(W), comm.Allreduce(), comp, NoneMemory(),
                      jnp.asarray(x))
    np.testing.assert_array_equal(out, x.mean(0))
