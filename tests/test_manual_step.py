"""Executable copy of IMPLEMENTING.md's "Manual per-parameter stepping".

The reference's dist backend drives compression through a hand-written
loop — ``grc.step(grad, name)`` per named parameter
(examples/dist/CIFAR10-dawndist/core.py:203-206). The doc section shows the
TPU-native equivalent (Communicator.step per leaf inside shard_map); this
test runs that exact code and checks the semantics the reference's loop
guarantees: the aggregated gradient is the cross-rank mean reconstruction
and the residual memory keeps what the codec dropped.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from grace_tpu import grace_from_params
from grace_tpu.parallel import data_parallel_mesh, shard_map

W = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < W:
        pytest.skip(f"needs {W} devices")
    return data_parallel_mesh(jax.devices()[:W])


def build_step(grc, mesh, lr=0.1):
    # --- verbatim from IMPLEMENTING.md "Manual per-parameter stepping" ---
    def device_step(params, grads, mem, rng):
        new_params, new_mem = {}, {}
        for i, name in enumerate(sorted(grads)):
            out, ms, _ = grc.communicator.step(
                grads[name][0], mem[name][0], None, grc.memory,
                grc.compressor, jax.random.fold_in(rng, i))
            new_mem[name] = ms[None]
            new_params[name] = params[name] - lr * out
        return new_params, new_mem

    return jax.jit(shard_map(
        device_step, mesh=mesh,
        in_specs=(P(), P("data"), P("data"), P()),
        out_specs=(P(), P("data")), check_vma=False))
    # ---------------------------------------------------------------------


def test_manual_step_none_is_cross_rank_mean(mesh):
    grc = grace_from_params({"compressor": "none", "memory": "none",
                             "communicator": "allgather"})
    step = build_step(grc, mesh)
    rng = np.random.default_rng(0)
    params = {"w": jnp.zeros((16,)), "b": jnp.zeros((4,))}
    grads = {"w": jnp.asarray(rng.normal(size=(W, 16)), jnp.float32),
             "b": jnp.asarray(rng.normal(size=(W, 4)), jnp.float32)}
    mem = {k: jnp.zeros_like(v) for k, v in grads.items()}
    new_params, _ = step(params, grads, mem, jax.random.key(0))
    for k in grads:
        np.testing.assert_allclose(
            np.asarray(new_params[k]),
            -0.1 * np.asarray(grads[k]).mean(0), rtol=1e-5, atol=1e-6)


def test_manual_step_topk_residual_identity(mesh):
    """Residual + decompressed == the original gradient, per rank — the
    error-feedback invariant of the reference's Memory.update."""
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.25,
                             "memory": "residual",
                             "communicator": "allgather"})
    step = build_step(grc, mesh)
    rng = np.random.default_rng(1)
    g = rng.normal(size=(W, 32)).astype(np.float32)
    params = {"w": jnp.zeros((32,))}
    mem = {"w": jnp.zeros_like(jnp.asarray(g))}
    _, new_mem = step(params, {"w": jnp.asarray(g)}, mem, jax.random.key(0))
    residual = np.asarray(new_mem["w"])          # (W, 32), rank-local
    recon = g - residual                         # what each rank transmitted
    kept = recon != 0
    np.testing.assert_allclose(recon[kept], g[kept], rtol=1e-6)
    assert 0 < kept.sum() <= W * 8               # k = 25% of 32
