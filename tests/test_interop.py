"""Interop tests: GraceBridge and the torch DistributedOptimizer.

Behavioral parity targets from the reference's patched Horovod optimizer
(patch_files/horovod/torch/__init__.py:46-250): named-parameter validation,
backward_passes_per_step accumulation, the double-backward assertion, the
zero_grad race guard, the skip_synchronize protocol, and — the actual point
— that gradients coming out of step() are the globally aggregated,
compressed-exchanged mean.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu import grace_from_params
from grace_tpu.interop import GraceBridge

torch = pytest.importorskip("torch")

from grace_tpu.interop.torch import (DistributedOptimizer,  # noqa: E402
                                     broadcast_optimizer_state,
                                     broadcast_parameters)


class TestGraceBridge:
    def test_none_allreduce_is_global_mean(self, mesh):
        grc = grace_from_params({"compressor": "none", "memory": "none",
                                 "communicator": "allreduce"})
        bridge = GraceBridge(grc, n=16, mesh=mesh)
        rng = np.random.default_rng(0)
        g = rng.standard_normal((8, 16)).astype(np.float32)
        out = np.asarray(bridge.exchange_global(g))
        np.testing.assert_allclose(out, g.mean(axis=0), rtol=1e-5)

    def test_topk_residual_state_accumulates(self, mesh):
        grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.25,
                                 "memory": "residual",
                                 "communicator": "allgather"})
        bridge = GraceBridge(grc, n=16, mesh=mesh)
        rng = np.random.default_rng(0)
        g = rng.standard_normal((8, 16)).astype(np.float32)
        np.asarray(bridge.exchange_global(g))
        mem = np.asarray(jax.tree_util.tree_leaves(bridge.state.mem)[0])
        assert mem.shape == (8, 16)          # per-rank residuals
        assert np.abs(mem).sum() > 0
        # rank residuals differ (distinct inputs -> distinct error feedback)
        assert not np.allclose(mem[0], mem[1])

    def test_local_exchange_roundtrip(self, mesh):
        """Single process: all ranks carry this process's grads; the mean of
        identical uncompressed payloads is the payload itself."""
        grc = grace_from_params({"compressor": "none", "memory": "none",
                                 "communicator": "allreduce"})
        bridge = GraceBridge(grc, n=8, mesh=mesh)
        g = np.arange(8, dtype=np.float32)
        out = np.asarray(bridge.exchange(g))
        np.testing.assert_allclose(out, g, rtol=1e-6)

    def test_shape_validation(self, mesh):
        grc = grace_from_params({"compressor": "none", "memory": "none",
                                 "communicator": "allreduce"})
        bridge = GraceBridge(grc, n=8, mesh=mesh)
        with pytest.raises(ValueError, match="flat gradients"):
            bridge.exchange(np.zeros(9, np.float32))
        with pytest.raises(ValueError, match="expected"):
            bridge.exchange_global(np.zeros((4, 8), np.float32))


def _toy_model():
    torch.manual_seed(0)
    return torch.nn.Sequential(torch.nn.Linear(10, 16), torch.nn.ReLU(),
                               torch.nn.Linear(16, 3))


def _make_opt(model, mesh, cfg=None, **kw):
    cfg = cfg or {"compressor": "none", "memory": "none",
                  "communicator": "allreduce"}
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    return DistributedOptimizer(opt, grace_from_params(cfg),
                                named_parameters=model.named_parameters(),
                                mesh=mesh, **kw)


class TestDistributedOptimizer:
    def test_step_applies_aggregated_grads(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh)
        x = torch.randn(8, 10)
        y = torch.randint(0, 3, (8,))
        before = [p.detach().clone() for p in model.parameters()]
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        after = list(model.parameters())
        assert any(not torch.equal(b, a.detach())
                   for b, a in zip(before, after))

    def test_training_converges(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh,
                        cfg={"compressor": "topk", "compress_ratio": 0.5,
                             "memory": "residual",
                             "communicator": "allgather"})
        torch.manual_seed(1)
        x = torch.randn(64, 10)
        y = (x.sum(dim=1) > 0).long() % 3
        first = None
        for _ in range(40):
            opt.zero_grad()
            loss = torch.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            if first is None:
                first = float(loss)
        assert float(loss) < first * 0.7, (first, float(loss))

    def test_duplicate_names_rejected(self, mesh):
        model = _toy_model()
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        named = [("same", p) for p in model.parameters()]
        with pytest.raises(ValueError, match="unique"):
            DistributedOptimizer(opt, grace_from_params(
                {"compressor": "none", "memory": "none",
                 "communicator": "allreduce"}),
                named_parameters=named, mesh=mesh)

    def test_unnamed_params_rejected(self, mesh):
        model = _toy_model()
        opt = torch.optim.SGD(model.parameters(), lr=0.1)
        named = list(model.named_parameters())[:-1]
        with pytest.raises(ValueError, match="not named"):
            DistributedOptimizer(opt, grace_from_params(
                {"compressor": "none", "memory": "none",
                 "communicator": "allreduce"}),
                named_parameters=named, mesh=mesh)

    def test_double_backward_asserts(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh)
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="backward_passes_per_step"):
            torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()   # drain so teardown is clean

    def test_backward_passes_per_step_accumulates(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh, backward_passes_per_step=2)
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        assert opt._pending is None       # not launched yet: 1 of 2 passes
        torch.nn.functional.cross_entropy(model(x), y).backward()
        assert opt._pending is not None   # second pass launched the exchange
        opt.step()

    def test_zero_grad_guard(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh)
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="race condition"):
            opt.zero_grad()
        opt.step()          # resolves the pending exchange
        opt.zero_grad()     # fine after step

    def test_skip_synchronize_protocol(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh)
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()      # must not warn / re-synchronize
        # step again without skip: warns about the double synchronize
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()
        with pytest.warns(UserWarning, match="skip_synchronize"):
            opt.step()

    def test_grads_equal_plain_sgd_with_none_compressor(self, mesh):
        """With no compression, DistributedOptimizer == plain local SGD
        (single process: the global mean of identical rows is the row)."""
        model_a, model_b = _toy_model(), _toy_model()
        model_b.load_state_dict(model_a.state_dict())
        opt_a = _make_opt(model_a, mesh)
        opt_b = torch.optim.SGD(model_b.parameters(), lr=0.1)
        x = torch.randn(8, 10)
        y = torch.randint(0, 3, (8,))
        for opt, model in ((opt_a, model_a), (opt_b, model_b)):
            loss = torch.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
        for pa, pb in zip(model_a.parameters(), model_b.parameters()):
            np.testing.assert_allclose(pa.detach().numpy(),
                                       pb.detach().numpy(), atol=1e-6)


class TestBroadcast:
    def test_broadcast_parameters_single_process_noop(self):
        model = _toy_model()
        before = {k: v.clone() for k, v in model.state_dict().items()}
        broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.equal(before[k], v)

    def test_broadcast_optimizer_state_preserves_types(self):
        model = _toy_model()
        opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
        # populate momentum buffers
        loss = model(torch.randn(4, 10)).sum()
        loss.backward()
        opt.step()
        sd_before = opt.state_dict()
        broadcast_optimizer_state(opt, root_rank=0)
        sd_after = opt.state_dict()
        g0b, g0a = sd_before["param_groups"][0], sd_after["param_groups"][0]
        assert type(g0a["lr"]) is type(g0b["lr"]) and g0a["lr"] == g0b["lr"]
        assert g0a["momentum"] == g0b["momentum"]
        for k in sd_before["state"]:
            for kk, v in sd_before["state"][k].items():
                if isinstance(v, torch.Tensor):
                    assert torch.equal(v, sd_after["state"][k][kk])


class TestBucketedExchange:
    """VERDICT round-3 weak item 5: per-bucket exchanges dispatched as
    backward fills them (overlap), replacing the single launch at the LAST
    gradient hook. Semantics must be unchanged by the bucket partition."""

    def _tiny_cap(self):
        # ~0.3 KiB: the toy model is ~0.9 KiB of f32, so this forces
        # multiple buckets (the 640 B first-layer weight gets its own)
        return 0.3 / 1024

    def test_multiple_buckets_formed(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh, bucket_cap_mb=self._tiny_cap())
        assert len(opt._buckets) > 1
        assert len(opt._bridges) == len(opt._buckets)
        # partition covers every trainable param exactly once
        ids = [id(p) for b in opt._buckets for p in b]
        assert sorted(ids) == sorted(id(p) for p in opt._grace_params)

    def test_buckets_launch_during_backward(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh, bucket_cap_mb=self._tiny_cap())
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        # every bucket dispatched by hooks, before synchronize/step
        assert all(p is not None for p in opt._pending_b)
        opt.step()

    def test_bucketed_grads_equal_plain_sgd(self, mesh):
        model_a, model_b = _toy_model(), _toy_model()
        model_b.load_state_dict(model_a.state_dict())
        opt_a = _make_opt(model_a, mesh, bucket_cap_mb=self._tiny_cap())
        opt_b = torch.optim.SGD(model_b.parameters(), lr=0.1)
        x = torch.randn(8, 10)
        y = torch.randint(0, 3, (8,))
        for opt, model in ((opt_a, model_a), (opt_b, model_b)):
            loss = torch.nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
        for pa, pb in zip(model_a.parameters(), model_b.parameters()):
            np.testing.assert_allclose(pa.detach().numpy(),
                                       pb.detach().numpy(), atol=1e-6)

    def test_grace_state_roundtrip_per_bucket(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh,
                        cfg={"compressor": "topk", "compress_ratio": 0.5,
                             "memory": "residual",
                             "communicator": "allgather"},
                        bucket_cap_mb=self._tiny_cap())
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.step()
        state = jax.device_get(opt.grace_state)
        assert isinstance(state, tuple) and len(state) == len(opt._buckets)
        opt.grace_state = state        # restore must round-trip
        with pytest.raises(ValueError, match="entries"):
            opt.grace_state = state[:1]

    def test_double_backward_asserts_with_buckets(self, mesh):
        model = _toy_model()
        opt = _make_opt(model, mesh, bucket_cap_mb=self._tiny_cap())
        x = torch.randn(4, 10)
        y = torch.randint(0, 3, (4,))
        torch.nn.functional.cross_entropy(model(x), y).backward()
        with pytest.raises(AssertionError, match="backward_passes_per_step"):
            torch.nn.functional.cross_entropy(model(x), y).backward()
        opt.synchronize()

    def test_set_backward_passes_rejects_inflight_grads(self, mesh):
        # Resetting counters mid-flight would let the next backward
        # overwrite pending exchanges (dropping their aggregates and
        # double-advancing residual state) — must refuse instead.
        model = _toy_model()
        opt = _make_opt(model, mesh)
        x = torch.randn(4, 10)
        torch.nn.functional.cross_entropy(
            model(x), torch.randint(0, 3, (4,))).backward()
        with pytest.raises(AssertionError, match="in flight"):
            opt.set_backward_passes_per_step(2)
        opt.synchronize()
        opt.set_backward_passes_per_step(2)   # fine once drained
