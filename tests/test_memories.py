"""Memory (error-feedback) semantics; reference cites in each class docstring."""

import jax
import jax.numpy as jnp
import numpy as np

from grace_tpu import compressors as C
from grace_tpu import memories as M

KEY = jax.random.key(0)


def test_none_memory_passthrough():
    mem = M.NoneMemory()
    x = jnp.asarray([1.0, 2.0])
    st = mem.init_state(x)
    out, st = mem.compensate(x, st)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert mem.update(out, (x,), None, C.NoneCompressor(), st) is st


def test_residual_accumulates(rng):
    """Error feedback: what top-k drops this step comes back next step."""
    mem = M.ResidualMemory()
    comp = C.TopKCompressor(compress_ratio=0.5)
    x = jnp.asarray([10.0, 1.0, -8.0, 0.5])
    st = mem.init_state(x)
    c, st = mem.compensate(x, st)
    payload, ctx, _ = comp.compress(c, None, KEY)
    st = mem.update(c, payload, ctx, comp, st)
    # top-2 sent {10, -8}; residual keeps {1.0, 0.5}
    np.testing.assert_allclose(np.asarray(st), [0.0, 1.0, 0.0, 0.5])
    # next step: dropped mass is compensated in
    y = jnp.asarray([0.0, 0.0, 0.0, 0.0])
    c2, _ = mem.compensate(y, st)
    np.testing.assert_allclose(np.asarray(c2), [0.0, 1.0, 0.0, 0.5])


def test_residual_beta_gamma():
    mem = M.ResidualMemory(beta=0.5, gamma=2.0)
    st = jnp.asarray([4.0])
    out, _ = mem.compensate(jnp.asarray([1.0]), st)
    np.testing.assert_allclose(np.asarray(out), [0.5 * 4.0 + 2.0 * 1.0])


def test_efsignsgd_memory_lr_scaling():
    mem = M.EFSignSGDMemory(lr=0.25)
    x = jnp.asarray([2.0, -2.0])
    st = mem.init_state(x)
    out, st = mem.compensate(x, st)
    np.testing.assert_allclose(np.asarray(out), [0.5, -0.5])


def test_dgc_memory_momentum_and_masking():
    mem = M.DgcMemory(momentum=0.5)
    comp = C.DgcCompressor(compress_ratio=0.5, sample_ratio=1.0)
    x = jnp.asarray([5.0, 0.1, -4.0, 0.2])
    st = mem.init_state(x)
    c, st = mem.compensate(x, st)
    np.testing.assert_allclose(np.asarray(c), np.asarray(x))  # first step: u = g, v = u
    payload, ctx, _ = comp.compress(c, None, KEY)
    st = mem.update(c, payload, ctx, comp, st)
    # transmitted coords are zeroed in both accumulators
    sent = np.asarray(comp.decompress(payload, ctx)) != 0
    assert np.all(np.asarray(st["residual"])[sent] == 0)
    assert np.all(np.asarray(st["gradient"])[sent] == 0)
    # non-transmitted coords retain accumulation
    assert np.all(np.asarray(st["gradient"])[~sent] != 0)


def test_powersgd_memory_1d_bypass():
    mem = M.PowerSGDMemory()
    x = jnp.asarray([1.0, 2.0])
    assert mem.init_state(x) is None
    out, st = mem.compensate(x, None)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    assert mem.update(out, (x,), None, C.NoneCompressor(), None) is None


def test_powersgd_memory_residual_2d(rng):
    mem = M.PowerSGDMemory()
    x = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))
    st = mem.init_state(x)
    out, _ = mem.compensate(x, st)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_residual_state_dtype_bf16(rng):
    """state_dtype='bfloat16' stores the residual narrow but computes the
    compensate in the gradient dtype; feedback still accumulates."""
    mem = M.ResidualMemory(state_dtype="bfloat16")
    comp = C.TopKCompressor(compress_ratio=0.5)
    x = jnp.asarray([10.0, 1.0, -8.0, 0.5], jnp.float32)
    st = mem.init_state(x)
    assert st.dtype == jnp.bfloat16
    c, st = mem.compensate(x, st)
    assert c.dtype == jnp.float32            # math in gradient dtype
    payload, ctx, _ = comp.compress(c, None, KEY)
    st = mem.update(c, payload, ctx, comp, st)
    assert st.dtype == jnp.bfloat16
    # bf16 holds these exactly: same residual as the f32 test
    np.testing.assert_allclose(np.asarray(st, np.float32),
                               [0.0, 1.0, 0.0, 0.5])
    c2, _ = mem.compensate(jnp.zeros(4, jnp.float32), st)
    assert c2.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(c2), [0.0, 1.0, 0.0, 0.5])


def test_residual_state_dtype_typo_fails_fast():
    import pytest
    with pytest.raises(TypeError):
        M.ResidualMemory(state_dtype="bfloat17")
