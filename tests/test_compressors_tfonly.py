"""Tests for the four tf-only reference algorithms (sketch/u8bit/adaq/inceptionn)."""

import jax
import jax.numpy as jnp
import numpy as np

from grace_tpu import compressors as C

KEY = jax.random.key(7)


def rand(shape, rng, scale=1.0):
    return jnp.asarray(rng.normal(size=shape).astype(np.float32) * scale)


def _roundtrip(comp, x, key=KEY):
    payload, ctx, _ = comp.compress(x, comp.init_state(x), key)
    return payload, ctx, comp.decompress(payload, ctx)


def test_sketch_bins_and_means(rng):
    x = rand((2000,), rng)
    comp = C.SketchCompressor(bins=64)
    payload, ctx, out = _roundtrip(comp, x)
    ids, means = payload
    assert ids.dtype == jnp.uint8
    assert means.shape == (64,)
    # each value decodes to the mean of its quantile bin: error bounded by
    # bin width; check rank correlation and overall closeness
    err = np.abs(np.asarray(out) - np.asarray(x))
    assert np.quantile(err, 0.95) < 0.2  # 64 quantile bins over N(0,1)


def test_sketch_uint16_for_many_bins(rng):
    comp = C.SketchCompressor(bins=512)
    payload, _, _ = _roundtrip(comp, rand((4096,), rng))
    assert payload[0].dtype == jnp.uint16


def test_u8bit_roundtrip(rng):
    x = rand((1000,), rng)
    comp = C.U8bitCompressor()
    payload, ctx, out = _roundtrip(comp, x)
    code, scale = payload
    assert code.dtype == jnp.int8
    out, x = np.asarray(out), np.asarray(x)
    # nonuniform 8-bit: relative error small for large entries
    big = np.abs(x) > 0.1 * np.abs(x).max()
    rel = np.abs(out[big] - x[big]) / np.abs(x[big])
    assert np.max(rel) < 0.15
    assert np.all(np.sign(out[big]) == np.sign(x[big]))


def test_u8bit_codebook_range():
    from grace_tpu.compressors.u8bit import _dynamic_tree_codebook
    book = _dynamic_tree_codebook()
    assert book.shape == (127,)
    assert np.all(np.diff(book) > 0)
    assert book[0] < 1e-5 and 0.9 < book[-1] <= 1.0


def test_adaq_half_means(rng):
    x = rand((5000,), rng)
    comp = C.AdaqCompressor(compress_ratio=0.05)
    payload, ctx, out = _roundtrip(comp, x)
    out, xs = np.asarray(out), np.asarray(x)
    pos_sent = out > 0
    neg_sent = out < 0
    assert pos_sent.sum() > 0 and neg_sent.sum() > 0
    # all transmitted positives share one value (the half mean); same for negatives
    assert np.unique(out[pos_sent]).size == 1
    assert np.unique(out[neg_sent]).size == 1
    # transmitted coords really are large-magnitude entries of matching sign
    assert np.all(xs[pos_sent] > 0) and np.all(xs[neg_sent] < 0)
    # selection is in the right ballpark of ratio·numel per half
    assert pos_sent.sum() < 0.15 * 5000 and neg_sent.sum() < 0.15 * 5000


def test_inceptionn_error_bound(rng):
    x = rand((4000,), rng, scale=0.05)
    comp = C.InceptionNCompressor(error_bound=1e-3)
    payload, ctx, out = _roundtrip(comp, x)
    v16, v32, idx = payload
    assert v16.dtype == jnp.uint16
    err = np.abs(np.asarray(out) - np.asarray(x))
    # dropped values are < 2^-10+eps; truncation error bounded by ulp at scale
    assert err.max() < 2e-3


def test_inceptionn_overflow_lane_exact(rng):
    x = jnp.asarray([3.5, -2.25, 0.001, 0.5, -0.125] + [0.01] * 27,
                    jnp.float32)
    comp = C.InceptionNCompressor(error_bound=1e-4, overflow_ratio=0.25)
    payload, ctx, out = _roundtrip(comp, x)
    out = np.asarray(out)
    # values >= 1.0 are exactly preserved via the fp32 lane
    np.testing.assert_array_equal(out[:2], [3.5, -2.25])
    # mid-range value within relative truncation error
    np.testing.assert_allclose(out[3], 0.5, rtol=1e-3)
    assert abs(out[4] - (-0.125)) / 0.125 < 1e-2


def test_inceptionn_overflow_clamps_when_capacity_exceeded():
    # 8 values >= 1 but capacity only 1 -> the rest clamp to ~1.0, sign kept
    x = jnp.asarray([4.0, -3.0, 2.0, 1.5, 1.25, 1.1, 1.05, 1.01],
                    jnp.float32)
    comp = C.InceptionNCompressor(error_bound=1e-4, overflow_ratio=0.125)
    _, _, out = _roundtrip(comp, x)
    out = np.asarray(out)
    np.testing.assert_allclose(out[0], 4.0)       # top-1 exact
    np.testing.assert_allclose(out[1], -1.0, rtol=1e-3)  # clamped, sign kept
    assert np.all(np.abs(out[2:]) <= 1.0)
