"""Resilience: in-graph non-finite guard, dense fallback, chaos, rollback.

The properties pinned here are the acceptance criteria of the resilience
subsystem (ISSUE 1): atomic in-graph skip (params AND every GraceState
mem/comp leaf bitwise-unchanged across a poisoned step, on all ranks),
zero overhead when healthy (bit-identity with the unguarded run), the
K-consecutive→M-step dense fallback window, and kill-and-resume via
``restore_last_good`` reproducing the uninterrupted trajectory.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.checkpoint import Checkpointer, divergence_rollback
from grace_tpu.resilience import (ChaosCommunicator, ChaosCompressor,
                                  guard_transform, guarded_chain)
from grace_tpu.resilience.chaos import _flip_one_bit, _implant
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.utils.logging import GuardMonitor
from grace_tpu.utils.metrics import guard_report

BATCH, DIM, CLASSES = 64, 20, 4

TOPK_EF = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": "allgather",
           "escape": "fp16"}


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, grace_params=TOPK_EF, lr=0.3, chaos=None, **guard_kw):
    grc = grace_from_params(dict(grace_params))
    if chaos is not None:
        grc = dataclasses.replace(
            grc, communicator=chaos(grc.communicator))
    tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    return state, step


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


def _grace_of(state):
    return state.opt_state.inner[0]   # guard(chain(grace, sgd)) layout


# ---------------------------------------------------------------------------
# guard: atomic skip
# ---------------------------------------------------------------------------

def test_single_rank_nan_skips_step_atomically(mesh):
    """NaN in ONE rank's local gradient (only rank 0's batch shard is
    poisoned) → post-exchange updates are NaN on all ranks → the step is
    skipped atomically: params and every mem/comp leaf (the state arrays
    span all ranks via the world axis) stay bitwise-identical."""
    x, y = _problem()
    state, step = _build(mesh)
    for _ in range(3):
        state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    before = state

    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan          # rows 0..63 = rank 0's shard only
    state, _ = step(state, (jnp.asarray(xbad), y))

    rep = guard_report(state)
    assert rep["notfinite_count"] == 1
    assert rep["last_bad_step"] == 3
    assert _leaves_equal(before.params, state.params)
    g0, g1 = _grace_of(before), _grace_of(state)
    assert _leaves_equal(g0.mem, g1.mem)
    assert _leaves_equal(g0.comp, g1.comp)
    assert _leaves_equal(g0.count, g1.count)

    # clean data → training resumes from the unpoisoned state
    state, loss = step(state, (x, y))
    assert np.isfinite(float(loss))
    assert not _leaves_equal(before.params, state.params)
    assert guard_report(state)["notfinite_count"] == 1


def test_guard_zero_overhead_when_healthy(mesh):
    """Uninjected runs — plain, escape-armed, and fully guarded — must be
    BIT-identical: jnp.where(False, old, new) and the untaken cond branch
    may not perturb a single value."""
    x, y = _problem()

    def run(grace_params, guard):
        grc = grace_from_params(dict(grace_params))
        if guard:
            tx = guarded_chain(grc, optax.sgd(0.3),
                               fallback_after=3, fallback_steps=4)
        else:
            tx = optax.chain(grc.transform(seed=0), optax.sgd(0.3))
        state = init_train_state(_init_params(), tx, mesh)
        step = make_train_step(_loss_fn, tx, mesh, donate=False)
        for _ in range(6):
            state, loss = step(state, (x, y))
        return state.params, float(loss)

    plain = dict(TOPK_EF)
    plain.pop("escape")
    p0, l0 = run(plain, guard=False)     # no escape, no guard
    p1, l1 = run(TOPK_EF, guard=False)   # escape cond present, flag False
    p2, l2 = run(TOPK_EF, guard=True)    # full guard
    assert l0 == l1 == l2
    assert _leaves_equal(p0, p1)
    assert _leaves_equal(p1, p2)


def test_guard_max_norm_bound():
    """Norm-explosion guard, single device (no mesh axis bound)."""
    tx = guard_transform(optax.sgd(1.0), max_norm=1.0, axis_name=None)
    params = {"w": jnp.ones((4,))}
    st = tx.init(params)
    upd, st = tx.update({"w": jnp.full((4,), 100.0)}, st, params)
    assert int(st.notfinite_count) == 1
    assert float(jnp.abs(upd["w"]).max()) == 0.0
    upd, st = tx.update({"w": jnp.full((4,), 0.01)}, st, params)
    assert int(st.notfinite_count) == 1
    np.testing.assert_allclose(np.asarray(upd["w"]), -0.01, rtol=1e-6)


# ---------------------------------------------------------------------------
# chaos injectors
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_single_rank_nan_freezes_state(mesh):
    """Chaos NaN on exactly one mesh index, every step: every step skips,
    nothing (params, mem, comp) moves, on any rank."""
    x, y = _problem()
    state, step = _build(
        mesh, chaos=lambda inner: ChaosCommunicator(
            inner=inner, nan_prob=1.0, rank=3, seed=7))
    init_state = state
    for i in range(5):
        state, _ = step(state, (x, y))
    rep = guard_report(state)
    assert rep["notfinite_count"] == 5
    assert rep["consecutive"] == 5
    assert _leaves_equal(init_state.params, state.params)
    g0, g1 = _grace_of(init_state), _grace_of(state)
    assert _leaves_equal(g0.mem, g1.mem)
    assert _leaves_equal(g0.comp, g1.comp)


@pytest.mark.chaos
def test_fallback_window_engages_and_rearms(mesh):
    """K=3 consecutive bad steps → dense escape hatch for exactly M=4
    steps (health flag set, training progresses because the dense path
    bypasses the compressed pipeline the fault lives in) → compression
    re-arms → faults bite again."""
    K, M = 3, 4
    x, y = _problem()
    state, step = _build(
        mesh, chaos=lambda inner: ChaosCommunicator(
            inner=inner, nan_prob=1.0, rank=0, seed=7),
        fallback_after=K, fallback_steps=M)

    flags, losses, nf = [], [], []
    for i in range(16):
        state, loss = step(state, (x, y))
        rep = guard_report(state)
        flags.append(bool(np.asarray(_grace_of(state).fallback)))
        losses.append(float(loss))
        nf.append(rep["notfinite_count"])

    # Steps 0..K-1 bad; trip at the end of step K-1 arms the flag for the
    # next M steps; at the end of the window the flag drops and the
    # compressed (faulted) pipeline trips again exactly K steps later.
    assert nf[:K] == list(range(1, K + 1))
    assert flags[:K] == [False] * (K - 1) + [True]
    assert flags[K - 1:K - 1 + M] == [True] * M          # exactly M dense
    assert flags[K - 1 + M] is False                     # re-armed
    assert nf[K - 1 + M - 1] == K                        # no skips in window
    assert nf[2 * K + M - 1] == 2 * K                    # second trip
    # dense window made real progress, and the run stays finite throughout
    assert losses[K + M] < losses[K]
    assert all(np.isfinite(l) for l in losses[2:])


@pytest.mark.chaos
def test_chaos_is_deterministic(mesh):
    """Same chaos seed → bit-identical fault pattern and trajectory."""
    x, y = _problem()

    def run(seed):
        state, step = _build(
            mesh, chaos=lambda inner: ChaosCommunicator(
                inner=inner, nan_prob=0.25, rank=2, seed=seed))
        losses = []
        for _ in range(8):
            state, loss = step(state, (x, y))
            losses.append(float(loss))
        return losses, guard_report(state)["notfinite_count"]

    la, ca = run(12)
    lb, cb = run(12)
    assert ca == cb
    assert la == lb           # float-exact: same faults, same math
    assert 0 < ca < 8         # this seed hits some steps, misses others


def test_implant_and_bitflip_primitives():
    key = jax.random.key(0)
    x = jnp.zeros((13,), jnp.float32)
    nanned = _implant(x, key, jnp.nan)
    assert int(jnp.isnan(nanned).sum()) == 1

    t = jax.random.normal(jax.random.key(1), (64,), jnp.float32)
    flipped = _flip_one_bit(t, key)
    a = np.asarray(jax.lax.bitcast_convert_type(t, jnp.uint32))
    b = np.asarray(jax.lax.bitcast_convert_type(flipped, jnp.uint32))
    xor = a ^ b
    assert (xor != 0).sum() == 1                      # one element touched
    assert bin(int(xor[xor != 0][0])).count("1") == 1  # by exactly one bit


@pytest.mark.chaos
def test_stale_residual_fault(mesh):
    """stale_prob=1 suppresses the residual update: memory replays last
    step's state instead of accumulating this step's compression error."""
    from jax.sharding import PartitionSpec as P

    from grace_tpu.comm import Allgather
    from grace_tpu.compressors import TopKCompressor
    from grace_tpu.memories import ResidualMemory
    from grace_tpu.parallel import shard_map

    comp = TopKCompressor(compress_ratio=0.25)
    memory = ResidualMemory()
    clean = Allgather()
    stale = ChaosCommunicator(inner=Allgather(), stale_prob=1.0, seed=3)

    g = jnp.asarray(np.linspace(-1, 1, 8 * 16, dtype=np.float32)
                    .reshape(8, 16))

    def body(comm, gg):
        gg = gg[0]
        out, mem, _ = comm.step(gg, memory.init_state(gg),
                                comp.init_state(gg), memory, comp,
                                jax.random.key(0))
        return out[None], mem[None]

    def run(comm):
        fn = shard_map(lambda gg: body(comm, gg), mesh=mesh,
                       in_specs=P("data"), out_specs=(P("data"), P("data")),
                       check_vma=False)
        return fn(g)

    out_clean, mem_clean = run(clean)
    out_stale, mem_stale = run(stale)
    # the exchange itself is untouched...
    np.testing.assert_array_equal(np.asarray(out_clean),
                                  np.asarray(out_stale))
    # ...but the stale run kept the initial (zero) residual
    assert float(jnp.abs(mem_clean).sum()) > 0
    assert float(jnp.abs(mem_stale).sum()) == 0


@pytest.mark.chaos
def test_chaos_compressor_payload_bitflip(mesh):
    """Payload bit-flips corrupt the wire but not the codec semantics: the
    decompressed aggregate differs from the clean run while the clean
    pipeline (bitflip_prob=0) is bit-identical to the unwrapped one."""
    from jax.sharding import PartitionSpec as P

    from grace_tpu.comm import Allgather
    from grace_tpu.compressors import NoneCompressor
    from grace_tpu.memories import NoneMemory
    from grace_tpu.parallel import shard_map

    memory = NoneMemory()
    g = jnp.asarray(np.linspace(-1, 1, 8 * 32, dtype=np.float32)
                    .reshape(8, 32))

    def run(comp):
        def body(gg):
            gg = gg[0]
            out, _, _ = Allgather().step(gg, None, None, memory, comp,
                                         jax.random.key(5))
            return out[None]

        fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"), check_vma=False)
        return np.asarray(fn(g))

    base = run(NoneCompressor())
    wrapped_clean = run(ChaosCompressor(inner=NoneCompressor()))
    flipped = run(ChaosCompressor(inner=NoneCompressor(),
                                  bitflip_prob=1.0, seed=9))
    np.testing.assert_array_equal(base, wrapped_clean)
    assert not np.array_equal(base, flipped)


# ---------------------------------------------------------------------------
# kill-and-resume / divergence rollback
# ---------------------------------------------------------------------------

def test_kill_and_resume_matches_uninterrupted(mesh, tmp_path):
    """Crash after step 6, restore_last_good, replay — per-leaf identical
    to the run that never died (residual state is part of the checkpoint,
    so the trajectories coincide exactly)."""
    x, y = _problem()
    state, step = _build(mesh)

    with Checkpointer(tmp_path / "ck", max_to_keep=None) as ckpt:
        for i in range(6):
            state, loss = step(state, (x, y))
            rep = guard_report(state)
            ckpt.save(i, state, force=True,
                      good=np.isfinite(float(loss))
                      and rep["consecutive"] == 0)
        ckpt.wait()
        assert ckpt.last_good_step() == 5

        cont = state
        cont_losses = []
        for i in range(4):
            cont, loss = step(cont, (x, y))
            cont_losses.append(float(loss))

        resumed = ckpt.restore_last_good(state)
        res_losses = []
        for i in range(4):
            resumed, loss = step(resumed, (x, y))
            res_losses.append(float(loss))

    assert res_losses == cont_losses
    for a, b in zip(jax.tree_util.tree_leaves(cont.params),
                    jax.tree_util.tree_leaves(resumed.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)


def test_divergence_rollback_skips_data_window(mesh, tmp_path):
    x, y = _problem()
    state, step = _build(mesh)
    with Checkpointer(tmp_path / "dr", max_to_keep=None) as ckpt:
        snapshots = {}
        for i in range(4):
            state, loss = step(state, (x, y))
            ckpt.save(i, state, force=True, good=(i <= 2))
            snapshots[i] = state
        ckpt.wait()
        restored, good_step, resume_at = divergence_rollback(
            ckpt, state, failed_step=7, skip_window=3)
    assert good_step == 2
    assert resume_at == 10
    assert _leaves_equal(snapshots[2], restored)


def test_guard_report_and_monitor(mesh):
    x, y = _problem()
    state, step = _build(mesh, fallback_after=2, fallback_steps=2)
    assert guard_report({"not": "a guard state"}) == {}

    lines = []
    mon = GuardMonitor(printer=lambda *a: lines.append(" ".join(map(str, a))))
    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan
    batches = [x, xbad, xbad, x, x, x]   # 2 consecutive bad → trip (K=2)
    for i, xb in enumerate(batches):
        state, _ = step(state, (jnp.asarray(xb), y))
        mon.update(i, guard_report(state))
    rep = guard_report(state)
    assert rep["notfinite_count"] == 2
    assert not rep["fallback_active"]    # window (M=2) opened and closed
    assert any("skipped" in l for l in lines)
    assert any("fallback engaged" in l for l in lines)
    assert any("re-armed" in l for l in lines)


# ---------------------------------------------------------------------------
# warmup boundary (regression pin)
# ---------------------------------------------------------------------------

def test_warmup_boundary_handoff():
    """count == warmup_steps must hand off to after(0), not the warm ramp."""
    from grace_tpu.train import warmup_schedule

    marker = 0.123
    sched = warmup_schedule(0.1, 8, warmup_steps=5,
                            after=lambda t: marker + 0.01 * t)
    np.testing.assert_allclose(float(sched(5)), marker, rtol=1e-6)
    np.testing.assert_allclose(float(sched(7)), marker + 0.02, rtol=1e-6)
    # ramp: base at 0, base + (scaled-base) * 4/5 one step before the end
    np.testing.assert_allclose(float(sched(0)), 0.1, rtol=1e-6)
    np.testing.assert_allclose(float(sched(4)), 0.1 + 0.7 * 4 / 5, rtol=1e-6)
    # degenerate warmup: scaled (or after(count)) from step 0
    np.testing.assert_allclose(float(warmup_schedule(0.1, 8, 0)(0)), 0.8,
                               rtol=1e-6)
    np.testing.assert_allclose(
        float(warmup_schedule(0.1, 8, 0, after=lambda t: marker + 1.0 * t)(2)),
        marker + 2.0, rtol=1e-6)


# ---------------------------------------------------------------------------
# long soak (slow, excluded from tier-1)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_soak_low_rate_injection_converges(mesh):
    """1.5% per-(step,leaf) NaN injection over 120 steps: the guard keeps
    the run finite and training still makes progress."""
    x, y = _problem()
    state, step = _build(
        mesh, chaos=lambda inner: ChaosCommunicator(
            inner=inner, nan_prob=0.015, rank=1, seed=13),
        fallback_after=3, fallback_steps=8)
    first = None
    for _ in range(120):
        state, loss = step(state, (x, y))
        if first is None:
            first = float(loss)
    rep = guard_report(state)
    assert np.isfinite(float(loss))
    assert float(loss) < first
    assert rep["notfinite_count"] >= 1
