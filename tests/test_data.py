"""Native data pipeline tests: C++ loader vs the Python reference contract.

Covers the DistributedSampler-equivalent guarantees (deterministic epoch
permutation from (seed, epoch); ranks partition each epoch disjointly),
normalization correctness, prefetch-queue integrity under threading, and
the file-format readers (MNIST idx written on the fly).
"""

import gzip
import os
import struct
import subprocess

import numpy as np
import pytest

from grace_tpu.data import (MemoryDataset, NativeLoader, PythonLoader,
                            make_loader, mnist_dataset, native_library_path)

NATIVE = native_library_path()


def _build_native():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run(["make", "-C", os.path.join(root, "native")], check=True,
                   capture_output=True)


if NATIVE is None:
    try:
        _build_native()
        NATIVE = native_library_path()
    except Exception:
        NATIVE = None

needs_native = pytest.mark.skipif(NATIVE is None,
                                  reason="native library not built")


def _dataset(n=100, h=8, w=8, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return MemoryDataset(
        images=rng.integers(0, 256, (n, h, w, c), dtype=np.uint8),
        labels=rng.integers(0, 10, n).astype(np.int32),
        mean=(0.5, 0.4, 0.3), std=(0.2, 0.2, 0.2))


def _collect(loader, epoch):
    xs, ys = zip(*list(loader.epoch(epoch)))
    return np.concatenate(xs), np.concatenate(ys)


class TestPythonLoader:
    def test_batches_and_shapes(self):
        ld = PythonLoader(_dataset(), batch_size=16, seed=1)
        batches = list(ld.epoch(0))
        assert len(batches) == 100 // 16
        x, y = batches[0]
        assert x.shape == (16, 8, 8, 3) and x.dtype == np.float32
        assert y.shape == (16,) and y.dtype == np.int32

    def test_deterministic_and_epoch_varying(self):
        ld = PythonLoader(_dataset(), batch_size=16, seed=1)
        x0, y0 = _collect(ld, 0)
        x0b, y0b = _collect(ld, 0)
        np.testing.assert_array_equal(y0, y0b)
        _, y1 = _collect(ld, 1)
        assert not np.array_equal(y0, y1)

    def test_rank_sharding_disjoint(self):
        ds = _dataset(n=96)
        seen = []
        for rank in range(4):
            ld = PythonLoader(ds, batch_size=8, seed=3, rank=rank, world=4,
                              shuffle=True)
            _, y = _collect(ld, 0)
            assert len(y) == 24
            seen.append(y)
        # Together the ranks consume each epoch exactly once.
        all_labels = np.sort(np.concatenate(seen))
        np.testing.assert_array_equal(all_labels, np.sort(ds.labels))

    def test_normalization(self):
        ds = _dataset()
        ld = PythonLoader(ds, batch_size=10, shuffle=False, seed=0)
        x, y = next(iter(ld.epoch(0)))
        expect = (ds.images[:10].astype(np.float32)
                  - np.array(ds.mean) * 255) / (np.array(ds.std) * 255)
        np.testing.assert_allclose(x, expect, rtol=1e-5, atol=1e-6)


@needs_native
class TestNativeLoader:
    def test_matches_python_contract(self):
        """Same guarantees, not bit-identical order (different RNG)."""
        ds = _dataset(n=128)
        ld = NativeLoader(ds, batch_size=16, seed=5)
        x, y = _collect(ld, 0)
        assert x.shape == (128, 8, 8, 3)
        # a permutation of the dataset
        np.testing.assert_array_equal(np.sort(y), np.sort(ds.labels))
        ld.close()

    def test_deterministic_per_seed_epoch(self):
        ds = _dataset(n=64)
        a = NativeLoader(ds, batch_size=8, seed=7)
        b = NativeLoader(ds, batch_size=8, seed=7)
        _, ya = _collect(a, 3)
        _, yb = _collect(b, 3)
        np.testing.assert_array_equal(ya, yb)
        _, yc = _collect(a, 4)
        assert not np.array_equal(ya, yc)
        a.close(), b.close()

    def test_normalization_matches_python(self):
        ds = _dataset(n=32)
        nat = NativeLoader(ds, batch_size=8, shuffle=False, seed=0)
        py = PythonLoader(ds, batch_size=8, shuffle=False, seed=0)
        (xn, yn), (xp, yp) = next(iter(nat.epoch(0))), next(iter(py.epoch(0)))
        np.testing.assert_array_equal(yn, yp)
        np.testing.assert_allclose(xn, xp, rtol=1e-5, atol=1e-6)
        nat.close()

    def test_rank_sharding_disjoint(self):
        ds = _dataset(n=96)
        seen = []
        for rank in range(4):
            ld = NativeLoader(ds, batch_size=8, seed=3, rank=rank, world=4)
            _, y = _collect(ld, 0)
            seen.append(y)
            ld.close()
        np.testing.assert_array_equal(np.sort(np.concatenate(seen)),
                                      np.sort(ds.labels))

    def test_threaded_queue_integrity(self):
        """Many threads, small queue: batches must still arrive in order
        with every sample exactly once."""
        ds = _dataset(n=1024, h=4, w=4, c=1)
        ld = NativeLoader(MemoryDataset(ds.images, np.arange(1024,
                                                            dtype=np.int32)),
                          batch_size=32, seed=9, n_threads=8, queue_depth=3)
        for epoch in range(3):
            _, y = _collect(ld, epoch)
            np.testing.assert_array_equal(np.sort(y), np.arange(1024))
        ld.close()

    def test_short_final_batch_wraps(self):
        ds = _dataset(n=20)
        ld = NativeLoader(ds, batch_size=8, drop_last=False, shuffle=False,
                          seed=0)
        batches = list(ld.epoch(0))
        assert len(batches) == 3
        assert batches[-1][0].shape == (8, 8, 8, 3)
        ld.close()

    def test_mnist_idx_reader(self, tmp_path):
        """Write idx files, read through the NATIVE file loader via ctypes."""
        import ctypes
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (10, 28, 28), dtype=np.uint8)
        labels = rng.integers(0, 10, 10, dtype=np.uint8)
        with gzip.open(tmp_path / "train-images-idx3-ubyte.gz", "wb") as f:
            f.write(struct.pack(">IIII", 2051, 10, 28, 28))
            f.write(imgs.tobytes())
        with open(tmp_path / "train-labels-idx1-ubyte", "wb") as f:
            f.write(struct.pack(">II", 2049, 10))
            f.write(labels.tobytes())

        lib = ctypes.CDLL(NATIVE)
        lib.gl_open.restype = ctypes.c_void_p
        lib.gl_open.argtypes = [ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                                ctypes.c_int64, ctypes.c_int, ctypes.c_int,
                                ctypes.c_uint64, ctypes.c_int64,
                                ctypes.c_int64]
        lib.gl_start_epoch.restype = ctypes.c_int64
        lib.gl_start_epoch.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                       ctypes.c_int64, ctypes.c_int64]
        lib.gl_next.restype = ctypes.c_int
        lib.gl_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_void_p]
        lib.gl_close.argtypes = [ctypes.c_void_p]
        h = lib.gl_open(0, str(tmp_path).encode(), 1, 5, 0, 1, 0, 0, 1)
        assert h
        nb = lib.gl_start_epoch(h, 0, 2, 2)
        assert nb == 2
        x = np.empty((5, 28, 28, 1), np.float32)
        y = np.empty((5,), np.int32)
        assert lib.gl_next(h, x.ctypes.data_as(ctypes.c_void_p),
                           y.ctypes.data_as(ctypes.c_void_p)) == 1
        np.testing.assert_array_equal(y, labels[:5].astype(np.int32))
        expect = (imgs[:5, :, :, None].astype(np.float32)
                  - 0.1307 * 255) / (0.3081 * 255)
        np.testing.assert_allclose(x, expect, rtol=1e-5, atol=1e-6)
        lib.gl_close(h)

    def test_make_loader_prefers_native(self):
        ld = make_loader(_dataset(n=16), batch_size=8)
        assert isinstance(ld, NativeLoader)


class TestDatasetValidation:
    def test_bad_dtype_rejected(self):
        with pytest.raises(ValueError, match="uint8"):
            MemoryDataset(np.zeros((4, 2, 2, 1), np.float32),
                          np.zeros(4, np.int32))

    def test_length_mismatch_rejected(self):
        with pytest.raises(ValueError, match="mismatch"):
            MemoryDataset(np.zeros((4, 2, 2, 1), np.uint8),
                          np.zeros(3, np.int32))

    def test_mnist_dataset_roundtrip(self, tmp_path):
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (6, 28, 28), dtype=np.uint8)
        labels = rng.integers(0, 10, 6, dtype=np.uint8)
        with open(tmp_path / "t10k-images-idx3-ubyte", "wb") as f:
            f.write(struct.pack(">IIII", 2051, 6, 28, 28))
            f.write(imgs.tobytes())
        with open(tmp_path / "t10k-labels-idx1-ubyte", "wb") as f:
            f.write(struct.pack(">II", 2049, 6))
            f.write(labels.tobytes())
        ds = mnist_dataset(str(tmp_path), train=False)
        assert ds.images.shape == (6, 28, 28, 1)
        np.testing.assert_array_equal(ds.labels, labels.astype(np.int32))


class TestPrefetchToDevice:
    def test_order_values_and_sharding(self):
        import jax
        from grace_tpu.data import prefetch_to_device
        from grace_tpu.parallel import batch_sharded, data_parallel_mesh

        mesh = data_parallel_mesh()
        n_dev = len(jax.devices())
        batches = [(np.full((2 * n_dev, 3), i, np.float32),
                    np.arange(2 * n_dev, dtype=np.int32) + i)
                   for i in range(5)]
        out = list(prefetch_to_device(iter(batches), mesh=mesh, size=2))
        assert len(out) == 5
        want = batch_sharded(mesh)
        for i, (x, y) in enumerate(out):
            assert x.sharding.is_equivalent_to(want, x.ndim)
            np.testing.assert_array_equal(np.asarray(x), batches[i][0])
            np.testing.assert_array_equal(np.asarray(y), batches[i][1])

    def test_short_and_empty_iterators(self):
        from grace_tpu.data import prefetch_to_device
        from grace_tpu.parallel import data_parallel_mesh
        import jax
        mesh = data_parallel_mesh()
        n = len(jax.devices())
        one = [(np.zeros((n, 1), np.float32),)]
        assert len(list(prefetch_to_device(iter(one), mesh=mesh,
                                           size=4))) == 1
        assert list(prefetch_to_device(iter([]), mesh=mesh)) == []

    def test_requires_mesh_or_sharding(self):
        import pytest
        from grace_tpu.data import prefetch_to_device
        with pytest.raises(ValueError):
            list(prefetch_to_device(iter([])))
