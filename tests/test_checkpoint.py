"""Checkpoint round-trip tests — including compression (GraceState) state.

The key property the reference lacks (SURVEY.md §5): residual/error-feedback
state survives save/restore bit-exactly, so a resumed run continues the same
trajectory as an uninterrupted one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.checkpoint import (Checkpointer, latest_step,
                                  restore_checkpoint, save_checkpoint)
from grace_tpu.train import init_train_state, make_train_step


def _setup(mesh):
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.1,
                             "memory": "residual",
                             "communicator": "allgather"})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(1e-2))
    params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
    state = init_train_state(params, tx, mesh)

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    step = make_train_step(loss_fn, tx, mesh, donate=False)
    rng = np.random.default_rng(0)
    batch = (jnp.asarray(rng.standard_normal((32, 16)), jnp.float32),
             jnp.asarray(rng.standard_normal((32, 4)), jnp.float32))
    return state, step, batch


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestCheckpointRoundTrip:
    def test_full_state_roundtrip(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(3):
            state, loss = step(state, batch)
        save_checkpoint(tmp_path / "ckpt", state, step=3)
        restored = restore_checkpoint(tmp_path / "ckpt", state)
        _assert_trees_equal(state, restored)

    def test_resume_matches_uninterrupted(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(2):
            state, _ = step(state, batch)
        save_checkpoint(tmp_path / "c", state, step=2)

        # uninterrupted: 3 more steps
        cont = state
        for _ in range(3):
            cont, _ = step(cont, batch)

        # resumed: restore then 3 more steps
        resumed = restore_checkpoint(tmp_path / "c", state)
        for _ in range(3):
            resumed, _ = step(resumed, batch)
        _assert_trees_equal(cont, resumed)

    def test_grace_residual_state_is_saved(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(2):
            state, _ = step(state, batch)
        grace_state = state.opt_state[0]
        # residual memory holds nonzero error feedback after topk steps
        assert any(float(jnp.abs(m).sum()) > 0 for m in grace_state.mem)
        save_checkpoint(tmp_path / "c", state, step=2)
        restored = restore_checkpoint(tmp_path / "c", state)
        _assert_trees_equal(grace_state, restored.opt_state[0])

    def test_bridge_state_roundtrip(self, mesh, tmp_path):
        """Interop frontends (torch/TF) checkpoint their compression state
        via GraceBridge.state — resume must be bit-faithful including each
        rank's residual, something the reference never persisted."""
        from grace_tpu import grace_from_params
        from grace_tpu.interop.bridge import GraceBridge

        grace = grace_from_params({"compressor": "topk",
                                   "compress_ratio": 0.25,
                                   "memory": "residual",
                                   "communicator": "allgather"})
        bridge = GraceBridge(grace, n=64, mesh=mesh)
        g = np.linspace(-1, 1, 64).astype(np.float32)
        np.asarray(bridge.exchange(g))
        save_checkpoint(tmp_path / "b", bridge.state, step=1)

        cont = np.asarray(bridge.exchange(g))

        bridge2 = GraceBridge(grace, n=64, mesh=mesh)
        bridge2.state = restore_checkpoint(tmp_path / "b", bridge2.state)
        resumed = np.asarray(bridge2.exchange(g))
        np.testing.assert_array_equal(cont, resumed)

    def test_manager_keep_and_latest(self, tmp_path):
        tree = {"x": jnp.arange(4.0)}
        with Checkpointer(tmp_path / "m", max_to_keep=2) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, tree, force=True)
            ckpt.wait()
            assert ckpt.latest_step() == 3
            assert len(list(ckpt.all_steps())) <= 2  # retention enforced
        assert latest_step(tmp_path / "m") == 3

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(tmp_path / "nothing", {"x": jnp.zeros(2)})


class TestStructureMismatch:
    """Resume-after-config-change must fail with a named leaf path, not a
    raw orbax traceback (ISSUE 1 satellite)."""

    def test_extra_target_leaf_named(self, tmp_path):
        state = {"params": {"w": jnp.ones((4, 2)), "b": jnp.zeros(2)}}
        save_checkpoint(tmp_path / "c", state, step=1)
        bad_target = {"params": {"w": jnp.ones((4, 2)), "b": jnp.zeros(2),
                                 "momentum": jnp.zeros(2)}}
        with pytest.raises(ValueError, match="params/momentum"):
            restore_checkpoint(tmp_path / "c", bad_target)

    def test_missing_target_leaf_named(self, tmp_path):
        state = {"params": {"w": jnp.ones((4, 2))}, "extra": jnp.zeros(3)}
        save_checkpoint(tmp_path / "c", state, step=1)
        with pytest.raises(ValueError, match="extra"):
            restore_checkpoint(tmp_path / "c",
                               {"params": {"w": jnp.ones((4, 2))}})

    def test_train_state_optimizer_change_named(self, mesh, tmp_path):
        """The real-world case: checkpoint written with sgd, restored into
        an adam-shaped state — error names a grace/optimizer leaf."""
        import optax

        from grace_tpu.train import init_train_state

        grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.1,
                                 "memory": "residual",
                                 "communicator": "allgather"})
        params = {"w": jnp.ones((16, 4))}
        sgd_state = init_train_state(
            params, optax.chain(grc.transform(), optax.sgd(1e-2)), mesh)
        adam_state = init_train_state(
            params, optax.chain(grc.transform(), optax.adam(1e-2)), mesh)
        save_checkpoint(tmp_path / "c", sgd_state, step=1)
        with pytest.raises(ValueError, match="structure mismatch|restore"):
            restore_checkpoint(tmp_path / "c", adam_state)


class TestLeafMismatch:
    """Same tree structure, different leaf shapes/dtypes must name the
    offending leaf — and recognize the elastic world-resize signature
    (same trailing dims, different leading axis) with a dedicated
    WorldSizeMismatch hint (ISSUE 11 satellite)."""

    def test_world_resize_raises_worldsize_mismatch(self, tmp_path):
        from grace_tpu.checkpoint import WorldSizeMismatch

        state = {"opt": {"mem": jnp.zeros((8, 16, 4))},
                 "w": jnp.ones((16, 4))}
        save_checkpoint(tmp_path / "c", state, step=1)
        target = {"opt": {"mem": jnp.zeros((6, 16, 4))},
                  "w": jnp.ones((16, 4))}
        with pytest.raises(WorldSizeMismatch, match="opt/mem") as ei:
            restore_checkpoint(tmp_path / "c", target)
        msg = str(ei.value)
        assert "(8, 16, 4)" in msg and "(6, 16, 4)" in msg
        assert "checkpoint world 8" in msg and "target world 6" in msg
        assert "reshard_grace_state" in msg
        # WorldSizeMismatch stays a ValueError: existing callers that
        # catch the structure-mismatch error keep working
        assert isinstance(ei.value, ValueError)

    def test_plain_shape_change_names_leaf_and_both_shapes(self, tmp_path):
        from grace_tpu.checkpoint import WorldSizeMismatch

        state = {"w": jnp.ones((4, 2))}
        save_checkpoint(tmp_path / "c", state, step=1)
        with pytest.raises(ValueError, match="'w'") as ei:
            restore_checkpoint(tmp_path / "c", {"w": jnp.ones((2, 4))})
        assert "(4, 2)" in str(ei.value) and "(2, 4)" in str(ei.value)
        assert not isinstance(ei.value, WorldSizeMismatch)

    def test_dtype_change_names_leaf_and_both_dtypes(self, tmp_path):
        state = {"w": jnp.ones((4, 2), jnp.float32)}
        save_checkpoint(tmp_path / "c", state, step=1)
        with pytest.raises(ValueError, match="'w'") as ei:
            restore_checkpoint(tmp_path / "c",
                               {"w": jnp.ones((4, 2), jnp.int32)})
        assert "float32" in str(ei.value) and "int32" in str(ei.value)

    def test_grace_state_world_resize_hint(self, mesh, tmp_path):
        """The real case: a W=8 train state restored into a W=6 target."""
        from grace_tpu.checkpoint import WorldSizeMismatch
        from grace_tpu.parallel import data_parallel_mesh

        state, step, batch = _setup(mesh)
        save_checkpoint(tmp_path / "c", state, step=1)
        grc = grace_from_params({"compressor": "topk",
                                 "compress_ratio": 0.1,
                                 "memory": "residual",
                                 "communicator": "allgather"})
        tx = optax.chain(grc.transform(seed=0), optax.sgd(1e-2))
        params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
        target6 = init_train_state(
            params, tx, data_parallel_mesh(jax.devices()[:6]))
        with pytest.raises(WorldSizeMismatch,
                           match="checkpoint world 8, target world 6"):
            restore_checkpoint(tmp_path / "c", target6)


class TestLastKnownGood:
    def test_restore_last_good_picks_newest_good(self, tmp_path):
        with Checkpointer(tmp_path / "g", max_to_keep=None) as ckpt:
            for s, good in ((1, True), (2, True), (3, False), (4, None)):
                ckpt.save(s, {"x": jnp.full((2,), float(s))}, force=True,
                          good=good)
            ckpt.wait()
            assert ckpt.latest_step() == 4
            assert ckpt.last_good_step() == 2
            restored = ckpt.restore_last_good({"x": jnp.zeros(2)})
        np.testing.assert_array_equal(np.asarray(restored["x"]),
                                      [2.0, 2.0])

    def test_good_mark_can_be_revoked(self, tmp_path):
        with Checkpointer(tmp_path / "r", max_to_keep=None) as ckpt:
            ckpt.save(1, {"x": jnp.ones(2)}, force=True, good=True)
            ckpt.mark_good(1, False)   # e.g. post-hoc eval found divergence
            ckpt.wait()
            assert ckpt.last_good_step() is None
            with pytest.raises(FileNotFoundError):
                ckpt.restore_last_good({"x": jnp.zeros(2)})

    def test_good_record_survives_reopen(self, tmp_path):
        with Checkpointer(tmp_path / "p", max_to_keep=None) as ckpt:
            ckpt.save(7, {"x": jnp.ones(2)}, force=True, good=True)
            ckpt.wait()
        with Checkpointer(tmp_path / "p", max_to_keep=None) as ckpt:
            assert ckpt.last_good_step() == 7

    def test_retention_gc_prunes_good_steps(self, tmp_path):
        """A good step garbage-collected by max_to_keep must not be offered
        for rollback."""
        with Checkpointer(tmp_path / "gc", max_to_keep=2) as ckpt:
            ckpt.save(1, {"x": jnp.ones(2)}, force=True, good=True)
            for s in (2, 3):
                ckpt.save(s, {"x": jnp.full((2,), float(s))}, force=True,
                          good=False)
            ckpt.wait()
            steps = set(ckpt.all_steps())
            if 1 not in steps:        # retention kicked in
                assert ckpt.last_good_step() is None
