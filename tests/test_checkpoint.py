"""Checkpoint round-trip tests — including compression (GraceState) state.

The key property the reference lacks (SURVEY.md §5): residual/error-feedback
state survives save/restore bit-exactly, so a resumed run continues the same
trajectory as an uninterrupted one.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.checkpoint import (Checkpointer, latest_step,
                                  restore_checkpoint, save_checkpoint)
from grace_tpu.train import init_train_state, make_train_step


def _setup(mesh):
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.1,
                             "memory": "residual",
                             "communicator": "allgather"})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(1e-2))
    params = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
    state = init_train_state(params, tx, mesh)

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    step = make_train_step(loss_fn, tx, mesh, donate=False)
    rng = np.random.default_rng(0)
    batch = (jnp.asarray(rng.standard_normal((32, 16)), jnp.float32),
             jnp.asarray(rng.standard_normal((32, 4)), jnp.float32))
    return state, step, batch


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestCheckpointRoundTrip:
    def test_full_state_roundtrip(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(3):
            state, loss = step(state, batch)
        save_checkpoint(tmp_path / "ckpt", state, step=3)
        restored = restore_checkpoint(tmp_path / "ckpt", state)
        _assert_trees_equal(state, restored)

    def test_resume_matches_uninterrupted(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(2):
            state, _ = step(state, batch)
        save_checkpoint(tmp_path / "c", state, step=2)

        # uninterrupted: 3 more steps
        cont = state
        for _ in range(3):
            cont, _ = step(cont, batch)

        # resumed: restore then 3 more steps
        resumed = restore_checkpoint(tmp_path / "c", state)
        for _ in range(3):
            resumed, _ = step(resumed, batch)
        _assert_trees_equal(cont, resumed)

    def test_grace_residual_state_is_saved(self, mesh, tmp_path):
        state, step, batch = _setup(mesh)
        for _ in range(2):
            state, _ = step(state, batch)
        grace_state = state.opt_state[0]
        # residual memory holds nonzero error feedback after topk steps
        assert any(float(jnp.abs(m).sum()) > 0 for m in grace_state.mem)
        save_checkpoint(tmp_path / "c", state, step=2)
        restored = restore_checkpoint(tmp_path / "c", state)
        _assert_trees_equal(grace_state, restored.opt_state[0])

    def test_bridge_state_roundtrip(self, mesh, tmp_path):
        """Interop frontends (torch/TF) checkpoint their compression state
        via GraceBridge.state — resume must be bit-faithful including each
        rank's residual, something the reference never persisted."""
        from grace_tpu import grace_from_params
        from grace_tpu.interop.bridge import GraceBridge

        grace = grace_from_params({"compressor": "topk",
                                   "compress_ratio": 0.25,
                                   "memory": "residual",
                                   "communicator": "allgather"})
        bridge = GraceBridge(grace, n=64, mesh=mesh)
        g = np.linspace(-1, 1, 64).astype(np.float32)
        np.asarray(bridge.exchange(g))
        save_checkpoint(tmp_path / "b", bridge.state, step=1)

        cont = np.asarray(bridge.exchange(g))

        bridge2 = GraceBridge(grace, n=64, mesh=mesh)
        bridge2.state = restore_checkpoint(tmp_path / "b", bridge2.state)
        resumed = np.asarray(bridge2.exchange(g))
        np.testing.assert_array_equal(cont, resumed)

    def test_manager_keep_and_latest(self, tmp_path):
        tree = {"x": jnp.arange(4.0)}
        with Checkpointer(tmp_path / "m", max_to_keep=2) as ckpt:
            for s in (1, 2, 3):
                ckpt.save(s, tree, force=True)
            ckpt.wait()
            assert ckpt.latest_step() == 3
            assert len(list(ckpt.all_steps())) <= 2  # retention enforced
        assert latest_step(tmp_path / "m") == 3

    def test_restore_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            restore_checkpoint(tmp_path / "nothing", {"x": jnp.zeros(2)})
