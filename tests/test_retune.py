"""graft-retune: fault-tolerant online re-tuning (ISSUE 18).

Pins the transaction contract of
:class:`grace_tpu.resilience.retune.RetuneController` — drift watch,
two-phase PREPARE/COMMIT promotion, probation + automatic bit-exact
demotion, and the bounded-timeout discipline on every transition leg —
plus the rung-invariant GraceState migration map
(:func:`grace_tpu.transform.migrate_grace_state`: carried / overlap /
fresh verdicts, PowerSGD warm-started Q across rank changes) and the
tuner's measure-timeout verdicts (:func:`grace_tpu.tuning.measure.
bounded_call` / ``measure_shortlist`` with a stalling candidate).
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.resilience import (ConsensusConfig, RetuneController,
                                  guarded_chain, state_digest)
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import GraceState, migrate_grace_state
from grace_tpu.tuning.measure import MeasureTimeout, bounded_call

pytestmark = pytest.mark.retune


# ---------------------------------------------------------------------------
# bounded_call / measure-timeout verdicts (the tuner's watchdog)
# ---------------------------------------------------------------------------

def test_bounded_call_returns_value():
    assert bounded_call(lambda: 41 + 1, 5.0) == 42
    assert bounded_call(lambda: "inline", None) == "inline"


def test_bounded_call_timeout_attempts_and_backoff():
    calls = []

    def stall():
        calls.append(1)
        time.sleep(30)

    t0 = time.perf_counter()
    with pytest.raises(MeasureTimeout) as ei:
        bounded_call(stall, 0.05, retries=2, label="wedged")
    dt = time.perf_counter() - t0
    # Three attempts with doubling backoff: 0.05 + 0.1 + 0.2.
    assert ei.value.attempts == 3
    assert ei.value.timeout_s == pytest.approx(0.2)
    assert len(calls) == 3
    assert 0.3 < dt < 5.0
    assert "wedged" in str(ei.value)


def test_bounded_call_exception_propagates_unretried():
    calls = []

    def boom():
        calls.append(1)
        raise ValueError("deterministic failure")

    with pytest.raises(ValueError, match="deterministic failure"):
        bounded_call(boom, 5.0, retries=3)
    # A deterministic failure must not become flaky success by repetition.
    assert len(calls) == 1


def test_measure_shortlist_timeout_verdict(mesh):
    """A wedged candidate lands in ``skipped`` with
    ``verdict='measure_timeout'`` (attempts + final timeout recorded), a
    crashing one with ``verdict='error'`` — and the funnel moves on past
    both instead of hanging or raising."""
    from grace_tpu.tuning.cost import TuneTopology
    from grace_tpu.tuning.measure import measure_shortlist

    class _Stall:
        name = "wedged-candidate"
        tpu_only = False

        def build(self):
            time.sleep(30)

    class _Crash:
        name = "crashing-candidate"
        tpu_only = False

        def build(self):
            raise RuntimeError("compile exploded")

    doc = measure_shortlist([_Stall(), _Crash()], TuneTopology.parse("8"),
                            mesh, timed_steps=2, repeats=1,
                            measure_timeout_s=0.2, measure_retries=1)
    rows = {s["candidate"]: s for s in doc["skipped"]}
    assert rows["wedged-candidate"]["verdict"] == "measure_timeout"
    assert rows["wedged-candidate"]["attempts"] == 2
    assert rows["wedged-candidate"]["timeout_s"] == pytest.approx(0.4)
    assert rows["crashing-candidate"]["verdict"] == "error"
    assert "compile exploded" in rows["crashing-candidate"]["reason"]
    assert doc["rows"] == [] and doc["winner"] is None
    assert doc["measure_timeout_s"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# migration map: carried / overlap / fresh, PowerSGD warm start
# ---------------------------------------------------------------------------

def _mlp_params(rng):
    return {
        "w1": jnp.asarray(rng.normal(scale=0.3, size=(32, 16)),
                          jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.normal(scale=0.3, size=(16, 8)), jnp.float32),
        "b2": jnp.zeros((8,), jnp.float32),
    }


def _loss_fn(p, b):
    x, y = b
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    logits = h @ p["w2"] + p["b2"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _batch(rng, n=16):
    return (jnp.asarray(rng.normal(size=(n, 32)).astype(np.float32)),
            jnp.asarray(rng.integers(0, 8, size=(n,)).astype(np.int32)))


def _powersgd_state(mesh, rng, rank):
    grc = grace_from_params({"compressor": "powersgd",
                             "compress_rank": rank,
                             "memory": "powersgd",
                             "communicator": "allreduce"})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
    state = init_train_state(_mlp_params(rng), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    state, _ = step(state, _batch(rng))
    return state


def _grace_nodes(tree):
    out = []
    jax.tree_util.tree_map(
        lambda n: out.append(n) if isinstance(n, GraceState) else n,
        tree, is_leaf=lambda n: isinstance(n, GraceState))
    return out


def test_migrate_powersgd_rank_change_warm_starts_q(mesh, rng):
    """rank 2 → rank 4 within the PowerSGD family: every per-direction
    leaf migrates by LAST-AXIS overlap — the first two columns carry
    bit-exactly, the new columns keep the fresh draw."""
    old = _powersgd_state(mesh, rng, rank=2)
    fresh = _powersgd_state(mesh, np.random.default_rng(1), rank=4)
    migrated_opt, stats = migrate_grace_state(old.opt_state,
                                              fresh.opt_state)
    assert stats["comp_structure_match"] and stats["mem_structure_match"]
    # The matrix leaves (w1, w2) carry rank-shaped Q/P state: overlap.
    assert stats["comp"]["overlap"] + stats["mem"]["overlap"] >= 2
    assert stats["comp"]["fresh"] == 0 and stats["mem"]["fresh"] == 0

    old_g, new_g = _grace_nodes(old.opt_state)[0], \
        _grace_nodes(migrated_opt)[0]
    fresh_g = _grace_nodes(fresh.opt_state)[0]
    checked = 0
    for o, n, f in zip(jax.tree_util.tree_leaves(old_g.comp),
                       jax.tree_util.tree_leaves(new_g.comp),
                       jax.tree_util.tree_leaves(fresh_g.comp)):
        if (hasattr(o, "ndim") and o.ndim >= 2
                and o.shape[:-1] == n.shape[:-1]
                and o.shape[-1] == 2 and n.shape[-1] == 4):
            np.testing.assert_array_equal(np.asarray(n[..., :2]),
                                          np.asarray(o))
            np.testing.assert_array_equal(np.asarray(n[..., 2:]),
                                          np.asarray(f[..., 2:]))
            checked += 1
    assert checked >= 1
    # Replicated fields carry bit-exactly: the step counter continues.
    assert int(np.asarray(jax.device_get(new_g.count)).reshape(-1)[0]) == \
        int(np.asarray(jax.device_get(old_g.count)).reshape(-1)[0])


def test_migrate_cross_family_is_fresh(mesh, rng):
    """homoqsgd → powersgd: no meaningful warm state exists — comp/mem
    take the fresh init (structure mismatch), replicated fields carry."""
    grc = grace_from_params({"compressor": "homoqsgd", "quantum_num": 7,
                             "memory": "residual",
                             "communicator": "allreduce",
                             "fusion": "flat"})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
    old = init_train_state(_mlp_params(rng), tx, mesh)
    fresh = _powersgd_state(mesh, np.random.default_rng(1), rank=4)
    migrated_opt, stats = migrate_grace_state(old.opt_state,
                                              fresh.opt_state)
    assert not stats["comp_structure_match"]
    assert stats["comp"]["carried"] == stats["comp"]["overlap"] == 0
    new_g = _grace_nodes(migrated_opt)[0]
    old_g = _grace_nodes(old.opt_state)[0]
    np.testing.assert_array_equal(np.asarray(jax.device_get(new_g.count)),
                                  np.asarray(jax.device_get(old_g.count)))


def test_state_digest_is_content_sensitive(mesh, rng):
    state = _powersgd_state(mesh, rng, rank=2)
    d1 = state_digest(state)
    assert d1 == state_digest(state)
    bumped = state._replace(params={**state.params,
                                    "b1": state.params["b1"] + 1.0})
    assert state_digest(bumped) != d1


# ---------------------------------------------------------------------------
# controller: drift watch, watchdog legs, probation semantics (host-side)
# ---------------------------------------------------------------------------

def _host_controller(**kw):
    kw.setdefault("build", lambda p: (None, None))
    kw.setdefault("params", {"compressor": "homoqsgd"})
    return RetuneController(**kw)


def test_observe_fires_only_on_sustained_drift():
    ctl = _host_controller(window=4, drift_factor=2.0, drift_windows=2)
    fired = []
    step = 0
    # Window 1 learns the baseline (mean 1.0); window 2 is healthy.
    for v in [1.0] * 8:
        fired.append(ctl.observe(step, v))
        step += 1
    # One hot window is not sustained drift yet...
    for v in [3.0] * 4:
        fired.append(ctl.observe(step, v))
        step += 1
    assert not any(fired)
    # ...the second consecutive hot window is.
    out = [ctl.observe(step + i, 3.0) for i in range(4)]
    assert out[-1] is True
    assert ctl.events[-1]["event"] == "retune_drift"
    assert ctl.events[-1]["baseline"] == pytest.approx(1.0)
    # None rows (telemetry disabled) are ignored, not counted as zeros.
    assert ctl.observe(99, None) is False


def test_observe_hot_streak_resets_on_quiet_window():
    ctl = _host_controller(window=2, drift_factor=1.5, drift_windows=2)
    for i, v in enumerate([1.0, 1.0]):          # baseline
        ctl.observe(i, v)
    assert ctl.observe(2, 5.0) is False
    assert ctl.observe(3, 5.0) is False         # hot window 1
    assert ctl.observe(4, 1.0) is False
    assert ctl.observe(5, 1.0) is False         # quiet: streak resets
    assert ctl.observe(6, 5.0) is False
    assert ctl.observe(7, 5.0) is False         # hot window 1 again
    assert not any(e["event"] == "retune_drift" for e in ctl.events)


def test_watchdog_bounds_a_hung_leg_and_records_timeouts():
    ctl = _host_controller(leg_timeout_s=0.05, leg_retries=1)
    ok, result, timeouts = ctl._watchdog("drill", 7,
                                         lambda: time.sleep(30))
    assert ok is False and result is None and timeouts == 2
    recs = [e for e in ctl.events if e["event"] == "retune_timeout"]
    assert len(recs) == 2
    assert recs[0]["leg"] == "drill" and recs[0]["attempt"] == 1
    assert recs[1]["timeout_s"] == pytest.approx(0.1)   # doubled
    # A healthy leg passes through with no events.
    ok, result, timeouts = ctl._watchdog("drill", 8, lambda: "done")
    assert ok and result == "done" and timeouts == 0


def test_watchdog_exceptions_propagate_unretried():
    ctl = _host_controller(leg_timeout_s=5.0, leg_retries=3)
    calls = []

    def boom():
        calls.append(1)
        raise RuntimeError("leg failed")

    with pytest.raises(RuntimeError, match="leg failed"):
        ctl._watchdog("drill", 0, boom)
    assert len(calls) == 1


def test_watch_triggers_on_guard_and_clears_quiet():
    ctl = _host_controller(probation_steps=10,
                           demote_on=("guard_skip", "consensus_escalation"))
    ctl.phase = "probation"
    ctl._probation_until = 10
    # Telemetry metric rows (no event) and benign events pass through.
    assert ctl.watch(3, [{"step": 3, "grad_norm": 1.0},
                         {"event": "watch", "step": 3}]) is None
    assert ctl.watch(5, [{"event": "guard_skip", "step": 5}]) == \
        "guard_skip"
    assert ctl.phase == "probation"      # watch reports; demote() acts
    # Past the horizon with no trigger: the promotion sticks.
    assert ctl.watch(10, []) is None
    assert ctl.phase == "idle"
    assert ctl.events[-1]["event"] == "retune_probation_clear"


def test_controller_validates_knobs():
    with pytest.raises(ValueError, match="drift_factor"):
        _host_controller(drift_factor=1.0)
    with pytest.raises(ValueError, match="window"):
        _host_controller(window=0)
    with pytest.raises(ValueError, match="leg_timeout_s"):
        _host_controller(leg_timeout_s=0.0)
    with pytest.raises(ValueError, match="leg_retries"):
        _host_controller(leg_retries=-1)


# ---------------------------------------------------------------------------
# the full transaction on the 8-device mesh
# ---------------------------------------------------------------------------

OLD_PARAMS = {"compressor": "homoqsgd", "quantum_num": 7,
              "memory": "residual", "communicator": "allreduce",
              "fusion": "flat", "escape": "fp16", "telemetry": 16,
              "consensus": ConsensusConfig(audit_every=10)}
NEW_PARAMS = {"compressor": "powersgd", "compress_rank": 4,
              "memory": "powersgd", "communicator": "allreduce",
              "escape": "fp16", "telemetry": 16,
              "consensus": ConsensusConfig(audit_every=10),
              "adapt": {"window": 5, "ladder": [{"compress_rank": 1}]}}


def _build(p):
    grc = grace_from_params(p)
    tx = guarded_chain(grc, optax.sgd(0.05), fallback_after=3,
                       fallback_steps=4)
    return grc, tx


def _controller(ckpt_dir, **kw):
    from grace_tpu.checkpoint import Checkpointer
    kw.setdefault("window", 4)
    kw.setdefault("probation_steps", 8)
    kw.setdefault("leg_timeout_s", 120.0)
    return RetuneController(
        build=_build, params=OLD_PARAMS,
        consensus=OLD_PARAMS["consensus"],
        checkpointer=Checkpointer(str(ckpt_dir), max_to_keep=2), **kw)


def _warm(mesh, rng, steps=4):
    grc, tx = _build(OLD_PARAMS)
    state = init_train_state(_mlp_params(rng), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    for i in range(steps):
        state, loss = step(state, _batch(rng))
    return state, float(loss)


def test_promotion_transaction_and_probation_clear(mesh, rng, tmp_path):
    """PREPARE stages without touching live state, COMMIT cuts over
    behind the consensus barrier, a quiet probation window clears."""
    from grace_tpu.resilience import replica_variants

    state, _ = _warm(mesh, rng)
    ctl = _controller(tmp_path / "ckpt")
    pre_digest = state_digest(state)

    staged = ctl.prepare(4, state, mesh, NEW_PARAMS)
    assert staged is not None and ctl.phase == "prepared"
    assert staged.footprint_matches and staged.checkpointed
    # PREPARE never wrote the incumbent: live state is bit-identical.
    assert state_digest(state) == pre_digest

    out = ctl.commit(4, mesh)
    assert out is not None
    state, (grc2, tx2), ev = out
    assert ev["event"] == "retune_promote"
    assert ev["old"] == "homoqsgd" and ev["new"] == "powersgd"
    assert ev.get("replica_variants", 1) == 1
    assert ctl.phase == "probation"
    assert replica_variants(state.params) == 1

    # The promoted transform trains (the PowerSGD ladder dispatches
    # through one lax.switch) and a quiet probation clears.
    step2 = make_train_step(_loss_fn, tx2, mesh, donate=False)
    for i in range(5, 5 + ctl.probation_steps):
        state, loss = step2(state, _batch(rng))
        assert ctl.watch(i, []) is None
    assert np.isfinite(float(loss))
    assert ctl.phase == "idle"
    assert ctl.params["compressor"] == "powersgd"
    names = [e["event"] for e in ctl.events]
    assert names.index("retune_prepare") < names.index("retune_promote") \
        < names.index("retune_probation_clear")


def test_demotion_restores_last_known_good_bit_exactly(mesh, rng,
                                                       tmp_path):
    """A guard trip during probation demotes: the PREPARE-time checkpoint
    comes back digest-identical and the incumbent config is restored."""
    state, _ = _warm(mesh, rng)
    ctl = _controller(tmp_path / "ckpt")
    staged = ctl.prepare(4, state, mesh, NEW_PARAMS)
    assert staged is not None
    lkg = staged.lkg_digest
    out = ctl.commit(4, mesh)
    assert out is not None
    state, (_, tx2), ev = out
    step2 = make_train_step(_loss_fn, tx2, mesh, donate=False)
    state, _ = step2(state, _batch(rng))

    trig = ctl.watch(5, [{"event": "guard_skip", "step": 5}])
    assert trig == "guard_skip"
    restored, (_, tx3), dem = ctl.demote(5, state, mesh, trigger=trig)
    assert dem["restored"] is True and dem["bit_exact"] is True
    assert dem["trigger"] == "guard_skip"
    assert state_digest(restored) == lkg
    assert ctl.phase == "idle"
    assert ctl.params["compressor"] == "homoqsgd"
    # The demoted run keeps training under the incumbent config.
    step3 = make_train_step(_loss_fn, tx3, mesh, donate=False)
    restored, loss = step3(restored, _batch(rng))
    assert np.isfinite(float(loss))
    # prepare() mid-probation is a programming error, post-demote is fine.
    assert ctl.prepare(6, restored, mesh, NEW_PARAMS) is not None


def test_prepare_aborts_on_chain_structure_mismatch(mesh, rng):
    """A build whose optimizer chain does not match the live state's
    (guarded vs unguarded) aborts at the migrate gate — the incumbent
    keeps running and the abort is recorded, not raised."""
    state, _ = _warm(mesh, rng)

    def unguarded(p):
        grc = grace_from_params(p)
        return grc, optax.chain(grc.transform(seed=0), optax.sgd(0.05))

    ctl = RetuneController(build=unguarded, params=OLD_PARAMS,
                           consensus=None, window=4)
    assert ctl.prepare(4, state, mesh, NEW_PARAMS) is None
    assert ctl.phase == "idle"
    ev = ctl.events[-1]
    assert ev["event"] == "retune_abort" and ev["leg"] == "migrate"


def test_prepare_during_probation_raises(mesh, rng, tmp_path):
    state, _ = _warm(mesh, rng)
    ctl = _controller(tmp_path / "ckpt")
    assert ctl.prepare(4, state, mesh, NEW_PARAMS) is not None
    assert ctl.commit(4, mesh) is not None
    with pytest.raises(RuntimeError, match="probation"):
        ctl.prepare(5, state, mesh, NEW_PARAMS)


def test_powersgd_ladder_states_padded_to_max_rank(mesh, rng):
    """The rung-invariant comp-state layout: a PowerSGD ladder pads every
    per-direction leaf to the LADDER's max rank so one ``lax.switch``
    dispatches all rungs over one state shape."""
    grc = grace_from_params({"compressor": "powersgd", "compress_rank": 2,
                             "memory": "powersgd",
                             "communicator": "allreduce",
                             "escape": "fp16", "telemetry": 16,
                             "adapt": {"window": 5,
                                       "ladder": [{"compress_rank": 4}]}})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
    state = init_train_state(_mlp_params(rng), tx, mesh)
    ranks = {leaf.shape[-1]
             for g in _grace_nodes(state.opt_state)
             for leaf in jax.tree_util.tree_leaves(g.comp)
             if hasattr(leaf, "ndim") and leaf.ndim >= 2}
    assert ranks == {4}, (
        f"comp-state last-axis ranks {ranks}: every rung must share the "
        "ladder max (4) so rank moves are mask flips, not reshapes")
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    for _ in range(3):
        state, loss = step(state, _batch(rng))
    assert np.isfinite(float(loss))
