"""graft-watch: in-graph cross-rank health aggregation, streaming anomaly
detection, and the unified run timeline (ISSUE 8).

The properties pinned here are the acceptance criteria of the watch
subsystem: cross-rank summaries computed in-graph for one tiny collective
per window (wire cost folded honestly into the telemetry ring, single
flush transfer preserved), a seeded single-rank compression-error drift
flagged with the correct rank within one window while the guard provably
stays silent, zero false positives on a healthy run, window-ordered
drain across guard-fallback and consensus-audit windows, and the
graft_watch CLI's baseline regression gate (exit 1 + WATCH_LAST.json).
"""

import dataclasses
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.resilience import ChaosCompressor, ConsensusConfig, \
    guarded_chain
from grace_tpu.telemetry import (AnomalyConfig, JSONLSink, TelemetryReader,
                                 Timeline, WatchConfig, WatchMonitor)
from grace_tpu.telemetry.aggregate import (WATCH_FIELDS, normalize_watch,
                                           watch_gather_bytes)
from grace_tpu.telemetry.anomaly import Ewma
from grace_tpu.telemetry.timeline import classify
from grace_tpu.train import init_train_state, make_train_step

BATCH, DIM, CLASSES = 64, 20, 4

TOPK_WATCH = {"compressor": "topk", "compress_ratio": 0.3,
              "memory": "residual", "communicator": "allgather",
              "telemetry": 64, "watch": 5}


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, grace_params, lr=0.3, guard=False, drift_rank=None,
           drift_scale=0.6, consensus=None, **guard_kw):
    grc = grace_from_params(dict(grace_params))
    if drift_rank is not None:
        grc = dataclasses.replace(grc, compressor=ChaosCompressor(
            inner=grc.compressor, drift_scale=drift_scale, rank=drift_rank))
    if guard:
        tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    else:
        tx = optax.chain(grc.transform(seed=0), optax.sgd(lr))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=consensus)
    return state, step


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))

    def close(self):
        pass


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# in-graph aggregation
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.telemetry
def test_watch_rows_on_window_boundaries_with_consistent_stats(mesh):
    """Summaries land exactly on window boundaries; replicated stats obey
    min <= mean <= max; the per-rank skew vectors re-assembled from the
    world axis have length W and sum to ~0 (deviations from the mean)."""
    x, y = _problem()
    state, step = _build(mesh, TOPK_WATCH)
    reader = TelemetryReader(sink=None, every=100)
    for _ in range(12):
        state, _ = step(state, (x, y))
    records = reader.flush(state)
    watch = [r for r in records if r.get("event") == "watch"]
    assert [r["step"] for r in watch] == [0, 5, 10]
    for rec in watch:
        for metric in ("grad_norm", "compression_error", "residual_norm"):
            assert (rec[f"{metric}_min"] <= rec[f"{metric}_mean"]
                    <= rec[f"{metric}_max"])
            skew = rec[f"{metric}_skew"]
            assert len(skew) == 8
            assert abs(sum(skew)) < 1e-3 * max(rec[f"{metric}_mean"], 1.0)
        assert 0 <= rec["skew_rank"] < 8
        assert rec["skew_max"] >= 0
        assert rec["watch_bytes"] == watch_gather_bytes(8) == 7 * 3 * 4


@pytest.mark.watch
@pytest.mark.telemetry
def test_watch_bytes_fold_into_wire_accounting(mesh):
    """Window-boundary rows carry the gather's bytes in wire_bytes AND the
    per-link split (ici on a single slice), other rows don't — and the
    ici + dcn == wire_bytes identity survives the fold."""
    x, y = _problem()
    state, step = _build(mesh, TOPK_WATCH)
    reader = TelemetryReader(sink=None, every=100)
    for _ in range(7):
        state, _ = step(state, (x, y))
    rows = [r for r in reader.flush(state) if "wire_bytes" in r]
    assert len(rows) == 7
    base = rows[1]["wire_bytes"]        # step 1: no watch gather
    gb = watch_gather_bytes(8)
    for rec in rows:
        boundary = rec["step"] % 5 == 0
        assert rec["watch_bytes"] == (gb if boundary else 0.0)
        assert rec["wire_bytes"] == base + (gb if boundary else 0.0)
        assert rec["wire_bytes_ici"] + rec["wire_bytes_dcn"] \
            == rec["wire_bytes"]


@pytest.mark.watch
@pytest.mark.telemetry
def test_flush_is_still_one_transfer_with_watch_armed(mesh, monkeypatch):
    """Watch rings ride the SAME device_get as the metric rings and guard
    counters — arming watch must not add transfers."""
    x, y = _problem()
    state, step = _build(mesh, dict(TOPK_WATCH, escape="fp16"), guard=True)
    reader = TelemetryReader(sink=None, every=10, anomaly=True)

    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    for i in range(20):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    assert len(calls) == 2
    assert reader.flushes == 2


@pytest.mark.watch
@pytest.mark.chaos
def test_watch_row_rolls_back_with_skipped_step(mesh):
    """A poisoned step on a window boundary rolls the watch ring back with
    the rest of the state: no NaN summary ever reaches a flush and the
    boundary row is written by the retried (accepted) step instead."""
    x, y = _problem()
    state, step = _build(mesh, dict(TOPK_WATCH, escape="fp16"), guard=True)
    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan
    # Wall step 5 is poisoned; accepted counts stay contiguous so the
    # count-5 boundary row comes from the NEXT (healthy) batch.
    batches = [x] * 5 + [jnp.asarray(xbad)] + [x] * 3
    reader = TelemetryReader(sink=None, every=100)
    for xb in batches:
        state, _ = step(state, (jnp.asarray(xb), y))
    records = reader.flush(state)
    watch = [r for r in records if r.get("event") == "watch"]
    assert [r["step"] for r in watch] == [0, 5]
    for rec in watch:
        for name, agg in WATCH_FIELDS:
            vals = rec[name] if agg == "gather" else [rec[name]]
            assert all(np.isfinite(v) for v in vals), (rec["step"], name)
    metric_steps = [r["step"] for r in records if "wire_bytes" in r]
    assert metric_steps == list(range(8))      # 9 wall steps, 1 skipped


@pytest.mark.watch
def test_watch_requires_telemetry():
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                             "memory": "residual",
                             "communicator": "allgather", "watch": 5})
    with pytest.raises(ValueError, match="requires telemetry"):
        grc.transform(seed=0)


@pytest.mark.watch
def test_normalize_watch_spellings():
    assert normalize_watch(None) is None and normalize_watch(False) is None
    assert normalize_watch(True) == WatchConfig()
    assert normalize_watch(7) == WatchConfig(window=7)
    assert normalize_watch({"window": 3, "capacity": 4}) \
        == WatchConfig(window=3, capacity=4)
    with pytest.raises(TypeError):
        normalize_watch("yes")
    with pytest.raises(ValueError):
        WatchConfig(window=0)


@pytest.mark.watch
@pytest.mark.profiling
def test_state_footprint_counts_watch_ring(mesh):
    """The live watch ring bytes are part of the telem component and the
    expected model (eval_shape of init x world) matches them — the ring's
    row shape is world-independent by design, so the footprint check
    keeps working on any mesh."""
    from grace_tpu.profiling import check_state_footprint

    grc = grace_from_params(dict(TOPK_WATCH))
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.1))
    params = _init_params()
    state = init_train_state(params, tx, mesh)
    with_watch = check_state_footprint(state, grc, params, world=8)
    assert with_watch["matches"]
    no_watch = grace_from_params(
        {k: v for k, v in TOPK_WATCH.items() if k != "watch"})
    expected_delta = 8 * (16 * len(WATCH_FIELDS) * 4 + 16 * 4)
    assert with_watch["model"]["telem_bytes"] \
        - check_state_footprint(
            state, no_watch, params, world=8)["model"]["telem_bytes"] \
        == expected_delta


# ---------------------------------------------------------------------------
# anomaly detection
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.chaos
def test_seeded_drift_flagged_with_correct_rank_within_one_window(mesh):
    """The acceptance scenario, in-process: a single-rank payload drift —
    finite (guard-blind), per-rank (consensus-blind) — produces a skew
    watch_anomaly naming exactly that rank at the first window boundary,
    while a drift-free twin of the run produces zero anomalies."""
    x, y = _problem()
    sink = _ListSink()
    state, step = _build(mesh, TOPK_WATCH, drift_rank=5)
    reader = TelemetryReader(sink, every=10, anomaly=True)
    for i in range(20):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    anomalies = [r for r in sink.records
                 if r.get("event") == "watch_anomaly"]
    # Attribution judged on the codec-health metrics the drift corrupts;
    # grad_norm skew can legitimately flag batch-shard heterogeneity.
    skews = [a for a in anomalies if a["kind"] == "skew"
             and a["metric"] in ("compression_error", "residual_norm")]
    assert skews, "seeded drift produced no skew anomaly"
    assert {a["rank"] for a in skews} == {5}
    assert min(a["step"] for a in skews) == 0      # first window boundary
    assert any(a["metric"] == "compression_error" for a in skews)

    healthy_sink = _ListSink()
    state, step = _build(mesh, TOPK_WATCH)
    reader = TelemetryReader(healthy_sink, every=10, anomaly=True)
    for i in range(20):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    assert not [r for r in healthy_sink.records
                if r.get("event") == "watch_anomaly"]


@pytest.mark.watch
def test_skew_detector_hysteresis_one_record_per_episode():
    """A persistently drifting rank is flagged once on the rising edge,
    not once per window — and re-arms after the skew subsides."""
    monitor = WatchMonitor()

    def watch_rec(step, outlier):
        skew = [0.01, -0.02, 0.3 if outlier else 0.01, -0.01, 0.01,
                -0.02, 0.02, 0.0]
        return {"event": "watch", "step": step,
                "compression_error_mean": 0.5,
                "compression_error_skew": skew,
                "grad_norm_mean": 1.0, "grad_norm_skew": [0.0] * 8,
                "residual_norm_mean": 1.0, "residual_norm_skew": [0.0] * 8}

    out = monitor.observe([watch_rec(0, True), watch_rec(5, True),
                           watch_rec(10, True)])
    assert len([a for a in out if a["metric"] == "compression_error"]) == 1
    out = monitor.observe([watch_rec(15, False), watch_rec(20, True)])
    hits = [a for a in out if a["metric"] == "compression_error"]
    assert len(hits) == 1 and hits[0]["step"] == 20    # new episode


@pytest.mark.watch
def test_ewma_spike_and_step_time_and_retrace_detectors():
    monitor = WatchMonitor(config=AnomalyConfig(warmup=3))
    base = [{"event": "perf_step_times", "step": s, "p50_ms": 10.0 + 0.01 * s}
            for s in range(5)]
    assert monitor.observe(base) == []
    spike = monitor.observe([{"event": "perf_step_times", "step": 6,
                              "p50_ms": 40.0}])
    assert [a["kind"] for a in spike] == ["step_time"]
    retr = monitor.observe([{"event": "perf_retrace", "step": 7,
                             "cache_size": 2, "retraces": 1}])
    assert [a["kind"] for a in retr] == ["retrace"]

    e = Ewma(alpha=0.25, warmup=2)
    assert e.update(1.0) is None and e.update(1.0) is None
    assert e.update(1.0) < 1.0
    assert e.update(100.0) > 4.0


@pytest.mark.watch
def test_wire_model_drift_detector():
    """The exchange bytes (wire - audit - watch) changing mid-run beyond
    rtol is an anomaly; audit/watch surcharges on their own are not."""
    monitor = WatchMonitor()
    rows = [{"step": 0, "wire_bytes": 1000.0, "audit_bytes": 0.0,
             "watch_bytes": 84.0, "fallback": 0.0},
            {"step": 1, "wire_bytes": 916.0, "audit_bytes": 0.0,
             "watch_bytes": 0.0, "fallback": 0.0},
            {"step": 2, "wire_bytes": 1016.0, "audit_bytes": 100.0,
             "watch_bytes": 0.0, "fallback": 0.0}]
    assert monitor.observe(rows) == []
    drift = monitor.observe([{"step": 3, "wire_bytes": 2000.0,
                              "audit_bytes": 0.0, "watch_bytes": 0.0,
                              "fallback": 0.0}])
    assert [a["kind"] for a in drift] == ["wire_drift"]
    assert drift[0]["expected"] == 916.0


# ---------------------------------------------------------------------------
# drain ordering across guard-fallback + consensus-audit windows
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.telemetry
@pytest.mark.consensus
def test_multiwindow_drain_ordering_under_guard_and_consensus(mesh):
    """Records from window N always precede window N+1 in the sink, and
    steps are strictly increasing, even when a guard-fallback window and
    consensus audit steps land inside the same flush — the step-keying the
    timeline relies on."""
    x, y = _problem()
    params = dict(TOPK_WATCH, escape="fp16", consensus=True)
    state, step = _build(mesh, params, guard=True, fallback_after=2,
                         fallback_steps=4,
                         consensus=ConsensusConfig(audit_every=5))
    sink = _ListSink()
    reader = TelemetryReader(sink, every=12)
    xbad = jnp.asarray(np.where(np.arange(x.size).reshape(x.shape) == 0,
                                np.nan, np.asarray(x)).astype(np.float32))
    flush_of = {}
    for i in range(24):
        xb = xbad if i in (6, 7) else x       # 2 consecutive bad -> fallback
        state, _ = step(state, (xb, y))
        for rec in reader.update(i, state):
            if "wire_bytes" in rec:
                flush_of[rec["step"]] = reader.flushes
    metric = [r for r in sink.records if "wire_bytes" in r]
    steps = [r["step"] for r in metric]
    assert steps == sorted(steps) == list(range(22))   # 24 wall, 2 skipped
    assert any(r["fallback"] for r in metric)          # fallback inside
    assert any(r["audit_bytes"] > 0 for r in metric)   # audits inside
    assert reader.flushes == 2
    # Window partition: every step of flush 1 precedes every step of 2.
    assert max(s for s, f in flush_of.items() if f == 1) \
        < min(s for s, f in flush_of.items() if f == 2)
    # Watch rows stay window-ordered alongside the metric rows.
    watch_steps = [r["step"] for r in sink.records
                   if r.get("event") == "watch"]
    assert watch_steps == sorted(watch_steps)


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------

@pytest.mark.watch
def test_timeline_classify_merge_and_queries():
    records = [
        {"provenance": {"tool": "test"}},
        {"step": 0, "grad_norm": 1.0, "wire_bytes": 100.0},
        {"event": "watch", "step": 0, "skew_max": 0.1, "skew_rank": 2,
         "compression_error_mean": 0.4},
        {"event": "watch_anomaly", "step": 0, "kind": "skew",
         "metric": "compression_error", "rank": 2, "score": 9.0},
        {"step": 1, "grad_norm": 0.9, "wire_bytes": 100.0},
        {"event": "guard_skip", "step": 2, "notfinite_count": 1},
        {"event": "consensus_repair", "step": 3, "repairs": 1},
        {"event": "perf_step_times", "step": 3, "p50_ms": 1.0},
        {"event": "lint_finding", "step": 3, "severity": "error"},
        {"event": "guard_only", "guard_step": 4},
    ]
    assert classify(records[1]) == "telemetry"
    assert classify(records[3]) == "anomaly"
    assert classify(records[-1]) == "guard"
    tl = Timeline.from_records(records)
    assert tl.provenance == {"tool": "test"}
    assert len(tl) == 9
    # Within a step, emission order is preserved (causal order).
    kinds_at_0 = [e.kind for e in tl.at_step(0)]
    assert kinds_at_0 == ["telemetry", "watch", "anomaly"]
    assert [e.kind for e in tl.between(2, 3)] == \
        ["guard", "consensus", "perf", "lint"]
    assert tl.first("anomaly").step == 0
    assert tl.steps() == [0, 1, 2, 3]
    s = tl.summary()
    assert s["anomalies"] == 1 and s["anomalous_ranks"] == [2]
    assert s["first_anomaly_step"] == 0 and s["first_guard_step"] == 2
    assert s["anomalies_by_kind"] == {"skew": 1}
    text = tl.render()
    assert "ANOMALY skew/compression_error rank=2" in text
    with pytest.raises(ValueError):
        tl.kinds("nonsense")


@pytest.mark.watch
def test_timeline_from_jsonl_skips_torn_tail(tmp_path):
    path = tmp_path / "run.jsonl"
    path.write_text(json.dumps({"step": 0, "grad_norm": 1.0}) + "\n"
                    + '{"step": 1, "grad_no')          # killed mid-line
    tl = Timeline.from_jsonl(str(path))
    assert len(tl) == 1 and tl.events[0].step == 0


# ---------------------------------------------------------------------------
# graft_watch CLI
# ---------------------------------------------------------------------------

def _write_artifact(path, drift: bool):
    sink = JSONLSink(path, provenance={"tool": "test", "data": "synthetic"})
    monitor = WatchMonitor(sink=sink)
    for s in range(20):
        sink.write({"step": s, "grad_norm": 1.0, "wire_bytes": 100.0,
                    "audit_bytes": 0.0, "watch_bytes": 0.0,
                    "fallback": 0.0, "compression_error": 0.4})
        if s % 5 == 0:
            outlier = 0.3 if (drift and s >= 10) else 0.01
            rec = {"event": "watch", "step": s,
                   "grad_norm_mean": 1.0, "grad_norm_skew": [0.0] * 8,
                   "residual_norm_mean": 1.0,
                   "residual_norm_skew": [0.0] * 8,
                   "compression_error_mean": 0.4,
                   "compression_error_skew":
                       [0.01, -0.01, 0.0, outlier, 0.01, -0.02, 0.0, 0.0]}
            sink.write(rec)
            monitor.observe([rec])
    sink.close()


@pytest.mark.watch
def test_graft_watch_cli_views_and_evidence(tmp_path, capsys):
    watch_tool = _load_tool("graft_watch")
    art = tmp_path / "run.jsonl"
    _write_artifact(str(art), drift=True)
    out = tmp_path / "WATCH_LAST.json"
    rc = watch_tool.main([str(art), "--timeline", "--anomalies",
                          "--out", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "== timeline" in text and "== anomalies" in text
    assert "rank=3" in text
    assert "anomalous ranks: [3]" in text
    doc = json.loads(out.read_text())
    assert doc["tool"] == "graft_watch"
    assert doc["anomalous_ranks"] == [3]
    assert doc["recorded_anomalies"] and doc["derived_anomalies"]

    rc = watch_tool.main([str(art), "--json", "--out", ""])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["anomalies_by_kind"] == {"skew": 1}


@pytest.mark.watch
def test_graft_watch_baseline_gates_seeded_regression(tmp_path, capsys):
    """The regression gate: a clean baseline vs a drift run exits 1 and
    writes the evidence document; clean-vs-clean exits 0."""
    watch_tool = _load_tool("graft_watch")
    clean = tmp_path / "clean.jsonl"
    drift = tmp_path / "drift.jsonl"
    _write_artifact(str(clean), drift=False)
    _write_artifact(str(drift), drift=True)
    base = tmp_path / "WATCH_BASELINE.json"
    out = tmp_path / "WATCH_LAST.json"

    assert watch_tool.main([str(clean), "--write-baseline", str(base),
                            "--out", ""]) == 0
    assert watch_tool.main([str(clean), "--baseline", str(base),
                            "--out", ""]) == 0
    capsys.readouterr()
    rc = watch_tool.main([str(drift), "--baseline", str(base),
                          "--out", str(out)])
    assert rc == 1
    text = capsys.readouterr().out
    assert "BASELINE REGRESSIONS" in text
    assert "new kind" in text
    doc = json.loads(out.read_text())
    assert doc["regressions"]
    assert doc["baseline"] == str(base)


@pytest.mark.watch
def test_evidence_summary_picks_up_watch_last(tmp_path, monkeypatch):
    evidence_summary = _load_tool("evidence_summary")
    monkeypatch.setattr(evidence_summary, "ROOT", str(tmp_path))
    doc = {"tool": "graft_watch", "artifact": "chaos_telemetry.jsonl",
           "events": 69, "kind_counts": {"telemetry": 60, "watch": 6,
                                         "anomaly": 3},
           "anomalies": 3, "anomalous_ranks": [3],
           "first_anomaly_step": 0, "regressions": [],
           "captured_at": "2026-08-04T00:00:00+00:00"}
    (tmp_path / "WATCH_LAST.json").write_text(json.dumps(doc))
    md = evidence_summary.build()
    assert "Run health (graft-watch)" in md
    assert "anomalous rank(s) [3]" in md
    assert "0 baseline regression(s)" in md


# ---------------------------------------------------------------------------
# telemetry_report watch section + --json (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.telemetry
def test_telemetry_report_watch_section_and_json(tmp_path, capsys):
    report = _load_tool("telemetry_report")
    path = tmp_path / "r.jsonl"
    sink = JSONLSink(path, provenance={"data": "synthetic"})
    for s in range(6):
        sink.write({"step": s, "grad_norm": 1.0, "wire_bytes": 184.0
                    if s % 5 == 0 else 100.0, "dense_bytes": 336.0,
                    "fallback": 0.0, "watch_bytes": 84.0
                    if s % 5 == 0 else 0.0})
    sink.write({"event": "watch", "step": 5, "grad_norm_mean": 1.0,
                "grad_norm_min": 0.9, "grad_norm_max": 1.1,
                "compression_error_mean": 0.4,
                "compression_error_min": 0.3, "compression_error_max": 0.6,
                "residual_norm_mean": 1.0, "residual_norm_min": 0.9,
                "residual_norm_max": 1.1, "skew_max": 0.42, "skew_rank": 6})
    sink.write({"event": "watch_anomaly", "step": 5, "kind": "skew",
                "metric": "compression_error", "rank": 6, "score": 9.5,
                "threshold": 6.0, "value": 0.2})
    sink.write({"event": "guard_skip", "step": 5, "notfinite_count": 1})
    sink.close()

    assert report.main([str(path)]) == 0
    text = capsys.readouterr().out
    assert "== watch" in text
    assert "worst compression-error skew: 0.42 (rank 6" in text
    assert "skew/compression_error (rank 6)" in text
    # watch events never leak into the guard section
    assert "watch_anomaly" not in text.split("== guard events")[1]

    assert report.main([str(path), "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["records"] == 6
    assert doc["metrics"]["watch_bytes"]["max"] == 84.0
    assert len(doc["watch_summaries"]) == 1
    assert doc["watch_anomalies"][0]["rank"] == 6
    assert [e["event"] for e in doc["guard_events"]] == ["guard_skip"]


# ---------------------------------------------------------------------------
# JSONLSink hardening (satellite)
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.telemetry
def test_jsonl_sink_retries_transient_oserror(tmp_path):
    path = tmp_path / "r.jsonl"
    sink = JSONLSink(path)
    sink.write({"step": 0})
    real_file = sink._file
    fails = {"n": 0}

    class Flaky:
        def write(self, s):
            if fails["n"] == 0:
                fails["n"] += 1
                raise OSError("transient NFS blip")
            return real_file.write(s)

        def __getattr__(self, name):
            return getattr(real_file, name)

    sink._file = Flaky()
    sink.write({"step": 1})
    sink._file = real_file
    sink.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert {"step": 0} in lines and {"step": 1} in lines
    assert fails["n"] == 1


@pytest.mark.watch
@pytest.mark.telemetry
def test_jsonl_sink_fsyncs_on_close(tmp_path, monkeypatch):
    import grace_tpu.telemetry.sinks as sinks_mod

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(sinks_mod.os, "fsync",
                        lambda fd: synced.append(fd) or real_fsync(fd))
    sink = JSONLSink(tmp_path / "s.jsonl")
    sink.write({"step": 0})
    sink.close()
    assert synced, "close() must fsync so a preempted run never loses " \
                   "flushed-but-unsynced records"
    sink.close()                                   # idempotent


# ---------------------------------------------------------------------------
# chaos_smoke --watch (CI wiring)
# ---------------------------------------------------------------------------

@pytest.mark.watch
@pytest.mark.chaos
def test_chaos_smoke_watch_names_drifting_rank_before_any_guard_event(
        tmp_path):
    """The acceptance artifact: a sharded (world=8) run with a seeded
    single-rank compression-error drift must contain a watch_anomaly
    naming that rank, emitted before any guard event exists (here: the
    guard stays entirely silent — the point of the scenario)."""
    smoke = _load_tool("chaos_smoke")
    out = tmp_path / "watch_telemetry.jsonl"
    rc = smoke.main(["--watch", "--watch-rank", "5", "--steps", "30",
                     "--batch", "16", "--watch-window", "5",
                     "--telemetry-out", str(out),
                     "--telemetry-every", "10"])
    assert rc == 0

    lines = [json.loads(l) for l in out.read_text().splitlines()]
    anomalies = [l for l in lines if l.get("event") == "watch_anomaly"]
    assert anomalies, "no watch_anomaly in the artifact"
    skews = [a for a in anomalies if a["kind"] == "skew"]
    assert {a["rank"] for a in skews} == {5}
    assert min(a["step"] for a in skews) <= 5      # within one window
    guard_events = [l for l in lines
                    if str(l.get("event", "")).startswith("guard")
                    and l.get("event") != "guard_only"]
    assert not guard_events, "guard fired on a finite drift"
    # The timeline tells the same story end-to-end.
    tl = Timeline.from_jsonl(str(out))
    s = tl.summary()
    assert s["anomalous_ranks"] == [5] and s["first_anomaly_step"] <= 5
