"""Factory coverage: every reference registry string builds (SURVEY.md §2.6)."""

import pytest

from grace_tpu import comm
from grace_tpu import compressors as C
from grace_tpu import memories as M
from grace_tpu.helper import grace_from_params

ALL_COMPRESSORS = ["none", "fp16", "bf16", "topk", "cyclictopk", "randomk",
                   "threshold", "qsgd", "terngrad", "signsgd", "signum",
                   "efsignsgd", "onebit", "natural", "dgc", "powersgd",
                   "u8bit", "sketch", "adaq", "inceptionn"]
ALL_MEMORIES = ["none", "residual", "efsignsgd", "dgc", "powersgd"]
ALL_COMMUNICATORS = ["allreduce", "allgather", "broadcast", "identity",
                     "twoshot", "ring", "rscatter", "hier",
                     "sign_allreduce"]


@pytest.mark.parametrize("name", ALL_COMPRESSORS)
def test_every_compressor_buildable(name):
    grc = grace_from_params({"compressor": name})
    assert grc.compressor is not None


@pytest.mark.parametrize("name", ALL_MEMORIES)
def test_every_memory_buildable(name):
    grc = grace_from_params({"memory": name})
    assert grc.memory is not None


@pytest.mark.parametrize("name", ALL_COMMUNICATORS)
def test_every_communicator_buildable(name):
    grc = grace_from_params({"communicator": name})
    assert grc.communicator is not None


def test_unknown_names_raise():
    for key in ["compressor", "memory", "communicator"]:
        with pytest.raises(ValueError):
            grace_from_params({key: "nope"})


def test_hyperparams_threaded():
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.07,
                             "memory": "residual", "beta": 0.5,
                             "communicator": "allgather",
                             "axis_name": "dp"})
    assert grc.compressor.compress_ratio == 0.07
    assert grc.memory.beta == 0.5
    assert grc.communicator.axis_name == "dp"
    assert isinstance(grc.communicator, comm.Allgather)


def test_reference_keys_accepted():
    # the reference schema keys pass through / are ignored where meaningless
    grc = grace_from_params({"compressor": "powersgd", "compress_rank": 3,
                             "memory": "powersgd", "world_size": 64})
    assert grc.compressor.rank == 3
    assert isinstance(grc.memory, M.PowerSGDMemory)


def test_none_compressor_positional_misuse_rejected():
    with pytest.raises(TypeError):
        C.NoneCompressor(0.005)  # reference bug: silently set average=0.005
