"""graft-flow: the dependence-graph layer and its three passes (ISSUE 9).

Same contract as test_analysis.py: the registered matrix must audit CLEAN
with the new passes enabled (covered there via AUDIT_CONFIGS — this file
adds the numbers those audits are built on), and every new alarm must be
proven LIVE on a deliberately seeded bad graph: a serialized bucket chain,
a W=4096 fp16 hop-sum, a hand-rolled bf16 vote past 256 ranks, an
undersized index dtype, a broken bit-packer, a replicated O(W) buffer, and
a state traced under a different config than the one audited.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import optax
import pytest
from jax import lax

from grace_tpu.analysis import (build_depgraph, build_grace, footprint_model,
                                footprint_report, overlap_summary,
                                pass_memory_footprint, pass_numeric_safety,
                                pass_overlap_schedulability, trace_fn,
                                trace_update)
from grace_tpu.analysis import flow
from grace_tpu.analysis.configs import AUDIT_CONFIGS, audit_config
from grace_tpu.comm import vote_exact_max_world
from grace_tpu.telemetry.scopes import STAGE_EXCHANGE, trace_stage

pytestmark = pytest.mark.analysis

X64 = jax.ShapeDtypeStruct((64,), jnp.float32)


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _exchange(fn):
    """Wrap a traced body in the exchange stage scope, the vocabulary the
    chain counting keys on."""
    def wrapped(*args):
        with trace_stage(STAGE_EXCHANGE):
            return fn(*args)
    return wrapped


def _topk_grace(**extra):
    params = {"compressor": "topk", "compress_ratio": 0.3,
              "memory": "residual", "communicator": "allgather", **extra}
    return build_grace({"name": "x", "params": params})


# ---------------------------------------------------------------------------
# the dependence graph itself
# ---------------------------------------------------------------------------

def test_depgraph_ancestor_closure():
    """c = psum(a); d = c + b: the psum is an ancestor of the add, the
    add is not an ancestor of the psum, and the add's gradient roots
    cover both inputs while the psum's cover only the first."""

    def f(a, b):
        c = lax.psum(a * 2.0, "data")
        return c + b * 3.0

    t = trace_fn(f, [X64, X64], name="dep")
    g = build_depgraph(t)
    colls = [n for n in g.nodes if n.collective]
    assert len(colls) == 1
    psum = colls[0]
    adds = [n for n in g.nodes if n.prim == "add"]
    assert adds, "no add node"
    final = adds[-1]
    assert g.is_ancestor(psum.idx, final.idx)
    assert not g.is_ancestor(final.idx, psum.idx)
    assert g.n_grad_roots == 2
    assert psum.roots == 0b01                 # only arg a
    assert final.roots == 0b11                # both args


def test_depgraph_flattens_cond_branches():
    """Equations inside cond branches join the global graph and the cond's
    outputs carry their dependence."""

    def f(x, flag):
        y = lax.cond(flag, lambda o: lax.psum(o, "data"),
                     lambda o: o * 2.0, x)
        return y + 1.0

    t = trace_fn(f, [X64, jax.ShapeDtypeStruct((), jnp.bool_)], name="cond")
    g = build_depgraph(t)
    colls = [n for n in g.nodes if n.collective]
    assert len(colls) == 1                    # the branch psum is a node
    final_add = [n for n in g.nodes if n.prim == "add"][-1]
    assert g.is_ancestor(colls[0].idx, final_add.idx)


# ---------------------------------------------------------------------------
# pass 5: overlap schedulability
# ---------------------------------------------------------------------------

def test_serialized_bucket_graph_fires():
    """THE seeded-bad graph: bucket 2's exchange consumes bucket 1's
    result, so the two promised chains collapse into one serialized
    sequence — the scheduler can never overlap them."""

    def serialized(a, b):
        s1 = lax.psum(a * 2.0, "data")
        return lax.psum(s1 + b, "data")

    t = trace_fn(_exchange(serialized), [X64, X64], name="serialized",
                 meta={"expected_chains": 2})
    s = overlap_summary(t)
    assert s["exchange_collectives"] == 2
    assert s["independent_chains"] == 1
    findings = pass_overlap_schedulability(t)
    assert len(findings) == 1
    assert findings[0].severity == "error"
    assert "serialization point" in findings[0].message
    assert findings[0].stage == STAGE_EXCHANGE


def test_independent_bucket_graph_clean():
    def parallel(a, b):
        return lax.psum(a * 2.0, "data") + lax.psum(b * 3.0, "data")

    t = trace_fn(_exchange(parallel), [X64, X64], name="parallel",
                 meta={"expected_chains": 2})
    assert overlap_summary(t)["independent_chains"] == 2
    assert pass_overlap_schedulability(t) == []


def test_static_overlap_bound_zero_when_everything_chains():
    """All compute feeds the collective or consumes its result: nothing is
    schedulable under the exchange, bound == 0."""

    def chained(x):
        y = x * 2.0 + 1.0
        s = lax.psum(y, "data")
        return s * 3.0

    t = trace_fn(chained, [X64], name="chained")
    assert overlap_summary(t)["static_overlap_bound"] == 0.0


def test_static_overlap_bound_positive_with_independent_compute():
    """A second, data-independent compute chain big enough to hide the
    collective pushes the bound to 1."""

    def overlappable(x, z):
        s = lax.psum(x, "data")
        busy = jnp.tanh(z * 2.0) + jnp.tanh(z * 3.0)   # independent of s
        return s, busy

    t = trace_fn(overlappable, [X64, X64], name="overlappable")
    s = overlap_summary(t)
    assert s["static_overlap_bound"] == 1.0
    per = s["per_collective"][0]
    assert per["independent_compute_bytes"] > 0


def test_measured_overlap_exceeding_static_bound_fires():
    """graft-prof reporting more overlap than the dataflow permits means
    the attribution is lying — flagged, with both numbers emitted."""

    def chained(x):
        return lax.psum(x * 2.0, "data") * 3.0

    t = trace_fn(chained, [X64], name="lying-profile",
                 meta={"measured_overlap": 0.8})
    findings = pass_overlap_schedulability(t)
    assert len(findings) == 1
    d = dict(findings[0].details)
    assert d["measured_overlap"] == 0.8
    assert d["static_overlap_bound"] == 0.0
    # measured within the bound is fine
    t2 = trace_fn(chained, [X64], name="honest-profile",
                  meta={"measured_overlap": 0.0})
    assert pass_overlap_schedulability(t2) == []


def test_bucketed_registry_config_exposes_two_chains():
    """The registered fusion=1024 config: the bucketing plan splits the
    default params into 2 buckets and the traced graph must expose (at
    least) 2 independent compress→exchange chains — the contract ROADMAP
    item 2's chunked bucket scheduling builds on."""
    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "topk-allgather-bucketed")
    grace = build_grace(entry)
    t = trace_update(grace, name=entry["name"], meta={"grace": grace})
    s = overlap_summary(t)
    assert flow._expected_chains(t) == 2
    # EXACTLY the plan's K (chain heads group by gradient-root set, so the
    # two-tensor top-k payload is one chain per bucket, not two).
    assert s["independent_chains"] == 2
    assert pass_overlap_schedulability(t) == []


def test_pipelined_ring_registry_config_exposes_pipeline_chains():
    """ACCEPTANCE (ISSUE 19): the registered double-buffered packed ring
    (pipeline=2) promises — and the traced graph exposes — 2 independent
    collective chains, one per grace/pipeline/<p> segment; the serial
    twin exposes 1. This chain count is the static referee behind the
    tuner's wire_pipeline discount."""
    entry = next(e for e in AUDIT_CONFIGS
                 if e["name"] == "qsgd2-ring-packed-pipelined")
    grace = build_grace(entry)
    assert grace.communicator.pipeline == 2
    t = trace_update(grace, name=entry["name"], meta={"grace": grace})
    assert flow._expected_chains(t) == 2
    assert overlap_summary(t)["independent_chains"] == 2
    assert pass_overlap_schedulability(t) == []
    # the serial twin of the same codec exposes a single chain
    serial = build_grace({"name": "serial",
                          "params": {**dict(entry["params"]),
                                     "pipeline": 1}})
    t1 = trace_update(serial, name="serial", meta={"grace": serial})
    assert overlap_summary(t1)["independent_chains"] == 1


# ---------------------------------------------------------------------------
# pass 6: numeric-range safety
# ---------------------------------------------------------------------------

def test_fp16_hop_sum_overflows_at_large_world():
    """THE seeded-bad graph: a W=4096 fp16 payload sum saturates the 65504
    cliff (4096 terms x 256 magnitude budget >> finfo(f16).max) with no
    NaN for the guard to see."""

    def f16sum(x):
        return lax.psum(x.astype(jnp.float16), "data")

    t = trace_fn(f16sum, [X64], world=4096, name="f16-hop-4096")
    findings = pass_numeric_safety(t)
    assert len(findings) == 1
    d = dict(findings[0].details)
    assert d["dtype"] == "float16" and d["terms"] == 4096
    assert "overflows to inf" in findings[0].message
    # same graph at world 8: 8 terms, comfortably inside the budget
    assert pass_numeric_safety(
        trace_fn(f16sum, [X64], world=8, name="f16-hop-8")) == []
    # bfloat16 has no overflow cliff: clean at any audited W
    assert pass_numeric_safety(trace_fn(
        lambda x: lax.psum(x.astype(jnp.bfloat16), "data"),
        [X64], world=4096, name="bf16-hop-4096")) == []


def test_safe_sum_terms_derivation():
    assert flow.safe_sum_terms(jnp.float16) == int(65504 / 256)
    assert flow.safe_sum_terms(jnp.bfloat16) > 10 ** 30
    assert flow.safe_sum_terms(jnp.int32) is None


def test_vote_exact_max_world_rederives_256_from_first_principles():
    """The bf16-vote 256 bound is not folklore: p explicit mantissa bits
    represent integers exactly up to 2^(p+1), and a W-rank vote tally
    lives in [-W, W]."""
    assert vote_exact_max_world("bfloat16") \
        == 2 ** (jnp.finfo(jnp.bfloat16).nmant + 1) == 256
    assert vote_exact_max_world("float16") == 2048
    assert vote_exact_max_world("float32") == 2 ** 24
    with pytest.raises(TypeError):
        vote_exact_max_world(jnp.int32)


def test_runtime_vote_guard_reads_the_same_constant():
    """The comm-level runtime check and the static pass read ONE constant:
    tracing the psum-vote communicator past the bound raises with the
    function's name in the message (surfaced as a trace finding by the
    registry machinery)."""
    findings = audit_config(
        {"name": "vote-512",
         "params": {"compressor": "signsgd", "memory": "none",
                    "communicator": "sign_allreduce"}}, world=512)
    assert len(findings) == 1 and findings[0].pass_name == "trace"
    assert "vote_exact_max_world" in findings[0].message


def test_hand_rolled_vote_psum_past_bound_fires_statically():
    """A vote psum that bypasses the communicator's runtime guard (the
    hand-rolled case) is still caught by the static pass via the
    psum_vote trace scope."""

    def vote(x):
        with trace_stage(f"{STAGE_EXCHANGE}/psum_vote"):
            return lax.psum(x.astype(jnp.bfloat16), "data")

    t = trace_fn(vote, [X64], world=512, name="vote-512")
    findings = pass_numeric_safety(t)
    assert len(findings) == 1
    assert dict(findings[0].details)["exact_max_world"] == 256
    assert pass_numeric_safety(
        trace_fn(vote, [X64], world=256, name="vote-256")) == []


def test_undersized_index_dtype_fires():
    """A selection codec shipping int16 indices for a 100k-element fused
    leaf: positions past 32767 wrap on decode."""
    from grace_tpu.core import Compressor

    @dataclasses.dataclass(frozen=True)
    class NarrowTopK(Compressor):
        summable_payload = False
        supports_hop_requant = False

        def compress(self, x, state, rng):
            k = 16
            idx = jnp.argsort(-jnp.abs(x))[:k].astype(jnp.int16)
            return (x[:k], idx), (x.size, x.shape, x.dtype), state

        def decompress(self, payload, ctx):
            values, idx = payload
            n, shape, dtype = ctx
            return jnp.zeros((n,), dtype).at[idx.astype(jnp.int32)].set(
                values).reshape(shape)

    base = _topk_grace()
    grace = dataclasses.replace(base, compressor=NarrowTopK())
    big = {"w": jax.ShapeDtypeStruct((100_000,), jnp.float32)}
    t = trace_update(grace, params=big, name="narrow-idx",
                     meta={"grace": grace, "param_structs": big})
    findings = pass_numeric_safety(t)
    assert len(findings) == 1
    assert "int16 index payload" in findings[0].message
    # the real TopK (int32 indices) on the same leaf is clean
    t2 = trace_update(base, params=big, name="wide-idx",
                      meta={"grace": base, "param_structs": big})
    assert pass_numeric_safety(t2) == []


def test_broken_bit_packer_fires():
    """Injected 3-codes-per-byte 'pack_bits': in-range codes truncate."""

    def bad_pack(bits):
        n = bits.shape[0]
        nbytes = -(-n // 3)                       # wrong lane count
        padded = jnp.zeros((nbytes * 3,), jnp.uint8).at[:n].set(
            bits.astype(jnp.uint8))
        return jnp.sum(padded.reshape(nbytes, 3), axis=1, dtype=jnp.uint8)

    from grace_tpu.ops.packing import unpack_bits

    grace = build_grace({"name": "x",
                         "params": {"compressor": "signsgd",
                                    "memory": "none",
                                    "communicator": "allgather"}})
    t = trace_update(grace, name="bad-pack", meta={"grace": grace})
    findings = flow._packing_findings(
        t, pack_fns=((1, bad_pack, unpack_bits),))
    assert findings and all("ops/packing" in f.message for f in findings)
    # the shipped packers hold their declared widths
    assert flow._packing_findings(t) == []


@pytest.mark.parametrize("width", [2, 3, 4])
def test_bad_packer_fires_at_every_subbyte_width(width):
    """The pass-6 packer audit is live at the NEW widths too: an injected
    packer that truncates the top bit of ``width``-bit codes (declares
    the width, packs width-1) corrupts in-range codes and must fire for
    each of 2/3/4 — the widths QSGD/homoqsgd select via pack_width."""
    from grace_tpu.ops.packing import pack_widths

    good = {w: (p, u) for w, p, u in pack_widths()}
    narrow_pack, _ = good[width - 1]
    _, wide_unpack = good[width]

    def truncating_pack(codes):
        # drop the MSB, pack at width-1: ceil(n*(width-1)/8) bytes — both
        # the byte-count contract and the round-trip break
        return narrow_pack(codes & jnp.uint8((1 << (width - 1)) - 1))

    grace = build_grace({"name": "x",
                         "params": {"compressor": "qsgd", "quantum_num": 7,
                                    "memory": "none",
                                    "communicator": "allgather"}})
    t = trace_update(grace, name=f"bad-{width}bit", meta={"grace": grace})
    findings = flow._packing_findings(
        t, pack_fns=((width, truncating_pack, wide_unpack),))
    assert findings
    assert all("ops/packing" in f.message and f"{width}-bit" in f.message
               for f in findings)


def test_packing_check_only_runs_for_packed_payloads():
    """fp16 ships no sub-byte payload — no packing findings regardless."""
    grace = build_grace({"name": "x", "params": {"compressor": "fp16",
                                                 "memory": "none",
                                                 "communicator":
                                                 "allreduce"}})
    t = trace_update(grace, name="fp16", meta={"grace": grace})

    def exploding_pack(bits):                     # must never be called
        raise AssertionError("packing check ran for an unpacked codec")

    assert flow._packing_findings(
        t, pack_fns=((1, exploding_pack, exploding_pack),)) == []


# ---------------------------------------------------------------------------
# pass 7: HBM footprint
# ---------------------------------------------------------------------------

def test_footprint_model_matches_live_world8_state(mesh):
    """ACCEPTANCE: the pass's model equals grace_state_footprint on the
    live world=8 chaos_smoke-shaped state (topk + residual + escape +
    telemetry, sharded over the 8-device mesh)."""
    from grace_tpu.profiling import grace_state_footprint
    from grace_tpu.train import init_train_state

    grace = build_grace({"name": "smoke",
                         "params": {"compressor": "topk",
                                    "compress_ratio": 0.3,
                                    "memory": "residual",
                                    "communicator": "allgather",
                                    "escape": "fp16", "telemetry": 32}})
    tx = optax.chain(grace.transform(seed=0), optax.sgd(0.1))
    params = {"w": jnp.zeros((32, 16)), "b": jnp.zeros((16,))}
    state = init_train_state(params, tx, mesh)
    live = grace_state_footprint(state.opt_state)
    model = footprint_model(grace, params, world=8)
    for key in ("mem_bytes", "comp_bytes", "telem_bytes", "total_bytes"):
        assert live[key] == model[key], key


def test_footprint_report_groups_match_the_model():
    from grace_tpu.analysis.trace import default_param_structs

    grace = _topk_grace(telemetry=16)
    t = trace_update(grace, name="fp", meta={"grace": grace})
    rep = footprint_report(t)
    model = footprint_model(grace, default_param_structs())
    for key in ("mem_bytes", "comp_bytes", "telem_bytes"):
        assert rep[key] == model[key], key
    assert rep["wire_peak_bytes"] > 0            # the gathered (W, k) stack
    assert rep["wire_total_bytes"] >= rep["wire_peak_bytes"]
    assert rep["n_collectives"] >= 2             # values + indices gathers


def test_state_traced_under_different_config_fires():
    ga = _topk_grace(telemetry=4)
    gb = _topk_grace(telemetry=64)
    t = trace_update(ga, name="drifted", meta={"grace": gb})
    findings = pass_memory_footprint(t)
    assert len(findings) == 1
    assert "different" in findings[0].message
    assert dict(findings[0].details)["component"] == "telem_bytes"
    assert pass_memory_footprint(
        trace_update(ga, name="same", meta={"grace": ga})) == []


def test_replicated_o_w_buffer_fires():
    """THE seeded-bad graph: a replicated (P()) state buffer shaped (W,)
    — O(W) HBM per rank on every rank."""
    base = _topk_grace()
    world = 8

    class OWGrace:
        communicator = base.communicator
        compressor = base.compressor
        fusion = None

        def transform(self, seed=0):
            tx = base.transform(seed)

            def init(params):
                return tx.init(params)._replace(
                    audit=jnp.zeros((world,), jnp.float32))

            def update(updates, state, params=None):
                out, new = tx.update(updates, state, params)
                return out, new._replace(audit=state.audit)

            return optax.GradientTransformation(init, update)

    t = trace_update(OWGrace(), name="o-w-buffer")
    findings = pass_memory_footprint(t)
    assert len(findings) == 1
    assert "O(W)" in findings[0].message or "O(W²)" in findings[0].message
    assert dict(findings[0].details)["path"] == "audit"


def test_replicated_state_scalars_do_not_fire():
    grace = _topk_grace()
    t = trace_update(grace, name="plain")
    assert [p for p, _ in t.state_replicated]    # count/rng/fallback exist
    assert pass_memory_footprint(t) == []


# ---------------------------------------------------------------------------
# CLI + evidence + smoke wiring
# ---------------------------------------------------------------------------

def test_graft_lint_all_configs_end_to_end(tmp_path, capsys):
    """CI gate: the full registry, all ten passes, exit 0 — a pass
    regression fails pytest, not just the smoke. Evidence lands at the
    given path with per-pass counts for every pass that ran."""
    graft_lint = _load_tool("graft_lint")
    evidence = tmp_path / "LINT_LAST.json"
    assert graft_lint.main(["--all-configs",
                            "--evidence", str(evidence)]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out
    doc = json.loads(evidence.read_text())
    assert doc["errors"] == 0
    assert set(doc["passes_run"]) == {
        "collective_consistency", "bit_exactness", "wire_reconciliation",
        "signature_stability", "overlap_schedulability", "numeric_safety",
        "memory_footprint", "rng_lineage", "rollback_coverage",
        "replication_contract"}
    assert all(v == 0 for v in doc["pass_counts"].values())
    assert doc["configs_audited"] == len(AUDIT_CONFIGS)
    # The static half of the overlap sandwich rides the evidence: every
    # bucketed (fusion=<int>) update-mode config records its bound and its
    # chain counts, and the executor delivers exactly the promised K.
    assert "topk-allgather-bucketed" in doc["overlap_bounds"]
    assert "qsgd4-ring-packed-bucketed" in doc["overlap_bounds"]
    for rep in doc["overlap_bounds"].values():
        assert rep["independent_chains"] == rep["expected_chains"]
        assert rep["static_overlap_bound"] is not None


def test_graft_lint_passes_selection(tmp_path, capsys):
    graft_lint = _load_tool("graft_lint")
    assert graft_lint.main(["--config", "fp16-allreduce", "--no-rules",
                            "--passes", "numeric_safety"]) == 0
    assert graft_lint.main(["--passes", "not_a_pass"]) == 2


def test_new_finding_kinds_render_in_telemetry_report(tmp_path):
    """The unified-timeline satellite: schedulability/numeric/footprint
    findings written as lint_finding events render with their stage
    attribution, like guard/consensus events do."""
    from grace_tpu.analysis import write_jsonl

    def serialized(a, b):
        s1 = lax.psum(a * 2.0, "data")
        return lax.psum(s1 + b, "data")

    t = trace_fn(_exchange(serialized), [X64, X64], name="ser",
                 meta={"expected_chains": 2, "measured_overlap": 0.9})
    findings = pass_overlap_schedulability(t)
    t16 = trace_fn(lambda x: lax.psum(x.astype(jnp.float16), "data"),
                   [X64], world=4096, name="f16")
    findings += pass_numeric_safety(t16)
    ga, gb = _topk_grace(telemetry=4), _topk_grace(telemetry=64)
    findings += pass_memory_footprint(
        trace_update(ga, name="drift", meta={"grace": gb}))
    assert {f.pass_name for f in findings} == {
        "overlap_schedulability", "numeric_safety", "memory_footprint"}

    path = tmp_path / "lint.jsonl"
    write_jsonl(findings, str(path), provenance={"tool": "graft_lint"})
    telemetry_report = _load_tool("telemetry_report")
    provenance, records, events = telemetry_report.load(str(path))
    rendered = telemetry_report.render(provenance, records, events)
    assert "lint_finding" in rendered
    for kind in ("overlap_schedulability", "numeric_safety",
                 "memory_footprint"):
        assert kind in rendered
    assert f"[{STAGE_EXCHANGE}]" in rendered      # stage attribution
    doc = telemetry_report.build_doc(provenance, records, events)
    assert len(doc["lint_findings"]) == len(findings)
    assert doc["guard_events"] == []


def test_evidence_summary_renders_per_pass_counts(tmp_path, monkeypatch):
    ev = _load_tool("evidence_summary")
    monkeypatch.setattr(ev, "ROOT", str(tmp_path))
    (tmp_path / "LINT_LAST.json").write_text(json.dumps({
        "tool": "graft_lint", "errors": 0, "warnings": 0,
        "configs_audited": 45, "rules_checked": 3,
        "passes_run": ["a", "b"], "pass_counts": {"a": 0, "b": 0},
        "captured_at": "2026-08-04T00:00:00+00:00"}))
    md = ev.build()
    assert "all 2 passes clean" in md
    (tmp_path / "LINT_LAST.json").write_text(json.dumps({
        "tool": "graft_lint", "errors": 2, "warnings": 0,
        "configs_audited": 45, "rules_checked": 3,
        "pass_counts": {"a": 0, "numeric_safety": 2}}))
    assert "numeric_safety 2" in ev.build()


def test_chaos_smoke_lint_gate_runs_flow_passes(tmp_path, monkeypatch):
    """chaos_smoke --lint audits its own config with the graft-flow AND
    graft-sound passes before any step runs (clean here — the artifact
    stays free of lint_finding events)."""
    import grace_tpu.analysis as analysis
    smoke = _load_tool("chaos_smoke")
    audited = {}
    real_audit = analysis.audit_config

    def spy(entry, *a, **kw):
        audited["passes"] = tuple(entry["passes"])
        return real_audit(entry, *a, **kw)

    # chaos_smoke imports audit_config at gate time, so the module
    # attribute is the seam.
    monkeypatch.setattr(analysis, "audit_config", spy)
    out = tmp_path / "smoke.jsonl"
    rc = smoke.main(["--steps", "8", "--nan-prob", "1.0", "--batch", "16",
                     "--fallback-after", "2", "--fallback-steps", "4",
                     "--lint", "--telemetry-out", str(out),
                     "--telemetry-every", "4"])
    assert rc == 0
    # the smoke's own guarded config must prove its stateful semantics,
    # not just its collective/flow properties
    assert {"rng_lineage", "rollback_coverage",
            "replication_contract"} <= set(audited["passes"])
    # clean gate: no lint_finding events in the artifact
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert not [l for l in lines if l.get("event") == "lint_finding"]
