"""Model zoo tests: shapes, state threading, and end-to-end compressed training.

The reference's only QA was examples-as-smoke-tests (SURVEY.md §4); here the
same coverage is a real test suite: forward shapes for each model, BN state
updates, and a convergence check of the FULL grace pipeline (topk + residual
+ allgather over the 8-device mesh) on a separable toy problem.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.models import lenet, resnet, resnet_cifar, transformer
from grace_tpu.parallel import batch_sharded
from grace_tpu.train import (init_stateful_train_state,
                             make_stateful_train_step)


def test_lenet_forward():
    params, state = lenet.init(jax.random.key(0))
    x = jnp.zeros((4, 28, 28, 1))
    logits, _ = lenet.apply(params, state, x)
    assert logits.shape == (4, 10)
    assert jnp.all(jnp.isfinite(logits))


def test_resnet_cifar_forward_and_state():
    params, state = resnet_cifar.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    logits, new_state = resnet_cifar.apply(params, state, x, train=True)
    assert logits.shape == (2, 10)
    # BN running stats must move in train mode…
    moved = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.any(a != b)), state, new_state)
    assert any(jax.tree_util.tree_leaves(moved))
    # …and stay fixed in eval mode.
    logits_e, state_e = resnet_cifar.apply(params, new_state, x, train=False)
    same = jax.tree_util.tree_map(
        lambda a, b: bool(jnp.all(a == b)), new_state, state_e)
    assert all(jax.tree_util.tree_leaves(same))
    assert jnp.all(jnp.isfinite(logits_e))


def test_resnet50_forward_tiny():
    params, state = resnet.init(jax.random.key(0), depth=50, num_classes=10)
    x = jax.random.normal(jax.random.key(1), (2, 64, 64, 3))
    logits, _ = resnet.apply(params, state, x, train=True)
    assert logits.shape == (2, 10)
    assert jnp.all(jnp.isfinite(logits))


def test_transformer_forward_and_mlm():
    cfg = transformer.tiny()
    params, state = transformer.init(jax.random.key(0), cfg)
    ids = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    mask = jnp.ones((2, 16), bool)
    logits, _ = transformer.apply(params, state, ids, cfg=cfg, mask=mask)
    assert logits.shape == (2, cfg.num_classes)
    mlm = transformer.mlm_logits(params, ids, cfg, mask)
    assert mlm.shape == (2, 16, cfg.vocab_size)
    assert jnp.all(jnp.isfinite(mlm))


def test_transformer_bf16_matches_shape():
    cfg = transformer.tiny()
    params, state = transformer.init(jax.random.key(0), cfg)
    ids = jnp.zeros((2, 8), jnp.int32)
    logits, _ = transformer.apply(params, state, ids, cfg=cfg,
                                  dtype=jnp.bfloat16)
    assert logits.dtype == jnp.float32  # head always computes fp32
    assert jnp.all(jnp.isfinite(logits))


@pytest.mark.parametrize("grace_params", [
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "allgather"},
    {"compressor": "none", "memory": "none", "communicator": "allreduce"},
])
def test_end_to_end_compressed_training(mesh, grace_params):
    """LeNet on a separable toy problem: loss must drop under compression."""
    params, mstate = lenet.init(jax.random.key(0))
    grace = grace_from_params(grace_params)
    optimizer = optax.chain(grace.transform(seed=0), optax.sgd(0.05))

    # Separable synthetic "digits": class mean patterns + noise.
    rng = np.random.default_rng(0)
    protos = rng.standard_normal((10, 28, 28, 1)).astype(np.float32)
    y = np.tile(np.arange(10), 8)[:64]
    x = protos[y] + 0.1 * rng.standard_normal((64, 28, 28, 1)).astype(np.float32)
    batch = jax.device_put((jnp.asarray(x), jnp.asarray(y)),
                           batch_sharded(mesh))

    def loss_fn(params, mstate, batch):
        xb, yb = batch
        logits, new_mstate = lenet.apply(params, mstate, xb)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    step = make_stateful_train_step(loss_fn, optimizer, mesh)
    ts = init_stateful_train_state(params, mstate, optimizer, mesh)

    ts, first = step(ts, batch)
    for _ in range(30):
        ts, loss = step(ts, batch)
    assert jnp.isfinite(loss)
    assert float(loss) < float(first) * 0.5, (first, loss)


def test_vgg_forward_and_state():
    from grace_tpu.models import vgg
    params, state = vgg.init(jax.random.key(0), depth=11, num_classes=7)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    logits, new_state = vgg.apply(params, state, x, train=True)
    assert logits.shape == (2, 7)
    # BN state updated in train mode
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(state),
                        jax.tree_util.tree_leaves(new_state)))
    assert changed
    # eval mode: state passes through untouched
    _, eval_state = vgg.apply(params, new_state, x, train=False)
    for a, b in zip(jax.tree_util.tree_leaves(new_state),
                    jax.tree_util.tree_leaves(eval_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_vgg_adaptive_pool_matches_torchvision():
    """_adaptive_avg_pool must reproduce torch AdaptiveAvgPool2d((7,7))
    bit-for-bit semantics at every regime: true pooling (h>7, divisible or
    not), identity (h=7), and cell duplication (h<7 — where the former
    bilinear-resize implementation diverged; ADVICE round-2 item 3)."""
    torch = pytest.importorskip("torch")
    from grace_tpu.models.vgg import _adaptive_avg_pool
    rng = np.random.default_rng(0)
    for h in (1, 3, 5, 7, 10, 14, 21):
        x = rng.standard_normal((2, h, h, 4)).astype(np.float32)
        got = np.asarray(_adaptive_avg_pool(jnp.asarray(x), 7))
        want = torch.nn.AdaptiveAvgPool2d((7, 7))(
            torch.from_numpy(x.transpose(0, 3, 1, 2)))
        want = want.numpy().transpose(0, 2, 3, 1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=f"h={h}")


def test_vgg_depth_recovery_and_no_bn():
    from grace_tpu.models import vgg
    params, state = vgg.init(jax.random.key(1), depth=13, num_classes=3,
                             batch_norm=False)
    assert state == {}
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    logits, _ = vgg.apply(params, state, x, train=True)  # depth inferred
    assert logits.shape == (1, 3)
