"""graft-lint: the static auditor and repo rule engine (ISSUE 5).

Device-free by construction — everything traces over an AbstractMesh, so
these tests never touch the 8-device fixture. Two halves:

* the full registered compat matrix must audit CLEAN (the CI gate that
  locks the invariants PRs 1-4 established by hand);
* deliberately seeded bad graphs/sources must make each pass and each repo
  rule FIRE — an auditor is only evidence if its alarms are proven live.
"""

import json

import jax
import jax.numpy as jnp
import optax
import pytest
from jax import lax

from grace_tpu import comm
from grace_tpu.analysis import (AUDIT_CONFIGS, audit_config, build_grace,
                                run_repo_rules, trace_fn, trace_update,
                                write_jsonl)
from grace_tpu.analysis.passes import (count_recv_bytes,
                                       pass_bit_exactness,
                                       pass_collective_consistency,
                                       pass_signature_stability,
                                       pass_wire_reconciliation)
from grace_tpu.analysis.rules import registered_markers, repo_root
from grace_tpu.analysis.trace import default_param_structs
from grace_tpu.transform import fusion_payload_nbytes

pytestmark = pytest.mark.analysis

X64 = jax.ShapeDtypeStruct((64,), jnp.float32)


# ---------------------------------------------------------------------------
# the clean gate: the full compat matrix audits green
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("entry", AUDIT_CONFIGS,
                         ids=[e["name"] for e in AUDIT_CONFIGS])
def test_registered_config_audits_clean(entry):
    findings = audit_config(entry)
    assert findings == [], "\n".join(
        f"{f.pass_name}: {f.message}" for f in findings)


def test_registry_covers_compressor_catalog():
    """Every cataloged codec is audited under at least one communicator."""
    import grace_tpu.compressors as C

    audited = {e["params"]["compressor"] for e in AUDIT_CONFIGS}
    catalog = {"none", "fp16", "topk", "randomk", "threshold", "qsgd",
               "terngrad", "signsgd", "signum", "efsignsgd", "onebit",
               "natural", "dgc", "powersgd", "sketch", "u8bit", "adaq",
               "inceptionn",
               # the aggregation-homomorphic family (ISSUE 13)
               "homoqsgd", "countsketch",
               # the sharded-model track (ISSUE 14): ScaleCom-style
               # cyclic local-selection topk
               "cyclictopk"}
    assert catalog <= audited
    # and the catalog names really are the exported classes
    assert len(C.__all__) == 21


def test_incompatible_config_traces_to_a_finding():
    """A triad the communicators reject (topk+Allreduce: unsummable
    payload) surfaces as a trace finding, never an exception — the lint
    run must survive a broken registry entry and report it."""
    findings = audit_config({"name": "bad-triad",
                             "params": {"compressor": "topk",
                                        "memory": "residual",
                                        "communicator": "allreduce"}})
    assert len(findings) == 1 and findings[0].pass_name == "trace"
    assert "summable" in findings[0].message


# ---------------------------------------------------------------------------
# seeded bad graphs: each pass proven live
# ---------------------------------------------------------------------------

def test_cond_divergent_collective_fires():
    """PASS 1: a psum in one cond branch only, predicate derived from
    rank-varying data — the cross-rank deadlock shape."""

    def bad(x):
        return lax.cond(x.sum() > 0,
                        lambda o: lax.psum(o, "data"),
                        lambda o: o * 2.0, x)

    t = trace_fn(bad, [X64], name="bad-cond")
    findings = pass_collective_consistency(t)
    assert len(findings) == 1
    assert findings[0].severity == "error"
    assert "different collective sequences" in findings[0].message


def test_replicated_predicate_cond_passes():
    """The dense-escape shape: branch-divergent collectives are legal when
    the predicate is replicated (every rank takes the same branch)."""

    def ok(x, flag):
        return lax.cond(flag,
                        lambda o: lax.psum(o, "data"),
                        lambda o: o * 2.0, x)

    t = trace_fn(ok, [X64, jax.ShapeDtypeStruct((), jnp.bool_)],
                 varying=[True, False], name="escape-shape")
    assert pass_collective_consistency(t) == []


def test_replication_regained_through_psum():
    """A predicate derived from rank-varying data THROUGH a full-axis psum
    is replicated again — the guard's OR-reduced bad flag shape."""

    def ok(x):
        any_bad = lax.psum(jnp.any(x > 0).astype(jnp.int32), "data") > 0
        return lax.cond(any_bad,
                        lambda o: lax.psum(o, "data"),
                        lambda o: o * 2.0, x)

    t = trace_fn(ok, [X64], name="guard-shape")
    assert pass_collective_consistency(t) == []


def test_float_checksum_psum_fires():
    """PASS 2: bit-pattern words pushed through a float-space psum — the
    PR-3 ±0.0 aliasing bug class, rebuilt on purpose."""

    def bad(x):
        bits = lax.bitcast_convert_type(x, jnp.uint32)
        return lax.psum(bits.astype(jnp.float32), "data")

    t = trace_fn(bad, [X64], name="bad-checksum")
    findings = pass_bit_exactness(t)
    assert len(findings) == 1
    assert "bit-pattern" in findings[0].message


def test_integer_checksum_psum_clean():
    """The sanctioned masked_broadcast shape: integer-space psum of bit
    words, bitcast back to float afterwards — exactly what PR 3 shipped."""

    def ok(x):
        bits = lax.bitcast_convert_type(x, jnp.uint32)
        summed = lax.psum(jnp.where(lax.axis_index("data") == 0, bits,
                                    jnp.zeros_like(bits)), "data")
        return lax.bitcast_convert_type(summed, jnp.float32)

    t = trace_fn(ok, [X64], name="masked-broadcast-shape")
    assert pass_bit_exactness(t) == []


def test_stale_wire_model_fires():
    """PASS 3: a communicator whose recv_wire_bytes drifted from its real
    collective schedule (here: claims half the bytes) is flagged."""
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class StaleModelAllgather(comm.Allgather):
        def recv_wire_bytes(self, payload_nbytes, n_elems, world,
                            vote=False):
            return payload_nbytes * max(0, world - 1) // 2   # drifted

    base = build_grace({"name": "x",
                        "params": {"compressor": "topk",
                                   "compress_ratio": 0.3,
                                   "memory": "residual",
                                   "communicator": "allgather"}})
    grace = dataclasses.replace(base,
                                communicator=StaleModelAllgather())
    t = trace_update(grace, name="stale-model", meta={"grace": grace})
    findings = pass_wire_reconciliation(t)
    assert len(findings) == 1
    assert "drift" in findings[0].message
    # and the honest model on the same trace reconciles
    t2 = trace_update(base, name="fresh-model", meta={"grace": base})
    assert pass_wire_reconciliation(t2) == []


def test_wire_count_matches_model_exactly_for_allgather():
    """Beyond tolerance: the gather schedule has no rounding, so counted
    == modeled to the byte."""
    grace = build_grace({"name": "x",
                         "params": {"compressor": "topk",
                                    "compress_ratio": 0.3,
                                    "memory": "residual",
                                    "communicator": "allgather"}})
    t = trace_update(grace, name="exact")
    counted = count_recv_bytes(t.body, t.axis_name, t.world)
    _, comp_b, n_elems = fusion_payload_nbytes(
        grace.compressor, list(default_param_structs().values()), None)
    assert counted == grace.communicator.recv_wire_bytes(
        comp_b, n_elems, t.world)


def test_signature_leak_fires():
    """PASS 4: a Python float leaking into the carried step counter turns
    the state signature into a moving target (retrace every step)."""
    base = build_grace({"name": "x",
                        "params": {"compressor": "topk",
                                   "compress_ratio": 0.3,
                                   "memory": "residual",
                                   "communicator": "allgather"}})

    class LeakyGrace:
        communicator = base.communicator

        def transform(self, seed=0):
            tx = base.transform(seed)

            def update(updates, state, params=None):
                out, new_state = tx.update(updates, state, params)
                # the seeded bug: a host scalar promotes count to weak f32
                return out, new_state._replace(count=new_state.count + 1.5)

            return optax.GradientTransformation(tx.init, update)

    t = trace_update(LeakyGrace(), name="leaky")
    findings = pass_signature_stability(t)
    assert any("count" in f.message and "fixed point" in f.message
               for f in findings)


def test_host_callback_fires():
    """PASS 4: jax.debug.print inside the compiled step is a host sync."""

    def bad(x):
        jax.debug.print("sum {}", x.sum())
        return lax.psum(x, "data")

    t = trace_fn(bad, [X64], name="bad-callback")
    findings = pass_signature_stability(t)
    assert len(findings) == 1 and "host callback" in findings[0].message


# ---------------------------------------------------------------------------
# satellite: recv_wire_bytes W=1 / W=2 edge cases
# ---------------------------------------------------------------------------

_COMMUNICATORS = [comm.Allreduce, comm.Allgather, comm.Broadcast,
                  comm.SignAllreduce, comm.TwoShotAllreduce,
                  comm.RingAllreduce, comm.HierarchicalAllreduce,
                  comm.Identity]


@pytest.mark.parametrize("cls", _COMMUNICATORS,
                         ids=[c.__name__ for c in _COMMUNICATORS])
def test_recv_wire_bytes_degenerate_worlds(cls):
    """W=1 (ring degenerates to zero hops) must cost 0 bytes — and never
    divide by zero or go negative; W=2 must be positive for every real
    communicator and bounded by the dense 2-rank exchange."""
    c = cls()
    payload, n = 4096, 1024
    for vote in (False, True):
        assert c.recv_wire_bytes(payload, n, 1, vote=vote) == 0
    two = c.recv_wire_bytes(payload, n, 2)
    assert two >= 0
    if cls is comm.Identity:
        assert two == 0
    else:
        assert 0 < two <= 2 * payload + 4 * n   # ≤ dense-ish upper bound
    # W=0 is nonsensical but must price to 0, not negative: the tuner
    # enumerates degenerate meshes, and a negative byte price would rank
    # the broken config best (the ring-family 2·p·(W-1)/W formulas used
    # to return -2p here before the max(0, W-1) clamp).
    for vote in (False, True):
        assert c.recv_wire_bytes(payload, n, 0, vote=vote) == 0
        lb = c.recv_link_bytes(payload, n, 0, vote=vote)
        assert lb.ici == lb.dcn == 0


def test_hier_slice1_degenerate_worlds():
    """HierarchicalAllreduce(slice_size=1) — every rank its own slice, the
    tuner's most degenerate generated mesh: W<=1 prices to 0 on both links,
    and at W>1 the schedule is pure cross-slice exchange ((W-1)·payload
    partials, no intra-slice hops) — all DCN once a multi-slice topology
    says the axis crosses."""
    from grace_tpu.core import Topology

    c = comm.HierarchicalAllreduce(slice_size=1)
    payload, n = 4096, 1024
    for w in (0, 1):
        for vote in (False, True):
            assert c.recv_wire_bytes(payload, n, w, vote=vote) == 0
            lb = c.recv_link_bytes(payload, n, w,
                                   topology=Topology(slice_size=1),
                                   vote=vote)
            assert lb.ici == lb.dcn == 0
    # W=2, slice_size=1: no intra hops (S-1 == 0), one cross-slice partial.
    assert c.recv_wire_bytes(payload, n, 2) == payload
    lb = c.recv_link_bytes(payload, n, 2, topology=Topology(slice_size=1))
    assert (lb.ici, lb.dcn) == (0, payload)


def test_ring_wire_model_monotone_in_world():
    """2·p·(W-1)/W is increasing and flat-bounded by 2·p — the whole point
    of the ring; a regression here corrupts every bench projection."""
    c = comm.RingAllreduce()
    vals = [c.recv_wire_bytes(8192, 2048, w) for w in (1, 2, 4, 8, 64)]
    assert vals[0] == 0
    assert all(a < b for a, b in zip(vals, vals[1:]))
    assert vals[-1] < 2 * 8192


# ---------------------------------------------------------------------------
# per-link (ici, dcn) wire model — ISSUE 6 prerequisite surgery
# ---------------------------------------------------------------------------

# The PRE-refactor scalar formulas, hardcoded: recv_wire_bytes is now the
# sum of the per-link split, and this pins that the refactor moved ZERO
# bytes — bit-identical for every communicator, world, and vote flag.
_OLD_SCALAR = {
    comm.Allreduce: lambda p, n, w, vote: (
        2 * 2 * n * (w - 1) // max(1, w) if vote
        else 2 * p * (w - 1) // max(1, w)),
    comm.Allgather: lambda p, n, w, vote: p * max(0, w - 1),
    comm.Broadcast: lambda p, n, w, vote: p * max(0, w - 1),
    comm.SignAllreduce: lambda p, n, w, vote:
        2 * 2 * n * (w - 1) // max(1, w),
    comm.TwoShotAllreduce: lambda p, n, w, vote:
        2 * p * (w - 1) // max(1, w),
    comm.RingAllreduce: lambda p, n, w, vote:
        2 * p * (w - 1) // max(1, w),
    # Default-constructed (slice_size=None): one slice, so the two-level
    # schedule — and therefore the model — collapses to the flat ring.
    comm.HierarchicalAllreduce: lambda p, n, w, vote:
        2 * p * (w - 1) // max(1, w),
    comm.Identity: lambda p, n, w, vote: 0,
}


@pytest.mark.parametrize("cls", _COMMUNICATORS,
                         ids=[c.__name__ for c in _COMMUNICATORS])
def test_recv_link_bytes_sums_to_old_scalar_model(cls):
    from grace_tpu.core import Topology

    c = cls()
    payload, n = 8192, 2048
    topologies = (None, Topology(), Topology(slice_size=4),
                  Topology(slice_size=8), Topology(slice_size=1024))
    for w in (1, 2, 4, 8, 64, 256):
        for vote in (False, True):
            old = _OLD_SCALAR[cls](payload, n, w, vote)
            assert c.recv_wire_bytes(payload, n, w, vote=vote) == old
            for topo in topologies:
                lb = c.recv_link_bytes(payload, n, w, topology=topo,
                                       vote=vote)
                assert lb.ici + lb.dcn == old == lb.total, \
                    (cls.__name__, w, vote, topo, lb)


@pytest.mark.parametrize("cls", _COMMUNICATORS,
                         ids=[c.__name__ for c in _COMMUNICATORS])
def test_recv_link_bytes_split_semantics(cls):
    """Flat schedules: all-ICI within one slice, all-DCN once the axis
    crosses a slice boundary (the critical rank's incoming link)."""
    from grace_tpu.core import Topology

    c = cls()
    payload, n, w = 8192, 2048, 64
    inside = c.recv_link_bytes(payload, n, w,
                               topology=Topology(slice_size=64))
    assert inside.dcn == 0
    crossing = c.recv_link_bytes(payload, n, w,
                                 topology=Topology(slice_size=8))
    assert crossing.ici == 0
    assert crossing.dcn == inside.ici         # same bytes, other link
    # default topology is single-slice: everything ICI
    assert c.recv_link_bytes(payload, n, w).dcn == 0


def test_topology_descriptor():
    from grace_tpu.core import SINGLE_SLICE, Topology

    assert not SINGLE_SLICE.crosses_dcn(10 ** 6)
    assert Topology(slice_size=8).crosses_dcn(9)
    assert not Topology(slice_size=8).crosses_dcn(8)
    with pytest.raises(ValueError):
        Topology(slice_size=0)
    # CPU / simulated devices: always one slice
    assert Topology.detect().slice_size is None


def test_bench_projection_uses_shared_per_link_model():
    """The xslice projection block prices the split the communicator
    reports — dense and compressed both through recv_link_bytes."""
    import bench

    class FakeComp:
        vote_aggregate = False

    class FakeGrace:
        compressor = FakeComp()
        communicator = comm.Allgather()

    rows = bench.project_multichip(0.1, 0.1, FakeGrace(),
                                   wire_b=10 ** 6, dense_b=10 ** 8,
                                   n_elems=25 * 10 ** 6)
    for row in rows:
        x = row["xslice"]
        assert x["slice_size"] == bench.XSLICE_CHIPS
        assert x["ici_bytes"] + x["dcn_bytes"] == row["recv_bytes_per_rank"]
        if row["world"] > bench.XSLICE_CHIPS:
            assert x["ici_bytes"] == 0        # flat gather beyond one slice
            # flat DCN pricing matches the legacy all-DCN scenario
            assert x["step_ms"] == row["step_ms_dcn"]
        else:
            assert x["dcn_bytes"] == 0
            assert x["step_ms"] == row["step_ms_ici"]


# ---------------------------------------------------------------------------
# repo rule engine
# ---------------------------------------------------------------------------

def test_repo_rules_clean():
    findings = run_repo_rules()
    assert findings == [], "\n".join(f"{f.config}: {f.message}"
                                     for f in findings)


def test_rule_fires_on_undeclared_compressor():
    src = ("from grace_tpu.core import Compressor\n"
           "class ShinyNewCompressor(Compressor):\n"
           "    ratio: float = 0.5\n")
    findings = run_repo_rules(
        rules=("compressor-capabilities",),
        sources={"grace_tpu/compressors/shiny.py": src})
    mine = [f for f in findings if "ShinyNewCompressor" in f.message]
    assert len(mine) == 1
    assert "payload_algebra" in mine[0].message


def test_rule_fires_on_bad_fields_reducer():
    src = ('FIELDS = (("grad_norm", "mean"), ("mystery", "median"))\n')
    findings = run_repo_rules(
        rules=("telemetry-fields-reducer",),
        sources={"grace_tpu/telemetry/state.py": src})
    assert len(findings) == 1 and "median" in findings[0].message


def test_rule_fires_on_unregistered_marker():
    src = ("import pytest\n"
           "@pytest.mark.totally_new_marker\n"
           "def test_x():\n    pass\n")
    findings = run_repo_rules(
        rules=("pytest-marker-registration",),
        sources={"tests/test_fake_marker.py": src})
    assert any(f.details and dict(f.details).get("marker")
               == "totally_new_marker" for f in findings)


def test_analysis_marker_is_registered():
    assert "analysis" in registered_markers(repo_root())


# ---------------------------------------------------------------------------
# reporting: JSONL round-trips through tools/telemetry_report.py
# ---------------------------------------------------------------------------

def test_jsonl_findings_render_in_telemetry_report(tmp_path):
    import os
    import sys
    sys.path.insert(0, os.path.join(repo_root(), "tools"))
    import telemetry_report

    findings = audit_config({"name": "bad-triad",
                             "params": {"compressor": "topk",
                                        "memory": "residual",
                                        "communicator": "allreduce"}})
    path = tmp_path / "lint.jsonl"
    write_jsonl(findings, str(path), provenance={"tool": "graft_lint"})
    provenance, records, events = telemetry_report.load(str(path))
    assert provenance == {"tool": "graft_lint"}
    assert records == []
    assert [e["event"] for e in events] == ["lint_finding"]
    rendered = telemetry_report.render(provenance, records, events)
    assert "lint_finding" in rendered


def test_cli_rules_only_exits_zero(capsys):
    import os
    import sys
    sys.path.insert(0, os.path.join(repo_root(), "tools"))
    import graft_lint

    assert graft_lint.main(["--rules-only"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out


def test_findings_are_json_serializable():
    findings = audit_config({"name": "bad-triad",
                             "params": {"compressor": "topk",
                                        "memory": "residual",
                                        "communicator": "allreduce"}})
    doc = json.dumps([f.as_dict() for f in findings])
    assert "bad-triad" in doc
