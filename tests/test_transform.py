"""End-to-end: grace_transform inside a shard_map train step on 8 devices.

The convergence-as-test strategy of the reference (SURVEY.md §4: DAWNBench
accuracy target as regression signal), shrunk to a synthetic problem that
runs in seconds on the simulated mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import grace_tpu
from grace_tpu import grace_from_params
from grace_tpu.train import init_train_state, make_train_step

BATCH, DIM, CLASSES = 64, 20, 4


def make_problem(rng):
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true + 0.1 * rng.normal(size=(BATCH * 8, CLASSES)), axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def init_params(rng):
    return {"w": jnp.asarray(rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def train(mesh, grace_params, steps=60, lr=0.3, seed=0):
    rng = np.random.default_rng(seed)
    x, y = make_problem(rng)
    grc = grace_from_params(grace_params)
    tx = optax.chain(grc.transform(seed=1), optax.sgd(lr))
    params = init_params(rng)
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    losses = []
    for _ in range(steps):
        state, loss = step(state, (x, y))
        losses.append(float(loss))
    return losses


CONFIGS = [
    {"compressor": "none", "memory": "none", "communicator": "allreduce"},
    {"compressor": "fp16", "memory": "none", "communicator": "allreduce"},
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "allgather"},
    {"compressor": "randomk", "compress_ratio": 0.5, "memory": "residual",
     "communicator": "allgather"},
    {"compressor": "qsgd", "quantum_num": 64, "memory": "none",
     "communicator": "allgather"},
    {"compressor": "terngrad", "memory": "none", "communicator": "allgather"},
    {"compressor": "dgc", "compress_ratio": 0.3, "memory": "dgc",
     "communicator": "allgather"},
    {"compressor": "natural", "memory": "residual", "communicator": "allgather"},
    {"compressor": "powersgd", "compress_rank": 4, "memory": "powersgd",
     "communicator": "allreduce"},
    {"compressor": "sketch", "quantum_num": 64, "memory": "none",
     "communicator": "allgather"},
    {"compressor": "u8bit", "memory": "none", "communicator": "allgather"},
    {"compressor": "adaq", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "allgather"},
    {"compressor": "inceptionn", "memory": "none",
     "communicator": "allgather"},
    # Two-shot scatter-reduce-recompress path (O(k) wire per rank).
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "twoshot"},
    {"compressor": "qsgd", "quantum_num": 64, "memory": "none",
     "communicator": "twoshot"},
    # Hop-pipelined compressed ring (ISSUE 4): per-hop requantization must
    # still converge through the full transform (topk re-selects, qsgd
    # re-quantizes at each of the W-1 reduce-scatter hops).
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "ring"},
    {"compressor": "qsgd", "quantum_num": 64, "memory": "none",
     "communicator": "ring"},
    # Two-level ICI×DCN schedule (ISSUE 7): slice_size=4 splits the
    # 8-device mesh into 2 slices, so training runs through the intra-slice
    # hop requants AND the slice-boundary re-encode + cross-slice vote/sum.
    {"compressor": "topk", "compress_ratio": 0.3, "memory": "residual",
     "communicator": "hier", "slice_size": 4},
    {"compressor": "qsgd", "quantum_num": 64, "memory": "none",
     "communicator": "hier", "slice_size": 4},
]


@pytest.mark.parametrize(
    "cfg", CONFIGS,
    ids=[f"{c['compressor']}-{c['communicator']}" for c in CONFIGS])
def test_training_converges(mesh, cfg):
    losses = train(mesh, cfg)
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_signsgd_converges(mesh):
    # sign methods need a smaller lr (update magnitude is O(1) per coord)
    losses = train(mesh, {"compressor": "signsgd", "memory": "none",
                          "communicator": "allgather"}, lr=0.02)
    assert losses[-1] < losses[0] * 0.8


def test_signsgd_allreduce_converges_and_matches_allgather(mesh):
    """Regression (round 2): signsgd + 'allreduce' once psummed packed sign
    bytes and training climbed. The vote routing must give the exact same
    trajectory as the allgather majority vote — the pipeline is
    deterministic, so equality is step-exact."""
    cfg = {"compressor": "signsgd", "memory": "none"}
    via_gather = train(mesh, {**cfg, "communicator": "allgather"}, lr=0.02)
    via_reduce = train(mesh, {**cfg, "communicator": "allreduce"}, lr=0.02)
    assert via_reduce[-1] < via_reduce[0] * 0.8
    np.testing.assert_allclose(via_reduce, via_gather, rtol=1e-6)


def test_efsignsgd_converges(mesh):
    losses = train(mesh, {"compressor": "efsignsgd", "memory": "efsignsgd",
                          "lr": 0.1, "communicator": "allgather"}, lr=1.0)
    assert losses[-1] < losses[0] * 0.8


def test_compressed_tracks_uncompressed(mesh):
    """Top-K with error feedback stays close to the uncompressed trajectory."""
    base = train(mesh, {"compressor": "none", "memory": "none",
                        "communicator": "allreduce"})
    comp = train(mesh, {"compressor": "topk", "compress_ratio": 0.5,
                        "memory": "residual", "communicator": "allgather"})
    assert comp[-1] < base[-1] * 2.0 + 0.1


def test_grace_state_checkpointable(mesh):
    """Compression state is a pytree: serializes/restores losslessly.

    The reference never checkpoints residuals (SURVEY.md §5); here it is a
    flat pytree restorable by any checkpointer.
    """
    rng = np.random.default_rng(0)
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                             "memory": "residual", "communicator": "allgather"})
    tx = optax.chain(grc.transform(), optax.sgd(0.1))
    params = init_params(rng)
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    x, y = make_problem(rng)
    state, _ = step(state, (x, y))
    leaves, treedef = jax.tree_util.tree_flatten(state)
    restored = jax.tree_util.tree_unflatten(
        treedef, [np.asarray(l) for l in leaves])
    state2, l2 = step(jax.tree_util.tree_map(jnp.asarray, restored), (x, y))
    state1, l1 = step(state, (x, y))
    assert np.isclose(float(l1), float(l2))


def test_old_style_state_rejected(mesh):
    """States built without the world axis must fail loudly, not mis-shard."""
    rng = np.random.default_rng(0)
    grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                             "memory": "residual", "communicator": "allgather"})
    tx = optax.chain(grc.transform(), optax.sgd(0.1))
    params = init_params(rng)
    from grace_tpu.train import TrainState
    bad = TrainState(params, tx.init(params))  # missing world axis
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    x, y = make_problem(rng)
    # Either our explicit guard fires (divisible shapes) or shard_map's
    # divisibility check does — both are loud ValueErrors, never silence.
    with pytest.raises(ValueError, match="world axis|evenly divisible"):
        step(bad, (x, y))


def test_remat_step_matches_plain(mesh):
    """jax.checkpoint changes memory scheduling, not math: remat and plain
    steps must agree (to float tolerance — XLA may reassociate the
    recomputed forward, so bitwise equality is not guaranteed)."""
    rng = np.random.default_rng(3)
    x, y = make_problem(rng)
    cfg = {"compressor": "topk", "compress_ratio": 0.3,
           "memory": "residual", "communicator": "allgather"}

    def run(remat):
        grc = grace_from_params(dict(cfg))
        tx = optax.chain(grc.transform(seed=1), optax.sgd(0.1))
        params = init_params(np.random.default_rng(3))
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False, remat=remat)
        for _ in range(5):
            state, loss = step(state, (x, y))
        return float(loss), state.params

    loss_a, params_a = run(remat=False)
    loss_b, params_b = run(remat=True)
    np.testing.assert_allclose(loss_a, loss_b, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(params_a),
                    jax.tree_util.tree_leaves(params_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-7)


def test_remat_stateful_step_matches_plain(mesh):
    """The stateful path composes jax.checkpoint with has_aux (BN-style
    model state flows out of the rematted function) — cover it too."""
    from grace_tpu.train import (init_stateful_train_state,
                                 make_stateful_train_step)
    rng = np.random.default_rng(5)
    x, y = make_problem(rng)

    def sloss(params, mstate, batch):
        xb, yb = batch
        logits = xb @ params["w"] + params["b"]
        new_mstate = {"ema": 0.9 * mstate["ema"] + 0.1 * xb.mean()}
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, yb)
        return loss.mean(), new_mstate

    def run(remat):
        grc = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                                 "memory": "residual",
                                 "communicator": "allgather"})
        tx = optax.chain(grc.transform(seed=1), optax.sgd(0.1))
        params = init_params(np.random.default_rng(5))
        mstate = {"ema": jnp.zeros(())}
        state = init_stateful_train_state(params, mstate, tx, mesh)
        step = make_stateful_train_step(sloss, tx, mesh, donate=False,
                                        remat=remat)
        for _ in range(3):
            state, loss = step(state, (x, y))
        return float(loss), float(state.model_state["ema"])

    (loss_a, ema_a), (loss_b, ema_b) = run(False), run(True)
    np.testing.assert_allclose(loss_a, loss_b, rtol=1e-6)
    np.testing.assert_allclose(ema_a, ema_b, rtol=1e-6)
