"""graft-sound: the stateful-semantics passes (ISSUE 20).

Same doctrine as test_analysis.py: the registry stays clean (checked by
``test_registered_config_audits_clean``, which now runs all ten passes),
and each new pass is proven LIVE here on a deliberately seeded bad graph —
reused rng lineage, an un-rolled-back state leaf, a rank-varying write
into a replicated field. Plus the plain-pytest pin of the field-role /
partition_specs agreement that pass 10 checks statically.
"""

import jax
import jax.numpy as jnp
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from grace_tpu.analysis.passes import PASS_NAMES, run_passes
from grace_tpu.analysis.rules import run_repo_rules
from grace_tpu.analysis.state_passes import (_contract_drift,
                                             pass_replication_contract,
                                             pass_rng_lineage,
                                             pass_rollback_coverage)
from grace_tpu.analysis.trace import trace_fn
from grace_tpu.core import DEFAULT_AXIS
from grace_tpu.resilience.guard import (GUARD_ROLLBACK_EXCLUDED,
                                        GUARD_SCAN_EXCLUDED_TYPES)
from grace_tpu.transform import (GRACE_OBSERVATIONAL_FIELDS,
                                 GRACE_REPLICATED_FIELDS,
                                 GRACE_VARYING_FIELDS, GraceState, MeshSpec,
                                 partition_specs)

pytestmark = pytest.mark.analysis

KEY = jax.ShapeDtypeStruct((2,), jnp.uint32)
F8 = jax.ShapeDtypeStruct((8,), jnp.float32)
F4 = jax.ShapeDtypeStruct((4,), jnp.float32)
I32 = jax.ShapeDtypeStruct((), jnp.int32)


def _traced_state(fn, args, paths, varying, name, meta=None):
    """A TracedGraph with the state-var bookkeeping the graft-sound passes
    read, built from a bare function: the first ``len(paths)`` args are
    the state leaves (and the first ``len(paths)`` outputs their step-exit
    twins), rooted at a bare GraceState (prefix '')."""
    t = trace_fn(fn, args, varying=varying, name=name, meta=meta)
    n = len(paths)
    assert len(t.grad_in) >= n and len(t.body.outvars) >= n
    t.state_in_vars = list(zip(paths, t.grad_in[:n]))
    t.state_out_vars = list(zip(paths, t.body.outvars[:n]))
    t.grace_prefixes = ("",)
    return t


# ---------------------------------------------------------------------------
# pass 8: rng lineage
# ---------------------------------------------------------------------------

def test_rng_lineage_fires_on_shared_lineage():
    """Two independent stochastic sites (different draw shapes) consuming
    the same derived key — the correlated-noise bug."""

    def bad(kd, w, b):
        k = jax.random.fold_in(jax.random.wrap_key_data(kd), 7)
        return (w + jax.random.uniform(k, w.shape),
                b + jax.random.uniform(k, b.shape))

    t = trace_fn(bad, [KEY, F8, F4], varying=[False, True, True],
                 name="rng-reuse")
    findings = pass_rng_lineage(t)
    assert any("share one rng lineage" in f.message
               and f.severity == "error" for f in findings), findings


def test_rng_lineage_exempts_identical_redraw():
    """The telemetry-probe idiom: re-drawing the IDENTICAL shape from the
    same key is one draw after CSE, not two correlated sites."""

    def ok(kd, w):
        k = jax.random.fold_in(jax.random.wrap_key_data(kd), 3)
        return w + jax.random.uniform(k, w.shape) \
            * jax.random.uniform(k, w.shape)

    t = trace_fn(ok, [KEY, F8], varying=[False, True], name="rng-probe")
    assert pass_rng_lineage(t) == []


def test_rng_lineage_blesses_distinct_folds():
    def ok(kd, w, b):
        key = jax.random.wrap_key_data(kd)
        return (w + jax.random.uniform(jax.random.fold_in(key, 0),
                                       w.shape),
                b + jax.random.uniform(jax.random.fold_in(key, 1),
                                       b.shape))

    t = trace_fn(ok, [KEY, F8, F4], varying=[False, True, True],
                 name="rng-folds")
    assert pass_rng_lineage(t) == []


def test_rng_lineage_exempts_exclusive_branches():
    """Different arms of one cond are mutually exclusive — the adapt
    ladder's rungs may derive from one key without correlating."""

    def ok(kd, w, p):
        k = jax.random.fold_in(jax.random.wrap_key_data(kd), 5)
        return w[0] + lax.cond(
            p, lambda: jnp.sum(jax.random.uniform(k, (8,))),
            lambda: jnp.sum(jax.random.uniform(k, (4,))))

    t = trace_fn(ok, [KEY, F8, jax.ShapeDtypeStruct((), jnp.bool_)],
                 varying=[False, True, False], name="rng-branches")
    assert pass_rng_lineage(t) == []


def test_rng_lineage_fires_on_rank_varying_key():
    """A key folded with axis_index draws a different schedule per rank —
    rank-deterministic selection (cyclictopk, shared Top-K) desyncs."""

    def bad(kd, w):
        k = jax.random.fold_in(jax.random.wrap_key_data(kd),
                               lax.axis_index(DEFAULT_AXIS))
        return w + jax.random.uniform(k, w.shape)

    t = trace_fn(bad, [KEY, F8], varying=[False, True], name="rng-varying")
    findings = pass_rng_lineage(t)
    assert any("rank-varying key" in f.message and f.severity == "error"
               for f in findings), findings


# ---------------------------------------------------------------------------
# pass 9: rollback coverage
# ---------------------------------------------------------------------------

def _guarded(fn, args, paths, varying, name):
    return _traced_state(fn, args, paths, varying, name,
                         meta={"guard": {"fallback_after": 3,
                                         "fallback_steps": 8}})


def test_rollback_coverage_fires_on_unrolled_leaf():
    """A state leaf written without a guard-gated restore: the new-field-
    skips-rollback bug, found at trace time instead of in a chaos drill."""

    def bad(count, mem, extra, g):
        nf = jnp.any(~jnp.isfinite(g))
        return (jnp.where(nf, count, count + 1),
                jnp.where(nf, mem, mem + g),
                extra + 1.0,                      # skips the rollback
                jnp.sum(g))

    t = _guarded(bad, [I32, F8, F8, F8], ("count", "mem/w", "extra"),
                 [False, True, True, True], "rollback-miss")
    findings = pass_rollback_coverage(t)
    assert len(findings) == 1, findings
    assert "'extra'" in findings[0].message
    assert findings[0].severity == "error"


def test_rollback_coverage_clean_when_all_leaves_restored():
    def ok(count, mem, extra, g):
        nf = jnp.any(~jnp.isfinite(g))
        return (jnp.where(nf, count, count + 1),
                jnp.where(nf, mem, mem + g),
                jnp.where(nf, extra, extra + 1.0),
                jnp.sum(g))

    t = _guarded(ok, [I32, F8, F8, F8], ("count", "mem/w", "extra"),
                 [False, True, True, True], "rollback-ok")
    assert pass_rollback_coverage(t) == []


def test_rollback_coverage_honors_declared_exclusions():
    """Leaves whose path carries a GUARD_ROLLBACK_EXCLUDED segment are
    deliberately written through — the guard's own counters."""

    def ok(count, step, g):
        nf = jnp.any(~jnp.isfinite(g))
        return jnp.where(nf, count, count + 1), step + 1, jnp.sum(g)

    t = _guarded(ok, [I32, I32, F8], ("count", "step"),
                 [False, False, True], "rollback-excluded")
    assert pass_rollback_coverage(t) == []


def test_rollback_coverage_noops_without_guard():
    """No guard, no rollback contract: the pass must not condemn plain
    update-mode traces."""

    def fn(count, g):
        return count + 1, jnp.sum(g)

    t = _traced_state(fn, [I32, F8], ("count",), [False, True], "no-guard")
    assert pass_rollback_coverage(t) == []


# ---------------------------------------------------------------------------
# pass 10: replication contract
# ---------------------------------------------------------------------------

def test_replication_contract_fires_on_rank_varying_write():
    """axis_index leaking into a replicated field — the adapt-rung desync
    class pass 10 exists to catch."""

    def bad(count, g):
        return count + lax.axis_index(DEFAULT_AXIS), jnp.sum(g)

    t = _traced_state(bad, [I32, F8], ("count",), [False, True],
                      "repl-violation")
    findings = pass_replication_contract(t)
    assert any("'count'" in f.message and f.severity == "error"
               for f in findings), findings


def test_replication_contract_blesses_full_axis_reduction():
    """A write derived from a full-axis psum is replicated by
    construction — every rank computes the identical reduction."""

    def ok(count, g):
        return (count + lax.psum(jnp.sum(g), DEFAULT_AXIS).astype(
            jnp.int32) * 0 + 1, jnp.sum(g))

    t = _traced_state(ok, [I32, F8], ("count",), [False, True],
                      "repl-psum")
    assert pass_replication_contract(t) == []


def test_replication_contract_warns_on_dead_varying_field():
    """A GRACE_VARYING_FIELDS field that is provably replicated is
    sharded dead weight (or belongs in the replicated set)."""

    def lazy(mem, g):
        return lax.psum(mem, DEFAULT_AXIS) / 8.0, jnp.sum(g)

    t = _traced_state(lazy, [F8, F8], ("mem/w",), [True, True],
                      "repl-dead-varying")
    findings = pass_replication_contract(t)
    assert any(f.severity == "warning" and "'mem'" in f.message
               for f in findings), findings


def test_contract_constants_do_not_drift():
    """The static third of pass 10, pinned directly."""
    assert _contract_drift() == ()


# ---------------------------------------------------------------------------
# the field-role / partition_specs agreement (satellite pin)
# ---------------------------------------------------------------------------

def test_field_roles_exactly_cover_gracestate():
    varying, replicated = set(GRACE_VARYING_FIELDS), set(
        GRACE_REPLICATED_FIELDS)
    assert varying | replicated == set(GraceState._fields)
    assert not varying & replicated
    assert set(GRACE_OBSERVATIONAL_FIELDS) <= varying


@pytest.mark.parametrize("mesh", [
    MeshSpec(), MeshSpec(dp_axis="dp", fsdp_axis="fsdp")],
    ids=["1d", "2d"])
def test_partition_specs_agree_with_field_roles(mesh):
    leaf = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    state = GraceState(**{f: leaf for f in GraceState._fields})
    specs = partition_specs(state, mesh)
    for f in GraceState._fields:
        want = mesh.varying_spec() if f in GRACE_VARYING_FIELDS else P()
        assert getattr(specs, f) == want, f


def test_observational_types_match_fields():
    """The two spellings of the check_state strip contract: field names
    (transform) and pytree node types (guard) must describe the same set."""
    from grace_tpu.telemetry.aggregate import WatchState
    from grace_tpu.telemetry.state import TelemetryState

    assert set(GRACE_OBSERVATIONAL_FIELDS) == {"telem", "watch"}
    assert set(GUARD_SCAN_EXCLUDED_TYPES) == {TelemetryState, WatchState}
    assert set(GRACE_OBSERVATIONAL_FIELDS) <= set(GRACE_VARYING_FIELDS)


def test_guard_exclusions_name_real_leaves():
    """Every declared rollback exclusion is a GuardState field or the
    GraceState fallback flag — a typo here would silently re-arm the
    rollback-coverage pass on the guard's own counters."""
    from grace_tpu.resilience.guard import GuardState

    legal = set(GuardState._fields) | {"fallback"}
    assert set(GUARD_ROLLBACK_EXCLUDED) <= legal


# ---------------------------------------------------------------------------
# registration plumbing + AST rule
# ---------------------------------------------------------------------------

def test_ten_passes_registered():
    assert PASS_NAMES[-3:] == ("rng_lineage", "rollback_coverage",
                               "replication_contract")
    assert len(PASS_NAMES) == 10

    def fn(x):
        return x + 1.0

    t = trace_fn(fn, [F8], name="resolve-all")
    # Every registered name must resolve and run (most no-op on a bare
    # stateless trace).
    run_passes(t, PASS_NAMES)


def test_field_role_rule_clean_on_repo():
    assert run_repo_rules(rules=("grace-state-field-roles",)) == []


def _transform_src():
    import os

    from grace_tpu.analysis.rules import repo_root

    with open(os.path.join(repo_root(), "grace_tpu", "transform.py")) as f:
        return f.read()


def test_field_role_rule_fires_on_unroled_field():
    src = _transform_src()
    bad = src.replace("    adapt: Any = None",
                      "    adapt: Any = None\n    shiny_new: Any = None",
                      1)
    findings = run_repo_rules(rules=("grace-state-field-roles",),
                              sources={"grace_tpu/transform.py": bad})
    assert any(f.details and dict(f.details).get("field") == "shiny_new"
               and "GRACE_VARYING_FIELDS" in f.message for f in findings)


def test_field_role_rule_fires_on_ghost_constant_entry():
    src = _transform_src()
    bad = src.replace('GRACE_VARYING_FIELDS = ("mem", "comp", "telem", '
                      '"watch")',
                      'GRACE_VARYING_FIELDS = ("mem", "comp", "telem", '
                      '"watch", "ghost")', 1)
    assert bad != src
    findings = run_repo_rules(rules=("grace-state-field-roles",),
                              sources={"grace_tpu/transform.py": bad})
    assert any(f.details and dict(f.details).get("field") == "ghost"
               for f in findings)
