"""Pallas stochastic-quantization kernel — interpreter-mode tests on CPU.

The kernel must reproduce QSGD's encoding statistics: levels bounded by
quantum_num (+1 for stochastic overshoot at the max), unbiased expectation,
sign preservation, and the jnp reference path must round-trip with the same
reconstruction error profile.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu.compressors import QSGDCompressor
from grace_tpu.ops.pallas_quant import quantize_stochastic


class TestQuantizeStochastic:
    def test_levels_bounded_and_signed(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(5000), jnp.float32)
        norm = jnp.linalg.norm(x)
        q = quantize_stochastic(x, norm, jnp.int32(7), 64, interpret=True)
        q = np.asarray(q, np.int32)
        assert q.shape == (5000,)
        # |level| <= quantum_num (largest |x| = norm*frac<1 of levels) + 1
        assert np.abs(q).max() <= 65
        signs_match = np.sign(q) == np.sign(np.asarray(x))
        assert signs_match[q != 0].all()

    def test_unbiased_expectation(self):
        """E[decoded] == x: average many independent quantizations."""
        x = jnp.asarray([0.3, -0.7, 0.05, 0.9], jnp.float32)
        norm = jnp.linalg.norm(x)
        dec = []
        for seed in range(400):
            q = quantize_stochastic(x, norm, jnp.int32(seed), 8,
                                    interpret=True)
            dec.append(np.asarray(q, np.float32) * float(norm) / 8)
        mean = np.stack(dec).mean(axis=0)
        np.testing.assert_allclose(mean, np.asarray(x), atol=0.04)

    def test_zero_norm_safe(self):
        x = jnp.zeros(100, jnp.float32)
        q = quantize_stochastic(x, jnp.float32(0.0), jnp.int32(1), 64,
                                interpret=True)
        assert np.all(np.asarray(q) == 0)

    def test_non_multiple_length_padding(self):
        x = jnp.asarray(np.random.default_rng(1).standard_normal(777),
                        jnp.float32)
        q = quantize_stochastic(x, jnp.linalg.norm(x), jnp.int32(3), 64,
                                interpret=True)
        assert q.shape == (777,)

    def test_error_profile_matches_jnp_path(self):
        """Pallas and jnp paths draw different randomness but must have the
        same reconstruction error magnitude (same quantization grid)."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal(4096), jnp.float32)
        key = jax.random.key(0)
        ref = QSGDCompressor(quantum_num=64)
        pal = QSGDCompressor(quantum_num=64, use_pallas=True)
        (qr, nr), ctx, _ = ref.compress(x, None, key)
        (qp, np_), _, _ = pal.compress(x, None, key)
        err_ref = np.abs(np.asarray(ref.decompress((qr, nr), ctx)) -
                         np.asarray(x)).mean()
        err_pal = np.abs(np.asarray(pal.decompress((qp, np_), ctx)) -
                         np.asarray(x)).mean()
        assert err_pal < err_ref * 1.5 + 1e-6
        assert qp.dtype == qr.dtype


class TestQSGDPallasTraining:
    def test_converges_inside_shard_map(self, mesh):
        import optax
        from grace_tpu import grace_from_params
        from grace_tpu.train import init_train_state, make_train_step

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((256, 12)), jnp.float32)
        w = rng.standard_normal((12, 3)).astype(np.float32)
        y = jnp.asarray(np.argmax(np.asarray(x) @ w, axis=1))

        def loss_fn(params, batch):
            xb, yb = batch
            logits = xb @ params["w"] + params["b"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        grc = grace_from_params({"compressor": "qsgd", "quantum_num": 64,
                                 "memory": "none",
                                 "communicator": "allgather",
                                 "use_pallas": True})
        tx = optax.chain(grc.transform(seed=1), optax.sgd(0.2))
        params = {"w": jnp.zeros((12, 3)), "b": jnp.zeros((3,))}
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        losses = []
        for _ in range(40):
            state, loss = step(state, (x, y))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
