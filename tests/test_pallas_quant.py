"""Pallas stochastic-quantization kernel — interpreter-mode tests on CPU.

The kernel must reproduce QSGD's encoding statistics: levels bounded by
quantum_num (+1 for stochastic overshoot at the max), unbiased expectation,
sign preservation, and the jnp reference path must round-trip with the same
reconstruction error profile.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu.compressors import (QSGDCompressor, SignSGDCompressor,
                                   SignumCompressor)
from grace_tpu.ops.packing import pack_4bit, pack_bits, unpack_4bit
from grace_tpu.ops.pallas_quant import (quantize_pack_stochastic,
                                        quantize_stochastic, sign_pack)


class TestQuantizeStochastic:
    def test_levels_bounded_and_signed(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal(5000), jnp.float32)
        norm = jnp.linalg.norm(x)
        q = quantize_stochastic(x, norm, jnp.int32(7), 64, interpret=True)
        q = np.asarray(q, np.int32)
        assert q.shape == (5000,)
        # |level| <= quantum_num (largest |x| = norm*frac<1 of levels) + 1
        assert np.abs(q).max() <= 65
        signs_match = np.sign(q) == np.sign(np.asarray(x))
        assert signs_match[q != 0].all()

    def test_unbiased_expectation(self):
        """E[decoded] == x: average many independent quantizations."""
        x = jnp.asarray([0.3, -0.7, 0.05, 0.9], jnp.float32)
        norm = jnp.linalg.norm(x)
        dec = []
        for seed in range(400):
            q = quantize_stochastic(x, norm, jnp.int32(seed), 8,
                                    interpret=True)
            dec.append(np.asarray(q, np.float32) * float(norm) / 8)
        mean = np.stack(dec).mean(axis=0)
        np.testing.assert_allclose(mean, np.asarray(x), atol=0.04)

    def test_zero_norm_safe(self):
        x = jnp.zeros(100, jnp.float32)
        q = quantize_stochastic(x, jnp.float32(0.0), jnp.int32(1), 64,
                                interpret=True)
        assert np.all(np.asarray(q) == 0)

    def test_non_multiple_length_padding(self):
        x = jnp.asarray(np.random.default_rng(1).standard_normal(777),
                        jnp.float32)
        q = quantize_stochastic(x, jnp.linalg.norm(x), jnp.int32(3), 64,
                                interpret=True)
        assert q.shape == (777,)

    def test_error_profile_matches_jnp_path(self):
        """Pallas and jnp paths draw different randomness but must have the
        same reconstruction error magnitude (same quantization grid)."""
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal(4096), jnp.float32)
        key = jax.random.key(0)
        ref = QSGDCompressor(quantum_num=64)
        pal = QSGDCompressor(quantum_num=64, use_pallas=True)
        (qr, nr), ctx, _ = ref.compress(x, None, key)
        (qp, np_), _, _ = pal.compress(x, None, key)
        err_ref = np.abs(np.asarray(ref.decompress((qr, nr), ctx)) -
                         np.asarray(x)).mean()
        err_pal = np.abs(np.asarray(pal.decompress((qp, np_), ctx)) -
                         np.asarray(x)).mean()
        assert err_pal < err_ref * 1.5 + 1e-6
        assert qp.dtype == qr.dtype


class TestFusedCompressAndPack:
    """The compress-and-pack kernels must emit EXACTLY the bytes of the
    staged 'quantize then reference-pack' path — fusing the pack changes
    where the wire words are produced, never what they are."""

    @pytest.mark.parametrize("n", [1, 2, 3, 777, 16384, 20000])
    def test_quantize_pack_bit_identity_vs_staged_pack(self, n):
        """Fused 4-bit QSGD == quantize_stochastic (same seed, same PRNG
        stream, same block layout) -> clamp -> nibble fold -> pack_4bit."""
        rng = np.random.default_rng(n)
        x = jnp.asarray(rng.standard_normal(n), jnp.float32)
        norm = jnp.linalg.norm(x)
        got = np.asarray(quantize_pack_stochastic(x, norm, jnp.int32(5), 7,
                                                  interpret=True))
        levels = np.asarray(quantize_stochastic(x, norm, jnp.int32(5), 7,
                                                interpret=True), np.int32)
        levels = np.clip(levels, -7, 7)
        codes = np.where(levels < 0, levels + 16, levels).astype(np.uint8)
        want = np.asarray(pack_4bit(jnp.asarray(codes)))
        assert got.shape == want.shape == (-(-n // 2),)
        np.testing.assert_array_equal(got, want)

    def test_quantize_pack_rejects_wide_quantum(self):
        with pytest.raises(ValueError, match="4-bit"):
            quantize_pack_stochastic(jnp.ones(8), jnp.float32(1.0),
                                     jnp.int32(0), 64, interpret=True)

    @pytest.mark.parametrize("n", [1, 7, 8, 9, 777, 32768, 40000])
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16,
                                       jnp.float16])
    def test_sign_pack_bit_identity_vs_pack_bits(self, n, dtype):
        rng = np.random.default_rng(n)
        x = jnp.asarray(rng.standard_normal(n), dtype)
        got = np.asarray(sign_pack(x, interpret=True))
        want = np.asarray(pack_bits(x >= 0))
        assert got.shape == want.shape == (-(-n // 8),)
        np.testing.assert_array_equal(got, want)

    def test_sign_pack_negative_zero(self):
        """-0.0 >= 0 is True on both paths — the sign-bit edge case."""
        x = jnp.asarray([-0.0, 0.0, -1.0, 1.0], jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(sign_pack(x, interpret=True)),
            np.asarray(pack_bits(x >= 0)))

    def test_packed_qsgd_compressor_roundtrip_dtypes_shapes(self):
        """quantum_num<=7 ships ceil(n/2) packed bytes; decode error stays
        inside one quantization bin, across dtypes and shapes."""
        rng = np.random.default_rng(3)
        key = jax.random.key(0)
        for shape in [(5,), (33, 7), (128,)]:
            for dtype in (jnp.float32, jnp.bfloat16):
                x = jnp.asarray(rng.standard_normal(shape), dtype)
                for c in (QSGDCompressor(quantum_num=7, use_pallas=False),
                          QSGDCompressor(quantum_num=7, use_pallas=True)):
                    (p, norm), ctx, _ = c.compress(x, None, key)
                    assert p.dtype == jnp.uint8
                    assert p.shape == (-(-x.size // 2),)
                    dec = c.decompress((p, norm), ctx)
                    assert dec.shape == shape and dec.dtype == dtype
                    err = np.max(np.abs(np.asarray(dec, np.float32)
                                        - np.asarray(x, np.float32)))
                    assert err <= float(norm) / 7 + 1e-3

    def test_packed_staged_bytes_decode_by_reference_unpacker(self):
        """The staged path's wire bytes ARE the pack_widths contract: the
        module-level unpack_4bit recovers the exact nibble codes."""
        rng = np.random.default_rng(4)
        key = jax.random.key(1)
        x = jnp.asarray(rng.standard_normal(101), jnp.float32)
        c = QSGDCompressor(quantum_num=7, use_pallas=False)
        (p, norm), ctx, _ = c.compress(x, None, key)
        codes = np.asarray(unpack_4bit(p, x.size))
        levels = np.where(codes >= 8, codes.astype(np.int32) - 16, codes)
        assert np.abs(levels).max() <= 7
        dec = np.asarray(c.decompress((p, norm), ctx))
        np.testing.assert_allclose(
            dec, float(norm) / 7 * levels.astype(np.float32), rtol=1e-6)

    def test_signsgd_kernel_and_staged_bit_identical(self):
        rng = np.random.default_rng(5)
        key = jax.random.key(0)
        x = jnp.asarray(rng.standard_normal(4097), jnp.float32)
        (p0,), ctx, _ = SignSGDCompressor(use_pallas=False).compress(
            x, None, key)
        (p1,), _, _ = SignSGDCompressor(use_pallas=True).compress(
            x, None, key)
        np.testing.assert_array_equal(np.asarray(p0), np.asarray(p1))
        sm = SignumCompressor(use_pallas=True)
        (pm,), _, _ = sm.compress(x, sm.init_state(x), key)
        sm0 = SignumCompressor(use_pallas=False)
        (pm0,), _, _ = sm0.compress(x, sm0.init_state(x), key)
        np.testing.assert_array_equal(np.asarray(pm), np.asarray(pm0))

    def test_use_pallas_auto_selects_kernel_on_tpu(self, monkeypatch):
        """'auto' resolves to the kernel exactly when the backend is a
        real TPU — and to the staged path elsewhere (no silent interpret-
        mode slowdowns in production CPU runs)."""
        for c in (QSGDCompressor(quantum_num=7), SignSGDCompressor()):
            assert c.use_pallas == "auto"
            assert c._pallas_mode() == (False, False)       # CPU: staged
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        for c in (QSGDCompressor(quantum_num=7), SignSGDCompressor()):
            assert c._pallas_mode() == (True, False)        # TPU: kernel

    def test_env_escape_hatch_disables_kernels(self, monkeypatch):
        monkeypatch.setenv("GRACE_DISABLE_PALLAS", "1")
        with pytest.warns(RuntimeWarning):
            assert SignSGDCompressor(
                use_pallas=True)._pallas_mode() == (False, False)

    def test_packed_qsgd_converges_inside_shard_map(self, mesh):
        import optax
        from grace_tpu import grace_from_params
        from grace_tpu.train import init_train_state, make_train_step

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((256, 12)), jnp.float32)
        w = rng.standard_normal((12, 3)).astype(np.float32)
        y = jnp.asarray(np.argmax(np.asarray(x) @ w, axis=1))

        def loss_fn(params, batch):
            xb, yb = batch
            logits = xb @ params["w"] + params["b"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        grc = grace_from_params({"compressor": "qsgd", "quantum_num": 7,
                                 "memory": "residual",
                                 "communicator": "allgather",
                                 "use_pallas": False})
        tx = optax.chain(grc.transform(seed=1), optax.sgd(0.2))
        params = {"w": jnp.zeros((12, 3)), "b": jnp.zeros((3,))}
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        losses = []
        for _ in range(40):
            state, loss = step(state, (x, y))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


class TestQSGDPallasTraining:
    def test_converges_inside_shard_map(self, mesh):
        import optax
        from grace_tpu import grace_from_params
        from grace_tpu.train import init_train_state, make_train_step

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((256, 12)), jnp.float32)
        w = rng.standard_normal((12, 3)).astype(np.float32)
        y = jnp.asarray(np.argmax(np.asarray(x) @ w, axis=1))

        def loss_fn(params, batch):
            xb, yb = batch
            logits = xb @ params["w"] + params["b"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, yb).mean()

        grc = grace_from_params({"compressor": "qsgd", "quantum_num": 64,
                                 "memory": "none",
                                 "communicator": "allgather",
                                 "use_pallas": True})
        tx = optax.chain(grc.transform(seed=1), optax.sgd(0.2))
        params = {"w": jnp.zeros((12, 3)), "b": jnp.zeros((3,))}
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        losses = []
        for _ in range(40):
            state, loss = step(state, (x, y))
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])
