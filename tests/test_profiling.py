"""Performance attribution (grace_tpu.profiling) — ISSUE 6.

Covers the read side of the observability stack:

* trace analyzer exactness on the checked-in canned trace
  (tests/data/perf_trace.json.gz — hand-built spans with known durations,
  so attribution is asserted to the microsecond);
* overlap-fraction math on disjoint / fully-hidden / partially-hidden
  collective-vs-compute span pairs;
* the xplane protobuf path (round-trip through the module's own schema
  table);
* StepTimer fixes: warn-once on never-synced dispatch timing, timing row
  retained on BaseException;
* ProfileRecorder: a seeded weak-type closure leak detected as a runtime
  retrace, percentile/sync-missing records, GraceState footprint checked
  against live arrays (per-device and world-sharded layouts);
* tools/perf_report.py CLI: clean exit on the fixture, exit 1 on a seeded
  baseline regression, PROF_LAST.json evidence, evidence_summary pickup.

Everything runs on CPU with no devices (the mesh fixture is the simulated
8-device CPU mesh).
"""

from __future__ import annotations

import gzip
import json
import os
import shutil
import sys
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu.profiling import (ProfileRecorder, Span, analyze_spans,
                                 analyze_trace, check_state_footprint,
                                 expected_state_footprint,
                                 grace_state_footprint, interval_union_us,
                                 overlap_us, parse_xplane)
from grace_tpu.profiling.trace_analysis import _XPLANE_SCHEMA, UNATTRIBUTED
from grace_tpu.utils.profiling import StepTimer

pytestmark = pytest.mark.profiling

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")
FIXTURE = os.path.join(DATA, "perf_trace.json.gz")
TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def _tools_import(name):
    if TOOLS not in sys.path:
        sys.path.insert(0, TOOLS)
    import importlib
    return importlib.import_module(name)


# ---------------------------------------------------------------------------
# canned-trace attribution (exact numbers: see the fixture's span layout —
# per device, per step: fwd/bwd 400µs, compress 150µs (50µs nested child),
# decompress 100µs, optimizer 100µs on the compute lane; a 200µs all-gather
# on the async lane overlapping compress by 50µs; a 900µs step marker.
# 2 devices × 4 steps.)
# ---------------------------------------------------------------------------

def test_fixture_exact_stage_attribution():
    a = analyze_trace(FIXTURE)
    assert a.devices == ["/device:TPU:0", "/device:TPU:1"]
    assert a.device_lanes_detected
    stages_ms = {k: round(v * 1e-3, 6) for k, v in a.stage_us.items()}
    assert stages_ms == {"grace/forward_backward": 3.2,
                         "grace/exchange": 1.6,
                         "grace/compress": 1.2,
                         "grace/decompress": 0.8,
                         "grace/optimizer": 0.8}
    # the acceptance invariant: per-stage device time sums to total exactly
    assert abs(sum(a.stage_us.values()) - a.total_us) < 1e-9
    assert round(a.total_us * 1e-3, 6) == 7.6


def test_fixture_overlap_and_split():
    a = analyze_trace(FIXTURE)
    assert round(a.collective_us * 1e-3, 6) == 1.6
    assert round(a.compute_us * 1e-3, 6) == 6.0
    # 50µs of each 200µs all-gather hides under the compress tail
    assert a.overlap_fraction == pytest.approx(0.25, abs=1e-9)


def test_fixture_step_percentiles():
    a = analyze_trace(FIXTURE)
    sp = a.step_percentiles_ms()
    assert sp["n"] == 8                       # 2 devices × 4 steps
    assert sp["p50_ms"] == pytest.approx(0.9)
    assert sp["max_ms"] == pytest.approx(0.9)


def test_analysis_as_dict_render_consistent():
    a = analyze_trace(FIXTURE)
    d = a.as_dict()
    assert d["overlap_fraction"] == pytest.approx(0.25)
    assert sum(d["stages_ms"].values()) == pytest.approx(
        d["total_device_ms"])
    text = a.render()
    assert "grace/forward_backward" in text and "overlap" in text


# ---------------------------------------------------------------------------
# overlap-fraction math on constructed span pairs
# ---------------------------------------------------------------------------

def _dev_spans(comp, coll):
    """Compute spans on lane 'a', collective spans on lane 'b', one TPU."""
    spans = [Span(name="fusion.1", ts=s, dur=e - s,
                  device="/device:TPU:0", lane="a") for s, e in comp]
    spans += [Span(name="all-reduce.1", ts=s, dur=e - s,
                   device="/device:TPU:0", lane="b") for s, e in coll]
    return spans


def test_overlap_disjoint_is_zero():
    a = analyze_spans(_dev_spans([(0, 100)], [(100, 200)]))
    assert a.overlap_fraction == 0.0


def test_overlap_fully_hidden_is_one():
    a = analyze_spans(_dev_spans([(0, 200)], [(50, 150)]))
    assert a.overlap_fraction == 1.0


def test_overlap_partial_is_exact():
    a = analyze_spans(_dev_spans([(0, 100)], [(50, 150)]))
    assert a.overlap_fraction == pytest.approx(0.5)


def test_overlap_none_without_collectives():
    a = analyze_spans(_dev_spans([(0, 100)], []))
    assert a.overlap_fraction is None
    assert "n/a" in a.render()


def test_overlap_not_double_counted_across_fragments():
    # two collective fragments, one long compute region: intersection is
    # measured on interval unions, not per-span products
    a = analyze_spans(_dev_spans([(0, 300)], [(0, 100), (50, 150)]))
    assert a.collective_us == 150.0           # union, not 200
    assert a.overlap_fraction == 1.0


def test_interval_primitives():
    assert interval_union_us([(0, 10), (5, 20), (30, 40)]) == \
        [(0, 20), (30, 40)]
    assert overlap_us([(0, 20), (30, 40)], [(10, 35)]) == 15.0


def test_self_time_nesting_no_double_count():
    spans = [
        Span("grace/compress/outer.1", ts=0, dur=100,
             device="/device:TPU:0", lane="a"),
        Span("grace/decompress/inner.2", ts=10, dur=30,
             device="/device:TPU:0", lane="a"),
    ]
    a = analyze_spans(spans)
    assert a.stage_us["grace/compress"] == pytest.approx(70.0)
    assert a.stage_us["grace/decompress"] == pytest.approx(30.0)
    assert a.total_us == pytest.approx(100.0)


def test_unattributed_bucket_keeps_sum_exact():
    spans = _dev_spans([(0, 100)], []) + [
        Span("grace/compress/x.1", ts=200, dur=50,
             device="/device:TPU:0", lane="a")]
    a = analyze_spans(spans)
    assert a.stage_us[UNATTRIBUTED] == pytest.approx(100.0)
    assert abs(sum(a.stage_us.values()) - a.total_us) < 1e-9


# ---------------------------------------------------------------------------
# xplane path: round-trip through the module's own schema table
# ---------------------------------------------------------------------------

def _vint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _f_varint(field: int, val: int) -> bytes:
    return _vint(field << 3) + _vint(val)


def _f_len(field: int, payload: bytes) -> bytes:
    return _vint((field << 3) | 2) + _vint(len(payload)) + payload


def _xspace_bytes() -> bytes:
    S = _XPLANE_SCHEMA

    def ev_meta(mid, name):
        md = _f_varint(S["XEventMetadata"]["id"], mid) + \
            _f_len(S["XEventMetadata"]["name"], name.encode())
        return _f_varint(S["map_entry"]["key"], mid) + \
            _f_len(S["map_entry"]["value"], md)

    def event(mid, off_ps, dur_ps):
        return (_f_varint(S["XEvent"]["metadata_id"], mid)
                + _f_varint(S["XEvent"]["offset_ps"], off_ps)
                + _f_varint(S["XEvent"]["duration_ps"], dur_ps))

    def line(name, ts_ns, events):
        buf = _f_len(S["XLine"]["name"], name.encode()) + \
            _f_varint(S["XLine"]["timestamp_ns"], ts_ns)
        for e in events:
            buf += _f_len(S["XLine"]["events"], e)
        return buf

    ops = line("XLA Ops", 5000, [
        event(1, 0, 100_000_000),             # grace/compress, 100µs
        event(2, 100_000_000, 50_000_000),    # all-reduce, 50µs
    ])
    steps = line("Steps", 5000, [event(3, 0, 150_000_000)])
    plane = (_f_len(S["XPlane"]["name"], b"/device:TPU:0")
             + _f_len(S["XPlane"]["lines"], ops)
             + _f_len(S["XPlane"]["lines"], steps)
             + _f_len(S["XPlane"]["event_metadata"],
                      ev_meta(1, "grace/compress/pack.1"))
             + _f_len(S["XPlane"]["event_metadata"],
                      ev_meta(2, "all-reduce.2"))
             + _f_len(S["XPlane"]["event_metadata"], ev_meta(3, "step 0")))
    return _f_len(S["XSpace"]["planes"], plane)


def test_xplane_roundtrip(tmp_path):
    data = _xspace_bytes()
    spans = parse_xplane(data)
    assert {s.name for s in spans} == {"grace/compress/pack.1",
                                       "all-reduce.2", "step 0"}
    comp = next(s for s in spans if "compress" in s.name)
    assert comp.ts == pytest.approx(5.0)      # 5000 ns base → µs
    assert comp.dur == pytest.approx(100.0)
    a = analyze_spans(spans)
    assert a.stage_us["grace/compress"] == pytest.approx(100.0)
    assert a.stage_us[UNATTRIBUTED] == pytest.approx(50.0)
    assert a.collective_us == pytest.approx(50.0)
    assert a.step_times_us == [pytest.approx(150.0)]
    # and the file-extension dispatch picks the proto reader
    path = tmp_path / "host.xplane.pb"
    path.write_bytes(data)
    a2 = analyze_trace(str(path))
    assert a2.total_us == pytest.approx(a.total_us)


# ---------------------------------------------------------------------------
# HLO-metadata scope enrichment (the XLA:CPU capture layout: execution
# events carry bare instruction names; scopes live in the embedded HLO
# proto's per-instruction metadata.op_name)
# ---------------------------------------------------------------------------

def test_hlo_scope_map_harvests_nearest_named_ancestor():
    from grace_tpu.profiling import hlo_scope_map

    # a message with field-1 name "all-gather.11" whose nested submessage
    # carries an op_name string containing the grace scope — the shape of
    # HloInstructionProto{name=1, metadata{op_name}}
    op_name = b"jit(step)/grace/optimizer/grace/exchange/all_gather"
    meta = _f_len(2, op_name)
    instr = _f_len(1, b"all-gather.11") + _f_len(7, meta)
    blob = _f_len(3, instr)                   # wrapped once more (module)
    m = hlo_scope_map(blob)
    # the harvested value may carry framing bytes of the enclosing
    # message — attribution is substring-based, so only the stage matters
    from grace_tpu.telemetry.scopes import match_stage
    assert list(m) == ["all-gather.11"]
    assert match_stage(m["all-gather.11"]) == "grace/exchange"


def test_enrich_spans_overrides_stage_free_scope():
    """Chrome CPU exports stuff the bare op name into args.name — an
    existing stage-free scope must not block enrichment (the verify-drive
    bug), while spans already attributable stay untouched."""
    from grace_tpu.profiling import enrich_spans

    spans = [
        Span(name="all-gather.11", scope="all-gather.11",   # args.name echo
             ts=0, dur=10, device="/host:CPU", lane="t"),
        Span(name="grace/compress/x.1", scope="", ts=10, dur=10,
             device="/host:CPU", lane="t"),
        Span(name="copy.9", scope="", ts=20, dur=10,
             device="/host:CPU", lane="t"),
    ]
    m = {"all-gather.11": "jit(s)/grace/exchange/all_gather",
         "grace/compress/x.1": "jit(s)/grace/decompress/WRONG"}
    out = enrich_spans(spans, m)
    assert out[0].stage() == "grace/exchange"
    assert out[1].stage() == "grace/compress"   # already attributable: kept
    assert out[2].stage() == ""                 # no mapping: untouched


def test_match_stage_prefers_innermost_scope():
    """jax name stacks nest (optimizer wraps the transform wraps the
    exchange): the innermost (rightmost) stage is the one doing the work."""
    from grace_tpu.telemetry.scopes import match_stage

    nested = "jit(s)/grace/optimizer/grace/exchange/grace/decompress/fuse.1"
    assert match_stage(nested) == "grace/decompress"
    assert match_stage("grace/exchange/psum_vote") == "grace/exchange"
    assert match_stage("grace/optimizer/grace/exchange") == "grace/exchange"
    assert match_stage("unrelated/fusion.3") == ""


# ---------------------------------------------------------------------------
# StepTimer satellite fixes
# ---------------------------------------------------------------------------

def test_steptimer_warns_once_on_missing_sync():
    t = StepTimer(warmup=0)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for _ in range(3):
            with t.step():
                pass
    msgs = [w for w in caught if "sync_on" in str(w.message)]
    assert len(msgs) == 1                     # once, not per step
    assert t.measured_async_dispatch
    assert len(t) == 3


def test_steptimer_synced_steps_do_not_warn():
    t = StepTimer(warmup=0)
    x = jnp.ones((4,))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with t.step():
            t.sync_on(x * 2)
    assert not [w for w in caught if "sync_on" in str(w.message)]
    assert not t.measured_async_dispatch


def test_steptimer_keeps_timing_row_on_exception():
    t = StepTimer(warmup=0)
    with pytest.raises(KeyboardInterrupt):
        with t.step():
            raise KeyboardInterrupt       # BaseException, not Exception
    assert len(t) == 1                    # the row is NOT swallowed
    assert t.failed_steps == 1
    # and the poisoned sync target was cleared for the next step
    with t.step():
        t.sync_on(jnp.ones(()))
    assert len(t) == 2 and t.failed_steps == 1


def test_steptimer_percentiles():
    t = StepTimer(warmup=1)
    t._times = [99.0, 1.0, 2.0, 3.0, 4.0]     # warmup row skipped
    assert t.p50_sec == pytest.approx(2.5)
    assert t.percentile_sec(100) == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# ProfileRecorder
# ---------------------------------------------------------------------------

class ListSink:
    def __init__(self):
        self.records = []

    def write(self, rec):
        self.records.append(dict(rec))

    def close(self):
        pass


def test_recorder_detects_weak_type_retrace():
    """The seeded signature_stability bug class, caught at RUNTIME: an
    int32 carry plus a Python float promotes to weak f32, so the second
    call retraces — the recorder must attribute it to that step."""

    @jax.jit
    def leaky(c):
        return c + 1.5

    sink = ListSink()
    rec = ProfileRecorder(sink, every=100, warmup=0, step_fn=leaky)
    c = jnp.zeros((), jnp.int32)
    for i in range(4):
        with rec.step():
            c = leaky(c)
            rec.sync_on(c)
        rec.update(i)
    assert rec.retraces == 1
    events = [(r["event"], r.get("step")) for r in sink.records]
    assert ("perf_compile", 0) in events
    assert ("perf_retrace", 1) in events      # attributed to the 2nd step


def test_recorder_stable_step_no_retrace():
    @jax.jit
    def stable(c):
        return c + jnp.float32(1)

    rec = ProfileRecorder(ListSink(), every=100, warmup=0, step_fn=stable)
    c = jnp.zeros((), jnp.float32)
    for i in range(4):
        with rec.step():
            c = stable(c)
            rec.sync_on(c)
        rec.update(i)
    assert rec.retraces == 0


@pytest.mark.filterwarnings(
    "ignore:StepTimer.step\\(\\) completed without sync_on:RuntimeWarning")
def test_recorder_flush_records_percentiles_and_sync_flag():
    sink = ListSink()
    rec = ProfileRecorder(sink, every=2, warmup=0)
    for i in range(4):
        with rec.step():
            pass                              # no sync_on: dispatch-only
        rec.update(i)
    times = [r for r in sink.records if r["event"] == "perf_step_times"]
    assert len(times) == 2                    # every=2 over 4 steps
    last = times[-1]
    assert last["n_steps"] == 4
    assert {"mean_ms", "p50_ms", "p90_ms", "p99_ms", "max_ms"} <= set(last)
    assert last["sync_missing"] is True       # the caveat travels with it


def test_recorder_compile_count_understands_lazy_wrapper():
    from grace_tpu.profiling import compile_count

    @jax.jit
    def f(x):
        return x + 1

    class Wrapper:                            # grace_tpu.train shape
        jit_cache = {"k": f}

    assert compile_count(Wrapper()) == 0
    f(jnp.ones(()))
    assert compile_count(Wrapper()) == 1
    assert compile_count(object()) is None


# ---------------------------------------------------------------------------
# GraceState footprint accounting
# ---------------------------------------------------------------------------

def _grace(telemetry=16):
    from grace_tpu import grace_from_params
    return grace_from_params({"compressor": "topk", "compress_ratio": 0.25,
                              "memory": "residual",
                              "communicator": "allgather",
                              "telemetry": telemetry})


def test_footprint_matches_live_arrays():
    g = _grace()
    params = {"w": jnp.zeros((64,)), "b": jnp.zeros((8,))}
    state = g.transform(seed=0).init(params)
    out = check_state_footprint(state, g, params, world=1)
    assert out["matches"]
    # residual memory is one dense copy of the gradients
    assert out["live"]["mem_bytes"] == (64 + 8) * 4
    assert out["live"]["telem_bytes"] > 0


def test_footprint_mismatch_flags_config_drift():
    g = _grace(telemetry=16)
    params = {"w": jnp.zeros((64,)), "b": jnp.zeros((8,))}
    state = g.transform(seed=0).init(params)
    other = _grace(telemetry=False)           # model built w/o telemetry
    out = check_state_footprint(state, other, params, world=1)
    assert not out["matches"]
    assert out["model"]["telem_bytes"] == 0 < out["live"]["telem_bytes"]


def test_footprint_world_scaling_on_sharded_state(mesh):
    import optax
    from grace_tpu.train import init_train_state

    g = _grace(telemetry=8)
    tx = optax.chain(g.transform(seed=0), optax.sgd(0.1))
    params = {"w": jnp.zeros((32, 16)), "b": jnp.zeros((16,))}
    state = init_train_state(params, tx, mesh)
    out = check_state_footprint(state.opt_state, g, params, world=8)
    assert out["matches"]
    assert out["live"]["mem_bytes"] == 8 * (32 * 16 + 16) * 4


def test_footprint_model_is_abstract():
    """expected_state_footprint must not allocate (it is eval_shape-only,
    so it stays honest on a device-free box and never OOMs pricing a big
    codec)."""
    g = _grace()
    params = {"w": jax.ShapeDtypeStruct((1 << 20,), jnp.float32)}
    fp = expected_state_footprint(g, params, world=256)
    assert fp["mem_bytes"] == 256 * (1 << 20) * 4


def test_recorder_emits_footprint_record():
    g = _grace()
    params = {"w": jnp.zeros((16,))}
    state = g.transform(seed=0).init(params)
    sink = ListSink()
    rec = ProfileRecorder(sink)
    out = rec.record_state_footprint(state, g, params, world=1, step=7)
    assert out["footprint_matches"]
    assert sink.records[-1]["event"] == "perf_state_footprint"
    assert sink.records[-1]["model_mem_bytes"] == out["mem_bytes"]


def test_grace_state_footprint_counts_components():
    g = _grace()
    state = g.transform(seed=0).init({"w": jnp.zeros((10,))})
    fp = grace_state_footprint(state)
    assert fp["grace_states"] == 1
    assert fp["total_bytes"] == (fp["mem_bytes"] + fp["comp_bytes"]
                                 + fp["telem_bytes"]
                                 + fp["bookkeeping_bytes"])


# ---------------------------------------------------------------------------
# perf_report CLI (offline, no devices) + evidence flow
# ---------------------------------------------------------------------------

def test_perf_report_clean_run_and_evidence(tmp_path, capsys):
    perf_report = _tools_import("perf_report")
    out = tmp_path / "PROF_LAST.json"
    rc = perf_report.main(["--trace", FIXTURE, "--out", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "grace/forward_backward" in text and "overlap" in text
    doc = json.loads(out.read_text())
    assert doc["tool"] == "perf_report"
    assert sum(doc["stages_ms"].values()) == pytest.approx(
        doc["total_device_ms"])
    assert doc["overlap_fraction"] == pytest.approx(0.25)
    assert "canned CPU fixture" in doc["note"]


def test_perf_report_baseline_gate_exit_codes(tmp_path):
    perf_report = _tools_import("perf_report")
    base = tmp_path / "base.json"
    rc = perf_report.main(["--trace", FIXTURE, "--out", "",
                           "--write-baseline", str(base)])
    assert rc == 0
    # gating against its own baseline is clean…
    rc = perf_report.main(["--trace", FIXTURE, "--out", "",
                           "--baseline", str(base)])
    assert rc == 0
    # …and a seeded regression (baseline claims 2× faster) exits 1
    doc = json.loads(base.read_text())
    doc["step_times"]["p50_ms"] /= 2
    doc["total_device_ms"] /= 2
    regressed = tmp_path / "regressed.json"
    regressed.write_text(json.dumps(doc))
    rc = perf_report.main(["--trace", FIXTURE, "--out", "",
                           "--baseline", str(regressed)])
    assert rc == 1


def test_perf_report_overlap_regression_fires(tmp_path):
    perf_report = _tools_import("perf_report")
    current = {"step_times": None, "total_device_ms": 1.0,
               "stages_ms": {}, "overlap_fraction": 0.10}
    baseline = {"step_times": None, "total_device_ms": 1.0,
                "stages_ms": {}, "overlap_fraction": 0.50}
    findings = perf_report.compare_to_baseline(current, baseline, 0.10)
    assert any("overlap" in f for f in findings)
    # improvements never regress
    assert perf_report.compare_to_baseline(baseline, current, 0.10) == []


def test_tpu_profile_report_runs_offline(tmp_path, capsys):
    """Satellite: --report works on CPU against a saved trace via the
    shared analyzer (the ad-hoc xplane summary is gone)."""
    tpu_profile = _tools_import("tpu_profile")
    shutil.copy(FIXTURE, tmp_path / "host.trace.json.gz")
    tpu_profile.report(str(tmp_path))
    text = capsys.readouterr().out
    assert "grace/compress" in text and "overlap" in text


def test_telemetry_report_renders_perf_records(tmp_path, capsys):
    telemetry_report = _tools_import("telemetry_report")
    path = tmp_path / "run.jsonl"
    rows = [
        {"provenance": {"data": "synthetic"}},
        {"step": 0, "grad_norm": 1.0, "wire_bytes": 10, "dense_bytes": 40},
        {"event": "perf_compile", "step": 0, "cache_size": 1},
        {"event": "perf_retrace", "step": 3, "cache_size": 2,
         "retraces": 1},
        {"event": "perf_step_times", "step": 9, "n_steps": 10,
         "mean_ms": 2.0, "p50_ms": 1.9, "p90_ms": 2.5, "p99_ms": 3.0,
         "max_ms": 3.1, "sync_missing": True},
        {"event": "perf_memory", "step": 9, "n_devices": 8,
         "bytes_in_use": 1000, "peak_bytes_in_use": 2000},
        {"event": "perf_state_footprint", "step": 9, "mem_bytes": 288,
         "comp_bytes": 0, "telem_bytes": 640, "footprint_matches": True},
        {"event": "guard_skip", "step": 4, "notfinite_count": 1},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert telemetry_report.main([str(path)]) == 0
    text = capsys.readouterr().out
    assert "== profiling" in text
    assert "p50 1.900" in text
    assert "retraces: 1" in text
    assert "async-dispatch" in text
    assert "peak 2,000 B" in text
    assert "matches" in text
    # guard events keep their own section, without the perf records
    assert "guard_skip" in text.split("== guard events")[1]
    assert "perf_step_times" not in text.split("== guard events")[1]


def test_evidence_summary_picks_up_prof_last(tmp_path, monkeypatch):
    evidence_summary = _tools_import("evidence_summary")
    monkeypatch.setattr(evidence_summary, "ROOT", str(tmp_path))
    prof = {"tool": "perf_report", "trace": "tests/data/perf_trace.json.gz",
            "stages_ms": {"grace/compress": 1.2, "grace/exchange": 1.6},
            "total_device_ms": 7.6, "overlap_fraction": 0.25,
            "step_times": {"p50_ms": 0.9}, "regressions": [],
            "note": "canned CPU fixture trace",
            "captured_at": "2026-08-04T00:00:00+00:00"}
    (tmp_path / "PROF_LAST.json").write_text(json.dumps(prof))
    md = evidence_summary.build()
    assert "Performance attribution" in md
    assert "overlap fraction 25.0%" in md
    assert "0 baseline regression(s)" in md
