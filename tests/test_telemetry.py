"""In-graph telemetry: ring buffer, reader, sinks, guard interplay.

The properties pinned here are the acceptance criteria of the telemetry
subsystem (ISSUE 2): per-step metric rows recorded entirely on-device and
drained in ONE device-to-host transfer per flush window, ring wraparound
accounted (never silent), telemetry-under-guard (a skipped step's row rolls
back with the state — accumulators never corrupt), and effective wire bytes
flipping to the dense escape cost across a fallback window and returning
after re-arm.
"""

import json
import struct

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.resilience import guarded_chain
from grace_tpu.telemetry import (FIELDS, JSONLSink, MultiSink,
                                 TelemetryConfig, TelemetryReader,
                                 TensorBoardSink)
from grace_tpu.telemetry.sinks import masked_crc
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import set_fallback_flag
from grace_tpu.utils import payload_nbytes
from grace_tpu.utils.logging import GuardMonitor, run_provenance
from grace_tpu.utils.metrics import guard_report

BATCH, DIM, CLASSES = 64, 20, 4

TOPK_TELEM = {"compressor": "topk", "compress_ratio": 0.3,
              "memory": "residual", "communicator": "allgather"}

REQUIRED = ("grad_norm", "update_norm", "residual_norm", "residual_max",
            "compression_error", "wire_bytes", "dense_bytes", "fallback",
            "audit_bytes", "wire_bytes_ici", "wire_bytes_dcn",
            "wire_bytes_wan", "watch_bytes", "negotiation_bytes",
            "adapt_rung", "adapt_bytes")


def _problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(DIM, CLASSES)).astype(np.float32)
    x = rng.normal(size=(BATCH * 8, DIM)).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _loss_fn(params, batch):
    x, y = batch
    logits = x @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": jnp.asarray(
                rng.normal(size=(DIM, CLASSES)).astype(np.float32) * 0.1),
            "b": jnp.zeros((CLASSES,), jnp.float32)}


def _build(mesh, grace_params, lr=0.3, guard=False, **guard_kw):
    grc = grace_from_params(dict(grace_params))
    if guard:
        tx = guarded_chain(grc, optax.sgd(lr), **guard_kw)
    else:
        tx = optax.chain(grc.transform(seed=0), optax.sgd(lr))
    state = init_train_state(_init_params(), tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False)
    return state, step


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))

    def close(self):
        pass


# ---------------------------------------------------------------------------
# acceptance: 50-step run -> JSONL with all fields + provenance header,
# one transfer per window
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_fifty_step_jsonl_with_provenance(mesh, tmp_path):
    x, y = _problem()
    params = dict(TOPK_TELEM, telemetry=True)
    state, step = _build(mesh, params)

    path = tmp_path / "run.jsonl"
    sink = JSONLSink(path, provenance=run_provenance("synthetic",
                                                     tool="test"))
    reader = TelemetryReader(sink, every=10)
    for i in range(50):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    reader.close()

    lines = [json.loads(l) for l in path.read_text().splitlines()]
    header, records = lines[0], lines[1:]
    assert "provenance" in header
    assert header["provenance"]["data"] == "synthetic"
    assert header["provenance"]["platform"] == "cpu"
    assert len(records) == 50
    assert [r["step"] for r in records] == list(range(50))
    from grace_tpu.compressors import TopKCompressor
    for rec in records:
        for field in REQUIRED:
            assert field in rec, field
        assert np.isfinite(rec["grad_norm"]) and rec["grad_norm"] > 0
        assert rec["residual_norm"] >= 0
        assert 0 <= rec["compression_error"] <= 1.5
        # wire_bytes is COMMUNICATOR-AWARE received bytes (ISSUE 4):
        # allgather pays (W-1)x one rank's payload — more than this
        # config's raw dense gradient bytes at W=8 with 30% density,
        # which is exactly the scaling the ring communicator fixes.
        leaves = jax.tree_util.tree_leaves(_init_params())
        comp_b = sum(payload_nbytes(
            TopKCompressor(compress_ratio=0.3), l) for l in leaves)
        assert rec["wire_bytes"] == comp_b * 7
        assert rec["dense_bytes"] == sum(l.size * 4 for l in leaves)
    assert reader.flushes == 5 and reader.dropped == 0


@pytest.mark.telemetry
def test_flush_is_one_transfer_per_window(mesh, monkeypatch):
    """The acceptance bound: each N-step window costs exactly one
    jax.device_get, and the steps between flushes cost zero."""
    x, y = _problem()
    state, step = _build(mesh, dict(TOPK_TELEM, telemetry=True))
    reader = TelemetryReader(sink=None, every=10)

    calls = []
    real = jax.device_get
    monkeypatch.setattr(jax, "device_get",
                        lambda *a, **kw: calls.append(1) or real(*a, **kw))
    for i in range(50):
        state, _ = step(state, (x, y))
        reader.update(i, state)
    assert len(calls) == 5
    assert reader.flushes == 5


# ---------------------------------------------------------------------------
# ring wraparound + flush atomicity under jit
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_ring_wraparound_is_counted_not_silent(mesh):
    """Flush interval (20) beyond ring capacity (8): only the newest 8 rows
    survive, and the reader reports the 12 dropped — silent truncation
    would read as full coverage."""
    x, y = _problem()
    state, step = _build(mesh, dict(TOPK_TELEM, telemetry=8))
    reader = TelemetryReader(sink=None, every=20)
    records = []
    for i in range(20):
        state, _ = step(state, (x, y))
        records += reader.update(i, state)
    assert [r["step"] for r in records] == list(range(12, 20))
    assert records[-1]["dropped_steps"] == 12
    assert reader.dropped == 12


@pytest.mark.telemetry
def test_flush_windows_are_contiguous_and_exact(mesh):
    """Flush atomicity under jit: consecutive flushes partition the step
    sequence — no duplicates, no gaps, rows bitwise-stable across the
    flush boundary."""
    x, y = _problem()
    state, step = _build(mesh, dict(TOPK_TELEM,
                                    telemetry=TelemetryConfig(capacity=32)))
    reader = TelemetryReader(sink=None, every=7)
    seen = []
    for i in range(21):
        state, _ = step(state, (x, y))
        flushed = reader.update(i, state)
        if flushed:
            assert len(flushed) == 7
        seen += flushed
    assert [r["step"] for r in seen] == list(range(21))
    # Re-flushing with no new steps emits nothing (idempotent drain).
    assert reader.flush(state) == []


# ---------------------------------------------------------------------------
# telemetry under the guard
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_skipped_step_does_not_corrupt_accumulators(mesh):
    """A poisoned step rolls the ring back with the rest of the inner
    state: no NaN row ever reaches a flush, the step counter does not
    advance, and the guard's own counters arrive via the same flush."""
    x, y = _problem()
    params = dict(TOPK_TELEM, escape="fp16", telemetry=32)
    state, step = _build(mesh, params, guard=True)

    xbad = np.asarray(x).copy()
    xbad[0, 0] = np.nan
    batches = [x, x, x, jnp.asarray(xbad), x, x]
    reader = TelemetryReader(sink=None, every=len(batches))
    records = []
    for i, xb in enumerate(batches):
        state, _ = step(state, (jnp.asarray(xb), y))
        records += reader.update(i, state)

    # 6 wall steps, 1 skipped -> 5 accepted rows, counts 0..4 contiguous.
    assert [r["step"] for r in records] == list(range(5))
    for rec in records:
        for field in REQUIRED:
            assert np.isfinite(rec[field]), (rec["step"], field)
    assert records[-1]["guard_notfinite_count"] == 1
    assert records[-1]["guard_step"] == 6
    assert guard_report(state)["notfinite_count"] == 1


# ---------------------------------------------------------------------------
# effective wire bytes: dense <-> compressed flip
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_effective_wire_bytes_flip_across_fallback_window(mesh):
    """Forcing the fallback flag flips the recorded wire bytes to the
    escape codec's dense cost and back after re-arm — fallback windows
    show their true communication price."""
    x, y = _problem()
    params = dict(TOPK_TELEM, escape="fp16", telemetry=32)
    state, step = _build(mesh, params)

    leaves = jax.tree_util.tree_leaves(_init_params())
    from grace_tpu.comm import Allgather, Allreduce
    from grace_tpu.compressors import FP16Compressor, TopKCompressor
    n_elems = sum(l.size for l in leaves)
    # wire_bytes records COMMUNICATOR-AWARE received bytes (ISSUE 4): the
    # compressed path rides this config's allgather, the escape hatch a
    # dense psum priced by the Allreduce ring model.
    esc_bytes = Allreduce().recv_wire_bytes(
        sum(payload_nbytes(FP16Compressor(), l) for l in leaves),
        n_elems, 8)
    comp_bytes = Allgather().recv_wire_bytes(
        sum(payload_nbytes(TopKCompressor(compress_ratio=0.3), l)
            for l in leaves), n_elems, 8)
    assert esc_bytes != comp_bytes

    reader = TelemetryReader(sink=None, every=100)
    for _ in range(3):
        state, _ = step(state, (x, y))
    state = set_fallback_flag(state, True)     # force the dense window
    for _ in range(3):
        state, _ = step(state, (x, y))
    state = set_fallback_flag(state, False)    # re-arm
    for _ in range(3):
        state, _ = step(state, (x, y))

    records = reader.flush(state)
    wire = [r["wire_bytes"] for r in records]
    flags = [r["fallback"] for r in records]
    err = [r["compression_error"] for r in records]
    assert wire == [comp_bytes] * 3 + [esc_bytes] * 3 + [comp_bytes] * 3
    assert flags == [0.0] * 3 + [1.0] * 3 + [0.0] * 3
    # During the dense window the codec is bypassed: effective error ~0.
    assert all(e == 0.0 for e in err[3:6])
    assert all(e > 0.0 for e in err[:3] + err[6:])
    assert all(r["dense_bytes"] == sum(l.size * 4 for l in leaves)
               for r in records)


# ---------------------------------------------------------------------------
# GuardMonitor transition edges + sink wiring
# ---------------------------------------------------------------------------

def _report(nf=0, fb_remaining=0, consecutive=0, step=0):
    return {"step": step, "notfinite_count": nf, "last_bad_step": -1,
            "consecutive": consecutive, "fallback_remaining": fb_remaining,
            "fallback_active": fb_remaining > 0}


@pytest.mark.telemetry
def test_guard_monitor_transition_edges():
    """Re-arm must fire on the EXACT boundary step: the first report whose
    fallback_active drops to False, not one step later (and never twice)."""
    sink = _ListSink()
    lines = []
    mon = GuardMonitor(printer=lambda *a: lines.append(" ".join(map(str, a))),
                       sink=sink)
    reports = [
        _report(step=0),                                   # healthy
        _report(step=1, nf=1, consecutive=1),              # skip
        _report(step=2, nf=2, consecutive=2, fb_remaining=3),  # engage
        _report(step=3, nf=2, fb_remaining=2),             # dense window
        _report(step=4, nf=2, fb_remaining=1),
        _report(step=5, nf=2, fb_remaining=0),             # re-arm boundary
        _report(step=6, nf=2),                             # stays quiet
    ]
    for i, rep in enumerate(reports):
        mon.update(i, rep)

    events = [(r["event"], r["step"]) for r in sink.records]
    assert ("guard_skip", 1) in events
    assert ("guard_skip", 2) in events
    assert ("guard_fallback_engaged", 2) in events
    assert events.count(("guard_rearmed", 5)) == 1
    assert not any(e == "guard_rearmed" and s != 5 for e, s in events)
    assert any("re-armed" in l for l in lines)


# ---------------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_jsonl_sink_header_once_and_multisink(tmp_path):
    path = tmp_path / "s.jsonl"
    other = _ListSink()
    sink = MultiSink(JSONLSink(path, provenance={"data": "synthetic"}),
                     other)
    sink.write({"step": 0, "loss": 1.5})
    sink.write({"step": 1, "loss": np.float32(1.25)})  # numpy scalars ok
    sink.close()
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines[0] == {"provenance": {"data": "synthetic"}}
    assert lines[1:] == [{"step": 0, "loss": 1.5},
                         {"step": 1, "loss": 1.25}]
    assert len(other.records) == 2
    with pytest.raises(ValueError):
        sink.sinks[0].write({"step": 2})


@pytest.mark.telemetry
def test_tensorboard_sink_writes_valid_event_frames(tmp_path):
    logdir = tmp_path / "tb"
    with TensorBoardSink(logdir, tag_prefix="grace") as sink:
        sink.write({"step": 3, "loss": 0.5, "note": "skipped-nonnumeric"})
        sink.write({"step": 4, "grad_norm": 1.25})
    files = list(logdir.glob("events.out.tfevents.*"))
    assert len(files) == 1
    data = files[0].read_bytes()

    events = []
    off = 0
    while off < len(data):
        (length,) = struct.unpack_from("<Q", data, off)
        (len_crc,) = struct.unpack_from("<I", data, off + 8)
        assert len_crc == masked_crc(data[off:off + 8])
        payload = data[off + 12:off + 12 + length]
        (data_crc,) = struct.unpack_from("<I", data, off + 12 + length)
        assert data_crc == masked_crc(payload)
        events.append(payload)
        off += 12 + length + 4
    assert off == len(data)            # no trailing garbage
    assert b"brain.Event:2" in events[0]
    assert b"grace/loss" in events[1]
    assert b"note" not in events[1]    # non-numeric fields skipped
    assert b"grace/grad_norm" in events[2]


# ---------------------------------------------------------------------------
# chaos_smoke telemetry artifact (CI wiring)
# ---------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
@pytest.mark.telemetry
def test_chaos_smoke_writes_telemetry_artifact(tmp_path):
    """The smoke tool must leave a non-empty, provenance-stamped telemetry
    JSONL behind — the artifact CI archives for every resilience run."""
    smoke = _load_tool("chaos_smoke")
    out = tmp_path / "chaos_telemetry.jsonl"
    rc = smoke.main(["--steps", "12", "--nan-prob", "1.0", "--batch", "16",
                     "--fallback-after", "2", "--fallback-steps", "4",
                     "--telemetry-out", str(out), "--telemetry-every", "6"])
    assert rc == 0

    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert lines, "telemetry artifact is empty"
    assert lines[0]["provenance"]["tool"] == "chaos_smoke"
    assert lines[-1], "last telemetry record is empty"
    metric_rows = [l for l in lines[1:] if "grad_norm" in l]
    assert metric_rows, "no per-step metric rows in the artifact"
    for rec in metric_rows:
        for field in REQUIRED:
            assert field in rec, field
    # nan_prob=1.0: every accepted step ran inside a dense-fallback window,
    # and the guard's transition events landed in the same stream.
    assert all(r["fallback"] == 1.0 for r in metric_rows)
    events = {l["event"] for l in lines[1:] if "event" in l}
    assert "guard_skip" in events and "guard_fallback_engaged" in events


# ---------------------------------------------------------------------------
# report tool
# ---------------------------------------------------------------------------

@pytest.mark.telemetry
def test_telemetry_report_renders_summary(tmp_path, capsys):
    report = _load_tool("telemetry_report")
    path = tmp_path / "r.jsonl"
    sink = JSONLSink(path, provenance={"data": "synthetic",
                                       "git_commit": "abc123"})
    for i in range(6):
        fb = 1.0 if i in (2, 3) else 0.0
        sink.write({"step": i, "grad_norm": 1.0 + i, "update_norm": 1.0,
                    "residual_norm": 0.1, "residual_max": 0.2,
                    "compression_error": 0.0 if fb else 0.4,
                    "wire_bytes": 168.0 if fb else 200.0,
                    "dense_bytes": 336.0, "fallback": fb})
    sink.write({"event": "guard_skip", "step": 2, "notfinite_count": 1})
    sink.close()

    provenance, records, events = report.load(str(path))
    assert len(records) == 6 and len(events) == 1
    text = report.render(provenance, records, events)
    assert "git_commit: abc123" in text
    assert "grad_norm" in text and "compression_error" in text
    assert "dense-fallback windows (recorded steps): 2..3" in text
    assert "ratio 0.5635" in text          # (4*200+2*168)/(6*336)
    assert "guard_skip" in text
    assert report.main([str(path)]) == 0
    capsys.readouterr()                    # swallow the printed report


# ---------------------------------------------------------------------------
# field registry sanity
# ---------------------------------------------------------------------------

def test_fields_registry_matches_required():
    assert tuple(name for name, _ in FIELDS) == REQUIRED
    assert all(agg in ("mean", "max", "first") for _, agg in FIELDS)
