"""Tests for the multi-host sync/metric utilities and LR warmup schedule.

Single-process semantics are exercised directly (broadcast_tree/metric_average
are identity/mean there by contract); the multi-process branch is the thin
multihost_utils call, which cannot run in a single-process suite.
"""

import subprocess
import sys

import jax.numpy as jnp
import numpy as np

from grace_tpu.parallel import broadcast_tree, metric_average
from grace_tpu.train import warmup_schedule


def test_import_does_not_initialize_backend():
    """Regression: a module-level `jnp.uint32(...)` constant once made
    `import grace_tpu` initialize the jax backend, foreclosing platform
    selection (the CPU-mesh pinning in conftest/dryrun/examples) and
    `jax.distributed.initialize` — and hanging outright when the default
    platform's tunnel was unhealthy. Library import must stay device-free."""
    code = ("import grace_tpu; from jax._src import xla_bridge; "
            "raise SystemExit(1 if xla_bridge._backends else 0)")
    proc = subprocess.run([sys.executable, "-c", code], timeout=120,
                          capture_output=True, text=True,
                          env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                               "PYTHONPATH": ":".join(sys.path)})
    assert proc.returncode == 0, (proc.stdout, proc.stderr)


class TestBroadcastTree:
    def test_single_process_identity(self):
        tree = {"w": np.arange(6.0).reshape(2, 3), "b": np.float32(1.5)}
        out = broadcast_tree(tree)
        np.testing.assert_array_equal(out["w"], tree["w"])
        assert out["b"] == tree["b"]


class TestMetricAverage:
    def test_single_process_mean_is_identity(self):
        metrics = {"loss": 0.25, "acc": np.float64(0.9)}
        out = metric_average(metrics)
        assert float(out["loss"]) == 0.25
        assert float(out["acc"]) == 0.9


class TestMultiAxisMesh:
    def test_grace_trains_on_data_axis_of_2d_mesh(self):
        """The named-axis claim (parallel/__init__.py docstring): grace runs
        on the 'data' axis of a ('data','model') mesh unchanged — model-axis
        dims just replicate, so TP can be layered in later without touching
        the compression pipeline."""
        import jax
        import jax.numpy as jnp
        import optax

        from grace_tpu import grace_from_params
        from grace_tpu.parallel import make_mesh
        from grace_tpu.train import init_train_state, make_train_step
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh((4, 2), ("data", "model"))
        grace = grace_from_params({"compressor": "topk",
                                   "compress_ratio": 0.25,
                                   "memory": "residual",
                                   "communicator": "allgather"})
        tx = optax.chain(grace.transform(seed=0), optax.sgd(0.1))

        def loss_fn(params, batch):
            x, y = batch
            pred = x @ params["w"]
            return jnp.mean((pred - y) ** 2)

        params = {"w": jnp.ones((8, 1))}
        state = init_train_state(params, tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)

        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
        y = (x @ np.linspace(-1, 1, 8).reshape(8, 1)).astype(jnp.float32)
        batch = jax.device_put((x, y), NamedSharding(mesh, P("data")))

        first = None
        for _ in range(15):
            state, loss = step(state, batch)
            first = float(loss) if first is None else first
        assert float(loss) < first * 0.5, (first, float(loss))


class TestWarmupSchedule:
    def test_ramp_endpoints(self):
        # Reference semantics (LearningRateWarmupCallback): start at base_lr,
        # reach base_lr * world_size at warmup end, then hold.
        sched = warmup_schedule(base_lr=0.1, world_size=8, warmup_steps=100)
        assert np.isclose(float(sched(0)), 0.1)
        assert np.isclose(float(sched(50)), 0.1 + (0.8 - 0.1) * 0.5)
        assert np.isclose(float(sched(100)), 0.8)
        assert np.isclose(float(sched(10_000)), 0.8)

    def test_after_schedule_takes_over(self):
        decay = lambda t: 0.8 * 0.5 ** (t / 10.0)
        sched = warmup_schedule(0.1, 8, 10, after=decay)
        assert np.isclose(float(sched(5)), 0.1 + 0.7 * 0.5)
        assert np.isclose(float(sched(10)), 0.8)    # t_after = 0
        assert np.isclose(float(sched(20)), 0.4)    # one half-life after warmup

    def test_jit_traceable(self):
        import jax
        sched = warmup_schedule(0.1, 4, 10)
        vals = jax.jit(jax.vmap(sched))(jnp.arange(12))
        assert vals.shape == (12,)
        assert float(vals[0]) < float(vals[-1])

    def test_works_in_optax_chain(self):
        import jax
        import optax
        sched = warmup_schedule(0.05, 2, 5)
        tx = optax.sgd(learning_rate=sched)
        params = {"w": jnp.ones(3)}
        state = tx.init(params)
        grads = {"w": jnp.ones(3)}
        updates, state = jax.jit(tx.update)(grads, state, params)
        # step 0 update = -base_lr * grad
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.05, rtol=1e-6)


class TestInitializeDistributed:
    """VERDICT round-3 item 9: the auto-detect path must not swallow a
    *mis-configured* cluster env (silently training as independent
    single-process replicas); only a genuinely marker-free environment
    downgrades to a no-op."""

    _MARKERS = ("SLURM_JOB_ID", "SLURM_PROCID", "OMPI_COMM_WORLD_RANK",
                "OMPI_COMM_WORLD_SIZE", "PMI_RANK", "PMI_SIZE",
                "JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS")

    def test_marker_free_env_is_noop(self, monkeypatch):
        from grace_tpu.parallel import initialize_distributed
        for v in self._MARKERS:
            monkeypatch.delenv(v, raising=False)
        initialize_distributed()   # must not raise

    def test_partial_cluster_env_raises(self, monkeypatch):
        import pytest

        from grace_tpu.parallel import initialize_distributed
        for v in self._MARKERS:
            monkeypatch.delenv(v, raising=False)
        # SLURM job id present but no rank/size/coordinator: a cluster that
        # *almost* auto-detects must die loudly, naming the marker.
        monkeypatch.setenv("SLURM_JOB_ID", "12345")
        with pytest.raises(RuntimeError, match="SLURM_JOB_ID"):
            initialize_distributed()
