"""Fused Pallas chunk-Top-K local pipeline vs the plain XLA path.

The Communicator.step fast path (core.py) collapses compensate -> compress
-> residual-update into ops/pallas_topk.py's one-pass kernel whenever the
memory declares linear error feedback. These tests pin the contract: the
fused path must be BIT-IDENTICAL to the staged path — payload, exchanged
output, and residual state — across awkward paddings, feedback
coefficients, and the bf16 wire format. Interpreter mode runs the same
kernel code on CPU (use_pallas=True off-TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu.parallel import shard_map
from grace_tpu.comm import Identity
from grace_tpu.compressors import TopKCompressor
from grace_tpu.memories import EFSignSGDMemory, ResidualMemory
from grace_tpu.ops.pallas_topk import chunk_compress_feedback


def _step(compressor, memory, x, resid, rng):
    comm = Identity(axis_name="data")
    from jax.sharding import Mesh, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def body(x, resid):
        return comm.step(x, resid, None, memory, compressor, rng)[:2]

    return shard_map(body, mesh=mesh, in_specs=(P(), P()),
                         out_specs=(P(), P()), check_vma=False)(x, resid)


@pytest.mark.parametrize("n,ratio", [(1000, 0.01), (1003, 0.013),
                                     (4096, 0.25), (257, 0.04)])
def test_fused_step_bit_identical(n, ratio):
    key = jax.random.key(0)
    x = jax.random.normal(key, (n,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (n,), jnp.float32) * 0.1
    rng = jax.random.key(2)
    mem = ResidualMemory()
    plain = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                           use_pallas=False)
    fused = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                           use_pallas=True)
    out_p, mem_p = _step(plain, mem, x, resid, rng)
    out_f, mem_f = _step(fused, mem, x, resid, rng)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_f))
    np.testing.assert_array_equal(np.asarray(mem_p), np.asarray(mem_f))


def test_fused_respects_feedback_coeffs():
    n = 2048
    x = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    resid = jax.random.normal(jax.random.key(1), (n,), jnp.float32)
    rng = jax.random.key(2)
    for mem in (ResidualMemory(beta=0.9, gamma=0.5), EFSignSGDMemory(lr=0.3)):
        plain = TopKCompressor(compress_ratio=0.05, algorithm="chunk",
                               use_pallas=False)
        fused = TopKCompressor(compress_ratio=0.05, algorithm="chunk",
                               use_pallas=True)
        out_p, mem_p = _step(plain, mem, x, resid, rng)
        out_f, mem_f = _step(fused, mem, x, resid, rng)
        np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_f),
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(mem_p), np.asarray(mem_f),
                                   rtol=0, atol=1e-6)


def test_fused_bf16_wire_rounding_lands_in_residual():
    n = 3000
    x = jax.random.normal(jax.random.key(0), (n,), jnp.float32) * 3.7
    resid = jnp.zeros((n,), jnp.float32)
    rng = jax.random.key(2)
    mem = ResidualMemory()
    plain = TopKCompressor(compress_ratio=0.02, algorithm="chunk",
                           wire_dtype="bfloat16", use_pallas=False)
    fused = TopKCompressor(compress_ratio=0.02, algorithm="chunk",
                           wire_dtype="bfloat16", use_pallas=True)
    out_p, mem_p = _step(plain, mem, x, resid, rng)
    out_f, mem_f = _step(fused, mem, x, resid, rng)
    np.testing.assert_array_equal(np.asarray(out_p), np.asarray(out_f))
    np.testing.assert_array_equal(np.asarray(mem_p), np.asarray(mem_f))
    # the rounding error must be non-trivially present (bf16 has 8 mantissa
    # bits; 3.7-scaled normals round visibly)
    assert float(jnp.abs(mem_f).max()) > 0


def test_kernel_indices_in_range_and_unique():
    for n, ratio in [(1000, 0.01), (999, 0.1), (130, 0.5)]:
        k = max(1, int(n * ratio))
        if n < 2 * k:
            continue
        flat = jax.random.normal(jax.random.key(3), (n,), jnp.float32)
        vals, win, resid = chunk_compress_feedback(
            flat, None, k, interpret=True)
        idx = np.asarray(win) * k + np.arange(k)
        assert idx.max() < n and idx.min() >= 0
        assert len(np.unique(idx)) == k
        # winners zeroed, losers intact
        dense = np.zeros(n, np.float32)
        dense[idx] = np.asarray(vals)
        np.testing.assert_allclose(np.asarray(resid),
                                   np.asarray(flat) - dense, atol=1e-7)


def test_nan_column_keeps_indices_in_range():
    n, k = 1000, 10
    flat = jax.random.normal(jax.random.key(0), (n,), jnp.float32)
    flat = flat.at[437].set(jnp.nan)        # poisons column 437 % 10 = 7
    vals, win, resid = chunk_compress_feedback(flat, None, k, interpret=True)
    idx = np.asarray(win) * k + np.arange(k)
    assert idx.max() < n and idx.min() >= 0
    assert len(np.unique(idx)) == k
    # the NaN lane stays visible in the residual (not silently dropped)
    assert np.isnan(np.asarray(resid)).any()


def test_vmem_overflow_ratio_falls_back():
    # ~10k rows at ratio 1e-4 cannot fit 128-lane f32 blocks in the VMEM
    # budget; the fused hook must decline rather than blow compilation.
    from grace_tpu.ops.pallas_topk import (aggregate_block_cols,
                                           compress_block_cols)
    assert compress_block_cols(10_000) == 0
    # pod-scale worlds inflate the aggregate kernel's input blocks
    assert aggregate_block_cols(4, 65536) == 0
    assert aggregate_block_cols(4, 8) >= 128
    comp = TopKCompressor(compress_ratio=1e-4, algorithm="chunk",
                          use_pallas=True)
    x = jnp.ones((200_000,), jnp.float32)
    st = jnp.zeros((200_000,), jnp.float32)
    assert comp.fused_feedback_compress(x, st, (1.0, 1.0),
                                        jax.random.key(0)) is None


def test_bf16_buffer_falls_back_to_staged_path():
    comp = TopKCompressor(compress_ratio=0.1, algorithm="chunk",
                          use_pallas=True)
    x = jnp.ones((1000,), jnp.bfloat16)
    st = jnp.zeros((1000,), jnp.bfloat16)
    assert comp.fused_feedback_compress(x, st, (1.0, 1.0),
                                        jax.random.key(0)) is None


@pytest.mark.parametrize("world,n,ratio", [(1, 1000, 0.01), (4, 1003, 0.013),
                                           (8, 4096, 0.25),
                                           # > _AGG_UNROLL_MAX: exercises the
                                           # lax.fori_loop accumulation path
                                           (40, 1000, 0.01)])
def test_aggregate_kernel_matches_staged_exchange(world, n, ratio):
    """Exchange-side kernel == vmapped one-hot decompress + sum + average,
    including colliding indices across ranks and the tail row."""
    from grace_tpu.ops.pallas_topk import chunk_aggregate_dense

    from grace_tpu.compressors.topk import static_k
    comp = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                          use_pallas=False)
    k = static_k(n, ratio)
    if n < 2 * k:
        pytest.skip("degenerate")
    xs = jax.random.normal(jax.random.key(0), (world, n), jnp.float32)
    payloads = [comp.compress(xs[w], None, jax.random.key(1))[0]
                for w in range(world)]
    vals = jnp.stack([p[0] for p in payloads])
    idx = jnp.stack([p[1] for p in payloads])
    ctx = (n, (n,), jnp.float32)

    staged = jnp.mean(jax.vmap(
        lambda v, i: comp.decompress((v, i), ctx))(vals, idx), axis=0)
    fused = chunk_aggregate_dense(vals, (idx // k).astype(jnp.int32), k, n,
                                  average=True, interpret=True)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(staged),
                               rtol=0, atol=1e-6)

    hook = TopKCompressor(compress_ratio=ratio, algorithm="chunk",
                          use_pallas=True)
    out = hook.fused_aggregate_decompress((vals, idx), ctx, world)
    if world == 1:
        assert out is not None
        np.testing.assert_allclose(np.asarray(out), np.asarray(staged),
                                   rtol=0, atol=1e-6)
    else:
        # interpret mode declines multi-device worlds (deadlock guard)
        assert out is None


def test_non_chunk_and_tiny_k_fall_back():
    mem_state = jnp.zeros((100,), jnp.float32)
    x = jnp.ones((100,), jnp.float32)
    rng = jax.random.key(0)
    exact = TopKCompressor(compress_ratio=0.1, algorithm="exact",
                           use_pallas=True)
    assert exact.fused_feedback_compress(x, mem_state, (1.0, 1.0), rng) is None
    huge_k = TopKCompressor(compress_ratio=0.9, algorithm="chunk",
                            use_pallas=True)
    assert huge_k.fused_feedback_compress(x, mem_state, (1.0, 1.0), rng) \
        is None
    off = TopKCompressor(compress_ratio=0.1, algorithm="chunk",
                         use_pallas=False)
    assert off.fused_feedback_compress(x, mem_state, (1.0, 1.0), rng) is None
