"""True multi-process distributed tests: 2 processes, gloo, real DCN path.

The reference exercises multi-node behavior only on live NCCL/MPI clusters
(SURVEY.md §4: no fakes, no CI). The single-process suite simulates ranks as
mesh devices; THIS file covers what that cannot: `jax.distributed`
bring-up through `grace_tpu.parallel.initialize_distributed`, cross-process
collectives, and the multi-process branches of `broadcast_tree` /
`metric_average` (test_parallel.py covers only their single-process
identity paths).

Each test launches two subprocess workers that rendezvous on a fresh local
port. Workers run the FULL compressed pipeline over a 4-device mesh (2
devices per process), so the grace exchange genuinely crosses a process
boundary. Workers print machine-checkable lines; the parent asserts both
processes agree and match the expected values.
"""

import socket
import subprocess
import sys

import pytest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_WORKER = r'''
import sys
import jax
jax.config.update("jax_platforms", "cpu")
from grace_tpu.parallel import set_cpu_device_count
set_cpu_device_count(2)   # 2 local -> 4 global devices

port, pid = sys.argv[1], int(sys.argv[2])
from grace_tpu.parallel import (broadcast_tree, data_parallel_mesh,
                                initialize_distributed, metric_average)
initialize_distributed(f"127.0.0.1:{port}", num_processes=2, process_id=pid)
assert jax.process_count() == 2, jax.process_count()

import jax.numpy as jnp
import numpy as np
import optax
from grace_tpu import grace_from_params
from grace_tpu.train import init_train_state, make_train_step

mesh = data_parallel_mesh()            # 4 global devices
W = mesh.devices.size
assert W == 4, W

# Deterministic problem, identical on both hosts by construction.
rng = np.random.default_rng(0)
Wt = rng.standard_normal((12, 4))
x = rng.standard_normal((64, 12)).astype(np.float32)
y = np.argmax(x @ Wt, axis=1).astype(np.int32)

def loss_fn(params, batch):
    xb, yb = batch
    logits = xb @ params["w"] + params["b"]
    return optax.softmax_cross_entropy_with_integer_labels(logits, yb).mean()

grc = grace_from_params({"compressor": sys.argv[3],
                         "memory": sys.argv[4],
                         "communicator": sys.argv[5],
                         "compress_ratio": 0.5})
tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
params = {"w": jnp.zeros((12, 4)), "b": jnp.zeros((4,))}
state = init_train_state(params, tx, mesh)
step = make_train_step(loss_fn, tx, mesh, donate=False)

from jax.sharding import NamedSharding, PartitionSpec as P
sharding = NamedSharding(mesh, P("data"))
batch = (jax.make_array_from_process_local_data(
             sharding, x[pid * 32:(pid + 1) * 32], (64, 12)),
         jax.make_array_from_process_local_data(
             NamedSharding(mesh, P("data")), y[pid * 32:(pid + 1) * 32],
             (64,)))

losses = []
for _ in range(10):
    state, loss = step(state, batch)
    losses.append(float(jax.device_get(loss)))
print(f"LOSSES {pid} {losses[0]:.6f} {losses[-1]:.6f}", flush=True)

# Final params digest must be identical across processes (replicated).
digest = float(sum(np.abs(np.asarray(jax.device_get(l))).sum()
                   for l in jax.tree_util.tree_leaves(state.params)))
print(f"DIGEST {pid} {digest:.8f}", flush=True)

# broadcast_tree: root's value wins on every process.
tree = {"v": np.full(3, float(pid))}
out = broadcast_tree(tree, root_process=0)
print(f"BCAST {pid} {out['v'].tolist()}", flush=True)

# metric_average: mean over the two processes' host-side values.
avg = metric_average({"acc": float(pid)})   # 0.0 and 1.0 -> 0.5
print(f"AVG {pid} {float(avg['acc']):.4f}", flush=True)
'''


def _run_pair(compressor, memory, communicator, timeout=420):
    port = _free_port()
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(port), str(i),
         compressor, memory, communicator],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        # A hung or failed worker (collective deadlock — the failure mode
        # this suite exists to catch) must not outlive the test and starve
        # the rest of the session.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2500:]}"
    return outs


def _field(out, tag):
    for line in out.splitlines():
        if line.startswith(tag + " "):
            return line.split(" ", 2)[2]
    raise AssertionError(f"{tag} line missing in:\n{out[-2000:]}")


@pytest.mark.slow
@pytest.mark.parametrize("cfg", [
    ("topk", "residual", "allgather"),
    ("signsgd", "none", "allreduce"),
    ("topk", "residual", "twoshot"),
], ids=lambda c: "-".join(c))
def test_two_process_training_agrees_and_learns(cfg):
    outs = _run_pair(*cfg)
    first0, last0 = map(float, _field(outs[0], "LOSSES").split())
    first1, last1 = map(float, _field(outs[1], "LOSSES").split())
    # replicated loss: both processes observe the same values
    assert abs(first0 - first1) < 1e-5 and abs(last0 - last1) < 1e-5
    assert last0 < first0, (first0, last0)      # it actually learns
    assert _field(outs[0], "DIGEST") == _field(outs[1], "DIGEST")


@pytest.mark.slow
def test_multiprocess_broadcast_and_metric_average():
    outs = _run_pair("none", "none", "allreduce")
    # root (process 0) value [0,0,0] wins on both processes
    assert _field(outs[0], "BCAST") == _field(outs[1], "BCAST") \
        == "[0.0, 0.0, 0.0]"
    assert _field(outs[0], "AVG") == _field(outs[1], "AVG") == "0.5000"
