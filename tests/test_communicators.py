"""Communicator semantics on a real 8-device (simulated CPU) mesh.

This is the "fake backend" the reference never had (SURVEY.md §4): genuine
all_gather/psum collectives, single process.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from grace_tpu.parallel import shard_map
from grace_tpu import comm
from grace_tpu import compressors as C

W = 8


def run_exchange(mesh, communicator, compressor, per_rank, state=None, seed=0):
    """per_rank: [W, ...] array, one slice per rank; returns one rank's output."""

    def body(x):
        x = x[0]  # shard_map gives [1, ...] per device on the data axis
        st = state if state is not None else compressor.init_state(x)
        payload, ctx, _ = compressor.compress(x, st, jax.random.key(seed))
        return communicator.exchange(payload, ctx, compressor)[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                       out_specs=P("data"), check_vma=False)
    return np.asarray(fn(per_rank)[0])


def test_allreduce_none_average(mesh, rng):
    x = rng.normal(size=(W, 16)).astype(np.float32)
    out = run_exchange(mesh, comm.Allreduce(), C.NoneCompressor(), jnp.asarray(x))
    np.testing.assert_allclose(out, x.mean(0), rtol=1e-5)


def test_allreduce_none_sum(mesh, rng):
    x = rng.normal(size=(W, 16)).astype(np.float32)
    out = run_exchange(mesh, comm.Allreduce(), C.NoneCompressor(average=False),
                       jnp.asarray(x))
    np.testing.assert_allclose(out, x.sum(0), rtol=1e-5)


def test_allgather_topk(mesh, rng):
    x = rng.normal(size=(W, 50)).astype(np.float32)
    comp = C.TopKCompressor(compress_ratio=0.2)
    out = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    # expected: mean over ranks of each rank's top-10-sparsified tensor
    expect = np.zeros((W, 50), np.float32)
    for r in range(W):
        idx = np.argsort(-np.abs(x[r]))[:10]
        expect[r, idx] = x[r, idx]
    np.testing.assert_allclose(out, expect.mean(0), rtol=1e-5)


def test_allgather_signsgd_majority_vote(mesh):
    # 5 ranks positive, 3 negative at coord 0; opposite at coord 1
    col0 = np.array([1, 1, 1, 1, 1, -1, -1, -1], np.float32)
    x = np.stack([col0, -col0], axis=1)
    comp = C.SignSGDCompressor()
    out = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    np.testing.assert_array_equal(out, [1.0, -1.0])


def test_allgather_qsgd_per_rank_norms(mesh, rng):
    """Each rank has a different norm; ctx-replication contract must hold."""
    x = (rng.normal(size=(W, 40)) * np.arange(1, W + 1)[:, None]).astype(np.float32)
    comp = C.QSGDCompressor(quantum_num=127)
    out = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    # error per rank bounded by its norm/q; mean over ranks
    bound = np.linalg.norm(x, axis=1).sum() / 127 / W + 1e-5
    assert np.max(np.abs(out - x.mean(0))) <= bound


def test_allgather_randomk_shared_indices(mesh, rng):
    x = rng.normal(size=(W, 30)).astype(np.float32)
    comp = C.RandomKCompressor(compress_ratio=0.5)
    out = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x), seed=3)
    # all ranks picked the same indices -> result is mean of x at those coords
    nz = out != 0
    assert nz.sum() == 15
    np.testing.assert_allclose(out[nz], x.mean(0)[nz], rtol=1e-5)


def test_broadcast_equals_allgather(mesh, rng):
    x = rng.normal(size=(W, 24)).astype(np.float32)
    comp = C.FP16Compressor()
    a = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    b = run_exchange(mesh, comm.Broadcast(), comp, jnp.asarray(x))
    np.testing.assert_array_equal(a, b)


def test_sign_allreduce_matches_allgather_majority(mesh, rng):
    """psum-based majority vote == allgather + SignSGD.aggregate (SURVEY.md
    §7 hard part 4) — same result, fixed-cost collective."""
    x = rng.normal(size=(W, 33)).astype(np.float32)
    comp = C.SignSGDCompressor()
    via_gather = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    via_psum = run_exchange(mesh, comm.SignAllreduce(), comp, jnp.asarray(x))
    np.testing.assert_array_equal(via_gather, via_psum)
    assert set(np.unique(via_psum)) <= {-1.0, 1.0}


def test_sign_allreduce_rejects_non_vote_compressors(mesh, rng):
    import pytest
    x = rng.normal(size=(W, 16)).astype(np.float32)
    with pytest.raises(TypeError, match="majority-vote"):
        run_exchange(mesh, comm.SignAllreduce(), C.TopKCompressor(0.5),
                     jnp.asarray(x))
    # average=False is NOT sufficient: EF-SignSGD's aggregate divides by lr,
    # which the re-sign would silently drop.
    with pytest.raises(TypeError, match="majority-vote"):
        run_exchange(mesh, comm.SignAllreduce(), C.EFSignSGDCompressor(),
                     jnp.asarray(x))


def test_allreduce_routes_sign_methods_through_vote(mesh, rng):
    """Regression: 'allreduce' + signsgd once psummed the packed sign BYTES
    and decompressed the byte-sum — garbage votes that made toy training
    climb. The generic Allreduce must route vote_aggregate compressors
    through the psum majority vote (== allgather + aggregate)."""
    x = rng.normal(size=(W, 33)).astype(np.float32)
    comp = C.SignSGDCompressor()
    via_gather = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
    via_allreduce = run_exchange(mesh, comm.Allreduce(), comp, jnp.asarray(x))
    np.testing.assert_array_equal(via_gather, via_allreduce)


def test_allreduce_rejects_non_summable_payloads(mesh, rng):
    """The reference only documents the Allreduce compatibility matrix
    (IMPLEMENTING.md:43-45) and silently sums Top-K values belonging to
    different per-rank indices; here the combination is a TypeError."""
    import pytest
    x = rng.normal(size=(W, 16)).astype(np.float32)
    for comp in [C.TopKCompressor(0.5), C.QSGDCompressor(),
                 C.OneBitCompressor(), C.EFSignSGDCompressor()]:
        with pytest.raises(TypeError, match="summable_payload"):
            run_exchange(mesh, comm.Allreduce(), comp, jnp.asarray(x))


def test_sign_allreduce_from_params(mesh, rng):
    from grace_tpu import grace_from_params
    g = grace_from_params({"compressor": "signum",
                           "communicator": "sign_allreduce"})
    assert isinstance(g.communicator, comm.SignAllreduce)
    x = rng.normal(size=(W, 16)).astype(np.float32)
    out = run_exchange(mesh, g.communicator, g.compressor, jnp.asarray(x))
    assert set(np.unique(out)) <= {-1.0, 1.0}


def test_powersgd_inside_compress(mesh, rng):
    """PowerSGD's collectives run inside compress; empty payload path."""
    x = rng.normal(size=(W, 12, 6)).astype(np.float32)
    comp = C.PowerSGDCompressor(rank=6, axis_name="data")

    out = run_exchange(mesh, comm.Allreduce(), comp, jnp.asarray(x))
    # rank 6 >= min(n, m) = 6 -> reconstruction should approximate the mean
    np.testing.assert_allclose(out, x.mean(0), atol=1e-3)


def test_powersgd_hwio_matricization(mesh, rng):
    """4-D conv kernels factor on the output-channel (last) dim — the
    (shape[0], -1) rule of the torch reference would give a degenerate
    (3, rest) matrix for HWIO layouts (wire cost > dense; see
    compressors/powersgd.py docstring)."""
    x = rng.normal(size=(W, 3, 3, 4, 8)).astype(np.float32)
    comp = C.PowerSGDCompressor(rank=4, axis_name="data")

    q0 = comp.init_state(jnp.asarray(x[0]))
    # Q factors over the 8-channel output dim, not the 3-tall kernel dim.
    assert q0.shape == (8, 4)

    out = run_exchange(mesh, comm.Allreduce(), comp, jnp.asarray(x))
    assert out.shape == x.shape[1:]
    # rank-4 truncation of a (36, 8) matrix: inexact but must be a real
    # low-rank approximation of the mean, not garbage.
    err = np.linalg.norm(out - x.mean(0)) / np.linalg.norm(x.mean(0))
    assert err < 0.9, err


def test_powersgd_1d_bypass(mesh, rng):
    x = rng.normal(size=(W, 9)).astype(np.float32)
    comp = C.PowerSGDCompressor(rank=2, axis_name="data")
    out = run_exchange(mesh, comm.Allreduce(), comp, jnp.asarray(x))
    np.testing.assert_allclose(out, x.mean(0), rtol=1e-5)


def test_allreduce_int_payload_average_raises(mesh, rng):
    x = rng.normal(size=(W, 16)).astype(np.float32)
    try:
        run_exchange(mesh, comm.Allreduce(), C.QSGDCompressor(quantum_num=64),
                     jnp.asarray(x))
        raised = False
    except TypeError:
        raised = True
    assert raised


def run_step(mesh, communicator, compressor, memory, per_rank, seed=0):
    """Full pipeline step (compensate→compress→update→exchange) per rank;
    returns (output, new_mem_state) for rank 0."""

    def body(x):
        x = x[0]
        ms = memory.init_state(x)
        cs = compressor.init_state(x)
        out, ms, _ = communicator.step(x, ms, cs, memory, compressor,
                                       jax.random.key(seed))
        ms_leaf = ms if ms is not None else jnp.zeros_like(x)
        return out[None], ms_leaf[None]

    fn = shard_map(body, mesh=mesh, in_specs=P("data"),
                       out_specs=(P("data"), P("data")), check_vma=False)
    out, ms = fn(per_rank)
    return np.asarray(out[0]), np.asarray(ms[0])


class TestTwoShotAllreduce:
    """Scatter-reduce-recompress all-reduce (O(k) wire vs allgather's O(Wk))."""

    def test_none_equals_dense_mean(self, mesh, rng):
        from grace_tpu.memories import NoneMemory
        x = rng.normal(size=(W, 41)).astype(np.float32)  # 41: exercises padding
        out, _ = run_step(mesh, comm.TwoShotAllreduce(), C.NoneCompressor(),
                          NoneMemory(), jnp.asarray(x))
        np.testing.assert_allclose(out, x.mean(0), rtol=1e-6)

    def test_signsgd_equals_allgather_vote(self, mesh, rng):
        """Vote is elementwise, so chunking cannot change it, and stage-2
        sign-compression of ±1 is lossless: two-shot == allgather, exactly."""
        from grace_tpu.memories import NoneMemory
        x = rng.normal(size=(W, 53)).astype(np.float32)
        comp = C.SignSGDCompressor()
        via_gather = run_exchange(mesh, comm.Allgather(), comp, jnp.asarray(x))
        via_twoshot, _ = run_step(mesh, comm.TwoShotAllreduce(), comp,
                                  NoneMemory(), jnp.asarray(x))
        np.testing.assert_array_equal(via_gather, via_twoshot)

    def test_topk_residual_memory_sees_stage1_error(self, mesh, rng):
        """ResidualMemory.update must receive the stage-1 reconstruction:
        residual + reconstruction == the compensated gradient."""
        from grace_tpu.memories import ResidualMemory
        x = rng.normal(size=(W, 64)).astype(np.float32)
        comp = C.TopKCompressor(compress_ratio=0.25)
        out, residual = run_step(mesh, comm.TwoShotAllreduce(), comp,
                                 ResidualMemory(), jnp.asarray(x))
        recon = x[0] - residual           # stage-1 decode of rank 0's chunks
        # every reconstructed lane is either 0 (dropped) or the original value
        kept = recon != 0
        np.testing.assert_allclose(recon[kept], x[0][kept], rtol=1e-6)
        assert 0 < kept.sum() <= 64 * 0.25 + 8  # per-chunk k=2 of 8 lanes

    def test_rejects_stateful_compressors(self, mesh, rng):
        import pytest
        from grace_tpu.memories import NoneMemory
        x = rng.normal(size=(W, 16)).astype(np.float32)
        with pytest.raises(TypeError, match="stateless"):
            run_step(mesh, comm.TwoShotAllreduce(), C.SignumCompressor(),
                     NoneMemory(), jnp.asarray(x))

    def test_from_params_builds_twoshot(self, mesh):
        # End-to-end convergence through grace_from_params is covered by the
        # twoshot entries in tests/test_transform.py CONFIGS.
        from grace_tpu import grace_from_params
        g = grace_from_params({"compressor": "topk", "compress_ratio": 0.3,
                               "memory": "residual",
                               "communicator": "twoshot"})
        assert isinstance(g.communicator, comm.TwoShotAllreduce)

    def test_rejects_data_derived_ctx(self, mesh, rng):
        """Stage 3 decodes every rank's gathered chunk with the rank-local
        ctx2, which is only sound for data-free ctx arrays. A codec that
        stashes e.g. its input's norm in ctx (legal under the base Ctx
        contract) must be rejected at trace time, not silently corrupt."""
        import pytest
        from grace_tpu.memories import NoneMemory

        class NormInCtx(C.NoneCompressor):
            def compress(self, x, state, rng):
                norm = jnp.maximum(jnp.linalg.norm(x), 1e-12)
                return (x / norm,), {"norm": norm}, state

            def decompress(self, payload, ctx):
                return payload[0] * ctx["norm"]

        x = rng.normal(size=(W, 32)).astype(np.float32)
        with pytest.raises(TypeError, match="data-free ctx"):
            run_step(mesh, comm.TwoShotAllreduce(), NormInCtx(),
                     NoneMemory(), jnp.asarray(x))

    def test_catalog_stateless_codecs_have_data_free_ctx(self):
        """Every stateless catalog codec must keep data-derived arrays in
        the payload (the TwoShot soundness condition, checked structurally
        by comm.ctx_is_data_free)."""
        codecs = [C.NoneCompressor(), C.FP16Compressor(),
                  C.TopKCompressor(compress_ratio=0.1),
                  C.RandomKCompressor(compress_ratio=0.1),
                  C.ThresholdCompressor(threshold=0.01),
                  C.QSGDCompressor(quantum_num=64), C.TernGradCompressor(),
                  C.SignSGDCompressor(), C.EFSignSGDCompressor(lr=0.1),
                  C.OneBitCompressor(), C.NaturalCompressor(),
                  C.DgcCompressor(compress_ratio=0.1), C.U8bitCompressor(),
                  C.SketchCompressor(bins=64),
                  C.AdaqCompressor(compress_ratio=0.1),
                  C.InceptionNCompressor()]
        for codec in codecs:
            assert comm.ctx_is_data_free(codec, 256, jnp.float32), codec

    def test_stage2_feedback_tightens_tracking(self, mesh, rng):
        """ScaleCom-style owner error feedback: with stage2_feedback the
        cumulative aggregated gradient tracks the allgather (single-loss)
        trajectory at least as closely as without it."""
        from grace_tpu.memories import ResidualMemory

        def accumulate(communicator):
            rng_local = np.random.default_rng(7)
            grads = rng_local.normal(size=(6, W, 96)).astype(np.float32)
            comp = C.TopKCompressor(compress_ratio=0.25)
            memory = ResidualMemory()

            def body(gs):
                gs = gs[:, 0]                       # (steps, n) local grads
                ms = memory.init_state(gs[0])
                total = jnp.zeros_like(gs[0])
                for t in range(gs.shape[0]):
                    out, ms, _ = communicator.step(
                        gs[t], ms, None, memory, comp, jax.random.key(t))
                    total = total + out
                return total[None]

            fn = shard_map(body, mesh=mesh, in_specs=P(None, "data"),
                               out_specs=P("data"), check_vma=False)
            return np.asarray(fn(jnp.asarray(grads))[0]), grads

        got_fb, grads = accumulate(comm.TwoShotAllreduce(stage2_feedback=True))
        got_no, _ = accumulate(comm.TwoShotAllreduce())
        ref, _ = accumulate(comm.Allgather())   # single-compression reference
        err_fb = np.linalg.norm(got_fb - ref)
        err_no = np.linalg.norm(got_no - ref)
        assert err_fb <= err_no + 1e-5, (err_fb, err_no)

    def test_stage2_feedback_rejects_dgc_memory(self, mesh, rng):
        import pytest
        from grace_tpu.memories import DgcMemory
        x = rng.normal(size=(W, 32)).astype(np.float32)
        with pytest.raises(TypeError, match="stage2_feedback"):
            run_step(mesh, comm.TwoShotAllreduce(stage2_feedback=True),
                     C.TopKCompressor(0.25), DgcMemory(), jnp.asarray(x))


def test_allreduce_chunked_psum_matches_whole(mesh, rng, monkeypatch):
    """The oversized-1-D chunked psum (comm._psum, the XLA layout-pathology
    guard) is numerically identical to one whole psum. Thresholds are
    monkeypatched small so the test exercises the chunk seams (including a
    ragged tail) without a 33M-element buffer."""
    monkeypatch.setattr(comm, "_PSUM_CHUNK_THRESHOLD", 1000)
    monkeypatch.setattr(comm, "_PSUM_CHUNK_ELEMS", 768)
    x = rng.standard_normal((W, 2500)).astype(np.float32)  # 2500 % 768 != 0
    out = run_exchange(mesh, comm.Allreduce(), C.NoneCompressor(average=False),
                       jnp.asarray(x))
    np.testing.assert_allclose(out, x.sum(0), rtol=1e-5)
    # 2-D payloads and small 1-D payloads must bypass chunking entirely.
    y = rng.standard_normal((W, 40, 12)).astype(np.float32)
    out2 = run_exchange(mesh, comm.Allreduce(),
                        C.NoneCompressor(average=False), jnp.asarray(y))
    np.testing.assert_allclose(out2, y.sum(0), rtol=1e-5)
