"""graft-elastic tests: world-resize re-sharding, slice-granular shrink,
the consensus-gated rejoin barrier, the drain controller, and the
chaos_smoke --elastic lifecycle (ISSUE 11).

The re-shard contract under test, per GraceState field family:

* ``mem`` error-feedback residuals — re-ZEROED at the new world (the PR-3
  zeroing rationale, fleet-wide);
* ``comp`` compressor state — re-INITIALIZED by ``init_state`` (zeros are
  not a valid PowerSGD Q);
* ``telem``/``watch`` rings — re-ALLOCATED at the new world with their
  step/wraparound counters reset;
* replicated bookkeeping (count, rng_key, fallback, audit) and everything
  outside GraceState (params, optimizer momenta, guard counters) —
  carried forward BIT-EXACTLY.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from grace_tpu import grace_from_params
from grace_tpu.core import Topology
from grace_tpu.parallel import data_parallel_mesh
from grace_tpu.resilience import (ConsensusConfig, ElasticController,
                                  audit_report, guarded_chain,
                                  implant_stale_replica, plan_resize,
                                  rejoin_barrier, replica_variants,
                                  reshard_grace_state, validate_resharded)
from grace_tpu.train import init_train_state, make_train_step

pytestmark = pytest.mark.elastic


# ---------------------------------------------------------------------------
# fixture: a consensus+guard+telemetry+watch run at W=8
# ---------------------------------------------------------------------------

PARAMS = {"w": jnp.ones((16, 4)), "b": jnp.zeros((4,))}
GRACE = {"compressor": "topk", "compress_ratio": 0.25, "memory": "residual",
         "communicator": "allgather", "escape": "fp16",
         "consensus": ConsensusConfig(audit_every=50),
         "telemetry": 8, "watch": {"window": 2, "capacity": 4}}


def _loss_fn(params, batch):
    x, y = batch
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def _build(mesh, grace_params=GRACE, params=PARAMS):
    grc = grace_from_params(dict(grace_params))
    tx = guarded_chain(grc, optax.sgd(1e-2),
                       fallback_after=3, fallback_steps=4)
    state = init_train_state(params, tx, mesh)
    step = make_train_step(_loss_fn, tx, mesh, donate=False,
                           consensus=grace_params.get("consensus"))
    return grc, tx, state, step


def _batch(n=32, seed=0, poison=False):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 16)).astype(np.float32)
    if poison:
        x[0, 0] = np.nan
    return (jnp.asarray(x),
            jnp.asarray(rng.standard_normal((n, 4)), jnp.float32))


@pytest.fixture(scope="module")
def trained8(mesh):
    """(grace, tx, state, step) after 4 healthy steps + 1 guard-skipped
    poisoned step at W=8 — nonzero residuals, nonzero telemetry/watch
    rings, nonzero guard counters, armed audit state."""
    grc, tx, state, step = _build(mesh)
    batch = _batch()
    for _ in range(4):
        state, loss = step(state, batch)
    state, _ = step(state, _batch(poison=True))   # guard skips this one
    assert np.isfinite(float(loss))
    return grc, tx, state, step


def _grace_node(state):
    return state.opt_state.inner[0]


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# Topology.shrink / plan_resize / hier shrunk
# ---------------------------------------------------------------------------

class TestResizePlanning:
    def test_whole_slice_loss_keeps_slice_size(self):
        topo, w = Topology(slice_size=4).shrink(8, range(4, 8))
        assert topo.slice_size == 4 and w == 4

    def test_partial_slice_loss_collapses_to_flat(self):
        topo, w = Topology(slice_size=4).shrink(8, [5])
        assert topo.slice_size is None and w == 7

    def test_flat_topology_stays_flat(self):
        topo, w = Topology().shrink(8, [3])
        assert topo.slice_size is None and w == 7

    def test_empty_loss_is_identity(self):
        topo = Topology(slice_size=4)
        assert topo.shrink(8, []) == (topo, 8)

    def test_out_of_range_and_total_loss_raise(self):
        with pytest.raises(ValueError, match="outside the world"):
            Topology().shrink(8, [8])
        with pytest.raises(ValueError, match="no survivors"):
            Topology().shrink(2, [0, 1])

    def test_plan_resize_survivor_renumbering(self):
        plan = plan_resize(8, [5], Topology(slice_size=4))
        assert plan.survivors == (0, 1, 2, 3, 4, 6, 7)
        assert plan.new_world == 7
        assert not plan.whole_slices
        plan = plan_resize(8, range(4, 8), Topology(slice_size=4))
        assert plan.survivors == (0, 1, 2, 3)
        assert plan.whole_slices and plan.topology.slice_size == 4

    def test_hier_communicator_shrunk(self):
        from grace_tpu.comm import HierarchicalAllreduce

        comm = HierarchicalAllreduce(axis_name="data", slice_size=4)
        kept = comm.shrunk(Topology(slice_size=4))
        assert isinstance(kept, HierarchicalAllreduce)
        assert kept.slice_size == 4 and kept.axis_name == "data"
        flat = comm.shrunk(Topology())
        assert flat.slice_size is None


# ---------------------------------------------------------------------------
# reshard_grace_state: every field family (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

class TestReshard:
    @pytest.fixture(scope="class")
    def resharded(self, trained8, mesh):
        grc, tx, state, _ = trained8
        mesh6 = data_parallel_mesh(jax.devices()[:6])
        new_state = reshard_grace_state(state, tx, mesh, mesh6)
        return state, new_state, mesh6

    def test_mem_residuals_rezeroed_at_new_world(self, resharded):
        old_state, new_state, _ = resharded
        old_g, new_g = _grace_node(old_state), _grace_node(new_state)
        # the old run genuinely accumulated residuals — the zeroing is real
        assert any(float(jnp.abs(m).sum()) > 0 for m in old_g.mem)
        for m in new_g.mem:
            assert m.shape[0] == 6
            assert float(jnp.abs(m).sum()) == 0.0

    def test_telemetry_and_watch_rings_reallocated_reset(self, resharded):
        old_state, new_state, _ = resharded
        old_g, new_g = _grace_node(old_state), _grace_node(new_state)
        # old rings hold rows (steps recorded); new rings are pristine
        assert int(jnp.max(old_g.telem.steps)) >= 0
        assert int(jnp.max(old_g.watch.steps)) >= 0
        for ring in (new_g.telem, new_g.watch):
            assert ring.steps.shape[0] == 6          # world axis
            assert int(jnp.max(ring.steps)) == -1    # wraparound reset
            assert float(jnp.abs(ring.rings).sum()) == 0.0
        # capacity (per-rank row count) preserved from the config
        assert new_g.telem.steps.shape[1] == old_g.telem.steps.shape[1]
        assert new_g.watch.steps.shape[1] == old_g.watch.steps.shape[1]

    def test_replicated_bookkeeping_carried_bit_exactly(self, resharded):
        old_state, new_state, _ = resharded
        old_g, new_g = _grace_node(old_state), _grace_node(new_state)
        for name in ("count", "rng_key", "fallback"):
            assert _leaves_equal(getattr(old_g, name), getattr(new_g, name))
        assert _leaves_equal(old_g.audit, new_g.audit)     # audit counters

    def test_guard_counters_and_params_carried_bit_exactly(self, resharded):
        old_state, new_state, _ = resharded
        old_guard, new_guard = old_state.opt_state, new_state.opt_state
        assert int(old_guard.notfinite_count) == 1   # the poisoned step
        for name in ("notfinite_count", "last_bad_step", "consecutive",
                     "fallback_remaining", "step"):
            assert _leaves_equal(getattr(old_guard, name),
                                 getattr(new_guard, name))
        assert _leaves_equal(old_state.params, new_state.params)
        # downstream (sgd) optimizer state rides along too
        assert _leaves_equal(old_guard.inner[1], new_guard.inner[1])

    def test_resharded_state_trains(self, resharded, trained8):
        _, new_state, mesh6 = resharded
        grc, tx, _, _ = trained8
        step6 = make_train_step(_loss_fn, tx, mesh6, donate=False,
                                consensus=GRACE["consensus"])
        batch = _batch(n=30, seed=3)
        state = new_state
        for _ in range(2):
            state, loss = step6(state, batch)
        assert np.isfinite(float(loss))
        assert int(_grace_node(state).count) == \
            int(_grace_node(new_state).count) + 2

    def test_powersgd_comp_state_reinitialized_not_zeroed(self, mesh):
        grc, tx, state, step = _build(
            mesh, {"compressor": "powersgd", "compress_rank": 2,
                   "memory": "powersgd", "communicator": "allreduce"})
        state, _ = step(state, _batch())
        mesh6 = data_parallel_mesh(jax.devices()[:6])
        new_state = reshard_grace_state(state, tx, mesh, mesh6)
        comp = [c for c in _grace_node(new_state).comp if c is not None]
        assert comp, "powersgd run produced no comp state"
        for q in comp:
            assert q.shape[0] == 6
            # zeros are not a valid Q — re-init must produce a live iterate
            assert float(jnp.abs(q).sum()) > 0

    def test_reshard_rejects_wrong_old_mesh(self, trained8):
        grc, tx, state, _ = trained8
        mesh6 = data_parallel_mesh(jax.devices()[:6])
        with pytest.raises(ValueError, match="world axis 8"):
            reshard_grace_state(state, tx, mesh6, mesh6)

    def test_validate_against_footprint_model(self, resharded, trained8):
        grc, tx, _, _ = trained8
        _, new_state, _ = resharded
        report = validate_resharded(new_state, grc, PARAMS, 6)
        assert report["matches"]
        assert report["model"] == pytest.approx(report["live"])
        with pytest.raises(ValueError, match="footprint model at world 8"):
            validate_resharded(new_state, grc, PARAMS, 8)


# ---------------------------------------------------------------------------
# rejoin barrier
# ---------------------------------------------------------------------------

@pytest.mark.consensus
class TestRejoinBarrier:
    def test_repairs_stale_replica_and_zeroes_its_residuals(self, mesh):
        grc, tx, state, step = _build(mesh)
        batch = _batch()
        state, _ = step(state, batch)
        stale_params = jax.device_get(state.params)   # "yesterday's" params
        for _ in range(3):
            state, _ = step(state, batch)             # fleet trains on
        g = _grace_node(state)
        assert all(float(jnp.abs(m[5]).sum()) > 0 for m in g.mem)
        state = implant_stale_replica(state, 5, stale_params)
        assert replica_variants(state.params) == 2

        state, report = rejoin_barrier(state, GRACE["consensus"], mesh)
        assert report["barrier_repairs"] == 1
        assert report["replica_variants"] == 1
        assert report["last_divergent_rank"] == 5
        assert report["fingerprint_bytes"] == 8 * 2 * 8 * 4
        assert report["repair_bytes"] > 0
        g = _grace_node(state)
        for m in g.mem:
            # the rejoiner's residuals zeroed (PR-3 rationale); the
            # fleet's error feedback survives the admission untouched
            assert float(jnp.abs(m[5]).sum()) == 0.0
            assert float(jnp.abs(m[0]).sum()) > 0

    def test_noop_on_already_consistent_rejoin(self, mesh):
        grc, tx, state, step = _build(mesh)
        state, _ = step(state, _batch())
        before = jax.device_get(state)
        state, report = rejoin_barrier(state, GRACE["consensus"], mesh)
        assert report["barrier_repairs"] == 0
        assert report["replica_variants"] == 1
        assert _leaves_equal(before.params, state.params)
        assert _leaves_equal(before.opt_state.inner[0].mem,
                             state.opt_state.inner[0].mem)

    def test_requires_armed_consensus(self, mesh):
        grc, tx, state, _ = _build(mesh)
        with pytest.raises(ValueError, match="armed consensus"):
            rejoin_barrier(state, None, mesh)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------

class TestElasticController:
    def test_drain_signal_thresholds_codec_skew_episodes(self):
        ctl = ElasticController(anomaly_threshold=2)
        skew = {"kind": "skew", "metric": "compression_error", "rank": 3}
        assert ctl.observe(1, [skew]) is None            # 1 episode: hold
        assert ctl.observe(2, [skew]) == 3               # threshold crossed
        assert ctl.observe(3, [skew, skew]) is None      # drains only once

    def test_grad_norm_skews_do_not_drain(self):
        ctl = ElasticController(anomaly_threshold=1)
        noise = {"kind": "skew", "metric": "grad_norm", "rank": 2}
        ewma = {"kind": "ewma", "metric": "compression_error_mean",
                "rank": -1}
        assert ctl.observe(1, [noise, ewma]) is None
        assert ctl.observe(
            2, [{"kind": "skew", "metric": "residual_norm",
                 "rank": 6}]) == 6

    def test_drain_saves_last_known_good(self, tmp_path):
        from grace_tpu.checkpoint import Checkpointer

        with Checkpointer(tmp_path / "ck", max_to_keep=None) as ckpt:
            ctl = ElasticController(checkpointer=ckpt, anomaly_threshold=1)
            rec = ctl.drain(7, {"x": jnp.arange(4.0)}, rank=5)
            assert rec["event"] == "elastic_drain" and rec["rank"] == 5
            assert ckpt.last_good_step() == 7
        assert ctl.events and ctl.events[0]["checkpointed"]

    def test_events_stream_into_sink_as_elastic_kind(self, tmp_path):
        from grace_tpu.telemetry import JSONLSink
        from grace_tpu.telemetry.timeline import Timeline, classify

        path = tmp_path / "e.jsonl"
        sink = JSONLSink(path)
        ctl = ElasticController(sink=sink, anomaly_threshold=1)
        ctl._emit("elastic_resize", 10, old_world=8, new_world=7)
        sink.close()
        records = [json.loads(l) for l in path.read_text().splitlines()]
        assert classify(records[-1]) == "elastic"
        t = Timeline.from_records(records)
        assert t.summary()["kind_counts"]["elastic"] == 1


# ---------------------------------------------------------------------------
# transform: single build-time topology resolution (ISSUE 11 satellite)
# ---------------------------------------------------------------------------

class TestTopologyResolution:
    def test_detect_called_once_at_build_and_shared(self, monkeypatch):
        from grace_tpu import core
        from grace_tpu.transform import grace_transform

        calls = []
        orig = core.Topology.detect.__func__

        def counting(cls, devices=None):
            calls.append(1)
            return orig(cls, devices)

        monkeypatch.setattr(core.Topology, "detect", classmethod(counting))
        grc = grace_from_params({"compressor": "topk",
                                 "compress_ratio": 0.25,
                                 "memory": "residual",
                                 "communicator": "allgather",
                                 "telemetry": 4,
                                 "watch": {"window": 2, "capacity": 4}})
        tx = grc.transform(seed=0)
        assert len(calls) == 1, "Topology.detect must resolve at build time"
        assert isinstance(tx.update.grace_topology, core.Topology)

    def test_update_never_re_detects(self, mesh, monkeypatch):
        from grace_tpu import core

        grc, tx, state, step = _build(mesh)

        def boom(cls, devices=None):   # pragma: no cover - must not run
            raise AssertionError("Topology.detect called after build")

        monkeypatch.setattr(core.Topology, "detect", classmethod(boom))
        batch = _batch()
        for _ in range(2):   # crosses a watch window: both paths execute
            state, loss = step(state, batch)
        assert np.isfinite(float(loss))

    def test_explicit_topology_skips_detection(self, monkeypatch):
        from grace_tpu import core

        def boom(cls, devices=None):   # pragma: no cover - must not run
            raise AssertionError("explicit topology must not detect")

        monkeypatch.setattr(core.Topology, "detect", classmethod(boom))
        grc = grace_from_params({"compressor": "none",
                                 "communicator": "hier", "slice_size": 4,
                                 "telemetry": 4})
        tx = grc.transform(seed=0)
        assert tx.update.grace_topology.slice_size == 4

    def test_no_telemetry_resolves_nothing(self, monkeypatch):
        from grace_tpu import core

        def boom(cls, devices=None):   # pragma: no cover - must not run
            raise AssertionError("no telemetry: nothing prices a split")

        monkeypatch.setattr(core.Topology, "detect", classmethod(boom))
        grc = grace_from_params({"compressor": "none",
                                 "communicator": "allgather"})
        assert grc.transform(seed=0).update.grace_topology is None


# ---------------------------------------------------------------------------
# the full lifecycle smoke (tier-1, world=8) + evidence pickup
# ---------------------------------------------------------------------------

def _load_tool(name):
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        f"{name}_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.chaos
class TestElasticSmoke:
    def test_chaos_smoke_elastic_cycle(self, tmp_path):
        """kill → W−1 resume → rejoin → W with bit-identical replicas,
        repairs == rejoins, the convergence floor met, and the re-sharded
        state matching flow pass 7's footprint model at both worlds."""
        smoke = _load_tool("chaos_smoke")
        out = tmp_path / "elastic.jsonl"
        doc_path = tmp_path / "ELASTIC_LAST.json"
        rc = smoke.main(["--elastic", "--steps", "36", "--batch", "16",
                         "--watch-window", "5", "--telemetry-every", "10",
                         "--audit-every", "10", "--floor", "2.4",
                         "--telemetry-out", str(out),
                         "--elastic-out", str(doc_path),
                         "--ckpt-dir", str(tmp_path / "ck")])
        assert rc == 0
        doc = json.loads(doc_path.read_text())
        assert doc["world_cycle"] == [8, 7, 8]
        assert doc["drain"]["rank"] == 5
        assert doc["rejoin"]["barrier_repairs"] == doc["rejoin"]["rejoins"]
        assert doc["rejoin"]["replica_variants"] == 1
        assert doc["rejoin"]["fingerprint_bytes"] > 0
        assert doc["floor"]["met"]
        assert doc["footprint"] == {"7": True, "8": True}
        events = [e["event"] for e in doc["resize_events"]]
        assert events == ["elastic_drain", "elastic_resize",
                          "elastic_resize", "elastic_rejoin"]
        # the same lifecycle streams into the telemetry artifact
        from grace_tpu.telemetry.timeline import Timeline

        t = Timeline.from_jsonl(str(out))
        assert t.summary()["kind_counts"]["elastic"] == 4
        assert [e.record["event"] for e in t.kinds("elastic")] == events

    @pytest.mark.slow
    @pytest.mark.hier
    def test_chaos_smoke_elastic_hier_slice_kill(self, tmp_path):
        """--hier: losing the flagged rank's whole slice is a K→K−1
        resize that keeps slice_size through the cycle."""
        smoke = _load_tool("chaos_smoke")
        doc_path = tmp_path / "ELASTIC_LAST.json"
        rc = smoke.main(["--elastic", "--hier", "--slice-size", "4",
                         "--steps", "36", "--batch", "16",
                         "--watch-window", "5", "--telemetry-every", "10",
                         "--audit-every", "10", "--floor", "2.4",
                         "--telemetry-out", str(tmp_path / "h.jsonl"),
                         "--elastic-out", str(doc_path),
                         "--ckpt-dir", str(tmp_path / "ck")])
        assert rc == 0
        doc = json.loads(doc_path.read_text())
        assert doc["world_cycle"] == [8, 4, 8]
        assert doc["slice_size"] == 4
        resize = next(e for e in doc["resize_events"]
                      if e["event"] == "elastic_resize")
        assert resize["lost_ranks"] == [4, 5, 6, 7]
        assert resize["whole_slices"] and resize["slice_size"] == 4
        assert doc["rejoin"]["replica_variants"] == 1
        assert doc["footprint"] == {"4": True, "8": True}


def test_evidence_summary_picks_up_elastic_last(tmp_path, monkeypatch):
    evidence_summary = _load_tool("evidence_summary")
    monkeypatch.setattr(evidence_summary, "ROOT", str(tmp_path))
    doc = {"tool": "chaos_smoke", "captured_at": "2026-08-04T12:00:00",
           "world_cycle": [8, 7, 8],
           "resize_events": [{"event": "elastic_drain"},
                             {"event": "elastic_resize"}],
           "rejoin": {"rejoins": 1, "barrier_repairs": 1,
                      "replica_variants": 1, "fingerprint_bytes": 512},
           "floor": {"final_loss": 1.2, "floor": 2.25, "met": True},
           "footprint": {"7": True, "8": True}}
    (tmp_path / "ELASTIC_LAST.json").write_text(json.dumps(doc))
    md = evidence_summary.build()
    assert "chaos_smoke --elastic" in md
    assert "world cycle 8 → 7 → 8" in md
    assert "1 repair(s) for 1 rejoin(s)" in md
    assert "bit-identical" in md
    assert "floor met" in md
