"""Tests for grace_tpu.utils: loggers, timers, wire metrics."""

import io
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from grace_tpu import compressors as C
from grace_tpu.utils import (StepTimer, TableLogger, Timer, TSVLogger,
                             payload_nbytes, wire_report)


class TestTimer:
    def test_segments_and_total(self):
        t = Timer()
        time.sleep(0.01)
        d1 = t()
        time.sleep(0.01)
        d2 = t(include_in_total=False)
        assert d1 >= 0.01 and d2 >= 0.01
        assert t.total_time == pytest.approx(d1)

    def test_sync_hook_called(self):
        calls = []
        t = Timer(sync=lambda: calls.append(1))
        t()
        assert len(calls) == 2  # once at init, once per reading


class TestTableLogger:
    def test_header_latched_and_aligned(self):
        buf = io.StringIO()
        log = TableLogger(width=8, stream=buf)
        log.append({"epoch": 1, "loss": 0.5})
        log.append({"epoch": 2, "loss": 0.25, "extra": "ignored"})
        lines = buf.getvalue().strip().split("\n")
        assert len(lines) == 4   # header, row, new-column notice, row
        assert "epoch" in lines[0] and "loss" in lines[0]
        assert lines[2] == "# new columns (ignored): extra"
        assert "ignored" not in lines[3]  # keys latched from first row
        assert "0.2500" in lines[3]

    def test_missing_key_renders_blank_and_new_key_warns_once(self):
        # The telemetry case: fields appear only after the first flush
        # window and early rows lack them — neither may KeyError.
        buf = io.StringIO()
        log = TableLogger(width=8, stream=buf)
        log.append({"epoch": 1, "loss": 0.5})
        log.append({"epoch": 2})                           # lost a key
        log.append({"epoch": 3, "loss": 0.1, "gnorm": 1.0})  # gained one
        log.append({"epoch": 4, "loss": 0.2, "gnorm": 2.0})  # no re-warn
        lines = buf.getvalue().split("\n")
        notices = [l for l in lines if l.startswith("#")]
        assert notices == ["# new columns (ignored): gnorm"]
        row2 = lines[2]
        assert row2.startswith(f"{2:>8}") and row2.rstrip() == f"{2:>8}"
        assert all("gnorm" not in l for l in lines if not l.startswith("#"))


class TestTSVLogger:
    def test_dawnbench_format(self, tmp_path):
        log = TSVLogger()
        log.append({"epoch": 1, "total time": 3600.0, "test acc": 0.9408})
        s = str(log)
        lines = s.split("\n")
        assert lines[0] == "epoch\thours\ttop1Accuracy"
        assert lines[1] == "1\t1.00000000\t94.08"
        p = tmp_path / "logs.tsv"
        log.write(str(p))
        assert p.read_text().startswith("epoch\thours")


class TestStepTimer:
    def test_warmup_excluded(self):
        st = StepTimer(warmup=1)
        # host-only step bodies: the (intentional) no-sync_on warning is the
        # expected condition here, asserted explicitly
        with pytest.warns(RuntimeWarning, match="sync_on"):
            for i in range(3):
                with st.step():
                    time.sleep(0.02 if i == 0 else 0.005)
        assert len(st.steady) == 2
        assert st.mean_sec < 0.02
        assert st.throughput(10) > 0
        assert st.measured_async_dispatch

    def test_sync_on_blocks_device_value(self):
        st = StepTimer(warmup=0)
        x = jnp.arange(1024.0)
        with st.step():
            y = (x * 2).sum()
            st.sync_on(y)
        assert st.mean_sec >= 0


class TestWireMetrics:
    def test_none_compressor_is_identity_cost(self):
        x = jnp.zeros((128,), jnp.float32)
        assert payload_nbytes(C.NoneCompressor(), x) == 128 * 4

    def test_topk_payload_scales_with_ratio(self):
        x = jnp.zeros((1000,), jnp.float32)
        b = payload_nbytes(C.TopKCompressor(compress_ratio=0.01), x)
        # 10 values (f32) + 10 indices (i32) = 80 bytes
        assert b == 80

    def test_signsgd_saves_bandwidth(self):
        x = jnp.zeros((1024,), jnp.float32)
        b = payload_nbytes(C.SignSGDCompressor(), x)
        assert b < 1024 * 4

    def test_shipped_defaults_beat_dense_bytes(self):
        # VERDICT round-1 item 9: every compressor's default config must cost
        # less on the wire than shipping the dense gradient (None excepted —
        # it IS the dense baseline).
        # 2-D input: PowerSGD's low-rank factorization degenerates on
        # vectors (P+Q of a 1xN matrix costs as much as N values).
        x = jnp.zeros((64, 64), jnp.float32)
        dense = 64 * 64 * 4
        for comp in [C.FP16Compressor(), C.TopKCompressor(),
                     C.RandomKCompressor(), C.ThresholdCompressor(),
                     C.QSGDCompressor(), C.TernGradCompressor(),
                     C.SignSGDCompressor(), C.SignumCompressor(),
                     C.EFSignSGDCompressor(), C.OneBitCompressor(),
                     C.NaturalCompressor(), C.DgcCompressor(),
                     C.AdaqCompressor(),
                     C.U8bitCompressor(), C.SketchCompressor(),
                     C.InceptionNCompressor()]:
            # (PowerSGD excluded: it psums inside compress, so its cost is
            # only measurable inside shard_map — covered in test_fusion.)
            assert payload_nbytes(comp, x) < dense, type(comp).__name__

    def test_topk_bf16_wire_saves_quarter(self):
        x = jnp.zeros((1000,), jnp.float32)
        f32 = payload_nbytes(C.TopKCompressor(compress_ratio=0.1), x)
        bf16 = payload_nbytes(C.TopKCompressor(compress_ratio=0.1,
                                               wire_dtype="bfloat16"), x)
        assert f32 == 100 * 8 and bf16 == 100 * 6
        # round-trip decodes back to the original dtype, values ~exact for
        # bf16-representable inputs
        comp = C.TopKCompressor(compress_ratio=0.5, wire_dtype="bfloat16")
        g = jnp.asarray([1.5, -2.0, 0.25, 0.0])
        payload, ctx, _ = comp.compress(g, None, jax.random.key(0))
        out = comp.decompress(payload, ctx)
        assert out.dtype == g.dtype
        np.testing.assert_allclose(np.asarray(out), [1.5, -2.0, 0, 0])

    def test_threshold_calibrated_tracks_density(self):
        # 2% of entries exceed tau -> capacity tuned to ~3% (1.5x safety),
        # two orders tighter than the 25% correctness default.
        rng = np.random.default_rng(0)
        g = jnp.asarray(rng.standard_normal(10_000) * 0.001)
        g = g.at[:200].set(1.0)   # 2% large entries
        comp = C.ThresholdCompressor(threshold=0.01)
        tuned = comp.calibrated(g)
        assert np.isclose(tuned.capacity_ratio, 0.03, atol=0.005)
        assert payload_nbytes(tuned, g) < payload_nbytes(comp, g) / 5
        # round-trip stays exact: capacity still covers every selected entry
        payload, ctx, _ = tuned.compress(g, None, jax.random.key(0))
        out = tuned.decompress(payload, ctx)
        np.testing.assert_allclose(np.asarray(out)[:200], 1.0)

    def test_wire_report_over_tree(self):
        tree = {"w": jnp.zeros((100, 10)), "b": jnp.zeros((10,))}
        rep = wire_report(C.TopKCompressor(compress_ratio=0.1), tree)
        assert rep.dense_bytes == (1000 + 10) * 4
        assert len(rep.leaves) == 2
        assert 0 < rep.ratio < 1
        assert "ratio" in rep.summary()
        assert "CompressionReport" in str(rep)

    def test_randomk_values_only(self):
        # RandomK sends values only (indices derived from shared seed,
        # reference grace_dl/dist/compressor/randomk.py:26-29).
        x = jnp.zeros((1000,), jnp.float32)
        b = payload_nbytes(C.RandomKCompressor(compress_ratio=0.01), x)
        assert b == 10 * 4


def test_debug_nan_residuals_counts_nan_and_inf():
    """The census reports NaN AND Inf per leaf (~jnp.isfinite), in one
    device-to-host transfer; clean states stay an empty dict."""
    from grace_tpu.utils import debug_nan_residuals

    clean = {"a": jnp.zeros((4,)), "n": jnp.arange(3)}   # int leaf ignored
    assert debug_nan_residuals(clean) == {}

    poisoned = {
        "a": jnp.asarray([1.0, jnp.nan, jnp.inf, -jnp.inf]),
        "b": {"c": jnp.asarray([jnp.nan, jnp.nan])},
        "ok": jnp.ones((2,)),
    }
    rep = debug_nan_residuals(poisoned)
    assert set(rep) == {"['a']", "['b']['c']"}
    assert rep["['a']"] == {"nan": 1, "inf": 2}
    assert rep["['b']['c']"] == {"nan": 2, "inf": 0}


def test_run_provenance_includes_git_commit():
    from grace_tpu.utils import git_commit, run_provenance

    prov = run_provenance("synthetic", argv="--steps 5")
    assert prov["data"] == "synthetic"
    assert prov["argv"] == "--steps 5"
    # This repo IS a git checkout, so the best-effort lookup must succeed
    # here and match the helper.
    rev = git_commit()
    assert rev and prov["git_commit"] == rev
    assert 4 <= len(rev) <= 16 and all(c in "0123456789abcdef" for c in rev)


def test_wire_report_powersgd_analytic():
    """PowerSGD's compress psums inside shard_map, so wire_report must use
    its analytic wire_nbytes instead of shape-tracing compress (regression:
    the digits example once crashed with 'unbound axis name: data')."""
    import jax.numpy as jnp

    from grace_tpu.compressors import PowerSGDCompressor
    from grace_tpu.utils import wire_report

    params = {"w": jnp.zeros((20, 8)), "b": jnp.zeros((8,))}
    rep = wire_report(PowerSGDCompressor(rank=4), params)
    # w: (20+8)*4 floats; b rides dense: 8 floats
    assert rep.wire_bytes == ((20 + 8) * 4 + 8) * 4
    assert rep.dense_bytes == (20 * 8 + 8) * 4
