"""graft-adapt (ISSUE 15): the in-graph adaptive compression controller.

The properties pinned here are the acceptance criteria:

* the controller is pure replicated state math: tighten within one window
  of a mean/peak spike or guard evidence, loosen only after
  ``quiet_windows`` quiet windows with no hold (hysteresis — it cannot
  flap at window rate), escalate-and-hold on a guard trip;
* a quiet adaptive run IS the static top-rung run, bitwise — the ladder's
  steady state matches the hand-picked config exactly (the throughput
  half of "matches the best static config", with the tuner's
  price-equality pin alongside);
* telemetry prices every row at the ACTIVE rung (per-rung wire plan —
  the dense-fallback flip generalized), the ``ici+dcn == wire_bytes``
  identity survives, and the guard's fallback flag forces rung 0;
* the policy state is replicated GraceState bookkeeping: ``P()`` specs,
  inside the consensus fingerprint, rolled back bitwise by the guard,
  re-initialized by an elastic world resize;
* the static stack covers the ladder: the three registered adapt configs
  audit clean over every pass, flow pass 6 fires on an unsafe
  shared-scale RUNG (not just the base codec), and the tuner's funnel
  gates every rung's legality and numeric bounds;
* ``chaos_smoke --adapt`` proves tighten-before-guard ordering from the
  artifact, and the convergence floors hold — the routed-transformer
  track (the PR-14 leftover) and the adaptive-vs-static pair.
"""

import dataclasses
import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from grace_tpu import compressors as C
from grace_tpu import grace_from_params
from grace_tpu.resilience import guarded_chain
from grace_tpu.resilience.adapt import (AdaptConfig, AdaptMonitor,
                                        AdaptState, adapt_advance,
                                        adapt_init, adapt_report,
                                        adapt_signal_bytes, normalize_adapt)
from grace_tpu.telemetry import TelemetryReader
from grace_tpu.train import init_train_state, make_train_step
from grace_tpu.transform import (GRACE_REPLICATED_FIELDS, GraceState,
                                 grace_transform, partition_specs)

W = 8

pytestmark = pytest.mark.adapt


def _f32(x):
    return jnp.asarray(x, jnp.float32)


def _advance_window(state, cfg, err_mean, err_peak, fallback=False,
                    start_count=0):
    """Run ``cfg.window`` controller steps with a constant signal; returns
    the post-boundary state."""
    for i in range(cfg.window):
        state = adapt_advance(state, cfg, jnp.asarray(start_count + i,
                                                      jnp.int32),
                              jnp.asarray(fallback, jnp.bool_),
                              _f32(err_mean), _f32(err_peak))
    return state


def _cfg(**kw):
    base = dict(ladder=(C.QSGDCompressor(quantum_num=127,
                                         use_pallas=False),
                        C.QSGDCompressor(quantum_num=15,
                                         use_pallas=False)),
                window=4, tighten_error=0.5, tighten_peak=0.75,
                loosen_error=0.25, quiet_windows=2, hold_windows=3)
    base.update(kw)
    return AdaptConfig(**base)


# ---------------------------------------------------------------------------
# config normalization + validation
# ---------------------------------------------------------------------------

def test_normalize_adapt_spellings():
    base = C.QSGDCompressor(quantum_num=15, use_pallas=False)
    for spec in (True, 7, {"window": 7}):
        cfg = normalize_adapt(spec, base)
        assert cfg.ladder[-1] == base          # base appended as top rung
        assert cfg.n_rungs == 2                # dense + base
    cfg = normalize_adapt(7, base)
    assert cfg.window == 7
    # Idempotent when the ladder already ends with the base codec.
    again = normalize_adapt(cfg, base)
    assert again.ladder == cfg.ladder
    # A declared ladder keeps its order, base on top.
    gentle = C.QSGDCompressor(quantum_num=127, use_pallas=False)
    cfg = normalize_adapt({"ladder": [gentle]}, base)
    assert cfg.ladder == (gentle, base) and cfg.top_rung == 2
    assert normalize_adapt(None, base) is None
    assert normalize_adapt(False, base) is None
    with pytest.raises(TypeError):
        normalize_adapt("yes", base)


def test_adapt_config_validation():
    with pytest.raises(ValueError, match="window"):
        _cfg(window=0)
    with pytest.raises(ValueError, match="hysteresis"):
        _cfg(tighten_error=0.3, loosen_error=0.3)
    with pytest.raises(ValueError, match="tighten_peak"):
        _cfg(tighten_peak=0.1)
    with pytest.raises(ValueError, match="quiet_windows"):
        _cfg(quiet_windows=0)
    with pytest.raises(ValueError, match="hold_windows"):
        _cfg(hold_windows=-1)
    with pytest.raises(ValueError, match="start_rung"):
        normalize_adapt(_cfg(start_rung=9),
                        C.QSGDCompressor(quantum_num=15, use_pallas=False))


def test_adapt_build_requirements():
    """The transform's own gates: escape is rung 0, telemetry's error is
    the signal, and routes are outside the rung plan."""
    comp = C.QSGDCompressor(quantum_num=15, use_pallas=False)
    from grace_tpu.comm import Allgather
    from grace_tpu.memories import NoneMemory
    kw = dict(compressor=comp, memory=NoneMemory(),
              communicator=Allgather())
    with pytest.raises(ValueError, match="escape"):
        grace_transform(**kw, adapt=True, telemetry=True)
    with pytest.raises(ValueError, match="compression_error"):
        grace_transform(**kw, adapt=True, escape=C.FP16Compressor(),
                        telemetry={"compression_error": False})
    with pytest.raises(ValueError, match="telemetry"):
        grace_transform(**kw, adapt=True, escape=C.FP16Compressor())
    with pytest.raises(ValueError, match="routes"):
        grace_transform(**kw, adapt=True, escape=C.FP16Compressor(),
                        telemetry=True,
                        routes=[("x", (comp, NoneMemory(), Allgather()))])


# ---------------------------------------------------------------------------
# controller semantics (pure replicated state math)
# ---------------------------------------------------------------------------

def test_tighten_on_mean_spike_within_one_window():
    cfg = _cfg()
    a = adapt_init(cfg)
    assert int(a.rung) == cfg.top_rung == 2
    a = _advance_window(a, cfg, err_mean=0.9, err_peak=0.9)
    assert int(a.rung) == 1 and int(a.tightens) == 1
    assert int(a.escalations) == 0
    # Window accumulators reset at the boundary.
    assert float(a.err_sum) == 0.0 and float(a.err_peak) == 0.0


def test_tighten_on_peak_spike_alone():
    """The worst-rank channel: a single drifting rank raises the pmax but
    barely moves the mean — the controller must still tighten."""
    cfg = _cfg()
    a = _advance_window(adapt_init(cfg), cfg, err_mean=0.1, err_peak=0.9)
    assert int(a.rung) == 1 and int(a.tightens) == 1


def test_hysteresis_band_holds_rung():
    """A signal between loosen_error and tighten_error moves nothing, in
    either direction, for any number of windows."""
    cfg = _cfg()
    a = adapt_init(cfg)
    for w in range(4):
        a = _advance_window(a, cfg, err_mean=0.4, err_peak=0.4,
                            start_count=w * cfg.window)
    assert int(a.rung) == cfg.top_rung
    assert int(a.tightens) == 0 and int(a.loosens) == 0
    assert int(a.quiet) == 0                  # the band is not "quiet"


def test_loosen_needs_consecutive_quiet_windows():
    cfg = _cfg()
    a = adapt_init(cfg)._replace(rung=jnp.asarray(0, jnp.int32))
    a = _advance_window(a, cfg, 0.0, 0.0)
    assert int(a.rung) == 0 and int(a.quiet) == 1   # one quiet: no move
    a = _advance_window(a, cfg, 0.0, 0.0, start_count=cfg.window)
    assert int(a.rung) == 1 and int(a.loosens) == 1  # second quiet: loosen
    assert int(a.quiet) == 0                  # counter restarts per rung
    # An interleaved spike resets the quiet streak.
    a = _advance_window(a, cfg, 0.9, 0.9, start_count=2 * cfg.window)
    assert int(a.rung) == 0
    a = _advance_window(a, cfg, 0.0, 0.0, start_count=3 * cfg.window)
    assert int(a.rung) == 0 and int(a.quiet) == 1


def test_guard_evidence_escalates_and_holds():
    """A step under the guard's fallback flag tightens at the boundary
    AND freezes loosening for hold_windows — the ladder floor was too
    loose."""
    cfg = _cfg()
    a = adapt_init(cfg)
    a = _advance_window(a, cfg, 0.0, 0.0, fallback=True)
    assert int(a.rung) == 1 and int(a.escalations) == 1
    assert int(a.hold) == cfg.hold_windows
    # Quiet windows now pass but the hold blocks loosening until it
    # decays (one per boundary).
    for w in range(cfg.hold_windows):
        a = _advance_window(a, cfg, 0.0, 0.0,
                            start_count=(w + 1) * cfg.window)
        assert int(a.rung) == 1, f"loosened during hold (window {w})"
    a = _advance_window(a, cfg, 0.0, 0.0,
                        start_count=(cfg.hold_windows + 1) * cfg.window)
    assert int(a.rung) == 2 and int(a.loosens) == 1


def test_rung_floor_is_dense():
    cfg = _cfg()
    a = adapt_init(cfg)
    for w in range(5):
        a = _advance_window(a, cfg, 0.9, 0.9, start_count=w * cfg.window)
    assert int(a.rung) == 0                   # clamped at the dense floor


def test_nonfinite_signal_reads_as_spike_not_poison():
    cfg = _cfg()
    a = _advance_window(adapt_init(cfg), cfg, err_mean=float("nan"),
                        err_peak=float("inf"))
    assert int(a.rung) == 1                   # tightened
    assert np.isfinite(float(a.err_sum))      # accumulators stay finite


# ---------------------------------------------------------------------------
# state contract: replicated, fingerprinted, repaired, resharded
# ---------------------------------------------------------------------------

def test_adapt_is_replicated_grace_state():
    assert "adapt" in GRACE_REPLICATED_FIELDS
    grc = _adaptive_grace()
    tx = grc.transform(seed=0)
    state = jax.eval_shape(tx.init, {"w": jnp.zeros((20, 4), jnp.float32)})
    specs = partition_specs(state, "data")
    for leaf in jax.tree_util.tree_leaves(
            specs.adapt, is_leaf=lambda x: isinstance(x, P)):
        assert leaf == P()
    # The consensus fingerprint covers it: two states differing only in
    # the commanded rung fingerprint differently.
    from grace_tpu.resilience.consensus import (fingerprint_tree,
                                                replicated_view)
    live = tx.init({"w": jnp.zeros((20, 4), jnp.float32)})
    assert live.adapt is not None
    moved = live._replace(adapt=live.adapt._replace(
        rung=live.adapt.rung - 1))
    fp_a = np.asarray(fingerprint_tree(replicated_view(live)))
    fp_b = np.asarray(fingerprint_tree(replicated_view(moved)))
    assert not np.array_equal(fp_a, fp_b)


def _adaptive_grace(**adapt_overrides):
    spec = {"window": 4, "ladder": [{"quantum_num": 127}],
            "tighten_error": 0.5, "tighten_peak": 0.75,
            "loosen_error": 0.25, "quiet_windows": 2, "hold_windows": 2}
    spec.update(adapt_overrides)
    return grace_from_params({
        "compressor": "qsgd", "quantum_num": 15, "use_pallas": False,
        "memory": "none", "communicator": "allgather",
        "escape": "fp16", "telemetry": 16, "adapt": spec})


def _ls_problem(seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(20, 4)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(64, 20)).astype(np.float32))
    y = jnp.asarray(np.argmax(np.asarray(x) @ w_true, axis=1)
                    .astype(np.int32))

    def loss_fn(p, b):
        xb, yb = b
        return optax.softmax_cross_entropy_with_integer_labels(
            xb @ p["w"], yb).mean()

    return loss_fn, (x, y)


def test_quiet_adaptive_run_is_bitwise_static_top_rung(mesh):
    """The steady state IS the static config: with thresholds no healthy
    signal crosses, the ladder never leaves the top rung and the adaptive
    run's params equal the static (escape+telemetry, no adapt) run's
    bit-for-bit — same codec, same rng derivation, same exchange."""
    loss_fn, batch = _ls_problem()
    static = {"compressor": "qsgd", "quantum_num": 15, "use_pallas": False,
              "memory": "none", "communicator": "allgather",
              "escape": "fp16", "telemetry": 16}

    def run(params_dict):
        grc = grace_from_params(params_dict)
        tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
        state = init_train_state({"w": jnp.zeros((20, 4), jnp.float32)},
                                 tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        for _ in range(6):
            state, _ = step(state, batch)
        return np.asarray(state.params["w"])

    w_static = run(static)
    w_adapt = run({**static, "adapt": {
        "window": 4, "ladder": [{"quantum_num": 127}],
        "tighten_error": 50.0, "tighten_peak": 75.0,
        "loosen_error": 25.0}})
    np.testing.assert_array_equal(w_static, w_adapt)


def test_live_spike_tightens_and_telemetry_prices_per_rung(mesh):
    """End-to-end over the mesh: an aggressive-topk ladder on random
    gradients (rel error ~1) tightens at the first boundary; every
    telemetry row's wire bytes equal the ACTIVE rung's static plan plus
    the controller's signal cost, and ici+dcn == wire_bytes survives."""
    loss_fn, batch = _ls_problem()
    grc = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.01, "memory": "residual",
        "communicator": "allgather", "escape": "fp16", "telemetry": 16,
        "adapt": {"window": 3, "ladder": [{"compress_ratio": 0.25}],
                  "tighten_error": 0.5, "tighten_peak": 0.75,
                  "loosen_error": 0.25}})
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
    state = init_train_state({"w": jnp.zeros((20, 4), jnp.float32)},
                             tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    for _ in range(8):
        state, _ = step(state, batch)
    rep = adapt_report(state)
    assert rep["tightens"] >= 1 and rep["rung"] < 2
    rows = TelemetryReader(None, every=1).flush(state)
    rows = [r for r in rows if "adapt_rung" in r]
    assert rows

    # Static per-rung expectation: payload bytes through each rung's own
    # schedule (the escape psum at rung 0, the allgather above it) + the
    # signal reductions' cost.
    from grace_tpu.comm import Allreduce
    from grace_tpu.utils.metrics import payload_nbytes
    struct = jax.ShapeDtypeStruct((20, 4), jnp.float32)
    plans = {0: Allreduce().recv_wire_bytes(
        payload_nbytes(C.FP16Compressor(), struct), 80, W)}
    for ri, comp in enumerate(grc.adapt.ladder, start=1):
        pb = payload_nbytes(comp, struct)
        plans[ri] = grc.communicator.recv_wire_bytes(pb, 80, W)
    sig = adapt_signal_bytes(W)
    for r in rows:
        rung = int(r["adapt_rung"])
        assert rung in (0, 1, 2)
        assert r["adapt_bytes"] == float(sig)
        assert r["wire_bytes"] == float(plans[rung] + sig)
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]
    # The recorded rung trajectory actually moved (the tighten is
    # observable from the ring, which is what AdaptMonitor diffs).
    assert len({int(r["adapt_rung"]) for r in rows}) > 1


def test_fallback_flag_forces_dense_rung_and_escape_pricing(mesh):
    """The guard's fallback flag routes the ladder to rung 0: the row
    records adapt_rung 0 and the escape psum's wire bill."""
    from grace_tpu.transform import set_fallback_flag

    loss_fn, batch = _ls_problem()
    grc = _adaptive_grace()
    tx = optax.chain(grc.transform(seed=0), optax.sgd(0.05))
    state = init_train_state({"w": jnp.zeros((20, 4), jnp.float32)},
                             tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    state, _ = step(state, batch)
    state = state._replace(opt_state=set_fallback_flag(state.opt_state,
                                                       True))
    state, _ = step(state, batch)
    rows = TelemetryReader(None, every=1).flush(state)
    fb_rows = [r for r in rows if r.get("fallback")]
    assert fb_rows, "the fallback step left no row"
    from grace_tpu.comm import Allreduce
    from grace_tpu.utils.metrics import payload_nbytes
    struct = jax.ShapeDtypeStruct((20, 4), jnp.float32)
    esc_b = payload_nbytes(C.FP16Compressor(), struct)
    esc_wire = Allreduce().recv_wire_bytes(esc_b, 80, W)
    for r in fb_rows:
        assert int(r["adapt_rung"]) == 0
        assert r["wire_bytes"] == float(esc_wire + adapt_signal_bytes(W))
        assert r["wire_bytes_ici"] + r["wire_bytes_dcn"] == r["wire_bytes"]


def test_guard_rollback_keeps_adapt_state_bitwise(mesh):
    """A guard-skipped step rolls the policy state back with everything
    else: under total NaN injection (no fallback arming) the controller
    never advances."""
    from grace_tpu.resilience import ChaosCommunicator

    loss_fn, batch = _ls_problem()
    grc = _adaptive_grace()
    grc = dataclasses.replace(grc, communicator=ChaosCommunicator(
        inner=grc.communicator, nan_prob=1.0, rank=0, seed=1))
    tx = guarded_chain(grc, optax.sgd(0.05))
    state = init_train_state({"w": jnp.zeros((20, 4), jnp.float32)},
                             tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    for _ in range(6):
        state, _ = step(state, batch)
    rep = adapt_report(state)
    init_rep = {"rung": 2, "tightens": 0, "loosens": 0, "escalations": 0,
                "hold": 0, "quiet": 0, "last_change_step": -1}
    assert rep == init_rep
    from grace_tpu.utils.metrics import guard_report
    assert guard_report(state)["notfinite_count"] == 6


def test_elastic_reshard_reinitializes_adapt(mesh):
    """A world resize carries count/rng bit-exactly but RE-INITIALIZES
    the policy state — the windowed statistics and operating rung were
    learned at the old world's signal profile."""
    from grace_tpu.parallel import data_parallel_mesh
    from grace_tpu.resilience import reshard_grace_state

    loss_fn, batch = _ls_problem()
    grc = _adaptive_grace()
    # Thresholds the healthy signal crosses, so the rung MOVES before
    # the resize — proving re-init, not carry.
    grc2 = dataclasses.replace(grc, adapt=dataclasses.replace(
        grc.adapt, tighten_error=1e-6, loosen_error=1e-7,
        tighten_peak=1e-6))
    tx = optax.chain(grc2.transform(seed=0), optax.sgd(0.05))
    params = {"w": jnp.zeros((20, 4), jnp.float32)}
    state = init_train_state(params, tx, mesh)
    step = make_train_step(loss_fn, tx, mesh, donate=False)
    for _ in range(5):
        state, _ = step(state, batch)
    rep = adapt_report(state)
    assert rep["tightens"] >= 1 and rep["rung"] < 2

    new_mesh = data_parallel_mesh(jax.devices()[:4])
    tx_new = optax.chain(grc2.transform(seed=0), optax.sgd(0.05))
    resharded = reshard_grace_state(state, tx_new, mesh, new_mesh)
    rep2 = adapt_report(resharded)
    assert rep2 == {"rung": 2, "tightens": 0, "loosens": 0,
                    "escalations": 0, "hold": 0, "quiet": 0,
                    "last_change_step": -1}
    # ...while the replicated clock carried bit-exactly.
    graces = [n for n in jax.tree_util.tree_leaves(
        resharded.opt_state,
        is_leaf=lambda n: isinstance(n, GraceState))
        if isinstance(n, GraceState)]
    assert int(np.asarray(graces[0].count).reshape(-1)[0]) == 5


def test_mismatched_rung_state_structure_raises():
    """A ladder whose rung threads a different compressor-state structure
    (PowerSGD's Q vs topk's None) is rejected with the named error, not
    an opaque lax.switch TypeError."""
    from grace_tpu.analysis.trace import trace_update

    grc = grace_from_params({
        "compressor": "topk", "compress_ratio": 0.1, "memory": "residual",
        "communicator": "allgather", "escape": "fp16", "telemetry": True})
    base = grc.compressor
    bad = AdaptConfig(ladder=(C.PowerSGDCompressor(rank=2), base),
                      window=4)
    grc = dataclasses.replace(grc, adapt=bad)
    with pytest.raises(ValueError, match="identical mem/comp state"):
        trace_update(grc, world=W, name="bad-ladder")


# ---------------------------------------------------------------------------
# static analysis: registry clean, rungs audited
# ---------------------------------------------------------------------------

@pytest.mark.analysis
def test_adapt_registry_configs_audit_clean():
    from grace_tpu.analysis.configs import AUDIT_CONFIGS, audit_config

    names = {"adapt-homoqsgd-ring", "adapt-topk-hier",
             "adapt-guard-consensus"}
    entries = [e for e in AUDIT_CONFIGS if e["name"] in names]
    assert len(entries) == 3
    for e in entries:
        findings = audit_config(e)
        assert findings == [], (e["name"], [f.message for f in findings])


@pytest.mark.analysis
def test_shared_scale_rung_bound_fires_statically():
    """Flow pass 6 audits EVERY reachable rung: a ladder whose gentle
    8-bit rung cannot cover the world fires even though the base (top)
    rung is safe — and the same config at a small world is clean."""
    from grace_tpu.analysis import flow
    from grace_tpu.analysis.trace import TracedGraph

    grc = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 7, "accum_dtype": "int32",
        "memory": "residual", "communicator": "ring", "fusion": "flat",
        "escape": "fp16", "telemetry": True,
        "adapt": {"window": 5, "ladder": [
            {"quantum_num": 127, "accum_dtype": "int16"}]}})
    rung1 = grc.adapt.ladder[0]
    bound = rung1.payload_sum_max_world()
    base_bound = grc.compressor.payload_sum_max_world()
    assert bound < 512 <= base_bound    # only the RUNG is unsafe at 512

    def fake_trace(world):
        return TracedGraph(name="adapt-rung-bound", closed=None,
                           body=None, world=world, axis_name="data",
                           varying={}, meta={"grace": grc})

    findings = flow._shared_scale_findings(fake_trace(512))
    assert len(findings) == 1
    assert "HomoQSGDCompressor" in findings[0].message
    assert dict(findings[0].details)["payload_sum_max_world"] == bound
    assert flow._shared_scale_findings(fake_trace(8)) == []


# ---------------------------------------------------------------------------
# tuner: rung-schedule pricing + per-rung gates
# ---------------------------------------------------------------------------

@pytest.mark.tune
def test_adaptive_candidate_priced_at_steady_state_matches_static():
    """The acceptance criterion's throughput half, statically: the
    adaptive candidate's projected step time equals the static top-rung
    config's (the controller is free at steady state in the wire model),
    and the funnel record carries the full rung schedule."""
    from grace_tpu.tuning.cost import TuneTopology, price_candidate

    structs = {"w": jax.ShapeDtypeStruct((4096, 64), jnp.float32)}
    spec = TuneTopology(world=256, slice_size=8)
    static = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
        "communicator": "ring", "fusion": "flat"})
    adaptive = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 7, "memory": "residual",
        "communicator": "ring", "fusion": "flat", "escape": "fp16",
        "telemetry": 16,
        "adapt": {"window": 25, "ladder": [{"quantum_num": 127}]}})
    p_static = price_candidate(static, structs, spec)
    p_adapt = price_candidate(adaptive, structs, spec)
    assert (p_adapt["projected_step_ms"]
            == p_static["projected_step_ms"])
    assert p_adapt["steady_state_rung"] == 2
    rungs = p_adapt["rung_prices"]
    assert [r["rung"] for r in rungs] == [0, 1, 2]
    assert rungs[0]["codec"] == "FP16Compressor"      # the dense escape
    # Degrading never gets cheaper (this homoqsgd ladder TIES across all
    # rungs — int16 accumulator width is quantum-independent, the whole
    # reason THC-style bit-width switching is free here: the rungs trade
    # quality, not bytes) and the top rung's payload is the static
    # config's exactly.
    assert (rungs[2]["projected_step_ms"] <= rungs[1]["projected_step_ms"]
            <= rungs[0]["projected_step_ms"])
    assert rungs[2]["payload_bytes"] == p_static["payload_bytes"]


@pytest.mark.tune
def test_funnel_gates_every_rung():
    from grace_tpu.tuning.candidates import Candidate, candidate_legal
    from grace_tpu.tuning.cost import TuneTopology
    from grace_tpu.tuning.prune import numeric_verdict

    # An int16-accum 8-bit rung dies at W=512 even though the base rung
    # is int32-safe — the numeric gate names the rung.
    grc = grace_from_params({
        "compressor": "homoqsgd", "quantum_num": 7, "accum_dtype": "int32",
        "memory": "residual", "communicator": "ring", "fusion": "flat",
        "escape": "fp16", "telemetry": True,
        "adapt": {"window": 5, "ladder": [
            {"quantum_num": 127, "accum_dtype": "int16"}]}})
    assert numeric_verdict(grc, TuneTopology(world=8)) is None
    verdict = numeric_verdict(grc, TuneTopology(world=512))
    assert verdict and "adapt rung" in verdict
    # A rung codec the communicator rejects at build/step time dies at
    # the capability gate with the rung named.
    cand = Candidate("bad-adapt-rung", {
        "compressor": "qsgd", "quantum_num": 15, "use_pallas": False,
        "memory": "none", "communicator": "ring", "fusion": "flat",
        "escape": "fp16", "telemetry": True,
        "adapt": {"window": 5, "ladder": [{"compressor": "onebit"}]}})
    legal, reason, _ = candidate_legal(cand, TuneTopology(world=8))
    assert not legal and "adapt rung" in reason


@pytest.mark.tune
def test_generated_adaptive_variant_is_legal_and_priced():
    from grace_tpu.tuning.candidates import (candidate_legal,
                                             generated_variants)
    from grace_tpu.tuning.cost import TuneTopology, price_candidate

    spec = TuneTopology(world=8)
    cands = [c for c in generated_variants(spec)
             if c.name == "tune-adapt-homoqsgd4-ring"]
    assert len(cands) == 1
    legal, reason, grace = candidate_legal(cands[0], spec)
    assert legal, reason
    price = price_candidate(grace, {"w": jax.ShapeDtypeStruct(
        (512,), jnp.float32)}, spec)
    assert "rung_prices" in price and len(price["rung_prices"]) == 3


# ---------------------------------------------------------------------------
# host side: monitor, timeline, report
# ---------------------------------------------------------------------------

def test_adapt_monitor_emits_transitions_and_skips_fallback():
    mon = AdaptMonitor()
    rows = [
        {"step": 0, "adapt_rung": 2.0, "fallback": 0.0},
        {"step": 1, "adapt_rung": 2.0, "fallback": 0.0},
        {"step": 2, "adapt_rung": 1.0, "fallback": 0.0},   # tighten
        {"step": 3, "adapt_rung": 0.0, "fallback": 1.0},   # guard window:
        {"step": 4, "adapt_rung": 1.0, "fallback": 0.0},   # not a policy
        {"step": 5, "adapt_rung": 2.0, "fallback": 0.0},   # move; loosen
        {"event": "watch", "step": 5},                     # ignored
        {"step": 6, "adapt_rung": -1.0},                   # unarmed row
    ]
    events = mon.observe(rows)
    assert [(e["event"], e["step"]) for e in events] == [
        ("adapt_tighten", 2), ("adapt_loosen", 5)]
    from grace_tpu.telemetry.timeline import Timeline, classify
    assert classify({"event": "adapt_tighten"}) == "adapt"
    tl = Timeline.from_records(rows[:6] + events)
    assert tl.first("adapt").record["event"] == "adapt_tighten"
    assert tl.summary()["first_adapt_step"] == 2


def test_telemetry_report_renders_adapt_section():
    import importlib.util as ilu
    spec = ilu.spec_from_file_location(
        "telemetry_report_adapt_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "telemetry_report.py"))
    report = ilu.module_from_spec(spec)
    spec.loader.exec_module(report)
    records = [{"step": i, "adapt_rung": float(2 - (i >= 3)),
                "adapt_bytes": 14.0, "wire_bytes": 100.0,
                "dense_bytes": 336.0} for i in range(6)]
    events = [{"event": "adapt_tighten", "step": 3, "rung": 1,
               "from_rung": 2}]
    text = report.render(None, records, events)
    assert "== adapt (graft-adapt rung transitions) ==" in text
    assert "1 tighten(s), 0 loosen(s)" in text
    assert "dwell" in text
    doc = report.build_doc(None, records, events)
    assert doc["adapt_events"] == events
    assert events[0] not in doc["guard_events"]


# ---------------------------------------------------------------------------
# chaos smoke e2e + evidence
# ---------------------------------------------------------------------------

def _load_smoke():
    spec = importlib.util.spec_from_file_location(
        "chaos_smoke_adapt_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "chaos_smoke.py"))
    smoke = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(smoke)
    return smoke


@pytest.mark.chaos
def test_chaos_smoke_adapt_tighten_before_guard(tmp_path):
    """The --adapt scenario end to end: drift → tighten within one window
    with the guard silent, quiet → loosen, NaN → guard trip + escalation,
    with the tighten-before-guard ordering proven from the artifact's
    unified timeline and the ADAPT evidence doc written."""
    smoke = _load_smoke()
    out = tmp_path / "adapt_chaos.jsonl"
    ev = tmp_path / "ADAPT_LAST.json"
    rc = smoke.main(["--adapt", "--steps", "72", "--batch", "16",
                     "--adapt-window", "6", "--telemetry-every", "6",
                     "--telemetry-out", str(out), "--adapt-out", str(ev)])
    assert rc == 0
    doc = json.loads(ev.read_text())
    assert doc["ordering_ok"] is True
    assert doc["tighten"]["within_one_window"] is True
    assert doc["tighten"]["count"] >= 1 and doc["loosen"]["count"] >= 1
    assert doc["escalations"] >= 1
    assert doc["first_adapt_step"] < doc["first_guard_step"]

    from grace_tpu.telemetry.timeline import Timeline
    tl = Timeline.from_jsonl(str(out))
    kinds = tl.summary()["kind_counts"]
    assert kinds.get("adapt", 0) >= 2 and kinds.get("guard", 0) >= 1
    first_adapt = next(e for e in tl.kinds("adapt") if e.step is not None)
    first_guard = next(e for e in tl.kinds("guard") if e.step is not None)
    assert first_adapt.step < first_guard.step


def test_evidence_summary_renders_adapt(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "evidence_summary_adapt_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "tools",
                     "evidence_summary.py"))
    es = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(es)
    doc = {"tool": "chaos_smoke", "captured_at": "2026-08-05T00:00:00",
           "window": 6, "ladder": ["a", "b", "c"],
           "tighten": {"count": 2, "first_step": 5,
                       "within_one_window": True},
           "loosen": {"count": 1, "first_step": 40},
           "escalations": 1, "guard_skips": 4, "ordering_ok": True}
    (tmp_path / "ADAPT_LAST.json").write_text(json.dumps(doc))
    monkeypatch.setattr(es, "ROOT", str(tmp_path))
    md = es.build()
    assert "Adaptive compression (graft-adapt)" in md
    assert "adapt_tighten precedes the first guard event" in md
    assert "within one window" in md


# ---------------------------------------------------------------------------
# convergence floors: the routed transformer track + adaptive vs static
# ---------------------------------------------------------------------------

def test_routed_transformer_track_convergence_floor(mesh):
    """The PR-14 leftover: the bert_routed_rscatter-shaped track (big
    leaves ride topk through the per-shard reduce-scatter, ln/bias leaves
    ride dense fp16 psum) pinned against the dense reference's floor on a
    CPU-smoke-sized problem."""
    rng = np.random.default_rng(11)
    w_true = rng.normal(size=(24, 6)).astype(np.float32)
    x = jnp.asarray(rng.normal(size=(64, 24)).astype(np.float32))
    y = jnp.asarray(np.argmax(np.asarray(x) @ w_true, axis=1)
                    .astype(np.int32))

    def loss_fn(p, b):
        xb, yb = b
        h = jnp.tanh(xb @ p["emb"] * p["ln_scale"] + p["bias"])
        return optax.softmax_cross_entropy_with_integer_labels(
            h @ p["head"], yb).mean()

    params = {"emb": jnp.asarray(rng.normal(scale=0.3, size=(24, 16)),
                                 jnp.float32),
              "ln_scale": jnp.ones((16,), jnp.float32),
              "bias": jnp.zeros((16,), jnp.float32),
              "head": jnp.asarray(rng.normal(scale=0.3, size=(16, 6)),
                                  jnp.float32)}

    def final_loss(p_dict):
        grc = grace_from_params(p_dict)
        tx = optax.chain(grc.transform(seed=0), optax.sgd(0.3))
        state = init_train_state(jax.tree_util.tree_map(jnp.copy, params),
                                 tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        loss = None
        for _ in range(60):
            state, loss = step(state, (x, y))
        return float(loss)

    dense = final_loss({"compressor": "fp16", "memory": "none",
                        "communicator": "allreduce"})
    routed = final_loss({
        "compressor": "topk", "compress_ratio": 0.25,
        "memory": "residual", "communicator": "rscatter",
        "route": [("*ln*", {"compressor": "fp16", "memory": "none",
                            "communicator": "allreduce"}),
                  ("*bias*", {"compressor": "fp16", "memory": "none",
                              "communicator": "allreduce"})]})
    assert dense < 1.0, dense              # the reference itself converged
    assert routed < dense + 0.1, (routed, dense)


def test_adaptive_matches_static_convergence_floor(mesh):
    """The acceptance criterion's accuracy half: the self-tuning config
    reaches the hand-picked static config's final loss on a real
    trajectory (here bitwise-equal would also hold — the quiet ladder
    never leaves the top rung — but the floor comparison is the stated
    contract and survives threshold retunes)."""
    loss_fn, batch = _ls_problem(seed=3)

    def final_loss(extra):
        grc = grace_from_params({
            "compressor": "homoqsgd", "quantum_num": 7,
            "memory": "residual", "communicator": "ring",
            "fusion": "flat", **extra})
        tx = optax.chain(grc.transform(seed=0), optax.sgd(0.3))
        state = init_train_state({"w": jnp.zeros((20, 4), jnp.float32)},
                                 tx, mesh)
        step = make_train_step(loss_fn, tx, mesh, donate=False)
        loss = None
        for _ in range(60):
            state, loss = step(state, batch)
        return float(loss), state

    static, _ = final_loss({})
    adaptive, state = final_loss({
        "escape": "fp16", "telemetry": 16,
        "adapt": {"window": 10, "ladder": [{"quantum_num": 127}],
                  "tighten_error": 5.0, "tighten_peak": 7.5,
                  "loosen_error": 2.5}})
    assert static < 0.8, static
    assert adaptive < static + 0.05, (adaptive, static)
    assert adapt_report(state)["rung"] == 2   # held the steady state
